"""User-facing Dataset and Booster.

Reference: python-package/lightgbm/basic.py — class Dataset (lazy
construction, reference= bin alignment, set_field/get_field, free_raw_data)
and class Booster (update, rollback_one_iter, eval, predict, save_model,
model_from_string, feature_importance...).

Unlike the reference there is no ctypes boundary: the "C API layer" of the
reference (src/c_api.cpp) collapses into direct Python calls; the hot arrays
live on the TPU as jax arrays owned by the model objects.
"""

from __future__ import annotations

import functools
import json
import os
from pathlib import Path
from typing import Any, Dict, List, Optional, Sequence, Union

import jax
import jax.numpy as jnp
import numpy as np

from .binning import DatasetBinner
from .config import Config
from .models.gbdt import GBDT, create_boosting
from .models.tree import Tree
from .ops import predict as predict_ops
from .utils import checkpoint as _checkpoint
from .utils.guards import validate_finite


class LightGBMError(Exception):
    """reference: LightGBMError in python-package/lightgbm/basic.py."""


class CorruptModelError(LightGBMError):
    """A model/snapshot file failed integrity verification (torn write,
    truncation, bit rot).  engine.train catches this to fall back to the
    newest valid snapshot; see utils/checkpoint.py."""


def _is_scipy_sparse(data) -> bool:
    return hasattr(data, "tocsr") and hasattr(data, "toarray")


@functools.partial(jax.jit, donate_argnums=(0,))
def _ooc_fill_rows(dev, chunk, row_lo):
    """One streamed-ingest step: place a fixed-shape row chunk into the
    (donated) device matrix.  Donation keeps the fill O(chunk) traffic
    per step instead of alloc+copy of the whole matrix."""
    return jax.lax.dynamic_update_slice(dev, chunk, (row_lo, 0))


def _to_2d_float(data) -> np.ndarray:
    """Accepts numpy arrays, pandas DataFrames (incl. category dtypes),
    scipy CSR/CSC matrices, Sequence objects, and lists thereof (reference:
    the c_api ingestion surface — DenseToCSR, CSR/CSC handlers, pandas
    categorical encoding in python-package/lightgbm/basic.py, and the
    Sequence streaming interface)."""
    if isinstance(data, Sequence_):
        data = _from_sequences([data])
    elif isinstance(data, list) and data and isinstance(data[0], Sequence_):
        data = _from_sequences(data)
    if hasattr(data, "dtypes") and hasattr(data, "columns"):  # pandas frame
        import pandas as pd  # local: pandas is optional

        cols = []
        for c in data.columns:
            col = data[c]
            if isinstance(col.dtype, pd.CategoricalDtype):
                codes = col.cat.codes.to_numpy().astype(np.float64)
                codes[codes < 0] = np.nan  # NA category -> missing
                cols.append(codes)
            else:
                cols.append(col.to_numpy(dtype=np.float64, na_value=np.nan))
        arr = np.stack(cols, axis=1)
        return arr
    if hasattr(data, "schema") and hasattr(data, "column"):  # pyarrow
        return _arrow_to_2d(data)
    if hasattr(data, "values"):  # pandas series
        data = data.values
    if _is_scipy_sparse(data):
        data = data.toarray()
    arr = np.asarray(data, dtype=np.float64)
    if arr.ndim == 1:
        arr = arr.reshape(-1, 1)
    return arr


def _arrow_to_2d(data) -> np.ndarray:
    """pyarrow Table/RecordBatch -> float64 matrix, column-at-a-time with no
    pandas hop (reference: include/LightGBM/arrow.h chunked-array iterators).
    Null-free numeric chunks convert zero-copy via the buffer protocol;
    chunks with nulls cast to float64 with NaN; dictionary columns use their
    integer codes (pandas-categorical semantics)."""
    import pyarrow as pa

    def chunk_values(chunk) -> np.ndarray:
        t = chunk.type
        if isinstance(t, pa.DictionaryType):
            idx = chunk.indices  # nulls live in the indices
            return idx.cast(pa.float64()).to_numpy(zero_copy_only=False)
        if pa.types.is_boolean(t):
            return chunk.cast(pa.float64()).to_numpy(zero_copy_only=False)
        if chunk.null_count == 0:
            return np.asarray(chunk, dtype=np.float64)
        return chunk.cast(pa.float64()).to_numpy(zero_copy_only=False)

    cols = []
    for i in range(data.num_columns):
        col = data.column(i)
        if (isinstance(col.type, pa.DictionaryType)
                and getattr(col, "num_chunks", 1) > 1):
            # per-chunk dictionaries may order categories differently; codes
            # are only comparable after unification
            col = col.unify_dictionaries()
        chunks = col.chunks if hasattr(col, "chunks") else [col]
        if len(chunks) == 1:
            cols.append(chunk_values(chunks[0]))
        elif not chunks:
            cols.append(np.zeros(0, np.float64))
        else:
            cols.append(np.concatenate([chunk_values(c) for c in chunks]))
    return np.stack(cols, axis=1) if cols else np.zeros((data.num_rows, 0))


class Sequence_:
    """Generic row-chunk data source (reference: lightgbm.Sequence —
    python-package/lightgbm/basic.py Sequence ABC + the push-rows streaming
    C API).  Subclass with __len__ and __getitem__ (row slice -> ndarray);
    `batch_size` bounds peak memory during construction."""

    batch_size = 65536

    def __len__(self) -> int:  # pragma: no cover - abstract
        raise NotImplementedError

    def __getitem__(self, idx):  # pragma: no cover - abstract
        raise NotImplementedError


def _from_sequences(seqs) -> np.ndarray:
    chunks = []
    for seq in seqs:
        n = len(seq)
        bs = max(int(getattr(seq, "batch_size", 65536)), 1)
        for lo in range(0, n, bs):
            chunk = np.asarray(seq[slice(lo, min(lo + bs, n))], np.float64)
            if chunk.ndim == 1:
                # a 1-D slice is a batch of single-feature ROWS
                chunk = chunk.reshape(-1, 1)
            chunks.append(chunk)
    return np.concatenate(chunks, axis=0)


def _allgather_rows_f64(local: np.ndarray) -> np.ndarray:
    """Row-concatenate a float64 array across processes BIT-EXACTLY (float64
    as int32 pairs — x64 is disabled in jax, and f32 rounding would corrupt
    values like bin boundaries vs the serial path).  Uneven per-rank row
    counts are handled by padding to the max count and slicing each rank's
    block back to its true length (reference: Network::Allgather carries
    per-rank byte counts)."""
    from jax.experimental import multihost_utils

    a = np.ascontiguousarray(np.asarray(local, np.float64))
    lead = a.shape[0]
    counts = np.asarray(multihost_utils.process_allgather(
        jnp.asarray([lead], jnp.int32), tiled=True)).ravel()
    cmax = int(counts.max()) if len(counts) else lead
    if lead < cmax:
        a = np.concatenate(
            [a, np.zeros((cmax - lead,) + a.shape[1:], np.float64)])
    bits = a.view(np.int32).reshape(cmax, -1)
    g = np.ascontiguousarray(np.asarray(
        multihost_utils.process_allgather(jnp.asarray(bits), tiled=True)))
    full = g.view(np.float64).reshape((len(counts) * cmax,) + a.shape[1:])
    if (counts == cmax).all():
        return full
    return np.concatenate([
        full[r * cmax: r * cmax + int(c)] for r, c in enumerate(counts)
    ])


def _sync_binning_sample(local: np.ndarray, target_cnt: int,
                         seed: int) -> np.ndarray:
    """Pre-partitioned multi-controller binning sync: every rank holds a
    different row shard, so bin boundaries must come from the GLOBAL sample
    (reference: DatasetLoader's distributed bin sync via
    Network::Allgather of BinMappers)."""
    import jax as _jax

    nproc = _jax.process_count()
    per = max(min(target_cnt // nproc, local.shape[0]), 1)
    rng_s = np.random.RandomState(seed)
    idx = (rng_s.choice(local.shape[0], per, replace=False)
           if local.shape[0] > per else np.arange(local.shape[0]))
    return _allgather_rows_f64(local[idx])


def _feature_names_of(data, num_features: int) -> List[str]:
    if hasattr(data, "schema") and hasattr(data, "column"):  # pyarrow:
        return [str(n) for n in data.schema.names]  # .columns is the arrays
    if hasattr(data, "columns"):
        return [str(c) for c in data.columns]
    return [f"Column_{i}" for i in range(num_features)]


class Dataset:
    """reference: class Dataset in python-package/lightgbm/basic.py.

    Lazily constructed: raw data is held until `construct()` (which the
    training entry calls), then binned via binning.DatasetBinner and shipped
    to the device as a compact int matrix.
    """

    def __init__(
        self,
        data,
        label=None,
        reference: Optional["Dataset"] = None,
        weight=None,
        group=None,
        init_score=None,
        feature_name: Union[str, List[str]] = "auto",
        categorical_feature: Union[str, List[int]] = "auto",
        params: Optional[Dict[str, Any]] = None,
        free_raw_data: bool = True,
        position=None,
    ):
        self.data = data
        self.label = None if label is None else np.asarray(label, dtype=np.float64).ravel()
        self.reference = reference
        self.weight = None if weight is None else np.asarray(weight, dtype=np.float64).ravel()
        self.group = None if group is None else np.asarray(group, dtype=np.int64).ravel()
        self.init_score = None if init_score is None else np.asarray(init_score, dtype=np.float64)
        self.params = dict(params or {})
        self.feature_name = feature_name
        self.categorical_feature = categorical_feature
        self.free_raw_data = free_raw_data
        self._constructed = False
        self.binner: Optional[DatasetBinner] = None
        self.bins: Optional[np.ndarray] = None
        self.feature_names: List[str] = []
        # rank position info (reference: Metadata positions_; Dataset(position=...))
        self.position = None if position is None else np.asarray(position, np.int64).ravel()
        self._used_indices = None

    # -- construction ---------------------------------------------------
    def construct(self, reference: Optional["Dataset"] = None) -> "Dataset":
        if self._constructed:
            return self
        ref = reference if reference is not None else self.reference
        cfg = Config.from_dict(self.params)
        pre_binner = pre_bins = None
        if isinstance(self.data, (str, os.PathLike)):
            # file-path datasets (reference: Dataset accepts a path;
            # DatasetLoader::LoadFromFile).  two_round streams the file
            # twice — sample+count, then bin per chunk — and never holds
            # the raw float matrix (reference: two_round=true semantics).
            path = os.fspath(self.data)
            from .io.parser import load_data_file, load_data_file_two_round

            col_kw = dict(
                header=bool(cfg.header),
                label_column=cfg.label_column,
                weight_column=cfg.weight_column,
                group_column=cfg.group_column,
                ignore_column=cfg.ignore_column,
            )
            with open(path, "rb") as _fh:
                _magic = _fh.read(4)
            if _magic == b"PK\x03\x04":
                # save_binary npz checkpoint (reference:
                # DatasetLoader::LoadFromBinFile) — binned matrix + mappers
                # reload directly, no raw parsing or re-binning.  With
                # out_of_core= the matrix member is NOT materialized: it
                # streams in row chunks through a reused host buffer
                # (io/stream.py), and device residency follows
                # max_rows_in_hbm (docs round 12)
                from .binning import BinMapper

                shard_spec = self.params.get("bin_cache_shard")
                if cfg.out_of_core:
                    if shard_spec is not None:
                        raise ValueError(
                            "bin_cache_shard and out_of_core are not "
                            "combinable yet: the shard feed materializes "
                            "its rows (pass the shard to BinCacheStream "
                            "directly for streamed sweeps)")
                    from .io.stream import BinCacheStream

                    self._ooc_stream = BinCacheStream(path)
                with np.load(path, allow_pickle=False) as z:
                    sizes = z["upper_sizes"]
                    uppers = z["uppers"]
                    mt = z["missing_types"]
                    cat_sizes = (z["cat_sizes"] if "cat_sizes" in z.files
                                 else np.zeros(len(sizes), np.int64))
                    cats = z["cats"] if "cats" in z.files else np.zeros(0)
                    minv = (z["min_values"] if "min_values" in z.files
                            else np.zeros(len(sizes)))
                    maxv = (z["max_values"] if "max_values" in z.files
                            else np.zeros(len(sizes)))
                    mappers, off, coff = [], 0, 0
                    for i, s in enumerate(sizes):
                        s = int(s)
                        cs = int(cat_sizes[i])
                        mappers.append(BinMapper(
                            upper_bounds=uppers[off:off + s],
                            missing_type=int(mt[i]),
                            is_categorical=cs > 0,
                            categories=(cats[coff:coff + cs] if cs else None),
                            min_value=float(minv[i]),
                            max_value=float(maxv[i]),
                        ))
                        off += s
                        coff += cs
                    pre_binner = DatasetBinner(mappers=mappers)
                    pre_bins = (None if (
                        getattr(self, "_ooc_stream", None) is not None
                        or shard_spec is not None)
                        else np.asarray(z["bins"]))
                    _seg = None
                    if pre_bins is not None:
                        # live append segments (io/stream.py round 22)
                        # extend the cache past the base npz: the
                        # materialized load must see them too (the ooc
                        # stream and shard feed already compose them)
                        from .io.stream import load_segmented_cache

                        _seg = load_segmented_cache(path)
                        if _seg is not None:
                            pre_bins = _seg[0]
                    loaded = {
                        "label": (z["label"] if z["label"].size else None),
                        "weight": (z["weight"] if z["weight"].size else None),
                        "group": (z["group"] if z["group"].size else None),
                        "init_score": (
                            z["init_score"]
                            if "init_score" in z.files and z["init_score"].size
                            else None),
                        "position": (
                            z["position"]
                            if "position" in z.files and z["position"].size
                            else None),
                        "feature_names": [str(x) for x in z["feature_names"]],
                    }
                    if _seg is not None:
                        # per-row metadata concatenated across segments
                        loaded["label"] = (_seg[1] if _seg[1].size
                                           else None)
                        loaded["weight"] = (_seg[2] if _seg[2].size
                                            else None)
                if shard_spec is not None:
                    # rank-sharded cache feed (docs/DISTRIBUTED.md): this
                    # worker materializes ONLY its [lo, hi) rows of the
                    # shared cache — streamed through BinCacheStream's
                    # shard form with CRC verification of every fully
                    # covered block — plus optional weight-0 padding to
                    # the fleet's equal-shard size (pre_partition needs
                    # equal shards; pad rows can never contribute)
                    from .io.stream import read_cache_shard

                    s_lo, s_hi = int(shard_spec[0]), int(shard_spec[1])
                    pad_to = (int(shard_spec[2]) if len(shard_spec) > 2
                              else s_hi - s_lo)
                    if pad_to < s_hi - s_lo:
                        raise ValueError(
                            f"bin_cache_shard pad size {pad_to} is below "
                            f"the shard's {s_hi - s_lo} rows")
                    if loaded.get("group") is not None:
                        raise ValueError(
                            "bin_cache_shard does not support grouped "
                            "(ranking) caches: shard boundaries would cut "
                            "queries")
                    pre_bins = read_cache_shard(path, s_lo, s_hi)
                    n_pad = pad_to - (s_hi - s_lo)
                    if n_pad:
                        pre_bins = np.concatenate([
                            pre_bins,
                            np.zeros((n_pad, pre_bins.shape[1]),
                                     pre_bins.dtype)])

                    def _slice_pad(v, fill):
                        if v is None:
                            return None
                        v = np.asarray(v)[s_lo:s_hi]
                        if n_pad:
                            v = np.concatenate([
                                v, np.full((n_pad,) + v.shape[1:], fill,
                                           v.dtype)])
                        return v

                    w = loaded.get("weight")
                    if w is None and n_pad:
                        # padding must carry weight 0; synthesize unit
                        # weights for the real rows
                        w = np.ones(s_hi - s_lo, np.float64)
                        loaded["weight"] = np.concatenate(
                            [w, np.zeros(n_pad)])
                    else:
                        loaded["weight"] = _slice_pad(w, 0.0)
                    loaded["label"] = _slice_pad(loaded.get("label"), 0.0)
                    loaded["init_score"] = _slice_pad(
                        loaded.get("init_score"), 0.0)
                    loaded["position"] = _slice_pad(
                        loaded.get("position"), 0)
            elif cfg.two_round:
                import jax as _jax

                if ref is not None:
                    ref.construct()
                    factory = lambda sample, names: ref.binner  # noqa: E731
                else:
                    def factory(sample, names, _cfg=cfg):
                        cats_f = []
                        if isinstance(self.categorical_feature, (list, tuple)):
                            cats_f = [
                                names.index(c) if isinstance(c, str) else int(c)
                                for c in self.categorical_feature
                            ]
                        forced = None
                        if _cfg.forcedbins_filename:
                            with open(_cfg.forcedbins_filename) as fh:
                                forced = {
                                    int(e["feature"]):
                                        [float(v) for v in e["bin_upper_bound"]]
                                    for e in json.load(fh)
                                }
                        return DatasetBinner.fit(
                            sample, max_bin=_cfg.max_bin,
                            min_data_in_bin=_cfg.min_data_in_bin,
                            sample_cnt=len(sample),
                            use_missing=_cfg.use_missing,
                            zero_as_missing=_cfg.zero_as_missing,
                            categorical_features=cats_f,
                            max_bin_by_feature=_cfg.max_bin_by_feature,
                            seed=_cfg.data_random_seed,
                            forced_bins=forced,
                        )
                if (ref is None and cfg.pre_partition
                        and _jax.process_count() > 1):
                    # per-rank streamed shards: sync the reservoir sample
                    # across ranks before fitting mappers, so every rank
                    # bins on identical boundaries (same gather the
                    # in-memory pre_partition path uses)
                    inner_factory = factory

                    def factory(sample, names, _cfg=cfg,
                                _inner=inner_factory):
                        sample_g = _sync_binning_sample(
                            np.asarray(sample, np.float64),
                            _cfg.bin_construct_sample_cnt,
                            _cfg.data_random_seed)
                        return _inner(sample_g, names)

                loaded = load_data_file_two_round(
                    path, factory,
                    sample_cnt=cfg.bin_construct_sample_cnt,
                    seed=cfg.data_random_seed,
                    sample_needed=(ref is None), **col_kw,
                )
                pre_binner, pre_bins = loaded["binner"], loaded["bins"]
            else:
                loaded = load_data_file(path, **col_kw)
                self.data = loaded["data"]
            if self.label is None and loaded.get("label") is not None:
                self.label = np.asarray(loaded["label"], np.float64).ravel()
            if self.weight is None and loaded.get("weight") is not None:
                self.weight = np.asarray(loaded["weight"], np.float64).ravel()
            if self.group is None and loaded.get("group") is not None:
                self.group = np.asarray(loaded["group"], np.int64).ravel()
            if self.init_score is None and loaded.get("init_score") is not None:
                self.init_score = np.asarray(loaded["init_score"], np.float64)
            if self.position is None and loaded.get("position") is not None:
                self.position = np.asarray(loaded["position"], np.int64).ravel()
            if self.feature_name == "auto":
                self.feature_name = list(loaded["feature_names"])
        # non-finite guard rail layer 1 (docs/ROBUSTNESS.md): a NaN/inf
        # target silently corrupts every boosting round downstream — reject
        # it here, once, host-side, with the offending row in the message
        # (features are exempt: non-finite feature values take the
        # missing-value path in binning)
        validate_finite("label", self.label)
        validate_finite("weight", self.weight)
        validate_finite("init_score", self.init_score)
        # sparse inputs are binned straight from CSC (reference:
        # src/io/sparse_bin.hpp — stored nonzeros + implicit zeros); only the
        # compact binned matrix is materialized, never dense raw floats
        sparse_csc = None
        if pre_bins is not None or getattr(self, "_ooc_stream", None) is not None:
            raw = None
            num_feature = (pre_bins.shape[1] if pre_bins is not None
                           else self._ooc_stream.n_cols)
        elif _is_scipy_sparse(self.data) and cfg.is_enable_sparse:
            # (linear_tree + sparse raises below, before any raw upload)
            sparse_csc = self.data.tocsc()
            raw = None
            num_feature = sparse_csc.shape[1]
        else:
            raw = _to_2d_float(self.data)
            num_feature = raw.shape[1]
        self.feature_names = (
            list(self.feature_name)
            if isinstance(self.feature_name, (list, tuple))
            else _feature_names_of(self.data, num_feature)
        )
        cats: Sequence[int] = ()
        if isinstance(self.categorical_feature, (list, tuple)):
            cats = [
                self.feature_names.index(c) if isinstance(c, str) else int(c)
                for c in self.categorical_feature
            ]
        if pre_binner is not None:
            self.binner = pre_binner
        elif ref is not None:
            ref.construct()
            # bin alignment with the reference dataset (reference= semantics)
            self.binner = ref.binner
        else:
            forced_bins = None
            if cfg.forcedbins_filename:
                # reference: DatasetLoader reads the forced-bins JSON
                # ([{"feature": idx, "bin_upper_bound": [...]}]) and routes
                # each entry into BinMapper::FindBin as forced boundaries
                import json as _json

                with open(cfg.forcedbins_filename) as fh:
                    forced_bins = {
                        int(e["feature"]): [float(v) for v in e["bin_upper_bound"]]
                        for e in _json.load(fh)
                    }
            fit_kwargs = dict(
                max_bin=cfg.max_bin,
                min_data_in_bin=cfg.min_data_in_bin,
                sample_cnt=cfg.bin_construct_sample_cnt,
                use_missing=cfg.use_missing,
                zero_as_missing=cfg.zero_as_missing,
                categorical_features=cats,
                max_bin_by_feature=cfg.max_bin_by_feature,
                seed=cfg.data_random_seed,
                forced_bins=forced_bins,
            )
            import jax as _jax

            if (
                cfg.pre_partition
                and _jax.process_count() > 1
                and raw is not None
            ):
                sample_g = _sync_binning_sample(
                    raw, cfg.bin_construct_sample_cnt, cfg.data_random_seed)
                fit_kwargs["sample_cnt"] = len(sample_g)
                self.binner = DatasetBinner.fit(sample_g, **fit_kwargs)
            elif sparse_csc is not None:
                self.binner = DatasetBinner.fit_sparse(sparse_csc, **fit_kwargs)
            else:
                self.binner = DatasetBinner.fit(raw, **fit_kwargs)
        if pre_bins is not None:
            self.bins = pre_bins
        elif getattr(self, "_ooc_stream", None) is not None:
            self.bins = None  # never materialized host-side (out_of_core)
        elif sparse_csc is not None:
            self.bins = self.binner.transform_sparse(sparse_csc)
        else:
            self.bins = self.binner.transform(raw)
        # out-of-core residency decision (docs round 12): with out_of_core=
        # the binned matrix streams in row chunks; if the rows fit the
        # max_rows_in_hbm budget the chunks ASSEMBLE the device matrix
        # (resident regime — training is the standard growers, bit-for-bit)
        # and otherwise the matrix never becomes device-resident (spill
        # regime — chunked-histogram training, ops/treegrow_ooc.py)
        self.ooc = bool(cfg.out_of_core)
        self.ooc_spill = False
        self.ooc_chunk_rows = 0
        if self.ooc:
            from .io.stream import DEFAULT_CHUNK_ROWS

            n_rows_total = (self._ooc_stream.n_rows
                            if getattr(self, "_ooc_stream", None) is not None
                            else self.bins.shape[0])
            self.ooc_chunk_rows = int(cfg.out_of_core_chunk_rows) or min(
                DEFAULT_CHUNK_ROWS, n_rows_total)
            cap = int(cfg.max_rows_in_hbm)
            self.ooc_spill = 0 < cap < n_rows_total
        # int16 on device: half the HBM of int32 at Epsilon scale (max_bin
        # caps at 65535 by far); compute casts per tile
        if self.ooc_spill:
            self.bins_device = None  # larger than the HBM budget: streamed
        elif self.ooc:
            self.bins_device = self._ooc_assemble_device()
        else:
            self.bins_device = jnp.asarray(self.bins, jnp.int16)
        self._bins_device_t = None
        self.num_bins_pf_device = jnp.asarray(self.binner.num_bins_per_feature)
        self.missing_bin_pf_device = jnp.asarray(self.binner.missing_bin_per_feature)
        self.max_num_bins = int(self.binner.max_num_bins)
        # EFB (reference: DatasetLoader::FindGroups/FastFeatureBundling):
        # bundle sparse exclusive features so histogram passes scan fewer
        # columns; split search / trees stay in original-feature space
        self.efb = None
        self._efb_device = None
        if ref is not None:
            if getattr(ref, "efb", None) is not None:
                # aligned binning: reuse the plan; the bundled matrix for THIS
                # data is encoded lazily (valid sets never need it — only the
                # train set's histogram passes do)
                self.efb = ref.efb._replace(bundled_bins=None)
        elif cfg.enable_bundle and self.ooc:
            # EFB's bundling passes scan the full host matrix, which the
            # out-of-core path never materializes; the OOC growers run on
            # the unbundled feature space (envelope note, docs round 12)
            pass
        elif cfg.enable_bundle:
            from .io.efb import find_bundles

            # bundle capacity uses the FULL max_bin budget, not the widest
            # individual feature — one-hot blocks (2-bin features) must be
            # able to pack ~max_bin features per bundle (reference:
            # FeatureGroup bin counts exceed member features'); the histogram
            # width is raised to the bundle capacity below
            bundle_cap = max(self.max_num_bins, int(cfg.max_bin) + 1)
            self.efb = find_bundles(
                self.bins,
                self.binner.num_bins_per_feature,
                bundle_cap,
                categorical_mask=np.asarray(self.binner.categorical_mask),
                seed=cfg.data_random_seed,
            )
            if self.efb is not None and self.efb.is_useful:
                # histogram width follows the widest achieved column (the
                # gather-table stride), not the packing capacity
                self.max_num_bins = max(
                    self.max_num_bins, int(self.efb.gather_idx.shape[1])
                )
        if getattr(self, "_ooc_stream", None) is not None:
            self._num_data, self._num_feature = self._ooc_stream.shape
        else:
            self._num_data, self._num_feature = (
                self.bins.shape if raw is None else raw.shape
            )
        if cfg.linear_tree or (ref is not None and getattr(ref, "raw_device", None) is not None):
            # linear trees need raw feature values at fit/score time
            # (reference: linear_tree_learner.cpp keeps a raw-data view)
            if raw is None:
                raise LightGBMError(
                    "linear_tree requires dense raw feature values; pass "
                    "is_enable_sparse=False (sparse input) or disable "
                    "two_round (file streaming) to materialize them"
                )
            self.raw_device = jnp.asarray(raw.astype(np.float32))
        if self.free_raw_data:
            self.data = None
        self._constructed = True
        return self

    # -- out-of-core data plane (docs round 12) -------------------------
    ooc = False
    ooc_spill = False
    ooc_chunk_rows = 0
    _ooc_stream = None

    def _ooc_assemble_device(self) -> jnp.ndarray:
        """Resident regime: assemble the device matrix from streamed
        chunks — one reused host buffer, one-deep upload prefetch, a
        donated O(chunk) placement per step.  The assembled matrix is
        IDENTICAL to a whole-array upload (chunking is pure placement),
        so training downstream is bit-for-bit the in-memory path."""
        from .io.stream import prefetch_device

        if self._ooc_stream is None:
            # host bins are already fully materialized (ndarray input, no
            # cache to stream from) — chunked placement would rebuild the
            # identical matrix with ceil(N/chunk) extra dispatches for
            # zero host- or device-memory benefit; upload it whole, the
            # in-memory path's own idiom
            return jnp.asarray(self.bins, jnp.int16)
        n, f = self._ooc_stream.shape
        src = self._ooc_stream.chunks(self.ooc_chunk_rows)
        dev = jnp.zeros((n, f), jnp.int16)
        # no pad_rows: the tail chunk keeps its native shape (one extra
        # compile) so dynamic_update_slice can never clamp-shift the fill
        for row_lo, _m, chunk in prefetch_device(src, dtype=jnp.int16):
            dev = _ooc_fill_rows(dev, chunk, jnp.int32(row_lo))
        return dev

    def ooc_chunk_iter(self):
        """Fresh (row_lo, host_chunk_view) sweep over the binned matrix —
        the spill-regime grower re-invokes this once per histogram pass
        (ops/treegrow_ooc.py)."""
        if self._ooc_stream is not None:
            return self._ooc_stream.chunks(self.ooc_chunk_rows)
        from .io.stream import array_chunks

        return array_chunks(self.bins, self.ooc_chunk_rows)

    def efb_device_tables(self):
        """Lazy device tables for EFB training: (bundled_bins, gather,
        default_mask) — encoded/uploaded on first use (train set only)."""
        if self.efb is None:
            return None
        if self._efb_device is None:
            bundled = self.efb.bundled_bins
            if bundled is None:
                from .io.efb import apply_bundles

                bundled = apply_bundles(
                    self.efb, self.bins, self.binner.num_bins_per_feature
                )
                self.efb = self.efb._replace(bundled_bins=bundled)
            self._efb_device = (
                jnp.asarray(bundled, jnp.int16),
                jnp.asarray(self.efb.gather_idx),
                jnp.asarray(self.efb.default_mask),
            )
        return self._efb_device

    @property
    def query_boundaries(self) -> Optional[np.ndarray]:
        if self.group is None:
            return None
        return np.concatenate([[0], np.cumsum(self.group)]).astype(np.int64)

    def bins_device_t(self) -> jnp.ndarray:
        """(F, N) feature-major shadow of bins_device — the fast grower's
        partition reads become contiguous row slices (docs/PERF_NOTES.md).
        Built lazily: only TPU training paths request it."""
        if getattr(self, "_bins_device_t", None) is None:
            if self.bins is None:
                if self.bins_device is None:
                    raise LightGBMError(
                        "bins_device_t needs a device-resident matrix, but "
                        "this out_of_core dataset exceeds max_rows_in_hbm "
                        "(spill regime) and only streams bins in chunks — "
                        "raise max_rows_in_hbm or drop out_of_core")
                # out-of-core resident: the host matrix was never
                # materialized — transpose the assembled device matrix
                self._bins_device_t = jnp.asarray(
                    jnp.transpose(self.bins_device))
            else:
                self._bins_device_t = jnp.asarray(
                    np.ascontiguousarray(self.bins.T), jnp.int16
                )
        return self._bins_device_t

    def efb_bins_device_t(self) -> Optional[jnp.ndarray]:
        """(F_b, N) feature-major shadow of the EFB bundled matrix (the
        windowed grower gathers window rows from it); lazy, device-side
        transpose (one-time)."""
        if self.efb is None:
            return None
        if getattr(self, "_efb_device_t", None) is None:
            tabs = self.efb_device_tables()
            self._efb_device_t = jnp.asarray(jnp.transpose(tabs[0]))
        return self._efb_device_t

    def num_data(self) -> int:
        if self._constructed:
            return self._num_data
        return _to_2d_float(self.data).shape[0]

    def num_feature(self) -> int:
        if self._constructed:
            return self._num_feature
        return _to_2d_float(self.data).shape[1]

    # -- field access (reference: Dataset.set_field/get_field) ----------
    def set_field(self, field_name: str, data) -> "Dataset":
        if field_name == "label":
            self.label = None if data is None else np.asarray(data, np.float64).ravel()
            validate_finite("label", self.label)
        elif field_name == "weight":
            self.weight = None if data is None else np.asarray(data, np.float64).ravel()
            validate_finite("weight", self.weight)
        elif field_name == "group" or field_name == "query":
            self.group = None if data is None else np.asarray(data, np.int64).ravel()
        elif field_name == "init_score":
            self.init_score = None if data is None else np.asarray(data, np.float64)
            validate_finite("init_score", self.init_score)
        elif field_name == "position":
            self.position = None if data is None else np.asarray(data, np.int64).ravel()
        else:
            raise LightGBMError(f"Unknown field: {field_name}")
        return self

    def get_field(self, field_name: str):
        return {
            "label": self.label,
            "weight": self.weight,
            "group": self.group,
            "query": self.group,
            "init_score": self.init_score,
            "position": self.position,
        }.get(field_name)

    set_label = lambda self, label: self.set_field("label", label)
    set_weight = lambda self, weight: self.set_field("weight", weight)
    set_group = lambda self, group: self.set_field("group", group)
    set_init_score = lambda self, s: self.set_field("init_score", s)
    set_position = lambda self, p: self.set_field("position", p)
    get_label = lambda self: self.label
    get_weight = lambda self: self.weight
    get_group = lambda self: self.group
    get_init_score = lambda self: self.init_score
    get_position = lambda self: self.position

    def get_data(self):
        """reference: Dataset.get_data — the raw data (None once freed)."""
        return self.data

    def get_feature_name(self) -> List[str]:
        self.construct()
        return list(self.feature_names)

    def set_feature_name(self, feature_name) -> "Dataset":
        """reference: Dataset.set_feature_name."""
        if feature_name is not None and feature_name != "auto":
            names = list(feature_name)
            if self._constructed and len(names) != self.num_feature():
                raise LightGBMError(
                    f"Length of feature names {len(names)} does not equal "
                    f"number of features {self.num_feature()}"
                )
            self.feature_name = names
            if self._constructed:
                self.feature_names = names
        return self

    def set_categorical_feature(self, categorical_feature) -> "Dataset":
        """reference: Dataset.set_categorical_feature — must happen before
        construction (bin mappers depend on it)."""
        if self.categorical_feature == categorical_feature:
            return self
        if self._constructed:
            raise LightGBMError(
                "Cannot set categorical feature after freed raw data, "
                "set free_raw_data=False when construct Dataset to avoid this."
            )
        self.categorical_feature = categorical_feature
        return self

    def set_reference(self, reference: "Dataset") -> "Dataset":
        """reference: Dataset.set_reference — align bins to another dataset."""
        if self._constructed:
            if self.reference is reference:
                return self
            raise LightGBMError(
                "Cannot set reference after Dataset was constructed."
            )
        self.reference = reference
        return self

    def get_ref_chain(self, ref_limit: int = 100):
        """reference: Dataset.get_ref_chain — set of datasets along the
        reference= chain."""
        head = self
        ref_chain = set()
        while len(ref_chain) < ref_limit:
            if isinstance(head, Dataset):
                ref_chain.add(head)
                if head.reference is not None and head.reference not in ref_chain:
                    head = head.reference
                else:
                    break
            else:
                break
        return ref_chain

    def feature_num_bin(self, feature: Union[int, str]) -> int:
        """reference: Dataset.feature_num_bin (LGBM_DatasetGetFeatureNumBin)."""
        self.construct()
        if isinstance(feature, str):
            feature = self.feature_names.index(feature)
        return int(self.binner.mappers[feature].num_bins)

    def _host_bins(self, what: str) -> np.ndarray:
        """Host binned matrix for paths that need the whole thing at once.
        Resident out_of_core datasets never parse host bins, but hold the
        assembled device matrix — materialize one host copy from it; the
        spill regime has neither, so those paths are outside its envelope."""
        if self.bins is not None:
            return self.bins
        if self.bins_device is not None:
            # cached in a SEPARATE attribute so bins stays None (the OOC
            # sentinel) — per-tree callers (categorical traversal during
            # rollback/replay) must not pay a full device->host pull each
            cache = getattr(self, "_host_bins_cache", None)
            if cache is None or cache[0] is not self.bins_device:
                cache = (self.bins_device, np.asarray(self.bins_device))
                self._host_bins_cache = cache
            return cache[1]
        raise LightGBMError(
            f"{what} needs the full binned matrix, but this out_of_core "
            "dataset exceeds max_rows_in_hbm (spill regime) and only "
            "streams bins in chunks — raise max_rows_in_hbm or drop "
            "out_of_core; see ops/treegrow_ooc.py")

    def add_features_from(self, other: "Dataset") -> "Dataset":
        """Column-concatenate another constructed dataset (reference:
        Dataset::AddFeaturesFrom)."""
        self.construct()
        other.construct()
        if self.num_data() != other.num_data():
            raise LightGBMError("Cannot add features from Dataset with a different number of rows")
        self.binner = DatasetBinner(mappers=list(self.binner.mappers) + list(other.binner.mappers))
        self.bins = np.concatenate(
            [self._host_bins("add_features_from"),
             other._host_bins("add_features_from")], axis=1)
        self.bins_device = jnp.asarray(self.bins, jnp.int16)
        self._bins_device_t = None
        self.num_bins_pf_device = jnp.asarray(self.binner.num_bins_per_feature)
        self.missing_bin_pf_device = jnp.asarray(self.binner.missing_bin_per_feature)
        self.max_num_bins = int(self.binner.max_num_bins)
        self.feature_names = list(self.feature_names) + list(other.feature_names)
        self._num_feature = len(self.feature_names)
        if self.data is not None and other.data is not None:
            self.data = np.column_stack([_to_2d_float(self.data), _to_2d_float(other.data)])
        self.efb = None  # bundling plan is stale after adding columns
        self._efb_device = None
        return self

    def create_valid(self, data, label=None, weight=None, group=None, init_score=None,
                     params=None) -> "Dataset":
        """reference: Dataset.create_valid — valid set sharing this dataset's
        bin mappers."""
        return Dataset(
            data, label=label, reference=self, weight=weight, group=group,
            init_score=init_score, params=params or self.params,
        )

    def subset(self, used_indices, params=None) -> "Dataset":
        """Row subset sharing bin mappers (reference: Dataset.subset/CopySubrow)."""
        self.construct()
        idx = np.asarray(used_indices, dtype=np.int64)
        sub = Dataset.__new__(Dataset)
        sub.__dict__.update({k: v for k, v in self.__dict__.items()})
        sub.bins = self._host_bins("subset")[idx]
        sub.bins_device = jnp.asarray(sub.bins, jnp.int16)
        sub._bins_device_t = None
        if getattr(self, "efb", None) is not None:
            sub.efb = self.efb._replace(bundled_bins=None)  # re-encoded lazily
            sub._efb_device = None
        if getattr(self, "raw_device", None) is not None:
            sub.raw_device = self.raw_device[jnp.asarray(idx)]
        sub.label = None if self.label is None else self.label[idx]
        sub.weight = None if self.weight is None else self.weight[idx]
        sub.init_score = None if self.init_score is None else self.init_score[idx]
        if self.group is not None:
            # rebuild group sizes from the selected rows' query ids
            # (reference: Metadata partitioning of query boundaries)
            qid = np.repeat(np.arange(len(self.group)), self.group)[idx]
            change = np.nonzero(np.diff(qid) != 0)[0] + 1
            bounds = np.concatenate([[0], change, [len(qid)]])
            sub.group = np.diff(bounds).astype(np.int64)
        else:
            sub.group = None
        sub._num_data = len(idx)
        sub._used_indices = idx
        sub._constructed = True
        return sub

    def save_binary(self, filename: str) -> "Dataset":
        """Binned dataset checkpoint (reference: Dataset::SaveBinaryFile).
        Uses npz rather than the reference's custom byte format; a Dataset
        constructed from the saved path reloads the binned matrix and
        mappers directly, skipping raw parsing/binning (reference:
        DatasetLoader::LoadFromBinFile)."""
        self.construct()
        if self.bins is None:
            raise LightGBMError(
                "save_binary needs the host binned matrix, which an "
                "out_of_core dataset deliberately never materializes — "
                "the source cache it streams from IS the binary file")
        # write to the EXACT filename (np.savez appends .npz to bare paths;
        # the reference honors the given name)
        with open(filename, "wb") as fh:
            self._savez_binary(fh)
        return self

    def _savez_binary(self, fh) -> None:
        # one writer for every save_binary cache (io/stream.py): the
        # per-chunk CRC32 trailer table BinCacheStream re-verifies on
        # every streamed sweep rides along, so a torn or bit-rotted cache
        # fails row-ranged instead of training on garbage bins
        # (docs/ROBUSTNESS.md); the continual runner creates and APPENDS
        # to the same format through write_bin_cache/append_rows
        from .io.stream import write_bin_cache

        write_bin_cache(
            fh, self.bins, self.binner.mappers,
            label=self.label, weight=self.weight, group=self.group,
            # reference Metadata persists init_score and positions too
            # (SaveBinaryFile/LoadFromBinFile round-trip)
            init_score=self.init_score, position=self.position,
            feature_names=self.feature_names,
        )

    # -- tree traversal on binned data ----------------------------------
    def predict_leaf_binned_tree(self, tree: Tree) -> jnp.ndarray:
        """Leaf index per row for one tree on this dataset's binned matrix.
        Pads node arrays to power-of-two buckets to bound jit recompiles.

        Spill-regime out_of_core datasets (no device-resident matrix)
        traverse CHUNK-WISE over the stream — the path crash-resume's
        score replay takes (docs/ROBUSTNESS.md "Elastic fleet recovery"):
        a resumed rank rebuilds its score state without ever
        materializing the matrix."""
        n = self.num_data()
        m = tree.num_internal
        if m == 0:
            return jnp.zeros((n,), jnp.int32)
        if tree.num_cat > 0 and self.bins_device is not None:
            # categorical nodes need bin-subset membership — host walk
            return jnp.asarray(
                tree.predict_leaf_binned_batch(
                    np.asarray(self._host_bins("categorical-tree traversal")),
                    self.binner)
            )
        # model-string-loaded trees: recover bin-space thresholds lazily
        self._tree_threshold_bin(tree)
        cap = 1
        while cap < m:
            cap *= 2

        def pad(a, fill=0):
            a = np.asarray(a)  # convert ONCE; dtype reads off the binding
            out = np.full(cap, fill, dtype=a.dtype)
            out[:m] = a[:m]
            return jnp.asarray(out[None])

        if self.bins_device is None:
            return self._predict_leaf_binned_tree_streamed(tree, pad)

        leaf = predict_ops.predict_leaf_binned(
            self.bins_device,
            self.missing_bin_pf_device,
            pad(tree.split_feature),
            pad(tree.threshold_bin),
            pad(tree.default_left()),
            pad(tree.left_child, fill=-1),
            pad(tree.right_child, fill=-1),
            jnp.asarray([tree.num_leaves], jnp.int32),
        )[0]
        return leaf

    def _tree_threshold_bin(self, tree: Tree) -> None:
        """Recover bin-space thresholds for a model-string-loaded tree
        (exact when thresholds are this binner's bin uppers — the
        reference stores bin uppers as thresholds)."""
        if tree.threshold_bin is not None or tree.num_cat > 0:
            return
        m = tree.num_internal
        tb = np.zeros(m, np.int32)
        for i in range(m):
            f = int(tree.split_feature[i])
            tb[i] = int(self.binner.mappers[f].transform(
                np.asarray([tree.threshold[i]]))[0])
        tree.threshold_bin = tb

    def predict_leaf_binned_trees_chunked(self, trees):
        """One stream sweep for MANY trees: yields ``(row_lo, valid,
        leaf)`` per chunk where ``leaf`` is the (T, chunk_rows) leaf
        matrix from the stacked traversal kernel.  The spill-regime
        resume replay path: T separate :meth:`predict_leaf_binned_tree`
        sweeps would re-decompress the save_binary cache T times; this
        pays ONE sequential pass for the whole ensemble."""
        trees = list(trees)
        if any(t.num_cat > 0 for t in trees):
            raise LightGBMError(
                "categorical trees are outside the chunked multi-tree "
                "traversal (spill-regime replay; ops/treegrow_ooc.py)")
        for t in trees:
            self._tree_threshold_bin(t)
        m_max = max((t.num_internal for t in trees), default=0)
        cap = 1
        while cap < max(m_max, 1):
            cap *= 2

        def stack(get, dtype, fill=0):
            out = np.full((len(trees), cap), fill, dtype=dtype)
            for ti, t in enumerate(trees):
                m = t.num_internal
                if m:
                    out[ti, :m] = np.asarray(get(t))[:m]
            return jnp.asarray(out)

        args = (
            self.missing_bin_pf_device,
            stack(lambda t: t.split_feature, np.int32),
            stack(lambda t: t.threshold_bin, np.int32),
            stack(lambda t: t.default_left(), np.bool_),
            stack(lambda t: t.left_child, np.int32, fill=-1),
            stack(lambda t: t.right_child, np.int32, fill=-1),
            jnp.asarray([t.num_leaves for t in trees], jnp.int32),
        )
        from .io.stream import prefetch_device

        for row_lo, valid, dev in prefetch_device(
                self.ooc_chunk_iter(), dtype=jnp.int16,
                pad_rows=self.ooc_chunk_rows):
            yield row_lo, valid, predict_ops.predict_leaf_binned(dev, *args)

    def _predict_leaf_cat_streamed(self, tree: Tree) -> jnp.ndarray:
        """Categorical-tree spill traversal: the stream yields HOST chunk
        views, so the bin-subset host walk runs per chunk — no matrix
        materialization (host walks are the resident categorical path's
        behavior too)."""
        parts = []
        for _row_lo, chunk in self.ooc_chunk_iter():
            parts.append(np.asarray(
                tree.predict_leaf_binned_batch(np.array(chunk),
                                               self.binner)))
        return jnp.asarray(np.concatenate(parts).astype(np.int32))

    def _predict_leaf_binned_tree_streamed(self, tree: Tree, pad):
        """Spill-regime traversal: sweep the bin stream once, traversing
        each uploaded chunk with the same jitted kernel the resident path
        uses (chunks are padded to the stream's fixed chunk rows so the
        whole sweep compiles once; the tail rides the same executable
        with its pad rows discarded).  Per-chunk leaves stay ON DEVICE
        and concatenate once at the end — the sweep adds no host pulls."""
        if tree.num_cat > 0:
            return self._predict_leaf_cat_streamed(tree)
        args = (
            self.missing_bin_pf_device,
            pad(tree.split_feature),
            pad(tree.threshold_bin),
            pad(tree.default_left()),
            pad(tree.left_child, fill=-1),
            pad(tree.right_child, fill=-1),
            jnp.asarray([tree.num_leaves], jnp.int32),
        )
        from .io.stream import prefetch_device

        parts = []
        for _row_lo, valid, dev in prefetch_device(
                self.ooc_chunk_iter(), dtype=jnp.int16,
                pad_rows=self.ooc_chunk_rows):
            leaf = predict_ops.predict_leaf_binned(dev, *args)[0]
            parts.append(leaf[:valid])
        return jnp.concatenate(parts) if len(parts) > 1 else parts[0]


class Booster:
    """reference: class Booster in python-package/lightgbm/basic.py."""

    def __init__(
        self,
        params: Optional[Dict[str, Any]] = None,
        train_set: Optional[Dataset] = None,
        model_file: Optional[str] = None,
        model_str: Optional[str] = None,
    ):
        self.params = dict(params or {})
        self.best_iteration = -1
        self.best_score: Dict[str, Dict[str, float]] = {}
        self._train_set = train_set
        if model_file is not None:
            # snapshots carry an integrity trailer (utils/checkpoint.py):
            # verify-and-strip so a torn file raises instead of parsing into
            # a half-model; plain model files (no trailer) load as before
            try:
                text = Path(model_file).read_text(encoding="utf-8")
            except UnicodeDecodeError as e:
                # bit rot / binary garbage: torn, not a crash — so the
                # engine's snapshot fallback can still run
                raise CorruptModelError(
                    f"{model_file} is not valid UTF-8 ({e}); the file is "
                    "corrupted") from None
            model_str, ok = _checkpoint.verify_text(text)
            if ok is False or (
                    ok is None and _checkpoint.is_snapshot_path(model_file)):
                # snapshots are always written WITH a trailer, so a
                # snapshot whose trailer is missing was truncated before
                # the trailer line — every bit as torn as a bad digest
                raise CorruptModelError(
                    f"{model_file} failed integrity verification (torn or "
                    "truncated checkpoint); resume from an older snapshot — "
                    "utils/checkpoint.py latest_valid_snapshot scans the "
                    "family, and engine.train falls back automatically")
        if model_str is not None:
            self._gbdt = GBDT.load_model_from_string(model_str)
            self.cfg = self._gbdt.cfg
        elif train_set is not None:
            if not isinstance(train_set, Dataset):
                raise TypeError("Training data should be Dataset instance")
            self.cfg = Config.from_dict(self.params)
            merged = dict(train_set.params or {})
            merged.update(self.params)
            train_set.params = merged
            self._gbdt = create_boosting(self.cfg, train_set)
        else:
            raise LightGBMError("need either params+train_set or a model")

    # -- training -------------------------------------------------------
    def update(self, train_set: Optional[Dataset] = None, fobj=None) -> bool:
        """One boosting iteration; returns True if training should stop
        (reference: Booster.update / LGBM_BoosterUpdateOneIter)."""
        if train_set is not None and train_set is not self._train_set:
            self._train_set = train_set
            self._gbdt.reset_training_data(train_set)
        if fobj is not None:
            score = self._gbdt._score
            grad, hess = fobj(np.asarray(score), self._gbdt.train_set)
            return self.__boost(grad, hess)
        return self._gbdt.train_one_iter()

    def __boost(self, grad, hess) -> bool:
        return self._gbdt.train_one_iter(np.asarray(grad), np.asarray(hess))

    def rollback_one_iter(self) -> "Booster":
        self._gbdt.rollback_one_iter()
        return self

    def add_valid(self, data: Dataset, name: str) -> "Booster":
        self._gbdt.add_valid(data, name)
        return self

    def reset_parameter(self, params: Dict[str, Any]) -> "Booster":
        """Mutate runtime-resettable params (reference: Booster.reset_parameter
        -> LGBM_BoosterResetParameter -> GBDT::ResetConfig)."""
        self.params.update(params)
        self._gbdt.cfg.update(params)
        self._gbdt.reset_split_params()
        return self

    def set_train_data_name(self, name: str) -> "Booster":
        """reference: Booster.set_train_data_name (eval printing label)."""
        self._train_data_name = name
        return self

    def shuffle_models(self, start_iteration: int = 0, end_iteration: int = -1) -> "Booster":
        """Shuffle tree order in [start, end) (reference:
        Booster.shuffle_models -> GBDT ShuffleModels)."""
        models = self._gbdt.models
        end = len(models) if end_iteration < 0 else min(end_iteration, len(models))
        seg = models[start_iteration:end]
        np.random.shuffle(seg)
        # mutation + version bump in ONE pack-lock section (round 19): a
        # concurrent serving pack build either completes before this and
        # stays consistent, or observes the bump at insert time and
        # rebuilds — it can never cache a half-shuffled pack
        with self._gbdt._plock():
            self._gbdt.models[start_iteration:end] = seg
            self._gbdt._invalidate_pred_cache("shuffle_models")
        return self

    def _init_score_offset(self) -> float:
        scores = getattr(self._gbdt, "init_scores", None) or [0.0]
        return float(scores[0]) if len(scores) == 1 else 0.0

    def lower_bound(self) -> float:
        """Minimum possible model output (reference: Booster.lower_bound ->
        GBDT::GetLowerBoundValue: sum over trees of min leaf value)."""
        return float(sum(
            float(np.min(t.leaf_value[: t.num_leaves])) for t in self._gbdt.models
        ) + self._init_score_offset())

    def upper_bound(self) -> float:
        """Maximum possible model output (reference: Booster.upper_bound)."""
        return float(sum(
            float(np.max(t.leaf_value[: t.num_leaves])) for t in self._gbdt.models
        ) + self._init_score_offset())

    def trees_to_dataframe(self):
        """Flatten the model into a pandas DataFrame, one row per node/leaf
        (reference: Booster.trees_to_dataframe)."""
        import pandas as pd

        def node_rows(tree_idx, struct, parent, depth, rows):
            if "split_index" in struct:
                idx = f"{tree_idx}-S{struct['split_index']}"
                rows.append({
                    "tree_index": tree_idx,
                    "node_depth": depth,
                    "node_index": idx,
                    "left_child": None,
                    "right_child": None,
                    "parent_index": parent,
                    "split_feature": struct["split_feature"],
                    "split_gain": struct["split_gain"],
                    "threshold": struct["threshold"],
                    "decision_type": struct["decision_type"],
                    "missing_direction": "left" if struct["default_left"] else "right",
                    "missing_type": struct["missing_type"],
                    "value": struct["internal_value"],
                    "weight": struct["internal_weight"],
                    "count": struct["internal_count"],
                })
                me = len(rows) - 1
                rows[me]["left_child"] = node_rows(
                    tree_idx, struct["left_child"], idx, depth + 1, rows)
                rows[me]["right_child"] = node_rows(
                    tree_idx, struct["right_child"], idx, depth + 1, rows)
                return idx
            idx = f"{tree_idx}-L{struct['leaf_index']}"
            rows.append({
                "tree_index": tree_idx,
                "node_depth": depth,
                "node_index": idx,
                "left_child": None,
                "right_child": None,
                "parent_index": parent,
                "split_feature": None,
                "split_gain": None,
                "threshold": None,
                "decision_type": None,
                "missing_direction": None,
                "missing_type": None,
                "value": struct["leaf_value"],
                "weight": struct.get("leaf_weight"),
                "count": struct.get("leaf_count"),
            })
            return idx

        model = self.dump_model()
        feature_names = model["feature_names"]
        rows: List[Dict[str, Any]] = []
        for t in model["tree_info"]:
            node_rows(t["tree_index"], t["tree_structure"], None, 1, rows)
        df = pd.DataFrame(rows)
        df["split_feature"] = df["split_feature"].map(
            lambda v: feature_names[int(v)] if v is not None and not pd.isna(v) else None
        )
        return df

    def current_iteration(self) -> int:
        return self._gbdt.iter_

    def num_trees(self) -> int:
        return len(self._gbdt.models)

    def num_model_per_iteration(self) -> int:
        return self._gbdt.num_tree_per_iteration

    def num_feature(self) -> int:
        return len(self._gbdt.feature_names)

    def feature_name(self) -> List[str]:
        return list(self._gbdt.feature_names)

    # -- eval -------------------------------------------------------------
    def eval_train(self, feval=None):
        return self._eval(0, self._gbdt.train_name, feval)

    def eval_valid(self, feval=None):
        out = []
        for i in range(len(self._gbdt.valid_sets)):
            out.extend(self._eval(i + 1, self._gbdt.valid_names[i], feval))
        return out

    def eval(self, data: Dataset, name: str, feval=None):
        for i, vs in enumerate(self._gbdt.valid_sets):
            if vs is data:
                return self._eval(i + 1, name, feval)
        self.add_valid(data, name)
        return self._eval(len(self._gbdt.valid_sets), name, feval)

    def _eval(self, data_idx: int, name: str, feval=None):
        res = [
            (name, mname, val, hib)
            for (_n, mname, val, hib) in self._gbdt.eval_at(data_idx)
        ]
        if feval is not None:
            ds = self._gbdt.train_set if data_idx == 0 else self._gbdt.valid_sets[data_idx - 1]
            score = self._gbdt._score if data_idx == 0 else self._gbdt._valid_scores[data_idx - 1]
            for r in _call_feval(feval, np.asarray(score), ds):
                res.append((name, r[0], r[1], r[2]))
        return res

    # -- prediction -------------------------------------------------------
    def predict(
        self,
        data,
        start_iteration: int = 0,
        num_iteration: Optional[int] = None,
        raw_score: bool = False,
        pred_leaf: bool = False,
        pred_contrib: bool = False,
        **kwargs,
    ) -> np.ndarray:
        if num_iteration is None:
            num_iteration = self.best_iteration if self.best_iteration > 0 else -1
        if _is_scipy_sparse(data):
            # bounded-memory sparse prediction: densify per row chunk only
            # (reference: the CSR predict path never materializes the full
            # dense matrix either).  Chunk rows from a byte budget so wide
            # matrices stay bounded too.
            chunk = max(1, int(512e6 // (max(data.shape[1], 1) * 8)))
            if data.shape[0] > chunk:
                csr = data.tocsr()
                outs = []
                for lo in range(0, csr.shape[0], chunk):
                    outs.append(self.predict(
                        csr[lo:lo + chunk], start_iteration=start_iteration,
                        num_iteration=num_iteration, raw_score=raw_score,
                        pred_leaf=pred_leaf, pred_contrib=pred_contrib,
                        **kwargs,
                    ))
                return np.concatenate(outs, axis=0)
        X = _to_2d_float(data)
        n_feat = self.num_feature()
        if n_feat and X.shape[1] != n_feat and not kwargs.get("predict_disable_shape_check", False):
            # reference: LGBM_BoosterPredictForMat raises on feature-count
            # mismatch unless predict_disable_shape_check is set
            raise LightGBMError(
                f"The number of features in data ({X.shape[1]}) is not the same "
                f"as it was in training data ({n_feat}). You can set "
                f"predict_disable_shape_check=true to discard this error."
            )
        return self._gbdt.predict(
            X,
            raw_score=raw_score,
            start_iteration=start_iteration,
            num_iteration=num_iteration,
            pred_leaf=pred_leaf,
            pred_contrib=pred_contrib,
            # giant-batch serving: mesh= shards the traversal over the row
            # axis in ONE SPMD dispatch, bitwise the single-device result
            mesh=kwargs.get("mesh"),
        )

    def predict_sharded(self, data, mesh, **kwargs) -> np.ndarray:
        """Row-sharded giant-batch :meth:`predict`: scores ``data`` as ONE
        SPMD dispatch over the row ("data") axis of ``mesh`` — bitwise the
        single-device result (models/gbdt.py predict_raw_sharded).  A 2-D
        training mesh works directly (rows shard, features replicate)."""
        return self.predict(data, mesh=mesh, **kwargs)

    def refit(self, data, label, decay_rate: float = 0.9, weight=None,
              **kwargs) -> "Booster":
        """Refit leaf values on new data (reference: GBDT::RefitTree via
        LGBM_BoosterRefit): new_leaf = decay * old + (1-decay) * new_optimal.

        Multiclass ensembles renew tree ``t`` against class ``t % k``'s
        gradient column, accumulating into a per-class score plane — the
        reference's iter-major RefitTree order.  ``weight`` optionally
        carries per-row sample weights into the gradient call (reference:
        RefitTree reuses the Dataset's weights)."""
        X = _to_2d_float(data)
        label = np.asarray(label, dtype=np.float64).ravel()
        new_booster = Booster(model_str=self.model_to_string())
        new_booster._gbdt.cfg = self.cfg
        gbdt = new_booster._gbdt
        k = gbdt.num_tree_per_iteration
        score = np.zeros((len(label), k) if k > 1 else len(label),
                         dtype=np.float64)
        w_dev = None
        if weight is not None:
            weight = np.asarray(weight, dtype=np.float64).ravel()
            if len(weight) != len(label):
                raise LightGBMError(
                    f"refit: {len(label)} labels but {len(weight)} weights")
            w_dev = jnp.asarray(weight, jnp.float32)
        from .objectives import create_objective

        obj = create_objective(self.cfg)
        for t_i, tree in enumerate(gbdt.models):
            leaf = tree.predict_leaf(X)
            g, h = obj.get_gradients(jnp.asarray(score, jnp.float32), jnp.asarray(label, jnp.float32), w_dev)
            g, h = np.asarray(g, np.float64), np.asarray(h, np.float64)
            if k > 1:  # tree t renews against its class column c = t % k
                c = t_i % k
                g, h = g[:, c], h[:, c]
            sum_g = np.bincount(leaf, weights=g, minlength=tree.num_leaves)
            sum_h = np.bincount(leaf, weights=h, minlength=tree.num_leaves)
            lam2 = self.cfg.lambda_l2
            new_vals = -sum_g / (sum_h + lam2 + 1e-15) * tree.shrinkage
            tree.leaf_value = decay_rate * tree.leaf_value + (1.0 - decay_rate) * np.where(
                sum_h > 0, new_vals, tree.leaf_value
            )
            if k > 1:
                score[:, t_i % k] += tree.predict(X)
            else:
                score += tree.predict(X)
        gbdt._invalidate_pred_cache("refit")  # leaf values renewed in place
        # (bump-on-mutate: in-flight serving readers keep the old pack)
        return new_booster

    # -- serialization ----------------------------------------------------
    def model_to_string(self, num_iteration: int = -1, start_iteration: int = 0,
                        importance_type: str = None,
                        raw_deltas: bool = False) -> str:
        # None defers to saved_feature_importance_type (reference: config).
        # raw_deltas: snapshot form — pure-delta trees + init_scores header
        # line, the bitwise-resume contract (docs/ROBUSTNESS.md)
        return self._gbdt.save_model_to_string(
            num_iteration, start_iteration, importance_type,
            raw_deltas=raw_deltas)

    def save_model(self, filename, num_iteration: int = -1, start_iteration: int = 0,
                   importance_type: str = None) -> "Booster":
        # atomic (temp + os.replace): a crash mid-write leaves the previous
        # file intact instead of a torn model (docs/ROBUSTNESS.md)
        _checkpoint.atomic_write_text(
            filename,
            self.model_to_string(num_iteration, start_iteration, importance_type))
        return self

    @classmethod
    def model_from_string(cls, model_str: str) -> "Booster":
        return cls(model_str=model_str)

    def dump_model(self, num_iteration: int = -1, start_iteration: int = 0) -> Dict[str, Any]:
        """JSON model dump (reference: GBDT::DumpModel)."""
        g = self._gbdt
        trees = []
        k = g.num_tree_per_iteration
        lo = start_iteration * k
        hi = len(g.models) if num_iteration < 0 else min((start_iteration + num_iteration) * k, len(g.models))
        for idx, t in enumerate(g.models[lo:hi]):
            trees.append({
                "tree_index": idx,
                "num_leaves": t.num_leaves,
                "num_cat": t.num_cat,
                "shrinkage": t.shrinkage,
                "tree_structure": _dump_node(t, 0 if t.num_internal else -1),
            })
        return {
            "name": "tree",
            "version": "v4",
            "num_class": self.cfg.num_class if hasattr(self, "cfg") else 1,
            "num_tree_per_iteration": k,
            "label_index": 0,
            "max_feature_idx": len(g.feature_names) - 1,
            "objective": g._objective_string(),
            "average_output": g.average_output,
            "feature_names": list(g.feature_names),
            "monotone_constraints": [],
            "feature_infos": {},
            "tree_info": trees,
        }

    def feature_importance(self, importance_type: str = "split", iteration=None) -> np.ndarray:
        return self._gbdt.feature_importance(importance_type)

    def get_split_value_histogram(self, feature, bins=None, xgboost_style: bool = False):
        """Histogram of a feature's split thresholds across the model
        (reference: basic.py Booster.get_split_value_histogram)."""
        if isinstance(feature, str):
            names = self.feature_name()
            if feature not in names:
                raise ValueError(f"Unknown feature name {feature!r}")
            feature = names.index(feature)
        values = []
        for tree in self._gbdt.models:
            is_cat = tree.is_categorical_node()
            for node in range(tree.num_internal):
                if int(tree.split_feature[node]) == feature and not is_cat[node]:
                    values.append(float(tree.threshold[node]))
        values = np.array(values, dtype=np.float64)
        if bins is None or (isinstance(bins, int) and bins > len(values)):
            bins = max(len(values), 1)
        hist, bin_edges = np.histogram(values, bins=bins)
        if xgboost_style:
            ret = np.column_stack((bin_edges[1:], hist))
            ret = ret[ret[:, 1] > 0]
            try:
                import pandas as pd

                return pd.DataFrame(ret, columns=["SplitValue", "Count"])
            except ImportError:
                return ret
        return hist, bin_edges

    # network API compatibility (collectives are XLA's job on TPU)
    def set_network(self, *args, **kwargs) -> "Booster":
        return self

    def free_network(self) -> "Booster":
        return self

    def free_dataset(self) -> "Booster":
        self._train_set = None
        return self

    def set_leaf_output(self, tree_id: int, leaf_id: int, value: float) -> "Booster":
        # in-place edit + version bump atomically under the pack lock
        # (round 19): in-flight serving readers keep the old pack, and a
        # pack build racing this edit retries instead of caching a torn one
        with self._gbdt._plock():
            self._gbdt.models[tree_id].leaf_value[leaf_id] = value
            self._gbdt._invalidate_pred_cache("set_leaf_output")
        return self

    def get_leaf_output(self, tree_id: int, leaf_id: int) -> float:
        return float(self._gbdt.models[tree_id].leaf_value[leaf_id])


def _dump_node(tree: Tree, node: int) -> Dict[str, Any]:
    if node < 0 or tree.num_internal == 0:
        leaf = -node - 1 if node < 0 else 0
        return {
            "leaf_index": leaf,
            "leaf_value": float(tree.leaf_value[leaf]),
            "leaf_weight": float(tree.leaf_weight[leaf]) if len(tree.leaf_weight) > leaf else 0.0,
            "leaf_count": int(tree.leaf_count[leaf]) if len(tree.leaf_count) > leaf else 0,
        }
    return {
        "split_index": node,
        "split_feature": int(tree.split_feature[node]),
        "split_gain": float(tree.split_gain[node]),
        "threshold": float(tree.threshold[node]),
        "decision_type": "<=",
        "default_left": bool(tree.default_left()[node]),
        "missing_type": ["None", "Zero", "NaN"][(int(tree.decision_type[node]) >> 2) & 3],
        "internal_value": float(tree.internal_value[node]),
        "internal_weight": float(tree.internal_weight[node]),
        "internal_count": int(tree.internal_count[node]),
        "left_child": _dump_node(tree, tree.left_child[node]),
        "right_child": _dump_node(tree, tree.right_child[node]),
    }


def _call_feval(feval, score: np.ndarray, ds: Dataset):
    ret = feval(score, ds)
    if ret is None:
        return []
    if isinstance(ret, list):
        return ret
    return [ret]
