#!/usr/bin/env python
"""Standalone jaxlint runner for pre-commit use:

    python helpers/run_jaxlint.py                  # scan lightgbm_tpu/
    python helpers/run_jaxlint.py --show-suppressed
    python helpers/run_jaxlint.py lightgbm_tpu/ops --rules R1,R3

Exit code 0 = clean (same contract tests/test_jaxlint_gate.py enforces in
tier-1), 1 = unsuppressed findings, 2 = bad usage.  Runs without touching
JAX device state, so it is safe anywhere — no TPU, no compile cache.
"""

import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from lightgbm_tpu.analysis.__main__ import main  # noqa: E402

if __name__ == "__main__":
    argv = sys.argv[1:]
    if not any(not a.startswith("-") for a in argv):
        pkg = Path(__file__).resolve().parent.parent / "lightgbm_tpu"
        argv = [str(pkg)] + argv
    sys.exit(main(argv))
