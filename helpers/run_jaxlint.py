#!/usr/bin/env python
"""Standalone static-analysis runner for pre-commit use — ALL layers:

    python helpers/run_jaxlint.py                  # AST lint + locks + jaxpr
    python helpers/run_jaxlint.py --ast-only       # R-rules only, no JAX
    python helpers/run_jaxlint.py --locks-only     # L-rules only, no JAX
    python helpers/run_jaxlint.py --no-runtime     # audit without the
                                                   # executing ledger check
    python helpers/run_jaxlint.py --show-suppressed
    python helpers/run_jaxlint.py lightgbm_tpu/ops --rules R1,R3
    python helpers/run_jaxlint.py --jaxpr --contract windowed_round_float

Layer 1 (jaxlint, rules R1-R17) scans source ASTs and runs without
touching JAX device state.  Layer 2 (the concurrency layer, rules L1-L5,
analysis/locks.py) builds the whole-package lock model and checks lock
ordering, blocking calls under locks, guard discipline, Condition.wait
predicates, and thread lifecycle — also pure AST, also no JAX.  Layer 3
(jaxpr audit, rules J1-J6) traces the registered flagship executables
hermetically on the host CPU and verifies their IR contracts
(analysis/contracts.py) — the layer that sees through the
closure-dispatched round body.  A default full scan runs layers 1+2 in
one pass (same rule registry) and piggybacks layer 3 behind them;
``--ast-only`` / ``--locks-only`` scope to one AST-side layer, and
``--list-rules``, ``--rules`` subsets, and explicit sub-package paths
keep the run scoped the same way (a scoped question gets a scoped
answer; the audit is whole-package by nature and costs real tracing
time).  Exit code 0 = clean (the contract tests/test_jaxlint_gate.py +
tests/test_lock_lint.py + tests/test_jaxpr_audit.py enforce in tier-1),
1 = findings, 2 = bad usage.
"""

import os
import sys
from pathlib import Path

# the jaxpr layer's sharded contracts want a loopback multi-device mesh;
# this must land BEFORE the lightgbm_tpu import below pulls jax in (under
# `python -m lightgbm_tpu.analysis` the parent package import beats main(),
# so the audit there runs on however many devices already exist — the
# contracts trace identically, only the lowering differs)
if "xla_force_host_platform_device_count" not in os.environ.get(
        "XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                               + " --xla_force_host_platform_device_count=8"
                               ).strip()

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from lightgbm_tpu.analysis.__main__ import main  # noqa: E402
from lightgbm_tpu.analysis.core import RULES  # noqa: E402

if __name__ == "__main__":
    argv = sys.argv[1:]
    ast_only = "--ast-only" in argv
    locks_only = "--locks-only" in argv
    argv = [a for a in argv if a not in ("--ast-only", "--locks-only")]
    jaxpr_flags = ("--jaxpr", "--contract", "--list-contracts")
    jaxpr_only = any(a.startswith(f) for a in argv for f in jaxpr_flags)
    if (ast_only or locks_only) and jaxpr_only:
        print("error: --ast-only/--locks-only contradict --jaxpr/"
              "--contract/--list-contracts", file=sys.stderr)
        sys.exit(2)
    if ast_only and locks_only:
        print("error: --ast-only contradicts --locks-only (a default run "
              "covers both layers)", file=sys.stderr)
        sys.exit(2)
    # the jaxpr layer only piggybacks on FULL default scans: an
    # informational run (--list-rules) or a scoped one (--rules,
    # --ast-only/--locks-only, explicit sub-package paths) asked a
    # narrow question, and silently paying the whole audit behind it
    # would be a surprise
    narrow = any(a.startswith(("--rules", "--list-rules")) for a in argv)
    scoped = any(not a.startswith("-") for a in argv)
    if locks_only:
        if narrow:
            print("error: --locks-only contradicts --rules/--list-rules",
                  file=sys.stderr)
            sys.exit(2)
        argv = ["--locks"] + argv
    elif ast_only:
        if narrow:
            print("error: --ast-only contradicts --rules/--list-rules",
                  file=sys.stderr)
            sys.exit(2)
        # scope to the R-layer by explicit rule selection: the L rules
        # share the registry, so a bare default run covers both
        ast_rules = ",".join(sorted(
            rid for rid, rule in RULES.items() if rule.layer == "ast"))
        argv = ["--rules", ast_rules] + argv
    if not scoped:
        pkg = Path(__file__).resolve().parent.parent / "lightgbm_tpu"
        argv = ([] if jaxpr_only else [str(pkg)]) + argv
    if jaxpr_only:
        sys.exit(main(argv))
    rc = main(argv)
    if not (ast_only or locks_only or narrow or scoped):
        # layer 3 shares the exit-code contract; forward the flags it
        # understands (--no-runtime skips the executing ledger check)
        passthru = [a for a in argv
                    if a in ("--show-suppressed", "--no-runtime")]
        rc = max(rc, main(["--jaxpr"] + passthru))
    sys.exit(rc)
