"""Generate docs/Parameters.md from the config table (reference analogue:
helpers/parameter_generator.py regenerating config_auto.cpp from
docs/Parameters.rst — here the Python dataclass IS the single source of
truth and the doc is generated FROM it, with an idempotency test keeping
them in sync: tests/test_parameter_docs.py)."""

from __future__ import annotations

import sys
from dataclasses import fields, MISSING
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

from lightgbm_tpu.config import _ALIASES, Config  # noqa: E402


def generate() -> str:
    alias_of = {}
    for alias, canon in _ALIASES.items():
        alias_of.setdefault(canon, []).append(alias)

    lines = [
        "# Parameters",
        "",
        "Generated from `lightgbm_tpu/config.py` by `helpers/parameter_docs.py`",
        "(the config dataclass is the single source of truth — reference",
        "analogue: docs/Parameters.rst <-> config_auto.cpp).",
        "Do not edit by hand; run `python helpers/parameter_docs.py` to",
        "regenerate.",
        "",
        "| parameter | default | type | aliases |",
        "|---|---|---|---|",
    ]
    for f in fields(Config):
        if f.name == "_explicit":  # bookkeeping, not a parameter
            continue
        if f.default is not MISSING:
            default = f.default
        elif f.default_factory is not MISSING:  # type: ignore[misc]
            default = f.default_factory()  # type: ignore[misc]
        else:
            default = ""
        tname = getattr(f.type, "__name__", None) or str(f.type)
        aliases = ", ".join(sorted(alias_of.get(f.name, [])))
        default_s = repr(default) if default != "" or isinstance(default, str) else ""
        lines.append(f"| `{f.name}` | `{default_s}` | {tname} | {aliases} |")
    lines.append("")
    n_params = sum(1 for f in fields(Config) if f.name != "_explicit")
    lines.append(f"Total: {n_params} parameters, {len(_ALIASES)} aliases.")
    lines.append("")
    return "\n".join(lines)


def main() -> None:
    out = Path(__file__).resolve().parents[1] / "docs" / "Parameters.md"
    out.write_text(generate())
    print(f"wrote {out}")


if __name__ == "__main__":
    main()
