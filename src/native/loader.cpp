// Native data loader: fast CSV/TSV/LibSVM text parsing to a dense matrix.
//
// TPU-native equivalent of the reference's C++ data-loading path
// (reference: src/io/parser.cpp -> CSVParser/TSVParser/LibSVMParser +
// src/io/dataset_loader.cpp -> DatasetLoader::LoadFromFile and
// include/LightGBM/utils/text_reader.h -> TextReader chunked reads).
// The heavy lifting — tokenizing millions of text rows — stays native and
// OpenMP-parallel exactly as in the reference; binning + device transfer
// happen in Python/JAX afterwards (host binning is numpy-vectorized and the
// training hot path is on-device, so parsing is the only text-speed-critical
// stage).
//
// Exposed as a tiny C ABI consumed via ctypes (no pybind11 in this image).
//
// Build: g++ -O3 -fPIC -shared -fopenmp -o _loader.so loader.cpp

#include <cctype>
#include <cmath>
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <limits>
#include <fstream>
#include <string>
#include <vector>

#ifdef _OPENMP
#include <omp.h>
#endif

namespace {

// Fast double parse (reference: Common::Atof / fast_double_parser vendored
// lib).  strtod is locale-dependent but this tool always writes C locale.
inline double parse_double(const char* p, const char** end) {
  return std::strtod(p, const_cast<char**>(end));
}

struct ParseResult {
  std::vector<double> data;  // row-major n x f
  std::vector<double> label;
  int64_t n = 0;
  int64_t f = 0;
  std::string error;
};

// Find the byte offset of each line start.
std::vector<size_t> line_offsets(const std::string& buf) {
  std::vector<size_t> offs;
  offs.push_back(0);
  for (size_t i = 0; i < buf.size(); ++i) {
    if (buf[i] == '\n' && i + 1 < buf.size()) offs.push_back(i + 1);
  }
  return offs;
}

inline bool is_blank_line(const char* p, const char* lend) {
  while (p < lend && (*p == ' ' || *p == '\t' || *p == '\r')) ++p;
  return p == lend;
}

// format: 0=csv, 1=tsv, 2=libsvm
int detect_format(const std::string& buf, size_t start) {
  const char* p = buf.c_str() + start;
  const char* e = buf.c_str() + buf.size();
  const char* lend = static_cast<const char*>(memchr(p, '\n', e - p));
  if (!lend) lend = e;
  // libsvm: second token contains ':'
  const char* q = p;
  while (q < lend && *q != ' ' && *q != '\t' && *q != ',') ++q;
  const char* r = q;
  while (r < lend && (*r == ' ' || *r == '\t')) ++r;
  const char* colon = static_cast<const char*>(memchr(r, ':', lend - r));
  const char* space = static_cast<const char*>(memchr(r, ' ', lend - r));
  if (colon && (!space || colon < space)) return 2;
  if (memchr(p, '\t', lend - p)) return 1;
  return 0;
}

void parse_delim(const std::string& buf, const std::vector<size_t>& lines,
                 char delim, int label_idx, ParseResult* out) {
  const int64_t n = static_cast<int64_t>(lines.size());
  // column count from the first line
  {
    const char* p = buf.c_str() + lines[0];
    const char* e = buf.c_str() + buf.size();
    const char* lend = static_cast<const char*>(memchr(p, '\n', e - p));
    if (!lend) lend = e;
    int64_t cols = 1;
    for (const char* q = p; q < lend; ++q)
      if (*q == delim) ++cols;
    out->f = (label_idx >= 0 && label_idx < cols) ? cols - 1 : cols;
  }
  out->n = n;
  // NaN-init so trailing/absent delimited fields read as missing, matching
  // the numpy fallback (np.full(..., nan)); LibSVM below stays 0.0 (sparse).
  out->data.assign(static_cast<size_t>(n) * out->f,
                   std::numeric_limits<double>::quiet_NaN());
  out->label.assign(n, 0.0);
  const int64_t f = out->f;
  bool ok = true;
#pragma omp parallel for schedule(static)
  for (int64_t i = 0; i < n; ++i) {
    const char* p = buf.c_str() + lines[i];
    const char* e = buf.c_str() + buf.size();
    const char* lend = static_cast<const char*>(memchr(p, '\n', e - p));
    if (!lend) lend = e;
    double* row = out->data.data() + i * f;
    int64_t col = 0, feat = 0;
    while (p < lend && feat <= f) {
      const char* tend;
      double v;
      // empty field or NA -> NaN
      const char* q = p;
      while (q < lend && *q != delim) ++q;
      if (q == p || (q - p >= 2 && (p[0] == 'N' || p[0] == 'n') &&
                     (p[1] == 'A' || p[1] == 'a'))) {
        v = std::nan("");
        tend = q;
      } else {
        v = parse_double(p, &tend);
        if (tend == p) v = std::nan("");
      }
      if (col == label_idx) {
        out->label[i] = v;
      } else if (feat < f) {
        row[feat++] = v;
      }
      ++col;
      p = q + (q < lend ? 1 : 0);
    }
    (void)ok;
  }
}

void parse_libsvm(const std::string& buf, const std::vector<size_t>& lines,
                  ParseResult* out) {
  const int64_t n = static_cast<int64_t>(lines.size());
  out->n = n;
  out->label.assign(n, 0.0);
  // pass 1: max feature index (1-based in libsvm files; 0-based accepted)
  int64_t maxf = -1;
#pragma omp parallel for schedule(static) reduction(max : maxf)
  for (int64_t i = 0; i < n; ++i) {
    const char* p = buf.c_str() + lines[i];
    const char* e = buf.c_str() + buf.size();
    const char* lend = static_cast<const char*>(memchr(p, '\n', e - p));
    if (!lend) lend = e;
    // skip label
    while (p < lend && *p != ' ' && *p != '\t') ++p;
    while (p < lend) {
      while (p < lend && (*p == ' ' || *p == '\t')) ++p;
      if (p >= lend) break;
      const char* tend;
      long idx = std::strtol(p, const_cast<char**>(&tend), 10);
      if (tend == p) break;
      if (idx > maxf) maxf = idx;
      p = tend;
      if (p < lend && *p == ':') {
        ++p;
        parse_double(p, &tend);
        p = tend;
      }
    }
  }
  out->f = maxf + 1;
  out->data.assign(static_cast<size_t>(n) * out->f, 0.0);
  const int64_t f = out->f;
#pragma omp parallel for schedule(static)
  for (int64_t i = 0; i < n; ++i) {
    const char* p = buf.c_str() + lines[i];
    const char* e = buf.c_str() + buf.size();
    const char* lend = static_cast<const char*>(memchr(p, '\n', e - p));
    if (!lend) lend = e;
    const char* tend;
    out->label[i] = parse_double(p, &tend);
    p = tend;
    double* row = out->data.data() + i * f;
    while (p < lend) {
      while (p < lend && (*p == ' ' || *p == '\t')) ++p;
      if (p >= lend || *p == '#') break;
      long idx = std::strtol(p, const_cast<char**>(&tend), 10);
      if (tend == p) break;
      p = tend;
      double v = 1.0;
      if (p < lend && *p == ':') {
        ++p;
        v = parse_double(p, &tend);
        p = tend;
      }
      if (idx >= 0 && idx < f) row[idx] = v;
    }
  }
}

}  // namespace

extern "C" {

// Parse a text file.  format: -1 auto, 0 csv, 1 tsv, 2 libsvm.
// label_idx: column index of the label for csv/tsv (-1 = no label column).
// has_header: skip the first non-comment line.
// Returns 0 on success.  Caller frees *out_data / *out_label via lgbmtpu_free.
int lgbmtpu_parse_file(const char* path, int format, int has_header,
                       int label_idx, double** out_data, double** out_label,
                       int64_t* out_n, int64_t* out_f) {
  std::ifstream in(path, std::ios::binary);
  if (!in) return 1;
  std::string buf((std::istreambuf_iterator<char>(in)),
                  std::istreambuf_iterator<char>());
  if (buf.empty()) return 2;

  // line starts, skipping comments ('#') and blank lines
  std::vector<size_t> lines;
  for (size_t off : line_offsets(buf)) {
    const char* p = buf.c_str() + off;
    const char* e = buf.c_str() + buf.size();
    const char* lend = static_cast<const char*>(memchr(p, '\n', e - p));
    if (!lend) lend = e;
    if (is_blank_line(p, lend) || *p == '#') continue;
    lines.push_back(off);
  }
  if (lines.empty()) return 2;
  if (format < 0) format = detect_format(buf, lines[0]);
  if (has_header && format != 2 && lines.size() > 1)
    lines.erase(lines.begin());

  ParseResult res;
  if (format == 2) {
    parse_libsvm(buf, lines, &res);
  } else {
    parse_delim(buf, lines, format == 1 ? '\t' : ',', label_idx, &res);
  }
  *out_n = res.n;
  *out_f = res.f;
  double* d = static_cast<double*>(malloc(sizeof(double) * res.data.size()));
  double* l = static_cast<double*>(malloc(sizeof(double) * res.label.size()));
  if (!d || !l) {
    free(d);
    free(l);
    return 3;
  }
  memcpy(d, res.data.data(), sizeof(double) * res.data.size());
  memcpy(l, res.label.data(), sizeof(double) * res.label.size());
  *out_data = d;
  *out_label = l;
  return 0;
}

void lgbmtpu_free(double* p) { free(p); }

int lgbmtpu_num_threads() {
#ifdef _OPENMP
  return omp_get_max_threads();
#else
  return 1;
#endif
}

}  // extern "C"
