/* C API for lightgbm_tpu — the reference's `LGBM_*` FFI surface
 * (reference: include/LightGBM/c_api.h, src/c_api.cpp) re-hosted over the
 * TPU-native Python/JAX core.  The shim embeds CPython: handles are
 * refcounted lightgbm_tpu.Booster objects, array arguments cross as raw
 * pointers wrapped zero-copy by numpy on the Python side
 * (lightgbm_tpu/capi_helpers.py).
 *
 * Return convention matches the reference: 0 = success, -1 = failure with
 * the message available via LGBM_GetLastError().
 */
#ifndef LIGHTGBM_TPU_C_API_H_
#define LIGHTGBM_TPU_C_API_H_

#include <stddef.h>
#include <stdint.h>

#ifdef __cplusplus
extern "C" {
#endif

typedef void* BoosterHandle;
typedef void* DatasetHandle;

#define C_API_PREDICT_NORMAL 0
#define C_API_PREDICT_RAW_SCORE 1
#define C_API_PREDICT_LEAF_INDEX 2
#define C_API_PREDICT_CONTRIB 3

/* reference: C_API_DTYPE_* */
#define C_API_DTYPE_FLOAT32 0
#define C_API_DTYPE_FLOAT64 1
#define C_API_DTYPE_INT32 2
#define C_API_DTYPE_INT64 3

#define C_API_FEATURE_IMPORTANCE_SPLIT 0
#define C_API_FEATURE_IMPORTANCE_GAIN 1

const char* LGBM_GetLastError(void);

/* ---- Dataset surface (reference: LGBM_Dataset*) ---- */

/* data: (nrow x ncol) matrix of `data_type`; parameters: "k=v k=v";
 * reference: bin-alignment dataset or NULL. */
int LGBM_DatasetCreateFromMat(const void* data,
                              int data_type,
                              int32_t nrow,
                              int32_t ncol,
                              int is_row_major,
                              const char* parameters,
                              const DatasetHandle reference,
                              DatasetHandle* out);

int LGBM_DatasetCreateFromFile(const char* filename,
                               const char* parameters,
                               const DatasetHandle reference,
                               DatasetHandle* out);

/* Streaming construction: preallocate by reference, push row blocks
 * (reference: LGBM_DatasetCreateByReference / LGBM_DatasetPushRows). */
int LGBM_DatasetCreateByReference(const DatasetHandle reference,
                                  int64_t num_total_row,
                                  DatasetHandle* out);

int LGBM_DatasetPushRows(DatasetHandle handle,
                         const void* data,
                         int data_type,
                         int32_t nrow,
                         int32_t ncol,
                         int32_t start_row);

int LGBM_DatasetFree(DatasetHandle handle);

/* field_name: label/weight/group/init_score/position. */
int LGBM_DatasetSetField(DatasetHandle handle,
                         const char* field_name,
                         const void* field_data,
                         int num_element,
                         int type);

int LGBM_DatasetGetNumData(DatasetHandle handle, int32_t* out);

int LGBM_DatasetGetNumFeature(DatasetHandle handle, int32_t* out);

/* ---- Booster training surface (reference: LGBM_Booster*) ---- */

int LGBM_BoosterCreate(const DatasetHandle train_data,
                       const char* parameters,
                       BoosterHandle* out);

int LGBM_BoosterAddValidData(BoosterHandle handle, const DatasetHandle valid_data);

int LGBM_BoosterUpdateOneIter(BoosterHandle handle, int* is_finished);

/* grad/hess: float32[num_data * num_class], caller-computed objective. */
int LGBM_BoosterUpdateOneIterCustom(BoosterHandle handle,
                                    const float* grad,
                                    const float* hess,
                                    int* is_finished);

int LGBM_BoosterRollbackOneIter(BoosterHandle handle);

int LGBM_BoosterGetCurrentIteration(BoosterHandle handle, int* out_iteration);

int LGBM_BoosterNumberOfTotalModel(BoosterHandle handle, int* out_models);

int LGBM_BoosterGetNumFeature(BoosterHandle handle, int* out_len);

int LGBM_BoosterResetParameter(BoosterHandle handle, const char* parameters);

/* Swap the training data under an existing booster; trees already grown
 * are kept (reference: GBDT::ResetTrainingData). */
int LGBM_BoosterResetTrainingData(BoosterHandle handle,
                                  const DatasetHandle train_data);

/* Number of bins of one feature, incl. missing/offset slots (reference:
 * LGBM_DatasetGetFeatureNumBin -> Dataset::FeatureNumBin). */
int LGBM_DatasetGetFeatureNumBin(DatasetHandle handle, int feature_idx,
                                 int* out);

int LGBM_BoosterGetEvalCounts(BoosterHandle handle, int* out_len);

/* data_idx: 0 = train, i = i-th validation set. */
int LGBM_BoosterGetEval(BoosterHandle handle,
                        int data_idx,
                        int* out_len,
                        double* out_results);

/* out_str: caller buffer of buffer_len bytes; *out_len receives the
 * required size incl. NUL (call twice to size, like the reference). */
int LGBM_BoosterSaveModelToString(BoosterHandle handle,
                                  int start_iteration,
                                  int num_iteration,
                                  int feature_importance_type,
                                  int64_t buffer_len,
                                  int64_t* out_len,
                                  char* out_str);

int LGBM_BoosterDumpModel(BoosterHandle handle,
                          int start_iteration,
                          int num_iteration,
                          int feature_importance_type,
                          int64_t buffer_len,
                          int64_t* out_len,
                          char* out_str);

/* out_results: double[num_feature]. */
int LGBM_BoosterFeatureImportance(BoosterHandle handle,
                                  int num_iteration,
                                  int importance_type,
                                  double* out_results);

int LGBM_BoosterCreateFromModelfile(const char* filename,
                                    int* out_num_iterations,
                                    BoosterHandle* out);

int LGBM_BoosterLoadModelFromString(const char* model_str,
                                    int* out_num_iterations,
                                    BoosterHandle* out);

int LGBM_BoosterFree(BoosterHandle handle);

int LGBM_BoosterGetNumClasses(BoosterHandle handle, int* out_len);

int LGBM_BoosterSaveModel(BoosterHandle handle,
                          int start_iteration,
                          int num_iteration,
                          int feature_importance_type,
                          const char* filename);

/* ---- CSR ingestion & prediction (reference: LGBM_DatasetCreateFromCSR,
 * LGBM_BoosterPredictForCSR).  indptr_type / data_type use the
 * C_API_DTYPE codes (0=f32 1=f64 2=i32 3=i64); indices are int32. */
int LGBM_DatasetCreateFromCSR(const void* indptr,
                              int indptr_type,
                              const int32_t* indices,
                              const void* data,
                              int data_type,
                              int64_t nindptr,
                              int64_t nelem,
                              int64_t num_col,
                              const char* parameters,
                              const DatasetHandle reference,
                              DatasetHandle* out);

int LGBM_BoosterPredictForCSR(BoosterHandle handle,
                              const void* indptr,
                              int indptr_type,
                              const int32_t* indices,
                              const void* data,
                              int data_type,
                              int64_t nindptr,
                              int64_t nelem,
                              int64_t num_col,
                              int predict_type,
                              int start_iteration,
                              int num_iteration,
                              const char* parameter,
                              int64_t* out_len,
                              double* out_result);

/* ---- sparse-output SHAP prediction (reference:
 * LGBM_BoosterPredictSparseOutput / LGBM_BoosterFreePredictSparse).
 * predict_type must be C_API_PREDICT_CONTRIB; matrix_type 0 = CSR input
 * and output, 1 = CSC (num_col_or_row = #cols for CSR, #rows for CSC).
 * The library malloc()s *out_indptr/*out_indices/*out_data; release them
 * with LGBM_BoosterFreePredictSparse.  Output data is written in the
 * requested data_type (C_API_DTYPE_FLOAT32 or _FLOAT64, matching the
 * reference's per-type allocation).  out_len[0] = indptr length,
 * out_len[1] = nnz. */
#define C_API_MATRIX_TYPE_CSR 0
#define C_API_MATRIX_TYPE_CSC 1

int LGBM_BoosterPredictSparseOutput(BoosterHandle handle,
                                    const void* indptr,
                                    int indptr_type,
                                    const int32_t* indices,
                                    const void* data,
                                    int data_type,
                                    int64_t nindptr,
                                    int64_t nelem,
                                    int64_t num_col_or_row,
                                    int predict_type,
                                    int start_iteration,
                                    int num_iteration,
                                    const char* parameter,
                                    int matrix_type,
                                    int64_t* out_len,
                                    void** out_indptr,
                                    int32_t** out_indices,
                                    void** out_data);

int LGBM_BoosterFreePredictSparse(void* indptr, int32_t* indices, void* data,
                                  int indptr_type, int data_type);

/* Row-callback dataset construction (reference:
 * LGBM_DatasetCreateFromCSRFunc): get_row_funptr is a
 * std::function<void(int idx, std::vector<std::pair<int, double>>&)>*
 * invoked once per row, exactly the reference's C++-ABI contract. */
int LGBM_DatasetCreateFromCSRFunc(void* get_row_funptr,
                                  int num_rows,
                                  int64_t num_col,
                                  const char* parameters,
                                  const DatasetHandle reference,
                                  DatasetHandle* out);

/* ---- single-row predict, plain and Fast (reference: SingleRowPredictor,
 * FastConfigHandle — the Fast variants freeze predict settings into an
 * opaque handle so the per-call path is minimal). */
typedef void* FastConfigHandle;

int LGBM_BoosterPredictForMatSingleRow(BoosterHandle handle,
                                       const void* data,
                                       int data_type,
                                       int32_t ncol,
                                       int is_row_major,
                                       int predict_type,
                                       int start_iteration,
                                       int num_iteration,
                                       const char* parameter,
                                       int64_t* out_len,
                                       double* out_result);

int LGBM_BoosterPredictForMatSingleRowFastInit(BoosterHandle handle,
                                               int predict_type,
                                               int start_iteration,
                                               int num_iteration,
                                               int data_type,
                                               int32_t ncol,
                                               const char* parameter,
                                               FastConfigHandle* out);

int LGBM_BoosterPredictForMatSingleRowFast(FastConfigHandle fast_config,
                                           const void* data,
                                           int64_t* out_len,
                                           double* out_result);

int LGBM_FastConfigFree(FastConfigHandle fast_config);

/* data: (nrow x ncol) matrix of `data_type` (C_API_DTYPE code).
 * out_result must hold nrow (normal/raw), nrow*num_class (multiclass), or
 * nrow*num_trees (leaf index) doubles; *out_len receives the count
 * written.  start_iteration/num_iteration window the trees used (-1 =
 * all); parameter carries "k=v" predict params. */
int LGBM_BoosterPredictForMat(BoosterHandle handle,
                              const void* data,
                              int data_type,
                              int32_t nrow,
                              int32_t ncol,
                              int is_row_major,
                              int predict_type,
                              int start_iteration,
                              int num_iteration,
                              const char* parameter,
                              int64_t* out_len,
                              double* out_result);

/* ---- CSC ingestion & prediction (reference: LGBM_DatasetCreateFromCSC,
 * LGBM_BoosterPredictForCSC).  col_ptr has ncol_ptr entries; indices are
 * int32 row ids; num_row is the dense row count. */
int LGBM_DatasetCreateFromCSC(const void* col_ptr,
                              int col_ptr_type,
                              const int32_t* indices,
                              const void* data,
                              int data_type,
                              int64_t ncol_ptr,
                              int64_t nelem,
                              int64_t num_row,
                              const char* parameters,
                              const DatasetHandle reference,
                              DatasetHandle* out);

int LGBM_BoosterPredictForCSC(BoosterHandle handle,
                              const void* col_ptr,
                              int col_ptr_type,
                              const int32_t* indices,
                              const void* data,
                              int data_type,
                              int64_t ncol_ptr,
                              int64_t nelem,
                              int64_t num_row,
                              int predict_type,
                              int start_iteration,
                              int num_iteration,
                              const char* parameter,
                              int64_t* out_len,
                              double* out_result);

/* ---- multi-block matrices (reference: LGBM_DatasetCreateFromMats,
 * LGBM_BoosterPredictForMats).  data: nmat pointers; nrow: rows per mat. */
int LGBM_DatasetCreateFromMats(int32_t nmat,
                               const void** data,
                               int data_type,
                               int32_t* nrow,
                               int32_t ncol,
                               int is_row_major,
                               const char* parameters,
                               const DatasetHandle reference,
                               DatasetHandle* out);

int LGBM_BoosterPredictForMats(BoosterHandle handle,
                               const void** data,
                               int data_type,
                               int32_t nmat,
                               int32_t* nrow,
                               int32_t ncol,
                               int predict_type,
                               int start_iteration,
                               int num_iteration,
                               const char* parameter,
                               int64_t* out_len,
                               double* out_result);

/* ---- sampled-column schema construction (reference:
 * LGBM_DatasetCreateFromSampledColumn → ConstructBinMappersFromSampleData;
 * bin mappers come from the per-column sample, rows arrive via PushRows). */
int LGBM_DatasetCreateFromSampledColumn(double** sample_data,
                                        int** sample_indices,
                                        int32_t ncol,
                                        const int* num_per_col,
                                        int32_t num_sample_row,
                                        int32_t num_local_row,
                                        int64_t num_dist_total_row,
                                        const char* parameters,
                                        DatasetHandle* out);

/* ---- dataset field/name/persistence (reference: LGBM_DatasetGetField,
 * Set/GetFeatureNames, SaveBinary, DumpText, GetSubset, AddFeaturesFrom,
 * UpdateParamChecking). */

/* *out_ptr points into dataset-owned memory (valid until the dataset is
 * freed); *out_type is a C_API_DTYPE code. */
int LGBM_DatasetGetField(DatasetHandle handle,
                         const char* field_name,
                         int* out_len,
                         const void** out_ptr,
                         int* out_type);

int LGBM_DatasetSetFeatureNames(DatasetHandle handle,
                                const char** feature_names,
                                int num_feature_names);

/* len buffers of buffer_len bytes each; *out_len = #names,
 * *out_buffer_len = max name length incl. NUL (size-then-fill). */
int LGBM_DatasetGetFeatureNames(DatasetHandle handle,
                                const int len,
                                int* out_len,
                                const size_t buffer_len,
                                size_t* out_buffer_len,
                                char** out_strs);

int LGBM_DatasetSaveBinary(DatasetHandle handle, const char* filename);

int LGBM_DatasetDumpText(DatasetHandle handle, const char* filename);

int LGBM_DatasetGetSubset(const DatasetHandle handle,
                          const int32_t* used_row_indices,
                          int32_t num_used_row_indices,
                          const char* parameters,
                          DatasetHandle* out);

int LGBM_DatasetAddFeaturesFrom(DatasetHandle target, DatasetHandle source);

int LGBM_DatasetUpdateParamChecking(const char* old_parameters,
                                    const char* new_parameters);

int LGBM_DatasetPushRowsByCSR(DatasetHandle handle,
                              const void* indptr,
                              int indptr_type,
                              const int32_t* indices,
                              const void* data,
                              int data_type,
                              int64_t nindptr,
                              int64_t nelem,
                              int64_t num_col,
                              int32_t start_row);

/* ---- streaming with metadata (reference: LGBM_DatasetInitStreaming,
 * LGBM_DatasetPushRowsWithMetadata, LGBM_DatasetMarkFinished,
 * LGBM_DatasetSetWaitForManualFinish). */
int LGBM_DatasetInitStreaming(DatasetHandle handle,
                              int32_t has_weights,
                              int32_t has_init_scores,
                              int32_t has_queries,
                              int32_t nclasses,
                              int32_t nthreads,
                              int32_t omp_max_threads);

int LGBM_DatasetPushRowsWithMetadata(DatasetHandle handle,
                                     const void* data,
                                     int data_type,
                                     int32_t nrow,
                                     int32_t ncol,
                                     int32_t start_row,
                                     const float* label,
                                     const float* weight,
                                     const double* init_score,
                                     const int32_t* query,
                                     int32_t tid);

int LGBM_DatasetPushRowsByCSRWithMetadata(DatasetHandle handle,
                                          const void* indptr,
                                          int indptr_type,
                                          const int32_t* indices,
                                          const void* data,
                                          int data_type,
                                          int64_t nindptr,
                                          int64_t nelem,
                                          int64_t num_col,
                                          int32_t start_row,
                                          const float* label,
                                          const float* weight,
                                          const double* init_score,
                                          const int32_t* query,
                                          int32_t tid);

int LGBM_DatasetMarkFinished(DatasetHandle handle);

int LGBM_DatasetSetWaitForManualFinish(DatasetHandle handle, int wait);

/* ---- serialized dataset reference + ByteBuffer (reference:
 * LGBM_DatasetSerializeReferenceToBinary,
 * LGBM_DatasetCreateFromSerializedReference, LGBM_ByteBuffer*). */
typedef void* ByteBufferHandle;

int LGBM_DatasetSerializeReferenceToBinary(DatasetHandle handle,
                                           ByteBufferHandle* out,
                                           int32_t* out_len);

int LGBM_ByteBufferGetAt(ByteBufferHandle handle, int32_t index,
                         uint8_t* out_val);

int LGBM_ByteBufferFree(ByteBufferHandle handle);

int LGBM_DatasetCreateFromSerializedReference(const void* ref_buffer,
                                              int32_t ref_buffer_size,
                                              int64_t num_row,
                                              int32_t num_classes,
                                              const char* parameters,
                                              DatasetHandle* out);

/* ---- booster model surgery & introspection ---- */

int LGBM_BoosterMerge(BoosterHandle handle, BoosterHandle other_handle);

/* leaf_preds: (nrow x num_trees) int32 leaf assignments on the attached
 * training data (reference: GBDT::RefitTree). */
int LGBM_BoosterRefit(BoosterHandle handle,
                      const int32_t* leaf_preds,
                      int32_t nrow,
                      int32_t ncol);

int LGBM_BoosterGetLeafValue(BoosterHandle handle,
                             int tree_idx,
                             int leaf_idx,
                             double* out_val);

int LGBM_BoosterSetLeafValue(BoosterHandle handle,
                             int tree_idx,
                             int leaf_idx,
                             double val);

int LGBM_BoosterGetLinear(BoosterHandle handle, int* out);

int LGBM_BoosterNumModelPerIteration(BoosterHandle handle,
                                     int* out_tree_per_iteration);

/* out_results: double[num_class]. */
int LGBM_BoosterGetLowerBoundValue(BoosterHandle handle, double* out_results);

int LGBM_BoosterGetUpperBoundValue(BoosterHandle handle, double* out_results);

int LGBM_BoosterGetEvalNames(BoosterHandle handle,
                             const int len,
                             int* out_len,
                             const size_t buffer_len,
                             size_t* out_buffer_len,
                             char** out_strs);

int LGBM_BoosterGetFeatureNames(BoosterHandle handle,
                                const int len,
                                int* out_len,
                                const size_t buffer_len,
                                size_t* out_buffer_len,
                                char** out_strs);

int LGBM_BoosterGetLoadedParam(BoosterHandle handle,
                               int64_t buffer_len,
                               int64_t* out_len,
                               char* out_str);

int LGBM_BoosterValidateFeatureNames(BoosterHandle handle,
                                     const char** data_names,
                                     int data_num_features);

int LGBM_BoosterShuffleModels(BoosterHandle handle,
                              int start_iter,
                              int end_iter);

/* Raw scores of the train (data_idx 0) or (i-1)-th valid dataset. */
int LGBM_BoosterGetNumPredict(BoosterHandle handle,
                              int data_idx,
                              int64_t* out_len);

int LGBM_BoosterGetPredict(BoosterHandle handle,
                           int data_idx,
                           int64_t* out_len,
                           double* out_result);

int LGBM_BoosterCalcNumPredict(BoosterHandle handle,
                               int num_row,
                               int predict_type,
                               int start_iteration,
                               int num_iteration,
                               int64_t* out_len);

int LGBM_BoosterPredictForFile(BoosterHandle handle,
                               const char* data_filename,
                               int data_has_header,
                               int predict_type,
                               int start_iteration,
                               int num_iteration,
                               const char* parameter,
                               const char* result_filename);

int LGBM_BoosterPredictForCSRSingleRow(BoosterHandle handle,
                                       const void* indptr,
                                       int indptr_type,
                                       const int32_t* indices,
                                       const void* data,
                                       int data_type,
                                       int64_t nindptr,
                                       int64_t nelem,
                                       int64_t num_col,
                                       int predict_type,
                                       int start_iteration,
                                       int num_iteration,
                                       const char* parameter,
                                       int64_t* out_len,
                                       double* out_result);

int LGBM_BoosterPredictForCSRSingleRowFastInit(BoosterHandle handle,
                                               int predict_type,
                                               int start_iteration,
                                               int num_iteration,
                                               int data_type,
                                               int64_t num_col,
                                               const char* parameter,
                                               FastConfigHandle* out);

int LGBM_BoosterPredictForCSRSingleRowFast(FastConfigHandle fast_config,
                                           const void* indptr,
                                           int indptr_type,
                                           const int32_t* indices,
                                           const void* data,
                                           int64_t nindptr,
                                           int64_t nelem,
                                           int64_t* out_len,
                                           double* out_result);

/* ---- Arrow C-data-interface ingestion (reference:
 * LGBM_DatasetCreateFromArrow / LGBM_DatasetSetFieldFromArrow /
 * LGBM_BoosterPredictForArrow over include/LightGBM/arrow.h).  chunks is a
 * contiguous array of n_chunks struct ArrowArray record batches (struct
 * layout per the Arrow C data interface spec); ownership transfers (release
 * is called). */
struct ArrowArray;
struct ArrowSchema;

int LGBM_DatasetCreateFromArrow(int64_t n_chunks,
                                const struct ArrowArray* chunks,
                                const struct ArrowSchema* schema,
                                const char* parameters,
                                const DatasetHandle reference,
                                DatasetHandle* out);

int LGBM_DatasetSetFieldFromArrow(DatasetHandle handle,
                                  const char* field_name,
                                  int64_t n_chunks,
                                  const struct ArrowArray* chunks,
                                  const struct ArrowSchema* schema);

int LGBM_BoosterPredictForArrow(BoosterHandle handle,
                                int64_t n_chunks,
                                const struct ArrowArray* chunks,
                                const struct ArrowSchema* schema,
                                int predict_type,
                                int start_iteration,
                                int num_iteration,
                                const char* parameter,
                                int64_t* out_len,
                                double* out_result);

/* ---- network bring-up (reference: LGBM_NetworkInit over socket/MPI
 * linkers; here the machine list drives jax.distributed + XLA collectives
 * — see docs/DISTRIBUTED.md). ---- */
int LGBM_NetworkInit(const char* machines,
                     int local_listen_port,
                     int listen_time_out,
                     int num_machines);

int LGBM_NetworkFree(void);

/* External collective fn pointers are not callable from the XLA-compiled
 * path.  With num_machines > 1 and non-null pointers this entry FAILS
 * unless the host opts into the XLA-transport substitution by setting
 * LIGHTGBM_TPU_ACCEPT_XLA_TRANSPORT=1 in the environment; topology is
 * then honored, transport is XLA's (docs/BINDINGS.md). */
int LGBM_NetworkInitWithFunctions(int num_machines,
                                  int rank,
                                  void* reduce_scatter_ext_fun,
                                  void* allgather_ext_fun);

/* ---- global configuration (reference: LGBM_DumpParamAliases,
 * LGBM_Get/SetMaxThreads, LGBM_RegisterLogCallback, LGBM_GetSampleCount,
 * LGBM_SampleIndices). ---- */
int LGBM_DumpParamAliases(int64_t buffer_len,
                          int64_t* out_len,
                          char* out_str);

int LGBM_GetMaxThreads(int* out);

int LGBM_SetMaxThreads(int num_threads);

int LGBM_RegisterLogCallback(void (*callback)(const char*));

int LGBM_GetSampleCount(int32_t num_total_row,
                        const char* parameters,
                        int* out);

/* out: int32 buffer of at least GetSampleCount entries. */
int LGBM_SampleIndices(int32_t num_total_row,
                       const char* parameters,
                       void* out,
                       int32_t* out_len);

#ifdef __cplusplus
}
#endif

#endif  /* LIGHTGBM_TPU_C_API_H_ */
