/* C API for lightgbm_tpu — the reference's `LGBM_*` FFI surface
 * (reference: include/LightGBM/c_api.h, src/c_api.cpp) re-hosted over the
 * TPU-native Python/JAX core.  The shim embeds CPython: handles are
 * refcounted lightgbm_tpu.Booster objects, array arguments cross as raw
 * pointers wrapped zero-copy by numpy on the Python side
 * (lightgbm_tpu/capi_helpers.py).
 *
 * Return convention matches the reference: 0 = success, -1 = failure with
 * the message available via LGBM_GetLastError().
 */
#ifndef LIGHTGBM_TPU_C_API_H_
#define LIGHTGBM_TPU_C_API_H_

#include <stdint.h>

#ifdef __cplusplus
extern "C" {
#endif

typedef void* BoosterHandle;

#define C_API_PREDICT_NORMAL 0
#define C_API_PREDICT_RAW_SCORE 1
#define C_API_PREDICT_LEAF_INDEX 2
#define C_API_PREDICT_CONTRIB 3

const char* LGBM_GetLastError(void);

int LGBM_BoosterCreateFromModelfile(const char* filename,
                                    int* out_num_iterations,
                                    BoosterHandle* out);

int LGBM_BoosterLoadModelFromString(const char* model_str,
                                    int* out_num_iterations,
                                    BoosterHandle* out);

int LGBM_BoosterFree(BoosterHandle handle);

int LGBM_BoosterGetNumClasses(BoosterHandle handle, int* out_len);

int LGBM_BoosterSaveModel(BoosterHandle handle,
                          int start_iteration,
                          int num_iteration,
                          int feature_importance_type,
                          const char* filename);

/* data: row-major (nrow x ncol) float64 matrix. out_result must hold
 * nrow (normal/raw), nrow*num_class (multiclass), or nrow*num_trees
 * (leaf index) doubles; *out_len receives the count written. */
int LGBM_BoosterPredictForMat(BoosterHandle handle,
                              const double* data,
                              int32_t nrow,
                              int32_t ncol,
                              int32_t is_row_major,
                              int32_t predict_type,
                              int64_t* out_len,
                              double* out_result);

#ifdef __cplusplus
}
#endif

#endif  /* LIGHTGBM_TPU_C_API_H_ */
