/* C API for lightgbm_tpu — the reference's `LGBM_*` FFI surface
 * (reference: include/LightGBM/c_api.h, src/c_api.cpp) re-hosted over the
 * TPU-native Python/JAX core.  The shim embeds CPython: handles are
 * refcounted lightgbm_tpu.Booster objects, array arguments cross as raw
 * pointers wrapped zero-copy by numpy on the Python side
 * (lightgbm_tpu/capi_helpers.py).
 *
 * Return convention matches the reference: 0 = success, -1 = failure with
 * the message available via LGBM_GetLastError().
 */
#ifndef LIGHTGBM_TPU_C_API_H_
#define LIGHTGBM_TPU_C_API_H_

#include <stdint.h>

#ifdef __cplusplus
extern "C" {
#endif

typedef void* BoosterHandle;
typedef void* DatasetHandle;

#define C_API_PREDICT_NORMAL 0
#define C_API_PREDICT_RAW_SCORE 1
#define C_API_PREDICT_LEAF_INDEX 2
#define C_API_PREDICT_CONTRIB 3

/* reference: C_API_DTYPE_* */
#define C_API_DTYPE_FLOAT32 0
#define C_API_DTYPE_FLOAT64 1
#define C_API_DTYPE_INT32 2
#define C_API_DTYPE_INT64 3

#define C_API_FEATURE_IMPORTANCE_SPLIT 0
#define C_API_FEATURE_IMPORTANCE_GAIN 1

const char* LGBM_GetLastError(void);

/* ---- Dataset surface (reference: LGBM_Dataset*) ---- */

/* data: (nrow x ncol) matrix of `data_type`; parameters: "k=v k=v";
 * reference: bin-alignment dataset or NULL. */
int LGBM_DatasetCreateFromMat(const void* data,
                              int data_type,
                              int32_t nrow,
                              int32_t ncol,
                              int is_row_major,
                              const char* parameters,
                              const DatasetHandle reference,
                              DatasetHandle* out);

int LGBM_DatasetCreateFromFile(const char* filename,
                               const char* parameters,
                               const DatasetHandle reference,
                               DatasetHandle* out);

/* Streaming construction: preallocate by reference, push row blocks
 * (reference: LGBM_DatasetCreateByReference / LGBM_DatasetPushRows). */
int LGBM_DatasetCreateByReference(const DatasetHandle reference,
                                  int64_t num_total_row,
                                  DatasetHandle* out);

int LGBM_DatasetPushRows(DatasetHandle handle,
                         const void* data,
                         int data_type,
                         int32_t nrow,
                         int32_t ncol,
                         int32_t start_row);

int LGBM_DatasetFree(DatasetHandle handle);

/* field_name: label/weight/group/init_score/position. */
int LGBM_DatasetSetField(DatasetHandle handle,
                         const char* field_name,
                         const void* field_data,
                         int num_element,
                         int type);

int LGBM_DatasetGetNumData(DatasetHandle handle, int32_t* out);

int LGBM_DatasetGetNumFeature(DatasetHandle handle, int32_t* out);

/* ---- Booster training surface (reference: LGBM_Booster*) ---- */

int LGBM_BoosterCreate(const DatasetHandle train_data,
                       const char* parameters,
                       BoosterHandle* out);

int LGBM_BoosterAddValidData(BoosterHandle handle, const DatasetHandle valid_data);

int LGBM_BoosterUpdateOneIter(BoosterHandle handle, int* is_finished);

/* grad/hess: float32[num_data * num_class], caller-computed objective. */
int LGBM_BoosterUpdateOneIterCustom(BoosterHandle handle,
                                    const float* grad,
                                    const float* hess,
                                    int* is_finished);

int LGBM_BoosterRollbackOneIter(BoosterHandle handle);

int LGBM_BoosterGetCurrentIteration(BoosterHandle handle, int* out_iteration);

int LGBM_BoosterNumberOfTotalModel(BoosterHandle handle, int* out_models);

int LGBM_BoosterGetNumFeature(BoosterHandle handle, int* out_len);

int LGBM_BoosterResetParameter(BoosterHandle handle, const char* parameters);

int LGBM_BoosterGetEvalCounts(BoosterHandle handle, int* out_len);

/* data_idx: 0 = train, i = i-th validation set. */
int LGBM_BoosterGetEval(BoosterHandle handle,
                        int data_idx,
                        int* out_len,
                        double* out_results);

/* out_str: caller buffer of buffer_len bytes; *out_len receives the
 * required size incl. NUL (call twice to size, like the reference). */
int LGBM_BoosterSaveModelToString(BoosterHandle handle,
                                  int start_iteration,
                                  int num_iteration,
                                  int feature_importance_type,
                                  int64_t buffer_len,
                                  int64_t* out_len,
                                  char* out_str);

int LGBM_BoosterDumpModel(BoosterHandle handle,
                          int start_iteration,
                          int num_iteration,
                          int feature_importance_type,
                          int64_t buffer_len,
                          int64_t* out_len,
                          char* out_str);

/* out_results: double[num_feature]. */
int LGBM_BoosterFeatureImportance(BoosterHandle handle,
                                  int num_iteration,
                                  int importance_type,
                                  double* out_results);

int LGBM_BoosterCreateFromModelfile(const char* filename,
                                    int* out_num_iterations,
                                    BoosterHandle* out);

int LGBM_BoosterLoadModelFromString(const char* model_str,
                                    int* out_num_iterations,
                                    BoosterHandle* out);

int LGBM_BoosterFree(BoosterHandle handle);

int LGBM_BoosterGetNumClasses(BoosterHandle handle, int* out_len);

int LGBM_BoosterSaveModel(BoosterHandle handle,
                          int start_iteration,
                          int num_iteration,
                          int feature_importance_type,
                          const char* filename);

/* ---- CSR ingestion & prediction (reference: LGBM_DatasetCreateFromCSR,
 * LGBM_BoosterPredictForCSR).  indptr_type / data_type use the
 * C_API_DTYPE codes (0=f32 1=f64 2=i32 3=i64); indices are int32. */
int LGBM_DatasetCreateFromCSR(const void* indptr,
                              int indptr_type,
                              const int32_t* indices,
                              const void* data,
                              int data_type,
                              int64_t nindptr,
                              int64_t nelem,
                              int64_t num_col,
                              const char* parameters,
                              const DatasetHandle reference,
                              DatasetHandle* out);

int LGBM_BoosterPredictForCSR(BoosterHandle handle,
                              const void* indptr,
                              int indptr_type,
                              const int32_t* indices,
                              const void* data,
                              int data_type,
                              int64_t nindptr,
                              int64_t nelem,
                              int64_t num_col,
                              int predict_type,
                              int64_t* out_len,
                              double* out_result);

/* ---- single-row predict, plain and Fast (reference: SingleRowPredictor,
 * FastConfigHandle — the Fast variants freeze predict settings into an
 * opaque handle so the per-call path is minimal). */
typedef void* FastConfigHandle;

int LGBM_BoosterPredictForMatSingleRow(BoosterHandle handle,
                                       const void* data,
                                       int data_type,
                                       int32_t ncol,
                                       int is_row_major,
                                       int predict_type,
                                       int64_t* out_len,
                                       double* out_result);

int LGBM_BoosterPredictForMatSingleRowFastInit(BoosterHandle handle,
                                               int predict_type,
                                               int data_type,
                                               int32_t ncol,
                                               const char* parameters,
                                               FastConfigHandle* out);

int LGBM_BoosterPredictForMatSingleRowFast(FastConfigHandle fast_config,
                                           const void* data,
                                           int64_t* out_len,
                                           double* out_result);

int LGBM_FastConfigFree(FastConfigHandle fast_config);

/* data: row-major (nrow x ncol) float64 matrix. out_result must hold
 * nrow (normal/raw), nrow*num_class (multiclass), or nrow*num_trees
 * (leaf index) doubles; *out_len receives the count written. */
int LGBM_BoosterPredictForMat(BoosterHandle handle,
                              const double* data,
                              int32_t nrow,
                              int32_t ncol,
                              int32_t is_row_major,
                              int32_t predict_type,
                              int64_t* out_len,
                              double* out_result);

#ifdef __cplusplus
}
#endif

#endif  /* LIGHTGBM_TPU_C_API_H_ */
