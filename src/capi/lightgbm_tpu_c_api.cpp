/* C API shim implementation — see lightgbm_tpu_c_api.h.
 *
 * Design (vs reference src/c_api.cpp): the reference's C API *is* its core;
 * here the core is Python/JAX, so the C ABI embeds CPython and forwards to
 * lightgbm_tpu.capi_helpers.  All entry points hold the GIL for their
 * duration (PyGILState_Ensure), so the library is usable both from plain C
 * programs (the embedded interpreter is initialized on first use) and from
 * inside an existing Python process via ctypes.
 */
#include "lightgbm_tpu_c_api.h"

#include <Python.h>

#include <cstdarg>
#include <cstdio>
#include <cstring>
#include <string>

namespace {

// per-thread, like the reference (c_api.cpp LGBM_GetLastError returns the
// CALLING thread's last error; a shared buffer would let one thread's
// failure overwrite another's success message)
thread_local std::string g_last_error = "ok";

void set_last_error(const std::string& msg) {
  g_last_error = msg;
}

void set_error_from_python() {
  PyObject *type = nullptr, *value = nullptr, *tb = nullptr;
  PyErr_Fetch(&type, &value, &tb);
  std::string msg = "unknown python error";
  if (value != nullptr) {
    PyObject* s = PyObject_Str(value);
    if (s != nullptr) {
      const char* c = PyUnicode_AsUTF8(s);
      if (c != nullptr) msg = c;
      Py_DECREF(s);
    }
  }
  Py_XDECREF(type);
  Py_XDECREF(value);
  Py_XDECREF(tb);
  set_last_error(msg);
}

struct GilGuard {
  PyGILState_STATE state;
  GilGuard() {
    if (!Py_IsInitialized()) {
      Py_InitializeEx(0);
    }
    state = PyGILState_Ensure();
  }
  ~GilGuard() { PyGILState_Release(state); }
};

PyObject* helpers() {
  // borrowed-module pattern: import once per call; cheap after first import
  return PyImport_ImportModule("lightgbm_tpu.capi_helpers");
}

int call_create(const char* kind, const char* arg, int* out_num_iterations,
                BoosterHandle* out) {
  GilGuard gil;
  PyObject* mod = helpers();
  if (mod == nullptr) {
    set_error_from_python();
    return -1;
  }
  PyObject* bst = PyObject_CallMethod(mod, kind, "s", arg);
  Py_DECREF(mod);
  if (bst == nullptr) {
    set_error_from_python();
    return -1;
  }
  if (out_num_iterations != nullptr) {
    PyObject* it = PyObject_CallMethod(bst, "current_iteration", nullptr);
    if (it == nullptr) {
      Py_DECREF(bst);
      set_error_from_python();
      return -1;
    }
    *out_num_iterations = static_cast<int>(PyLong_AsLong(it));
    Py_DECREF(it);
  }
  *out = static_cast<BoosterHandle>(bst);
  return 0;
}

// Call helpers.<method>(args...) and return the result (nullptr = error
// already recorded).  fmt/args as for PyObject_CallMethod.
PyObject* call_helper(const char* method, const char* fmt, ...) {
  PyObject* mod = helpers();
  if (mod == nullptr) {
    set_error_from_python();
    return nullptr;
  }
  va_list va;
  va_start(va, fmt);
  PyObject* callable = PyObject_GetAttrString(mod, method);
  Py_DECREF(mod);
  if (callable == nullptr) {
    va_end(va);
    set_error_from_python();
    return nullptr;
  }
  PyObject* args = Py_VaBuildValue(fmt, va);
  va_end(va);
  if (args == nullptr) {
    Py_DECREF(callable);
    set_error_from_python();
    return nullptr;
  }
  if (!PyTuple_Check(args)) {
    PyObject* t = PyTuple_Pack(1, args);
    Py_DECREF(args);
    args = t;
  }
  PyObject* r = PyObject_CallObject(callable, args);
  Py_DECREF(callable);
  Py_DECREF(args);
  if (r == nullptr) set_error_from_python();
  return r;
}

// Copy a Python str into a caller buffer with the reference's
// size-then-fill contract.
int str_to_buffer(PyObject* s, int64_t buffer_len, int64_t* out_len,
                  char* out_str) {
  Py_ssize_t n = 0;
  const char* c = PyUnicode_AsUTF8AndSize(s, &n);
  if (c == nullptr) {
    set_error_from_python();
    return -1;
  }
  *out_len = static_cast<int64_t>(n) + 1;
  if (out_str != nullptr && buffer_len >= n + 1) {
    std::memcpy(out_str, c, static_cast<size_t>(n) + 1);
  }
  return 0;
}

}  // namespace

extern "C" {

const char* LGBM_GetLastError(void) {
  return g_last_error.c_str();
}

/* ---- Dataset surface ---- */

int LGBM_DatasetCreateFromMat(const void* data, int data_type, int32_t nrow,
                              int32_t ncol, int is_row_major,
                              const char* parameters,
                              const DatasetHandle reference,
                              DatasetHandle* out) {
  GilGuard gil;
  PyObject* ref = reference != nullptr ? static_cast<PyObject*>(reference)
                                       : Py_None;
  PyObject* r = call_helper(
      "dataset_from_mat", "(KiiiisO)",
      reinterpret_cast<unsigned long long>(data), data_type,
      static_cast<int>(nrow), static_cast<int>(ncol), is_row_major,
      parameters, ref);
  if (r == nullptr) return -1;
  *out = static_cast<DatasetHandle>(r);
  return 0;
}

int LGBM_DatasetCreateFromFile(const char* filename, const char* parameters,
                               const DatasetHandle reference,
                               DatasetHandle* out) {
  GilGuard gil;
  PyObject* ref = reference != nullptr ? static_cast<PyObject*>(reference)
                                       : Py_None;
  PyObject* r = call_helper("dataset_from_file", "(ssO)", filename,
                            parameters, ref);
  if (r == nullptr) return -1;
  *out = static_cast<DatasetHandle>(r);
  return 0;
}

int LGBM_DatasetCreateByReference(const DatasetHandle reference,
                                  int64_t num_total_row,
                                  DatasetHandle* out) {
  GilGuard gil;
  PyObject* r = call_helper("dataset_create_by_reference", "(OL)",
                            static_cast<PyObject*>(reference),
                            static_cast<long long>(num_total_row));
  if (r == nullptr) return -1;
  *out = static_cast<DatasetHandle>(r);
  return 0;
}

int LGBM_DatasetPushRows(DatasetHandle handle, const void* data, int data_type,
                         int32_t nrow, int32_t ncol, int32_t start_row) {
  GilGuard gil;
  PyObject* r = call_helper(
      "dataset_push_rows", "(OKiiii)", static_cast<PyObject*>(handle),
      reinterpret_cast<unsigned long long>(data), data_type,
      static_cast<int>(nrow), static_cast<int>(ncol),
      static_cast<int>(start_row));
  if (r == nullptr) return -1;
  Py_DECREF(r);
  return 0;
}

int LGBM_DatasetFree(DatasetHandle handle) {
  if (handle == nullptr) return 0;
  GilGuard gil;
  Py_DECREF(static_cast<PyObject*>(handle));
  return 0;
}

int LGBM_DatasetSetField(DatasetHandle handle, const char* field_name,
                         const void* field_data, int num_element, int type) {
  GilGuard gil;
  PyObject* r = call_helper(
      "dataset_set_field", "(OsKii)", static_cast<PyObject*>(handle),
      field_name, reinterpret_cast<unsigned long long>(field_data),
      num_element, type);
  if (r == nullptr) return -1;
  Py_DECREF(r);
  return 0;
}

int LGBM_DatasetGetNumData(DatasetHandle handle, int32_t* out) {
  GilGuard gil;
  PyObject* r = call_helper("dataset_get_num_data", "(O)",
                            static_cast<PyObject*>(handle));
  if (r == nullptr) return -1;
  *out = static_cast<int32_t>(PyLong_AsLong(r));
  Py_DECREF(r);
  return 0;
}

int LGBM_DatasetGetNumFeature(DatasetHandle handle, int32_t* out) {
  GilGuard gil;
  PyObject* r = call_helper("dataset_get_num_feature", "(O)",
                            static_cast<PyObject*>(handle));
  if (r == nullptr) return -1;
  *out = static_cast<int32_t>(PyLong_AsLong(r));
  Py_DECREF(r);
  return 0;
}

/* ---- Booster training surface ---- */

int LGBM_BoosterCreate(const DatasetHandle train_data, const char* parameters,
                       BoosterHandle* out) {
  GilGuard gil;
  PyObject* r = call_helper("booster_create", "(Os)",
                            static_cast<PyObject*>(train_data), parameters);
  if (r == nullptr) return -1;
  *out = static_cast<BoosterHandle>(r);
  return 0;
}

int LGBM_BoosterAddValidData(BoosterHandle handle,
                             const DatasetHandle valid_data) {
  GilGuard gil;
  PyObject* r = call_helper("booster_add_valid", "(OO)",
                            static_cast<PyObject*>(handle),
                            static_cast<PyObject*>(valid_data));
  if (r == nullptr) return -1;
  Py_DECREF(r);
  return 0;
}

int LGBM_BoosterUpdateOneIter(BoosterHandle handle, int* is_finished) {
  GilGuard gil;
  PyObject* r = call_helper("booster_update", "(O)",
                            static_cast<PyObject*>(handle));
  if (r == nullptr) return -1;
  *is_finished = static_cast<int>(PyLong_AsLong(r));
  Py_DECREF(r);
  return 0;
}

int LGBM_BoosterUpdateOneIterCustom(BoosterHandle handle, const float* grad,
                                    const float* hess, int* is_finished) {
  GilGuard gil;
  PyObject* r = call_helper(
      "booster_update_custom", "(OKK)", static_cast<PyObject*>(handle),
      reinterpret_cast<unsigned long long>(grad),
      reinterpret_cast<unsigned long long>(hess));
  if (r == nullptr) return -1;
  *is_finished = static_cast<int>(PyLong_AsLong(r));
  Py_DECREF(r);
  return 0;
}

int LGBM_BoosterRollbackOneIter(BoosterHandle handle) {
  GilGuard gil;
  PyObject* r = call_helper("booster_rollback", "(O)",
                            static_cast<PyObject*>(handle));
  if (r == nullptr) return -1;
  Py_DECREF(r);
  return 0;
}

int LGBM_BoosterGetCurrentIteration(BoosterHandle handle,
                                    int* out_iteration) {
  GilGuard gil;
  PyObject* r = call_helper("booster_current_iteration", "(O)",
                            static_cast<PyObject*>(handle));
  if (r == nullptr) return -1;
  *out_iteration = static_cast<int>(PyLong_AsLong(r));
  Py_DECREF(r);
  return 0;
}

int LGBM_BoosterNumberOfTotalModel(BoosterHandle handle, int* out_models) {
  GilGuard gil;
  PyObject* r = call_helper("booster_num_total_model", "(O)",
                            static_cast<PyObject*>(handle));
  if (r == nullptr) return -1;
  *out_models = static_cast<int>(PyLong_AsLong(r));
  Py_DECREF(r);
  return 0;
}

int LGBM_BoosterGetNumFeature(BoosterHandle handle, int* out_len) {
  GilGuard gil;
  PyObject* r = call_helper("booster_num_feature", "(O)",
                            static_cast<PyObject*>(handle));
  if (r == nullptr) return -1;
  *out_len = static_cast<int>(PyLong_AsLong(r));
  Py_DECREF(r);
  return 0;
}

int LGBM_BoosterResetParameter(BoosterHandle handle, const char* parameters) {
  GilGuard gil;
  PyObject* r = call_helper("booster_reset_parameter", "(Os)",
                            static_cast<PyObject*>(handle), parameters);
  if (r == nullptr) return -1;
  Py_DECREF(r);
  return 0;
}

int LGBM_BoosterGetEvalCounts(BoosterHandle handle, int* out_len) {
  GilGuard gil;
  PyObject* r = call_helper("booster_eval_counts", "(O)",
                            static_cast<PyObject*>(handle));
  if (r == nullptr) return -1;
  *out_len = static_cast<int>(PyLong_AsLong(r));
  Py_DECREF(r);
  return 0;
}

int LGBM_BoosterGetEval(BoosterHandle handle, int data_idx, int* out_len,
                        double* out_results) {
  GilGuard gil;
  PyObject* r = call_helper(
      "booster_get_eval_into", "(OiK)", static_cast<PyObject*>(handle),
      data_idx, reinterpret_cast<unsigned long long>(out_results));
  if (r == nullptr) return -1;
  *out_len = static_cast<int>(PyLong_AsLong(r));
  Py_DECREF(r);
  return 0;
}

int LGBM_BoosterSaveModelToString(BoosterHandle handle, int start_iteration,
                                  int num_iteration,
                                  int feature_importance_type,
                                  int64_t buffer_len, int64_t* out_len,
                                  char* out_str) {
  (void)feature_importance_type;
  GilGuard gil;
  PyObject* r = call_helper("booster_save_string", "(Oii)",
                            static_cast<PyObject*>(handle), start_iteration,
                            num_iteration);
  if (r == nullptr) return -1;
  int rc = str_to_buffer(r, buffer_len, out_len, out_str);
  Py_DECREF(r);
  return rc;
}

int LGBM_BoosterDumpModel(BoosterHandle handle, int start_iteration,
                          int num_iteration, int feature_importance_type,
                          int64_t buffer_len, int64_t* out_len,
                          char* out_str) {
  (void)feature_importance_type;
  GilGuard gil;
  PyObject* r = call_helper("booster_dump_json", "(Oii)",
                            static_cast<PyObject*>(handle), start_iteration,
                            num_iteration);
  if (r == nullptr) return -1;
  int rc = str_to_buffer(r, buffer_len, out_len, out_str);
  Py_DECREF(r);
  return rc;
}

int LGBM_BoosterFeatureImportance(BoosterHandle handle, int num_iteration,
                                  int importance_type, double* out_results) {
  (void)num_iteration;
  GilGuard gil;
  PyObject* r = call_helper(
      "booster_feature_importance_into", "(OiK)",
      static_cast<PyObject*>(handle), importance_type,
      reinterpret_cast<unsigned long long>(out_results));
  if (r == nullptr) return -1;
  Py_DECREF(r);
  return 0;
}

int LGBM_BoosterCreateFromModelfile(const char* filename,
                                    int* out_num_iterations,
                                    BoosterHandle* out) {
  return call_create("booster_from_file", filename, out_num_iterations, out);
}

int LGBM_BoosterLoadModelFromString(const char* model_str,
                                    int* out_num_iterations,
                                    BoosterHandle* out) {
  return call_create("booster_from_string", model_str, out_num_iterations, out);
}

int LGBM_BoosterFree(BoosterHandle handle) {
  if (handle == nullptr) return 0;
  GilGuard gil;
  Py_DECREF(static_cast<PyObject*>(handle));
  return 0;
}

int LGBM_BoosterGetNumClasses(BoosterHandle handle, int* out_len) {
  GilGuard gil;
  PyObject* mod = helpers();
  if (mod == nullptr) {
    set_error_from_python();
    return -1;
  }
  PyObject* r = PyObject_CallMethod(mod, "num_classes", "O",
                                    static_cast<PyObject*>(handle));
  Py_DECREF(mod);
  if (r == nullptr) {
    set_error_from_python();
    return -1;
  }
  *out_len = static_cast<int>(PyLong_AsLong(r));
  Py_DECREF(r);
  return 0;
}

int LGBM_BoosterSaveModel(BoosterHandle handle, int start_iteration,
                          int num_iteration, int feature_importance_type,
                          const char* filename) {
  (void)feature_importance_type;
  GilGuard gil;
  PyObject* mod = helpers();
  if (mod == nullptr) {
    set_error_from_python();
    return -1;
  }
  PyObject* r = PyObject_CallMethod(
      mod, "save_model", "Osii", static_cast<PyObject*>(handle), filename,
      start_iteration, num_iteration);
  Py_DECREF(mod);
  if (r == nullptr) {
    set_error_from_python();
    return -1;
  }
  Py_DECREF(r);
  return 0;
}

int LGBM_DatasetCreateFromCSR(const void* indptr, int indptr_type,
                              const int32_t* indices, const void* data,
                              int data_type, int64_t nindptr, int64_t nelem,
                              int64_t num_col, const char* parameters,
                              const DatasetHandle reference,
                              DatasetHandle* out) {
  GilGuard gil;
  PyObject* ref = reference != nullptr ? static_cast<PyObject*>(reference)
                                       : Py_None;
  PyObject* r = call_helper(
      "dataset_from_csr", "(KiKKiLLLsO)",
      reinterpret_cast<unsigned long long>(indptr), indptr_type,
      reinterpret_cast<unsigned long long>(indices),
      reinterpret_cast<unsigned long long>(data), data_type,
      static_cast<long long>(nindptr), static_cast<long long>(nelem),
      static_cast<long long>(num_col), parameters, ref);
  if (r == nullptr) return -1;
  *out = static_cast<DatasetHandle>(r);
  return 0;
}

int LGBM_BoosterPredictForCSR(BoosterHandle handle, const void* indptr,
                              int indptr_type, const int32_t* indices,
                              const void* data, int data_type,
                              int64_t nindptr, int64_t nelem, int64_t num_col,
                              int predict_type, int64_t* out_len,
                              double* out_result) {
  GilGuard gil;
  PyObject* r = call_helper(
      "predict_csr_into", "(OKiKKiLLLiK)", static_cast<PyObject*>(handle),
      reinterpret_cast<unsigned long long>(indptr), indptr_type,
      reinterpret_cast<unsigned long long>(indices),
      reinterpret_cast<unsigned long long>(data), data_type,
      static_cast<long long>(nindptr), static_cast<long long>(nelem),
      static_cast<long long>(num_col), predict_type,
      reinterpret_cast<unsigned long long>(out_result));
  if (r == nullptr) return -1;
  *out_len = PyLong_AsLongLong(r);
  Py_DECREF(r);
  return 0;
}

int LGBM_BoosterPredictForMatSingleRow(BoosterHandle handle, const void* data,
                                       int data_type, int32_t ncol,
                                       int is_row_major, int predict_type,
                                       int64_t* out_len, double* out_result) {
  (void)is_row_major;  /* one row: both layouts identical */
  GilGuard gil;
  PyObject* r = call_helper(
      "predict_single_row_into", "(OKiiiK)", static_cast<PyObject*>(handle),
      reinterpret_cast<unsigned long long>(data), static_cast<int>(ncol),
      data_type, predict_type,
      reinterpret_cast<unsigned long long>(out_result));
  if (r == nullptr) return -1;
  *out_len = PyLong_AsLongLong(r);
  Py_DECREF(r);
  return 0;
}

int LGBM_BoosterPredictForMatSingleRowFastInit(BoosterHandle handle,
                                               int predict_type,
                                               int data_type, int32_t ncol,
                                               const char* parameters,
                                               FastConfigHandle* out) {
  GilGuard gil;
  PyObject* r = call_helper(
      "predict_single_row_fast_init", "(Oiiis)",
      static_cast<PyObject*>(handle), predict_type, data_type,
      static_cast<int>(ncol), parameters == nullptr ? "" : parameters);
  if (r == nullptr) return -1;
  *out = static_cast<FastConfigHandle>(r);
  return 0;
}

int LGBM_BoosterPredictForMatSingleRowFast(FastConfigHandle fast_config,
                                           const void* data, int64_t* out_len,
                                           double* out_result) {
  GilGuard gil;
  PyObject* r = call_helper(
      "predict_single_row_fast", "(OKK)",
      static_cast<PyObject*>(fast_config),
      reinterpret_cast<unsigned long long>(data),
      reinterpret_cast<unsigned long long>(out_result));
  if (r == nullptr) return -1;
  *out_len = PyLong_AsLongLong(r);
  Py_DECREF(r);
  return 0;
}

int LGBM_FastConfigFree(FastConfigHandle fast_config) {
  if (fast_config == nullptr) return 0;
  GilGuard gil;
  Py_DECREF(static_cast<PyObject*>(fast_config));
  return 0;
}

int LGBM_BoosterPredictForMat(BoosterHandle handle, const double* data,
                              int32_t nrow, int32_t ncol,
                              int32_t is_row_major, int32_t predict_type,
                              int64_t* out_len, double* out_result) {
  GilGuard gil;
  PyObject* mod = helpers();
  if (mod == nullptr) {
    set_error_from_python();
    return -1;
  }
  PyObject* r = PyObject_CallMethod(
      mod, "predict_into", "OKiiiiK", static_cast<PyObject*>(handle),
      reinterpret_cast<unsigned long long>(data), static_cast<int>(nrow),
      static_cast<int>(ncol), static_cast<int>(is_row_major),
      static_cast<int>(predict_type),
      reinterpret_cast<unsigned long long>(out_result));
  Py_DECREF(mod);
  if (r == nullptr) {
    set_error_from_python();
    return -1;
  }
  *out_len = PyLong_AsLongLong(r);
  Py_DECREF(r);
  return 0;
}

}  // extern "C"
