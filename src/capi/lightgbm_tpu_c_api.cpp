/* C API shim implementation — see lightgbm_tpu_c_api.h.
 *
 * Design (vs reference src/c_api.cpp): the reference's C API *is* its core;
 * here the core is Python/JAX, so the C ABI embeds CPython and forwards to
 * lightgbm_tpu.capi_helpers.  All entry points hold the GIL for their
 * duration (PyGILState_Ensure), so the library is usable both from plain C
 * programs (the embedded interpreter is initialized on first use) and from
 * inside an existing Python process via ctypes.
 */
#include "lightgbm_tpu_c_api.h"

#include <Python.h>

#include <cstdio>
#include <cstring>
#include <mutex>
#include <string>

namespace {

std::mutex g_err_mutex;
std::string g_last_error = "ok";

void set_last_error(const std::string& msg) {
  std::lock_guard<std::mutex> lk(g_err_mutex);
  g_last_error = msg;
}

void set_error_from_python() {
  PyObject *type = nullptr, *value = nullptr, *tb = nullptr;
  PyErr_Fetch(&type, &value, &tb);
  std::string msg = "unknown python error";
  if (value != nullptr) {
    PyObject* s = PyObject_Str(value);
    if (s != nullptr) {
      const char* c = PyUnicode_AsUTF8(s);
      if (c != nullptr) msg = c;
      Py_DECREF(s);
    }
  }
  Py_XDECREF(type);
  Py_XDECREF(value);
  Py_XDECREF(tb);
  set_last_error(msg);
}

struct GilGuard {
  PyGILState_STATE state;
  GilGuard() {
    if (!Py_IsInitialized()) {
      Py_InitializeEx(0);
    }
    state = PyGILState_Ensure();
  }
  ~GilGuard() { PyGILState_Release(state); }
};

PyObject* helpers() {
  // borrowed-module pattern: import once per call; cheap after first import
  return PyImport_ImportModule("lightgbm_tpu.capi_helpers");
}

int call_create(const char* kind, const char* arg, int* out_num_iterations,
                BoosterHandle* out) {
  GilGuard gil;
  PyObject* mod = helpers();
  if (mod == nullptr) {
    set_error_from_python();
    return -1;
  }
  PyObject* bst = PyObject_CallMethod(mod, kind, "s", arg);
  Py_DECREF(mod);
  if (bst == nullptr) {
    set_error_from_python();
    return -1;
  }
  if (out_num_iterations != nullptr) {
    PyObject* it = PyObject_CallMethod(bst, "current_iteration", nullptr);
    if (it == nullptr) {
      Py_DECREF(bst);
      set_error_from_python();
      return -1;
    }
    *out_num_iterations = static_cast<int>(PyLong_AsLong(it));
    Py_DECREF(it);
  }
  *out = static_cast<BoosterHandle>(bst);
  return 0;
}

}  // namespace

extern "C" {

const char* LGBM_GetLastError(void) {
  std::lock_guard<std::mutex> lk(g_err_mutex);
  return g_last_error.c_str();
}

int LGBM_BoosterCreateFromModelfile(const char* filename,
                                    int* out_num_iterations,
                                    BoosterHandle* out) {
  return call_create("booster_from_file", filename, out_num_iterations, out);
}

int LGBM_BoosterLoadModelFromString(const char* model_str,
                                    int* out_num_iterations,
                                    BoosterHandle* out) {
  return call_create("booster_from_string", model_str, out_num_iterations, out);
}

int LGBM_BoosterFree(BoosterHandle handle) {
  if (handle == nullptr) return 0;
  GilGuard gil;
  Py_DECREF(static_cast<PyObject*>(handle));
  return 0;
}

int LGBM_BoosterGetNumClasses(BoosterHandle handle, int* out_len) {
  GilGuard gil;
  PyObject* mod = helpers();
  if (mod == nullptr) {
    set_error_from_python();
    return -1;
  }
  PyObject* r = PyObject_CallMethod(mod, "num_classes", "O",
                                    static_cast<PyObject*>(handle));
  Py_DECREF(mod);
  if (r == nullptr) {
    set_error_from_python();
    return -1;
  }
  *out_len = static_cast<int>(PyLong_AsLong(r));
  Py_DECREF(r);
  return 0;
}

int LGBM_BoosterSaveModel(BoosterHandle handle, int start_iteration,
                          int num_iteration, int feature_importance_type,
                          const char* filename) {
  (void)feature_importance_type;
  GilGuard gil;
  PyObject* mod = helpers();
  if (mod == nullptr) {
    set_error_from_python();
    return -1;
  }
  PyObject* r = PyObject_CallMethod(
      mod, "save_model", "Osii", static_cast<PyObject*>(handle), filename,
      start_iteration, num_iteration);
  Py_DECREF(mod);
  if (r == nullptr) {
    set_error_from_python();
    return -1;
  }
  Py_DECREF(r);
  return 0;
}

int LGBM_BoosterPredictForMat(BoosterHandle handle, const double* data,
                              int32_t nrow, int32_t ncol,
                              int32_t is_row_major, int32_t predict_type,
                              int64_t* out_len, double* out_result) {
  GilGuard gil;
  PyObject* mod = helpers();
  if (mod == nullptr) {
    set_error_from_python();
    return -1;
  }
  PyObject* r = PyObject_CallMethod(
      mod, "predict_into", "OKiiiiK", static_cast<PyObject*>(handle),
      reinterpret_cast<unsigned long long>(data), static_cast<int>(nrow),
      static_cast<int>(ncol), static_cast<int>(is_row_major),
      static_cast<int>(predict_type),
      reinterpret_cast<unsigned long long>(out_result));
  Py_DECREF(mod);
  if (r == nullptr) {
    set_error_from_python();
    return -1;
  }
  *out_len = PyLong_AsLongLong(r);
  Py_DECREF(r);
  return 0;
}

}  // extern "C"
