/* C API shim implementation — see lightgbm_tpu_c_api.h.
 *
 * Design (vs reference src/c_api.cpp): the reference's C API *is* its core;
 * here the core is Python/JAX, so the C ABI embeds CPython and forwards to
 * lightgbm_tpu.capi_helpers.  All entry points hold the GIL for their
 * duration (PyGILState_Ensure), so the library is usable both from plain C
 * programs (the embedded interpreter is initialized on first use) and from
 * inside an existing Python process via ctypes.
 */
#include "lightgbm_tpu_c_api.h"

#include <Python.h>

#include <cstdarg>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <functional>
#include <string>
#include <utility>
#include <vector>

namespace {

// per-thread, like the reference (c_api.cpp LGBM_GetLastError returns the
// CALLING thread's last error; a shared buffer would let one thread's
// failure overwrite another's success message)
thread_local std::string g_last_error = "ok";

void set_last_error(const std::string& msg) {
  g_last_error = msg;
}

void set_error_from_python() {
  PyObject *type = nullptr, *value = nullptr, *tb = nullptr;
  PyErr_Fetch(&type, &value, &tb);
  std::string msg = "unknown python error";
  if (value != nullptr) {
    PyObject* s = PyObject_Str(value);
    if (s != nullptr) {
      const char* c = PyUnicode_AsUTF8(s);
      if (c != nullptr) msg = c;
      Py_DECREF(s);
    }
  }
  Py_XDECREF(type);
  Py_XDECREF(value);
  Py_XDECREF(tb);
  set_last_error(msg);
}

struct GilGuard {
  PyGILState_STATE state;
  GilGuard() {
    if (!Py_IsInitialized()) {
      Py_InitializeEx(0);
    }
    state = PyGILState_Ensure();
  }
  ~GilGuard() { PyGILState_Release(state); }
};

PyObject* helpers() {
  // borrowed-module pattern: import once per call; cheap after first import
  return PyImport_ImportModule("lightgbm_tpu.capi_helpers");
}

int call_create(const char* kind, const char* arg, int* out_num_iterations,
                BoosterHandle* out) {
  GilGuard gil;
  PyObject* mod = helpers();
  if (mod == nullptr) {
    set_error_from_python();
    return -1;
  }
  PyObject* bst = PyObject_CallMethod(mod, kind, "s", arg);
  Py_DECREF(mod);
  if (bst == nullptr) {
    set_error_from_python();
    return -1;
  }
  if (out_num_iterations != nullptr) {
    PyObject* it = PyObject_CallMethod(bst, "current_iteration", nullptr);
    if (it == nullptr) {
      Py_DECREF(bst);
      set_error_from_python();
      return -1;
    }
    *out_num_iterations = static_cast<int>(PyLong_AsLong(it));
    Py_DECREF(it);
  }
  *out = static_cast<BoosterHandle>(bst);
  return 0;
}

// Call helpers.<method>(args...) and return the result (nullptr = error
// already recorded).  fmt/args as for PyObject_CallMethod.
PyObject* call_helper(const char* method, const char* fmt, ...) {
  PyObject* mod = helpers();
  if (mod == nullptr) {
    set_error_from_python();
    return nullptr;
  }
  va_list va;
  va_start(va, fmt);
  PyObject* callable = PyObject_GetAttrString(mod, method);
  Py_DECREF(mod);
  if (callable == nullptr) {
    va_end(va);
    set_error_from_python();
    return nullptr;
  }
  PyObject* args = Py_VaBuildValue(fmt, va);
  va_end(va);
  if (args == nullptr) {
    Py_DECREF(callable);
    set_error_from_python();
    return nullptr;
  }
  if (!PyTuple_Check(args)) {
    PyObject* t = PyTuple_Pack(1, args);
    Py_DECREF(args);
    args = t;
  }
  PyObject* r = PyObject_CallObject(callable, args);
  Py_DECREF(callable);
  Py_DECREF(args);
  if (r == nullptr) set_error_from_python();
  return r;
}

// Fill a char** with a Python list of str using the reference's
// (len buffers of buffer_len) + size-then-fill contract.
int strlist_to_buffers(PyObject* list, int len, int* out_len,
                       size_t buffer_len, size_t* out_buffer_len,
                       char** out_strs) {
  if (!PyList_Check(list)) {
    set_last_error("expected list of names");
    return -1;
  }
  Py_ssize_t n = PyList_Size(list);
  *out_len = static_cast<int>(n);
  size_t need = 1;
  for (Py_ssize_t i = 0; i < n; ++i) {
    Py_ssize_t sz = 0;
    const char* c = PyUnicode_AsUTF8AndSize(PyList_GetItem(list, i), &sz);
    if (c == nullptr) {
      set_error_from_python();
      return -1;
    }
    if (static_cast<size_t>(sz) + 1 > need) need = static_cast<size_t>(sz) + 1;
    if (out_strs != nullptr && i < len && buffer_len > 0) {
      size_t ncopy = static_cast<size_t>(sz) + 1 <= buffer_len
                         ? static_cast<size_t>(sz) + 1
                         : buffer_len;
      std::memcpy(out_strs[i], c, ncopy);
      out_strs[i][ncopy - 1] = '\0';
    }
  }
  *out_buffer_len = need;
  return 0;
}

// Build a Python list[str] from a char** (for SetFeatureNames etc.).
PyObject* buffers_to_strlist(const char** strs, int n) {
  PyObject* list = PyList_New(n);
  if (list == nullptr) return nullptr;
  for (int i = 0; i < n; ++i) {
    PyObject* s = PyUnicode_FromString(strs[i]);
    if (s == nullptr) {
      Py_DECREF(list);
      return nullptr;
    }
    PyList_SetItem(list, i, s);  // steals
  }
  return list;
}

// Copy a Python str into a caller buffer with the reference's
// size-then-fill contract.
int str_to_buffer(PyObject* s, int64_t buffer_len, int64_t* out_len,
                  char* out_str) {
  Py_ssize_t n = 0;
  const char* c = PyUnicode_AsUTF8AndSize(s, &n);
  if (c == nullptr) {
    set_error_from_python();
    return -1;
  }
  *out_len = static_cast<int64_t>(n) + 1;
  if (out_str != nullptr && buffer_len >= n + 1) {
    std::memcpy(out_str, c, static_cast<size_t>(n) + 1);
  }
  return 0;
}

}  // namespace

extern "C" {

const char* LGBM_GetLastError(void) {
  return g_last_error.c_str();
}

/* ---- Dataset surface ---- */

int LGBM_DatasetCreateFromMat(const void* data, int data_type, int32_t nrow,
                              int32_t ncol, int is_row_major,
                              const char* parameters,
                              const DatasetHandle reference,
                              DatasetHandle* out) {
  GilGuard gil;
  PyObject* ref = reference != nullptr ? static_cast<PyObject*>(reference)
                                       : Py_None;
  PyObject* r = call_helper(
      "dataset_from_mat", "(KiiiisO)",
      reinterpret_cast<unsigned long long>(data), data_type,
      static_cast<int>(nrow), static_cast<int>(ncol), is_row_major,
      parameters, ref);
  if (r == nullptr) return -1;
  *out = static_cast<DatasetHandle>(r);
  return 0;
}

int LGBM_DatasetCreateFromFile(const char* filename, const char* parameters,
                               const DatasetHandle reference,
                               DatasetHandle* out) {
  GilGuard gil;
  PyObject* ref = reference != nullptr ? static_cast<PyObject*>(reference)
                                       : Py_None;
  PyObject* r = call_helper("dataset_from_file", "(ssO)", filename,
                            parameters, ref);
  if (r == nullptr) return -1;
  *out = static_cast<DatasetHandle>(r);
  return 0;
}

int LGBM_DatasetCreateByReference(const DatasetHandle reference,
                                  int64_t num_total_row,
                                  DatasetHandle* out) {
  GilGuard gil;
  PyObject* r = call_helper("dataset_create_by_reference", "(OL)",
                            static_cast<PyObject*>(reference),
                            static_cast<long long>(num_total_row));
  if (r == nullptr) return -1;
  *out = static_cast<DatasetHandle>(r);
  return 0;
}

int LGBM_DatasetPushRows(DatasetHandle handle, const void* data, int data_type,
                         int32_t nrow, int32_t ncol, int32_t start_row) {
  GilGuard gil;
  PyObject* r = call_helper(
      "dataset_push_rows", "(OKiiii)", static_cast<PyObject*>(handle),
      reinterpret_cast<unsigned long long>(data), data_type,
      static_cast<int>(nrow), static_cast<int>(ncol),
      static_cast<int>(start_row));
  if (r == nullptr) return -1;
  Py_DECREF(r);
  return 0;
}

int LGBM_DatasetFree(DatasetHandle handle) {
  if (handle == nullptr) return 0;
  GilGuard gil;
  Py_DECREF(static_cast<PyObject*>(handle));
  return 0;
}

int LGBM_DatasetSetField(DatasetHandle handle, const char* field_name,
                         const void* field_data, int num_element, int type) {
  GilGuard gil;
  PyObject* r = call_helper(
      "dataset_set_field", "(OsKii)", static_cast<PyObject*>(handle),
      field_name, reinterpret_cast<unsigned long long>(field_data),
      num_element, type);
  if (r == nullptr) return -1;
  Py_DECREF(r);
  return 0;
}

int LGBM_DatasetGetNumData(DatasetHandle handle, int32_t* out) {
  GilGuard gil;
  PyObject* r = call_helper("dataset_get_num_data", "(O)",
                            static_cast<PyObject*>(handle));
  if (r == nullptr) return -1;
  *out = static_cast<int32_t>(PyLong_AsLong(r));
  Py_DECREF(r);
  return 0;
}

int LGBM_DatasetGetNumFeature(DatasetHandle handle, int32_t* out) {
  GilGuard gil;
  PyObject* r = call_helper("dataset_get_num_feature", "(O)",
                            static_cast<PyObject*>(handle));
  if (r == nullptr) return -1;
  *out = static_cast<int32_t>(PyLong_AsLong(r));
  Py_DECREF(r);
  return 0;
}

/* ---- Booster training surface ---- */

int LGBM_BoosterCreate(const DatasetHandle train_data, const char* parameters,
                       BoosterHandle* out) {
  GilGuard gil;
  PyObject* r = call_helper("booster_create", "(Os)",
                            static_cast<PyObject*>(train_data), parameters);
  if (r == nullptr) return -1;
  *out = static_cast<BoosterHandle>(r);
  return 0;
}

int LGBM_BoosterAddValidData(BoosterHandle handle,
                             const DatasetHandle valid_data) {
  GilGuard gil;
  PyObject* r = call_helper("booster_add_valid", "(OO)",
                            static_cast<PyObject*>(handle),
                            static_cast<PyObject*>(valid_data));
  if (r == nullptr) return -1;
  Py_DECREF(r);
  return 0;
}

int LGBM_BoosterUpdateOneIter(BoosterHandle handle, int* is_finished) {
  GilGuard gil;
  PyObject* r = call_helper("booster_update", "(O)",
                            static_cast<PyObject*>(handle));
  if (r == nullptr) return -1;
  *is_finished = static_cast<int>(PyLong_AsLong(r));
  Py_DECREF(r);
  return 0;
}

int LGBM_BoosterUpdateOneIterCustom(BoosterHandle handle, const float* grad,
                                    const float* hess, int* is_finished) {
  GilGuard gil;
  PyObject* r = call_helper(
      "booster_update_custom", "(OKK)", static_cast<PyObject*>(handle),
      reinterpret_cast<unsigned long long>(grad),
      reinterpret_cast<unsigned long long>(hess));
  if (r == nullptr) return -1;
  *is_finished = static_cast<int>(PyLong_AsLong(r));
  Py_DECREF(r);
  return 0;
}

int LGBM_BoosterRollbackOneIter(BoosterHandle handle) {
  GilGuard gil;
  PyObject* r = call_helper("booster_rollback", "(O)",
                            static_cast<PyObject*>(handle));
  if (r == nullptr) return -1;
  Py_DECREF(r);
  return 0;
}

int LGBM_BoosterGetCurrentIteration(BoosterHandle handle,
                                    int* out_iteration) {
  GilGuard gil;
  PyObject* r = call_helper("booster_current_iteration", "(O)",
                            static_cast<PyObject*>(handle));
  if (r == nullptr) return -1;
  *out_iteration = static_cast<int>(PyLong_AsLong(r));
  Py_DECREF(r);
  return 0;
}

int LGBM_BoosterNumberOfTotalModel(BoosterHandle handle, int* out_models) {
  GilGuard gil;
  PyObject* r = call_helper("booster_num_total_model", "(O)",
                            static_cast<PyObject*>(handle));
  if (r == nullptr) return -1;
  *out_models = static_cast<int>(PyLong_AsLong(r));
  Py_DECREF(r);
  return 0;
}

int LGBM_BoosterGetNumFeature(BoosterHandle handle, int* out_len) {
  GilGuard gil;
  PyObject* r = call_helper("booster_num_feature", "(O)",
                            static_cast<PyObject*>(handle));
  if (r == nullptr) return -1;
  *out_len = static_cast<int>(PyLong_AsLong(r));
  Py_DECREF(r);
  return 0;
}

int LGBM_BoosterResetParameter(BoosterHandle handle, const char* parameters) {
  GilGuard gil;
  PyObject* r = call_helper("booster_reset_parameter", "(Os)",
                            static_cast<PyObject*>(handle), parameters);
  if (r == nullptr) return -1;
  Py_DECREF(r);
  return 0;
}

int LGBM_BoosterGetEvalCounts(BoosterHandle handle, int* out_len) {
  GilGuard gil;
  PyObject* r = call_helper("booster_eval_counts", "(O)",
                            static_cast<PyObject*>(handle));
  if (r == nullptr) return -1;
  *out_len = static_cast<int>(PyLong_AsLong(r));
  Py_DECREF(r);
  return 0;
}

int LGBM_BoosterGetEval(BoosterHandle handle, int data_idx, int* out_len,
                        double* out_results) {
  GilGuard gil;
  PyObject* r = call_helper(
      "booster_get_eval_into", "(OiK)", static_cast<PyObject*>(handle),
      data_idx, reinterpret_cast<unsigned long long>(out_results));
  if (r == nullptr) return -1;
  *out_len = static_cast<int>(PyLong_AsLong(r));
  Py_DECREF(r);
  return 0;
}

int LGBM_BoosterSaveModelToString(BoosterHandle handle, int start_iteration,
                                  int num_iteration,
                                  int feature_importance_type,
                                  int64_t buffer_len, int64_t* out_len,
                                  char* out_str) {
  (void)feature_importance_type;
  GilGuard gil;
  PyObject* r = call_helper("booster_save_string", "(Oii)",
                            static_cast<PyObject*>(handle), start_iteration,
                            num_iteration);
  if (r == nullptr) return -1;
  int rc = str_to_buffer(r, buffer_len, out_len, out_str);
  Py_DECREF(r);
  return rc;
}

int LGBM_BoosterDumpModel(BoosterHandle handle, int start_iteration,
                          int num_iteration, int feature_importance_type,
                          int64_t buffer_len, int64_t* out_len,
                          char* out_str) {
  (void)feature_importance_type;
  GilGuard gil;
  PyObject* r = call_helper("booster_dump_json", "(Oii)",
                            static_cast<PyObject*>(handle), start_iteration,
                            num_iteration);
  if (r == nullptr) return -1;
  int rc = str_to_buffer(r, buffer_len, out_len, out_str);
  Py_DECREF(r);
  return rc;
}

int LGBM_BoosterFeatureImportance(BoosterHandle handle, int num_iteration,
                                  int importance_type, double* out_results) {
  (void)num_iteration;
  GilGuard gil;
  PyObject* r = call_helper(
      "booster_feature_importance_into", "(OiK)",
      static_cast<PyObject*>(handle), importance_type,
      reinterpret_cast<unsigned long long>(out_results));
  if (r == nullptr) return -1;
  Py_DECREF(r);
  return 0;
}

int LGBM_BoosterCreateFromModelfile(const char* filename,
                                    int* out_num_iterations,
                                    BoosterHandle* out) {
  return call_create("booster_from_file", filename, out_num_iterations, out);
}

int LGBM_BoosterLoadModelFromString(const char* model_str,
                                    int* out_num_iterations,
                                    BoosterHandle* out) {
  return call_create("booster_from_string", model_str, out_num_iterations, out);
}

int LGBM_BoosterFree(BoosterHandle handle) {
  if (handle == nullptr) return 0;
  GilGuard gil;
  Py_DECREF(static_cast<PyObject*>(handle));
  return 0;
}

int LGBM_BoosterGetNumClasses(BoosterHandle handle, int* out_len) {
  GilGuard gil;
  PyObject* mod = helpers();
  if (mod == nullptr) {
    set_error_from_python();
    return -1;
  }
  PyObject* r = PyObject_CallMethod(mod, "num_classes", "O",
                                    static_cast<PyObject*>(handle));
  Py_DECREF(mod);
  if (r == nullptr) {
    set_error_from_python();
    return -1;
  }
  *out_len = static_cast<int>(PyLong_AsLong(r));
  Py_DECREF(r);
  return 0;
}

int LGBM_BoosterSaveModel(BoosterHandle handle, int start_iteration,
                          int num_iteration, int feature_importance_type,
                          const char* filename) {
  (void)feature_importance_type;
  GilGuard gil;
  PyObject* mod = helpers();
  if (mod == nullptr) {
    set_error_from_python();
    return -1;
  }
  PyObject* r = PyObject_CallMethod(
      mod, "save_model", "Osii", static_cast<PyObject*>(handle), filename,
      start_iteration, num_iteration);
  Py_DECREF(mod);
  if (r == nullptr) {
    set_error_from_python();
    return -1;
  }
  Py_DECREF(r);
  return 0;
}

int LGBM_DatasetCreateFromCSR(const void* indptr, int indptr_type,
                              const int32_t* indices, const void* data,
                              int data_type, int64_t nindptr, int64_t nelem,
                              int64_t num_col, const char* parameters,
                              const DatasetHandle reference,
                              DatasetHandle* out) {
  GilGuard gil;
  PyObject* ref = reference != nullptr ? static_cast<PyObject*>(reference)
                                       : Py_None;
  PyObject* r = call_helper(
      "dataset_from_csr", "(KiKKiLLLsO)",
      reinterpret_cast<unsigned long long>(indptr), indptr_type,
      reinterpret_cast<unsigned long long>(indices),
      reinterpret_cast<unsigned long long>(data), data_type,
      static_cast<long long>(nindptr), static_cast<long long>(nelem),
      static_cast<long long>(num_col), parameters, ref);
  if (r == nullptr) return -1;
  *out = static_cast<DatasetHandle>(r);
  return 0;
}

int LGBM_BoosterPredictForCSR(BoosterHandle handle, const void* indptr,
                              int indptr_type, const int32_t* indices,
                              const void* data, int data_type,
                              int64_t nindptr, int64_t nelem, int64_t num_col,
                              int predict_type, int start_iteration,
                              int num_iteration, const char* parameter,
                              int64_t* out_len, double* out_result) {
  GilGuard gil;
  PyObject* r = call_helper(
      "predict_csr_into", "(OKiKKiLLLiiisK)", static_cast<PyObject*>(handle),
      reinterpret_cast<unsigned long long>(indptr), indptr_type,
      reinterpret_cast<unsigned long long>(indices),
      reinterpret_cast<unsigned long long>(data), data_type,
      static_cast<long long>(nindptr), static_cast<long long>(nelem),
      static_cast<long long>(num_col), predict_type, start_iteration,
      num_iteration, parameter == nullptr ? "" : parameter,
      reinterpret_cast<unsigned long long>(out_result));
  if (r == nullptr) return -1;
  *out_len = PyLong_AsLongLong(r);
  Py_DECREF(r);
  return 0;
}

int LGBM_BoosterPredictSparseOutput(BoosterHandle handle, const void* indptr,
                                    int indptr_type, const int32_t* indices,
                                    const void* data, int data_type,
                                    int64_t nindptr, int64_t nelem,
                                    int64_t num_col_or_row, int predict_type,
                                    int start_iteration, int num_iteration,
                                    const char* parameter, int matrix_type,
                                    int64_t* out_len, void** out_indptr,
                                    int32_t** out_indices, void** out_data) {
  if (data_type != C_API_DTYPE_FLOAT32 && data_type != C_API_DTYPE_FLOAT64) {
    set_last_error(
        "LGBM_BoosterPredictSparseOutput: data_type must be "
        "C_API_DTYPE_FLOAT32 or C_API_DTYPE_FLOAT64");
    return -1;
  }
  GilGuard gil;
  PyObject* r = call_helper(
      "predict_sparse_output", "(OKiKKiLLLiiisi)",
      static_cast<PyObject*>(handle),
      reinterpret_cast<unsigned long long>(indptr), indptr_type,
      reinterpret_cast<unsigned long long>(indices),
      reinterpret_cast<unsigned long long>(data), data_type,
      static_cast<long long>(nindptr), static_cast<long long>(nelem),
      static_cast<long long>(num_col_or_row), predict_type, start_iteration,
      num_iteration, parameter == nullptr ? "" : parameter, matrix_type);
  if (r == nullptr) return -1;
  /* (indptr_addr, indices_addr, data_addr, n_indptr, nnz) — buffers were
   * malloc()'d on the Python side via libc so free() releases them */
  unsigned long long a_indptr = PyLong_AsUnsignedLongLong(PyTuple_GetItem(r, 0));
  unsigned long long a_indices = PyLong_AsUnsignedLongLong(PyTuple_GetItem(r, 1));
  unsigned long long a_data = PyLong_AsUnsignedLongLong(PyTuple_GetItem(r, 2));
  long long n_indptr = PyLong_AsLongLong(PyTuple_GetItem(r, 3));
  long long nnz = PyLong_AsLongLong(PyTuple_GetItem(r, 4));
  Py_DECREF(r);
  if (PyErr_Occurred()) {
    set_error_from_python();
    return -1;
  }
  *out_indptr = reinterpret_cast<void*>(a_indptr);
  *out_indices = reinterpret_cast<int32_t*>(a_indices);
  *out_data = reinterpret_cast<void*>(a_data);
  out_len[0] = n_indptr;
  out_len[1] = nnz;
  return 0;
}

int LGBM_BoosterFreePredictSparse(void* indptr, int32_t* indices, void* data,
                                  int indptr_type, int data_type) {
  (void)indptr_type;
  (void)data_type;
  std::free(indptr);
  std::free(indices);
  std::free(data);
  return 0;
}

int LGBM_DatasetCreateFromCSRFunc(void* get_row_funptr, int num_rows,
                                  int64_t num_col, const char* parameters,
                                  const DatasetHandle reference,
                                  DatasetHandle* out) {
  /* the reference's contract: funptr is a C++ std::function pointer,
   * invoked once per row OUTSIDE the GIL (the callback may be arbitrary
   * caller code); rows materialize dense, then the mat path ingests */
  using RowFn = std::function<void(int, std::vector<std::pair<int, double>>&)>;
  auto* fn = reinterpret_cast<RowFn*>(get_row_funptr);
  if (fn == nullptr || num_rows < 0 || num_col <= 0) {
    set_last_error("LGBM_DatasetCreateFromCSRFunc: bad arguments");
    return -1;
  }
  std::vector<double> buf(static_cast<size_t>(num_rows) * num_col, 0.0);
  std::vector<std::pair<int, double>> row;
  for (int i = 0; i < num_rows; ++i) {
    row.clear();
    (*fn)(i, row);
    for (const auto& kv : row) {
      if (kv.first >= 0 && kv.first < num_col) {
        buf[static_cast<size_t>(i) * num_col + kv.first] = kv.second;
      }
    }
  }
  GilGuard gil;
  PyObject* ref = reference != nullptr ? static_cast<PyObject*>(reference)
                                       : Py_None;
  PyObject* r = call_helper(
      "dataset_from_mat", "(KiiiisO)",
      reinterpret_cast<unsigned long long>(buf.data()), C_API_DTYPE_FLOAT64,
      num_rows, static_cast<int>(num_col), 1,
      parameters == nullptr ? "" : parameters, ref);
  if (r == nullptr) return -1;
  *out = static_cast<DatasetHandle>(r);
  return 0;
}

int LGBM_BoosterResetTrainingData(BoosterHandle handle,
                                  const DatasetHandle train_data) {
  GilGuard gil;
  PyObject* r = call_helper("booster_reset_training_data", "(OO)",
                            static_cast<PyObject*>(handle),
                            static_cast<PyObject*>(train_data));
  if (r == nullptr) return -1;
  Py_DECREF(r);
  return 0;
}

int LGBM_DatasetGetFeatureNumBin(DatasetHandle handle, int feature_idx,
                                 int* out) {
  GilGuard gil;
  PyObject* r = call_helper("dataset_get_feature_num_bin", "(Oi)",
                            static_cast<PyObject*>(handle), feature_idx);
  if (r == nullptr) return -1;
  *out = static_cast<int>(PyLong_AsLong(r));
  Py_DECREF(r);
  return 0;
}

int LGBM_BoosterPredictForMatSingleRow(BoosterHandle handle, const void* data,
                                       int data_type, int32_t ncol,
                                       int is_row_major, int predict_type,
                                       int start_iteration, int num_iteration,
                                       const char* parameter,
                                       int64_t* out_len, double* out_result) {
  (void)is_row_major;  /* one row: both layouts identical */
  GilGuard gil;
  PyObject* r = call_helper(
      "predict_single_row_into", "(OKiiiiisK)", static_cast<PyObject*>(handle),
      reinterpret_cast<unsigned long long>(data), static_cast<int>(ncol),
      data_type, predict_type, start_iteration, num_iteration,
      parameter == nullptr ? "" : parameter,
      reinterpret_cast<unsigned long long>(out_result));
  if (r == nullptr) return -1;
  *out_len = PyLong_AsLongLong(r);
  Py_DECREF(r);
  return 0;
}

int LGBM_BoosterPredictForMatSingleRowFastInit(BoosterHandle handle,
                                               int predict_type,
                                               int start_iteration,
                                               int num_iteration,
                                               int data_type, int32_t ncol,
                                               const char* parameter,
                                               FastConfigHandle* out) {
  GilGuard gil;
  PyObject* r = call_helper(
      "predict_single_row_fast_init", "(Oiiiiis)",
      static_cast<PyObject*>(handle), predict_type, start_iteration,
      num_iteration, data_type,
      static_cast<int>(ncol), parameter == nullptr ? "" : parameter);
  if (r == nullptr) return -1;
  *out = static_cast<FastConfigHandle>(r);
  return 0;
}

int LGBM_BoosterPredictForMatSingleRowFast(FastConfigHandle fast_config,
                                           const void* data, int64_t* out_len,
                                           double* out_result) {
  GilGuard gil;
  PyObject* r = call_helper(
      "predict_single_row_fast", "(OKK)",
      static_cast<PyObject*>(fast_config),
      reinterpret_cast<unsigned long long>(data),
      reinterpret_cast<unsigned long long>(out_result));
  if (r == nullptr) return -1;
  *out_len = PyLong_AsLongLong(r);
  Py_DECREF(r);
  return 0;
}

int LGBM_FastConfigFree(FastConfigHandle fast_config) {
  if (fast_config == nullptr) return 0;
  GilGuard gil;
  Py_DECREF(static_cast<PyObject*>(fast_config));
  return 0;
}

int LGBM_BoosterPredictForMat(BoosterHandle handle, const void* data,
                              int data_type, int32_t nrow, int32_t ncol,
                              int is_row_major, int predict_type,
                              int start_iteration, int num_iteration,
                              const char* parameter,
                              int64_t* out_len, double* out_result) {
  GilGuard gil;
  PyObject* r = call_helper(
      "predict_into", "(OKiiiiiiisK)", static_cast<PyObject*>(handle),
      reinterpret_cast<unsigned long long>(data), data_type,
      static_cast<int>(nrow), static_cast<int>(ncol),
      static_cast<int>(is_row_major), static_cast<int>(predict_type),
      start_iteration, num_iteration, parameter == nullptr ? "" : parameter,
      reinterpret_cast<unsigned long long>(out_result));
  if (r == nullptr) return -1;
  *out_len = PyLong_AsLongLong(r);
  Py_DECREF(r);
  return 0;
}

/* ---- CSC ---- */

int LGBM_DatasetCreateFromCSC(const void* col_ptr, int col_ptr_type,
                              const int32_t* indices, const void* data,
                              int data_type, int64_t ncol_ptr, int64_t nelem,
                              int64_t num_row, const char* parameters,
                              const DatasetHandle reference,
                              DatasetHandle* out) {
  GilGuard gil;
  PyObject* ref = reference != nullptr ? static_cast<PyObject*>(reference)
                                       : Py_None;
  PyObject* r = call_helper(
      "dataset_from_csc", "(KiKKiLLLsO)",
      reinterpret_cast<unsigned long long>(col_ptr), col_ptr_type,
      reinterpret_cast<unsigned long long>(indices),
      reinterpret_cast<unsigned long long>(data), data_type,
      static_cast<long long>(ncol_ptr), static_cast<long long>(nelem),
      static_cast<long long>(num_row), parameters, ref);
  if (r == nullptr) return -1;
  *out = static_cast<DatasetHandle>(r);
  return 0;
}

int LGBM_BoosterPredictForCSC(BoosterHandle handle, const void* col_ptr,
                              int col_ptr_type, const int32_t* indices,
                              const void* data, int data_type,
                              int64_t ncol_ptr, int64_t nelem, int64_t num_row,
                              int predict_type, int start_iteration,
                              int num_iteration, const char* parameter,
                              int64_t* out_len, double* out_result) {
  GilGuard gil;
  PyObject* r = call_helper(
      "predict_csc_into", "(OKiKKiLLLiiisK)", static_cast<PyObject*>(handle),
      reinterpret_cast<unsigned long long>(col_ptr), col_ptr_type,
      reinterpret_cast<unsigned long long>(indices),
      reinterpret_cast<unsigned long long>(data), data_type,
      static_cast<long long>(ncol_ptr), static_cast<long long>(nelem),
      static_cast<long long>(num_row), predict_type, start_iteration,
      num_iteration, parameter == nullptr ? "" : parameter,
      reinterpret_cast<unsigned long long>(out_result));
  if (r == nullptr) return -1;
  *out_len = PyLong_AsLongLong(r);
  Py_DECREF(r);
  return 0;
}

/* ---- multi-block matrices ---- */

int LGBM_DatasetCreateFromMats(int32_t nmat, const void** data, int data_type,
                               int32_t* nrow, int32_t ncol, int is_row_major,
                               const char* parameters,
                               const DatasetHandle reference,
                               DatasetHandle* out) {
  GilGuard gil;
  PyObject* ref = reference != nullptr ? static_cast<PyObject*>(reference)
                                       : Py_None;
  PyObject* r = call_helper(
      "dataset_from_mats", "(iKiKiisO)", static_cast<int>(nmat),
      reinterpret_cast<unsigned long long>(data), data_type,
      reinterpret_cast<unsigned long long>(nrow), static_cast<int>(ncol),
      is_row_major, parameters, ref);
  if (r == nullptr) return -1;
  *out = static_cast<DatasetHandle>(r);
  return 0;
}

int LGBM_BoosterPredictForMats(BoosterHandle handle, const void** data,
                               int data_type, int32_t nmat, int32_t* nrow,
                               int32_t ncol, int predict_type,
                               int start_iteration, int num_iteration,
                               const char* parameter,
                               int64_t* out_len, double* out_result) {
  GilGuard gil;
  PyObject* r = call_helper(
      "predict_mats_into", "(OiKiKiiiisK)", static_cast<PyObject*>(handle),
      static_cast<int>(nmat), reinterpret_cast<unsigned long long>(data),
      data_type, reinterpret_cast<unsigned long long>(nrow),
      static_cast<int>(ncol), predict_type, start_iteration, num_iteration,
      parameter == nullptr ? "" : parameter,
      reinterpret_cast<unsigned long long>(out_result));
  if (r == nullptr) return -1;
  *out_len = PyLong_AsLongLong(r);
  Py_DECREF(r);
  return 0;
}

/* ---- sampled-column construction ---- */

int LGBM_DatasetCreateFromSampledColumn(double** sample_data,
                                        int** sample_indices, int32_t ncol,
                                        const int* num_per_col,
                                        int32_t num_sample_row,
                                        int32_t num_local_row,
                                        int64_t num_dist_total_row,
                                        const char* parameters,
                                        DatasetHandle* out) {
  (void)num_dist_total_row; /* distributed total used only for logging */
  GilGuard gil;
  PyObject* r = call_helper(
      "dataset_from_sampled_column", "(KKiKiis)",
      reinterpret_cast<unsigned long long>(sample_data),
      reinterpret_cast<unsigned long long>(sample_indices),
      static_cast<int>(ncol),
      reinterpret_cast<unsigned long long>(num_per_col),
      static_cast<int>(num_sample_row), static_cast<int>(num_local_row),
      parameters);
  if (r == nullptr) return -1;
  *out = static_cast<DatasetHandle>(r);
  return 0;
}

/* ---- dataset field / names / persistence ---- */

int LGBM_DatasetGetField(DatasetHandle handle, const char* field_name,
                         int* out_len, const void** out_ptr, int* out_type) {
  GilGuard gil;
  PyObject* r = call_helper("dataset_get_field", "(Os)",
                            static_cast<PyObject*>(handle), field_name);
  if (r == nullptr) return -1;
  unsigned long long addr = 0;
  int n = 0, code = 0;
  if (!PyArg_ParseTuple(r, "Kii", &addr, &n, &code)) {
    Py_DECREF(r);
    set_error_from_python();
    return -1;
  }
  Py_DECREF(r);
  *out_ptr = reinterpret_cast<const void*>(addr);
  *out_len = n;
  *out_type = code;
  return 0;
}

int LGBM_DatasetSetFeatureNames(DatasetHandle handle,
                                const char** feature_names,
                                int num_feature_names) {
  GilGuard gil;
  PyObject* list = buffers_to_strlist(feature_names, num_feature_names);
  if (list == nullptr) {
    set_error_from_python();
    return -1;
  }
  PyObject* r = call_helper("dataset_set_feature_names", "(OO)",
                            static_cast<PyObject*>(handle), list);
  Py_DECREF(list);
  if (r == nullptr) return -1;
  Py_DECREF(r);
  return 0;
}

int LGBM_DatasetGetFeatureNames(DatasetHandle handle, const int len,
                                int* out_len, const size_t buffer_len,
                                size_t* out_buffer_len, char** out_strs) {
  GilGuard gil;
  PyObject* r = call_helper("dataset_feature_names", "(O)",
                            static_cast<PyObject*>(handle));
  if (r == nullptr) return -1;
  int rc = strlist_to_buffers(r, len, out_len, buffer_len, out_buffer_len,
                              out_strs);
  Py_DECREF(r);
  return rc;
}

int LGBM_DatasetSaveBinary(DatasetHandle handle, const char* filename) {
  GilGuard gil;
  PyObject* r = call_helper("dataset_save_binary", "(Os)",
                            static_cast<PyObject*>(handle), filename);
  if (r == nullptr) return -1;
  Py_DECREF(r);
  return 0;
}

int LGBM_DatasetDumpText(DatasetHandle handle, const char* filename) {
  GilGuard gil;
  PyObject* r = call_helper("dataset_dump_text", "(Os)",
                            static_cast<PyObject*>(handle), filename);
  if (r == nullptr) return -1;
  Py_DECREF(r);
  return 0;
}

int LGBM_DatasetGetSubset(const DatasetHandle handle,
                          const int32_t* used_row_indices,
                          int32_t num_used_row_indices,
                          const char* parameters, DatasetHandle* out) {
  GilGuard gil;
  PyObject* r = call_helper(
      "dataset_get_subset", "(OKis)", static_cast<PyObject*>(handle),
      reinterpret_cast<unsigned long long>(used_row_indices),
      static_cast<int>(num_used_row_indices), parameters);
  if (r == nullptr) return -1;
  *out = static_cast<DatasetHandle>(r);
  return 0;
}

int LGBM_DatasetAddFeaturesFrom(DatasetHandle target, DatasetHandle source) {
  GilGuard gil;
  PyObject* r = call_helper("dataset_add_features_from", "(OO)",
                            static_cast<PyObject*>(target),
                            static_cast<PyObject*>(source));
  if (r == nullptr) return -1;
  Py_DECREF(r);
  return 0;
}

int LGBM_DatasetUpdateParamChecking(const char* old_parameters,
                                    const char* new_parameters) {
  GilGuard gil;
  PyObject* r = call_helper("dataset_update_param_checking", "(ss)",
                            old_parameters, new_parameters);
  if (r == nullptr) return -1;
  Py_DECREF(r);
  return 0;
}

int LGBM_DatasetPushRowsByCSR(DatasetHandle handle, const void* indptr,
                              int indptr_type, const int32_t* indices,
                              const void* data, int data_type, int64_t nindptr,
                              int64_t nelem, int64_t num_col,
                              int32_t start_row) {
  GilGuard gil;
  PyObject* r = call_helper(
      "dataset_push_rows_by_csr", "(OKiKKiLLLi)",
      static_cast<PyObject*>(handle),
      reinterpret_cast<unsigned long long>(indptr), indptr_type,
      reinterpret_cast<unsigned long long>(indices),
      reinterpret_cast<unsigned long long>(data), data_type,
      static_cast<long long>(nindptr), static_cast<long long>(nelem),
      static_cast<long long>(num_col), static_cast<int>(start_row));
  if (r == nullptr) return -1;
  Py_DECREF(r);
  return 0;
}

/* ---- streaming with metadata ---- */

int LGBM_DatasetInitStreaming(DatasetHandle handle, int32_t has_weights,
                              int32_t has_init_scores, int32_t has_queries,
                              int32_t nclasses, int32_t nthreads,
                              int32_t omp_max_threads) {
  (void)nthreads;
  (void)omp_max_threads; /* host threading is numpy's job here */
  GilGuard gil;
  PyObject* r = call_helper(
      "dataset_init_streaming", "(Oiiii)", static_cast<PyObject*>(handle),
      static_cast<int>(has_weights), static_cast<int>(has_init_scores),
      static_cast<int>(has_queries), static_cast<int>(nclasses));
  if (r == nullptr) return -1;
  Py_DECREF(r);
  return 0;
}

int LGBM_DatasetPushRowsWithMetadata(DatasetHandle handle, const void* data,
                                     int data_type, int32_t nrow, int32_t ncol,
                                     int32_t start_row, const float* label,
                                     const float* weight,
                                     const double* init_score,
                                     const int32_t* query, int32_t tid) {
  (void)tid;
  GilGuard gil;
  PyObject* r = call_helper(
      "dataset_push_rows_with_metadata", "(OKiiiiKKKK)",
      static_cast<PyObject*>(handle),
      reinterpret_cast<unsigned long long>(data), data_type,
      static_cast<int>(nrow), static_cast<int>(ncol),
      static_cast<int>(start_row),
      reinterpret_cast<unsigned long long>(label),
      reinterpret_cast<unsigned long long>(weight),
      reinterpret_cast<unsigned long long>(init_score),
      reinterpret_cast<unsigned long long>(query));
  if (r == nullptr) return -1;
  Py_DECREF(r);
  return 0;
}

int LGBM_DatasetPushRowsByCSRWithMetadata(
    DatasetHandle handle, const void* indptr, int indptr_type,
    const int32_t* indices, const void* data, int data_type, int64_t nindptr,
    int64_t nelem, int64_t num_col, int32_t start_row, const float* label,
    const float* weight, const double* init_score, const int32_t* query,
    int32_t tid) {
  (void)tid;
  GilGuard gil;
  PyObject* r = call_helper(
      "dataset_push_rows_by_csr_with_metadata", "(OKiKKiLLLiKKKK)",
      static_cast<PyObject*>(handle),
      reinterpret_cast<unsigned long long>(indptr), indptr_type,
      reinterpret_cast<unsigned long long>(indices),
      reinterpret_cast<unsigned long long>(data), data_type,
      static_cast<long long>(nindptr), static_cast<long long>(nelem),
      static_cast<long long>(num_col), static_cast<int>(start_row),
      reinterpret_cast<unsigned long long>(label),
      reinterpret_cast<unsigned long long>(weight),
      reinterpret_cast<unsigned long long>(init_score),
      reinterpret_cast<unsigned long long>(query));
  if (r == nullptr) return -1;
  Py_DECREF(r);
  return 0;
}

int LGBM_DatasetMarkFinished(DatasetHandle handle) {
  GilGuard gil;
  PyObject* r = call_helper("dataset_mark_finished", "(O)",
                            static_cast<PyObject*>(handle));
  if (r == nullptr) return -1;
  Py_DECREF(r);
  return 0;
}

int LGBM_DatasetSetWaitForManualFinish(DatasetHandle handle, int wait) {
  GilGuard gil;
  PyObject* r = call_helper("dataset_set_wait_for_manual_finish", "(Oi)",
                            static_cast<PyObject*>(handle), wait);
  if (r == nullptr) return -1;
  Py_DECREF(r);
  return 0;
}

/* ---- serialized reference + ByteBuffer ---- */

int LGBM_DatasetSerializeReferenceToBinary(DatasetHandle handle,
                                           ByteBufferHandle* out,
                                           int32_t* out_len) {
  GilGuard gil;
  PyObject* r = call_helper("dataset_serialize_reference", "(O)",
                            static_cast<PyObject*>(handle));
  if (r == nullptr) return -1;
  *out = static_cast<ByteBufferHandle>(r); /* Python bytes object */
  *out_len = static_cast<int32_t>(PyBytes_Size(r));
  return 0;
}

int LGBM_ByteBufferGetAt(ByteBufferHandle handle, int32_t index,
                         uint8_t* out_val) {
  GilGuard gil;
  PyObject* bytes = static_cast<PyObject*>(handle);
  char* buf = nullptr;
  Py_ssize_t n = 0;
  if (PyBytes_AsStringAndSize(bytes, &buf, &n) != 0 || index < 0 ||
      index >= n) {
    PyErr_Clear();
    set_last_error("ByteBuffer index out of range");
    return -1;
  }
  *out_val = static_cast<uint8_t>(buf[index]);
  return 0;
}

int LGBM_ByteBufferFree(ByteBufferHandle handle) {
  if (handle == nullptr) return 0;
  GilGuard gil;
  Py_DECREF(static_cast<PyObject*>(handle));
  return 0;
}

int LGBM_DatasetCreateFromSerializedReference(const void* ref_buffer,
                                              int32_t ref_buffer_size,
                                              int64_t num_row,
                                              int32_t num_classes,
                                              const char* parameters,
                                              DatasetHandle* out) {
  (void)num_classes; /* class count rides in parameters */
  GilGuard gil;
  PyObject* r = call_helper(
      "dataset_from_serialized_reference", "(KiLs)",
      reinterpret_cast<unsigned long long>(ref_buffer),
      static_cast<int>(ref_buffer_size), static_cast<long long>(num_row),
      parameters);
  if (r == nullptr) return -1;
  *out = static_cast<DatasetHandle>(r);
  return 0;
}

/* ---- booster model surgery & introspection ---- */

int LGBM_BoosterMerge(BoosterHandle handle, BoosterHandle other_handle) {
  GilGuard gil;
  PyObject* r = call_helper("booster_merge", "(OO)",
                            static_cast<PyObject*>(handle),
                            static_cast<PyObject*>(other_handle));
  if (r == nullptr) return -1;
  Py_DECREF(r);
  return 0;
}

int LGBM_BoosterRefit(BoosterHandle handle, const int32_t* leaf_preds,
                      int32_t nrow, int32_t ncol) {
  GilGuard gil;
  PyObject* r = call_helper(
      "booster_refit_leaf_preds", "(OKii)", static_cast<PyObject*>(handle),
      reinterpret_cast<unsigned long long>(leaf_preds),
      static_cast<int>(nrow), static_cast<int>(ncol));
  if (r == nullptr) return -1;
  Py_DECREF(r);
  return 0;
}

int LGBM_BoosterGetLeafValue(BoosterHandle handle, int tree_idx, int leaf_idx,
                             double* out_val) {
  GilGuard gil;
  PyObject* r = call_helper("booster_get_leaf_value", "(Oii)",
                            static_cast<PyObject*>(handle), tree_idx,
                            leaf_idx);
  if (r == nullptr) return -1;
  *out_val = PyFloat_AsDouble(r);
  Py_DECREF(r);
  return 0;
}

int LGBM_BoosterSetLeafValue(BoosterHandle handle, int tree_idx, int leaf_idx,
                             double val) {
  GilGuard gil;
  PyObject* r = call_helper("booster_set_leaf_value", "(Oiid)",
                            static_cast<PyObject*>(handle), tree_idx, leaf_idx,
                            val);
  if (r == nullptr) return -1;
  Py_DECREF(r);
  return 0;
}

int LGBM_BoosterGetLinear(BoosterHandle handle, int* out) {
  GilGuard gil;
  PyObject* r = call_helper("booster_get_linear", "(O)",
                            static_cast<PyObject*>(handle));
  if (r == nullptr) return -1;
  *out = static_cast<int>(PyLong_AsLong(r));
  Py_DECREF(r);
  return 0;
}

int LGBM_BoosterNumModelPerIteration(BoosterHandle handle,
                                     int* out_tree_per_iteration) {
  GilGuard gil;
  PyObject* r = call_helper("booster_num_model_per_iteration", "(O)",
                            static_cast<PyObject*>(handle));
  if (r == nullptr) return -1;
  *out_tree_per_iteration = static_cast<int>(PyLong_AsLong(r));
  Py_DECREF(r);
  return 0;
}

int LGBM_BoosterGetLowerBoundValue(BoosterHandle handle,
                                   double* out_results) {
  GilGuard gil;
  PyObject* r = call_helper("booster_lower_bound", "(O)",
                            static_cast<PyObject*>(handle));
  if (r == nullptr) return -1;
  out_results[0] = PyFloat_AsDouble(r);
  Py_DECREF(r);
  return 0;
}

int LGBM_BoosterGetUpperBoundValue(BoosterHandle handle,
                                   double* out_results) {
  GilGuard gil;
  PyObject* r = call_helper("booster_upper_bound", "(O)",
                            static_cast<PyObject*>(handle));
  if (r == nullptr) return -1;
  out_results[0] = PyFloat_AsDouble(r);
  Py_DECREF(r);
  return 0;
}

int LGBM_BoosterGetEvalNames(BoosterHandle handle, const int len, int* out_len,
                             const size_t buffer_len, size_t* out_buffer_len,
                             char** out_strs) {
  GilGuard gil;
  PyObject* r = call_helper("booster_eval_names", "(O)",
                            static_cast<PyObject*>(handle));
  if (r == nullptr) return -1;
  int rc = strlist_to_buffers(r, len, out_len, buffer_len, out_buffer_len,
                              out_strs);
  Py_DECREF(r);
  return rc;
}

int LGBM_BoosterGetFeatureNames(BoosterHandle handle, const int len,
                                int* out_len, const size_t buffer_len,
                                size_t* out_buffer_len, char** out_strs) {
  GilGuard gil;
  PyObject* r = call_helper("booster_feature_names", "(O)",
                            static_cast<PyObject*>(handle));
  if (r == nullptr) return -1;
  int rc = strlist_to_buffers(r, len, out_len, buffer_len, out_buffer_len,
                              out_strs);
  Py_DECREF(r);
  return rc;
}

int LGBM_BoosterGetLoadedParam(BoosterHandle handle, int64_t buffer_len,
                               int64_t* out_len, char* out_str) {
  GilGuard gil;
  PyObject* r = call_helper("booster_loaded_param", "(O)",
                            static_cast<PyObject*>(handle));
  if (r == nullptr) return -1;
  int rc = str_to_buffer(r, buffer_len, out_len, out_str);
  Py_DECREF(r);
  return rc;
}

int LGBM_BoosterValidateFeatureNames(BoosterHandle handle,
                                     const char** data_names,
                                     int data_num_features) {
  GilGuard gil;
  PyObject* list = buffers_to_strlist(data_names, data_num_features);
  if (list == nullptr) {
    set_error_from_python();
    return -1;
  }
  PyObject* r = call_helper("booster_validate_feature_names", "(OO)",
                            static_cast<PyObject*>(handle), list);
  Py_DECREF(list);
  if (r == nullptr) return -1;
  Py_DECREF(r);
  return 0;
}

int LGBM_BoosterShuffleModels(BoosterHandle handle, int start_iter,
                              int end_iter) {
  GilGuard gil;
  PyObject* r = call_helper("booster_shuffle_models", "(Oii)",
                            static_cast<PyObject*>(handle), start_iter,
                            end_iter);
  if (r == nullptr) return -1;
  Py_DECREF(r);
  return 0;
}

int LGBM_BoosterGetNumPredict(BoosterHandle handle, int data_idx,
                              int64_t* out_len) {
  GilGuard gil;
  PyObject* r = call_helper("booster_get_num_predict", "(Oi)",
                            static_cast<PyObject*>(handle), data_idx);
  if (r == nullptr) return -1;
  *out_len = PyLong_AsLongLong(r);
  Py_DECREF(r);
  return 0;
}

int LGBM_BoosterGetPredict(BoosterHandle handle, int data_idx,
                           int64_t* out_len, double* out_result) {
  GilGuard gil;
  PyObject* r = call_helper(
      "booster_get_predict_into", "(OiK)", static_cast<PyObject*>(handle),
      data_idx, reinterpret_cast<unsigned long long>(out_result));
  if (r == nullptr) return -1;
  *out_len = PyLong_AsLongLong(r);
  Py_DECREF(r);
  return 0;
}

int LGBM_BoosterCalcNumPredict(BoosterHandle handle, int num_row,
                               int predict_type, int start_iteration,
                               int num_iteration, int64_t* out_len) {
  GilGuard gil;
  PyObject* r = call_helper("booster_calc_num_predict", "(Oiiii)",
                            static_cast<PyObject*>(handle), num_row,
                            predict_type, start_iteration, num_iteration);
  if (r == nullptr) return -1;
  *out_len = PyLong_AsLongLong(r);
  Py_DECREF(r);
  return 0;
}

int LGBM_BoosterPredictForFile(BoosterHandle handle, const char* data_filename,
                               int data_has_header, int predict_type,
                               int start_iteration, int num_iteration,
                               const char* parameter,
                               const char* result_filename) {
  GilGuard gil;
  PyObject* r = call_helper(
      "predict_for_file", "(Osiiiiss)", static_cast<PyObject*>(handle),
      data_filename, data_has_header, predict_type, start_iteration,
      num_iteration, parameter == nullptr ? "" : parameter, result_filename);
  if (r == nullptr) return -1;
  Py_DECREF(r);
  return 0;
}

int LGBM_BoosterPredictForCSRSingleRow(BoosterHandle handle,
                                       const void* indptr, int indptr_type,
                                       const int32_t* indices,
                                       const void* data, int data_type,
                                       int64_t nindptr, int64_t nelem,
                                       int64_t num_col, int predict_type,
                                       int start_iteration, int num_iteration,
                                       const char* parameter,
                                       int64_t* out_len, double* out_result) {
  GilGuard gil;
  PyObject* r = call_helper(
      "predict_csr_single_row_into", "(OKiKKiLLLiiisK)",
      static_cast<PyObject*>(handle),
      reinterpret_cast<unsigned long long>(indptr), indptr_type,
      reinterpret_cast<unsigned long long>(indices),
      reinterpret_cast<unsigned long long>(data), data_type,
      static_cast<long long>(nindptr), static_cast<long long>(nelem),
      static_cast<long long>(num_col), predict_type, start_iteration,
      num_iteration, parameter == nullptr ? "" : parameter,
      reinterpret_cast<unsigned long long>(out_result));
  if (r == nullptr) return -1;
  *out_len = PyLong_AsLongLong(r);
  Py_DECREF(r);
  return 0;
}

int LGBM_BoosterPredictForCSRSingleRowFastInit(BoosterHandle handle,
                                               int predict_type,
                                               int start_iteration,
                                               int num_iteration,
                                               int data_type,
                                               int64_t num_col,
                                               const char* parameter,
                                               FastConfigHandle* out) {
  GilGuard gil;
  PyObject* r = call_helper(
      "predict_csr_single_row_fast_init", "(Oiiiiis)",
      static_cast<PyObject*>(handle), predict_type, start_iteration,
      num_iteration, data_type,
      static_cast<int>(num_col), parameter == nullptr ? "" : parameter);
  if (r == nullptr) return -1;
  *out = static_cast<FastConfigHandle>(r);
  return 0;
}

int LGBM_BoosterPredictForCSRSingleRowFast(FastConfigHandle fast_config,
                                           const void* indptr,
                                           int indptr_type,
                                           const int32_t* indices,
                                           const void* data, int64_t nindptr,
                                           int64_t nelem, int64_t* out_len,
                                           double* out_result) {
  GilGuard gil;
  PyObject* r = call_helper(
      "predict_csr_single_row_fast", "(OKiKKLLK)",
      static_cast<PyObject*>(fast_config),
      reinterpret_cast<unsigned long long>(indptr), indptr_type,
      reinterpret_cast<unsigned long long>(indices),
      reinterpret_cast<unsigned long long>(data),
      static_cast<long long>(nindptr), static_cast<long long>(nelem),
      reinterpret_cast<unsigned long long>(out_result));
  if (r == nullptr) return -1;
  *out_len = PyLong_AsLongLong(r);
  Py_DECREF(r);
  return 0;
}

/* ---- Arrow C-data-interface ---- */

int LGBM_DatasetCreateFromArrow(int64_t n_chunks,
                                const struct ArrowArray* chunks,
                                const struct ArrowSchema* schema,
                                const char* parameters,
                                const DatasetHandle reference,
                                DatasetHandle* out) {
  GilGuard gil;
  PyObject* ref = reference != nullptr ? static_cast<PyObject*>(reference)
                                       : Py_None;
  PyObject* r = call_helper(
      "dataset_from_arrow", "(LKKsO)", static_cast<long long>(n_chunks),
      reinterpret_cast<unsigned long long>(chunks),
      reinterpret_cast<unsigned long long>(schema), parameters, ref);
  if (r == nullptr) return -1;
  *out = static_cast<DatasetHandle>(r);
  return 0;
}

int LGBM_DatasetSetFieldFromArrow(DatasetHandle handle, const char* field_name,
                                  int64_t n_chunks,
                                  const struct ArrowArray* chunks,
                                  const struct ArrowSchema* schema) {
  GilGuard gil;
  PyObject* r = call_helper(
      "dataset_set_field_from_arrow", "(OsLKK)",
      static_cast<PyObject*>(handle), field_name,
      static_cast<long long>(n_chunks),
      reinterpret_cast<unsigned long long>(chunks),
      reinterpret_cast<unsigned long long>(schema));
  if (r == nullptr) return -1;
  Py_DECREF(r);
  return 0;
}

int LGBM_BoosterPredictForArrow(BoosterHandle handle, int64_t n_chunks,
                                const struct ArrowArray* chunks,
                                const struct ArrowSchema* schema,
                                int predict_type, int start_iteration,
                                int num_iteration, const char* parameter,
                                int64_t* out_len, double* out_result) {
  GilGuard gil;
  PyObject* r = call_helper(
      "predict_arrow_into", "(OLKKiiisK)", static_cast<PyObject*>(handle),
      static_cast<long long>(n_chunks),
      reinterpret_cast<unsigned long long>(chunks),
      reinterpret_cast<unsigned long long>(schema), predict_type,
      start_iteration, num_iteration, parameter == nullptr ? "" : parameter,
      reinterpret_cast<unsigned long long>(out_result));
  if (r == nullptr) return -1;
  *out_len = PyLong_AsLongLong(r);
  Py_DECREF(r);
  return 0;
}

/* ---- network ---- */

int LGBM_NetworkInit(const char* machines, int local_listen_port,
                     int listen_time_out, int num_machines) {
  GilGuard gil;
  PyObject* r = call_helper("network_init", "(siii)",
                            machines == nullptr ? "" : machines,
                            local_listen_port, listen_time_out, num_machines);
  if (r == nullptr) return -1;
  Py_DECREF(r);
  return 0;
}

int LGBM_NetworkFree(void) {
  GilGuard gil;
  PyObject* r = call_helper("network_free", "()");
  if (r == nullptr) return -1;
  Py_DECREF(r);
  return 0;
}

int LGBM_NetworkInitWithFunctions(int num_machines, int rank,
                                  void* reduce_scatter_ext_fun,
                                  void* allgather_ext_fun) {
  /* XLA owns the transport; the helper errors when the host supplied real
   * collective fns for a multi-machine run without the explicit opt-in
   * (see header note). */
  GilGuard gil;
  PyObject* r = call_helper("network_init_with_functions", "(iiii)",
                            num_machines, rank,
                            reduce_scatter_ext_fun != nullptr ? 1 : 0,
                            allgather_ext_fun != nullptr ? 1 : 0);
  if (r == nullptr) return -1;
  Py_DECREF(r);
  return 0;
}

/* ---- global configuration ---- */

int LGBM_DumpParamAliases(int64_t buffer_len, int64_t* out_len,
                          char* out_str) {
  GilGuard gil;
  PyObject* r = call_helper("dump_param_aliases", "()");
  if (r == nullptr) return -1;
  int rc = str_to_buffer(r, buffer_len, out_len, out_str);
  Py_DECREF(r);
  return rc;
}

int LGBM_GetMaxThreads(int* out) {
  GilGuard gil;
  PyObject* r = call_helper("get_max_threads", "()");
  if (r == nullptr) return -1;
  *out = static_cast<int>(PyLong_AsLong(r));
  Py_DECREF(r);
  return 0;
}

int LGBM_SetMaxThreads(int num_threads) {
  GilGuard gil;
  PyObject* r = call_helper("set_max_threads", "(i)", num_threads);
  if (r == nullptr) return -1;
  Py_DECREF(r);
  return 0;
}

int LGBM_RegisterLogCallback(void (*callback)(const char*)) {
  GilGuard gil;
  PyObject* r = call_helper(
      "register_log_callback", "(K)",
      reinterpret_cast<unsigned long long>(callback));
  if (r == nullptr) return -1;
  Py_DECREF(r);
  return 0;
}

int LGBM_GetSampleCount(int32_t num_total_row, const char* parameters,
                        int* out) {
  GilGuard gil;
  PyObject* r = call_helper("get_sample_count", "(is)",
                            static_cast<int>(num_total_row), parameters);
  if (r == nullptr) return -1;
  *out = static_cast<int>(PyLong_AsLong(r));
  Py_DECREF(r);
  return 0;
}

int LGBM_SampleIndices(int32_t num_total_row, const char* parameters,
                       void* out, int32_t* out_len) {
  GilGuard gil;
  PyObject* r = call_helper("sample_indices_into", "(isK)",
                            static_cast<int>(num_total_row), parameters,
                            reinterpret_cast<unsigned long long>(out));
  if (r == nullptr) return -1;
  *out_len = static_cast<int32_t>(PyLong_AsLong(r));
  Py_DECREF(r);
  return 0;
}

}  // extern "C"
