"""Booster-fleet benchmark (round 21): B independent boosters per dispatch.

``bench.py`` measures ONE booster's round; this measures the fleet lever
(lightgbm_tpu/models/fleet.py): models/s when B independent boosters
train as ONE donated dispatch per round (``lgb.train_fleet``) vs the
host-loop baseline — the same solo windowed grower called B times per
round, which is exactly what jaxlint R18 flags.  B ∈ {1, 64, 4096}
(shapes per B below; 4096 samples the host loop and extrapolates, the
batched run is measured in full).

``parity`` runs first and asserts IN THE ARTIFACT PATH that every lane
of a B=8 fleet is BITWISE identical to its solo windowed-grower run —
float AND int8-quantized — the tests/test_fleet_train.py bar, re-checked
where the numbers are made.  Each throughput workload also pins the warm
fleet round budget (1 dispatch / 0 host syncs / 0 retraces per round at
that B) from the ``fleet_round`` event ledger.

Artifact contract mirrors bench.py: one JSON snapshot line printed +
flushed after every completed workload; the metrics snapshot rides every
emit and the jaxpr-audit verdict (incl. ``fleet_round_batched``) is
embedded at the end.  Set FLEET_BENCH_OUT to also write the final
snapshot to a file (e.g. BENCH_fleet_r01.json).

Env knobs: FLEET_BENCH_ROUNDS (default 5), FLEET_BENCH_BUDGET_S
(default 600), FLEET_BENCH_MAXB (default 4096), FLEET_BENCH_OUT.
"""

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

_T0 = time.monotonic()
_BUDGET_S = float(os.environ.get("FLEET_BENCH_BUDGET_S", 600))

_STATE = {
    "metric": "fleet_models_per_sec_B64",
    "value": None,
    "unit": "models/sec",
    "vs_baseline": None,  # batched / host-loop at B=64 (the >=5x bar)
    "workloads": {},
}


def _emit():
    try:
        from lightgbm_tpu.obs import metrics as _obs

        _STATE["metrics"] = _obs.snapshot()
    except Exception:  # noqa: BLE001 — artifact robustness first
        pass
    line = json.dumps(_STATE, default=str) + "\n"
    sys.stdout.write(line)
    sys.stdout.flush()
    out = os.environ.get("FLEET_BENCH_OUT")
    if out:
        with open(out, "w") as fh:
            fh.write(line)


def _remaining():
    return _BUDGET_S - (time.monotonic() - _T0)


def _guarded(name, fn, budget_floor=10.0):
    if _remaining() < budget_floor:
        _STATE["workloads"][name] = {"skipped": "budget"}
        _emit()
        return
    try:
        fn()
    except Exception as e:  # noqa: BLE001 — artifact robustness
        _STATE["workloads"][name] = {"error": f"{type(e).__name__}: {e}"[:300]}
    _emit()


def _params(quant=False, **over):
    p = {"objective": "binary", "num_leaves": 7, "verbosity": -1,
         "min_data_in_leaf": 5, "seed": 3}
    if quant:
        p.update(use_quantized_grad=True, num_grad_quant_bins=16)
    p.update(over)
    return p


def _data(b, n, f, seed=0):
    rng = np.random.RandomState(seed)
    X = rng.rand(n, f)
    labels = (rng.rand(b, n) > 0.5).astype(np.float64)
    return X, labels


def _solo_loop(X, labels, params, rounds, lanes=None):
    """The host-loop baseline AND the parity reference: each model alone
    through the single-model windowed grower — the exact solo op
    sequence (objective.prepare + boost_from_score + per-round gradient /
    grow_tree_windowed / score update), one python driver per model.
    Returns per-lane ([TreeArrays...], final score)."""
    import jax
    import jax.numpy as jnp

    import lightgbm_tpu as lgb
    from lightgbm_tpu.config import Config
    from lightgbm_tpu.models.gbdt import GBDT
    from lightgbm_tpu.objectives import create_objective
    from lightgbm_tpu.ops.treegrow_windowed import grow_tree_windowed

    cfg = Config.from_dict(dict(params))
    ds = lgb.Dataset(X, label=labels[0], params={"verbosity": -1})
    proto = GBDT(cfg, ds, objective=create_objective(cfg))
    ts = ds
    n = X.shape[0]
    quant = bool(cfg.use_quantized_grad)
    out = []
    for b in (range(labels.shape[0]) if lanes is None else lanes):
        obj = create_objective(cfg)
        if hasattr(obj, "prepare"):
            obj.prepare(labels[b], None)
        init = (float(obj.boost_from_score(
            jnp.asarray(labels[b], jnp.float32), None))
            if cfg.boost_from_average else 0.0)
        score = jnp.asarray(np.zeros(n, np.float32) + np.float32(init))
        lab_d = jnp.asarray(labels[b], jnp.float32)
        rm = jnp.ones((n,), bool)
        sw = jnp.ones((n,), jnp.float32)
        iters = []
        for it in range(rounds):
            g, h = obj.get_gradients(score, lab_d, None)
            qk = (jax.random.PRNGKey(cfg.seed * 1000003 + it * 31)
                  if quant else None)
            arrays, leaf_id = grow_tree_windowed(
                ts.bins_device_t(), g, h, rm, sw, proto._allowed_features,
                ts.num_bins_pf_device, ts.missing_bin_pf_device, None, qk,
                None, None, None, None, None,
                num_leaves=cfg.num_leaves, num_bins=ts.max_num_bins,
                max_depth=cfg.max_depth, params=proto._split_params,
                leaf_tile=proto._leaf_tile(ts),
                hist_precision=cfg.hist_precision, use_pallas=False,
                quantize_bins=(cfg.num_grad_quant_bins if quant else 0),
                stochastic_rounding=bool(cfg.stochastic_rounding),
                quant_renew=bool(cfg.quant_train_renew_leaf))
            score = score + (arrays.leaf_value
                             * jnp.float32(cfg.learning_rate))[leaf_id]
            iters.append(arrays)
        out.append((iters, np.asarray(score)))
    return out


_PARITY_FIELDS = ("num_leaves", "split_feature", "threshold_bin",
                  "leaf_value", "left_child", "right_child",
                  "default_left", "split_gain")


def bench_parity():
    """Every lane of a B=8 fleet bitwise == its solo grower run, float
    and int8-quantized — trees field-by-field AND final scores."""
    import lightgbm_tpu as lgb

    B, N, F, R = 8, 400, 8, 3
    X, labels = _data(B, N, F)
    row = {}
    for quant in (False, True):
        params = _params(quant)
        ds = lgb.Dataset(X, label=labels[0], params={"verbosity": -1})
        fb = lgb.train_fleet(dict(params), ds, labels, num_boost_round=R)
        solo = _solo_loop(X, labels, params, R)
        ok = True
        for b in range(B):
            iters, score = solo[b]
            for it in range(R):
                fl = fb._host_iter(it)
                for fld in _PARITY_FIELDS:
                    a = np.asarray(getattr(iters[it], fld))
                    f = getattr(fl, fld)[b]
                    if not np.array_equal(a, f, equal_nan=True):
                        ok = False
            if not np.array_equal(np.asarray(fb._score[b]), score):
                ok = False
        row["int8" if quant else "float"] = {
            "lanes": B, "rounds": R, "bitwise_vs_solo": ok}
        if not ok:
            raise AssertionError(
                f"fleet lanes diverged from solo grower (quant={quant})")
    _STATE["workloads"]["parity"] = row


def _round_budget(events, first_warm_iter=2):
    """The warm fleet round budget from the fleet_round event ledger:
    1 dispatch / 0 host syncs / 0 retries per ladder round and zero
    compiles, for every iteration past the warmup."""
    warm = [e for e in events if e.get("iteration", 0) > first_warm_iter]
    ok = bool(warm) and all(
        e.get("dispatches") == e.get("rounds")
        and e.get("host_syncs") == 0
        and e.get("retries") == 0
        and e.get("compiles") == 0
        for e in warm)
    return {"warm_iterations": len(warm),
            "one_dispatch_per_round": ok,
            "host_syncs": sum(e.get("host_syncs") or 0 for e in warm),
            "retries": sum(e.get("retries") or 0 for e in warm),
            "compiles": sum(e.get("compiles") or 0 for e in warm)}


def bench_fleet(b, n, f, rounds, host_lanes=None, extra_params=None):
    """Batched models/s at B=b vs the host loop (host_lanes samples the
    loop and extrapolates when b is large)."""
    import lightgbm_tpu as lgb
    from lightgbm_tpu.obs import metrics as _obs

    params = _params(**(extra_params or {}))
    X, labels = _data(b, n, f, seed=b)

    # batched: one warmup fleet (compiles), then the measured one
    for measured in (False, True):
        ds = lgb.Dataset(X, label=labels[0], params={"verbosity": -1})
        ev0 = len(_obs.events("fleet_round"))
        t0 = time.perf_counter()
        lgb.train_fleet(dict(params), ds, labels, num_boost_round=rounds)
        fleet_s = time.perf_counter() - t0
        events = _obs.events("fleet_round")[ev0:]
    budget = _round_budget(events)

    # host loop: the same solo grower per model (sampled when b is large;
    # per-model cost is B-independent, so the extrapolation is exact up
    # to variance)
    lanes = list(range(b if host_lanes is None else min(host_lanes, b)))
    _solo_loop(X, labels, params, rounds, lanes=lanes[:1])  # warm compiles
    t0 = time.perf_counter()
    _solo_loop(X, labels, params, rounds, lanes=lanes)
    host_s = (time.perf_counter() - t0) * (b / len(lanes))

    fleet_mps = round(b * rounds / fleet_s, 2)
    host_mps = round(b * rounds / host_s, 2)
    row = {
        "models": b, "rows": n, "features": f, "rounds": rounds,
        "fleet_s": round(fleet_s, 3),
        "host_loop_s": round(host_s, 3),
        "host_lanes_sampled": len(lanes),
        "fleet_model_rounds_per_sec": fleet_mps,
        "host_model_rounds_per_sec": host_mps,
        "speedup": round(host_s / max(fleet_s, 1e-9), 2),
        "round_budget": budget,
    }
    _STATE["workloads"][f"fleet_B{b}"] = row
    if not budget["one_dispatch_per_round"]:
        raise AssertionError(
            f"warm fleet round budget broke at B={b}: {budget}")
    if b == 64:
        _STATE["value"] = fleet_mps
        _STATE["vs_baseline"] = row["speedup"]
    _emit()


def main():
    import jax

    rounds = int(os.environ.get("FLEET_BENCH_ROUNDS", 5))
    maxb = int(os.environ.get("FLEET_BENCH_MAXB", 4096))
    _STATE["platform"] = jax.devices()[0].platform
    _STATE["rounds"] = rounds

    # the fleet's stated workload (ISSUE 17 / README "Booster fleets")
    # is per-tenant/per-segment personalization: MANY SMALL ensembles
    # over one shared binned matrix — so the throughput shapes are small
    # per-lane (256 rows x 4 features), where the host loop's per-model
    # driver + window-padding overhead is what batching amortizes
    _guarded("parity", bench_parity, budget_floor=30.0)
    _guarded("fleet_B1", lambda: bench_fleet(1, 256, 4, rounds),
             budget_floor=30.0)
    _guarded("fleet_B64", lambda: bench_fleet(64, 256, 4, rounds),
             budget_floor=60.0)
    if maxb >= 4096:
        # small rows/leaves keep the stacked state off-chip-sized;
        # boost_from_average=false skips 4096 per-lane host init pulls
        # (a real fleet at this B would do the same)
        _guarded("fleet_B4096",
                 lambda: bench_fleet(
                     4096, 128, 4, 3, host_lanes=64,
                     extra_params={"num_leaves": 4,
                                   "boost_from_average": False}),
                 budget_floor=120.0)

    # jaxpr-audit verdict (docs/ANALYSIS.md): the artifact carries proof
    # the fleet_round_batched contract (and the rest) held at trace
    # time, next to the numbers
    def _embed_audit():
        from lightgbm_tpu.analysis.jaxpr_audit import verdict

        _STATE["jaxpr_audit"] = verdict(runtime=False, exec_contracts=False)
        _STATE["workloads"]["jaxpr_audit"] = {
            "ok": _STATE["jaxpr_audit"].get("ok")}

    _guarded("jaxpr_audit", _embed_audit, budget_floor=30.0)

    _STATE["elapsed_s"] = round(time.monotonic() - _T0, 1)
    _emit()
    return 0


if __name__ == "__main__":
    sys.exit(main())
