"""Continual-training benchmark (round 19): the train-while-serving loop.

``serve_bench.py`` measures the serving PROCESS at a fixed model;
this measures the loop that keeps the model FRESH while it serves
(lightgbm_tpu/continual): streaming ingest throughput (in-memory window
and CRC'd durable-cache append), refit vs append-trees update latency,
and serve p50/p99 ACROSS rollovers — concurrent callers hammering the
runtime while the runner publishes refit and append updates — compared
against the committed BENCH_serve_r01 single-model baseline when it is
present next to the repo root.

``parity`` runs first and asserts IN THE ARTIFACT PATH that the
runner's rollovers reproduce the offline application of the same
primitives tree-bitwise, and that every served response during the
under-load run matches a legitimately published ensemble version — the
tests/test_continual.py pins, re-checked where the numbers are made.

Artifact contract mirrors bench.py: one JSON snapshot line printed +
flushed after every completed workload; the metrics snapshot rides every
emit and the jaxpr-audit verdict (incl. ``continual_refit_leaves``) is
embedded at the end.  Set CONTINUAL_BENCH_OUT to also write the final
snapshot to a file (e.g. BENCH_continual_r01.json).

Env knobs: CONTINUAL_BENCH_TREES (default 60), CONTINUAL_BENCH_CHUNK
(rows per ingest chunk, default 4096), CONTINUAL_BENCH_CHUNKS (default
16), CONTINUAL_BENCH_BUDGET_S (default 300), CONTINUAL_BENCH_OUT.
"""

import json
import os
import sys
import threading
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

_T0 = time.monotonic()
_BUDGET_S = float(os.environ.get("CONTINUAL_BENCH_BUDGET_S", 300))

_STATE = {
    "metric": "continual_ingest_rows_per_sec",
    "value": None,
    "unit": "rows/sec",
    "vs_baseline": None,  # serve-across-rollovers vs BENCH_serve_r01
    "workloads": {},
}


def _emit():
    try:
        from lightgbm_tpu.obs import metrics as _obs

        _STATE["metrics"] = _obs.snapshot()
    except Exception:  # noqa: BLE001 — artifact robustness first
        pass
    line = json.dumps(_STATE, default=str) + "\n"
    sys.stdout.write(line)
    sys.stdout.flush()
    out = os.environ.get("CONTINUAL_BENCH_OUT")
    if out:
        with open(out, "w") as fh:
            fh.write(line)


def _remaining():
    return _BUDGET_S - (time.monotonic() - _T0)


def _guarded(name, fn, budget_floor=10.0):
    if _remaining() < budget_floor:
        _STATE["workloads"][name] = {"skipped": "budget"}
        _emit()
        return
    try:
        fn()
    except Exception as e:  # noqa: BLE001 — artifact robustness
        _STATE["workloads"][name] = {"error": f"{type(e).__name__}: {e}"[:300]}
    _emit()


def _pcts(lat_s):
    lat = np.asarray(lat_s) * 1e3
    return (round(float(np.percentile(lat, 50)), 3),
            round(float(np.percentile(lat, 99)), 3))


def _trees_of(bst):
    s = bst.model_to_string()
    return s[s.index("Tree=0"):s.index("end of trees")]


def _setup(trees, f=16, n=20000, seed=0):
    import lightgbm_tpu as lgb

    rng = np.random.RandomState(seed)
    X = rng.randn(n, f)
    y = (X[:, 0] + 0.4 * X[:, 1] > 0).astype(float)
    ds = lgb.Dataset(X, label=y)
    bst = lgb.Booster(params={"objective": "binary", "num_leaves": 31,
                              "max_bin": 63, "verbosity": -1},
                      train_set=ds)
    for _ in range(trees):
        bst.update()
    return bst, ds, rng


def _chunk(rng, n, f=16):
    Xc = rng.randn(n, f)
    return Xc, (Xc[:, 0] + 0.4 * Xc[:, 1] > 0).astype(float)


def bench_parity(bst, ds, rng):
    """Runner rollovers == offline application, tree-bitwise."""
    import lightgbm_tpu as lgb
    from lightgbm_tpu.continual.refit import make_refit_entry, refit_leaves

    cr = lgb.continual_train(bst, {"append_trees": 2}, reference=ds,
                             start=False)
    chunks = [_chunk(rng, 2048) for _ in range(2)]
    cr.ingest(*chunks[0])
    cr.update("refit")
    cr.ingest(*chunks[1])
    cr.update("append")

    off = lgb.Booster(model_str=bst.model_to_string())
    off._gbdt.cfg = bst._gbdt.cfg
    entry = make_refit_entry(off._gbdt.objective,
                             off._gbdt.cfg.refit_decay_rate,
                             off._gbdt.cfg.lambda_l2)
    refit_leaves(off._gbdt, chunks[0][0], chunks[0][1], entry=entry)
    Xw = np.concatenate([c[0] for c in chunks])
    yw = np.concatenate([c[1] for c in chunks])
    off2 = lgb.train({"objective": "binary", "num_leaves": 31,
                      "max_bin": 63, "verbosity": -1},
                     lgb.Dataset(Xw, label=yw, reference=ds),
                     num_boost_round=2, init_model=off)
    ok = _trees_of(cr.booster) == _trees_of(off2)
    _STATE["workloads"]["parity"] = {
        "rollovers": 2, "tree_bitwise_vs_offline": ok}
    if not ok:
        raise AssertionError("runner rollovers diverged from the offline "
                             "application of the same primitives")
    return cr


def bench_ingest(bst, ds, rng, chunk_rows, n_chunks, tmp):
    """Streaming ingest rows/s: in-memory window vs durable CRC'd cache
    append (the append REWRITES the cache, so its cost grows with the
    cache — the artifact reports first/last chunk to show the slope)."""
    import lightgbm_tpu as lgb

    chunks = [_chunk(rng, chunk_rows) for _ in range(n_chunks)]

    cr = lgb.continual_train(bst, {}, reference=ds, start=False,
                             window_rows=chunk_rows * n_chunks)
    t0 = time.perf_counter()
    for c in chunks:
        cr.ingest(*c)
    mem_s = time.perf_counter() - t0
    mem_rps = round(chunk_rows * n_chunks / mem_s, 1)

    cache = os.path.join(tmp, "ingest.bin")
    cr2 = lgb.continual_train(bst, {}, reference=ds, start=False,
                              cache_path=cache,
                              window_rows=chunk_rows * n_chunks)
    per_chunk = []
    for c in chunks:
        t1 = time.perf_counter()
        cr2.ingest(*c)
        per_chunk.append(time.perf_counter() - t1)
    dur_rps = round(chunk_rows * n_chunks / sum(per_chunk), 1)
    _STATE["workloads"]["ingest"] = {
        "chunk_rows": chunk_rows, "chunks": n_chunks,
        "window_rows_per_sec": mem_rps,
        "durable_rows_per_sec": dur_rps,
        "durable_first_chunk_ms": round(per_chunk[0] * 1e3, 2),
        "durable_last_chunk_ms": round(per_chunk[-1] * 1e3, 2),
        "cache_bytes": os.path.getsize(cache),
    }
    _STATE["value"] = mem_rps
    _STATE["metric"] = f"continual_ingest_rows_per_sec_c{chunk_rows}"
    _emit()


def bench_update_latency(bst, ds, rng, chunk_rows):
    """Refit vs append-trees update latency (warm: the runner's cached
    refit entry and the already-compiled growers)."""
    import lightgbm_tpu as lgb

    cr = lgb.continual_train(bst, {"append_trees": 2}, reference=ds,
                             start=False)
    refit_lat, append_lat = [], []
    for _ in range(2):  # warmups: first refit + first append compile
        cr.ingest(*_chunk(rng, chunk_rows))
        cr.update("refit")
        cr.ingest(*_chunk(rng, chunk_rows))
        cr.update("append")
    for _ in range(5):
        cr.ingest(*_chunk(rng, chunk_rows))
        t0 = time.perf_counter()
        cr.update("refit")
        refit_lat.append(time.perf_counter() - t0)
    for _ in range(3):
        cr.ingest(*_chunk(rng, chunk_rows))
        t0 = time.perf_counter()
        cr.update("append")
        append_lat.append(time.perf_counter() - t0)
    r50, r99 = _pcts(refit_lat)
    a50, a99 = _pcts(append_lat)
    _STATE["workloads"]["update_latency"] = {
        "window_rows": chunk_rows,
        "refit": {"p50_ms": r50, "max_ms": round(max(refit_lat) * 1e3, 2),
                  "reps": len(refit_lat)},
        "append_2_trees": {"p50_ms": a50,
                           "max_ms": round(max(append_lat) * 1e3, 2),
                           "reps": len(append_lat)},
        "refit_vs_append_speedup": round(a50 / max(r50, 1e-9), 2),
    }
    _emit()


def bench_serve_across_rollovers(bst, ds, rng, tmp):
    """Concurrent callers through the runtime WHILE the runner publishes
    refit + append rollovers: p50/p99 across the swaps, every response
    verified against a published version, zero sheds — then compared to
    the committed BENCH_serve_r01 closed-loop baseline."""
    import lightgbm_tpu as lgb
    from lightgbm_tpu.obs import metrics as _obs
    from lightgbm_tpu.serve import ServingRuntime

    rt = ServingRuntime(bst, max_wait_ms=2, shed_unhealthy=False)
    cr = lgb.continual_train(bst, {"append_trees": 2}, runtime=rt,
                             reference=ds, state_dir=tmp, start=False)
    Q = rng.randn(64, 16)
    slices = [Q[i * 16:(i + 1) * 16] for i in range(4)]
    for s in slices:
        rt.predict(s, raw_score=True, timeout=120)  # warm the rungs
    versions = [bst]
    lat = []
    responses = []
    stop = threading.Event()
    errs = []

    def caller():
        try:
            while not stop.is_set():
                for i, s in enumerate(slices):
                    t1 = time.perf_counter()
                    r = rt.predict(s, raw_score=True, timeout=120)
                    lat.append(time.perf_counter() - t1)
                    responses.append((i, r))
        except BaseException as e:  # noqa: BLE001
            errs.append(f"{type(e).__name__}: {e}")

    shed0 = _obs.counter("serve_shed_total").value
    threads = [threading.Thread(target=caller) for _ in range(4)]
    for t in threads:
        t.start()
    rollovers = 0
    try:
        for kind in ("refit", "append", "refit"):
            cr.ingest(*_chunk(rng, 4096))
            cr.update(kind)
            versions.append(cr.booster)
            rollovers += 1
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=60)
    rt.stop()
    if errs:
        raise AssertionError(f"serving under rollover failed: {errs[:3]}")
    refs = [[v.predict(s, raw_score=True) for s in slices]
            for v in versions]
    bad = sum(1 for i, r in responses
              if not any(np.array_equal(refs[v][i], r)
                         for v in range(len(versions))))
    if bad:
        raise AssertionError(
            f"{bad}/{len(responses)} responses match no published version")
    p50, p99 = _pcts(lat)
    shed = _obs.counter("serve_shed_total").value - shed0
    row = {
        "rollovers": rollovers, "requests": len(responses),
        "rows_per_req": 16, "p50_ms": p50, "p99_ms": p99,
        "sheds_during_rollover": int(shed),
        "responses_bitwise_verified": True,
    }
    # vs the committed single-model serving baseline (same 16-row
    # closed-loop shape at C=4), when the artifact is present
    base_path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "BENCH_serve_r01.json")
    if os.path.exists(base_path):
        try:
            with open(base_path) as fh:
                base = json.loads(fh.read().strip())
            b = base.get("workloads", {}).get("closed_C4", {}).get(
                "coalesced", {})
            if b:
                row["baseline_serve_r01_C4"] = {
                    "p50_ms": b.get("p50_ms"), "p99_ms": b.get("p99_ms")}
                _STATE["vs_baseline"] = round(
                    p99 / max(float(b.get("p99_ms") or 0), 1e-9), 2)
        except (ValueError, OSError):
            pass
    _STATE["workloads"]["serve_across_rollovers"] = row
    _emit()


def main():
    import tempfile

    import jax

    trees = int(os.environ.get("CONTINUAL_BENCH_TREES", 60))
    chunk_rows = int(os.environ.get("CONTINUAL_BENCH_CHUNK", 4096))
    n_chunks = int(os.environ.get("CONTINUAL_BENCH_CHUNKS", 16))
    _STATE["platform"] = jax.devices()[0].platform
    _STATE["trees"] = trees

    bst, ds, rng = _setup(trees)
    tmp = tempfile.mkdtemp(prefix="continual_bench_")

    _guarded("parity", lambda: bench_parity(bst, ds, rng),
             budget_floor=20.0)
    _guarded("ingest",
             lambda: bench_ingest(bst, ds, rng, chunk_rows, n_chunks, tmp),
             budget_floor=30.0)
    _guarded("update_latency",
             lambda: bench_update_latency(bst, ds, rng, chunk_rows),
             budget_floor=45.0)
    _guarded("serve_across_rollovers",
             lambda: bench_serve_across_rollovers(bst, ds, rng, tmp),
             budget_floor=30.0)

    # jaxpr-audit verdict (docs/ANALYSIS.md): the artifact carries proof
    # the continual_refit_leaves contract (and the rest) held at trace
    # time, next to the numbers
    def _embed_audit():
        from lightgbm_tpu.analysis.jaxpr_audit import verdict

        _STATE["jaxpr_audit"] = verdict(runtime=False, exec_contracts=False)
        _STATE["workloads"]["jaxpr_audit"] = {
            "ok": _STATE["jaxpr_audit"].get("ok")}

    _guarded("jaxpr_audit", _embed_audit, budget_floor=30.0)

    _STATE["elapsed_s"] = round(time.monotonic() - _T0, 1)
    _emit()
    return 0


if __name__ == "__main__":
    sys.exit(main())
