"""On-chip lab for histogram-kernel variants (round-2 perf campaign).

Measures ms/pass at the bench shape for experimental one-hot formulations
vs the shipped `ops/hist_pallas.py` kernels.  Variants that win graduate
into the shipped kernel; variants that lose get recorded in
docs/PERF_NOTES.md so they aren't re-derived.

Usage: python benchmarks/kernel_lab.py [variants-comma-list] [N] [F] [B]
"""

import functools
import sys
import time

import numpy as np
import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _round_up(x, m):
    return (x + m - 1) // m * m


def timeit(fn, *args, reps=8):
    out = fn(*args)
    _ = np.asarray(out).ravel()[0]
    t0 = time.perf_counter()
    for _ in range(reps):
        out = fn(*args)
    host = np.asarray(out)
    dt = (time.perf_counter() - t0) / reps * 1e3
    return dt, host


# ---------------------------------------------------------------- variants

def make_direct(nc, *, cmp_dtype=jnp.int32, row_tile=1024, B=256, F=28,
                matmul_dtype=jnp.bfloat16):
    """Current shipped formulation: per-feature (T,B) one-hot + dot.
    cmp_dtype controls the iota/compare dtype (int32 today; int16 lab)."""

    def kernel(bins_ref, pay_ref, out_ref, acc_ref):
        i = pl.program_id(1)

        @pl.when(i == 0)
        def _():
            acc_ref[...] = jnp.zeros_like(acc_ref)

        pay = pay_ref[...].astype(matmul_dtype)
        T = pay.shape[0]
        iota_b = jax.lax.broadcasted_iota(cmp_dtype, (T, B), 1)
        for f in range(F):
            binf = bins_ref[:, f].astype(cmp_dtype)[:, None]
            oh = (binf == iota_b).astype(matmul_dtype)
            acc_ref[f] += jax.lax.dot_general(
                pay, oh, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)

        @pl.when(i == pl.num_programs(1) - 1)
        def _():
            out_ref[...] = acc_ref[...]

    @jax.jit
    def run(bins, pay):
        n = bins.shape[0]
        grid = (1, n // row_tile)
        return pl.pallas_call(
            kernel,
            grid=grid,
            in_specs=[
                pl.BlockSpec((row_tile, F), lambda j, i: (i, j), memory_space=pltpu.VMEM),
                pl.BlockSpec((row_tile, nc), lambda j, i: (i, 0), memory_space=pltpu.VMEM),
            ],
            out_specs=pl.BlockSpec((F, nc, B), lambda j, i: (j, 0, 0), memory_space=pltpu.VMEM),
            out_shape=jax.ShapeDtypeStruct((F, nc, B), jnp.float32),
            scratch_shapes=[pltpu.VMEM((F, nc, B), jnp.float32)],
            cost_estimate=pl.CostEstimate(
                flops=2 * n * F * B * nc,
                bytes_accessed=n * F * bins.dtype.itemsize + n * nc * 4,
                transcendentals=0,
            ),
        )(bins, pay)

    return run


def make_fused(nc, *, row_tile=256, B=256, F=28, matmul_dtype=jnp.bfloat16,
               cmp_dtype=jnp.int32):
    """One (T, F*B) one-hot + ONE dot for all features (bigger ops,
    fewer of them).  VMEM for the one-hot bounds the row tile."""

    def kernel(bins_ref, pay_ref, out_ref, acc_ref):
        i = pl.program_id(1)

        @pl.when(i == 0)
        def _():
            acc_ref[...] = jnp.zeros_like(acc_ref)

        pay = pay_ref[...].astype(matmul_dtype)
        T = pay.shape[0]
        iota = jax.lax.broadcasted_iota(cmp_dtype, (T, F, B), 2)
        binf = bins_ref[...].astype(cmp_dtype)[:, :, None]
        oh = (binf == iota).astype(matmul_dtype).reshape(T, F * B)
        acc_ref[...] += jax.lax.dot_general(
            pay, oh, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

        @pl.when(i == pl.num_programs(1) - 1)
        def _():
            out_ref[...] = acc_ref[...]

    @jax.jit
    def run(bins, pay):
        n = bins.shape[0]
        grid = (1, n // row_tile)
        return pl.pallas_call(
            kernel,
            grid=grid,
            in_specs=[
                pl.BlockSpec((row_tile, F), lambda j, i: (i, j), memory_space=pltpu.VMEM),
                pl.BlockSpec((row_tile, nc), lambda j, i: (i, 0), memory_space=pltpu.VMEM),
            ],
            out_specs=pl.BlockSpec((nc, F * B), lambda j, i: (0, 0), memory_space=pltpu.VMEM),
            out_shape=jax.ShapeDtypeStruct((nc, F * B), jnp.float32),
            scratch_shapes=[pltpu.VMEM((nc, F * B), jnp.float32)],
            cost_estimate=pl.CostEstimate(
                flops=2 * n * F * B * nc,
                bytes_accessed=n * F * bins.dtype.itemsize + n * nc * 4,
                transcendentals=0,
            ),
        )(bins, pay)

    return run


def make_transposed(nc, *, row_tile=1024, B=256, F=28,
                    matmul_dtype=jnp.bfloat16):
    """Feature-major bins (F, N); one-hot built TRANSPOSED (B, T) with the
    bin ids broadcast along sublanes (cheap) instead of lanes, dot
    contracts over the lane dim.  Tests whether the shipped kernel's
    per-feature column extraction/relayout is a hidden cost."""

    def kernel(binsT_ref, pay_ref, out_ref, acc_ref):
        i = pl.program_id(1)

        @pl.when(i == 0)
        def _():
            acc_ref[...] = jnp.zeros_like(acc_ref)

        pay = pay_ref[...].astype(matmul_dtype)  # (T, nc)
        T = pay.shape[0]
        iota_s = jax.lax.broadcasted_iota(jnp.int32, (B, T), 0)
        for f in range(F):
            binf = binsT_ref[f, :].astype(jnp.int32)[None, :]  # (1, T)
            ohT = (binf == iota_s).astype(matmul_dtype)  # (B, T)
            acc_ref[f] += jax.lax.dot_general(
                ohT, pay, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)  # (B, nc)

        @pl.when(i == pl.num_programs(1) - 1)
        def _():
            out_ref[...] = acc_ref[...]

    @jax.jit
    def run(binsT, pay):
        n = binsT.shape[1]
        grid = (1, n // row_tile)
        return pl.pallas_call(
            kernel,
            grid=grid,
            in_specs=[
                pl.BlockSpec((F, row_tile), lambda j, i: (0, i), memory_space=pltpu.VMEM),
                pl.BlockSpec((row_tile, nc), lambda j, i: (i, 0), memory_space=pltpu.VMEM),
            ],
            out_specs=pl.BlockSpec((F, B, nc), lambda j, i: (0, 0, 0), memory_space=pltpu.VMEM),
            out_shape=jax.ShapeDtypeStruct((F, B, nc), jnp.float32),
            scratch_shapes=[pltpu.VMEM((F, B, nc), jnp.float32)],
            cost_estimate=pl.CostEstimate(
                flops=2 * n * F * B * nc,
                bytes_accessed=n * F * binsT.dtype.itemsize + n * nc * 4,
                transcendentals=0,
            ),
        )(binsT, pay)

    return run


def make_inkernel_multi(ncl, lt, *, row_tile=1024, B=256, F=28,
                        matmul_dtype=jnp.bfloat16):
    """Multi-leaf pass with IN-KERNEL leaf-onehot x base expansion:
    reads base (N, ncl) + slot (N, 1) instead of a materialized
    (N, lt*ncl) payload."""
    NC = _round_up(lt * ncl, 8)

    def kernel(bins_ref, base_ref, slot_ref, out_ref, acc_ref):
        i = pl.program_id(1)

        @pl.when(i == 0)
        def _():
            acc_ref[...] = jnp.zeros_like(acc_ref)

        base = base_ref[...]  # (T, ncl) f32
        slot = slot_ref[...]  # (T, 1) i32
        T = base.shape[0]
        iota_c = jax.lax.broadcasted_iota(jnp.int32, (T, NC), 1)
        # pay[t, j] = base[t, j % ncl] * (slot[t] == j // ncl)
        sel = (iota_c // ncl) == slot  # (T, NC)
        base_tiled = jnp.concatenate(
            [base] * (NC // ncl + 1), axis=1)[:, :NC]  # cols j -> base[:, j % ncl]
        pay = jnp.where(sel, base_tiled, 0.0).astype(matmul_dtype)
        iota_b = jax.lax.broadcasted_iota(jnp.int32, (T, B), 1)
        for f in range(F):
            binf = bins_ref[:, f].astype(jnp.int32)[:, None]
            oh = (binf == iota_b).astype(matmul_dtype)
            acc_ref[f] += jax.lax.dot_general(
                pay, oh, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)

        @pl.when(i == pl.num_programs(1) - 1)
        def _():
            out_ref[...] = acc_ref[...]

    @jax.jit
    def run(bins, base, slot):
        n = bins.shape[0]
        grid = (1, n // row_tile)
        return pl.pallas_call(
            kernel,
            grid=grid,
            in_specs=[
                pl.BlockSpec((row_tile, F), lambda j, i: (i, j), memory_space=pltpu.VMEM),
                pl.BlockSpec((row_tile, ncl), lambda j, i: (i, 0), memory_space=pltpu.VMEM),
                pl.BlockSpec((row_tile, 1), lambda j, i: (i, 0), memory_space=pltpu.VMEM),
            ],
            out_specs=pl.BlockSpec((F, NC, B), lambda j, i: (j, 0, 0), memory_space=pltpu.VMEM),
            out_shape=jax.ShapeDtypeStruct((F, NC, B), jnp.float32),
            scratch_shapes=[pltpu.VMEM((F, NC, B), jnp.float32)],
        )(bins, base, slot)

    return run


def main():
    variants = sys.argv[1].split(",") if len(sys.argv) > 1 else [
        "direct48", "direct48_i16", "direct48_t2048", "fused48_256",
        "inkernel8x6", "direct8", "direct8_i16", "lane_sweep",
    ]
    n = int(sys.argv[2]) if len(sys.argv) > 2 else 1_000_000
    F = int(sys.argv[3]) if len(sys.argv) > 3 else 28
    B = int(sys.argv[4]) if len(sys.argv) > 4 else 256

    n = n // 4096 * 4096  # lab kernels do not pad; keep N tile-divisible
    rng = np.random.RandomState(0)
    bins = jnp.asarray(rng.randint(0, B, size=(n, F)).astype(np.int16))
    base8 = jnp.asarray(rng.randn(n, 8).astype(np.float32))
    slot = jnp.asarray(rng.randint(0, 8, size=(n, 1)).astype(np.int32))
    pay48 = jnp.asarray(rng.randn(n, 48).astype(np.float32))
    pay8 = base8

    results = {}
    for name in variants:
        try:
            if name == "lane_sweep":
                for nc in (8, 16, 24, 32, 40, 48, 64, 96):
                    fn = make_direct(nc)
                    pay = pay48[:, :nc] if nc <= 48 else jnp.tile(pay48, (1, 2))[:, :nc]
                    ms, _ = timeit(fn, bins, pay)
                    results[f"direct_nc{nc}"] = ms
                continue
            if name == "direct48":
                fn, args = make_direct(48), (bins, pay48)
            elif name == "direct48_i16":
                fn, args = make_direct(48, cmp_dtype=jnp.int16), (bins, pay48)
            elif name == "direct48_t2048":
                fn, args = make_direct(48, row_tile=2048), (bins, pay48)
            elif name == "fused48i16_256":
                fn, args = make_fused(48, row_tile=256, cmp_dtype=jnp.int16), (bins, pay48)
            elif name.startswith("fused48"):
                rt = int(name.split("_")[1])
                fn, args = make_fused(48, row_tile=rt), (bins, pay48)
            elif name == "inkernel8x6":
                fn, args = make_inkernel_multi(6, 8), (bins, base8[:, :6], slot)
            elif name.startswith("transposed"):
                nc = int(name.split("_")[0][10:])
                fn, args = make_transposed(nc), (
                    jnp.asarray(np.asarray(bins).T.copy()), pay48[:, :nc])
            elif name == "direct8":
                fn, args = make_direct(8), (bins, pay8)
            elif name == "direct8_i16":
                fn, args = make_direct(8, cmp_dtype=jnp.int16), (bins, pay8)
            else:
                print(f"  {name}: unknown")
                continue
            ms, out = timeit(fn, *args)
            results[name] = ms
            # correctness probe (first feature, first channel)
            if name.startswith("fused"):
                got = out.reshape(-1, F, B)[0, 0]
            elif name.startswith("transposed"):
                got = out[0, :, 0]
            elif name.startswith("inkernel"):
                ref1 = np.bincount(
                    np.asarray(bins)[:, 0],
                    weights=np.where(np.asarray(slot)[:, 0] == 0,
                                     np.asarray(base8)[:, 0], 0.0).astype(np.float64),
                    minlength=B)
                err = np.max(np.abs(out[0, 0] - ref1) / (np.abs(ref1) + 1))
                print(f"  {name}: rel_err={err:.2e}", flush=True)
                continue
            else:
                got = out[0, 0]
            ref1 = np.bincount(np.asarray(bins)[:, 0],
                               weights=np.asarray(args[1][:, 0], np.float64),
                               minlength=B)
            err = np.max(np.abs(got - ref1) / (np.abs(ref1) + 1))
            print(f"  {name}: rel_err={err:.2e}", flush=True)
        except Exception as e:
            print(f"  {name}: ERROR {type(e).__name__}: {str(e)[:240]}", flush=True)

    print(f"\nN={n} F={F} B={B} on {jax.devices()[0].platform}")
    for k, v in sorted(results.items(), key=lambda kv: kv[1]):
        print(f"  {k:28s} {v:8.2f} ms")


if __name__ == "__main__":
    main()
