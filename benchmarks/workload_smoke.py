"""Throughput smoke for the non-binary baseline workloads (BASELINE.md):
LambdaRank (MSLR-like) and multiclass (Airline-like) — plus, round 9, a
SERVING smoke that asserts the warm-predict dispatch budget and parity
against the host ``Tree.predict_batch`` walk, so CI catches serving
regressions without the chip.  Prints iters/sec (train) and rows/sec
(predict) for each on the current backend."""

import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def bench_rank(n, q_len, iters):
    import jax
    import lightgbm_tpu as lgb

    rng = np.random.RandomState(0)
    nq = n // q_len
    n = nq * q_len
    X = rng.randn(n, 64).astype(np.float32)
    w = rng.randn(64) / 8
    rel = X @ w + 0.7 * rng.randn(n)
    # 0-4 relevance labels per query by rank within query
    y = np.zeros(n)
    for qi in range(nq):
        s = slice(qi * q_len, (qi + 1) * q_len)
        order = np.argsort(np.argsort(-rel[s]))
        y[s] = np.clip(4 - order // (q_len // 5 + 1), 0, 4)
    d = lgb.Dataset(X, label=y, group=np.full(nq, q_len))
    bst = lgb.Booster(params={"objective": "lambdarank", "num_leaves": 31,
                              "max_bin": 63, "verbosity": -1}, train_set=d)
    bst.update()
    jax.block_until_ready(bst._gbdt._score)
    t0 = time.perf_counter()
    for _ in range(iters):
        bst.update()
    jax.block_until_ready(bst._gbdt._score)
    return iters / (time.perf_counter() - t0)


def bench_multiclass(n, k, iters):
    import jax
    import lightgbm_tpu as lgb

    rng = np.random.RandomState(1)
    X = rng.randn(n, 28).astype(np.float32)
    centers = rng.randn(k, 28)
    y = np.argmax(X @ centers.T + rng.randn(n, k), axis=1).astype(np.float64)
    d = lgb.Dataset(X, label=y)
    bst = lgb.Booster(params={"objective": "multiclass", "num_class": k,
                              "num_leaves": 31, "max_bin": 63,
                              "verbosity": -1}, train_set=d)
    bst.update()
    jax.block_until_ready(bst._gbdt._score)
    t0 = time.perf_counter()
    for _ in range(iters):
        bst.update()
    jax.block_until_ready(bst._gbdt._score)
    return iters / (time.perf_counter() - t0)


def bench_predict(n_rows=2000, n_trees=24, iters=20):
    """Fast serving smoke (small T/N, runs off-chip in seconds): trains a
    tiny model, ASSERTS the warm-call serving budget (1 dispatch + 1 sync,
    no retrace — the tests/test_predict_budget.py contract, re-checked here
    in the artifact path) and raw-prediction parity against the host
    ``Tree.predict_batch`` f64 walk, then reports warm rows/sec."""
    import time

    import numpy as np
    import lightgbm_tpu as lgb
    from lightgbm_tpu.utils.sanitizer import DispatchCounter

    rng = np.random.RandomState(2)
    X = rng.randn(n_rows, 16)
    y = (X[:, 0] + 0.4 * X[:, 1] > 0).astype(float)
    bst = lgb.Booster(params={"objective": "binary", "num_leaves": 15,
                              "max_bin": 63, "verbosity": -1},
                      train_set=lgb.Dataset(X, label=y))
    for _ in range(n_trees):
        bst.update()
    raw = bst.predict(X, raw_score=True)  # warm: pack + bucket compile

    host = np.zeros(n_rows)
    for t in bst._gbdt._trees_for_export(0, -1):
        host += t.predict_batch(np.asarray(X, np.float64))
    err = float(np.abs(raw - host).max())
    assert err < 1e-4, f"device serving path diverged from host walk: {err}"

    with DispatchCounter() as d:
        bst.predict(X, raw_score=True)
    assert d.dispatches == 1, f"warm predict cost {d.dispatches} dispatches"
    assert d.host_syncs == 1, f"warm predict cost {d.host_syncs} syncs"
    d.assert_no_recompile("warm predict smoke")

    # the metrics snapshot bench.py / predict_bench.py embed in their
    # artifacts must be schema-valid and cover the serving keys here too
    from lightgbm_tpu.obs import metrics as _obs

    snap = _obs.snapshot()
    _obs.validate_snapshot(snap)
    for key in ("predict_requests_total", "predict_bucket_hits_total",
                "train_boost_rounds_total", "device_dispatches_total"):
        assert key in snap["counters"], f"metrics snapshot missing {key}"
    assert snap["histograms"]["predict_warm_latency_ms"]["count"] >= 1, (
        "warm predict left no latency reservoir samples")
    # round 11: per-bucket latency labels + span tracing ride the same run
    assert any(k.startswith('predict_warm_latency_ms{bucket="')
               for k in snap["histograms"]), (
        "per-bucket warm-latency labels missing from the snapshot")
    from lightgbm_tpu.obs import trace as _tr

    assert _tr.spans("boost_round") and _tr.spans("predict.raw"), (
        "span tracing left no boost_round/predict spans")

    t0 = time.perf_counter()
    for _ in range(iters):
        bst.predict(X, raw_score=True)
    return n_rows * iters / (time.perf_counter() - t0), err


def bench_ooc(n_rows=3000, n_feat=8, rounds=3):
    """Out-of-core smoke (round 12, runs off-chip in seconds): trains
    from a ``save_binary`` cache in BOTH out-of-core regimes — resident
    (stream-assembled device matrix) and spill (chunked-histogram
    grower) — ASSERTS bitwise model parity against plain in-memory
    training, checks the snapshot carries the OOC keys, and reports
    streamed rows/sec for the spill run."""
    import tempfile

    import numpy as np
    import lightgbm_tpu as lgb
    from lightgbm_tpu.obs import metrics as _obs

    rng = np.random.RandomState(4)
    X = rng.randn(n_rows, n_feat)
    y = (X[:, 0] + 0.4 * X[:, 1] > 0).astype(float)
    params = {"objective": "binary", "num_leaves": 15, "max_bin": 255,
              "verbosity": -1, "feature_pre_filter": False}

    def train(ds):
        bst = lgb.Booster(params=params, train_set=ds)
        for _ in range(rounds):
            bst.update()
        return bst.model_to_string()

    want = train(lgb.Dataset(X, label=y, params=dict(params)))
    with tempfile.TemporaryDirectory() as td:
        cache = os.path.join(td, "smoke.bin")
        base = lgb.Dataset(X, label=y, params=dict(params))
        base.construct()
        base.save_binary(cache)

        resident = lgb.Dataset(cache, params=dict(
            params, out_of_core=True, out_of_core_chunk_rows=257))
        got = train(resident)
        assert got == want, "resident OOC diverged from in-memory"
        assert resident.bins is None, "resident OOC materialized host bins"

        spill = lgb.Dataset(cache, params=dict(
            params, out_of_core=True, max_rows_in_hbm=n_rows // 4,
            out_of_core_chunk_rows=512))
        # delta, not the cumulative process-global counter: earlier OOC
        # work in this process must not inflate rows/sec (the pattern
        # ooc_bench.bench_spill_train uses)
        passes0 = _obs.counter("train_ooc_passes_total").value
        t0 = time.perf_counter()
        got = train(spill)
        dt = time.perf_counter() - t0
        assert got == want, "spill OOC diverged from in-memory"
        assert spill.ooc_spill and spill.bins_device is None

    snap = _obs.snapshot()
    _obs.validate_snapshot(snap)
    for key in ("train_ooc_passes_total", "train_ooc_chunks_total"):
        assert key in snap["counters"], f"metrics snapshot missing {key}"
    passes = snap["counters"]["train_ooc_passes_total"] - passes0
    return n_rows * passes / dt, passes


def bench_megakernel(n_rows=2000, n_feat=10):
    """Round-16 smoke: the megakernel round (interpret mode) must grow
    the BIT-identical tree to the three-pass round, and the metrics
    snapshot must carry the megakernel keys — so an off-chip CI run
    catches megakernel regressions in the artifact path, not just in
    tier-1."""
    import time

    import jax.numpy as jnp
    import numpy as np
    from lightgbm_tpu.binning import DatasetBinner
    from lightgbm_tpu.obs import metrics as _obs
    from lightgbm_tpu.ops.split import SplitParams
    from lightgbm_tpu.ops.treegrow_windowed import grow_tree_windowed

    rng = np.random.RandomState(7)
    X = rng.randn(n_rows, n_feat)
    y = X @ rng.randn(n_feat) + 0.2 * rng.randn(n_rows)
    binner = DatasetBinner.fit(X, max_bin=63)
    args = (jnp.asarray(binner.transform(X).T, jnp.int16),
            jnp.asarray(0.6 * y, jnp.float32), jnp.ones((n_rows,), jnp.float32),
            jnp.ones((n_rows,), bool), jnp.ones((n_rows,), jnp.float32),
            jnp.ones((n_feat,), bool),
            jnp.asarray(binner.num_bins_per_feature),
            jnp.asarray(binner.missing_bin_per_feature))
    kw = dict(num_leaves=15, num_bins=64,
              params=SplitParams(min_data_in_leaf=5.0), leaf_tile=4,
              use_pallas=False)

    os.environ["LGBMTPU_MEGAKERNEL"] = "0"
    t0, l0 = grow_tree_windowed(*args, **kw)
    os.environ["LGBMTPU_MEGAKERNEL"] = "interpret"
    try:
        t_start = time.perf_counter()
        t1, l1 = grow_tree_windowed(*args, **kw)
        dt = time.perf_counter() - t_start
    finally:
        os.environ.pop("LGBMTPU_MEGAKERNEL", None)
    for name in t0._fields:
        a, b = np.asarray(getattr(t0, name)), np.asarray(getattr(t1, name))
        assert np.array_equal(a, b), f"megakernel diverged on {name}"
    assert np.array_equal(np.asarray(l0), np.asarray(l1))

    snap = _obs.snapshot()
    _obs.validate_snapshot(snap)
    assert snap["counters"].get("train_megakernel_trees_total", 0) >= 1, (
        "metrics snapshot missing the megakernel counter")
    return int(t0.num_leaves), dt


def bench_serve(n_rows=600, n_feat=8, n_trees=12):
    """Round-18 serving-loop smoke: concurrent requests through the
    coalescing runtime must come back BITWISE equal to individual
    predicts, the queued set must coalesce into fewer batches than
    requests, and the snapshot must carry the serve keys — so an
    off-chip CI run catches serving-loop regressions in the artifact
    path, not just in tier-1."""
    import numpy as np
    import lightgbm_tpu as lgb
    from lightgbm_tpu.obs import metrics as _obs
    from lightgbm_tpu.serve import ServingRuntime

    rng = np.random.RandomState(11)
    X = rng.randn(n_rows, n_feat)
    y = (X[:, 0] + 0.4 * X[:, 1] > 0).astype(float)
    bst = lgb.Booster(params={"objective": "binary", "num_leaves": 15,
                              "max_bin": 63, "verbosity": -1},
                      train_set=lgb.Dataset(X, label=y))
    for _ in range(n_trees):
        bst.update()

    parts = [X[i * 16:(i + 1) * 16] for i in range(8)]
    want = [bst.predict(p, raw_score=True) for p in parts]
    batches0 = _obs.counter("serve_batches_total").value
    rt = ServingRuntime(bst, max_wait_ms=100, start=False,
                        shed_unhealthy=False)
    handles = [rt.submit(p, raw_score=True) for p in parts]
    t0 = time.perf_counter()
    rt.start()
    got = [rt.result(h, timeout=120) for h in handles]
    dt = time.perf_counter() - t0
    rt.stop()
    for w, g in zip(want, got):
        assert np.array_equal(w, g), "coalesced response diverged"
    batches = _obs.counter("serve_batches_total").value - batches0
    assert batches < len(parts), (
        f"8 queued requests dispatched as {batches} batches — no "
        "coalescing happened")

    snap = _obs.snapshot()
    _obs.validate_snapshot(snap)
    for key in ("serve_requests_total", "serve_batches_total",
                "serve_coalesced_rows_total"):
        assert key in snap["counters"], f"metrics snapshot missing {key}"
    assert "serve_queue_depth" in snap["gauges"]
    assert snap["histograms"]["serve_batch_occupancy"]["count"] >= 1
    assert any(k.startswith('serve_request_latency_ms{tenant="')
               for k in snap["histograms"]), (
        "per-tenant serve latency labels missing from the snapshot")
    # round-25 phase breakdown: every request crossed all five phases,
    # so each labeled reservoir must have fired at least once
    for ph in ("queue", "coalesce", "staging", "dispatch", "sliceout"):
        key = _obs.labeled("serve_phase_ms", phase=ph)
        assert snap["histograms"].get(key, {}).get("count", 0) >= 1, (
            f"phase breakdown missing {key}")
    ex = snap["histograms"]["serve_request_latency_ms"].get("exemplar")
    assert ex and ex.get("trace_id"), (
        "serve_request_latency_ms carries no trace-id exemplar")
    return len(parts), batches, sum(p.shape[0] for p in parts) / dt


def bench_fleet_serve(n_rows=600, n_feat=8, n_trees=12):
    """Round-23 fleet-serve smoke: a 2-replica ServingFleet survives an
    injected replica death with ZERO lost requests and bitwise parity
    against individual predicts, requeues the failed batch, restarts the
    replacement, and leaves the fleet snapshot keys — so an off-chip CI
    run catches serve-path resilience regressions in the artifact path,
    not just in tier-1."""
    import numpy as np
    import lightgbm_tpu as lgb
    from lightgbm_tpu.obs import metrics as _obs
    from lightgbm_tpu.serve import ServingFleet
    from lightgbm_tpu.utils import faults as _flt

    rng = np.random.RandomState(23)
    X = rng.randn(n_rows, n_feat)
    y = (X[:, 0] + 0.4 * X[:, 1] > 0).astype(float)
    bst = lgb.Booster(params={"objective": "binary", "num_leaves": 15,
                              "max_bin": 63, "verbosity": -1},
                      train_set=lgb.Dataset(X, label=y))
    for _ in range(n_trees):
        bst.update()

    parts = [X[i * 16:(i + 1) * 16] for i in range(8)]
    want = [bst.predict(p, raw_score=True) for p in parts]
    d0 = _obs.counter("serve_replica_deaths_total").value
    q0 = _obs.counter("serve_requeues_total").value
    fl = ServingFleet(bst, replicas=2, max_wait_ms=20, hedge_ms=0,
                      restart_backoff_ms=50, shed_unhealthy=False)
    t0 = time.perf_counter()
    try:
        # warm with the fault env UNSET (fire() only counts armed sites)
        fl.predict(X[:16], raw_score=True, timeout=120)
        os.environ["LGBMTPU_FAULT"] = "replica_death:0"
        handles = [fl.submit(p, raw_score=True) for p in parts]
        got = [fl.result(h, timeout=120) for h in handles]
        for w, g in zip(want, got):
            assert np.array_equal(w, g), (
                "fleet response diverged across the injected death")
        assert _obs.counter("serve_replica_deaths_total").value == d0 + 1
        assert _obs.counter("serve_requeues_total").value > q0, (
            "the dead replica's batch was never requeued")
        deadline = time.monotonic() + 15
        while (any(r.state != 0 for r in fl._replicas)
               and time.monotonic() < deadline):
            time.sleep(0.05)
        assert fl.stats()["replicas"] == {0: "active", 1: "active"}, (
            "replacement replica never rejoined rotation")
    finally:
        os.environ.pop("LGBMTPU_FAULT", None)
        _flt.reset()
        fl.stop()
    dt = time.perf_counter() - t0

    snap = _obs.snapshot()
    _obs.validate_snapshot(snap)
    for key in ("serve_replica_deaths_total", "serve_requeues_total",
                "serve_replica_restarts_total", "faults_injected_total"):
        assert key in snap["counters"], f"metrics snapshot missing {key}"
    assert "serve_fleet_degraded" in snap["gauges"]
    assert any(k.startswith('serve_replica_batch_ms{replica="')
               for k in snap["histograms"]), (
        "per-replica batch latency labels missing from the snapshot")
    return len(parts), dt


def bench_continual(n_rows=600, n_feat=6, n_trees=6):
    """Round-19 continual smoke: a refit + an append rollover through a
    live ServingRuntime must keep every response bitwise equal to a
    published ensemble's cold predict, drop the staleness gauge to zero,
    and leave the continual snapshot keys — so an off-chip CI run
    catches train-while-serving regressions in the artifact path."""
    import numpy as np
    import lightgbm_tpu as lgb
    from lightgbm_tpu.obs import metrics as _obs
    from lightgbm_tpu.serve import ServingRuntime

    rng = np.random.RandomState(19)
    X = rng.randn(n_rows, n_feat)
    y = (X[:, 0] + 0.4 * X[:, 1] > 0).astype(float)
    ds = lgb.Dataset(X, label=y)
    bst = lgb.Booster(params={"objective": "binary", "num_leaves": 15,
                              "max_bin": 63, "verbosity": -1},
                      train_set=ds)
    for _ in range(n_trees):
        bst.update()
    rt = ServingRuntime(bst, max_wait_ms=2, shed_unhealthy=False)
    cr = lgb.continual_train(bst, {"append_trees": 2}, runtime=rt,
                             reference=ds, start=False)
    Q = rng.randn(32, n_feat)
    t0 = time.perf_counter()
    for kind in ("refit", "append"):
        Xc = rng.randn(200, n_feat)
        yc = (Xc[:, 0] + 0.4 * Xc[:, 1] > 0).astype(float)
        cr.ingest(Xc, yc)
        assert _obs.gauge("model_staleness_rows").value >= 200
        done = cr.update(kind)
        assert done == kind
        assert _obs.gauge("model_staleness_rows").value == 0.0
        got = rt.predict(Q, raw_score=True, timeout=120)
        assert np.array_equal(
            got, cr.booster.predict(Q, raw_score=True)), (
            f"served response diverged from the {kind}-published ensemble")
    dt = time.perf_counter() - t0
    rt.stop()
    assert cr.booster.num_trees() == n_trees + 2

    snap = _obs.snapshot()
    _obs.validate_snapshot(snap)
    for key in ("continual_rollovers_total", "continual_refits_total",
                "continual_appends_total", "continual_ingested_rows_total"):
        assert key in snap["counters"], f"metrics snapshot missing {key}"
    for key in ("model_staleness_rows", "model_staleness_s"):
        assert key in snap["gauges"], f"metrics snapshot missing {key}"
    assert len(_obs.events("continual_rollover")) == 2
    return 2, cr.booster.num_trees(), dt


def bench_multislice(n=1600, n_feat=10):
    """Hierarchical two-level-merge smoke (round 20): a 2-slice x 2-rank
    nested-mesh windowed training (needs >= 4 local devices — self-skips
    below) must equal single-device windowed growth structurally at full
    top-k coverage with zero retries/syncs, and the per-round DCN byte
    bill must be pinned in the metrics-facing audit detail."""
    import jax

    if jax.device_count() < 4:
        return None
    import jax.numpy as jnp

    from lightgbm_tpu.analysis.jaxpr_audit import run_jaxpr_audit
    from lightgbm_tpu.binning import DatasetBinner
    from lightgbm_tpu.ops.split import SplitParams
    from lightgbm_tpu.ops.treegrow_windowed import grow_tree_windowed
    from lightgbm_tpu.parallel.hierarchy import (
        SlicedData, grow_tree_windowed_hierarchical)
    from lightgbm_tpu.parallel.mesh import make_mesh_hierarchical

    rng = np.random.RandomState(5)
    X = rng.randn(n, n_feat)
    y = X @ rng.randn(n_feat) + 0.2 * rng.randn(n)
    binner = DatasetBinner.fit(X, max_bin=31)
    bins = binner.transform(X)
    grad = jnp.asarray(0.6 * y, jnp.float32)
    hess = jnp.ones((n,), jnp.float32)
    kw = dict(num_leaves=15, num_bins=32,
              params=SplitParams(min_data_in_leaf=5.0), leaf_tile=4,
              use_pallas=False)
    t0 = time.perf_counter()
    tree_s, _ = grow_tree_windowed(
        jnp.asarray(bins.T, jnp.int16), grad, hess, jnp.ones((n,), bool),
        jnp.ones((n,), jnp.float32), jnp.ones((n_feat,), bool),
        jnp.asarray(binner.num_bins_per_feature),
        jnp.asarray(binner.missing_bin_per_feature), **kw)
    sd = SlicedData(make_mesh_hierarchical(2, 2), bins,
                    binner.num_bins_per_feature,
                    binner.missing_bin_per_feature)
    stats = {}
    tree_h, leaf_h = grow_tree_windowed_hierarchical(
        sd, sd.pad_rows(np.asarray(grad)), sd.pad_rows(np.asarray(hess)),
        sd.row_valid, sd.pad_rows(np.ones(n, np.float32), fill=1.0),
        jnp.ones((n_feat,), bool), merge="psum", top_k_features=n_feat,
        stats=stats, **kw)
    import jax as _jax
    _jax.block_until_ready(leaf_h)
    m = int(tree_s.num_leaves) - 1
    assert int(tree_h.num_leaves) == m + 1
    assert (np.asarray(tree_s.split_feature)[:m]
            == np.asarray(tree_h.split_feature)[:m]).all()
    assert stats["retries"] == 0 and stats["host_syncs"] == 0, stats
    rep = run_jaxpr_audit(["windowed_round_hierarchical_psum"],
                          runtime=False)
    assert rep.ok, [f.format() for f in rep.findings]
    dcn = rep.results[0].detail["dcn_bytes"]
    assert 0 < dcn <= 16384
    return int(tree_h.num_leaves), dcn, time.perf_counter() - t0


def bench_feature2d(n=1600, n_feat=10):
    """2-D (rows x features) windowed smoke (round 24): a 2x2
    (row, feature) mesh training (needs >= 4 local devices — self-skips
    below) must equal single-device windowed growth structurally with
    zero retries/syncs, and the per-round feature-axis byte bill — the
    go/no-go broadcast + election only, never histograms — must be
    pinned in the metrics-facing audit detail."""
    import jax

    if jax.device_count() < 4:
        return None
    import jax.numpy as jnp

    from lightgbm_tpu.analysis.contracts import _2D_FEATURE_BUDGET
    from lightgbm_tpu.analysis.jaxpr_audit import run_jaxpr_audit
    from lightgbm_tpu.binning import DatasetBinner
    from lightgbm_tpu.ops.split import SplitParams
    from lightgbm_tpu.ops.treegrow_windowed import grow_tree_windowed
    from lightgbm_tpu.parallel.feature2d import (
        Sharded2DData, grow_tree_windowed_feature2d)
    from lightgbm_tpu.parallel.mesh import make_mesh_2d

    rng = np.random.RandomState(5)
    X = rng.randn(n, n_feat)
    y = X @ rng.randn(n_feat) + 0.2 * rng.randn(n)
    binner = DatasetBinner.fit(X, max_bin=31)
    bins = binner.transform(X)
    grad = jnp.asarray(0.6 * y, jnp.float32)
    hess = jnp.ones((n,), jnp.float32)
    kw = dict(num_leaves=15, num_bins=32,
              params=SplitParams(min_data_in_leaf=5.0), leaf_tile=4,
              use_pallas=False)
    t0 = time.perf_counter()
    tree_s, _ = grow_tree_windowed(
        jnp.asarray(bins.T, jnp.int16), grad, hess, jnp.ones((n,), bool),
        jnp.ones((n,), jnp.float32), jnp.ones((n_feat,), bool),
        jnp.asarray(binner.num_bins_per_feature),
        jnp.asarray(binner.missing_bin_per_feature), **kw)
    sd = Sharded2DData(make_mesh_2d(2, 2), bins,
                       binner.num_bins_per_feature,
                       binner.missing_bin_per_feature)
    stats = {}
    tree_d, leaf_d = grow_tree_windowed_feature2d(
        sd, sd.pad_rows_device(grad, jnp.float32),
        sd.pad_rows_device(hess, jnp.float32), sd.row_valid,
        sd.pad_rows_device(np.ones(n, np.float32), jnp.float32, fill=1.0),
        jnp.ones((sd.f_pad,), bool).at[n_feat:].set(False),
        stats=stats, **kw)
    jax.block_until_ready(leaf_d)
    m = int(tree_s.num_leaves) - 1
    assert int(tree_d.num_leaves) == m + 1
    assert (np.asarray(tree_s.split_feature)[:m]
            == np.asarray(tree_d.split_feature)[:m]).all()
    assert stats["retries"] == 0 and stats["host_syncs"] == 0, stats
    rep = run_jaxpr_audit(["windowed_round_2d_float"], runtime=False)
    assert rep.ok, [f.format() for f in rep.findings]
    fb = rep.results[0].detail["feature_bytes"]
    assert 0 < fb <= _2D_FEATURE_BUDGET
    return int(tree_d.num_leaves), fb, time.perf_counter() - t0


def bench_fleet(b=16, n_rows=256, n_feat=6, n_trees=3):
    """Round-20 fleet smoke: a B-lane fleet trained as one dispatch per
    round must leave every lane's served predictions bitwise equal to
    the same lane trained alone through ``lgb.train_fleet`` at B=1, with
    the warm round budget (dispatches == rounds, 0 syncs/retries/
    compiles) pinned from the fleet_round event ledger — the off-chip CI
    catch for batched-training regressions."""
    import numpy as np
    import lightgbm_tpu as lgb
    from lightgbm_tpu.obs import metrics as _obs

    rng = np.random.RandomState(20)
    X = rng.rand(n_rows, n_feat)
    labels = (rng.rand(b, n_rows) > 0.5).astype(np.float64)
    params = {"objective": "binary", "num_leaves": 7, "verbosity": -1,
              "min_data_in_leaf": 5, "seed": 3}
    ds = lgb.Dataset(X, label=labels[0])
    ev0 = len(_obs.events("fleet_round"))
    t0 = time.perf_counter()
    fb = lgb.train_fleet(params, ds, labels, num_boost_round=n_trees)
    dt = time.perf_counter() - t0
    warm = [e for e in _obs.events("fleet_round")[ev0:]
            if e.get("iteration", 0) > 1]
    assert warm and all(
        e.get("dispatches") == e.get("rounds") and e.get("host_syncs") == 0
        and e.get("retries") == 0 and e.get("compiles") == 0
        for e in warm), f"warm fleet round budget broke: {warm}"
    Q = rng.rand(64, n_feat)
    for lane in (0, b // 2, b - 1):
        ds1 = lgb.Dataset(X, label=labels[lane])
        solo = lgb.train_fleet(dict(params), ds1, labels[lane:lane + 1],
                               num_boost_round=n_trees)
        assert np.array_equal(
            fb.booster(lane).predict(Q, raw_score=True),
            solo.booster(0).predict(Q, raw_score=True)), (
            f"fleet lane {lane} diverged from its B=1 run")
    snap = _obs.snapshot()
    _obs.validate_snapshot(snap)
    assert "train_fleet_models_total" in snap["counters"]
    assert "fleet_models" in snap["gauges"]
    return b, n_trees, dt


def main():
    n = int(os.environ.get("SMOKE_ROWS", 1_000_000))
    iters = int(os.environ.get("SMOKE_ITERS", 10))
    which = (sys.argv[1].split(",") if len(sys.argv) > 1
             else ["rank", "multiclass", "predict", "serve", "ooc",
                   "megakernel", "continual", "fleet", "fleet_serve",
                   "multislice", "feature2d"])
    if "rank" in which:
        ips = bench_rank(n, q_len=128, iters=iters)
        print(f"lambdarank {n//1000}k rows x64f q128 63bins: {ips:.2f} iters/sec", flush=True)
    if "multiclass" in which:
        ips = bench_multiclass(n, k=5, iters=iters)
        print(f"multiclass5 {n//1000}k rows x28f 63bins: {ips:.2f} iters/sec (5 trees/iter)", flush=True)
    if "predict" in which:
        rps, err = bench_predict()
        print(f"predict 2k rows x16f T24: {rps:.0f} rows/sec warm "
              f"(1 dispatch/call, host-walk parity {err:.1e})", flush=True)
    if "serve" in which:
        reqs, batches, rps = bench_serve()
        print(f"serve 8x16-row concurrent requests: {batches} coalesced "
              f"batch(es), bitwise parity, {rps:.0f} rows/sec", flush=True)
    if "ooc" in which:
        rps, passes = bench_ooc()
        print(f"out_of_core 3k rows x8f: {rps:.0f} streamed rows/sec spill "
              f"({passes} hist passes, resident+spill bitwise parity)",
              flush=True)
    if "megakernel" in which:
        leaves, dt = bench_megakernel()
        print(f"megakernel 2k rows x10f: {leaves}-leaf tree bitwise == "
              f"three-pass round ({dt:.1f}s interpret, snapshot keys ok)",
              flush=True)
    if "continual" in which:
        rollovers, trees, dt = bench_continual()
        print(f"continual 600 rows x6f: {rollovers} zero-downtime "
              f"rollovers (refit+append) -> {trees} trees, served "
              f"bitwise, staleness drops, snapshot keys ok ({dt:.1f}s)",
              flush=True)
    if "fleet" in which:
        b, trees, dt = bench_fleet()
        print(f"fleet {b} boosters x256 rows x6f: {trees} rounds at one "
              f"dispatch/round, lanes bitwise == their B=1 runs, warm "
              f"budget pinned ({dt:.1f}s)", flush=True)
    if "fleet_serve" in which:
        reqs, dt = bench_fleet_serve()
        print(f"fleet_serve 2 replicas x{reqs} requests: injected replica "
              f"death, 0 lost, bitwise parity, requeued + restarted, "
              f"snapshot keys ok ({dt:.1f}s)", flush=True)
    if "multislice" in which:
        got = bench_multislice()
        if got is None:
            print("multislice: skipped (< 4 local devices)", flush=True)
        else:
            leaves, dcn, dt = got
            print(f"multislice 1.6k rows x10f on 2x2 nested mesh: "
                  f"{leaves}-leaf tree == single-device at full top-k, "
                  f"dcn_bytes/round={dcn} pinned ({dt:.1f}s)", flush=True)
    if "feature2d" in which:
        got = bench_feature2d()
        if got is None:
            print("feature2d: skipped (< 4 local devices)", flush=True)
        else:
            leaves, fb, dt = got
            print(f"feature2d 1.6k rows x10f on 2x2 (rows x features) "
                  f"mesh: {leaves}-leaf tree == single-device, "
                  f"feature_bytes/round={fb} pinned, hist merge row-axis "
                  f"only ({dt:.1f}s)", flush=True)


if __name__ == "__main__":
    main()
