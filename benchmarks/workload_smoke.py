"""Throughput smoke for the non-binary baseline workloads (BASELINE.md):
LambdaRank (MSLR-like) and multiclass (Airline-like).  Prints iters/sec
for each on the current backend."""

import os
import sys
import time

import numpy as np


def bench_rank(n, q_len, iters):
    import jax
    import lightgbm_tpu as lgb

    rng = np.random.RandomState(0)
    nq = n // q_len
    n = nq * q_len
    X = rng.randn(n, 64).astype(np.float32)
    w = rng.randn(64) / 8
    rel = X @ w + 0.7 * rng.randn(n)
    # 0-4 relevance labels per query by rank within query
    y = np.zeros(n)
    for qi in range(nq):
        s = slice(qi * q_len, (qi + 1) * q_len)
        order = np.argsort(np.argsort(-rel[s]))
        y[s] = np.clip(4 - order // (q_len // 5 + 1), 0, 4)
    d = lgb.Dataset(X, label=y, group=np.full(nq, q_len))
    bst = lgb.Booster(params={"objective": "lambdarank", "num_leaves": 31,
                              "max_bin": 63, "verbosity": -1}, train_set=d)
    bst.update()
    jax.block_until_ready(bst._gbdt._score)
    t0 = time.perf_counter()
    for _ in range(iters):
        bst.update()
    jax.block_until_ready(bst._gbdt._score)
    return iters / (time.perf_counter() - t0)


def bench_multiclass(n, k, iters):
    import jax
    import lightgbm_tpu as lgb

    rng = np.random.RandomState(1)
    X = rng.randn(n, 28).astype(np.float32)
    centers = rng.randn(k, 28)
    y = np.argmax(X @ centers.T + rng.randn(n, k), axis=1).astype(np.float64)
    d = lgb.Dataset(X, label=y)
    bst = lgb.Booster(params={"objective": "multiclass", "num_class": k,
                              "num_leaves": 31, "max_bin": 63,
                              "verbosity": -1}, train_set=d)
    bst.update()
    jax.block_until_ready(bst._gbdt._score)
    t0 = time.perf_counter()
    for _ in range(iters):
        bst.update()
    jax.block_until_ready(bst._gbdt._score)
    return iters / (time.perf_counter() - t0)


def main():
    n = int(os.environ.get("SMOKE_ROWS", 1_000_000))
    iters = int(os.environ.get("SMOKE_ITERS", 10))
    which = sys.argv[1].split(",") if len(sys.argv) > 1 else ["rank", "multiclass"]
    if "rank" in which:
        ips = bench_rank(n, q_len=128, iters=iters)
        print(f"lambdarank {n//1000}k rows x64f q128 63bins: {ips:.2f} iters/sec", flush=True)
    if "multiclass" in which:
        ips = bench_multiclass(n, k=5, iters=iters)
        print(f"multiclass5 {n//1000}k rows x28f 63bins: {ips:.2f} iters/sec (5 trees/iter)", flush=True)


if __name__ == "__main__":
    main()
