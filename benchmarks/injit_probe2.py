"""Second in-jit probe round: B-dependence, NC-dependence, iota hoisting,
and a no-onehot control (dot against a constant matrix) to separate
one-hot construction cost from MXU/dot-issue cost."""

import functools
import sys
import time

import numpy as np
import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

K = 20
FLOOR_MS = 23.4


def make_kernel(nc, B, *, row_tile=1024, F=28, hoist_iota=False, no_onehot=False,
                matmul_dtype=jnp.bfloat16):
    def kernel(bins_ref, pay_ref, out_ref, acc_ref):
        i = pl.program_id(1)

        @pl.when(i == 0)
        def _():
            acc_ref[...] = jnp.zeros_like(acc_ref)

        pay = pay_ref[...].astype(matmul_dtype)
        T = pay.shape[0]
        if hoist_iota:
            iota_b = jax.lax.broadcasted_iota(jnp.int32, (T, B), 1)
        for f in range(F):
            if no_onehot:
                # control: same dot shape, one-hot replaced by a cheap
                # constant matrix derived from bins (defeats CSE via f)
                oh = (bins_ref[:, f].astype(jnp.int32)[:, None] +
                      jnp.zeros((T, B), jnp.int32)).astype(matmul_dtype)
            else:
                if not hoist_iota:
                    iota_b = jax.lax.broadcasted_iota(jnp.int32, (T, B), 1)
                binf = bins_ref[:, f].astype(jnp.int32)[:, None]
                oh = (binf == iota_b).astype(matmul_dtype)
            acc_ref[f] += jax.lax.dot_general(
                pay, oh, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)

        @pl.when(i == pl.num_programs(1) - 1)
        def _():
            out_ref[...] = acc_ref[...]

    @jax.jit
    def run(bins, pay):
        n = bins.shape[0]
        grid = (1, n // row_tile)
        return pl.pallas_call(
            kernel,
            grid=grid,
            in_specs=[
                pl.BlockSpec((row_tile, F), lambda j, i: (i, j), memory_space=pltpu.VMEM),
                pl.BlockSpec((row_tile, nc), lambda j, i: (i, 0), memory_space=pltpu.VMEM),
            ],
            out_specs=pl.BlockSpec((F, nc, B), lambda j, i: (j, 0, 0), memory_space=pltpu.VMEM),
            out_shape=jax.ShapeDtypeStruct((F, nc, B), jnp.float32),
            scratch_shapes=[pltpu.VMEM((F, nc, B), jnp.float32)],
            cost_estimate=pl.CostEstimate(
                flops=2 * n * F * B * nc,
                bytes_accessed=n * F * bins.dtype.itemsize + n * nc * 4,
                transcendentals=0,
            ),
        )(bins, pay)

    return run


def main():
    n, F = 999424, 28
    rng = np.random.RandomState(0)
    bins = jnp.asarray(rng.randint(0, 64, size=(n, F)).astype(np.int16))
    pay48 = jnp.asarray(rng.randn(n, 48).astype(np.float32))

    which = sys.argv[1].split(",") if len(sys.argv) > 1 else [
        "b64", "hoist", "noonehot", "nc8",
    ]
    cases = {
        "b256": ("direct48 B256", make_kernel(48, 256), pay48),
        "b64": ("direct48 B64", make_kernel(48, 64), pay48),
        "hoist": ("direct48 B256 hoisted-iota", make_kernel(48, 256, hoist_iota=True), pay48),
        "noonehot": ("direct48 B256 no-onehot", make_kernel(48, 256, no_onehot=True), pay48),
        "nc8": ("direct8 B256", make_kernel(8, 256), pay48[:, :8]),
        "nc8b64": ("direct8 B64", make_kernel(8, 64), pay48[:, :8]),
    }

    for key in which:
        name, fn, pay = cases[key]

        @jax.jit
        def loop(fn=fn, pay=pay):
            def body(i, acc):
                p = pay * (1.0 + i.astype(jnp.float32) * 1e-9)
                return acc + fn(bins, p)[0, 0, 0]
            return jax.lax.fori_loop(0, K, body, jnp.float32(0))

        t0 = time.perf_counter()
        out = loop(); np.asarray(out).ravel()[:1]
        print(f"{name} compile+first: {time.perf_counter()-t0:.0f}s", flush=True)
        t0 = time.perf_counter()
        for _ in range(5):
            out = loop()
        np.asarray(out).ravel()[:1]
        total = (time.perf_counter() - t0) / 5 * 1e3
        print(f"{name:32s} per-iter ~{(total - FLOOR_MS)/K:6.2f} ms", flush=True)


if __name__ == "__main__":
    main()
