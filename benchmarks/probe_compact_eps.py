"""Round-3 lever-2 probe: row-compaction gather cost at Epsilon shape.

At 1M x 28 the leaf-gather was a measured dead end (909 ms vs ~3 ms
passes).  At Epsilon shape (400k x 2000, 255 bins) passes cost ~200 ms
each and the grower runs ~26 admission rounds; if a full-matrix gather
costs ~1-2 passes, physically regrouping rows by leaf once per round
could shrink later passes.  Measure the gather + a pass over the
compacted matrix.
"""
import time
import numpy as np
import jax
import jax.numpy as jnp

N, F = 400_000, 2000
rng = np.random.RandomState(0)
bins = jnp.asarray(rng.randint(0, 255, (N, F), np.int16), jnp.int16)
perm = jnp.asarray(rng.permutation(N))

@jax.jit
def gather_rows(b, p):
    return jnp.take(b, p, axis=0)

def timeit(fn, *a, reps=5):
    out = fn(*a); jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(reps):
        out = fn(*a)
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / reps

t_gather = timeit(gather_rows, bins, perm)
print(f"row-gather (400k x 2000 int16): {t_gather*1e3:.1f} ms")

# and the transposed (feature-major) layout the partition loop uses
bins_t = jnp.asarray(np.asarray(bins).T)
@jax.jit
def gather_cols(bt, p):
    return jnp.take(bt, p, axis=1)
t_gather_t = timeit(gather_cols, bins_t, perm)
print(f"col-gather of (2000 x 400k) int16: {t_gather_t*1e3:.1f} ms")
