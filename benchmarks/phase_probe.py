"""In-jit phase costing for the fast grower (round-2 perf campaign).

The axon tunnel imposes a ~10-14 ms host cost PER DISPATCH, so individual
jit calls cannot be timed meaningfully.  This probe wraps each candidate
phase in a fori_loop of K iterations inside ONE jit; true per-iteration
device cost = (total - dispatch_floor) / K.  Each body varies with the
loop index (cheaply) to defeat loop-invariant hoisting.
"""

import sys
import time

import numpy as np
import jax
import jax.numpy as jnp

import lightgbm_tpu.ops.hist_pallas as hp
from lightgbm_tpu.ops.split import SplitParams, find_best_split

K = 20


def timed(name, fn, reps=5):
    out = fn()
    np.asarray(out).ravel()[:1]
    t0 = time.perf_counter()
    for _ in range(reps):
        out = fn()
    np.asarray(out).ravel()[:1]
    ms = (time.perf_counter() - t0) / reps * 1e3
    print(f"{name:26s} {ms:8.2f} ms total -> {(ms):7.2f}/call; per-iter ~{ms/K:6.2f} ms",
          flush=True)
    return ms


def main():
    n, F, B, L = 1_000_000, 28, 256, 31
    rng = np.random.RandomState(0)
    bins = jnp.asarray(rng.randint(0, B, size=(n, F)).astype(np.int16))
    grad = jnp.asarray(rng.randn(n).astype(np.float32))
    hess = jnp.asarray(rng.rand(n).astype(np.float32))
    mask = jnp.ones((n,), bool)
    leaf_id0 = jnp.asarray(rng.randint(0, 8, size=(n,)).astype(np.int32))
    hist16 = jnp.asarray(rng.rand(16, 3, F, B).astype(np.float32))
    params = SplitParams(min_data_in_leaf=20.0)
    nbpf = jnp.full((F,), B, jnp.int32)
    mbpf = jnp.full((F,), B - 1, jnp.int32)
    fmask = jnp.ones((F,), bool)

    which = sys.argv[1].split(",") if len(sys.argv) > 1 else [
        "floor", "pass", "payload", "partition", "slotloop", "eval",
    ]

    if "floor" in which:
        x = jnp.ones((8,))
        timed("dispatch floor", jax.jit(lambda: x + 1.0))

    if "pass" in which:
        @jax.jit
        def pass_loop():
            def body(i, acc):
                g = grad * (1.0 + i.astype(jnp.float32) * 1e-9)
                h = hp.histogram_pallas_multi(
                    bins, g, hess, mask, leaf_id0, 0, 8, B,
                    precision="f32", row_tile=1024)
                return acc + h[0, 0, 0, 0]
            return jax.lax.fori_loop(0, K, body, jnp.float32(0))
        timed("multi pass (x20 in jit)", pass_loop)

    if "payload" in which:
        @jax.jit
        def payload_loop():
            def body(i, acc):
                g = grad * (1.0 + i.astype(jnp.float32) * 1e-9)
                m = mask.astype(jnp.float32)
                gm = g * m
                hm = hess * m
                g_hi = gm.astype(jnp.bfloat16).astype(jnp.float32)
                h_hi = hm.astype(jnp.bfloat16).astype(jnp.float32)
                chans = [g_hi, h_hi, m, gm - g_hi, hm - h_hi, jnp.zeros_like(m)]
                base = jnp.stack(chans, axis=-1)
                onehot = (leaf_id0[:, None] == jnp.arange(8, dtype=jnp.int32)[None, :]).astype(jnp.float32)
                pay = (onehot[:, :, None] * base[:, None, :]).reshape(n, 48)
                return acc + pay[0, 0] + pay[-1, -1]
            return jax.lax.fori_loop(0, K, body, jnp.float32(0))
        timed("payload prep (x20)", payload_loop)

    if "partition" in which:
        @jax.jit
        def partition_loop():
            def body(i, lid):
                for r in range(8):
                    fcol = jax.lax.dynamic_index_in_dim(
                        bins, (i + r) % F, axis=1, keepdims=False
                    ).astype(jnp.int32)
                    gl = fcol <= 128
                    lid = jnp.where((lid == r) & ~gl, lid + 8, lid)
                return lid
            return jax.lax.fori_loop(0, K, body, leaf_id0)
        timed("partition 8-col (x20)", partition_loop)

    if "slotloop" in which:
        small_slot = jnp.asarray(rng.permutation(L)[:L].astype(np.int32))

        @jax.jit
        def slot_loop():
            def body(i, acc):
                lid = leaf_id0 + i * 0
                leaf_slot = jnp.full((n,), -1, jnp.int32)
                ss = jnp.where(small_slot >= i % 3, small_slot, -1)
                for r in range(8):
                    has_r = ss == r
                    leaf_r = jnp.argmax(has_r).astype(jnp.int32)
                    exists = jnp.any(has_r)
                    leaf_slot = jnp.where(exists & (lid == leaf_r), r, leaf_slot)
                return acc + leaf_slot[0] + leaf_slot[-1]
            return jax.lax.fori_loop(0, K, body, jnp.int32(0))
        timed("slot-map loop (x20)", slot_loop)

    if "eval" in which:
        def one(hist, nid):
            return find_best_split(
                hist, hist[0].sum(), hist[1].sum(), hist[2].sum(),
                nbpf, mbpf, params, feature_mask=fmask, categorical_mask=None,
                monotone_constraints=None,
                out_lo=jnp.float32(-jnp.inf), out_hi=jnp.float32(jnp.inf),
                rng_key=None, depth=jnp.float32(0),
                parent_output=jnp.float32(0), cegb_feature_penalty=None,
            )

        @jax.jit
        def eval_loop():
            def body(i, acc):
                h = hist16 * (1.0 + i.astype(jnp.float32) * 1e-9)
                bb = jax.vmap(one, in_axes=(0, 0))(h, jnp.arange(16))
                return acc + bb.gain.sum()
            return jax.lax.fori_loop(0, K, body, jnp.float32(0))
        timed("eval 16 slots (x20)", eval_loop)


if __name__ == "__main__":
    main()
