"""Serving-loop benchmark (round 18): coalesced vs per-request serial.

``predict_bench.py`` measures the ENTRY (one caller, warm predict);
this measures the PROCESS (lightgbm_tpu/serve): K concurrent callers
whose requests coalesce into bucket-rung batches, against the
per-request serial baseline where each request pays its own dispatch +
sync + staging.  Two load shapes:

* ``closed_C<k>`` — closed loop: C caller threads, each issuing
  back-to-back blocking predicts of a small request (the tail-chasing
  regime).  Reports rows/s + per-request p50/p99 for the runtime and for
  the serial baseline (the same total work, one blocking predict per
  request), plus how many coalesced batches the runtime actually formed.
* ``open_loop`` — open loop: a DETERMINISTIC arrival schedule (fixed
  inter-arrival gap, fixed size cycle — no wall-clock randomness in the
  artifact; the measured latencies are of course wall clock) submitted
  asynchronously, completions collected afterwards.
* ``fleet_chaos`` (round 23) — the same open-loop schedule against a
  2-replica ServingFleet with an injected ``replica_death`` mid-run:
  reports lost-request count (must be 0), bitwise parity, requeue /
  restart counts and the chaos-run p50/p99 — resilience priced in the
  same artifact as throughput.

``parity`` runs first and asserts IN THE ARTIFACT PATH that every
coalesced response is bitwise the individual ``predict``'s — the same
pin tests/test_serve.py carries, re-checked where the numbers are made.

Artifact contract mirrors bench.py: one JSON snapshot line printed +
flushed after every completed workload; the metrics snapshot rides every
emit and the jaxpr-audit verdict (incl. ``predict_coalesced_bucket``) is
embedded at the end.  Set SERVE_BENCH_OUT to also write the final
snapshot to a file (e.g. BENCH_serve_r01.json).

Env knobs: SERVE_BENCH_CONCURRENCY="1,4,16,64", SERVE_BENCH_TREES
(default 200), SERVE_BENCH_ROWS (rows per request, default 8),
SERVE_BENCH_REQS (requests per caller, default 24), SERVE_BENCH_BUDGET_S
(default 300), SERVE_BENCH_OUT.
"""

import json
import os
import sys
import threading
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

_T0 = time.monotonic()
_BUDGET_S = float(os.environ.get("SERVE_BENCH_BUDGET_S", 300))

_STATE = {
    "metric": "serve_rows_per_sec",
    "value": None,
    "unit": "rows/sec",
    "vs_baseline": None,  # the serial baseline is in-artifact per workload
    "workloads": {},
}


def _emit():
    try:
        from lightgbm_tpu.obs import metrics as _obs

        _STATE["metrics"] = _obs.snapshot()
    except Exception:  # noqa: BLE001 — artifact robustness first
        pass
    line = json.dumps(_STATE, default=str) + "\n"
    sys.stdout.write(line)
    sys.stdout.flush()
    out = os.environ.get("SERVE_BENCH_OUT")
    if out:
        with open(out, "w") as fh:
            fh.write(line)


def _remaining():
    return _BUDGET_S - (time.monotonic() - _T0)


def _guarded(name, fn, budget_floor=10.0):
    if _remaining() < budget_floor:
        _STATE["workloads"][name] = {"skipped": "budget"}
        _emit()
        return
    try:
        fn()
    except Exception as e:  # noqa: BLE001 — artifact robustness
        _STATE["workloads"][name] = {"error": f"{type(e).__name__}: {e}"[:300]}
    _emit()


def _pcts(lat_s):
    lat = np.asarray(lat_s) * 1e3
    return (round(float(np.percentile(lat, 50)), 3),
            round(float(np.percentile(lat, 99)), 3))


_PHASES = ("queue", "coalesce", "staging", "dispatch", "sliceout")


def _phase_breakdown():
    """p50/p99 of the per-request phase stamps the runtime records at
    already-accounted sync points (zero extra device pulls).  Reservoirs
    accumulate across the artifact run, so each row reports the
    distribution as of the end of its workload."""
    from lightgbm_tpu.obs import metrics as _obs

    out = {}
    for ph in _PHASES:
        h = _obs.histogram(_obs.labeled("serve_phase_ms", phase=ph))
        if h.count:
            out[ph] = {"p50_ms": round(h.percentile(50), 3),
                       "p99_ms": round(h.percentile(99), 3),
                       "count": h.count}
    return out


def bench_parity(g, X):
    """Bitwise parity of coalesced responses, asserted in-artifact."""
    from lightgbm_tpu.serve import ServingRuntime

    parts = [X[0:10], X[10:17], X[17:40], X[40:41], X[41:73]]
    want = [g.predict(p, raw_score=True) for p in parts]
    rt = ServingRuntime(g, max_wait_ms=100, start=False,
                        shed_unhealthy=False)
    handles = [rt.submit(p, raw_score=True) for p in parts]
    rt.start()
    got = [rt.result(h, timeout=120) for h in handles]
    rt.stop()
    ok = all(np.array_equal(w, o) for w, o in zip(want, got))
    _STATE["workloads"]["parity"] = {
        "bitwise_parity": ok, "requests": len(parts),
        "rows": int(sum(p.shape[0] for p in parts))}
    if not ok:
        raise AssertionError("coalesced responses diverged from "
                             "individual predicts")


def _warm_ladder(g, X, max_rows):
    """Warm every bucket rung (masked + exact variants) a coalesced
    batch can land on, through ordinary single-caller predicts — the
    runtime then reuses these executables (the ladder-sharing property;
    cold compiles are predict_bench's business, not this artifact's)."""
    nb = 8
    while nb <= max_rows:
        g.predict(X[:nb], raw_score=True)      # exact-fill variant
        if nb > 8:
            g.predict(X[:nb - 1], raw_score=True)  # masked variant
        nb <<= 1


def bench_closed_loop(g, X, conc_list, rows, reqs_per_caller):
    """C callers x back-to-back requests: runtime vs per-request serial."""
    from lightgbm_tpu.obs import metrics as _obs
    from lightgbm_tpu.serve import ServingRuntime

    _warm_ladder(g, X, min(max(conc_list) * rows * 2, 4096))
    for conc in conc_list:
        name = f"closed_C{conc}"
        if _remaining() < 15:
            _STATE["workloads"][name] = {"skipped": "budget"}
            _emit()
            continue
        n_req = conc * reqs_per_caller
        slices = [X[(i * rows) % (X.shape[0] - rows):][:rows]
                  for i in range(n_req)]

        # serial baseline: the same requests, one blocking predict each
        t0 = time.perf_counter()
        ser_lat = []
        for s in slices:
            t1 = time.perf_counter()
            g.predict(s, raw_score=True)
            ser_lat.append(time.perf_counter() - t1)
        ser_wall = time.perf_counter() - t0
        ser_p50, ser_p99 = _pcts(ser_lat)

        batches0 = _obs.counter("serve_batches_total").value
        rt = ServingRuntime(g, max_wait_ms=2, shed_unhealthy=False)
        lat = [None] * n_req
        errs = []

        def caller(c):
            try:
                for j in range(reqs_per_caller):
                    i = c * reqs_per_caller + j
                    t1 = time.perf_counter()
                    rt.predict(slices[i], raw_score=True, timeout=120)
                    lat[i] = time.perf_counter() - t1
            except BaseException as e:  # noqa: BLE001
                errs.append(f"{type(e).__name__}: {e}")

        threads = [threading.Thread(target=caller, args=(c,))
                   for c in range(conc)]
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        wall = time.perf_counter() - t0
        rt.stop()
        if errs:
            raise AssertionError(f"closed loop C={conc}: {errs[:3]}")
        p50, p99 = _pcts(lat)
        batches = _obs.counter("serve_batches_total").value - batches0
        rps = round(n_req * rows / wall, 1)
        ser_rps = round(n_req * rows / ser_wall, 1)
        _STATE["workloads"][name] = {
            "concurrency": conc, "requests": n_req, "rows_per_req": rows,
            "coalesced": {"rows_per_sec": rps, "p50_ms": p50,
                          "p99_ms": p99, "batches": batches},
            "serial": {"rows_per_sec": ser_rps, "p50_ms": ser_p50,
                       "p99_ms": ser_p99, "batches": n_req},
            "speedup": round(rps / max(ser_rps, 1e-9), 2),
            "phases": _phase_breakdown(),
        }
        if _STATE["value"] is None or rps > _STATE["value"]:
            _STATE["value"] = rps
            _STATE["metric"] = f"serve_rows_per_sec_C{conc}_r{rows}"
        _emit()


def bench_open_loop(g, X, rows):
    """Deterministic open-loop arrivals: fixed 2 ms gap, sizes cycling a
    fixed pattern — submissions don't wait for completions."""
    from lightgbm_tpu.serve import Overloaded, ServingRuntime

    n_req, gap_s = 200, 0.002
    sizes = [1, rows, 4 * rows, 2]  # the deterministic size cycle
    _warm_ladder(g, X, 16 * max(sizes))
    rt = ServingRuntime(g, max_wait_ms=2, shed_unhealthy=False)
    handles, lat, shed = [], [], 0
    t0 = time.perf_counter()
    for i in range(n_req):
        target = t0 + i * gap_s
        delay = target - time.perf_counter()
        if delay > 0:
            time.sleep(delay)
        n = sizes[i % len(sizes)]
        try:
            handles.append(rt.submit(X[:n], raw_score=True))
        except Overloaded:
            shed += 1
    for h in handles:
        rt.result(h, timeout=120)
        # true per-request latency: the runtime stamps completion when
        # the batch's accounted sync retires, not when we collect
        lat.append(h.t_done - h.t0)
    wall = time.perf_counter() - t0
    rt.stop()
    p50, p99 = _pcts(lat)
    total_rows = sum(sizes[i % len(sizes)] for i in range(n_req)) - 0
    _STATE["workloads"]["open_loop"] = {
        "requests": n_req, "arrival_gap_ms": gap_s * 1e3,
        "size_cycle": sizes, "shed": shed,
        "rows_per_sec": round(total_rows / wall, 1),
        "p50_ms": p50, "p99_ms": p99,
        "phases": _phase_breakdown(),
    }
    _emit()


def bench_fleet_chaos(g, X, rows):
    """Chaos row (round 23): a 2-replica ServingFleet loses one replica
    to an injected ``replica_death`` mid-open-loop and must lose ZERO
    admitted requests, keep every response bitwise equal to the warm
    predict, requeue the failed batch exactly once, and restart the
    replacement — the resilience numbers published next to the
    throughput numbers they protect."""
    from lightgbm_tpu.obs import metrics as _obs
    from lightgbm_tpu.serve import ServingFleet
    from lightgbm_tpu.utils import faults as _flt

    n_req, gap_s = 120, 0.002
    sizes = [rows, 2 * rows, 1, rows]  # deterministic size cycle
    _warm_ladder(g, X, 16 * max(sizes))
    d0 = _obs.counter("serve_replica_deaths_total").value
    q0 = _obs.counter("serve_requeues_total").value
    r0 = _obs.counter("serve_replica_restarts_total").value
    fl = ServingFleet(g, replicas=2, max_wait_ms=2, shed_unhealthy=False,
                      restart_backoff_ms=50, hedge_ms=0)
    lat, lost = [], 0
    try:
        # warm the fleet path with the fault env UNSET: fire() only
        # advances counters for armed sites, so this never skews the arm
        fl.predict(X[:rows], raw_score=True, timeout=120)
        os.environ["LGBMTPU_FAULT"] = "replica_death:0"
        handles = []
        t0 = time.perf_counter()
        for i in range(n_req):
            target = t0 + i * gap_s
            delay = target - time.perf_counter()
            if delay > 0:
                time.sleep(delay)
            handles.append(fl.submit(X[:sizes[i % len(sizes)]],
                                     raw_score=True))
        results = []
        for h in handles:
            try:
                results.append(fl.result(h, timeout=120))
                lat.append(h.t_done - h.t0)
            except Exception:  # noqa: BLE001 — a lost admitted request
                lost += 1
        wall = time.perf_counter() - t0
        ok = all(
            np.array_equal(r, g.predict(X[:r.shape[0]], raw_score=True))
            for r in results)
        # the replacement rejoins on the supervisor cadence
        deadline = time.monotonic() + 15
        while (_obs.counter("serve_replica_restarts_total").value - r0 < 1
               and time.monotonic() < deadline):
            time.sleep(0.05)
    finally:
        os.environ.pop("LGBMTPU_FAULT", None)
        _flt.reset()
        fl.stop()
    p50, p99 = _pcts(lat)
    total_rows = sum(sizes[i % len(sizes)] for i in range(n_req))
    _STATE["workloads"]["fleet_chaos"] = {
        "replicas": 2, "requests": n_req, "lost": lost,
        "bitwise_parity": ok,
        "deaths": _obs.counter("serve_replica_deaths_total").value - d0,
        "requeues": _obs.counter("serve_requeues_total").value - q0,
        "restarts": _obs.counter("serve_replica_restarts_total").value - r0,
        "rows_per_sec": round(total_rows / wall, 1),
        "p50_ms": p50, "p99_ms": p99,
        "phases": _phase_breakdown(),
    }
    if lost or not ok:
        raise AssertionError(
            f"fleet chaos: lost={lost} bitwise_parity={ok}")
    _emit()


def main():
    import jax

    from benchmarks.predict_bench import synthetic_gbdt

    conc_list = [int(c) for c in os.environ.get(
        "SERVE_BENCH_CONCURRENCY", "1,4,16,64").split(",")]
    trees = int(os.environ.get("SERVE_BENCH_TREES", 200))
    rows = int(os.environ.get("SERVE_BENCH_ROWS", 8))
    reqs = int(os.environ.get("SERVE_BENCH_REQS", 24))
    f = 28
    _STATE["platform"] = jax.devices()[0].platform
    _STATE["trees"] = trees

    rng = np.random.RandomState(0)
    X = rng.randn(max(64 * rows, 4096), f).astype(np.float32)
    g = synthetic_gbdt(trees, depth=6, num_features=f, seed=7)

    _guarded("parity", lambda: bench_parity(g, X), budget_floor=20.0)
    _guarded("closed_loop",
             lambda: bench_closed_loop(g, X, conc_list, rows, reqs),
             budget_floor=30.0)
    _guarded("open_loop", lambda: bench_open_loop(g, X, rows),
             budget_floor=15.0)
    _guarded("fleet_chaos", lambda: bench_fleet_chaos(g, X, rows),
             budget_floor=25.0)

    # jaxpr-audit verdict (docs/ANALYSIS.md): the artifact carries proof
    # the serving contracts — incl. predict_coalesced_bucket — held at
    # trace time, next to the numbers
    def _embed_audit():
        from lightgbm_tpu.analysis.jaxpr_audit import verdict

        _STATE["jaxpr_audit"] = verdict(runtime=False, exec_contracts=False)
        _STATE["workloads"]["jaxpr_audit"] = {
            "ok": _STATE["jaxpr_audit"].get("ok")}

    _guarded("jaxpr_audit", _embed_audit, budget_floor=30.0)

    _STATE["elapsed_s"] = round(time.monotonic() - _T0, 1)
    _emit()
    return 0


if __name__ == "__main__":
    sys.exit(main())
