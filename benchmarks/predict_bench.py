"""Serving (predict) benchmark: cold compile, warm throughput, tail latency.

Rounds 1-8 tracked only training; this closes the inference blind spot the
round-9 serving layer was built for.  For each ensemble size T in
{100, 2000} (plus a multiclass shape) and batch size N in
{1, 128, 4096, 262144} it measures:

* ``cold_s``      — first call: host pack + upload + bucket compile
* ``rows_per_sec``— warm steady-state throughput (median over repeats)
* ``p50_ms`` / ``p99_ms`` — warm per-call batch latency percentiles
* ``warm_dispatches`` — dispatches of one warm call (the budget the
  tests pin; a regression here shows up in the artifact too)

Artifact contract mirrors bench.py: a full JSON snapshot line
(``{"metric": "predict_rows_per_sec", ...}``) is printed and flushed after
EVERY completed workload, so a driver timeout keeps everything measured so
far; a global budget (PREDICT_BENCH_BUDGET_S, default 300) records
not-yet-started workloads as skipped.  Set PREDICT_BENCH_OUT to also write
the final snapshot to a file (e.g. BENCH_predict_r01.json).

The ensembles are SYNTHETIC (random complete trees): serving cost depends
on T/depth/N, not on how the trees were fit, and synthesizing keeps the
bench off the 2000-round training cost.  ``synthetic_gbdt`` is also reused
by the workload smoke as the parity oracle harness.

Env knobs: PREDICT_BENCH_SIZES="1,128,4096" PREDICT_BENCH_TREES="100,2000"
PREDICT_BENCH_REPEATS (default 20; 5 for N >= 100k), PREDICT_BENCH_DEPTH
(default 6), PREDICT_BENCH_BUDGET_S, PREDICT_BENCH_OUT.
"""

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

_T0 = time.monotonic()
_BUDGET_S = float(os.environ.get("PREDICT_BENCH_BUDGET_S", 300))

_STATE = {
    "metric": "predict_rows_per_sec",
    "value": None,
    "unit": "rows/sec",
    "vs_baseline": None,  # no reference predict anchor yet (BASELINE.md)
    "workloads": {},
}


def _emit():
    try:
        # telemetry snapshot embedded in the artifact (docs/OBSERVABILITY.md):
        # warm-latency reservoirs + bucket hit/miss + dispatch ledger travel
        # with every emitted row
        from lightgbm_tpu.obs import metrics as _obs

        _STATE["metrics"] = _obs.snapshot()
    except Exception:  # noqa: BLE001 — artifact robustness first
        pass
    line = json.dumps(_STATE, default=str) + "\n"
    sys.stdout.write(line)
    sys.stdout.flush()
    out = os.environ.get("PREDICT_BENCH_OUT")
    if out:
        # the file carries the freshest snapshot too, so a driver kill
        # mid-workload still leaves every completed row on disk
        with open(out, "w") as fh:
            fh.write(line)


def _remaining():
    return _BUDGET_S - (time.monotonic() - _T0)


def _synthetic_tree(depth, num_features, rng):
    """Random complete binary tree of 2**depth leaves in the host Tree
    layout (left/right_child >= 0 internal, ~leaf encoded as -(leaf+1))."""
    from lightgbm_tpu.models.tree import Tree

    n_leaves = 2 ** depth
    m = n_leaves - 1
    left = np.zeros(m, np.int32)
    right = np.zeros(m, np.int32)
    next_internal = [0]
    next_leaf = [0]

    def build(d):
        if d == depth:
            leaf = next_leaf[0]
            next_leaf[0] += 1
            return -(leaf + 1)
        i = next_internal[0]
        next_internal[0] += 1
        left[i] = build(d + 1)
        right[i] = build(d + 1)
        return i

    build(0)
    return Tree(
        num_leaves=n_leaves,
        split_feature=rng.randint(0, num_features, m).astype(np.int32),
        threshold=rng.randn(m).astype(np.float64),
        threshold_bin=None,
        decision_type=np.zeros(m, np.uint8),
        split_gain=np.ones(m, np.float32),
        left_child=left,
        right_child=right,
        internal_value=np.zeros(m, np.float64),
        internal_weight=np.ones(m, np.float64),
        internal_count=np.ones(m, np.int64),
        leaf_value=(rng.randn(n_leaves) * 0.1).astype(np.float64),
        leaf_weight=np.ones(n_leaves, np.float64),
        leaf_count=np.ones(n_leaves, np.int64),
    )


def synthetic_gbdt(num_trees, depth=6, num_features=28, k=1, seed=0):
    """A GBDT with ``num_trees`` random trees — the serving-layer harness
    (packed cache, bucket ladder, one-dispatch multiclass all engage
    exactly as for a trained model)."""
    from lightgbm_tpu.config import Config
    from lightgbm_tpu.models.gbdt import GBDT

    params = ({"objective": "regression", "verbosity": -1} if k == 1 else
              {"objective": "multiclass", "num_class": k, "verbosity": -1})
    g = GBDT(Config.from_dict(params))
    rng = np.random.RandomState(seed)
    g.models = [_synthetic_tree(depth, num_features, rng)
                for _ in range(num_trees)]
    g.iter_ = num_trees // max(k, 1)
    g.feature_names = [f"f{i}" for i in range(num_features)]
    return g


def bench_one(g, X, repeats):
    """(cold_s, rows_per_sec, p50_ms, p99_ms, warm_dispatches) for
    raw-score prediction of X on gbdt g (fresh cache assumed for cold).
    Phases run under timed_section so the artifact rows carry the
    cold-vs-warm section split alongside the embedded snapshot."""
    from lightgbm_tpu.utils.profiling import timed_section
    from lightgbm_tpu.utils.sanitizer import DispatchCounter

    t0 = time.perf_counter()
    with timed_section("predict_cold"):
        first = g.predict(X, raw_score=True)
    cold = time.perf_counter() - t0
    assert np.isfinite(first).all()

    lat = []
    with DispatchCounter() as d:
        g.predict(X, raw_score=True)
    warm_dispatches = d.dispatches
    with timed_section("predict_warm"):
        for _ in range(repeats):
            t0 = time.perf_counter()
            g.predict(X, raw_score=True)
            lat.append(time.perf_counter() - t0)
    lat = np.asarray(lat)
    rows_per_sec = X.shape[0] / float(np.median(lat))
    return (cold, rows_per_sec,
            float(np.percentile(lat, 50) * 1e3),
            float(np.percentile(lat, 99) * 1e3), warm_dispatches)


def main():
    import jax

    sizes = [int(s) for s in os.environ.get(
        "PREDICT_BENCH_SIZES", "1,128,4096,262144").split(",")]
    trees = [int(t) for t in os.environ.get(
        "PREDICT_BENCH_TREES", "100,2000").split(",")]
    depth = int(os.environ.get("PREDICT_BENCH_DEPTH", 6))
    base_repeats = int(os.environ.get("PREDICT_BENCH_REPEATS", 20))
    f = 28
    _STATE["platform"] = jax.devices()[0].platform
    _STATE["depth"] = depth

    rng = np.random.RandomState(0)
    xfull = rng.randn(max(sizes), f).astype(np.float32)

    best = None
    combos = [(t, n, 1) for t in trees for n in sizes]
    # one multiclass shape: the one-dispatch class reduction under load
    combos.append((trees[0] * 5, 4096, 5))
    for t, n, k in combos:
        name = (f"T{t}_N{n}" if k == 1 else f"T{t}_N{n}_k{k}")
        repeats = base_repeats if n < 100_000 else max(base_repeats // 4, 3)
        # floor: per-call cost ~ N*T row-tree steps at >= ~5e6/s (measured
        # CPU; device is far faster so this only ever UNDER-skips there),
        # times (cold + counter + repeats) calls — a workload that cannot
        # finish in the remaining budget is recorded as skipped, not lost
        floor = 5.0 + (n * t / 5e6) * (repeats + 2)
        if _remaining() < floor:
            _STATE["workloads"][name] = {"skipped": "budget"}
            _emit()
            continue
        try:
            g = synthetic_gbdt(t, depth=depth, num_features=f, k=k,
                               seed=t + k)
            cold, rps, p50, p99, wd = bench_one(g, xfull[:n], repeats)
            _STATE["workloads"][name] = {
                "cold_s": round(cold, 3),
                "rows_per_sec": round(rps, 1),
                "p50_ms": round(p50, 3),
                "p99_ms": round(p99, 3),
                "warm_dispatches": wd,
                "repeats": repeats,
            }
            if k == 1 and (best is None or rps > best):
                best = rps
                _STATE["metric"] = f"predict_rows_per_sec_T{t}_N{n}_d{depth}"
                _STATE["value"] = round(rps, 1)
        except Exception as e:  # noqa: BLE001 — artifact robustness
            _STATE["workloads"][name] = {
                "error": f"{type(e).__name__}: {e}"[:300]}
        _emit()

    _STATE["elapsed_s"] = round(time.monotonic() - _T0, 1)
    _emit()
    return 0


if __name__ == "__main__":
    sys.exit(main())
