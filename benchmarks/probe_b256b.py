"""Probe round 2: feature-packed Pallas histogram via lane-CONCATENATED
one-hots (pltpu.repeat from k to k*B columns was rejected by Mosaic in
probe_b256.py; concat of (T, B) blocks at B=256 is lane-aligned).

Per group of K features: K compares (cheap per the round-2 invariances)
feeding ONE (T, NC)x(T, K*B) dot — if the per-dot operand-staging theory
holds, pass cost drops ~K-fold from the 7.7 ms baseline.

Also: NC=128 padded-payload control (staging theory predicts ~unchanged
cost vs NC=48), and int8 payload variant for the quantized path.
"""

import sys
import time

import numpy as np
import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

K_LOOP = 20
FLOOR_MS = 23.4
N, F, B = 999424, 28, 256


def make_cpack(kpack, *, nc=48, row_tile=1024, dtype=jnp.bfloat16,
               int8=False):
    G = (F + kpack - 1) // kpack
    FP = G * kpack  # features padded to a multiple of kpack

    def kernel(bins_ref, pay_ref, out_ref, acc_ref):
        i = pl.program_id(0)

        @pl.when(i == 0)
        def _():
            acc_ref[...] = jnp.zeros_like(acc_ref)

        pay = pay_ref[...]
        if not int8:
            pay = pay.astype(dtype)
        T = pay.shape[0]
        iota = jax.lax.broadcasted_iota(jnp.int32, (T, B), 1)
        bins_i32 = bins_ref[...].astype(jnp.int32)
        odt = jnp.int8 if int8 else dtype
        for g in range(G):
            ohs = [
                (bins_i32[:, g * kpack + j][:, None] == iota).astype(odt)
                for j in range(kpack)
            ]
            oh = jnp.concatenate(ohs, axis=-1)  # (T, kpack*B)
            acc_ref[g] += jax.lax.dot_general(
                pay, oh, (((0,), (0,)), ((), ())),
                preferred_element_type=acc_ref.dtype)  # (NC, kpack*B)

        @pl.when(i == pl.num_programs(0) - 1)
        def _():
            out_ref[...] = acc_ref[...]

    @jax.jit
    def run(bins, pay):
        n = bins.shape[0]
        if FP != F:
            bins = jnp.pad(bins, ((0, 0), (0, FP - F)), constant_values=B - 1)
        acc_dt = jnp.int32 if int8 else jnp.float32
        out = pl.pallas_call(
            kernel,
            grid=(n // row_tile,),
            in_specs=[
                pl.BlockSpec((row_tile, FP), lambda i: (i, 0), memory_space=pltpu.VMEM),
                pl.BlockSpec((row_tile, nc), lambda i: (i, 0), memory_space=pltpu.VMEM),
            ],
            out_specs=pl.BlockSpec((G, nc, kpack * B), lambda i: (0, 0, 0),
                                   memory_space=pltpu.VMEM),
            out_shape=jax.ShapeDtypeStruct((G, nc, kpack * B), acc_dt),
            scratch_shapes=[pltpu.VMEM((G, nc, kpack * B), acc_dt)],
            cost_estimate=pl.CostEstimate(
                flops=2 * n * FP * B * nc,
                bytes_accessed=n * FP * 2 + n * nc * 4,
                transcendentals=0,
            ),
        )(bins, pay)
        # (G, NC, kpack*B) with c = f_local*B + b -> (F, B, NC)
        out = out.reshape(G, nc, kpack, B)
        return jnp.transpose(out, (0, 2, 3, 1)).reshape(FP, B, nc)[:F]

    return run


def main():
    rng = np.random.RandomState(0)
    bins_np = rng.randint(0, B, size=(N, F)).astype(np.int16)
    pay_np = (rng.randn(N, 48) * 0.1).astype(np.float32)

    bins = jnp.asarray(bins_np)
    pay48 = jnp.asarray(pay_np)
    pay128 = jnp.asarray(np.pad(pay_np, ((0, 0), (0, 80))))
    pay_i8 = jnp.asarray(
        np.clip(np.round(pay_np / 0.02), -127, 127).astype(np.int8))

    ref = np.zeros((F, B, 2), np.float64)
    for f in range(F):
        ref[f, :, 0] = np.bincount(bins_np[:, f], weights=pay_np[:, 0], minlength=B)
        ref[f, :, 1] = np.bincount(bins_np[:, f], weights=pay_np[:, 47], minlength=B)
    ref_i8 = np.zeros((F, B), np.int64)
    i8c0 = np.asarray(pay_i8[:, 0], np.int64)
    for f in range(F):
        ref_i8[f] = np.bincount(bins_np[:, f], weights=i8c0, minlength=B)

    cases = {
        "cpack2_t1024": (make_cpack(2), pay48, ref, 48),
        "cpack4_t1024": (make_cpack(4), pay48, ref, 48),
        "cpack4_t2048": (make_cpack(4, row_tile=2048), pay48, ref, 48),
        "cpack7_t1024": (make_cpack(7), pay48, ref, 48),
        "cpack14_t512": (make_cpack(14, row_tile=512), pay48, ref, 48),
        "cpack4_nc128": (make_cpack(4, nc=128), pay128, ref, 128),
        "cpack1_nc128": (make_cpack(1, nc=128), pay128, ref, 128),
        "cpack4_int8": (make_cpack(4, int8=True), pay_i8, ref_i8, 48),
    }
    which = sys.argv[1].split(",") if len(sys.argv) > 1 else list(cases)

    for key in which:
        fn, pay, rr, nc = cases[key]
        t0 = time.perf_counter()
        try:
            out = fn(bins, pay)
            out_h = np.asarray(out)
        except Exception as e:  # noqa: BLE001
            print(f"{key:24s} FAILED: {type(e).__name__}: {str(e)[:160]}", flush=True)
            continue
        dt_c = time.perf_counter() - t0
        if key == "cpack4_int8":
            ok = "OK " if np.abs(out_h[:, :, 0].astype(np.int64) - rr).max() == 0 else "BAD"
        else:
            err0 = np.abs(out_h[:, :, 0] - rr[:, :, 0]).max()
            err1 = np.abs(out_h[:, :, 47] - rr[:, :, 1]).max()
            ok = "OK " if max(err0, err1) < 0.35 else f"BAD err=({err0:.3g},{err1:.3g})"
        print(f"{key:24s} compile+check {dt_c:5.0f}s  {ok}", flush=True)
        if not ok.startswith("OK"):
            continue

        @jax.jit
        def loop(fn=fn, pay=pay):
            def body(i, acc):
                if pay.dtype == jnp.int8:
                    p = pay + (i % 2).astype(jnp.int8)
                else:
                    p = pay * (1.0 + i.astype(jnp.float32) * 1e-9)
                return acc + fn(bins, p).ravel()[0].astype(jnp.float32)
            return jax.lax.fori_loop(0, K_LOOP, body, jnp.float32(0))

        t0 = time.perf_counter()
        o = loop(); np.asarray(o).ravel()[:1]
        dt_c2 = time.perf_counter() - t0
        t0 = time.perf_counter()
        for _ in range(5):
            o = loop()
        np.asarray(o).ravel()[:1]
        total = (time.perf_counter() - t0) / 5 * 1e3
        print(f"{key:24s} per-pass ~{(total - FLOOR_MS)/K_LOOP:6.2f} ms "
              f"(loop-compile {dt_c2:.0f}s)", flush=True)


if __name__ == "__main__":
    main()
