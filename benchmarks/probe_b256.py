"""Round-3 probe: histogram formulations for the max_bin=255 regime.

Goal (VERDICT r2 #1): a full-N pass at B=256, N=1M, F=28, C=48 in <= ~5 ms
(today: Pallas ~10.4 ms, XLA bf16 one-hot einsum ~25 ms).

Working theory from the round-2 invariances (time ~ N*F, invariant to B,
lanes, row tile): the per-(tile, feature) dot is bound by operand staging
(~128 lanes charged regardless of C), so packing K features into ONE dot
should cut the cost ~K-fold.  Variants:

  pallas_fpack{K}   - K features per dot: flat bins (bin*K + f_local),
                      pltpu.repeat to (T, K*B), one compare, one dot.
  pallas_base       - shipped kernel (baseline).
  xla_flatdot       - one_hot (T,F,B) reshaped to (T, F*B), ONE dot per tile.
  xla_hilo          - 4 x masked B=64 einsums (hi 2 bits mask the payload
                      per-feature via onehot_lo * mask_hi product).
  xla_fbatch        - batched dot_general over F with broadcast payload.
  xla_base          - shipped histogram_onehot_multi-style einsum (baseline).

Each variant is correctness-checked against numpy bincount at full N before
timing (layout bugs are the norm here).  Timing = in-jit fori_loop K=20
minus the ~23.4 ms dispatch floor (docs/PERF_NOTES.md methodology).
"""

import functools
import sys
import time

import numpy as np
import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

K_LOOP = 20
FLOOR_MS = 23.4
N, F, B, NC = 999424, 28, 256, 48


# ---------------------------------------------------------------- pallas fpack
def make_fpack(kpack, *, row_tile=1024, dtype=jnp.bfloat16):
    G = F // kpack
    assert F % kpack == 0

    def kernel(flat_ref, pay_ref, out_ref, acc_ref):
        i = pl.program_id(0)

        @pl.when(i == 0)
        def _():
            acc_ref[...] = jnp.zeros_like(acc_ref)

        pay = pay_ref[...].astype(dtype)  # (T, NC)
        T = pay.shape[0]
        iota = jax.lax.broadcasted_iota(jnp.int32, (T, kpack * B), 1)
        flat = flat_ref[...].astype(jnp.int32)  # (T, F), values bin*kpack+f_local
        for g in range(G):
            fb = flat[:, g * kpack:(g + 1) * kpack]  # (T, kpack)
            rep = pltpu.repeat(fb, B, axis=1)  # (T, kpack*B): rep[t,c]=fb[t,c%kpack]
            oh = (rep == iota).astype(dtype)
            acc_ref[g] += jax.lax.dot_general(
                pay, oh, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)  # (NC, kpack*B)

        @pl.when(i == pl.num_programs(0) - 1)
        def _():
            out_ref[...] = acc_ref[...]

    @jax.jit
    def run(bins, pay):
        n = bins.shape[0]
        flat = (bins.astype(jnp.int32) * kpack
                + (jnp.arange(F, dtype=jnp.int32) % kpack)[None, :]).astype(jnp.int16)
        out = pl.pallas_call(
            kernel,
            grid=(n // row_tile,),
            in_specs=[
                pl.BlockSpec((row_tile, F), lambda i: (i, 0), memory_space=pltpu.VMEM),
                pl.BlockSpec((row_tile, NC), lambda i: (i, 0), memory_space=pltpu.VMEM),
            ],
            out_specs=pl.BlockSpec((G, NC, kpack * B), lambda i: (0, 0, 0),
                                   memory_space=pltpu.VMEM),
            out_shape=jax.ShapeDtypeStruct((G, NC, kpack * B), jnp.float32),
            scratch_shapes=[pltpu.VMEM((G, NC, kpack * B), jnp.float32)],
            cost_estimate=pl.CostEstimate(
                flops=2 * n * F * B * NC,
                bytes_accessed=n * F * 2 + n * NC * 4,
                transcendentals=0,
            ),
        )(flat, pay)
        # (G, NC, kpack*B) -> (F, B, NC): column c = b*kpack + f_local
        out = out.reshape(G, NC, B, kpack)
        return jnp.transpose(out, (0, 3, 2, 1)).reshape(F, B, NC)

    return run


# ---------------------------------------------------------------- pallas base
def make_pallas_base(*, row_tile=1024, dtype=jnp.bfloat16):
    def kernel(bins_ref, pay_ref, out_ref, acc_ref):
        i = pl.program_id(0)

        @pl.when(i == 0)
        def _():
            acc_ref[...] = jnp.zeros_like(acc_ref)

        pay = pay_ref[...].astype(dtype)
        T = pay.shape[0]
        iota = jax.lax.broadcasted_iota(jnp.int32, (T, B), 1)
        bins_i32 = bins_ref[...].astype(jnp.int32)
        for f in range(F):
            oh = (bins_i32[:, f][:, None] == iota).astype(dtype)
            acc_ref[f] += jax.lax.dot_general(
                pay, oh, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)

        @pl.when(i == pl.num_programs(0) - 1)
        def _():
            out_ref[...] = acc_ref[...]

    @jax.jit
    def run(bins, pay):
        n = bins.shape[0]
        out = pl.pallas_call(
            kernel,
            grid=(n // row_tile,),
            in_specs=[
                pl.BlockSpec((row_tile, F), lambda i: (i, 0), memory_space=pltpu.VMEM),
                pl.BlockSpec((row_tile, NC), lambda i: (i, 0), memory_space=pltpu.VMEM),
            ],
            out_specs=pl.BlockSpec((F, NC, B), lambda i: (0, 0, 0),
                                   memory_space=pltpu.VMEM),
            out_shape=jax.ShapeDtypeStruct((F, NC, B), jnp.float32),
            scratch_shapes=[pltpu.VMEM((F, NC, B), jnp.float32)],
        )(bins, pay)
        return jnp.transpose(out, (0, 2, 1))  # (F, B, NC)

    return run


# ------------------------------------------------------------------ xla forms
def make_xla_base(*, row_tile=8192):
    @jax.jit
    def run(bins, pay):
        n = bins.shape[0]
        nt = n // row_tile
        bins_t = bins.reshape(nt, row_tile, F)
        pay_t = pay.astype(jnp.bfloat16).reshape(nt, row_tile, NC)

        def body(acc, inp):
            b_tile, p_tile = inp
            onehot = jax.nn.one_hot(b_tile.T, B, dtype=jnp.bfloat16)  # (F, T, B)
            hh = jnp.einsum("ftb,tc->fbc", onehot, p_tile,
                            preferred_element_type=jnp.float32)
            return acc + hh, None

        init = jnp.zeros((F, B, NC), jnp.float32)
        hist, _ = jax.lax.scan(body, init, (bins_t, pay_t))
        return hist

    return run


def make_xla_flatdot(*, row_tile=1024):
    @jax.jit
    def run(bins, pay):
        n = bins.shape[0]
        nt = n // row_tile
        bins_t = bins.reshape(nt, row_tile, F)
        pay_t = pay.astype(jnp.bfloat16).reshape(nt, row_tile, NC)

        def body(acc, inp):
            b_tile, p_tile = inp
            oh = jax.nn.one_hot(b_tile, B, dtype=jnp.bfloat16)  # (T, F, B)
            oh = oh.reshape(row_tile, F * B)
            hh = jax.lax.dot_general(
                oh, p_tile, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)  # (F*B, NC)
            return acc + hh, None

        init = jnp.zeros((F * B, NC), jnp.float32)
        hist, _ = jax.lax.scan(body, init, (bins_t, pay_t))
        return hist.reshape(F, B, NC)

    return run


def make_xla_hilo(*, row_tile=8192):
    BLO = 64

    @jax.jit
    def run(bins, pay):
        n = bins.shape[0]
        nt = n // row_tile
        bins_t = bins.reshape(nt, row_tile, F)
        pay_t = pay.astype(jnp.bfloat16).reshape(nt, row_tile, NC)

        def body(acc, inp):
            b_tile, p_tile = inp
            lo = (b_tile & (BLO - 1))
            hi = (b_tile >> 6)  # (T, F) in 0..3
            oh_lo = jax.nn.one_hot(lo.T, BLO, dtype=jnp.bfloat16)  # (F, T, 64)
            outs = []
            for v in range(B // BLO):
                mask = (hi.T == v).astype(jnp.bfloat16)  # (F, T)
                oh = oh_lo * mask[:, :, None]
                outs.append(jnp.einsum("ftb,tc->fbc", oh, p_tile,
                                       preferred_element_type=jnp.float32))
            hh = jnp.concatenate(outs, axis=1)  # (F, 256, NC)
            return acc + hh, None

        init = jnp.zeros((F, B, NC), jnp.float32)
        hist, _ = jax.lax.scan(body, init, (bins_t, pay_t))
        return hist

    return run


def make_xla_fbatch(*, row_tile=2048):
    @jax.jit
    def run(bins, pay):
        n = bins.shape[0]
        nt = n // row_tile
        bins_t = bins.reshape(nt, row_tile, F)
        pay_t = pay.astype(jnp.bfloat16).reshape(nt, row_tile, NC)

        def body(acc, inp):
            b_tile, p_tile = inp
            oh = jax.nn.one_hot(b_tile.T, B, dtype=jnp.bfloat16)  # (F, T, B)
            pb = jnp.broadcast_to(p_tile[None], (F, row_tile, NC))
            hh = jax.lax.dot_general(
                oh, pb, (((1,), (1,)), ((0,), (0,))),
                preferred_element_type=jnp.float32)  # (F, B, NC)
            return acc + hh, None

        init = jnp.zeros((F, B, NC), jnp.float32)
        hist, _ = jax.lax.scan(body, init, (bins_t, pay_t))
        return hist

    return run


# ---------------------------------------------------------------------- main
def main():
    rng = np.random.RandomState(0)
    bins_np = rng.randint(0, B, size=(N, F)).astype(np.int16)
    pay_np = (rng.randn(N, NC) * 0.1).astype(np.float32)

    bins = jnp.asarray(bins_np)
    pay = jnp.asarray(pay_np)

    # numpy reference for correctness (channel 0 and NC-1 suffice)
    ref = np.zeros((F, B, 2), np.float64)
    for f in range(F):
        ref[f, :, 0] = np.bincount(bins_np[:, f], weights=pay_np[:, 0], minlength=B)
        ref[f, :, 1] = np.bincount(bins_np[:, f], weights=pay_np[:, NC - 1], minlength=B)

    cases = {
        "pallas_base_t1024": make_pallas_base(row_tile=1024),
        "pallas_fpack4_t1024": make_fpack(4, row_tile=1024),
        "pallas_fpack2_t1024": make_fpack(2, row_tile=1024),
        "pallas_fpack7_t512": make_fpack(7, row_tile=512),
        "pallas_fpack4_t2048": make_fpack(4, row_tile=2048),
        "xla_base_t8192": make_xla_base(row_tile=8192),
        "xla_flatdot_t1024": make_xla_flatdot(row_tile=1024),
        "xla_flatdot_t4096": make_xla_flatdot(row_tile=4096),
        "xla_hilo_t8192": make_xla_hilo(row_tile=8192),
        "xla_fbatch_t2048": make_xla_fbatch(row_tile=2048),
    }
    which = sys.argv[1].split(",") if len(sys.argv) > 1 else list(cases)

    for key in which:
        fn = cases[key]
        t0 = time.perf_counter()
        try:
            out = fn(bins, pay)
            out_h = np.asarray(out)
        except Exception as e:  # noqa: BLE001 - probe must survive Mosaic rejects
            print(f"{key:24s} FAILED: {type(e).__name__}: {str(e)[:200]}", flush=True)
            continue
        dt_c = time.perf_counter() - t0
        err0 = np.abs(out_h[:, :, 0] - ref[:, :, 0]).max()
        err1 = np.abs(out_h[:, :, NC - 1] - ref[:, :, 1]).max()
        ok = "OK " if max(err0, err1) < 0.35 else f"BAD err=({err0:.3g},{err1:.3g})"
        print(f"{key:24s} compile+check {dt_c:5.0f}s  {ok}", flush=True)
        if ok != "OK ":
            continue

        @jax.jit
        def loop(fn=fn):
            def body(i, acc):
                p = pay * (1.0 + i.astype(jnp.float32) * 1e-9)
                return acc + fn(bins, p).ravel()[0]
            return jax.lax.fori_loop(0, K_LOOP, body, jnp.float32(0))

        t0 = time.perf_counter()
        o = loop(); np.asarray(o).ravel()[:1]
        dt_c2 = time.perf_counter() - t0
        t0 = time.perf_counter()
        for _ in range(5):
            o = loop()
        np.asarray(o).ravel()[:1]
        total = (time.perf_counter() - t0) / 5 * 1e3
        print(f"{key:24s} per-pass ~{(total - FLOOR_MS)/K_LOOP:6.2f} ms "
              f"(loop-compile {dt_c2:.0f}s)", flush=True)


if __name__ == "__main__":
    main()
