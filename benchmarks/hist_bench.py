"""Microbenchmark of histogram strategies on the current backend.

Usage: python benchmarks/hist_bench.py [N] [F] [B]
Measures ms/histogram for each strategy and checks correctness vs a numpy
reference.  Drives the measured strategy table in ops/histogram.py.
"""

import sys
import time

import numpy as np

import jax
import jax.numpy as jnp


def timeit(fn, *args, reps=10):
    # NOTE: on the axon tunnel backend block_until_ready does NOT wait for
    # execution; a host transfer does.  Dispatch `reps` times back-to-back
    # (they serialize on device) and sync once — the ~100 ms tunnel
    # round-trip amortizes over reps.
    out = fn(*args)
    _ = np.asarray(out).ravel()[0]
    t0 = time.perf_counter()
    for _i in range(reps):
        out = fn(*args)
    host = np.asarray(out)
    dt = (time.perf_counter() - t0) / reps * 1e3
    return dt, host


def main():
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 1_000_000
    f = int(sys.argv[2]) if len(sys.argv) > 2 else 28
    b = int(sys.argv[3]) if len(sys.argv) > 3 else 256

    rng = np.random.RandomState(0)
    bins = rng.randint(0, b, size=(n, f)).astype(np.int32)
    grad = rng.randn(n).astype(np.float32)
    hess = rng.rand(n).astype(np.float32)
    mask = (rng.rand(n) < 0.7)

    # numpy reference (channel-first (3, F, B) — package layout)
    ref = np.zeros((3, f, b), np.float64)
    m = mask.astype(np.float64)
    for j in range(f):
        ref[0, j] = np.bincount(bins[:, j], weights=grad * m, minlength=b)
        ref[1, j] = np.bincount(bins[:, j], weights=hess * m, minlength=b)
        ref[2, j] = np.bincount(bins[:, j], weights=m, minlength=b)

    db = jnp.asarray(bins)
    dg = jnp.asarray(grad)
    dh = jnp.asarray(hess)
    dm = jnp.asarray(mask)

    from lightgbm_tpu.ops.histogram import histogram_scatter, histogram_onehot_matmul
    from lightgbm_tpu.ops import hist_pallas as hp

    results = {}

    def check(name, out, tol):
        out = np.asarray(out, np.float64)
        err = np.max(np.abs(out - ref) / (np.abs(ref) + 1.0))
        ok = err < tol
        print(f"  {name}: rel_err={err:.2e} {'OK' if ok else 'FAIL'}")
        return ok

    variants = sys.argv[4].split(",") if len(sys.argv) > 4 else [
        "onehot_xla", "direct_f32_512", "direct_bf16_512", "q8_512", "multi16_512",
    ]

    refq = None
    for name in variants:
        try:
            if name == "scatter":
                fn = jax.jit(lambda: histogram_scatter(db, dg, dh, dm, b))
                ms, out = timeit(fn, reps=3)
                results[name] = ms
                check(name, out, 1e-4)
            elif name == "onehot_xla":
                fn = jax.jit(lambda: histogram_onehot_matmul(db, dg, dh, dm, b))
                ms, out = timeit(fn, reps=3)
                results[name] = ms
                check(name, out, 1e-4)
            elif name.startswith("q8_"):
                rt = int(name.split("_")[1])
                gq = jnp.asarray(np.clip(np.round(grad * 15), -31, 31).astype(np.int8))
                hq = jnp.asarray(np.clip(np.round(hess * 31), 0, 31).astype(np.int8))
                fn = jax.jit(
                    lambda r=rt: hp.histogram_pallas_quantized(
                        db, gq, hq, dm, b, row_tile=r
                    )
                )
                ms, out = timeit(fn)
                results[name] = ms
                if refq is None:
                    refq = np.zeros((3, f, b), np.int64)
                    mq = mask.astype(np.int64)
                    gqn = np.asarray(gq, np.int64)
                    hqn = np.asarray(hq, np.int64)
                    for j in range(f):
                        refq[0, j] = np.bincount(bins[:, j], weights=gqn * mq, minlength=b)
                        refq[1, j] = np.bincount(bins[:, j], weights=hqn * mq, minlength=b)
                        refq[2, j] = np.bincount(bins[:, j], weights=mq, minlength=b)
                exact = np.array_equal(np.asarray(out, np.int64), refq)
                print(f"  {name}: exact={'OK' if exact else 'FAIL'}")
            elif name.startswith("multi"):
                # multi-leaf pass: slot 0 = the mask, other slots empty; slot
                # 0's result must equal the single-leaf histogram
                tile = int(name[5:].split("_")[0])
                rt = int(name.split("_")[1])
                slot = jnp.where(dm, 0, -1).astype(jnp.int32)
                fn = jax.jit(
                    lambda t=tile, r=rt: hp.histogram_pallas_multi(
                        db, dg, dh, slot >= 0, jnp.maximum(slot, 0), 0, t, b,
                        precision="f32", row_tile=r,
                    )[0]
                )
                ms, out = timeit(fn)
                results[name] = ms
                check(name, out, 1e-4)
            else:
                _, prec, rt = name.split("_")
                fn = jax.jit(
                    lambda p=prec, r=int(rt): hp.histogram_pallas(
                        db, dg, dh, dm, b, precision=p, row_tile=r
                    )
                )
                ms, out = timeit(fn)
                results[f"pallas_{name}"] = ms
                check(name, out, 5e-3 if prec == "bf16" else 1e-4)
        except Exception as e:
            print(f"  {name}: ERROR {type(e).__name__}: {str(e)[:300]}", flush=True)

    print(f"\nN={n} F={f} B={b} on {jax.devices()[0].platform}")
    for k, v in sorted(results.items(), key=lambda kv: kv[1]):
        print(f"  {k:32s} {v:8.2f} ms")


if __name__ == "__main__":
    main()
