"""Round-5 probe: locate the windowed grower's per-round FIXED cost.

r5 measured (WPROF, Epsilon 400k x 2000 x 256 x 255 leaves, int8):
admit+sync ~0.13 s/round, pass ~0.19 s at W=32768 (where the window work
itself is ~30 ms) — so ~0.15 s/round of the pass is fixed.  The
channel-first layout rework did NOT move it, so the padded-copy theory is
dead; suspects now are (a) undonated 1.5 GB hist-state buffers forcing
alloc+copy per jit call, (b) the full-state scatter/subtract chain, (c)
dispatch/arg plumbing.  Each probe isolates one.

Timing: host pull of a tiny slice (block_until_ready lies through the
tunnel; PERF_NOTES r4).
"""

import functools
import sys
import time

import numpy as np

sys.path.insert(0, "/root/repo")

import jax
import jax.numpy as jnp

L, F, B = 255, 2000, 256
REPS = 10


def timed(name, fn, *args):
    out = fn(*args)  # compile
    _ = np.asarray(jax.tree.leaves(out)[0].ravel()[:4])
    t0 = time.perf_counter()
    for _ in range(REPS):
        out = fn(*args)
    _ = np.asarray(jax.tree.leaves(out)[0].ravel()[:4])
    dt = (time.perf_counter() - t0) / REPS
    print(f"{name:44s} {dt*1e3:8.1f} ms/call", flush=True)
    return out


def timed_donated(name, fn, first, *rest):
    """fn donates arg 0: thread the output back as the next input."""
    out = fn(first, *rest)  # compile (donates `first`)
    _ = np.asarray(out.ravel()[:4])
    t0 = time.perf_counter()
    for _ in range(REPS):
        out = fn(out, *rest)
    _ = np.asarray(out.ravel()[:4])
    dt = (time.perf_counter() - t0) / REPS
    print(f"{name:44s} {dt*1e3:8.1f} ms/call", flush=True)


def main():
    hist = jnp.zeros((L, 3, F, B), jnp.float32)
    fresh = jnp.ones((16, 3, F, B), jnp.float32)
    small_pos = jnp.arange(16, dtype=jnp.int32) * 3
    idx = jnp.arange(L, dtype=jnp.int32)
    sib = jnp.clip(idx + 1, 0, L - 1)
    is_big = (idx % 2) == 0

    # (a) pure passthrough: cost of shipping the state through a jit
    @jax.jit
    def passthrough(h):
        return h + 0.0

    timed("state passthrough (copy 1.5 GB)", passthrough, hist)

    @functools.partial(jax.jit, donate_argnums=(0,))
    def passthrough_don(h):
        return h + 0.0

    timed_donated("state passthrough DONATED", passthrough_don,
                  jnp.zeros_like(hist))

    # (b) the pass's hist-state op chain, undonated vs donated
    def chain(h, fr):
        h = h.at[small_pos].set(fr, mode="drop")
        big_sub = h[idx] - h[sib]
        return jnp.where(is_big[:, None, None, None], big_sub, h)

    timed("scatter+subtract chain", jax.jit(chain), hist, fresh)
    timed_donated("scatter+subtract chain DONATED",
                  functools.partial(jax.jit, donate_argnums=(0,))(chain),
                  jnp.zeros_like(hist), fresh)

    # (c) admit's parent snapshot
    def snapshot(h):
        return h.at[jnp.flip(small_pos)].set(h[:16], mode="drop")

    timed("parent snapshot scatter", jax.jit(snapshot), hist)
    timed_donated("parent snapshot DONATED",
                  functools.partial(jax.jit, donate_argnums=(0,))(snapshot),
                  jnp.zeros_like(hist))

    # (d) the fresh-leaf gather + batched search input slice
    fr_idx = jnp.arange(40, dtype=jnp.int32)

    @jax.jit
    def gather40(h):
        return h[fr_idx] * 2.0

    timed("hist[fr_idx] 40-slot gather", gather40, hist)


if __name__ == "__main__":
    main()
