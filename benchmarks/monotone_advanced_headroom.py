"""Measured bound on what monotone_constraints_method='advanced' could add.

Advanced (reference: monotone_constraints.hpp AdvancedLeafConstraints)
still enforces monotonicity, so its training fit is bounded above by the
UNCONSTRAINED model's: gap(advanced, intermediate) <= gap(none,
intermediate).  This script measures that bound on three fixtures whose
generative functions are genuinely monotone in the constrained features
(a mis-signed constraint would inflate the gap artificially).

Round-5 measured results (CPU, 6000 rows, 60 rounds, lr 0.1, 31 leaves):

| fixture          | mse none | basic   | intermediate | advanced headroom |
|------------------|----------|---------|--------------|-------------------|
| steps            | 0.05108  | 0.06767 | 0.06716      | <= 0.01608        |
| smooth-interact  | 0.06434  | 0.04723 | 0.04278      | <= 0 (negative)   |
| all-mono         | 0.05048  | 0.08669 | 0.08023      | <= 0.02975        |

On smooth-interact the constraint acts as a regularizer and intermediate
BEATS unconstrained — advanced cannot help there at all.  See
PARITY.md's monotone section for the descope argument this backs.
"""

import numpy as np

import lightgbm_tpu as lgb


def gap_experiment(name, X, y, mono, rounds=60, leaves=31):
    res = {}
    for method, extra in (
            ("none", {}),
            ("basic", {"monotone_constraints": mono,
                       "monotone_constraints_method": "basic"}),
            ("intermediate", {"monotone_constraints": mono,
                              "monotone_constraints_method": "intermediate"})):
        p = {"objective": "regression", "num_leaves": leaves,
             "verbosity": -1, "learning_rate": 0.1, "min_data_in_leaf": 10,
             **extra}
        bst = lgb.train(p, lgb.Dataset(X, label=y), rounds)
        res[method] = float(np.mean((bst.predict(X) - y) ** 2))
    un, ba, it = res["none"], res["basic"], res["intermediate"]
    print(f"{name}: mse none={un:.5f} basic={ba:.5f} inter={it:.5f} | "
          f"advanced headroom <= {max(it - un, 0.0):.5f}")


def main():
    rng = np.random.RandomState(0)
    n = 6000
    x = rng.randn(n, 3)
    y = (np.where(x[:, 0] > 0, 10.0, 0.0) + np.where(x[:, 1] > 0, 8.0, 0.0)
         + 0.5 * x[:, 2] + 0.05 * rng.randn(n))
    gap_experiment("steps", x, y, [1, 1, 0])

    x = rng.randn(n, 4)
    y = (np.exp(0.5 * x[:, 0]) + np.log1p(np.exp(x[:, 1]))
         + x[:, 2] * x[:, 3] + 0.1 * rng.randn(n))
    gap_experiment("smooth-interact", x, y, [1, 1, 0, 0])

    x = rng.randn(n, 4)
    y = (x[:, 0] ** 3 / 5 + np.tanh(x[:, 1]) + 0.5 * x[:, 2]
         + np.sqrt(np.abs(x[:, 3])) * np.sign(x[:, 3])
         + 0.1 * rng.randn(n))
    gap_experiment("all-mono", x, y, [1, 1, 1, 1])


if __name__ == "__main__":
    main()
