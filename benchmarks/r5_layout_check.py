"""Round-5 probe: measure the channel-first hist-state rework end-to-end.

Compares against the round-4 ledger (docs/PERF_NOTES.md):
  narrow 1M x 28, 31 leaves:  63-bin 35.1 it/s | 255-bin 11.0-11.8 it/s
  epsilon 400k x 2000, 255 leaves, 255-bin int8: 5.06 s/iter (full-pass)
                                  windowed int8: ~8.2 s/iter profiled

Timing uses a host pull of a score slice (NOT block_until_ready — it
returns early through the axon tunnel; PERF_NOTES round 4).

Usage: python benchmarks/r5_layout_check.py [narrow|epsilon|windowed]
"""

import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

CACHE_DIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), ".bench_cache")


def _time_iters(bst, iters):
    import lightgbm_tpu  # noqa: F401

    t0 = time.perf_counter()
    for _ in range(iters):
        bst.update()
    _ = np.asarray(bst._gbdt._score[:8])  # force pipeline drain
    return (time.perf_counter() - t0) / iters


def narrow():
    import lightgbm_tpu as lgb

    n, f = 1_000_000, 28
    rng = np.random.RandomState(0)
    X = rng.randn(n, f).astype(np.float32)
    w = rng.randn(f) / np.sqrt(f)
    y = ((X @ w + 0.3 * rng.randn(n)) > 0).astype(np.float64)
    for mb in (63, 255):
        params = {"objective": "binary", "num_leaves": 31, "max_bin": mb,
                  "verbosity": -1, "min_data_in_leaf": 20}
        ds = lgb.Dataset(X, label=y)
        t0 = time.perf_counter()
        bst = lgb.Booster(params=params, train_set=ds)
        bst.update()
        _ = np.asarray(bst._gbdt._score[:8])
        warm = time.perf_counter() - t0
        spi = _time_iters(bst, 30)
        print(f"narrow {mb}bins: {1.0/spi:.2f} it/s ({spi*1e3:.1f} ms/iter)"
              f" warmup {warm:.0f}s", flush=True)


def _epsilon_dataset(lgb, mb):
    os.makedirs(CACHE_DIR, exist_ok=True)
    cache = os.path.join(CACHE_DIR, f"epsilon_{mb}.bin")
    params = {"max_bin": mb}
    if not os.path.exists(cache):
        rng = np.random.RandomState(1)
        ne, fe = 400_000, 2000
        Xe = rng.randn(ne, fe).astype(np.float32)
        ye = ((Xe[:, :64] @ rng.randn(64) + rng.randn(ne)) > 0).astype(
            np.float64)
        t0 = time.perf_counter()
        ds = lgb.Dataset(Xe, label=ye, params=params)
        ds.construct()
        print(f"epsilon binning took {time.perf_counter()-t0:.0f}s",
              flush=True)
        ds.save_binary(cache)
        return ds
    t0 = time.perf_counter()
    ds = lgb.Dataset(cache, params=params)
    ds.construct()
    print(f"epsilon cache reload took {time.perf_counter()-t0:.0f}s",
          flush=True)
    return ds


def epsilon(windowed=False):
    import lightgbm_tpu as lgb

    ds = _epsilon_dataset(lgb, 255)
    params = {"objective": "binary", "num_leaves": 255, "max_bin": 255,
              "verbosity": -1, "min_data_in_leaf": 20}
    if windowed:
        params["windowed_growth"] = True
    t0 = time.perf_counter()
    bst = lgb.Booster(params=params, train_set=ds)
    bst.update()
    _ = np.asarray(bst._gbdt._score[:8])
    warm = time.perf_counter() - t0
    spi = _time_iters(bst, 5)
    tag = "windowed" if windowed else "fullpass"
    print(f"epsilon 255bins {tag}: {spi:.2f} s/iter warmup {warm:.0f}s",
          flush=True)


if __name__ == "__main__":
    which = sys.argv[1] if len(sys.argv) > 1 else "narrow"
    if which == "narrow":
        narrow()
    elif which == "epsilon":
        epsilon(False)
    elif which == "windowed":
        epsilon(True)
