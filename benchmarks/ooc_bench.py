"""Out-of-core / partition benchmark: the round-12 data-path levers.

Three levers, each emitting BENCH-style rows (bench.py contract — a full
JSON snapshot line printed + flushed after EVERY completed workload, so a
driver timeout keeps everything measured so far):

* ``stream_ingest_<chunk>`` — rows/sec assembling the device matrix from
  a ``save_binary`` cache through the chunked reader + one-deep upload
  prefetch (io/stream.py), per chunk size.  The resident-regime ingest
  cost: how fast a cache becomes a trainable device matrix.
* ``spill_train_<chunk>`` — spill-regime training throughput
  (ops/treegrow_ooc.py): streamed rows/sec across all histogram passes
  of a small boosting run, per chunk size, with bitwise parity vs
  in-memory training asserted in the artifact path itself.
* ``partition_move`` — move-phase timing of the segment partition at
  segment fractions {1.0, 0.25, 0.03}: the XLA permutation is O(N) flat
  across fractions; the HBM-resident DMA kernel's traffic is segment-
  proportional, so ON CHIP its move phase should FALL with the fraction
  — the written-proof-shaped claim this artifact is queued to verify at
  the next chip session (off-chip the kernel runs in interpret mode at a
  reduced N for semantics, not speed; ``pallas_interpret`` rows are
  marked so nobody reads them as device numbers).
* ``megakernel_move`` (round 16) — the same fraction sweep through the
  ROUND MEGAKERNEL (ops/round_pallas.py, partition + one-sweep window
  histogram in one Pallas call) vs the three-pass XLA composite
  (permutation + window gather + scatter histogram), with in-artifact
  BITWISE parity of the produced histograms.  Off-chip rows are
  interpret-mode (semantics + the parity proof, not speed); on chip the
  expected story is the J7-pinned 3->1 bin-sweep cut.

Env knobs: OOC_BENCH_ROWS (default 120k), OOC_BENCH_FEATURES (default
16), OOC_BENCH_CHUNKS (csv, default "4096,16384,65536"),
OOC_BENCH_BUDGET_S (default 300), OOC_BENCH_OUT (also write the final
snapshot to a file, e.g. BENCH_ooc_r01.json).
"""

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

_T0 = time.monotonic()
_BUDGET_S = float(os.environ.get("OOC_BENCH_BUDGET_S", 300))

_STATE = {
    "metric": "ooc_stream_rows_per_sec",
    "value": None,
    "unit": "rows/sec",
    "vs_baseline": None,  # no reference out-of-core anchor (BASELINE.md)
    "workloads": {},
}


def _emit():
    try:
        from lightgbm_tpu.obs import metrics as _obs

        _STATE["metrics"] = _obs.snapshot()
    except Exception:  # noqa: BLE001 — artifact robustness first
        pass
    line = json.dumps(_STATE, default=str) + "\n"
    sys.stdout.write(line)
    sys.stdout.flush()
    out = os.environ.get("OOC_BENCH_OUT")
    if out:
        with open(out, "w") as fh:
            fh.write(line)


def _remaining():
    return _BUDGET_S - (time.monotonic() - _T0)


def _guarded(name, fn, budget_floor=10.0):
    if _remaining() < budget_floor:
        _STATE["workloads"][name] = {"skipped": "budget"}
        _emit()
        return
    try:
        fn()
    except Exception as e:  # noqa: BLE001 — artifact robustness
        _STATE["workloads"][name] = {"error": f"{type(e).__name__}: {e}"[:300]}
    _emit()


def _make_cache(n, f, path):
    """Bin a synthetic dataset once and save_binary it — every lever
    streams from this cache, like a real out-of-core run would."""
    import lightgbm_tpu as lgb

    rng = np.random.RandomState(0)
    X = rng.randn(n, f).astype(np.float32)
    y = ((X[:, 0] + 0.5 * X[:, 1] + 0.3 * rng.randn(n)) > 0).astype(
        np.float64)
    ds = lgb.Dataset(X, label=y, params={"max_bin": 255, "verbosity": -1})
    ds.construct()
    ds.save_binary(path)
    return X, y


def bench_stream_ingest(cache, n, chunks):
    """Resident-regime ingest: cache -> assembled device matrix."""
    import jax
    import lightgbm_tpu as lgb

    for chunk in chunks:
        name = f"stream_ingest_{chunk}"
        if _remaining() < 10:
            _STATE["workloads"][name] = {"skipped": "budget"}
            continue
        t0 = time.perf_counter()
        ds = lgb.Dataset(cache, params={
            "max_bin": 255, "verbosity": -1, "out_of_core": True,
            "out_of_core_chunk_rows": chunk})
        ds.construct()
        jax.block_until_ready(ds.bins_device)
        dt = time.perf_counter() - t0
        _STATE["workloads"][name] = {
            "rows_per_sec": round(n / dt, 1), "ingest_s": round(dt, 3),
            "chunk_rows": chunk}
        if _STATE["value"] is None or n / dt > _STATE["value"]:
            _STATE["value"] = round(n / dt, 1)
        _emit()


def bench_spill_train(cache, X, y, n, chunks, rounds=2):
    """Spill-regime chunked-histogram training: streamed rows/sec across
    all histogram passes, parity-asserted against in-memory training."""
    import lightgbm_tpu as lgb
    from lightgbm_tpu.obs import metrics as _obs

    params = {"objective": "binary", "num_leaves": 15, "max_bin": 255,
              "verbosity": -1, "feature_pre_filter": False,
              "min_data_in_leaf": 20}

    def train(ds):
        bst = lgb.Booster(params=dict(params, **(
            {"out_of_core": True, "max_rows_in_hbm": 1,
             "out_of_core_chunk_rows": ds_chunk}
            if ds is not mem_ds else {})), train_set=ds)
        for _ in range(rounds):
            bst.update()
        return bst.model_to_string()

    mem_ds = lgb.Dataset(X, label=y, params=dict(params))
    ds_chunk = 0
    want = train(mem_ds)

    for chunk in chunks:
        name = f"spill_train_{chunk}"
        if _remaining() < 20:
            _STATE["workloads"][name] = {"skipped": "budget"}
            continue
        ds_chunk = chunk
        ds = lgb.Dataset(cache, params=dict(
            params, out_of_core=True, max_rows_in_hbm=1,
            out_of_core_chunk_rows=chunk))
        passes0 = _obs.counter("train_ooc_passes_total").value
        t0 = time.perf_counter()
        got = train(ds)
        dt = time.perf_counter() - t0
        passes = _obs.counter("train_ooc_passes_total").value - passes0
        _STATE["workloads"][name] = {
            "streamed_rows_per_sec": round(n * passes / dt, 1),
            "train_s": round(dt, 3), "hist_passes": passes,
            "chunk_rows": chunk, "bitwise_parity": got == want}
        if got != want:
            raise AssertionError(
                f"spill training diverged from in-memory at chunk={chunk}")
        _emit()


def bench_partition_move(n_xla, platform):
    """Move-phase timing at segment fractions: the O(N)-vs-segment-
    proportional claim in one row.  On TPU the real DMA kernel runs; off
    chip the interpret-mode kernel runs at a reduced N (semantics only)."""
    import jax
    import jax.numpy as jnp

    from lightgbm_tpu.ops.partition import partition_rows

    on_tpu = platform == "tpu"
    n_pallas = n_xla if on_tpu else min(n_xla, 20_000)
    entry = {"platform": platform, "n_xla": n_xla, "n_pallas": n_pallas,
             "pallas_mode": "device" if on_tpu else "interpret",
             "fractions": {}}
    rng = np.random.RandomState(9)
    for frac in (1.0, 0.25, 0.03):
        row = {}
        for tag, n, kw in (("xla", n_xla, dict(use_pallas=False)),
                           ("pallas", n_pallas,
                            dict(use_pallas=on_tpu, interpret=not on_tpu))):
            seg_rows = max(int(n * frac), 8)
            order = jnp.asarray(rng.permutation(n).astype(np.int32))
            seg_id = np.full(n, -1, np.int32)
            seg_id[:seg_rows] = 0
            args = (order, jnp.asarray(seg_id),
                    jnp.asarray([0], np.int32),
                    jnp.asarray([seg_rows], np.int32),
                    jnp.asarray(rng.rand(n) < 0.5))
            out = partition_rows(*args, **kw)  # warm the executable
            jax.block_until_ready(out)
            reps = 3 if (tag == "pallas" and not on_tpu) else 10
            t0 = time.perf_counter()
            for _ in range(reps):
                out = partition_rows(*args, **kw)
            jax.block_until_ready(out)
            row[f"{tag}_ms"] = round(
                (time.perf_counter() - t0) / reps * 1e3, 3)
        entry["fractions"][str(frac)] = row
        _STATE["workloads"]["partition_move"] = entry
        _emit()


def bench_megakernel_move(n_xla, platform, f=16, bins=32):
    """Round-16 lever: one fused-round data phase (partition + window
    histogram) through the megakernel vs the three-pass XLA composite,
    at the same segment fractions as ``partition_move``.  The histogram
    the kernel accumulates must be BITWISE the composite's (asserted in
    the artifact path) — same contract tests/test_megakernel.py pins at
    the grower level."""
    import jax
    import jax.numpy as jnp

    from lightgbm_tpu.ops.histogram import histogram
    from lightgbm_tpu.ops.partition import stable_partition_ranges
    from lightgbm_tpu.ops.round_pallas import round_megakernel

    on_tpu = platform == "tpu"
    n = n_xla if on_tpu else min(n_xla, 20_000)
    entry = {"platform": platform, "rows": n, "features": f,
             "pallas_mode": "device" if on_tpu else "interpret",
             "fractions": {}}
    rng = np.random.RandomState(16)
    bins_t = jnp.asarray(rng.randint(0, bins, (f, n)), jnp.int16)
    grad = jnp.asarray(rng.randn(n), jnp.float32)
    hess = jnp.asarray(rng.rand(n) + 0.5, jnp.float32)
    mask = jnp.ones((n,), bool)
    tile = 2  # slot 0 live, slot 1 dead — one segment per round phase

    def make_three_pass(seg_rows):
        @jax.jit
        def three_pass(order, seg_id, seg_start, seg_len, go):
            new_order, lefts = stable_partition_ranges(
                order, seg_id, seg_start, seg_len, go)
            rows = new_order[:seg_rows]  # the split segment (static size)
            sub = bins_t[:, rows].T      # the materialized window copy
            h = histogram(sub, grad[rows], hess[rows],
                          (jnp.arange(seg_rows) < lefts[0]).astype(
                              jnp.float32), bins, strategy="scatter")
            return new_order, h

        return three_pass

    for frac in (1.0, 0.25, 0.03):
        seg_rows = max(int(n * frac), 64)
        three_pass = make_three_pass(seg_rows)
        order = jnp.asarray(rng.permutation(n).astype(np.int32))
        go = jnp.asarray(rng.rand(n) < 0.5)
        seg_id = np.full(n, -1, np.int32)
        seg_id[:seg_rows] = 0
        seg_start = jnp.asarray([0, 0], jnp.int32)
        seg_len = jnp.asarray([seg_rows, 0], jnp.int32)
        n_left = jnp.asarray(
            [int(np.asarray(go)[:seg_rows].sum()), 0], jnp.int32)
        win_start = jnp.asarray([0, 0], jnp.int32)
        win_cnt = n_left  # window = the left run
        small = jnp.asarray([1, 0], jnp.int32)

        def mk():
            return round_megakernel(
                bins_t, order, go, grad, hess, mask,
                seg_start, seg_len, n_left, win_start, win_cnt, small,
                num_bins=bins, leaf_tile=tile, fuse_tail=False,
                interpret=not on_tpu)

        raw, fresh = mk()
        no3, h3 = three_pass(order, jnp.asarray(seg_id),
                             jnp.asarray([0], jnp.int32),
                             jnp.asarray([seg_rows], jnp.int32), go)
        jax.block_until_ready((fresh, h3))
        parity = bool(np.array_equal(np.asarray(fresh[0]), np.asarray(h3)))
        row = {"bitwise_parity": parity, "segment_rows": seg_rows}
        for tag, fn, reps in (("three_pass", lambda: three_pass(
                order, jnp.asarray(seg_id), jnp.asarray([0], jnp.int32),
                jnp.asarray([seg_rows], jnp.int32), go), 10),
                              ("megakernel", mk, 3 if not on_tpu else 10)):
            t0 = time.perf_counter()
            for _ in range(reps):
                out = fn()
            jax.block_until_ready(out)
            row[f"{tag}_ms"] = round(
                (time.perf_counter() - t0) / reps * 1e3, 3)
        entry["fractions"][str(frac)] = row
        _STATE["workloads"]["megakernel_move"] = entry
        _emit()
        if not parity:
            raise AssertionError(
                f"megakernel hist diverged from the three-pass composite "
                f"at fraction {frac}")


def main():
    import jax

    n = int(os.environ.get("OOC_BENCH_ROWS", 120_000))
    f = int(os.environ.get("OOC_BENCH_FEATURES", 16))
    chunks = [int(c) for c in os.environ.get(
        "OOC_BENCH_CHUNKS", "4096,16384,65536").split(",")]
    platform = jax.devices()[0].platform
    _STATE["platform"] = platform

    cache = os.path.join(os.path.dirname(os.path.abspath(__file__)), "..",
                         ".bench_cache", f"ooc_{n}x{f}.bin")
    os.makedirs(os.path.dirname(cache), exist_ok=True)
    t0 = time.perf_counter()
    X, y = _make_cache(n, f, cache)
    _STATE["workloads"]["make_cache"] = {
        "rows": n, "features": f, "bin_and_save_s":
        round(time.perf_counter() - t0, 2)}
    _emit()

    _guarded("stream_ingest", lambda: bench_stream_ingest(cache, n, chunks))
    _guarded("spill_train",
             lambda: bench_spill_train(cache, X, y, n, chunks),
             budget_floor=30.0)
    _guarded("partition_move", lambda: bench_partition_move(n, platform),
             budget_floor=20.0)
    _guarded("megakernel_move", lambda: bench_megakernel_move(n, platform),
             budget_floor=20.0)

    _STATE["elapsed_s"] = round(time.monotonic() - _T0, 1)
    _emit()
    try:
        os.remove(cache)  # the synthetic cache is a scratch artifact
    except OSError:
        pass


if __name__ == "__main__":
    main()
