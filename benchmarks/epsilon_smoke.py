"""Epsilon-shape capacity smoke (BASELINE.md config 2; VERDICT item 6):
EPS_QUANT=1 measures the quantized-training path (doubled leaf tile).
400k x 2000 dense, 255 leaves, 255 bins must train on ONE chip without OOM.
Prints iters/sec for a few iterations."""

import os
import sys
import time

import numpy as np


def main():
    n = int(os.environ.get("EPS_ROWS", 400_000))
    f = int(os.environ.get("EPS_COLS", 2000))
    iters = int(os.environ.get("EPS_ITERS", 3))
    rng = np.random.RandomState(0)
    X = rng.randn(n, f).astype(np.float32)
    w = rng.randn(f) / np.sqrt(f)
    y = ((X @ w + 0.5 * rng.randn(n)) > 0).astype(np.float64)

    import jax
    import lightgbm_tpu as lgb

    quant = os.environ.get("EPS_QUANT", "0") == "1"
    train = lgb.Dataset(X, label=y)
    del X
    params = {"objective": "binary", "num_leaves": 255, "max_bin": 255,
              "verbosity": -1, "min_data_in_leaf": 20}
    if quant:
        # int8 payloads carry 3 channels/leaf -> the wide-shape leaf tile
        # doubles (10 -> 20) at the same ~60-lane budget
        params.update(use_quantized_grad=True, num_grad_quant_bins=16)
    bst = lgb.Booster(params=params, train_set=train)
    print("leaf_tile:", bst._gbdt._leaf_tile(bst._gbdt.train_set), flush=True)
    bst.update()
    jax.block_until_ready(bst._gbdt._score)
    t0 = time.perf_counter()
    for _ in range(iters):
        bst.update()
    float(np.asarray(bst._gbdt._score)[0])
    dt = time.perf_counter() - t0
    print(f"epsilon-shape: {iters/dt:.3f} iters/sec ({n}x{f}, 255 leaves) OK")


if __name__ == "__main__":
    main()
