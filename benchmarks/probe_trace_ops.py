"""Round-7 probe: traced-op counts of the growers' round bodies.

The r5 compact-pair rework took the primary fused-step warmup from ~137 s
to ~240 s (docs/NEXT.md lever 4).  Compile time on the remote Mosaic
toolchain scales with traced-op count far more than with FLOPs, so this
probe makes the trace size itself a measurable artifact: jaxpr equation
counts for grow_tree_fast (the fused step's dominant component) and the
fused windowed round at representative configs.  bench.py records the
primary-config count in every artifact (trace_eqns) so the next
regression is caught structurally, off-chip, before it costs a 4-minute
warmup on the tunnel.

Usage: python benchmarks/probe_trace_ops.py [leaf_tile ...]
"""

import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))


def count_eqns(jaxpr) -> int:
    """Total equations including sub-jaxprs (scan/while/cond bodies)."""
    total = 0
    todo = [jaxpr]
    while todo:
        j = todo.pop()
        total += len(j.eqns)
        for eqn in j.eqns:
            for v in eqn.params.values():
                if hasattr(v, "jaxpr"):
                    todo.append(v.jaxpr)
                elif isinstance(v, (list, tuple)):
                    todo.extend(x.jaxpr for x in v if hasattr(x, "jaxpr"))
    return total


def fast_grower_eqns(n=4096, f=28, num_leaves=31, num_bins=64, leaf_tile=8):
    import jax
    import jax.numpy as jnp

    from lightgbm_tpu.ops.split import SplitParams
    from lightgbm_tpu.ops.treegrow_fast import grow_tree_fast

    jaxpr = jax.make_jaxpr(
        lambda b, g, h, m, sw, fm, nb, mb: grow_tree_fast(
            b, g, h, m, sw, fm, nb, mb,
            num_leaves=num_leaves, num_bins=num_bins,
            params=SplitParams(), leaf_tile=leaf_tile, use_pallas=False)
    )(
        jnp.zeros((n, f), jnp.int16), jnp.zeros((n,), jnp.float32),
        jnp.zeros((n,), jnp.float32), jnp.ones((n,), bool),
        jnp.ones((n,), jnp.float32), jnp.ones((f,), bool),
        jnp.full((f,), num_bins, jnp.int32), jnp.full((f,), -1, jnp.int32),
    )
    return count_eqns(jaxpr.jaxpr)


def windowed_round_eqns(n=4096, f=28, num_leaves=31, num_bins=64,
                        leaf_tile=8, W=8192):
    import jax
    import jax.numpy as jnp

    from lightgbm_tpu.ops.split import SplitParams
    from lightgbm_tpu.ops import treegrow_windowed as tw

    state, g, h, gq, hq, qs, gt, ht = tw._w_init(
        jnp.zeros((f, n), jnp.int16), jnp.zeros((n,), jnp.float32),
        jnp.zeros((n,), jnp.float32), jnp.ones((n,), bool),
        jnp.ones((n,), jnp.float32), jnp.full((f,), num_bins, jnp.int32),
        jnp.full((f,), -1, jnp.int32), jnp.ones((f,), bool),
        None, None, None,
        num_leaves=num_leaves, num_bins=num_bins, params=SplitParams(),
        leaf_tile=leaf_tile, use_pallas=False, quantize_bins=0,
        hist_precision="f32", stochastic_rounding=False)
    jaxpr = jax.make_jaxpr(
        lambda s, b, gg, hh, m: tw._round_fused(
            s, b, gg, hh, None, None, None, m,
            jnp.full((f,), num_bins, jnp.int32),
            jnp.full((f,), -1, jnp.int32), jnp.ones((f,), bool), None, None,
            num_leaves=num_leaves, num_bins=num_bins, max_depth=-1,
            params=SplitParams(), leaf_tile=leaf_tile, W=W,
            use_pallas=False, quantize_bins=0, hist_precision="f32")
    )(state, jnp.zeros((f, n), jnp.int16), g, h, jnp.ones((n,), bool))
    return count_eqns(jaxpr.jaxpr)


def main():
    tiles = [int(t) for t in sys.argv[1:]] or [8, 16]
    for t in tiles:
        print(f"grow_tree_fast   leaf_tile={t:2d}: "
              f"{fast_grower_eqns(leaf_tile=t):6d} eqns", flush=True)
    for t in tiles:
        print(f"windowed _round_fused leaf_tile={t:2d}: "
              f"{windowed_round_eqns(leaf_tile=t):6d} eqns", flush=True)


if __name__ == "__main__":
    main()
