"""Round-5 probe: can the 1M x 28 x 255-bin config reach vs_baseline 1.0?

The r3 floor analysis: ~7.6 ms per 255-bin Pallas pass across every dot
reorganization tried, ~6-7 passes per 31-leaf tree => ~12-13 it/s upper
region; vs_baseline 1.0 needs 21.8 it/s.  The compact-pair rework (r5)
removed most per-round fixed costs, so re-test the remaining levers that
change PASS COUNT or PASS COST:

  tile8-f32   shipped default (8 leaves/pass, 48 lanes)
  tile10-f32  60 lanes
  tile16-bf16 bf16 payload halves lanes/leaf -> 16 leaves at 64 lanes
              (fewer admission rounds; ~8-bit-mantissa hists)
  tile20-q16  int8 quantized, 3 lanes/leaf -> 20 leaves at 60 lanes

Each one trains 20 iterations end-to-end (host-pull sync).
"""

import sys
import time

import numpy as np

sys.path.insert(0, "/root/repo")


def run(tag, params, X, y, iters=20):
    import lightgbm_tpu as lgb

    ds = lgb.Dataset(X, label=y)
    t0 = time.perf_counter()
    bst = lgb.Booster(params=params, train_set=ds)
    bst.update()
    _ = np.asarray(bst._gbdt._score[:8])
    warm = time.perf_counter() - t0
    t0 = time.perf_counter()
    for _ in range(iters):
        bst.update()
    _ = np.asarray(bst._gbdt._score[:8])
    spi = (time.perf_counter() - t0) / iters
    print(f"{tag:14s} {1.0/spi:6.2f} it/s ({spi*1e3:6.1f} ms/iter) "
          f"warmup {warm:.0f}s", flush=True)


def main():
    n, f = 1_000_000, 28
    rng = np.random.RandomState(0)
    X = rng.randn(n, f).astype(np.float32)
    w = rng.randn(f) / np.sqrt(f)
    y = ((X @ w + 0.3 * rng.randn(n)) > 0).astype(np.float64)
    base = {"objective": "binary", "num_leaves": 31, "max_bin": 255,
            "verbosity": -1, "min_data_in_leaf": 20}
    which = sys.argv[1:] or ["tile8-f32", "tile16-bf16", "tile20-q16"]
    if "tile8-f32" in which:
        run("tile8-f32", dict(base), X, y)
    if "tile16-bf16" in which:
        run("tile16-bf16", dict(base, hist_precision="bf16"), X, y)
    if "tile20-q16" in which:
        run("tile20-q16", dict(base, use_quantized_grad=True,
                               quant_train_renew_leaf=True), X, y)


if __name__ == "__main__":
    main()
