"""Dispatch-loop timing for the variants whose fori_loop jits exceed the
remote-compile size limit (HTTP 413): dispatch R times back-to-back (they
serialize on device), sync once, subtract the ~1.3 ms/dispatch tunnel cost
(docs/PERF_NOTES.md).  Coarser than the in-jit probe but enough to rank."""

import sys
import time

import numpy as np
import jax
import jax.numpy as jnp

sys.path.insert(0, "benchmarks")
from probe_b256b import make_cpack, N, F, B  # noqa: E402

DISPATCH_MS = 1.3
R = 30


def main():
    rng = np.random.RandomState(0)
    bins_np = rng.randint(0, B, size=(N, F)).astype(np.int16)
    pay_np = (rng.randn(N, 48) * 0.1).astype(np.float32)
    bins = jnp.asarray(bins_np)
    pay48 = jnp.asarray(pay_np)
    pay128 = jnp.asarray(np.pad(pay_np, ((0, 0), (0, 80))))
    pay_i8 = jnp.asarray(np.clip(np.round(pay_np / 0.02), -127, 127).astype(np.int8))

    cases = {
        "cpack4_base48": (make_cpack(4), pay48),  # control vs in-jit 7.6ms
        "cpack4_int8": (make_cpack(4, int8=True), pay_i8),
        "cpack1_nc128": (make_cpack(1, nc=128), pay128),
        "cpack4_nc128": (make_cpack(4, nc=128), pay128),
    }
    which = sys.argv[1].split(",") if len(sys.argv) > 1 else list(cases)
    for key in which:
        fn, pay = cases[key]
        try:
            out = fn(bins, pay)
            np.asarray(out).ravel()[:1]
        except Exception as e:  # noqa: BLE001
            print(f"{key:16s} FAILED: {type(e).__name__}: {str(e)[:160]}", flush=True)
            continue
        t0 = time.perf_counter()
        for _ in range(R):
            out = fn(bins, pay)
        np.asarray(out).ravel()[:1]
        total = (time.perf_counter() - t0) / R * 1e3
        print(f"{key:16s} per-pass ~{total - DISPATCH_MS:6.2f} ms "
              f"(raw {total:.2f})", flush=True)


if __name__ == "__main__":
    main()
