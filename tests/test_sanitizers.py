"""Sanitizer-job analogue (SURVEY §6.2): the reference's CI runs an
ASan/UBSan build; the jit-purity equivalent here is training under
jax.enable_checks (internal invariant checking) and jax.debug_nans
(NaN propagation detection) — across every grower the engine can select:
strict, rounds, int8-quantized rounds, windowed, and a loopback
data-parallel round.  The static half of the sanitizer story is jaxlint
(lightgbm_tpu/analysis, gated by test_jaxlint_gate.py); the retrace half
is utils/sanitizer.py (gated by test_retrace.py)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import lightgbm_tpu as lgb


def _train_small(extra_params=None):
    rng = np.random.RandomState(0)
    X = rng.randn(300, 4)
    y = (X[:, 0] > 0).astype(float)
    d = lgb.Dataset(X, label=y)
    params = {"objective": "binary", "num_leaves": 7, "verbosity": -1}
    params.update(extra_params or {})
    bst = lgb.train(params, d, num_boost_round=3)
    p = bst.predict(X)
    assert np.isfinite(p).all()


def test_train_under_enable_checks():
    with jax.enable_checks(True):
        _train_small()


def test_train_under_enable_checks_rounds_grower():
    with jax.enable_checks(True):
        _train_small({"tree_growth_mode": "rounds"})


def test_train_under_debug_nans():
    """jax.debug_nans historically conflated the growers' -inf gain
    sentinels with NaNs on some paths; the sentinel plumbing is now clean
    enough to train under it — keep it that way."""
    with jax.debug_nans(True):
        _train_small()


def test_train_quantized_under_checks_and_debug_nans():
    """int8 discretized gradients (stochastic rounding, int32 accumulate,
    dequantized split eval) on the rounds grower under both sanitizers."""
    with jax.enable_checks(True), jax.debug_nans(True):
        _train_small({"tree_growth_mode": "rounds",
                      "use_quantized_grad": True})


def _windowed_inputs(n=1500, f=10, seed=0):
    from lightgbm_tpu.binning import DatasetBinner

    rng = np.random.RandomState(seed)
    X = rng.randn(n, f)
    y = X @ rng.randn(f)
    binner = DatasetBinner.fit(X, max_bin=63)
    bins_t = jnp.asarray(binner.transform(X).T, jnp.int16)
    return binner, bins_t, jnp.asarray(0.6 * y, jnp.float32)


def test_windowed_grower_under_enable_checks():
    """The windowed grower donates its hist state and drives growth from a
    host loop — the donation/threading invariants are exactly what
    enable_checks' internal assertions exercise."""
    from lightgbm_tpu.ops.split import SplitParams
    from lightgbm_tpu.ops.treegrow_windowed import grow_tree_windowed

    binner, bins_t, grad = _windowed_inputs()
    n, f = bins_t.shape[1], bins_t.shape[0]
    with jax.enable_checks(True):
        tree, leaf = grow_tree_windowed(
            bins_t, grad, jnp.ones((n,), jnp.float32),
            jnp.ones((n,), bool), jnp.ones((n,), jnp.float32),
            jnp.ones((f,), bool),
            jnp.asarray(binner.num_bins_per_feature),
            jnp.asarray(binner.missing_bin_per_feature),
            num_leaves=15, num_bins=64,
            params=SplitParams(min_data_in_leaf=5.0),
            leaf_tile=4, use_pallas=False)
    nl = int(tree.num_leaves)
    assert nl > 1
    assert np.isfinite(np.asarray(tree.leaf_value[:nl])).all()
    assert not np.isnan(np.asarray(leaf)).any()


def test_data_parallel_round_under_enable_checks():
    """One loopback data-parallel growth round (shard_map + psum over the
    virtual CPU mesh) under enable_checks: the collective/sharding layer
    runs with JAX's internal invariant checks on."""
    from lightgbm_tpu.binning import DatasetBinner
    from lightgbm_tpu.ops.split import SplitParams
    from lightgbm_tpu.parallel.data_parallel import (ShardedData,
                                                     grow_tree_data_parallel)
    from lightgbm_tpu.parallel.mesh import make_mesh

    if jax.device_count() < 4:
        pytest.skip("needs the virtual multi-device CPU mesh")
    rng = np.random.RandomState(7)
    n, f = 1200, 8
    X = rng.randn(n, f)
    y = X @ rng.randn(f)
    binner = DatasetBinner.fit(X, max_bin=31)
    bins = binner.transform(X)
    mesh = make_mesh(4)
    sharded = ShardedData(mesh, bins, binner.num_bins_per_feature,
                          binner.missing_bin_per_feature)
    with jax.enable_checks(True):
        tree, leaf = grow_tree_data_parallel(
            sharded,
            sharded.pad_rows(np.asarray(0.6 * y, np.float32)),
            sharded.pad_rows(np.full(n, 0.25, np.float32)),
            sharded.pad_rows(np.ones(n, bool), fill=False),
            sharded.pad_rows(np.ones(n, np.float32), fill=1.0),
            jnp.ones((f,), bool),
            num_leaves=7, num_bins=binner.max_num_bins,
            params=SplitParams(min_data_in_leaf=10))
    nl = int(tree.num_leaves)
    assert nl > 1
    assert np.isfinite(np.asarray(tree.leaf_value[:nl])).all()


def test_no_nans_in_training_state():
    """debug_nans-style spot check without the context manager (the grower
    uses -inf sentinels deliberately, which jax.debug_nans conflates with
    NaNs on some paths): every intermediate the booster keeps must be
    finite-or-sentinel, never NaN."""
    rng = np.random.RandomState(1)
    X = rng.randn(400, 5)
    y = (X[:, 0] + 0.5 * X[:, 1] > 0).astype(float)
    d = lgb.Dataset(X, label=y)
    bst = lgb.Booster(params={"objective": "binary", "num_leaves": 7,
                              "verbosity": -1}, train_set=d)
    for _ in range(4):
        bst.update()
        assert not np.isnan(np.asarray(bst._gbdt._score)).any()
        assert not np.isnan(np.asarray(bst._gbdt._cur_grad)).any()
    for t in bst._gbdt.models:
        assert np.isfinite(t.leaf_value[: t.num_leaves]).all()


def test_dispatch_counter_accounting():
    """DispatchCounter deltas: dispatches, blocking pulls and pipelined
    resolves are counted independently and snapshot-scoped."""
    from lightgbm_tpu.utils import sanitizer as san

    x = jnp.arange(8.0)
    with san.DispatchCounter() as d:
        san.record_dispatch()
        san.record_dispatch(2)
        v = san.sync_pull(x)
        san.async_pull_start(x)
        w = san.async_pull_result(x)
    assert (d.dispatches, d.host_syncs, d.async_resolves) == (3, 1, 1)
    assert np.asarray(v).shape == (8,) and np.asarray(w).shape == (8,)
    # a fresh counter starts from the new baseline
    with san.DispatchCounter() as d2:
        pass
    assert (d2.dispatches, d2.host_syncs, d2.async_resolves) == (0, 0, 0)


def test_dispatch_counter_round_budget():
    from lightgbm_tpu.utils import sanitizer as san

    with san.DispatchCounter() as d:
        for _ in range(4):
            san.record_dispatch()
    d.assert_round_budget(4, what="clean loop")
    with pytest.raises(san.BudgetError):
        d.assert_round_budget(4, dispatches_per_round=2, what="two-phase")

    with san.DispatchCounter() as d2:
        san.record_dispatch()
        san.sync_pull(jnp.zeros(()))
    with pytest.raises(san.BudgetError):
        d2.assert_round_budget(1, what="loop with a blocking pull")
