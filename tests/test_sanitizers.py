"""Sanitizer-job analogue (SURVEY §6.2): the reference's CI runs an
ASan/UBSan build; the jit-purity equivalent here is training under
jax.enable_checks (internal invariant checking) and jax.debug_nans
(NaN propagation detection)."""

import jax
import numpy as np
import pytest

import lightgbm_tpu as lgb


def _train_small(extra_params=None):
    rng = np.random.RandomState(0)
    X = rng.randn(300, 4)
    y = (X[:, 0] > 0).astype(float)
    d = lgb.Dataset(X, label=y)
    params = {"objective": "binary", "num_leaves": 7, "verbosity": -1}
    params.update(extra_params or {})
    bst = lgb.train(params, d, num_boost_round=3)
    p = bst.predict(X)
    assert np.isfinite(p).all()


def test_train_under_enable_checks():
    with jax.enable_checks(True):
        _train_small()


def test_train_under_enable_checks_rounds_grower():
    with jax.enable_checks(True):
        _train_small({"tree_growth_mode": "rounds"})


def test_no_nans_in_training_state():
    """debug_nans-style spot check without the context manager (the grower
    uses -inf sentinels deliberately, which jax.debug_nans conflates with
    NaNs on some paths): every intermediate the booster keeps must be
    finite-or-sentinel, never NaN."""
    rng = np.random.RandomState(1)
    X = rng.randn(400, 5)
    y = (X[:, 0] + 0.5 * X[:, 1] > 0).astype(float)
    d = lgb.Dataset(X, label=y)
    bst = lgb.Booster(params={"objective": "binary", "num_leaves": 7,
                              "verbosity": -1}, train_set=d)
    for _ in range(4):
        bst.update()
        assert not np.isnan(np.asarray(bst._gbdt._score)).any()
        assert not np.isnan(np.asarray(bst._gbdt._cur_grad)).any()
    for t in bst._gbdt.models:
        assert np.isfinite(t.leaf_value[: t.num_leaves]).all()
