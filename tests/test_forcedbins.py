"""forcedbins_filename: forced bin boundaries from JSON (reference:
DatasetLoader forced-bins JSON -> BinMapper::FindBin forced_upper_bounds)."""

import json
import os
import tempfile

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.binning import find_bin


def test_find_bin_forced_bounds_are_boundaries():
    rng = np.random.RandomState(0)
    vals = rng.randn(5000)
    m = find_bin(vals, max_bin=32, forced_bounds=[0.25, 1.5])
    assert 0.25 in m.upper_bounds
    assert 1.5 in m.upper_bounds
    assert m.num_bins <= 32
    # values straddling a forced bound land in different bins
    b = m.transform(np.array([0.249, 0.251]))
    assert b[0] != b[1]


def test_forced_bounds_respect_budget():
    rng = np.random.RandomState(1)
    vals = rng.randn(5000)
    forced = list(np.linspace(-2, 2, 64))
    m = find_bin(vals, max_bin=16, forced_bounds=forced)
    assert m.num_bins <= 16


def test_dataset_forcedbins_file_and_training():
    rng = np.random.RandomState(2)
    X = rng.randn(1500, 3)
    y = (X[:, 0] > 0.5).astype(float)
    fb = [{"feature": 0, "bin_upper_bound": [0.5]}]
    with tempfile.NamedTemporaryFile("w", suffix=".json", delete=False) as f:
        json.dump(fb, f)
        path = f.name
    try:
        d = lgb.Dataset(X, label=y, params={"forcedbins_filename": path})
        bst = lgb.train(
            {"objective": "binary", "num_leaves": 4, "verbosity": -1,
             "forcedbins_filename": path},
            d, num_boost_round=3,
        )
        # with the boundary forced exactly at the class edge, the root split
        # threshold should be 0.5 on feature 0
        m = bst.dump_model()
        root = m["tree_info"][0]["tree_structure"]
        assert root["split_feature"] == 0
        assert root["threshold"] == pytest.approx(0.5, abs=1e-9)
    finally:
        os.unlink(path)
