"""Tracing/profiling harness + NaN-sanitizer analog (SURVEY §6.1/§6.2:
the reference's TIMETAG timers and its sanitizer CI jobs)."""

import pytest
import glob
import os

import numpy as np

import lightgbm_tpu as lgb
from lightgbm_tpu.utils.profiling import device_trace, log_timings, timed_section

pytestmark = pytest.mark.slow


def _tiny_train(extra=None):
    rng = np.random.RandomState(0)
    X = rng.randn(800, 5).astype(np.float32)
    y = ((X @ rng.randn(5)) > 0).astype(np.float64)
    ds = lgb.Dataset(X, label=y)
    params = {"objective": "binary", "num_leaves": 7, "verbosity": -1}
    params.update(extra or {})
    bst = lgb.Booster(params=params, train_set=ds)
    for _ in range(3):
        bst.update()
    return bst, X, y


def test_device_trace_writes_profile(tmp_path):
    logdir = str(tmp_path / "trace")
    with device_trace(logdir):
        with timed_section("train"):
            _tiny_train()
    files = glob.glob(os.path.join(logdir, "**", "*"), recursive=True)
    assert any("trace" in f or f.endswith(".pb") or f.endswith(".json.gz") for f in files), files
    totals = log_timings()
    assert totals["train"] > 0


def test_training_is_nan_clean_under_debug_nans():
    """jax debug_nans is the sanitizer-CI analog: any NaN produced inside a
    jitted training op raises immediately."""
    import jax

    jax.config.update("jax_debug_nans", True)
    try:
        bst, X, y = _tiny_train()
        p = bst.predict(X)
        assert np.isfinite(p).all()
        # missing values must stay NaN-clean too
        Xn = X.copy()
        Xn[::7, 0] = np.nan
        ds = lgb.Dataset(Xn, label=y)
        bst2 = lgb.Booster(
            params={"objective": "binary", "num_leaves": 7, "verbosity": -1},
            train_set=ds,
        )
        for _ in range(2):
            bst2.update()
        assert np.isfinite(bst2.predict(Xn)).all()
    finally:
        jax.config.update("jax_debug_nans", False)
