"""Observability subsystem (round 10, docs/OBSERVABILITY.md): registry
semantics, event schema, snapshot round-trip, fleet aggregation — plus THE
acceptance pin: with telemetry default-on, the round-7 windowed budget
(1 dispatch / 0 blocking syncs / 0 retraces per steady-state round) and the
round-9 serving budget (warm predict = 1 dispatch + 1 pull) hold unchanged
while the run leaves a non-empty, schema-valid metrics snapshot covering
train, predict, and a robustness event.

The legacy profiling-harness tests (device trace capture, debug_nans train)
stay ``slow``; everything else here is tier-1.
"""

import glob
import json
import os

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.obs import metrics as obs
from lightgbm_tpu.utils.profiling import (device_trace, log_timings,
                                          timed_section)


@pytest.fixture(autouse=True)
def _fresh_registry():
    from lightgbm_tpu.obs import server as _srv
    from lightgbm_tpu.obs import trace as _trc

    obs.reset()
    obs.set_events_file(None)
    _trc.reset_trace()
    yield
    _srv.stop_server()
    obs.stop_periodic_snapshots(final_write=False)
    obs.reset()
    obs.set_events_file(None)
    _trc.reset_trace()


def _tiny_train(extra=None, rounds=3):
    rng = np.random.RandomState(0)
    X = rng.randn(800, 5).astype(np.float32)
    y = ((X @ rng.randn(5)) > 0).astype(np.float64)
    ds = lgb.Dataset(X, label=y)
    params = {"objective": "binary", "num_leaves": 7, "verbosity": -1}
    params.update(extra or {})
    bst = lgb.Booster(params=params, train_set=ds)
    for _ in range(rounds):
        bst.update()
    return bst, X, y


# ---------------------------------------------------------------------------
# registry semantics
# ---------------------------------------------------------------------------

def test_counter_gauge_semantics():
    c = obs.counter("t_total")
    c.inc()
    c.inc(4)
    assert c.value == 5
    assert obs.counter("t_total") is c  # create-on-first-use, then shared
    g = obs.gauge("t_gauge")
    g.set(2.5)
    g.set(-1.0)
    assert g.value == -1.0


def test_histogram_reservoir_bounded_and_percentiles():
    h = obs.histogram("t_hist")
    for v in range(10_000):
        h.observe(float(v))
    assert h.count == 10_000
    assert h.total == sum(range(10_000))
    assert h.min == 0.0 and h.max == 9999.0
    assert len(h._samples) == obs.RESERVOIR_CAP  # hard memory bound
    p50, p99 = h.percentile(50), h.percentile(99)
    # reservoir estimate: generous tolerance, exact rank not required
    assert 3500 < p50 < 6500, p50
    assert p99 > 9000, p99
    s = h.summary()
    assert s["count"] == 10_000 and s["p50"] == p50


def test_disabled_registry_is_noop():
    obs.set_enabled(False)
    try:
        obs.counter("t_off").inc()
        obs.histogram("t_off_h").observe(1.0)
        obs.event("t_off_event")
        assert obs.counter("t_off").value == 0
        assert obs.histogram("t_off_h").count == 0
        assert not obs.events("t_off_event")
    finally:
        obs.set_enabled(True)


def test_collector_merges_into_snapshot():
    obs.register_collector(
        "t_coll", lambda: {"counters": {"t_coll_total": 7},
                           "gauges": {"t_coll_gauge": 1.5}})
    try:
        snap = obs.snapshot()
        assert snap["counters"]["t_coll_total"] == 7
        assert snap["gauges"]["t_coll_gauge"] == 1.5
        # the sanitizer collector is registered at import and always present
        assert "device_dispatches_total" in snap["counters"]
        assert "device_compiles_total" in snap["counters"]
    finally:
        obs.REGISTRY._collectors.pop("t_coll", None)


# ---------------------------------------------------------------------------
# events: schema + JSONL sink
# ---------------------------------------------------------------------------

def test_event_schema_and_jsonl_sink(tmp_path):
    sink = str(tmp_path / "events.jsonl")
    obs.set_events_file(sink)
    obs.event("unit_test", detail="abc", n=3)
    obs.event("unit_test", n=4)
    recs = [json.loads(line) for line in
            open(sink, encoding="utf-8").read().splitlines()]
    assert len(recs) == 2
    for rec in recs:
        # the schema every record carries (docs/OBSERVABILITY.md)
        assert isinstance(rec["ts"], float)
        assert rec["kind"] == "unit_test"
        assert "rank" in rec  # None outside launcher workers
    assert recs[0]["detail"] == "abc" and recs[1]["n"] == 4
    # the in-memory ring saw the same records
    assert len(obs.events("unit_test")) == 2


def test_event_sink_failure_is_silent_and_final(tmp_path, monkeypatch):
    """A sink that cannot open fails ONCE: events keep flowing to the
    ring, nothing raises, and the registry neither retries per event nor
    falls back to the env-configured path."""
    env_sink = tmp_path / "env.jsonl"
    monkeypatch.setenv("LGBMTPU_EVENTS_FILE", str(env_sink))
    obs.set_events_file(str(tmp_path / "no_such_dir" / "x.jsonl"))
    obs.event("sink_fail", n=1)
    obs.event("sink_fail", n=2)
    assert len(obs.events("sink_fail")) == 2  # ring unaffected
    assert not env_sink.exists()  # no silent fallback to the env path
    # reverting to env resolution picks the env sink up again
    obs.set_events_file(None)
    obs.event("sink_fail", n=3)
    assert env_sink.exists()


def test_event_rank_stamped_from_env(tmp_path, monkeypatch):
    monkeypatch.setenv("LIGHTGBM_TPU_RANK", "3")
    reg = obs.Registry()
    reg.event("ranked")
    assert reg.events("ranked")[0]["rank"] == 3


def test_fleet_event_aggregation(tmp_path):
    """parallel/launcher.py merges per-rank JSONLs time-sorted, skipping a
    crashed worker's torn last line."""
    a = tmp_path / "worker0.events.jsonl"
    b = tmp_path / "worker1.events.jsonl"
    a.write_text(json.dumps({"ts": 2.0, "kind": "boost_round", "rank": 0})
                 + "\n")
    b.write_text(json.dumps({"ts": 1.0, "kind": "boost_round", "rank": 1})
                 + "\n" + '{"ts": 3.0, "kind": "torn')  # mid-crash tail
    out = tmp_path / "fleet.jsonl"
    n = obs.merge_event_files([str(a), str(b), str(tmp_path / "gone")],
                              str(out))
    assert n == 2
    recs = [json.loads(line) for line in out.read_text().splitlines()]
    assert [r["rank"] for r in recs] == [1, 0]  # time-sorted across ranks


# ---------------------------------------------------------------------------
# snapshot round-trip + rendering
# ---------------------------------------------------------------------------

def test_snapshot_roundtrip_and_renderers(tmp_path):
    obs.counter("t_rt_total").inc(3)
    obs.gauge("t_rt_gauge").set(0.5)
    obs.histogram("t_rt_ms").observe(1.5)
    obs.histogram(obs.SECTION_PREFIX + "train").observe(2.0)
    obs.event("t_rt")
    path = str(tmp_path / "metrics.json")
    obs.write_snapshot(path)
    snap = obs.load_snapshot(path)  # validates the schema on load
    assert snap["schema"] == obs.SCHEMA
    assert snap["counters"]["t_rt_total"] == 3
    assert snap["histograms"]["t_rt_ms"]["count"] == 1
    assert snap["events_total"] == 1
    prom = obs.render_prometheus(snap)
    assert "# TYPE lgbmtpu_t_rt_total counter" in prom
    assert "lgbmtpu_t_rt_total 3" in prom
    assert 'lgbmtpu_t_rt_ms{quantile="0.5"} 1.5' in prom
    report = obs.render_lightgbm(snap)
    assert "Time for train: 2.000000 s (1 calls)" in report
    assert any(line.startswith("t_rt_total = 3") for line in report)
    with pytest.raises(ValueError):
        obs.validate_snapshot({"schema": "bogus"})


def test_serve_reservoirs_render_as_label_sets_one_family():
    """Round 18 (ISSUE 13 satellite): the per-entry warm-latency
    reservoirs are LABEL SETS on the one ``predict_warm_latency_ms``
    family — ``{entry="raw"}`` next to the round-11 ``{bucket="..."}``
    labels — not the deprecated dotted-suffix names, which rendered as a
    separate Prometheus family per entry.  Pins the rendered label sets
    and the stable family count."""
    rng = np.random.RandomState(2)
    X = rng.randn(120, 5)
    y = (X[:, 0] > 0).astype(float)
    bst = lgb.Booster(params={"objective": "binary", "num_leaves": 7,
                              "verbosity": -1},
                      train_set=lgb.Dataset(X, label=y))
    for _ in range(2):
        bst.update()
    for _ in range(2):  # first call cold (compiles), second warm (records)
        bst.predict(X, raw_score=True)
        bst.predict(X)

    snap = obs.snapshot()
    hists = snap["histograms"]
    nb = 128  # the bucket X pads to
    assert 'predict_warm_latency_ms{entry="raw"}' in hists
    assert 'predict_warm_latency_ms{entry="converted"}' in hists
    assert f'predict_warm_latency_ms{{bucket="{nb}"}}' in hists
    assert not any("." in name and name.startswith("predict_warm_latency_ms")
                   for name in hists), "dotted-suffix reservoir names back"

    prom = obs.render_prometheus(snap)
    # ONE summary family, every variant a label set on it
    assert prom.count("# TYPE lgbmtpu_predict_warm_latency_ms summary") == 1
    assert "lgbmtpu_predict_warm_latency_ms_raw" not in prom
    assert 'lgbmtpu_predict_warm_latency_ms{entry="raw",quantile="0.5"}' \
        in prom
    assert ('lgbmtpu_predict_warm_latency_ms{entry="converted",'
            'quantile="0.99"}') in prom
    assert f'lgbmtpu_predict_warm_latency_ms{{bucket="{nb}",quantile=' \
        in prom


def test_obs_cli_dumps_snapshot(tmp_path, capsys):
    from lightgbm_tpu.obs.__main__ import main as obs_main

    obs.counter("t_cli_total").inc()
    path = str(tmp_path / "snap.json")
    obs.write_snapshot(path)
    assert obs_main([path]) == 0
    assert "lgbmtpu_t_cli_total 1" in capsys.readouterr().out
    assert obs_main([path, "--format", "lightgbm"]) == 0
    assert "t_cli_total = 1" in capsys.readouterr().out
    assert obs_main([str(tmp_path / "missing.json")]) == 2


# ---------------------------------------------------------------------------
# profiling satellite: registry-backed sections + honest sync
# ---------------------------------------------------------------------------

def test_timed_section_routes_through_registry():
    with timed_section("unit_section"):
        pass
    with timed_section("unit_section", sync=True):  # host-pull sync path
        pass
    h = obs.histogram(obs.SECTION_PREFIX + "unit_section")
    assert h.count == 2
    totals = log_timings(reset=True)
    assert totals["unit_section"] > 0
    assert not obs.histogram_items(obs.SECTION_PREFIX)  # reset cleared them


# ---------------------------------------------------------------------------
# config plumbing: metrics_file= + telemetry=
# ---------------------------------------------------------------------------

def test_train_writes_metrics_file(tmp_path):
    rng = np.random.RandomState(1)
    X = rng.randn(400, 4)
    y = (X[:, 0] > 0).astype(float)
    mfile = str(tmp_path / "run_metrics.json")
    lgb.train({"objective": "binary", "num_leaves": 7, "verbosity": -1,
               "metrics_file": mfile},
              lgb.Dataset(X, label=y), num_boost_round=3)
    snap = obs.load_snapshot(mfile)
    assert snap["counters"]["train_boost_rounds_total"] == 3


def test_telemetry_param_disables_registry():
    try:
        _tiny_train({"telemetry": False}, rounds=2)
        assert not obs.enabled()
        assert obs.counter("train_boost_rounds_total").value == 0
    finally:
        obs.set_enabled(True)


# ---------------------------------------------------------------------------
# ACCEPTANCE: telemetry default-on, budgets unchanged, snapshot non-empty
# ---------------------------------------------------------------------------

def test_budgets_hold_with_telemetry_on_and_snapshot_covers_run(tmp_path):
    """ISSUE 5 acceptance, extended by ISSUE 6: train (windowed
    steady-state round budget) + predict (warm serving budget) with the
    registry active, SPAN TRACING recording, and the HTTP endpoint
    serving live — then assert a schema-valid snapshot covering train,
    predict, and a robustness event (an injected kernel degrade).  The
    round-11 contract is that live introspection adds zero dispatches,
    zero blocking syncs, and zero retraces to both budgets."""
    import json as _json
    import urllib.request

    import jax
    import jax.numpy as jnp

    from lightgbm_tpu.binning import DatasetBinner
    from lightgbm_tpu.obs import server as obs_server
    from lightgbm_tpu.obs import trace as obs_trace
    from lightgbm_tpu.ops.split import SplitParams
    from lightgbm_tpu.ops.treegrow_windowed import grow_tree_windowed
    from lightgbm_tpu.utils import degrade
    from lightgbm_tpu.utils.sanitizer import DispatchCounter

    assert obs.enabled()  # default-on is the contract under test
    obs_trace.reset_trace()
    srv = obs_server.MetricsServer(port=0).start()  # live while we train

    # -- train side: the round-7 budget pin with telemetry recording -----
    n, f = 900, 8
    rng = np.random.RandomState(5)
    X = rng.randn(n, f)
    yv = X @ rng.randn(f) + 0.2 * rng.randn(n)
    binner = DatasetBinner.fit(X, max_bin=31)
    bins_t = jnp.asarray(binner.transform(X).T, jnp.int16)
    kw = dict(
        row_mask=jnp.ones((n,), bool),
        sample_weight=jnp.ones((n,), jnp.float32),
        feature_mask=jnp.ones((f,), bool),
        num_bins_pf=jnp.asarray(binner.num_bins_per_feature),
        missing_bin_pf=jnp.asarray(binner.missing_bin_per_feature),
    )
    static = dict(num_leaves=15, num_bins=32,
                  params=SplitParams(min_data_in_leaf=5.0), leaf_tile=4,
                  use_pallas=False)
    g0 = jnp.asarray(0.6 * yv, jnp.float32)
    g1 = jnp.asarray(0.6 * yv + 0.05, jnp.float32)
    hess = jnp.ones((n,), jnp.float32)
    tree, leaf = grow_tree_windowed(bins_t, g0, hess, **kw, **static)
    jax.block_until_ready(leaf)  # warmup compiles

    stats = {}
    with DispatchCounter() as d:
        tree, leaf = grow_tree_windowed(bins_t, g1, hess, **kw, **static,
                                        stats=stats)
        jax.block_until_ready(leaf)
    d.assert_round_budget(stats["rounds"],
                          what="windowed + telemetry + tracing + server")
    assert stats["host_syncs"] == 0 and stats["retries"] == 0, stats
    d.assert_no_recompile("windowed steady state with telemetry on")
    # the grower left per-round + per-tree spans, all closed at the
    # accounted async-info resolves (ZERO extra syncs, pinned just above)
    assert obs_trace.spans("windowed_round"), "no windowed_round spans"
    assert obs_trace.spans("windowed_tree"), "no windowed_tree spans"
    # reconciliation: every dispatched round has its span — the pipeline's
    # final in-flight round resolves in the drain loop and must be traced
    # there too (its spans carry drained=True)
    total_rounds = sum(s["attrs"]["rounds"]
                       for s in obs_trace.spans("windowed_tree"))
    assert len(obs_trace.spans("windowed_round")) == total_rounds
    assert any(s["attrs"].get("drained")
               for s in obs_trace.spans("windowed_round"))
    # round-12 W-ladder context: every round span carries its rung, the
    # transition that led there, and the whint it emitted — the rung must
    # agree with the W the round ran on, and the deltas must chain
    # (rung[i] - rung[i-1]) within one tree's span sequence
    from lightgbm_tpu.ops.treegrow_windowed import _window_rung
    wspans = obs_trace.spans("windowed_round")
    for s in wspans:
        a = s["attrs"]
        assert a["rung"] == _window_rung(a["W"], n) and "whint" in a
    for prev, cur in zip(wspans, wspans[1:]):
        if not cur["attrs"]["first"]:
            assert (cur["attrs"]["rung_delta"]
                    == cur["attrs"]["rung"] - prev["attrs"]["rung"])

    # -- predict side: the round-9 warm budget with telemetry recording --
    bst, Xb, _ = _tiny_train(rounds=4)
    bst.predict(Xb, raw_score=True)  # warm the bucket
    with DispatchCounter() as dp:
        bst.predict(Xb, raw_score=True)
    assert dp.dispatches == 1, dp.dispatches
    assert dp.host_syncs == 1, dp.host_syncs
    dp.assert_no_recompile("warm predict with telemetry on")
    assert obs_trace.spans("predict.raw"), "no predict spans"
    assert obs_trace.spans("boost_round"), "no boost_round spans"

    # -- the HTTP endpoint served the whole run and sees both families --
    prom_live = urllib.request.urlopen(
        srv.url("/metrics"), timeout=10).read().decode()
    assert "lgbmtpu_train_windowed_rounds_total" in prom_live
    assert "lgbmtpu_predict_requests_total" in prom_live
    assert 'lgbmtpu_predict_warm_latency_ms{bucket="' in prom_live
    hz = urllib.request.urlopen(srv.url("/healthz"), timeout=10)
    assert _json.load(hz)["status"] == "ok"
    srv.stop()

    # -- trace export round-trips as Chrome-trace JSON -------------------
    tpath = str(tmp_path / "run_trace.json")
    from lightgbm_tpu.obs import trace as _t
    assert _t.write_trace(tpath) > 0
    doc = _t.load_trace(tpath)
    assert all(ev["ph"] == "X" for ev in doc["traceEvents"])

    # -- robustness event: an injected kernel degrade -------------------
    degrade.reset()
    try:
        degrade.disable(degrade.HIST, "injected by test_observability")
    finally:
        degrade.reset()

    # -- the run left a non-empty, schema-valid snapshot -----------------
    snap = obs.snapshot()
    obs.validate_snapshot(snap)
    c = snap["counters"]
    assert c["train_windowed_rounds_total"] >= stats["rounds"]  # train
    assert c["train_boost_rounds_total"] == 4
    assert c["predict_requests_total"] >= 2  # predict
    assert c["predict_bucket_hits_total"] >= 1
    assert snap["histograms"]["predict_warm_latency_ms"]["count"] >= 1
    assert snap["histograms"]["train_window_rows"]["count"] >= 1
    assert c["degrade_disabled_total"] == 1  # robustness
    assert c["device_dispatches_total"] >= 1  # sanitizer collector merged
    kinds = {e["kind"] for e in obs.events()}
    assert {"boost_round", "windowed_tree", "degrade"} <= kinds
    # and the snapshot round-trips to a readable artifact
    path = str(tmp_path / "acceptance.json")
    obs.write_snapshot(path, snap)
    assert "lgbmtpu_train_windowed_rounds_total" in obs.render_prometheus(
        obs.load_snapshot(path))


# ---------------------------------------------------------------------------
# legacy profiling harness (slow: full device trace + debug_nans trains)
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_device_trace_writes_profile(tmp_path):
    logdir = str(tmp_path / "trace")
    with device_trace(logdir):
        with timed_section("train"):
            _tiny_train()
    files = glob.glob(os.path.join(logdir, "**", "*"), recursive=True)
    assert any("trace" in f or f.endswith(".pb") or f.endswith(".json.gz") for f in files), files
    totals = log_timings()
    assert totals["train"] > 0


@pytest.mark.slow
def test_training_is_nan_clean_under_debug_nans():
    """jax debug_nans is the sanitizer-CI analog: any NaN produced inside a
    jitted training op raises immediately."""
    import jax

    jax.config.update("jax_debug_nans", True)
    try:
        bst, X, y = _tiny_train()
        p = bst.predict(X)
        assert np.isfinite(p).all()
        # missing values must stay NaN-clean too
        Xn = X.copy()
        Xn[::7, 0] = np.nan
        ds = lgb.Dataset(Xn, label=y)
        bst2 = lgb.Booster(
            params={"objective": "binary", "num_leaves": 7, "verbosity": -1},
            train_set=ds,
        )
        for _ in range(2):
            bst2.update()
        assert np.isfinite(bst2.predict(Xn)).all()
    finally:
        jax.config.update("jax_debug_nans", False)
