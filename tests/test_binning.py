"""Binning unit tests (reference semantics: src/io/bin.cpp BinMapper)."""

import numpy as np
import pytest

from lightgbm_tpu.binning import (
    MISSING_NAN,
    MISSING_NONE,
    BinMapper,
    DatasetBinner,
    find_bin,
)


def test_distinct_value_fast_path():
    vals = np.array([1.0, 2.0, 3.0, 1.0, 2.0, 3.0, 1.0] * 3)
    m = find_bin(vals, max_bin=255, min_data_in_bin=1)
    assert m.missing_type == MISSING_NONE
    # 3 distinct values -> 3 bins; boundaries at midpoints
    b = m.transform(np.array([1.0, 1.4, 1.6, 2.0, 2.6, 3.0, 100.0]))
    assert b.tolist() == [0, 0, 1, 1, 2, 2, 2]


def test_min_data_in_bin_merges():
    # values with counts 1 each and min_data_in_bin=2 -> merged pairs
    vals = np.array([1.0, 2.0, 3.0, 4.0])
    m = find_bin(vals, max_bin=255, min_data_in_bin=2)
    b = m.transform(vals)
    assert b[0] == b[1]
    assert b[2] == b[3]
    assert b[0] != b[2]


def test_nan_gets_last_bin():
    vals = np.array([1.0, 2.0, np.nan, 3.0, np.nan])
    m = find_bin(vals, max_bin=255, min_data_in_bin=1)
    assert m.missing_type == MISSING_NAN
    assert m.missing_bin == m.num_bins - 1
    b = m.transform(vals)
    assert b[2] == m.missing_bin
    assert b[4] == m.missing_bin
    assert b[0] < m.missing_bin


def test_equal_count_binning_large():
    rng = np.random.RandomState(0)
    vals = rng.randn(100000)
    m = find_bin(vals, max_bin=63, min_data_in_bin=3)
    assert m.num_bins <= 63
    b = m.transform(vals)
    counts = np.bincount(b, minlength=m.num_bins)
    # roughly equal-frequency: no empty bins, max/median bounded
    assert (counts[counts > 0] > 0).all()
    assert m.num_bins > 32


def test_threshold_roundtrip():
    """Real-valued thresholds must reproduce binned decisions
    (reference: BinMapper::BinToValue + Tree threshold recording)."""
    rng = np.random.RandomState(1)
    vals = rng.randn(5000)
    m = find_bin(vals, max_bin=31, min_data_in_bin=3)
    bins = m.transform(vals)
    for t in range(m.num_bins - 1):
        thr = m.bin_to_threshold(t)
        np.testing.assert_array_equal(bins <= t, vals <= thr)


def test_categorical_binning():
    vals = np.array([5.0, 5.0, 5.0, 7.0, 7.0, 9.0])
    m = find_bin(vals, max_bin=255, is_categorical=True)
    assert m.is_categorical
    b = m.transform(vals)
    # most frequent category -> bin 0
    assert b[0] == 0
    assert b[3] == 1
    assert b[5] == 2


def test_dataset_binner():
    rng = np.random.RandomState(2)
    X = rng.randn(1000, 5)
    X[::7, 2] = np.nan
    binner = DatasetBinner.fit(X, max_bin=255)
    bins = binner.transform(X)
    assert bins.shape == X.shape
    assert bins.dtype == np.uint8
    assert binner.missing_bin_per_feature[2] >= 0
    assert binner.missing_bin_per_feature[0] == -1
