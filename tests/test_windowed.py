"""Windowed wide-regime grower (ops/treegrow_windowed.py): the physically
partitioned, window-gathered grower must reproduce the full-pass rounds
grower tree-for-tree (same admission semantics, same split search; only
the histogram data movement differs)."""

import numpy as np
import jax.numpy as jnp
import pytest

from lightgbm_tpu.binning import DatasetBinner
from lightgbm_tpu.ops.split import SplitParams
from lightgbm_tpu.ops.treegrow_fast import grow_tree_fast
from lightgbm_tpu.ops.treegrow_windowed import grow_tree_windowed


def _inputs(n=3000, f=40, seed=0):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, f)
    y = X @ rng.randn(f) + 0.3 * rng.randn(n)
    binner = DatasetBinner.fit(X, max_bin=63)
    bins = binner.transform(X)
    grad = jnp.asarray(2.0 * (0.3 * y), jnp.float32)  # arbitrary but fixed
    hess = jnp.ones((n,), jnp.float32)
    return binner, jnp.asarray(bins, jnp.int16), grad, hess


@pytest.mark.parametrize("masked", [False, True])
def test_windowed_matches_fast_grower(masked):
    binner, bins, grad, hess = _inputs()
    n = bins.shape[0]
    rng = np.random.RandomState(1)
    row_mask = (jnp.asarray(rng.rand(n) < 0.8) if masked
                else jnp.ones((n,), bool))
    sw = jnp.ones((n,), jnp.float32)
    fm = jnp.ones((bins.shape[1],), bool)
    nbpf = jnp.asarray(binner.num_bins_per_feature)
    mbpf = jnp.asarray(binner.missing_bin_per_feature)
    params = SplitParams(min_data_in_leaf=5.0)
    kw = dict(num_leaves=31, num_bins=64, params=params, leaf_tile=8,
              use_pallas=False)

    t_fast, lid_fast = grow_tree_fast(
        bins, grad, hess, row_mask, sw, fm, nbpf, mbpf, **kw)
    t_win, lid_win = grow_tree_windowed(
        bins.T, grad, hess, row_mask, sw, fm, nbpf, mbpf, **kw)

    assert int(t_win.num_leaves) == int(t_fast.num_leaves)
    nl = int(t_fast.num_leaves)
    np.testing.assert_array_equal(
        np.asarray(t_win.split_feature[: nl - 1]),
        np.asarray(t_fast.split_feature[: nl - 1]))
    np.testing.assert_array_equal(
        np.asarray(t_win.threshold_bin[: nl - 1]),
        np.asarray(t_fast.threshold_bin[: nl - 1]))
    np.testing.assert_allclose(
        np.asarray(t_win.leaf_value[:nl]), np.asarray(t_fast.leaf_value[:nl]),
        rtol=1e-4, atol=1e-6)
    # per-row leaf assignment identical
    np.testing.assert_array_equal(np.asarray(lid_win), np.asarray(lid_fast))


def test_windowed_quantized_matches_fast_grower_quantized():
    """The windowed grower's quantized path must reproduce the fast
    grower's quantized tree TREE-FOR-TREE: with stochastic_rounding=False
    both paths discretize gradients identically (same round/clip formula),
    so the only difference is histogram data movement — the same property
    the float test above asserts."""
    binner, bins, grad, hess = _inputs(seed=3)
    n = bins.shape[0]
    ones = jnp.ones((n,), bool)
    sw = jnp.ones((n,), jnp.float32)
    fm = jnp.ones((bins.shape[1],), bool)
    nbpf = jnp.asarray(binner.num_bins_per_feature)
    mbpf = jnp.asarray(binner.missing_bin_per_feature)
    params = SplitParams(min_data_in_leaf=5.0)
    kw = dict(num_leaves=15, num_bins=64, params=params, leaf_tile=8,
              use_pallas=False)
    qkw = dict(quantize_bins=16, stochastic_rounding=False, quant_renew=True)

    t_fast, lid_fast = grow_tree_fast(
        bins, grad, hess, ones, sw, fm, nbpf, mbpf, **kw, **qkw)
    t_q, lid_q = grow_tree_windowed(
        bins.T, grad, hess, ones, sw, fm, nbpf, mbpf, **kw, **qkw)

    assert int(t_q.num_leaves) == int(t_fast.num_leaves)
    nl = int(t_fast.num_leaves)
    assert nl > 1 and np.isfinite(np.asarray(t_q.leaf_value[:nl])).all()
    np.testing.assert_array_equal(
        np.asarray(t_q.split_feature[: nl - 1]),
        np.asarray(t_fast.split_feature[: nl - 1]))
    np.testing.assert_array_equal(
        np.asarray(t_q.threshold_bin[: nl - 1]),
        np.asarray(t_fast.threshold_bin[: nl - 1]))
    np.testing.assert_allclose(
        np.asarray(t_q.leaf_value[:nl]), np.asarray(t_fast.leaf_value[:nl]),
        rtol=1e-4, atol=1e-6)
    np.testing.assert_array_equal(np.asarray(lid_q), np.asarray(lid_fast))


def test_windowed_categorical_matches_fast_grower():
    """Round-5 envelope widening: categorical splits in the windowed grower
    (bitset partition + categorical search, both in the fused round body
    _round_fused since round 7) must
    reproduce the fast grower tree-for-tree."""
    rng = np.random.RandomState(5)
    n, f, n_cat = 3000, 10, 8
    X = rng.randn(n, f)
    cats = rng.randint(0, n_cat, n)
    X[:, 0] = cats
    effect = rng.randn(n_cat) * 2.0
    y = effect[cats] + X[:, 1] + 0.2 * rng.randn(n)
    binner = DatasetBinner.fit(X, max_bin=63, categorical_features=[0])
    bins = jnp.asarray(binner.transform(X), jnp.int16)
    grad = jnp.asarray(2.0 * 0.3 * y, jnp.float32)
    hess = jnp.ones((n,), jnp.float32)
    cat_mask = jnp.asarray(np.arange(f) == 0)
    ones = jnp.ones((n,), bool)
    sw = jnp.ones((n,), jnp.float32)
    fm = jnp.ones((f,), bool)
    nbpf = jnp.asarray(binner.num_bins_per_feature)
    mbpf = jnp.asarray(binner.missing_bin_per_feature)
    params = SplitParams(min_data_in_leaf=5.0)
    kw = dict(num_leaves=15, num_bins=64, params=params, leaf_tile=8,
              use_pallas=False)

    t_fast, lid_fast = grow_tree_fast(
        bins, grad, hess, ones, sw, fm, nbpf, mbpf,
        categorical_mask=cat_mask, **kw)
    t_win, lid_win = grow_tree_windowed(
        bins.T, grad, hess, ones, sw, fm, nbpf, mbpf,
        categorical_mask=cat_mask, **kw)

    assert int(t_win.num_leaves) == int(t_fast.num_leaves)
    nl = int(t_fast.num_leaves)
    # the fixture must actually produce categorical splits
    assert bool(np.asarray(t_fast.is_cat[: nl - 1]).any())
    np.testing.assert_array_equal(
        np.asarray(t_win.split_feature[: nl - 1]),
        np.asarray(t_fast.split_feature[: nl - 1]))
    np.testing.assert_array_equal(
        np.asarray(t_win.is_cat[: nl - 1]),
        np.asarray(t_fast.is_cat[: nl - 1]))
    np.testing.assert_array_equal(
        np.asarray(t_win.cat_mask[: nl - 1]),
        np.asarray(t_fast.cat_mask[: nl - 1]))
    np.testing.assert_allclose(
        np.asarray(t_win.leaf_value[:nl]), np.asarray(t_fast.leaf_value[:nl]),
        rtol=1e-4, atol=1e-6)
    np.testing.assert_array_equal(np.asarray(lid_win), np.asarray(lid_fast))


def test_windowed_efb_matches_fast_grower():
    """Round-5 envelope widening: EFB bundles in the windowed grower (the
    window gathers bundled columns; hists unbundle before search) must
    reproduce the fast grower tree-for-tree on the same bundles."""
    import lightgbm_tpu as lgb

    rng = np.random.RandomState(6)
    n, groups = 3000, 12
    # 8-way one-hot blocks: 87.5% sparse, above EFB's min_sparse_rate
    blocks = []
    for g in range(groups):
        col = rng.randint(0, 8, n)
        oh = np.zeros((n, 8))
        oh[np.arange(n), col] = 1.0
        blocks.append(oh)
    X = np.concatenate(blocks + [rng.randn(n, 2)], axis=1)
    y = X @ rng.randn(X.shape[1]) + 0.1 * rng.randn(n)
    ds = lgb.Dataset(X, label=y)
    ds.construct()
    assert ds.efb is not None
    tabs = ds.efb_device_tables()
    f = ds.bins.shape[1]
    bins = jnp.asarray(ds.bins, jnp.int16)
    efb_t = ds.efb_bins_device_t()
    grad = jnp.asarray(2.0 * 0.3 * y, jnp.float32)
    hess = jnp.ones((n,), jnp.float32)
    ones = jnp.ones((n,), bool)
    sw = jnp.ones((n,), jnp.float32)
    fm = jnp.ones((f,), bool)
    nbpf = ds.num_bins_pf_device
    mbpf = ds.missing_bin_pf_device
    params = SplitParams(min_data_in_leaf=5.0)
    kw = dict(num_leaves=15, num_bins=ds.max_num_bins, params=params,
              leaf_tile=8, use_pallas=False)

    t_fast, lid_fast = grow_tree_fast(
        bins, grad, hess, ones, sw, fm, nbpf, mbpf,
        efb_bins=tabs[0], efb_gather=tabs[1], efb_default=tabs[2], **kw)
    t_win, lid_win = grow_tree_windowed(
        bins.T, grad, hess, ones, sw, fm, nbpf, mbpf,
        efb_bins_t=efb_t, efb_gather=tabs[1], efb_default=tabs[2], **kw)

    assert int(t_win.num_leaves) == int(t_fast.num_leaves)
    nl = int(t_fast.num_leaves)
    np.testing.assert_array_equal(
        np.asarray(t_win.split_feature[: nl - 1]),
        np.asarray(t_fast.split_feature[: nl - 1]))
    np.testing.assert_array_equal(
        np.asarray(t_win.threshold_bin[: nl - 1]),
        np.asarray(t_fast.threshold_bin[: nl - 1]))
    np.testing.assert_allclose(
        np.asarray(t_win.leaf_value[:nl]), np.asarray(t_fast.leaf_value[:nl]),
        rtol=1e-4, atol=1e-6)
    np.testing.assert_array_equal(np.asarray(lid_win), np.asarray(lid_fast))


def test_windowed_efb_quantized_matches_fast_grower():
    """The production wide-regime DEFAULT combination — int8 quantized +
    EFB bundles — must also hold tree-for-tree between the growers
    (deterministic rounding makes both paths exact int histograms; the
    unbundle's integer default-bin fill is the piece under test)."""
    import lightgbm_tpu as lgb

    rng = np.random.RandomState(7)
    n, groups = 3000, 8
    blocks = []
    for g in range(groups):
        col = rng.randint(0, 8, n)
        oh = np.zeros((n, 8))
        oh[np.arange(n), col] = 1.0
        blocks.append(oh)
    X = np.concatenate(blocks + [rng.randn(n, 2)], axis=1)
    y = X @ rng.randn(X.shape[1]) + 0.1 * rng.randn(n)
    ds = lgb.Dataset(X, label=y)
    ds.construct()
    assert ds.efb is not None
    tabs = ds.efb_device_tables()
    f = ds.bins.shape[1]
    bins = jnp.asarray(ds.bins, jnp.int16)
    grad = jnp.asarray(2.0 * 0.3 * y, jnp.float32)
    hess = jnp.ones((n,), jnp.float32)
    ones = jnp.ones((n,), bool)
    sw = jnp.ones((n,), jnp.float32)
    fm = jnp.ones((f,), bool)
    params = SplitParams(min_data_in_leaf=5.0)
    kw = dict(num_leaves=15, num_bins=ds.max_num_bins, params=params,
              leaf_tile=8, use_pallas=False)
    qkw = dict(quantize_bins=16, stochastic_rounding=False, quant_renew=True)

    t_fast, lid_fast = grow_tree_fast(
        bins, grad, hess, ones, sw, fm, ds.num_bins_pf_device,
        ds.missing_bin_pf_device,
        efb_bins=tabs[0], efb_gather=tabs[1], efb_default=tabs[2],
        **kw, **qkw)
    t_win, lid_win = grow_tree_windowed(
        bins.T, grad, hess, ones, sw, fm, ds.num_bins_pf_device,
        ds.missing_bin_pf_device,
        efb_bins_t=ds.efb_bins_device_t(), efb_gather=tabs[1],
        efb_default=tabs[2], **kw, **qkw)

    assert int(t_win.num_leaves) == int(t_fast.num_leaves)
    nl = int(t_fast.num_leaves)
    assert nl > 1
    np.testing.assert_array_equal(
        np.asarray(t_win.split_feature[: nl - 1]),
        np.asarray(t_fast.split_feature[: nl - 1]))
    np.testing.assert_allclose(
        np.asarray(t_win.leaf_value[:nl]), np.asarray(t_fast.leaf_value[:nl]),
        rtol=1e-4, atol=1e-6)
    np.testing.assert_array_equal(np.asarray(lid_win), np.asarray(lid_fast))
