"""Windowed wide-regime grower (ops/treegrow_windowed.py): the physically
partitioned, window-gathered grower must reproduce the full-pass rounds
grower tree-for-tree (same admission semantics, same split search; only
the histogram data movement differs)."""

import numpy as np
import jax.numpy as jnp
import pytest

from lightgbm_tpu.binning import DatasetBinner
from lightgbm_tpu.ops.split import SplitParams
from lightgbm_tpu.ops.treegrow_fast import grow_tree_fast
from lightgbm_tpu.ops.treegrow_windowed import grow_tree_windowed


def _inputs(n=3000, f=40, seed=0):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, f)
    y = X @ rng.randn(f) + 0.3 * rng.randn(n)
    binner = DatasetBinner.fit(X, max_bin=63)
    bins = binner.transform(X)
    grad = jnp.asarray(2.0 * (0.3 * y), jnp.float32)  # arbitrary but fixed
    hess = jnp.ones((n,), jnp.float32)
    return binner, jnp.asarray(bins, jnp.int16), grad, hess


@pytest.mark.parametrize("masked", [False, True])
def test_windowed_matches_fast_grower(masked):
    binner, bins, grad, hess = _inputs()
    n = bins.shape[0]
    rng = np.random.RandomState(1)
    row_mask = (jnp.asarray(rng.rand(n) < 0.8) if masked
                else jnp.ones((n,), bool))
    sw = jnp.ones((n,), jnp.float32)
    fm = jnp.ones((bins.shape[1],), bool)
    nbpf = jnp.asarray(binner.num_bins_per_feature)
    mbpf = jnp.asarray(binner.missing_bin_per_feature)
    params = SplitParams(min_data_in_leaf=5.0)
    kw = dict(num_leaves=31, num_bins=64, params=params, leaf_tile=8,
              use_pallas=False)

    t_fast, lid_fast = grow_tree_fast(
        bins, grad, hess, row_mask, sw, fm, nbpf, mbpf, **kw)
    t_win, lid_win = grow_tree_windowed(
        bins.T, grad, hess, row_mask, sw, fm, nbpf, mbpf, **kw)

    assert int(t_win.num_leaves) == int(t_fast.num_leaves)
    nl = int(t_fast.num_leaves)
    np.testing.assert_array_equal(
        np.asarray(t_win.split_feature[: nl - 1]),
        np.asarray(t_fast.split_feature[: nl - 1]))
    np.testing.assert_array_equal(
        np.asarray(t_win.threshold_bin[: nl - 1]),
        np.asarray(t_fast.threshold_bin[: nl - 1]))
    np.testing.assert_allclose(
        np.asarray(t_win.leaf_value[:nl]), np.asarray(t_fast.leaf_value[:nl]),
        rtol=1e-4, atol=1e-6)
    # per-row leaf assignment identical
    np.testing.assert_array_equal(np.asarray(lid_win), np.asarray(lid_fast))


def test_windowed_quantized_matches_fast_grower_quantized():
    """The windowed grower's quantized path must reproduce the fast
    grower's quantized tree TREE-FOR-TREE: with stochastic_rounding=False
    both paths discretize gradients identically (same round/clip formula),
    so the only difference is histogram data movement — the same property
    the float test above asserts."""
    binner, bins, grad, hess = _inputs(seed=3)
    n = bins.shape[0]
    ones = jnp.ones((n,), bool)
    sw = jnp.ones((n,), jnp.float32)
    fm = jnp.ones((bins.shape[1],), bool)
    nbpf = jnp.asarray(binner.num_bins_per_feature)
    mbpf = jnp.asarray(binner.missing_bin_per_feature)
    params = SplitParams(min_data_in_leaf=5.0)
    kw = dict(num_leaves=15, num_bins=64, params=params, leaf_tile=8,
              use_pallas=False)
    qkw = dict(quantize_bins=16, stochastic_rounding=False, quant_renew=True)

    t_fast, lid_fast = grow_tree_fast(
        bins, grad, hess, ones, sw, fm, nbpf, mbpf, **kw, **qkw)
    t_q, lid_q = grow_tree_windowed(
        bins.T, grad, hess, ones, sw, fm, nbpf, mbpf, **kw, **qkw)

    assert int(t_q.num_leaves) == int(t_fast.num_leaves)
    nl = int(t_fast.num_leaves)
    assert nl > 1 and np.isfinite(np.asarray(t_q.leaf_value[:nl])).all()
    np.testing.assert_array_equal(
        np.asarray(t_q.split_feature[: nl - 1]),
        np.asarray(t_fast.split_feature[: nl - 1]))
    np.testing.assert_array_equal(
        np.asarray(t_q.threshold_bin[: nl - 1]),
        np.asarray(t_fast.threshold_bin[: nl - 1]))
    np.testing.assert_allclose(
        np.asarray(t_q.leaf_value[:nl]), np.asarray(t_fast.leaf_value[:nl]),
        rtol=1e-4, atol=1e-6)
    np.testing.assert_array_equal(np.asarray(lid_q), np.asarray(lid_fast))
