"""P1 exit gate: single-host GBDT end-to-end (SURVEY.md §10.2 P1).

Modeled on the reference's test strategy: small real data + the real engine +
tolerance asserts (reference: tests/python_package_test/test_engine.py).
"""

import numpy as np
import pytest
from sklearn.datasets import load_breast_cancer, make_regression
from sklearn.metrics import roc_auc_score
from sklearn.model_selection import train_test_split

import lightgbm_tpu as lgb


@pytest.fixture(scope="module")
def breast_cancer_split():
    X, y = load_breast_cancer(return_X_y=True)
    return train_test_split(X, y, test_size=0.2, random_state=42)


def test_binary_end_to_end(breast_cancer_split):
    X_tr, X_te, y_tr, y_te = breast_cancer_split
    train = lgb.Dataset(X_tr, label=y_tr)
    params = {"objective": "binary", "num_leaves": 15, "learning_rate": 0.1,
              "verbosity": -1, "min_data_in_leaf": 5}
    bst = lgb.train(params, train, num_boost_round=30)
    pred = bst.predict(X_te)
    assert pred.shape == (len(y_te),)
    assert ((pred >= 0) & (pred <= 1)).all()
    auc = roc_auc_score(y_te, pred)
    assert auc > 0.98, auc


def test_regression_end_to_end():
    X, y = make_regression(n_samples=2000, n_features=10, noise=10.0, random_state=0)
    X_tr, X_te = X[:1600], X[1600:]
    y_tr, y_te = y[:1600], y[1600:]
    train = lgb.Dataset(X_tr, label=y_tr)
    bst = lgb.train({"objective": "regression", "verbosity": -1}, train, num_boost_round=50)
    pred = bst.predict(X_te)
    base = np.mean((y_te - y_tr.mean()) ** 2)
    mse = np.mean((y_te - pred) ** 2)
    assert mse < 0.25 * base, (mse, base)


def test_train_score_matches_predict(breast_cancer_split):
    """Training-time scores (leaf_id gather) must equal raw predict
    (tree traversal on raw values) — the threshold-roundtrip contract."""
    X_tr, _, y_tr, _ = breast_cancer_split
    train = lgb.Dataset(X_tr, label=y_tr)
    params = {"objective": "binary", "num_leaves": 31, "verbosity": -1}
    bst = lgb.train(params, train, num_boost_round=10)
    internal_score = np.asarray(bst._gbdt._score)
    raw_pred = bst.predict(X_tr, raw_score=True)
    np.testing.assert_allclose(internal_score, raw_pred, rtol=1e-4, atol=1e-4)


def test_model_save_load_roundtrip(tmp_path, breast_cancer_split):
    X_tr, X_te, y_tr, _ = breast_cancer_split
    train = lgb.Dataset(X_tr, label=y_tr)
    bst = lgb.train({"objective": "binary", "verbosity": -1}, train, num_boost_round=10)
    path = tmp_path / "model.txt"
    bst.save_model(str(path))
    loaded = lgb.Booster(model_file=str(path))
    np.testing.assert_allclose(
        bst.predict(X_te, raw_score=True), loaded.predict(X_te, raw_score=True), rtol=1e-6
    )
    # string roundtrip too
    s = bst.model_to_string()
    loaded2 = lgb.Booster.model_from_string(s)
    np.testing.assert_allclose(
        bst.predict(X_te), loaded2.predict(X_te), rtol=1e-6
    )


def test_missing_values_learned_direction():
    """NaN routing must be learned per split (reference: use_missing)."""
    rng = np.random.RandomState(0)
    n = 2000
    x = rng.randn(n, 2)
    y = (x[:, 0] > 0).astype(np.float64)
    # make x0 missing for some positives -> missing should route right (positive)
    miss = rng.rand(n) < 0.3
    x[miss & (y > 0), 0] = np.nan
    train = lgb.Dataset(x, label=y)
    bst = lgb.train({"objective": "binary", "num_leaves": 7, "verbosity": -1},
                    train, num_boost_round=20)
    x_test = np.array([[np.nan, 0.0]])
    p = bst.predict(x_test)
    assert p[0] > 0.5


def test_early_stopping(breast_cancer_split):
    X_tr, X_te, y_tr, y_te = breast_cancer_split
    train = lgb.Dataset(X_tr, label=y_tr)
    valid = lgb.Dataset(X_te, label=y_te, reference=train)
    bst = lgb.train(
        {"objective": "binary", "metric": ["binary_logloss"], "verbosity": -1},
        train, num_boost_round=200, valid_sets=[valid],
        callbacks=[lgb.early_stopping(5, verbose=False)],
    )
    assert bst.best_iteration < 200
    assert bst.best_score["valid_0"]["binary_logloss"] < 0.2


def test_record_and_log_evaluation(breast_cancer_split):
    X_tr, X_te, y_tr, y_te = breast_cancer_split
    train = lgb.Dataset(X_tr, label=y_tr)
    valid = lgb.Dataset(X_te, label=y_te, reference=train)
    record = {}
    bst = lgb.train(
        {"objective": "binary", "metric": ["auc", "binary_logloss"], "verbosity": -1},
        train, num_boost_round=10, valid_sets=[valid],
        callbacks=[lgb.record_evaluation(record)],
    )
    assert "valid_0" in record
    assert len(record["valid_0"]["auc"]) == 10
    assert record["valid_0"]["auc"][-1] > 0.95


def test_multiclass():
    from sklearn.datasets import load_iris

    X, y = load_iris(return_X_y=True)
    train = lgb.Dataset(X, label=y)
    bst = lgb.train(
        {"objective": "multiclass", "num_class": 3, "verbosity": -1, "min_data_in_leaf": 5},
        train, num_boost_round=20,
    )
    pred = bst.predict(X)
    assert pred.shape == (len(y), 3)
    np.testing.assert_allclose(pred.sum(axis=1), 1.0, rtol=1e-5)
    acc = (np.argmax(pred, axis=1) == y).mean()
    assert acc > 0.95


def test_feature_importance(breast_cancer_split):
    X_tr, _, y_tr, _ = breast_cancer_split
    train = lgb.Dataset(X_tr, label=y_tr)
    bst = lgb.train({"objective": "binary", "verbosity": -1}, train, num_boost_round=5)
    imp_split = bst.feature_importance("split")
    imp_gain = bst.feature_importance("gain")
    assert imp_split.shape == (X_tr.shape[1],)
    assert imp_split.sum() > 0
    assert imp_gain.sum() > 0


def test_bagging_and_feature_fraction(breast_cancer_split):
    X_tr, X_te, y_tr, y_te = breast_cancer_split
    train = lgb.Dataset(X_tr, label=y_tr)
    bst = lgb.train(
        {"objective": "binary", "bagging_fraction": 0.5, "bagging_freq": 1,
         "feature_fraction": 0.5, "verbosity": -1},
        train, num_boost_round=30,
    )
    auc = roc_auc_score(y_te, bst.predict(X_te))
    assert auc > 0.97, auc


def test_lambda_regularization_shrinks_outputs(breast_cancer_split):
    X_tr, _, y_tr, _ = breast_cancer_split
    train1 = lgb.Dataset(X_tr, label=y_tr)
    train2 = lgb.Dataset(X_tr, label=y_tr)
    b1 = lgb.train({"objective": "binary", "lambda_l2": 0.0, "verbosity": -1}, train1, 5)
    b2 = lgb.train({"objective": "binary", "lambda_l2": 100.0, "verbosity": -1}, train2, 5)
    lv1 = np.abs(np.concatenate([t.leaf_value for t in b1._gbdt.models[1:]]))
    lv2 = np.abs(np.concatenate([t.leaf_value for t in b2._gbdt.models[1:]]))
    assert lv2.mean() < lv1.mean()
