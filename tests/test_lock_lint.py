"""Concurrency-layer (L1-L5) rule self-tests: positive / negative /
pragma-suppressed fixture snippets per rule, mirroring
tests/test_jaxlint_rules.py so a rule regression is caught independently
of the package's own code — plus the package-wide locks-layer gate and
the ``--locks`` CLI exit-code contract."""

import textwrap
from pathlib import Path

from lightgbm_tpu.analysis import run
from lightgbm_tpu.analysis.__main__ import main
from lightgbm_tpu.analysis.core import RULES

PKG_DIR = Path(__file__).resolve().parent.parent / "lightgbm_tpu"
LOCK_RULES = ["L1", "L2", "L3", "L4", "L5"]


def _scan(tmp_path, sources, rules=None):
    """sources: {filename: code} written into one scanned root."""
    root = tmp_path / "fixture_pkg"
    root.mkdir()
    for name, code in sources.items():
        (root / name).write_text(textwrap.dedent(code))
    return run([root], rules)


# ---------------------------------------------------------------------------
# L1 lock-order-inversion
# ---------------------------------------------------------------------------

def test_l1_positive_reversed_with_nesting(tmp_path):
    rep = _scan(tmp_path, {"mod.py": """
        import threading

        _a = threading.Lock()
        _b = threading.Lock()

        def f():
            with _a:
                with _b:
                    pass

        def g():
            with _b:
                with _a:
                    pass
    """}, rules=["L1"])
    assert len(rep.findings) == 1, rep.findings
    assert rep.findings[0].rule == "L1"
    assert "inversion" in rep.findings[0].message


def test_l1_negative_consistent_order(tmp_path):
    rep = _scan(tmp_path, {"mod.py": """
        import threading

        _a = threading.Lock()
        _b = threading.Lock()

        def f():
            with _a:
                with _b:
                    pass

        def g():
            with _a:
                with _b:
                    pass
    """}, rules=["L1"])
    assert rep.findings == []


def test_l1_positive_inversion_through_a_call(tmp_path):
    """f holds _a and calls helper() which acquires _b; g nests the other
    way — the edge collector sees one level of resolvable calls."""
    rep = _scan(tmp_path, {"mod.py": """
        import threading

        _a = threading.Lock()
        _b = threading.Lock()

        def helper():
            with _b:
                pass

        def f():
            with _a:
                helper()

        def g():
            with _b:
                with _a:
                    pass
    """}, rules=["L1"])
    assert len(rep.findings) == 1, rep.findings
    assert rep.findings[0].rule == "L1"


def test_l1_negative_reentrant_same_lock(tmp_path):
    """Nested acquisition of the SAME lock is reentrancy (rlock) or a
    plain bug, not an order inversion — no self-edges."""
    rep = _scan(tmp_path, {"mod.py": """
        import threading

        _a = threading.RLock()

        def f():
            with _a:
                with _a:
                    pass
    """}, rules=["L1"])
    assert rep.findings == []


def test_l1_positive_instance_attr_locks(tmp_path):
    rep = _scan(tmp_path, {"mod.py": """
        import threading

        class C:
            def __init__(self):
                self._a = threading.Lock()
                self._b = threading.Lock()

            def m1(self):
                with self._a:
                    with self._b:
                        pass

            def m2(self):
                with self._b:
                    with self._a:
                        pass
    """}, rules=["L1"])
    assert len(rep.findings) == 1, rep.findings
    assert rep.findings[0].rule == "L1"


def test_l1_pragma_suppressed(tmp_path):
    rep = _scan(tmp_path, {"mod.py": """
        import threading

        _a = threading.Lock()
        _b = threading.Lock()

        def f():
            with _a:
                with _b:  # jaxlint: disable=L1 (fixture: documented order exception)
                    pass

        def g():
            with _b:
                with _a:
                    pass
    """}, rules=["L1"])
    assert rep.findings == [], rep.findings
    assert len(rep.suppressed) == 1


# ---------------------------------------------------------------------------
# L2 blocking-call-under-lock
# ---------------------------------------------------------------------------

def test_l2_positive_open_under_lock(tmp_path):
    rep = _scan(tmp_path, {"mod.py": """
        import threading

        _lock = threading.Lock()

        def dump(payload):
            with _lock:
                with open("/tmp/x", "w") as fh:
                    fh.write(payload)
    """}, rules=["L2"])
    assert any("open" in f.message for f in rep.findings), rep.findings
    assert all(f.rule == "L2" for f in rep.findings)


def test_l2_positive_device_sync_under_lock(tmp_path):
    rep = _scan(tmp_path, {"mod.py": """
        import threading
        import numpy as np

        _lock = threading.Lock()

        def pull(x):
            with _lock:
                host = np.asarray(x)
                x.block_until_ready()
            return host
    """}, rules=["L2"])
    assert len(rep.findings) == 2, rep.findings
    assert any("device sync" in f.message for f in rep.findings)


def test_l2_positive_subprocess_and_sleep_under_lock(tmp_path):
    rep = _scan(tmp_path, {"mod.py": """
        import subprocess
        import threading
        import time

        _lock = threading.Lock()

        def build():
            with _lock:
                subprocess.run(["make"])
                time.sleep(1.0)
    """}, rules=["L2"])
    assert len(rep.findings) == 2, rep.findings


def test_l2_negative_io_outside_lock(tmp_path):
    rep = _scan(tmp_path, {"mod.py": """
        import threading

        _lock = threading.Lock()

        def dump(payload):
            with _lock:
                snap = list(payload)
            with open("/tmp/x", "w") as fh:
                fh.write("".join(snap))
    """}, rules=["L2"])
    assert rep.findings == []


def test_l2_positive_private_callee_inherits_held(tmp_path):
    """A private helper called only from under-lock sites is analyzed in
    its caller's context."""
    rep = _scan(tmp_path, {"mod.py": """
        import threading

        _lock = threading.Lock()

        def f():
            with _lock:
                _helper()

        def _helper():
            open("/tmp/x")
    """}, rules=["L2"])
    assert len(rep.findings) == 1, rep.findings
    assert "open" in rep.findings[0].message


def test_l2_negative_public_callee_open_world(tmp_path):
    """Public functions never inherit caller held sets: external callers
    the index cannot see may call them lock-free."""
    rep = _scan(tmp_path, {"mod.py": """
        import threading

        _lock = threading.Lock()

        def f():
            with _lock:
                helper()

        def helper():
            open("/tmp/x")
    """}, rules=["L2"])
    assert rep.findings == []


def test_l2_pragma_suppressed_with_reason(tmp_path):
    rep = _scan(tmp_path, {"mod.py": """
        import threading

        _io_lock = threading.Lock()

        def dump(fh, payload):
            with _io_lock:
                fh.write(payload)  # jaxlint: disable=L2 (fixture: dedicated IO leaf lock)
    """}, rules=["L2"])
    assert rep.findings == []
    assert len(rep.suppressed) == 1
    assert rep.suppressed[0][1].reason == "fixture: dedicated IO leaf lock"


# ---------------------------------------------------------------------------
# L3 unguarded-shared-mutation
# ---------------------------------------------------------------------------

def test_l3_positive_bare_minority_site(tmp_path):
    rep = _scan(tmp_path, {"mod.py": """
        import threading

        class C:
            def __init__(self):
                self._lk = threading.Lock()
                self.n = 0

            def inc(self):
                with self._lk:
                    self.n += 1

            def inc2(self):
                with self._lk:
                    self.n += 2

            def racy(self):
                self.n = 0
    """}, rules=["L3"])
    assert len(rep.findings) == 1, rep.findings
    assert rep.findings[0].rule == "L3"
    assert "no lock held" in rep.findings[0].message


def test_l3_negative_majority_bare(tmp_path):
    """One incidental under-lock store among many bare single-thread
    stores does not make the attribute 'guarded' (majority vote)."""
    rep = _scan(tmp_path, {"mod.py": """
        import threading

        class C:
            def __init__(self):
                self._lk = threading.Lock()
                self.n = 0

            def locked_once(self):
                with self._lk:
                    self.n += 1

            def trainer_a(self):
                self.n += 1

            def trainer_b(self):
                self.n += 1
    """}, rules=["L3"])
    assert rep.findings == []


def test_l3_negative_ctor_exempt(tmp_path):
    """__init__/__setstate__ run pre-publication — their stores are not
    race candidates."""
    rep = _scan(tmp_path, {"mod.py": """
        import threading

        class C:
            def __init__(self):
                self._lk = threading.Lock()
                self.n = 0

            def __setstate__(self, d):
                self.n = d["n"]

            def inc(self):
                with self._lk:
                    self.n += 1
    """}, rules=["L3"])
    assert rep.findings == []


def test_l3_positive_mutator_method_call(tmp_path):
    rep = _scan(tmp_path, {"mod.py": """
        import threading

        class C:
            def __init__(self):
                self._lk = threading.Lock()
                self.items = []

            def add(self, x):
                with self._lk:
                    self.items.append(x)

            def add2(self, x):
                with self._lk:
                    self.items.append(x)

            def racy(self, x):
                self.items.append(x)
    """}, rules=["L3"])
    assert len(rep.findings) == 1, rep.findings
    assert "items" in rep.findings[0].message


def test_l3_positive_declared_global(tmp_path):
    rep = _scan(tmp_path, {"mod.py": """
        import threading

        _lock = threading.Lock()
        _count = 0

        def inc():
            global _count
            with _lock:
                _count += 1

        def inc2():
            global _count
            with _lock:
                _count += 1

        def racy():
            global _count
            _count = 0
    """}, rules=["L3"])
    assert len(rep.findings) == 1, rep.findings
    assert "_count" in rep.findings[0].message


def test_l3_pragma_suppressed(tmp_path):
    rep = _scan(tmp_path, {"mod.py": """
        import threading

        class C:
            def __init__(self):
                self._lk = threading.Lock()
                self.n = 0

            def inc(self):
                with self._lk:
                    self.n += 1

            def single_thread_phase(self):
                self.n = 0  # jaxlint: disable=L3 (fixture: setup phase, single-threaded)
    """}, rules=["L3"])
    assert rep.findings == []
    assert len(rep.suppressed) == 1


# ---------------------------------------------------------------------------
# L4 wait-without-predicate-loop
# ---------------------------------------------------------------------------

def test_l4_positive_bare_wait(tmp_path):
    rep = _scan(tmp_path, {"mod.py": """
        import threading

        class C:
            def __init__(self):
                self._cv = threading.Condition()
                self.ready = False

            def block(self):
                with self._cv:
                    self._cv.wait()
    """}, rules=["L4"])
    assert len(rep.findings) == 1, rep.findings
    assert rep.findings[0].rule == "L4"


def test_l4_positive_if_guarded_wait(tmp_path):
    """A bare if around the wait still loses to spurious wakeups — only a
    while re-checks the predicate."""
    rep = _scan(tmp_path, {"mod.py": """
        import threading

        class C:
            def __init__(self):
                self._cv = threading.Condition()
                self.ready = False

            def block(self):
                with self._cv:
                    if not self.ready:
                        self._cv.wait()
    """}, rules=["L4"])
    assert len(rep.findings) == 1, rep.findings


def test_l4_negative_while_wrapped_wait(tmp_path):
    rep = _scan(tmp_path, {"mod.py": """
        import threading

        class C:
            def __init__(self):
                self._cv = threading.Condition()
                self.ready = False

            def block(self):
                with self._cv:
                    while not self.ready:
                        self._cv.wait(timeout=1.0)
    """}, rules=["L4"])
    assert rep.findings == []


def test_l4_negative_wait_for(tmp_path):
    rep = _scan(tmp_path, {"mod.py": """
        import threading

        class C:
            def __init__(self):
                self._cv = threading.Condition()
                self.ready = False

            def block(self):
                with self._cv:
                    self._cv.wait_for(lambda: self.ready)
    """}, rules=["L4"])
    assert rep.findings == []


def test_l4_positive_module_level_condition(tmp_path):
    rep = _scan(tmp_path, {"mod.py": """
        import threading

        _cv = threading.Condition()

        def block():
            with _cv:
                _cv.wait()
    """}, rules=["L4"])
    assert len(rep.findings) == 1, rep.findings


def test_l4_pragma_suppressed(tmp_path):
    rep = _scan(tmp_path, {"mod.py": """
        import threading

        class C:
            def __init__(self):
                self._cv = threading.Condition()

            def block(self):
                with self._cv:
                    self._cv.wait(timeout=0.5)  # jaxlint: disable=L4 (fixture: timeout-bounded poll, predicate re-checked by caller)
    """}, rules=["L4"])
    assert rep.findings == []
    assert len(rep.suppressed) == 1


# ---------------------------------------------------------------------------
# L5 orphan-thread
# ---------------------------------------------------------------------------

def test_l5_positive_orphan_instance_thread(tmp_path):
    rep = _scan(tmp_path, {"mod.py": """
        import threading

        class C:
            def start(self):
                self._t = threading.Thread(target=self._run, daemon=True)
                self._t.start()

            def _run(self):
                pass
    """}, rules=["L5"])
    assert len(rep.findings) == 1, rep.findings
    assert rep.findings[0].rule == "L5"
    assert "_t" in rep.findings[0].message


def test_l5_positive_orphan_local_thread(tmp_path):
    rep = _scan(tmp_path, {"mod.py": """
        import threading

        def fire_and_forget(fn):
            t = threading.Thread(target=fn)
            t.start()
    """}, rules=["L5"])
    assert len(rep.findings) == 1, rep.findings


def test_l5_negative_joined_in_stop(tmp_path):
    rep = _scan(tmp_path, {"mod.py": """
        import threading

        class C:
            def start(self):
                self._t = threading.Thread(target=self._run)
                self._t.start()

            def stop(self):
                self._t.join(timeout=5)

            def _run(self):
                pass
    """}, rules=["L5"])
    assert rep.findings == []


def test_l5_negative_swap_join_idiom(tmp_path):
    """stop() swaps the handle to a local before joining (the idiom that
    makes stop() idempotent under concurrent callers)."""
    rep = _scan(tmp_path, {"mod.py": """
        import threading

        class C:
            def start(self):
                self._t = threading.Thread(target=self._run)
                self._t.start()

            def stop(self):
                t, self._t = self._t, None
                if t is not None:
                    t.join(timeout=5)

            def _run(self):
                pass
    """}, rules=["L5"])
    assert rep.findings == []


def test_l5_negative_stop_event_pattern(tmp_path):
    rep = _scan(tmp_path, {"mod.py": """
        import threading

        class C:
            def start(self):
                self._stop = threading.Event()
                self._t = threading.Thread(target=self._run, daemon=True)
                self._t.start()

            def stop(self):
                self._stop.set()

            def _run(self):
                while not self._stop.is_set():
                    pass
    """}, rules=["L5"])
    assert rep.findings == []


def test_l5_pragma_suppressed(tmp_path):
    rep = _scan(tmp_path, {"mod.py": """
        import threading

        def fire_and_forget(fn):
            t = threading.Thread(target=fn, daemon=True)  # jaxlint: disable=L5 (fixture: process-lifetime daemon by design)
            t.start()
    """}, rules=["L5"])
    assert rep.findings == []
    assert len(rep.suppressed) == 1


# ---------------------------------------------------------------------------
# registry + package gate + CLI contract
# ---------------------------------------------------------------------------

def test_lock_rules_registered_under_locks_layer():
    for rid in LOCK_RULES:
        assert rid in RULES, rid
        assert RULES[rid].layer == "locks", rid
    # the R layer stayed where it was
    assert RULES["R1"].layer == "ast"


def test_package_locks_layer_is_clean():
    """The tier-1 pin for the acceptance bar: zero unwaived L findings on
    the package itself (intentional sites carry reasoned pragmas)."""
    report = run([PKG_DIR], LOCK_RULES)
    assert report.findings == [], [f.format() for f in report.findings]
    assert report.ok


def test_locks_cli_exit_codes(capsys):
    assert main(["--locks", str(PKG_DIR)]) == 0
    capsys.readouterr()
    # --locks selects a whole layer; mixing with other selectors is usage
    # error, same contract as --jaxpr
    assert main(["--locks", "--jaxpr"]) == 2
    assert main(["--locks", "--rules", "L1"]) == 2
    assert main(["--locks", "--list-contracts"]) == 2
    capsys.readouterr()


def test_locks_cli_reports_findings_rc1(tmp_path, capsys):
    bad = tmp_path / "badpkg"
    bad.mkdir()
    (bad / "mod.py").write_text(textwrap.dedent("""
        import threading

        _a = threading.Lock()
        _b = threading.Lock()

        def f():
            with _a:
                with _b:
                    pass

        def g():
            with _b:
                with _a:
                    pass
    """))
    assert main(["--locks", str(bad)]) == 1
    out = capsys.readouterr().out
    assert "L1" in out
