"""Booster-fleet training (round 21): B independent boosters per
dispatch (ops/treegrow_fleet.py + models/fleet.py + lgb.train_fleet).

The parity bar (ISSUE 17 acceptance): EVERY lane of a B=64 fleet is
BITWISE identical to the same model trained alone through the
single-model windowed grower — tree arrays field by field AND the final
raw scores — float and int8-quantized.  The fleet's W ladder floors at
8192/B per lane (the batch-total live window is what the solo 8192
compile-cost floor bounds), so the pin also proves the grown trees are
bitwise invariant to the window floor.  The warm per-round budget
(1 dispatch / 0 syncs / 0 retraces at any B) lives in test_retrace.py.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import lightgbm_tpu as lgb
from lightgbm_tpu import FleetError
from lightgbm_tpu.config import Config
from lightgbm_tpu.models.gbdt import GBDT
from lightgbm_tpu.objectives import create_objective
from lightgbm_tpu.ops.treegrow_windowed import grow_tree_windowed

PARAMS = {"objective": "binary", "num_leaves": 7, "verbosity": -1,
          "min_data_in_leaf": 5, "seed": 3}

FIELDS = ("num_leaves", "split_feature", "threshold_bin", "leaf_value",
          "left_child", "right_child", "default_left", "split_gain")


def _data(b, n, f, seed=0):
    rng = np.random.RandomState(seed)
    return rng.rand(n, f), (rng.rand(b, n) > 0.5).astype(np.float64)


def _solo(X, label, params, rounds):
    """One model through the exact solo op sequence the fleet vmaps:
    objective prepare/boost_from_score, then per round gradients ->
    windowed grower (8192 floor) -> score update.  Returns the per-round
    TreeArrays and the final raw score."""
    cfg = Config.from_dict(dict(params))
    ds = lgb.Dataset(X, label=label, params={"verbosity": -1})
    proto = GBDT(cfg, ds, objective=create_objective(cfg))
    n = X.shape[0]
    quant = bool(cfg.use_quantized_grad)
    obj = create_objective(cfg)
    if hasattr(obj, "prepare"):
        obj.prepare(label, None)
    init = float(obj.boost_from_score(jnp.asarray(label, jnp.float32), None))
    score = jnp.asarray(np.zeros(n, np.float32) + np.float32(init))
    lab_d = jnp.asarray(label, jnp.float32)
    rm = jnp.ones((n,), bool)
    sw = jnp.ones((n,), jnp.float32)
    iters = []
    for it in range(rounds):
        g, h = obj.get_gradients(score, lab_d, None)
        qk = (jax.random.PRNGKey(cfg.seed * 1000003 + it * 31)
              if quant else None)
        arrays, leaf_id = grow_tree_windowed(
            ds.bins_device_t(), g, h, rm, sw, proto._allowed_features,
            ds.num_bins_pf_device, ds.missing_bin_pf_device, None, qk,
            None, None, None, None, None,
            num_leaves=cfg.num_leaves, num_bins=ds.max_num_bins,
            max_depth=cfg.max_depth, params=proto._split_params,
            leaf_tile=proto._leaf_tile(ds),
            hist_precision=cfg.hist_precision, use_pallas=False,
            quantize_bins=(cfg.num_grad_quant_bins if quant else 0),
            stochastic_rounding=bool(cfg.stochastic_rounding),
            quant_renew=bool(cfg.quant_train_renew_leaf))
        score = score + (arrays.leaf_value
                         * jnp.float32(cfg.learning_rate))[leaf_id]
        iters.append(arrays)
    return iters, np.asarray(score)


def _assert_lane_bitwise(fb, lane, iters, score, rounds):
    for it in range(rounds):
        fl = fb._host_iter(it)
        for fld in FIELDS:
            a = np.asarray(getattr(iters[it], fld))
            f = getattr(fl, fld)[lane]
            assert np.array_equal(a, f, equal_nan=True), (
                f"lane {lane} iter {it} field {fld} diverged from solo")
    assert np.array_equal(np.asarray(fb._score[lane]), score), (
        f"lane {lane} final score diverged from solo")


@pytest.mark.parametrize("quant", [False, True], ids=["float", "int8"])
def test_b64_fleet_bitwise_equals_solo_grower(quant):
    """ISSUE 17 acceptance: every model in a B=64 batch bitwise == its
    solo windowed-grower run, float AND int8-quantized."""
    B, N, F, R = 64, 300, 6, 3 if not quant else 2
    params = dict(PARAMS)
    if quant:
        params.update(use_quantized_grad=True, num_grad_quant_bins=16)
    X, labels = _data(B, N, F)
    ds = lgb.Dataset(X, label=labels[0], params={"verbosity": -1})
    fb = lgb.train_fleet(dict(params), ds, labels, num_boost_round=R)
    for lane in range(B):
        iters, score = _solo(X, labels[lane], params, R)
        _assert_lane_bitwise(fb, lane, iters, score, R)


def test_weighted_fleet_bitwise_equals_solo_and_weights_flow():
    """Per-lane (B, N) sample weights reach each lane's gradients: the
    weighted fleet matches the weighted solo run bitwise and differs
    from the unweighted one."""
    B, N, F, R = 4, 250, 5, 2
    X, labels = _data(B, N, F, seed=11)
    rng = np.random.RandomState(12)
    weights = 0.25 + rng.rand(B, N)
    ds = lgb.Dataset(X, label=labels[0], params={"verbosity": -1})
    fb = lgb.train_fleet(dict(PARAMS), ds, labels, num_boost_round=R,
                         weights=weights)
    for lane in range(B):
        ds1 = lgb.Dataset(X, label=labels[lane], params={"verbosity": -1})
        solo = lgb.train_fleet(dict(PARAMS), ds1, labels[lane:lane + 1],
                               num_boost_round=R,
                               weights=weights[lane:lane + 1])
        Q = X[:64]
        assert np.array_equal(
            fb.booster(lane).predict(Q, raw_score=True),
            solo.booster(0).predict(Q, raw_score=True))
    ds1 = lgb.Dataset(X, label=labels[0], params={"verbosity": -1})
    unw = lgb.train_fleet(dict(PARAMS), ds1, labels[0:1], num_boost_round=R)
    assert not np.array_equal(
        fb.booster(0).predict(X[:64], raw_score=True),
        unw.booster(0).predict(X[:64], raw_score=True)), (
        "weights did not flow into lane gradients")


def test_per_lane_rounds_early_stop_device_side():
    """``rounds`` gives per-lane budgets: finished lanes ride as no-op
    lanes (no host-loop exit), each lane exports exactly its budgeted
    tree count, and budgeted lanes stay bitwise equal to solo runs of
    the same length."""
    B, N, F = 4, 250, 5
    rounds = [1, 4, 2, 4]
    X, labels = _data(B, N, F, seed=21)
    ds = lgb.Dataset(X, label=labels[0], params={"verbosity": -1})
    fb = lgb.train_fleet(dict(PARAMS), ds, labels, num_boost_round=4,
                         rounds=rounds)
    assert list(fb.num_iterations) == rounds
    for lane in range(B):
        bst = fb.booster(lane)
        assert bst.num_trees() == rounds[lane]
        iters, _ = _solo(X, labels[lane], PARAMS, rounds[lane])
        for it in range(rounds[lane]):
            fl = fb._host_iter(it)
            for fld in FIELDS:
                assert np.array_equal(np.asarray(getattr(iters[it], fld)),
                                      getattr(fl, fld)[lane],
                                      equal_nan=True)


def test_lane_boosters_serve_and_round_trip():
    """Per-lane Booster handles behave like standard boosters: predict
    matches a host walk of the lane's trees + init, model_to_string
    round-trips through Booster(model_str=...) with identical
    predictions."""
    B, N, F, R = 3, 300, 6, 3
    X, labels = _data(B, N, F, seed=31)
    ds = lgb.Dataset(X, label=labels[0], params={"verbosity": -1})
    fb = lgb.train_fleet(dict(PARAMS), ds, labels, num_boost_round=R)
    Q = np.random.RandomState(32).rand(80, F)
    for lane in range(B):
        bst = fb.booster(lane)
        got = bst.predict(Q, raw_score=True)
        assert got.shape == (80,)
        reloaded = lgb.Booster(model_str=bst.model_to_string())
        np.testing.assert_allclose(
            reloaded.predict(Q, raw_score=True), got, rtol=0, atol=1e-6)
        with pytest.raises(FleetError):
            bst._gbdt.train_one_iter()


def test_envelope_and_shape_refusals():
    """Out-of-envelope configs refuse loudly BEFORE any device work, and
    fleet_size acts as a shape guard."""
    B, N, F = 2, 120, 4
    X, labels = _data(B, N, F, seed=41)

    def fleet(params, **kw):
        ds = lgb.Dataset(X, label=labels[0], params={"verbosity": -1})
        return lgb.train_fleet(params, ds, labels, num_boost_round=2, **kw)

    with pytest.raises(FleetError, match="multiclass"):
        fleet({"objective": "multiclass", "num_class": 3, "verbosity": -1})
    with pytest.raises(FleetError, match="GOSS"):
        fleet(dict(PARAMS, data_sample_strategy="goss"))
    with pytest.raises(FleetError, match="monotone"):
        fleet(dict(PARAMS, monotone_constraints=[1, 0, 0, 0]))
    with pytest.raises(FleetError, match="feature sampling"):
        fleet(dict(PARAMS, feature_fraction=0.5))
    with pytest.raises(FleetError, match="fleet_size"):
        fleet(dict(PARAMS, fleet_size=B + 1))
    # matching fleet_size passes the guard
    fb = fleet(dict(PARAMS, fleet_size=B))
    assert fb.booster(0).num_trees() == 2
