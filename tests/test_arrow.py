"""Arrow ingestion without the pandas hop (reference:
include/LightGBM/arrow.h + LGBM_DatasetCreateFromArrow /
LGBM_DatasetSetFieldFromArrow / LGBM_BoosterPredictForArrow in
src/c_api.cpp)."""

import ctypes
import sys

import numpy as np
import pytest

import lightgbm_tpu as lgb

pa = pytest.importorskip("pyarrow")


def _data(n=600, f=4, seed=3):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, f)
    y = ((X @ rng.randn(f)) > 0).astype(np.float64)
    return X, y


def _model(X_or_table, y, **params):
    ds = lgb.Dataset(X_or_table, label=y)
    p = dict(objective="binary", num_leaves=7, verbosity=-1, **params)
    bst = lgb.Booster(params=p, train_set=ds)
    for _ in range(3):
        bst.update()
    return bst


def test_table_matches_numpy_no_pandas(monkeypatch):
    X, y = _data()
    table = pa.table({f"Column_{i}": X[:, i] for i in range(X.shape[1])})
    # prove the conversion path does not fall back to pandas
    monkeypatch.setitem(sys.modules, "pandas", None)
    bst_arrow = _model(table, y)
    monkeypatch.undo()
    bst_np = _model(X, y)
    assert bst_arrow.model_to_string() == bst_np.model_to_string()


def test_nulls_become_missing():
    X, y = _data()
    Xn = X.copy()
    Xn[::7, 1] = np.nan
    cols = {}
    for i in range(X.shape[1]):
        v = Xn[:, i]
        cols[f"Column_{i}"] = pa.array(
            [None if np.isnan(x) else x for x in v], type=pa.float64())
    table = pa.table(cols)
    assert table.column(1).null_count > 0
    bst_arrow = _model(table, y, use_missing=True)
    bst_np = _model(Xn, y, use_missing=True)
    assert bst_arrow.model_to_string() == bst_np.model_to_string()


def test_dictionary_column_uses_codes():
    X, y = _data()
    cats = np.array(["a", "b", "c"])[
        (np.abs(X[:, 0] * 3).astype(int) % 3)]
    dict_col = pa.array(cats).dictionary_encode()
    codes = dict_col.indices.to_numpy(zero_copy_only=False).astype(np.float64)
    table = pa.table({"Column_0": dict_col,
                      **{f"Column_{i}": X[:, i] for i in range(1, X.shape[1])}})
    Xc = np.column_stack([codes, X[:, 1:]])
    bst_arrow = _model(table, y, categorical_feature=[0])
    bst_np = _model(Xc, y, categorical_feature=[0])
    assert bst_arrow.model_to_string() == bst_np.model_to_string()


def test_multichunk_and_int_columns():
    X, y = _data()
    Xi = np.round(X * 10).astype(np.int64)
    batches = [
        pa.record_batch({f"Column_{i}": Xi[lo:lo + 200, i]
                         for i in range(X.shape[1])})
        for lo in range(0, len(y), 200)
    ]
    table = pa.Table.from_batches(batches)
    assert table.column(0).num_chunks == 3
    bst_arrow = _model(table, y)
    bst_np = _model(Xi.astype(np.float64), y)
    assert bst_arrow.model_to_string() == bst_np.model_to_string()


def test_chunked_dictionary_unifies_codes():
    # per-chunk dictionaries with different category orders must unify
    # before their codes are used as categorical values
    c1 = pa.array(["a", "b"]).dictionary_encode()
    c2 = pa.array(["b", "a"]).dictionary_encode()
    col = pa.chunked_array([c1, c2])
    table = pa.table({"Column_0": col})
    from lightgbm_tpu.basic import _arrow_to_2d

    vals = _arrow_to_2d(table)[:, 0]
    assert vals[0] == vals[3] and vals[1] == vals[2] and vals[0] != vals[1]


@pytest.mark.slow
def test_c_api_arrow_roundtrip():
    from test_c_api import _build

    lib = ctypes.CDLL(_build())
    lib.LGBM_GetLastError.restype = ctypes.c_char_p

    X, y = _data()
    batch = pa.record_batch({f"f{i}": X[:, i] for i in range(X.shape[1])})

    # export through the C data interface structs, as a real C caller would
    c_arr = (ctypes.c_uint8 * 80)()   # struct ArrowArray (spec: 80 bytes)
    c_schema = (ctypes.c_uint8 * 72)()  # struct ArrowSchema
    batch._export_to_c(ctypes.addressof(c_arr), ctypes.addressof(c_schema))

    h = ctypes.c_void_p()
    rc = lib.LGBM_DatasetCreateFromArrow(
        ctypes.c_int64(1), ctypes.byref(c_arr), ctypes.byref(c_schema),
        b"max_bin=63", None, ctypes.byref(h))
    assert rc == 0, lib.LGBM_GetLastError()

    lab = pa.array(y, type=pa.float64())
    la = (ctypes.c_uint8 * 80)()
    ls = (ctypes.c_uint8 * 72)()
    lab._export_to_c(ctypes.addressof(la), ctypes.addressof(ls))
    rc = lib.LGBM_DatasetSetFieldFromArrow(
        h, b"label", ctypes.c_int64(1), ctypes.byref(la), ctypes.byref(ls))
    assert rc == 0, lib.LGBM_GetLastError()

    bh = ctypes.c_void_p()
    rc = lib.LGBM_BoosterCreate(
        h, b"objective=binary num_leaves=7 verbosity=-1", ctypes.byref(bh))
    assert rc == 0, lib.LGBM_GetLastError()
    fin = ctypes.c_int()
    for _ in range(3):
        assert lib.LGBM_BoosterUpdateOneIter(bh, ctypes.byref(fin)) == 0

    # PredictForArrow == PredictForMat
    pa_out = np.zeros(len(y))
    n = ctypes.c_int64()
    batch2 = pa.record_batch({f"f{i}": X[:, i] for i in range(X.shape[1])})
    a2 = (ctypes.c_uint8 * 80)()
    s2 = (ctypes.c_uint8 * 72)()
    batch2._export_to_c(ctypes.addressof(a2), ctypes.addressof(s2))
    rc = lib.LGBM_BoosterPredictForArrow(
        bh, ctypes.c_int64(1), ctypes.byref(a2), ctypes.byref(s2), 0,
        0, -1, b"", ctypes.byref(n),
        pa_out.ctypes.data_as(ctypes.POINTER(ctypes.c_double)))
    assert rc == 0, lib.LGBM_GetLastError()

    mat_out = np.zeros(len(y))
    Xc = np.ascontiguousarray(X, np.float64)
    rc = lib.LGBM_BoosterPredictForMat(
        bh, Xc.ctypes.data_as(ctypes.c_void_p), 1, X.shape[0],
        X.shape[1], 1, 0, 0, -1, b"", ctypes.byref(n),
        mat_out.ctypes.data_as(ctypes.POINTER(ctypes.c_double)))
    assert rc == 0, lib.LGBM_GetLastError()
    np.testing.assert_allclose(pa_out, mat_out, rtol=1e-12)
    lib.LGBM_BoosterFree(bh)
    lib.LGBM_DatasetFree(h)
