"""Tests that previously-no-op parameters now change behavior:
path_smooth, monotone_penalty, CEGB, snapshot_freq, pred_early_stop,
lambdarank position bias.  (VERDICT round 1, items 7/9/10.)"""

import os

import numpy as np
import pytest

import lightgbm_tpu as lgb

pytestmark = pytest.mark.slow


def _data(n=4000, f=8, seed=0):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, f).astype(np.float32)
    w = rng.randn(f)
    y = X @ w + 0.3 * rng.randn(n)
    return X, y


def _train(params, X, y, rounds=5):
    base = {"objective": "regression", "num_leaves": 15, "verbosity": -1,
            "min_data_in_leaf": 5}
    base.update(params)
    ds = lgb.Dataset(X, label=y)
    bst = lgb.Booster(params=base, train_set=ds)
    for _ in range(rounds):
        bst.update()
    return bst


@pytest.mark.parametrize("mode", ["strict", "rounds"])
def test_path_smooth_shrinks_leaves_towards_parent(mode):
    X, y = _data()
    plain = _train({"tree_growth_mode": mode}, X, y, rounds=1)
    smooth = _train({"tree_growth_mode": mode, "path_smooth": 100.0}, X, y, rounds=1)
    lv_plain = np.asarray(plain._gbdt.models[0].leaf_value)
    lv_smooth = np.asarray(smooth._gbdt.models[0].leaf_value)
    # smoothing pulls outputs towards ancestors: leaf value spread shrinks
    assert np.std(lv_smooth) < np.std(lv_plain)
    # and a tiny smoothing factor is a no-op-sized change, not a rewrite
    tiny = _train({"tree_growth_mode": mode, "path_smooth": 1e-6}, X, y, rounds=1)
    lv_tiny = np.asarray(tiny._gbdt.models[0].leaf_value)
    if lv_tiny.shape == lv_plain.shape:
        assert np.allclose(lv_tiny, lv_plain, atol=1e-3)


def test_monotone_penalty_forbids_root_monotone_split():
    rng = np.random.RandomState(1)
    n = 4000
    x0 = rng.randn(n)
    X = np.stack([x0, 0.3 * rng.randn(n)], axis=1).astype(np.float32)
    y = 2.0 * x0 + 0.1 * rng.randn(n)  # x0 dominates
    base = {"objective": "regression", "num_leaves": 7, "verbosity": -1,
            "min_data_in_leaf": 5, "monotone_constraints": [1, 0]}
    b0 = _train(base, X, y, rounds=1)
    assert int(b0._gbdt.models[0].split_feature[0]) == 0  # sanity: x0 wins at root
    # penalty >= depth+1 forbids monotone splits at the root level entirely
    b1 = _train({**base, "monotone_penalty": 1.0}, X, y, rounds=1)
    t = b1._gbdt.models[0]
    assert t.num_internal == 0 or int(t.split_feature[0]) != 0


def test_cegb_split_penalty_prunes_tree():
    X, y = _data()
    big = _train({}, X, y, rounds=1)
    pruned = _train({"cegb_penalty_split": 1.0, "cegb_tradeoff": 10.0}, X, y, rounds=1)
    assert pruned._gbdt.models[0].num_leaves < big._gbdt.models[0].num_leaves


def test_cegb_coupled_feature_penalty_avoids_feature():
    rng = np.random.RandomState(2)
    n = 4000
    x0 = rng.randn(n)
    x1 = x0 + 0.01 * rng.randn(n)  # near-duplicate of x0
    X = np.stack([x0, x1], axis=1).astype(np.float32)
    y = x0 + 0.1 * rng.randn(n)
    free = _train({}, X, y, rounds=2)
    feats_free = {int(v) for t in free._gbdt.models for v in t.split_feature}
    pen = _train({"cegb_penalty_feature_coupled": [1e6, 0.0],
                  "cegb_tradeoff": 1.0}, X, y, rounds=2)
    feats_pen = {int(v) for t in pen._gbdt.models for v in t.split_feature}
    assert 0 not in feats_pen  # feature 0 priced out
    assert 1 in feats_pen


@pytest.mark.parametrize("mode", ["strict", "rounds"])
def test_cegb_lazy_feature_penalty_avoids_feature(mode):
    # lazy per-(row, feature) fetch charge (reference:
    # cost_effective_gradient_boosting.hpp): an expensive never-charged
    # feature is priced out even when informative.  VERDICT r3 item 6:
    # the rounds (TPU-default) grower threads the same (N, F) state.
    rng = np.random.RandomState(2)
    n = 4000
    x0 = rng.randn(n)
    x1 = x0 + 0.01 * rng.randn(n)  # near-duplicate of x0
    X = np.stack([x0, x1], axis=1).astype(np.float32)
    y = x0 + 0.1 * rng.randn(n)
    pen = _train({"cegb_penalty_feature_lazy": [0.0, 1e6],
                  "cegb_tradeoff": 1.0, "tree_growth_mode": mode},
                 X, y, rounds=2)
    feats_pen = {int(v) for t in pen._gbdt.models for v in t.split_feature}
    assert 1 not in feats_pen  # feature 1 priced out per-row
    assert 0 in feats_pen


@pytest.mark.parametrize("mode", ["strict", "rounds"])
def test_cegb_lazy_charges_rows_on_path_features(mode):
    # after a tree, exactly the in-bag rows are charged for the features on
    # their root-to-leaf path (the cross-tree feature_used_in_data state)
    X, y = _data(f=2)
    y = X[:, 0] + 0.05 * np.random.RandomState(3).randn(len(y))  # f1 is noise
    bst = _train({"cegb_penalty_feature_lazy": [1e-9, 1e-9],
                  "tree_growth_mode": mode}, X, y, rounds=1)
    g = bst._gbdt
    used = np.asarray(g._cegb_lazy_used)
    tree = g.models[0]
    feats_used = {int(v) for v in tree.split_feature}
    assert feats_used == {0}
    # every row reached at least one split on feature 0 -> charged for it
    assert used[:, 0].all()
    # feature 1 never split -> no row charged
    assert not used[:, 1].any()
    # a second tree extends (never clears) the charge state
    bst._gbdt.train_one_iter()
    used2 = np.asarray(g._cegb_lazy_used)
    assert (used2 | used == used2).all()


def test_snapshot_freq_writes_periodic_models(tmp_path):
    X, y = _data()
    out = str(tmp_path / "model.txt")
    ds = lgb.Dataset(X, label=y)
    lgb.train({"objective": "regression", "verbosity": -1, "snapshot_freq": 2,
               "output_model": out, "num_leaves": 7},
              ds, num_boost_round=5)
    snap = f"{out}.snapshot_iter_4"
    assert os.path.exists(snap)
    bst = lgb.Booster(model_file=snap)
    assert bst.current_iteration() == 4


def test_pred_early_stop_freezes_confident_rows():
    rng = np.random.RandomState(3)
    X = rng.randn(3000, 6).astype(np.float32)
    y = ((X[:, 0] + 0.05 * rng.randn(3000)) > 0).astype(np.float64)
    ds = lgb.Dataset(X, label=y)
    bst = lgb.Booster(params={"objective": "binary", "verbosity": -1,
                              "num_leaves": 15}, train_set=ds)
    for _ in range(30):
        bst.update()
    full = bst.predict(X)
    g = bst._gbdt
    g.cfg.pred_early_stop = True
    g.cfg.pred_early_stop_freq = 5
    g.cfg.pred_early_stop_margin = 2.0
    es = bst.predict(X)
    # rows that stopped early still classify identically
    assert np.mean((es > 0.5) == (full > 0.5)) > 0.999
    # and with a huge margin nothing stops: bitwise equal to the full path
    g.cfg.pred_early_stop_margin = 1e9
    assert np.allclose(bst.predict(X), full, atol=1e-7)
    g.cfg.pred_early_stop = False


def test_lambdarank_position_bias_learns_bias():
    rng = np.random.RandomState(4)
    nq, qlen = 80, 10
    n = nq * qlen
    X = rng.randn(n, 5).astype(np.float32)
    rel = (X[:, 0] > 0.5).astype(np.float64) + (X[:, 1] > 1.0)
    # presentation positions 0..qlen-1, with clicks biased to early positions
    pos = np.tile(np.arange(qlen), nq)
    ds = lgb.Dataset(X, label=rel, group=[qlen] * nq)
    ds.set_field("position", pos)
    bst = lgb.Booster(
        params={"objective": "lambdarank", "verbosity": -1, "num_leaves": 7,
                "lambdarank_position_bias_regularization": 1.0},
        train_set=ds,
    )
    for _ in range(5):
        bst.update()
    obj = bst._gbdt.objective
    bias = np.asarray(obj.pos_bias)
    assert bias.shape == (qlen,)
    assert np.all(np.isfinite(bias))
    assert np.any(bias != 0.0)  # the EM/Newton update actually ran


def test_ingestion_scipy_sparse_and_sequence():
    scipy_sparse = pytest.importorskip("scipy.sparse")
    X, y = _data(n=1000)
    Xs = scipy_sparse.csr_matrix(np.where(np.abs(X) < 1.0, 0.0, X))
    ds = lgb.Dataset(Xs, label=y)
    bst = lgb.Booster(params={"objective": "regression", "verbosity": -1,
                              "num_leaves": 7}, train_set=ds)
    bst.update()
    assert np.isfinite(bst.predict(Xs.toarray())).all()

    class Seq(lgb.Sequence):
        def __init__(self, arr):
            self.arr = arr
            self.batch_size = 100
        def __len__(self):
            return len(self.arr)
        def __getitem__(self, idx):
            return self.arr[idx]

    ds2 = lgb.Dataset(Seq(X), label=y)
    ds2.construct()
    assert ds2.num_data() == len(X)
    # two sequences concatenate
    ds3 = lgb.Dataset([Seq(X[:500]), Seq(X[500:])], label=y)
    ds3.construct()
    assert ds3.num_data() == len(X)


def test_ingestion_pandas_categorical():
    pd = pytest.importorskip("pandas")
    rng = np.random.RandomState(0)
    n = 2000
    df = pd.DataFrame({
        "num": rng.randn(n),
        "cat": pd.Categorical(rng.choice(["a", "b", "c"], n)),
    })
    y = (df["num"].to_numpy() + (df["cat"] == "b") * 2.0 + 0.1 * rng.randn(n))
    ds = lgb.Dataset(df, label=y, categorical_feature=["cat"])
    bst = lgb.Booster(params={"objective": "regression", "verbosity": -1,
                              "num_leaves": 7}, train_set=ds)
    for _ in range(5):
        bst.update()
    p = bst.predict(df)
    r = np.corrcoef(p, y)[0, 1]
    assert r > 0.9


def test_ingestion_pyarrow_table():
    pa = pytest.importorskip("pyarrow")
    X, y = _data(n=800, f=3)
    table = pa.table({f"f{i}": X[:, i] for i in range(3)})
    ds = lgb.Dataset(table, label=y)
    bst = lgb.Booster(params={"objective": "regression", "verbosity": -1,
                              "num_leaves": 7}, train_set=ds)
    bst.update()
    assert np.isfinite(bst.predict(X[:, :3])).all()


# ---- round-3: formerly-dead params now implemented (VERDICT r2 item 4) ----

def test_reg_sqrt_trains_in_sqrt_space():
    rng = np.random.RandomState(5)
    X = rng.randn(3000, 6)
    z = X @ rng.randn(6) + 0.1 * rng.randn(3000)
    y = np.sign(z) * z * z * 100.0  # large-range label
    ds = lgb.Dataset(X, label=y)
    bst = lgb.train({"objective": "regression", "reg_sqrt": True,
                     "num_leaves": 31, "verbosity": -1}, ds, 40)
    raw = bst.predict(X, raw_score=True)
    pred = bst.predict(X)
    # ConvertOutput: sign(raw) * raw^2
    np.testing.assert_allclose(pred, np.sign(raw) * raw * raw, rtol=1e-6)
    # the raw model lives in sqrt-label space
    t = np.sign(y) * np.sqrt(np.abs(y))
    assert np.corrcoef(raw, t)[0, 1] > 0.95
    # and beats a plain-L2 model on sqrt-scale error for this label shape
    assert np.mean((pred - y) ** 2) < np.var(y)
    # save/load must preserve the sqrt transform (reference writes
    # "regression sqrt" into the model header)
    bst2 = lgb.Booster(model_str=bst.model_to_string())
    np.testing.assert_allclose(bst2.predict(X[:100]), pred[:100], rtol=1e-6)


def test_bagging_by_query_keeps_queries_whole():
    rng = np.random.RandomState(6)
    n, q = 3000, 100
    X = rng.randn(n, 5)
    y = rng.randint(0, 3, n).astype(float)
    group = np.full(q, n // q)
    ds = lgb.Dataset(X, label=y, group=group)
    bst = lgb.Booster(params={"objective": "lambdarank", "verbosity": -1,
                              "bagging_by_query": True,
                              "bagging_fraction": 0.5, "bagging_freq": 1},
                      train_set=ds)
    bst.update()
    mask = np.asarray(bst._gbdt._bagging_mask()[0])
    mq = mask.reshape(q, n // q)
    assert np.all(mq.all(axis=1) | (~mq).any(axis=1))
    # every query is fully in or fully out
    assert np.all((mq.sum(axis=1) == 0) | (mq.sum(axis=1) == n // q))
    # and the fraction is respected roughly
    assert 0.3 < mask.mean() < 0.7


@pytest.mark.parametrize("mode", ["strict", "rounds"])
def test_feature_contri_zero_disables_feature(mode):
    rng = np.random.RandomState(7)
    X = rng.randn(2000, 4)
    y = X[:, 0] * 2.0 + 0.01 * rng.randn(2000)  # all signal in feature 0
    contri = [0.0, 1.0, 1.0, 1.0]
    ds = lgb.Dataset(X, label=y)
    bst = lgb.train({"objective": "regression", "feature_contri": contri,
                     "tree_growth_mode": mode, "num_leaves": 8,
                     "verbosity": -1}, ds, 3)
    assert bst.feature_importance("split")[0] == 0
    ds2 = lgb.Dataset(X, label=y)
    bst2 = lgb.train({"objective": "regression", "tree_growth_mode": mode,
                      "num_leaves": 8, "verbosity": -1}, ds2, 3)
    assert bst2.feature_importance("split")[0] > 0


def test_feature_pre_filter_excludes_unsplittable():
    rng = np.random.RandomState(8)
    n = 2000
    X = rng.randn(n, 3)
    X[:, 1] = 0.0
    X[:5, 1] = 1.0  # only 5 rows differ: unsplittable at min_data_in_leaf=50
    y = X[:, 0] + X[:, 1]
    ds = lgb.Dataset(X, label=y)
    bst = lgb.Booster(params={"objective": "regression", "verbosity": -1,
                              "min_data_in_leaf": 50,
                              "feature_pre_filter": True}, train_set=ds)
    allowed = np.asarray(bst._gbdt._allowed_features)
    assert not allowed[1] and allowed[0] and allowed[2]
    ds2 = lgb.Dataset(X, label=y)
    bst2 = lgb.Booster(params={"objective": "regression", "verbosity": -1,
                               "min_data_in_leaf": 50,
                               "feature_pre_filter": False}, train_set=ds2)
    assert np.asarray(bst2._gbdt._allowed_features).all()


def test_saved_feature_importance_type_gain():
    X, y = _data()
    ds = lgb.Dataset(X, label=y)
    p = {"objective": "regression", "verbosity": -1, "num_leaves": 8}
    bst = lgb.train(dict(p, saved_feature_importance_type=1), ds, 3,
                    keep_training_booster=True)
    s_gain = bst._gbdt.save_model_to_string()
    s_split = bst._gbdt.save_model_to_string(importance_type="split")
    assert s_gain != s_split
    gain = bst.feature_importance("gain")
    top = int(np.argmax(gain))
    name = f"Column_{top}"
    line = [ln for ln in s_gain.splitlines() if ln.startswith(name + "=")][0]
    assert abs(float(line.split("=")[1]) - gain[top]) / max(gain[top], 1) < 1e-4


def test_na_params_warn():
    logs = []
    lgb.register_logger(type("L", (), {
        "info": staticmethod(lambda m: logs.append(("i", m))),
        "warning": staticmethod(lambda m: logs.append(("w", m))),
    })())
    try:
        X, y = _data(n=500)
        ds = lgb.Dataset(X, label=y)
        lgb.train({"objective": "regression", "verbosity": 2,
                   "force_col_wise": True, "num_gpu": 4,
                   "histogram_pool_size": 128.0}, ds, 1)
    finally:
        lgb.register_logger(None)
    warned = " ".join(m for lv, m in logs if lv == "w")
    assert "force_col_wise" in warned
    assert "num_gpu" in warned
    assert "histogram_pool_size" in warned
