"""Tests that previously-no-op parameters now change behavior:
path_smooth, monotone_penalty, CEGB, snapshot_freq, pred_early_stop,
lambdarank position bias.  (VERDICT round 1, items 7/9/10.)"""

import os

import numpy as np
import pytest

import lightgbm_tpu as lgb


def _data(n=4000, f=8, seed=0):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, f).astype(np.float32)
    w = rng.randn(f)
    y = X @ w + 0.3 * rng.randn(n)
    return X, y


def _train(params, X, y, rounds=5):
    base = {"objective": "regression", "num_leaves": 15, "verbosity": -1,
            "min_data_in_leaf": 5}
    base.update(params)
    ds = lgb.Dataset(X, label=y)
    bst = lgb.Booster(params=base, train_set=ds)
    for _ in range(rounds):
        bst.update()
    return bst


@pytest.mark.parametrize("mode", ["strict", "rounds"])
def test_path_smooth_shrinks_leaves_towards_parent(mode):
    X, y = _data()
    plain = _train({"tree_growth_mode": mode}, X, y, rounds=1)
    smooth = _train({"tree_growth_mode": mode, "path_smooth": 100.0}, X, y, rounds=1)
    lv_plain = np.asarray(plain._gbdt.models[0].leaf_value)
    lv_smooth = np.asarray(smooth._gbdt.models[0].leaf_value)
    # smoothing pulls outputs towards ancestors: leaf value spread shrinks
    assert np.std(lv_smooth) < np.std(lv_plain)
    # and a tiny smoothing factor is a no-op-sized change, not a rewrite
    tiny = _train({"tree_growth_mode": mode, "path_smooth": 1e-6}, X, y, rounds=1)
    lv_tiny = np.asarray(tiny._gbdt.models[0].leaf_value)
    if lv_tiny.shape == lv_plain.shape:
        assert np.allclose(lv_tiny, lv_plain, atol=1e-3)


def test_monotone_penalty_forbids_root_monotone_split():
    rng = np.random.RandomState(1)
    n = 4000
    x0 = rng.randn(n)
    X = np.stack([x0, 0.3 * rng.randn(n)], axis=1).astype(np.float32)
    y = 2.0 * x0 + 0.1 * rng.randn(n)  # x0 dominates
    base = {"objective": "regression", "num_leaves": 7, "verbosity": -1,
            "min_data_in_leaf": 5, "monotone_constraints": [1, 0]}
    b0 = _train(base, X, y, rounds=1)
    assert int(b0._gbdt.models[0].split_feature[0]) == 0  # sanity: x0 wins at root
    # penalty >= depth+1 forbids monotone splits at the root level entirely
    b1 = _train({**base, "monotone_penalty": 1.0}, X, y, rounds=1)
    t = b1._gbdt.models[0]
    assert t.num_internal == 0 or int(t.split_feature[0]) != 0


def test_cegb_split_penalty_prunes_tree():
    X, y = _data()
    big = _train({}, X, y, rounds=1)
    pruned = _train({"cegb_penalty_split": 1.0, "cegb_tradeoff": 10.0}, X, y, rounds=1)
    assert pruned._gbdt.models[0].num_leaves < big._gbdt.models[0].num_leaves


def test_cegb_coupled_feature_penalty_avoids_feature():
    rng = np.random.RandomState(2)
    n = 4000
    x0 = rng.randn(n)
    x1 = x0 + 0.01 * rng.randn(n)  # near-duplicate of x0
    X = np.stack([x0, x1], axis=1).astype(np.float32)
    y = x0 + 0.1 * rng.randn(n)
    free = _train({}, X, y, rounds=2)
    feats_free = {int(v) for t in free._gbdt.models for v in t.split_feature}
    pen = _train({"cegb_penalty_feature_coupled": [1e6, 0.0],
                  "cegb_tradeoff": 1.0}, X, y, rounds=2)
    feats_pen = {int(v) for t in pen._gbdt.models for v in t.split_feature}
    assert 0 not in feats_pen  # feature 0 priced out
    assert 1 in feats_pen


def test_snapshot_freq_writes_periodic_models(tmp_path):
    X, y = _data()
    out = str(tmp_path / "model.txt")
    ds = lgb.Dataset(X, label=y)
    lgb.train({"objective": "regression", "verbosity": -1, "snapshot_freq": 2,
               "output_model": out, "num_leaves": 7},
              ds, num_boost_round=5)
    snap = f"{out}.snapshot_iter_4"
    assert os.path.exists(snap)
    bst = lgb.Booster(model_file=snap)
    assert bst.current_iteration() == 4


def test_pred_early_stop_freezes_confident_rows():
    rng = np.random.RandomState(3)
    X = rng.randn(3000, 6).astype(np.float32)
    y = ((X[:, 0] + 0.05 * rng.randn(3000)) > 0).astype(np.float64)
    ds = lgb.Dataset(X, label=y)
    bst = lgb.Booster(params={"objective": "binary", "verbosity": -1,
                              "num_leaves": 15}, train_set=ds)
    for _ in range(30):
        bst.update()
    full = bst.predict(X)
    g = bst._gbdt
    g.cfg.pred_early_stop = True
    g.cfg.pred_early_stop_freq = 5
    g.cfg.pred_early_stop_margin = 2.0
    es = bst.predict(X)
    # rows that stopped early still classify identically
    assert np.mean((es > 0.5) == (full > 0.5)) > 0.999
    # and with a huge margin nothing stops: bitwise equal to the full path
    g.cfg.pred_early_stop_margin = 1e9
    assert np.allclose(bst.predict(X), full, atol=1e-7)
    g.cfg.pred_early_stop = False


def test_lambdarank_position_bias_learns_bias():
    rng = np.random.RandomState(4)
    nq, qlen = 80, 10
    n = nq * qlen
    X = rng.randn(n, 5).astype(np.float32)
    rel = (X[:, 0] > 0.5).astype(np.float64) + (X[:, 1] > 1.0)
    # presentation positions 0..qlen-1, with clicks biased to early positions
    pos = np.tile(np.arange(qlen), nq)
    ds = lgb.Dataset(X, label=rel, group=[qlen] * nq)
    ds.set_field("position", pos)
    bst = lgb.Booster(
        params={"objective": "lambdarank", "verbosity": -1, "num_leaves": 7,
                "lambdarank_position_bias_regularization": 1.0},
        train_set=ds,
    )
    for _ in range(5):
        bst.update()
    obj = bst._gbdt.objective
    bias = np.asarray(obj.pos_bias)
    assert bias.shape == (qlen,)
    assert np.all(np.isfinite(bias))
    assert np.any(bias != 0.0)  # the EM/Newton update actually ran


def test_ingestion_scipy_sparse_and_sequence():
    scipy_sparse = pytest.importorskip("scipy.sparse")
    X, y = _data(n=1000)
    Xs = scipy_sparse.csr_matrix(np.where(np.abs(X) < 1.0, 0.0, X))
    ds = lgb.Dataset(Xs, label=y)
    bst = lgb.Booster(params={"objective": "regression", "verbosity": -1,
                              "num_leaves": 7}, train_set=ds)
    bst.update()
    assert np.isfinite(bst.predict(Xs.toarray())).all()

    class Seq(lgb.Sequence):
        def __init__(self, arr):
            self.arr = arr
            self.batch_size = 100
        def __len__(self):
            return len(self.arr)
        def __getitem__(self, idx):
            return self.arr[idx]

    ds2 = lgb.Dataset(Seq(X), label=y)
    ds2.construct()
    assert ds2.num_data() == len(X)
    # two sequences concatenate
    ds3 = lgb.Dataset([Seq(X[:500]), Seq(X[500:])], label=y)
    ds3.construct()
    assert ds3.num_data() == len(X)


def test_ingestion_pandas_categorical():
    pd = pytest.importorskip("pandas")
    rng = np.random.RandomState(0)
    n = 2000
    df = pd.DataFrame({
        "num": rng.randn(n),
        "cat": pd.Categorical(rng.choice(["a", "b", "c"], n)),
    })
    y = (df["num"].to_numpy() + (df["cat"] == "b") * 2.0 + 0.1 * rng.randn(n))
    ds = lgb.Dataset(df, label=y, categorical_feature=["cat"])
    bst = lgb.Booster(params={"objective": "regression", "verbosity": -1,
                              "num_leaves": 7}, train_set=ds)
    for _ in range(5):
        bst.update()
    p = bst.predict(df)
    r = np.corrcoef(p, y)[0, 1]
    assert r > 0.9


def test_ingestion_pyarrow_table():
    pa = pytest.importorskip("pyarrow")
    X, y = _data(n=800, f=3)
    table = pa.table({f"f{i}": X[:, i] for i in range(3)})
    ds = lgb.Dataset(table, label=y)
    bst = lgb.Booster(params={"objective": "regression", "verbosity": -1,
                              "num_leaves": 7}, train_set=ds)
    bst.update()
    assert np.isfinite(bst.predict(X[:, :3])).all()
