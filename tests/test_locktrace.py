"""Runtime lock-sanitizer pins (the dynamic half of the concurrency
layer, lightgbm_tpu/utils/locktrace.py).

The contract: every named lock participates in a process-wide witness
graph — an acquisition order that contradicts a previously-witnessed
order raises a typed ``LockOrderError`` naming BOTH sites; blocking
acquires become timeout-acquires so a true deadlock surfaces as a typed
``LockTimeoutError`` instead of a hung suite; wait/held reservoirs and
the violation counters flow through the obs registry.  The whole tier-1
suite runs with tracing ON (conftest), and the stress test here pins the
threaded serve + continual + hot-swap runtime at zero violations, zero
deadlocks, bitwise responses, and the warm 1-dispatch/1-accounted-sync
predict budget with all instrumentation live.
"""

import threading
import time

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.models.gbdt import GBDT
from lightgbm_tpu.obs import metrics as obs
from lightgbm_tpu.serve import ServingRuntime
from lightgbm_tpu.utils import locktrace as lt
from lightgbm_tpu.utils.sanitizer import DispatchCounter

PARAMS = {"objective": "binary", "num_leaves": 7, "verbosity": -1,
          "min_data_in_leaf": 5}


@pytest.fixture(autouse=True)
def _fresh_lock_state():
    """Each test gets a clean witness graph and obs registry, and leaves
    the session-wide strict tracing (conftest) back in force."""
    from lightgbm_tpu.obs import server as _srv

    obs.reset()
    lt.reset()
    yield
    _srv.stop_server()
    obs.reset()
    lt.reset()
    lt.set_timeout_s(60.0)
    lt.enable(True, strict=True)


def _setup(n=500, f=6, rounds=4, seed=0):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, f)
    y = (X[:, 0] + 0.5 * X[:, 1] > 0).astype(float)
    ds = lgb.Dataset(X, label=y)
    bst = lgb.Booster(params=dict(PARAMS), train_set=ds)
    for _ in range(rounds):
        bst.update()
    return bst, ds, X, y, rng


# ---------------------------------------------------------------------------
# witness graph: order inversions
# ---------------------------------------------------------------------------

def test_order_inversion_raises_typed_error_naming_both_sites():
    a, b = lt.lock("t.A"), lt.lock("t.B")
    with a:
        with b:  # witnesses A -> B
            pass
    with pytest.raises(lt.LockOrderError) as ei:
        with b:
            with a:  # closes the cycle
                pass
    msg = str(ei.value)
    assert "t.A" in msg and "t.B" in msg
    # names BOTH sites: the current acquire and the first-seen edge
    assert msg.count("test_locktrace.py") == 2, msg
    assert lt.stats()["order_violations"] == 1
    assert obs.counter("lock_order_violations_total").value == 1


def test_record_mode_counts_without_raising():
    lt.enable(True, strict=False)
    a, b = lt.lock("r.A"), lt.lock("r.B")
    with a:
        with b:
            pass
    with b:
        with a:  # inversion: counted, not raised
            pass
    assert lt.stats()["order_violations"] == 1
    assert obs.counter("lock_order_violations_total").value == 1


def test_transitive_inversion_detected():
    a, b, c = lt.lock("tr.A"), lt.lock("tr.B"), lt.lock("tr.C")
    with a:
        with b:
            pass
    with b:
        with c:
            pass
    with pytest.raises(lt.LockOrderError):
        with c:
            with a:  # A -> B -> C -> A
                pass
    assert lt.stats()["order_violations"] == 1


def test_same_name_different_instance_records_no_self_edge():
    """Two GBDT pack locks share the name 'gbdt.pack'; a rollover thread
    nesting them must not poison the graph with a self-edge."""
    p1, p2 = lt.rlock("same.pack"), lt.rlock("same.pack")
    with p1:
        with p2:
            pass
    with p2:
        with p1:
            pass
    assert lt.stats() == {"witness_edges": 0, "order_violations": 0,
                          "deadlock_timeouts": 0}


def test_rlock_reentrancy_is_not_a_violation():
    r = lt.rlock("re.R")
    with r:
        with r:
            assert r.locked()
    assert lt.stats()["order_violations"] == 0


# ---------------------------------------------------------------------------
# deadlock timeout + self-deadlock
# ---------------------------------------------------------------------------

def test_deadlock_surfaces_as_typed_timeout():
    lt.set_timeout_s(0.3)
    m = lt.lock("dl.M")
    release = threading.Event()

    def holder():
        with m:
            release.wait(5)

    t = threading.Thread(target=holder)
    t.start()
    time.sleep(0.05)
    with pytest.raises(lt.LockTimeoutError) as ei:
        m.acquire()
    assert "dl.M" in str(ei.value)
    release.set()
    t.join(timeout=10)
    assert lt.stats()["deadlock_timeouts"] == 1
    assert obs.counter("lock_deadlock_timeouts_total").value == 1


def test_self_deadlock_fails_fast():
    m = lt.lock("sd.M")
    m.acquire()
    try:
        with pytest.raises(lt.LockTimeoutError) as ei:
            m.acquire()
        assert "re-acquired" in str(ei.value)
    finally:
        m.release()


def test_explicit_timeout_keeps_caller_semantics():
    """A caller-passed timeout returns False instead of raising — only
    the default blocking acquire converts to a deadlock error."""
    m = lt.lock("to.M")
    release = threading.Event()

    def holder():
        with m:
            release.wait(5)

    t = threading.Thread(target=holder)
    t.start()
    time.sleep(0.05)
    assert m.acquire(timeout=0.1) is False
    assert m.acquire(blocking=False) is False
    release.set()
    t.join(timeout=10)
    assert lt.stats()["deadlock_timeouts"] == 0


# ---------------------------------------------------------------------------
# condition + metrics + disabled mode
# ---------------------------------------------------------------------------

def test_condition_wait_notify_keeps_bookkeeping_consistent():
    cv = lt.condition("cv.C")
    ready = []

    def waiter():
        with cv:
            while not ready:
                cv.wait(timeout=5)

    t = threading.Thread(target=waiter)
    t.start()
    time.sleep(0.05)
    with cv:
        ready.append(1)
        cv.notify_all()
    t.join(timeout=10)
    assert not t.is_alive()
    # the lock is free and re-acquirable after wait's release/re-acquire
    with cv:
        pass
    assert lt.stats()["order_violations"] == 0


def test_wait_and_held_reservoirs_exported_per_lock():
    m = lt.lock("mx.M")
    with m:
        time.sleep(0.01)
    snap = obs.snapshot()
    hists = snap.get("histograms", {})
    assert obs.labeled("lock_wait_ms", lock="mx.M") in hists
    held = obs.labeled("lock_held_ms", lock="mx.M")
    assert held in hists
    assert hists[held]["max"] >= 5.0  # the 10ms hold is visible


def test_disabled_mode_is_passthrough():
    lt.enable(False)
    a, b = lt.lock("off.A"), lt.lock("off.B")
    with a:
        with b:
            pass
    with b:
        with a:  # would be an inversion; disabled mode never checks
            pass
    assert lt.stats() == {"witness_edges": 0, "order_violations": 0,
                          "deadlock_timeouts": 0}


def test_healthz_degrades_on_order_violation():
    from lightgbm_tpu.obs.server import health

    lt.enable(True, strict=False)
    a, b = lt.lock("hz.A"), lt.lock("hz.B")
    with a:
        with b:
            pass
    with b:
        with a:
            pass
    code, body = health()
    assert code == 200  # degraded still serves; unhealthy is the 5xx tier
    assert body["status"] == "degraded"
    assert any(p["counter"] == "lock_order_violations_total"
               for p in body["problems"])


# ---------------------------------------------------------------------------
# GBDT pack-lock lazy-init (the __setstate__/_plock race fix)
# ---------------------------------------------------------------------------

def test_setstate_preserves_existing_pack_lock_identity():
    bst, *_ = _setup(rounds=2)
    state = bst._gbdt.__getstate__()
    clone = object.__new__(GBDT)
    clone.__setstate__(state)
    lk = clone._plock()
    assert lk is clone._pack_lock
    # a second __setstate__ onto a live object (the old code minted a
    # NEW lock here unconditionally — a caller already serving under lk
    # would race a caller on the replacement)
    clone.__setstate__(state)
    assert clone._plock() is lk


def test_plock_hammer_single_identity():
    """N threads racing the lazy _plock init on a lock-less instance all
    get the SAME lock object."""
    bst, *_ = _setup(rounds=2)
    state = bst._gbdt.__getstate__()
    for _ in range(20):
        clone = object.__new__(GBDT)
        clone.__dict__.update(state)
        assert getattr(clone, "_pack_lock", None) is None
        got = []
        barrier = threading.Barrier(8)

        def grab():
            barrier.wait(5)
            got.append(clone._plock())

        ts = [threading.Thread(target=grab) for _ in range(8)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(timeout=30)
        assert len(got) == 8
        assert all(g is got[0] for g in got), "two pack locks minted"


# ---------------------------------------------------------------------------
# THE stress pin: serve + continual + hot swap under strict tracing
# ---------------------------------------------------------------------------

def test_stress_serve_continual_swap_zero_violations_and_budget(tmp_path):
    """Concurrent predict load on two models + >=2 continual rollovers
    (in-place refit and append) + a hot swap_model, all with strict lock
    tracing, telemetry, span tracing and the HTTP server ON: zero
    order violations, zero deadlock timeouts, zero caller errors, every
    response bitwise equal to a legitimately-published ensemble, and the
    warm predict budget still 1 dispatch + 1 accounted sync."""
    from lightgbm_tpu.obs import server as _srv

    assert lt.enabled()
    _srv.start_server(0)
    bst, ds, X, y, rng = _setup()
    b_alt, _, _, _, _ = _setup(rounds=2, seed=7)
    b_alt2, _, _, _, _ = _setup(rounds=6, seed=8)

    rt = ServingRuntime(models={"main": bst, "alt": b_alt}, max_wait_ms=5,
                        shed_unhealthy=False)
    cr = lgb.continual_train(
        bst, {"update_every_rows": 120, "append_trees": 2},
        runtime=rt, model_name="main", reference=ds,
        state_dir=str(tmp_path), start=False)

    Q = rng.randn(48, 6)
    slices = [Q[i * 16:(i + 1) * 16] for i in range(3)]
    published = {"main": [bst], "alt": [b_alt]}
    responses = []
    stop = threading.Event()
    errors = []

    def caller(model):
        try:
            while not stop.is_set():
                for i, s in enumerate(slices):
                    responses.append((model, i, rt.predict(
                        s, model=model, raw_score=True, timeout=60)))
        except BaseException as e:  # noqa: BLE001
            errors.append(e)

    threads = ([threading.Thread(target=caller, args=("main",))
                for _ in range(2)]
               + [threading.Thread(target=caller, args=("alt",))])
    for t in threads:
        t.start()
    try:
        # in-place refit rollover, then an append rollover, live
        for kind_want in ("refit", "append"):
            Xc = rng.randn(150, 6)
            yc = (Xc[:, 0] + 0.5 * Xc[:, 1] > 0).astype(float)
            cr.ingest(Xc, yc)
            assert cr.update(kind_want) == kind_want
            published["main"].append(cr.booster)
        # hot swap the second tenant mid-load
        rt.swap_model("alt", b_alt2)
        published["alt"].append(b_alt2)
        time.sleep(0.2)  # let callers observe the final versions
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=60)
        cr.stop()
    assert not errors, errors
    assert responses, "stress produced no load"

    # bitwise: every response equals SOME published version of its model
    refs = {m: [[v.predict(s, raw_score=True) for s in slices]
                for v in vs] for m, vs in published.items()}
    for model, i, got in responses:
        assert any(np.array_equal(r[i], got) for r in refs[model]), (
            f"{model} slice {i} matches no published ensemble")

    # zero violations / deadlocks under the full threaded runtime
    assert lt.stats()["order_violations"] == 0
    assert lt.stats()["deadlock_timeouts"] == 0
    assert obs.counter("lock_order_violations_total").value == 0
    assert obs.counter("lock_deadlock_timeouts_total").value == 0

    # warm budget with the sanitizer's own instrumentation live
    rt.predict(Q[:32], model="main", raw_score=True, timeout=60)
    with DispatchCounter() as d:
        rt.predict(Q[:32], model="main", raw_score=True, timeout=60)
    assert d.dispatches == 1, d.dispatches
    assert d.host_syncs == 1, d.host_syncs
    d.assert_no_recompile("warm predict under strict lock tracing")

    # the traced runtime locks left their reservoirs behind
    snap = obs.snapshot()
    hists = snap.get("histograms", {})
    assert obs.labeled("lock_wait_ms", lock="serve.cv") in hists
    assert obs.labeled("lock_held_ms", lock="gbdt.pack") in hists
    rt.stop()
