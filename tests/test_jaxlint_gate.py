"""Tier-1 jaxlint gate: the analyzer over the whole package must report
ZERO unsuppressed findings, and every suppression must carry a reason —
the jit-purity analogue of the reference keeping its CI sanitizer builds
green (SURVEY §6.2).  A new host sync, per-call jit, use-after-donate,
axis-name typo or trace-impurity anywhere in lightgbm_tpu/ fails this test
at PR time instead of surfacing as benchmark archaeology."""

import functools
from pathlib import Path

import lightgbm_tpu
from lightgbm_tpu.analysis import RULES, run
from lightgbm_tpu.analysis.__main__ import main as jaxlint_main

PKG_DIR = Path(lightgbm_tpu.__file__).resolve().parent


@functools.lru_cache(maxsize=2)
def _package_report(strict_pragmas=False):
    # a whole-package lint walk costs ~10s; the source tree cannot change
    # mid-session, so the gate tests share one Report per pragma mode
    return run([PKG_DIR], strict_pragmas=strict_pragmas)


def test_package_has_zero_unsuppressed_findings():
    report = _package_report()
    assert report.ok, "new jaxlint findings (fix or pragma with a reason):\n" \
        + "\n".join(f.format() for f in report.findings)


def test_every_suppression_carries_a_reason():
    report = _package_report()
    for finding, pragma in report.suppressed:
        assert pragma.reason.strip(), f"reasonless pragma hides {finding.format()}"


def test_known_intentional_suppressions_are_still_needed():
    """The suppressed set documents real, intentional exceptions.  Round 7
    REMOVED the windowed grower's per-round sync pragma — the fused round
    has no host pull left to suppress, and it must stay that way; the
    fused-step factory pragmas in gbdt.py remain (this test pins the
    floor, not the exact set)."""
    report = _package_report()
    files = {Path(f.file).name for f, _ in report.suppressed}
    assert "gbdt.py" in files  # cached fused-step/eval jit factories (R2)
    assert "treegrow_windowed.py" not in files, (
        "the fused windowed round needs no sync pragma — a reappearing "
        "suppression means a per-round host pull came back")


def test_all_rules_are_registered():
    assert {"R1", "R2", "R3", "R4", "R5", "R6", "R7", "R8", "R9", "R10",
            "R11", "R12", "R13", "R14", "R15", "R16", "R17", "R18", "R19",
            "R20", "R21", "L1", "L2", "L3", "L4", "L5"} <= set(RULES)


def test_package_has_zero_stale_pragmas():
    """Every suppression in the tree still earns its keep: a pragma whose
    line no longer triggers the named rule (like the per-round R1 pragma
    retired in round 7) must be deleted, not accumulated."""
    report = _package_report(strict_pragmas=True)
    stale = [f for f in report.findings if f.rule == "P1"]
    assert not stale, "stale pragmas (delete the retired suppressions):\n" \
        + "\n".join(f.format() for f in stale)


def test_cli_exit_codes():
    assert jaxlint_main([str(PKG_DIR)]) == 0
    assert jaxlint_main(["--list-rules"]) == 0
    assert jaxlint_main(["/no/such/path"]) == 2
    assert jaxlint_main([str(PKG_DIR), "--rules", "R99"]) == 2


def test_cli_flags_a_dirty_tree(tmp_path):
    bad = tmp_path / "bad.py"
    bad.write_text(
        "import jax\nimport numpy as np\n\n"
        "@jax.jit\ndef f(x):\n    return np.asarray(x)\n")
    assert jaxlint_main([str(bad)]) == 1
