"""Test configuration: run on a virtual 8-device CPU mesh.

Mirrors the reference's multi-node-without-a-cluster test strategy
(tests/test_dask.py LocalCluster, tests/distributed/_test_distributed.py):
sharding tests run against N virtual CPU devices via
--xla_force_host_platform_device_count, no TPU required (SURVEY.md §5.3).

The session environment may register a remote-TPU PJRT plugin at interpreter
startup (sitecustomize), which cannot be undone in-process; when detected, the
whole pytest process is re-exec'd once with a scrubbed environment so the
suite runs hermetically on local CPU.
"""

import os
import sys

if os.environ.get("PALLAS_AXON_POOL_IPS") and not os.environ.get("_LGBM_TPU_TEST_REEXEC"):
    env = dict(os.environ)
    env["PALLAS_AXON_POOL_IPS"] = ""  # skip remote-TPU plugin registration
    env["JAX_PLATFORMS"] = "cpu"
    flags = env.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        env["XLA_FLAGS"] = flags + " --xla_force_host_platform_device_count=8"
    env["_LGBM_TPU_TEST_REEXEC"] = "1"
    os.execve(sys.executable, [sys.executable, "-m", "pytest"] + sys.argv[1:], env)

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = flags + " --xla_force_host_platform_device_count=8"
os.environ.setdefault("JAX_ENABLE_X64", "0")
