"""Test configuration: run on a virtual 8-device CPU mesh.

Mirrors the reference's multi-node-without-a-cluster test strategy
(tests/test_dask.py LocalCluster, tests/distributed/_test_distributed.py):
sharding tests run against N virtual CPU devices via
--xla_force_host_platform_device_count, no TPU required (SURVEY.md §5.3).

The session environment may register a remote-TPU PJRT plugin at interpreter
startup (sitecustomize).  Registration is harmless as long as the backend is
never *selected*: forcing ``jax_platforms=cpu`` before the first device query
keeps the whole suite hermetic on local CPU.  (An os.execve re-exec is NOT an
option here: pytest's fd-level capture is already active when conftest loads,
so the re-exec'd process inherits redirected fds and its output is orphaned.)
"""

import os
import pathlib

os.environ.setdefault("JAX_ENABLE_X64", "0")

# Persistent XLA compilation cache: tier-1 wall clock is dominated by CPU
# backend compiles (the bucket ladder + fused round re-compile identical
# HLO every run), and a warm disk cache roughly halves the suite.  The dir
# lives inside the repo so hermetic checkouts stay self-contained; only
# compiles >= 0.5s are cached, so cheap per-test executables still exercise
# the real compile path and in-process retrace/budget pins (which hook
# trace events and executable reuse, not disk) are unaffected.
_cache_dir = pathlib.Path(__file__).resolve().parent.parent / ".jax_compile_cache"
os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", str(_cache_dir))
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "0.5")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = flags + " --xla_force_host_platform_device_count=8"
os.environ["JAX_PLATFORMS"] = "cpu"

import jax

try:
    jax.config.update("jax_platforms", "cpu")
except Exception:  # backends already initialized: verified cpu below
    pass

# fail fast if the remote backend was selected anyway — a non-hermetic run
# would otherwise surface as confusing library failures
assert jax.default_backend() == "cpu", (
    f"test suite must run on local CPU, got {jax.default_backend()!r}"
)

# run the WHOLE suite under the runtime lock sanitizer, strict: every
# traced acquire checks the witness graph and raises LockOrderError on a
# cycle, and blocking acquires become 60s timeout-acquires so a true
# deadlock fails the test instead of hanging the run (docs/ANALYSIS.md)
from lightgbm_tpu.utils import locktrace as _locktrace  # noqa: E402

_locktrace.enable(True, strict=True)
