"""Device-side metric evaluation (VERDICT r2 item 8): metrics run inside one
jit per eval set; only scalars cross to the host.  Values must match the
host numpy implementations (reference: src/metric/cuda/*)."""

import numpy as np
import jax.numpy as jnp
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.config import Config
from lightgbm_tpu.metrics import _auc, _auc_device


def test_auc_device_matches_host_with_ties_and_weights():
    rng = np.random.RandomState(0)
    n = 5000
    s = np.round(rng.randn(n), 1)  # coarse rounding -> many ties
    y = (rng.rand(n) < 0.4).astype(np.float64)
    w = rng.rand(n).astype(np.float64) + 0.1
    host = _auc(s, y, w)
    dev = float(_auc_device(jnp.asarray(s, jnp.float32), jnp.asarray(y),
                            jnp.asarray(w, jnp.float32)))
    assert dev == pytest.approx(host, abs=2e-5)
    host_u = _auc(s, y, None)
    dev_u = float(_auc_device(jnp.asarray(s, jnp.float32), jnp.asarray(y), None))
    assert dev_u == pytest.approx(host_u, abs=2e-5)


def _binary_setup():
    rng = np.random.RandomState(1)
    X = rng.randn(3000, 8)
    y = ((X @ rng.randn(8) + 0.5 * rng.randn(3000)) > 0).astype(np.float64)
    return X, y


def test_eval_device_matches_host_binary():
    X, y = _binary_setup()
    w = np.random.RandomState(2).rand(3000) + 0.5
    train = lgb.Dataset(X[:2000], label=y[:2000], weight=w[:2000])
    valid = lgb.Dataset(X[2000:], label=y[2000:], weight=w[2000:],
                        reference=train)
    bst = lgb.train(
        {"objective": "binary", "verbosity": -1,
         "metric": ["auc", "binary_logloss", "binary_error", "l2"]},
        train, 10, valid_sets=[valid], keep_training_booster=True)
    g = bst._gbdt
    res = g.eval_at(1)
    assert [r[1] for r in res] == ["auc", "binary_logloss", "binary_error", "l2"]
    # host recomputation through each metric's numpy path
    ds = g.valid_sets[0]
    pred = g._converted(g._eval_margin(g._valid_scores[0]))
    label = np.asarray(ds.label)
    weight = np.asarray(ds.weight)
    for m, (name_, mn, v, hib) in zip(g.metrics, res):
        (hn, hv, hh) = m.eval(pred, label, weight)[0]
        assert mn == hn and hib == hh
        assert v == pytest.approx(hv, rel=2e-4, abs=2e-5)


def test_eval_avoids_score_transfer_when_all_metrics_device(monkeypatch):
    X, y = _binary_setup()
    train = lgb.Dataset(X[:2000], label=y[:2000])
    valid = lgb.Dataset(X[2000:], label=y[2000:], reference=train)
    bst = lgb.train({"objective": "binary", "verbosity": -1,
                     "metric": ["auc", "binary_logloss"]},
                    train, 5, valid_sets=[valid], keep_training_booster=True)
    g = bst._gbdt
    called = []
    orig = type(g)._converted
    monkeypatch.setattr(type(g), "_converted",
                        lambda self, s: (called.append(1), orig(self, s))[1])
    res = g.eval_at(1)
    assert len(res) == 2
    assert not called  # the (N,) score never crossed to the host


def test_eval_device_matches_host_multiclass():
    rng = np.random.RandomState(3)
    X = rng.randn(2000, 6)
    y = rng.randint(0, 4, 2000).astype(np.float64)
    train = lgb.Dataset(X[:1500], label=y[:1500])
    valid = lgb.Dataset(X[1500:], label=y[1500:], reference=train)
    bst = lgb.train({"objective": "multiclass", "num_class": 4,
                     "verbosity": -1, "metric": ["multi_logloss", "multi_error"]},
                    train, 5, valid_sets=[valid], keep_training_booster=True)
    g = bst._gbdt
    res = g.eval_at(1)
    ds = g.valid_sets[0]
    pred = g._converted(g._eval_margin(g._valid_scores[0]))
    for m, (name_, mn, v, hib) in zip(g.metrics, res):
        (hn, hv, hh) = m.eval(pred, np.asarray(ds.label), None)[0]
        assert mn == hn
        assert v == pytest.approx(hv, rel=2e-4, abs=2e-5)


def test_rank_metrics_device_match_host():
    # ndcg@k / map@k evaluate inside the per-eval-set jit (reference: the
    # CUDA rank metrics); values must match the host per-query loops
    rng = np.random.RandomState(4)
    n, docs = 2400, 24
    X = rng.randn(n, 10)
    y = np.clip(np.floor(X[:, 0] + rng.randn(n) * 0.5) + 2, 0, 4).astype(float)
    g = np.full(n // docs, docs)
    train = lgb.Dataset(X[:1800], label=y[:1800], group=g[: 1800 // docs])
    valid = lgb.Dataset(X[1800:], label=y[1800:], group=g[: 600 // docs],
                        reference=train)
    bst = lgb.train(
        {"objective": "lambdarank", "verbosity": -1,
         "metric": ["ndcg", "map"], "eval_at": [1, 3, 5]},
        train, 8, valid_sets=[valid], keep_training_booster=True)
    gb = bst._gbdt
    # the device path must actually engage for both rank metrics
    ds = gb.valid_sets[0]
    k = gb.num_tree_per_iteration
    assert all(m.supports_device(k) and m.needs_queries for m in gb.metrics)
    res = gb.eval_at(1)
    names = [r[1] for r in res]
    assert names == ["ndcg@1", "ndcg@3", "ndcg@5", "map@1", "map@3", "map@5"]
    pred = gb._converted(gb._eval_margin(gb._valid_scores[0]))
    label = np.asarray(ds.label)
    host = []
    for m in gb.metrics:
        host.extend(m.eval(pred, label, None, ds.query_boundaries))
    for (dn, dm, dv, dh), (hn, hv, hh) in zip(res, host):
        assert dm == hn and dh == hh
        assert dv == pytest.approx(hv, rel=2e-4, abs=2e-5)


def test_auc_mu_device_matches_host():
    rng = np.random.RandomState(6)
    n, k = 3000, 4
    X = rng.randn(n, 8)
    y = np.argmax(X[:, :k] + 0.8 * rng.randn(n, k), axis=1).astype(float)
    w = rng.rand(n) + 0.5
    train = lgb.Dataset(X[:2400], label=y[:2400], weight=w[:2400])
    valid = lgb.Dataset(X[2400:], label=y[2400:], weight=w[2400:],
                        reference=train)
    bst = lgb.train(
        {"objective": "multiclass", "num_class": k, "verbosity": -1,
         "metric": ["auc_mu", "multi_logloss"]},
        train, 8, valid_sets=[valid], keep_training_booster=True)
    g = bst._gbdt
    assert all(m.supports_device(k) for m in g.metrics)
    res = g.eval_at(1)
    assert [r[1] for r in res] == ["auc_mu", "multi_logloss"]
    ds = g.valid_sets[0]
    pred = g._converted(g._eval_margin(g._valid_scores[0]))
    label = np.asarray(ds.label)
    weight = np.asarray(ds.weight)
    for m, (_, mn, v, hib) in zip(g.metrics, res):
        hn, hv, hh = m.eval(pred, label, weight)[0]
        assert mn == hn and hib == hh
        assert v == pytest.approx(hv, rel=3e-4, abs=3e-5)


def test_auc_mu_device_matches_host_zero_weight_class():
    # a class whose rows all carry weight 0 still counts its pairs (host
    # semantics: skip is by label presence, not by weighted sums)
    from lightgbm_tpu.config import Config
    from lightgbm_tpu.metrics import AucMuMetric

    rng = np.random.RandomState(8)
    n, k = 600, 3
    pred = rng.rand(n, k)
    y = rng.randint(0, k, n).astype(np.float64)
    w = rng.rand(n) + 0.1
    w[y == 1] = 0.0  # class 1 fully zero-weighted
    m = AucMuMetric(Config.from_dict({"num_class": k}))
    host = m.eval(pred, y, w)[0][1]
    dev = float(m.device_eval(jnp.asarray(pred, jnp.float32),
                              jnp.asarray(y), jnp.asarray(w, jnp.float32)))
    assert dev == pytest.approx(host, rel=3e-4, abs=3e-5)
