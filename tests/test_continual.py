"""Continual-training pins (round 19, ISSUE 14 — lightgbm_tpu/continual).

The train-while-serving contract: a ContinualRunner beside a live
ServingRuntime completes refit AND append-trees rollovers under
concurrent predict load with every response bitwise equal to a cold
``Booster.predict`` of a legitimately-published ensemble version, the
warm 1-dispatch/1-accounted-sync predict budget pinned ACROSS a rollover
(telemetry + span tracing + HTTP server ON), zero Overloaded sheds
attributable to the swap, ``model_staleness_s`` visibly dropping at each
rollover on ``/metrics`` — and a crash at the ``continual_swap`` fault
site resumes from the fleet manifest with the previous ensemble still
serving and no torn pack ever published.
"""

import json
import os
import subprocess
import sys
import threading
import time
import urllib.request

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.continual import ContinualError
from lightgbm_tpu.continual.refit import make_refit_entry, refit_leaves
from lightgbm_tpu.obs import metrics as obs
from lightgbm_tpu.serve import ServingRuntime
from lightgbm_tpu.utils import checkpoint as ckpt
from lightgbm_tpu.utils.sanitizer import DispatchCounter

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_CPU_ENV = {
    "JAX_PLATFORMS": "cpu",
    "XLA_FLAGS": "--xla_force_host_platform_device_count=1",
}

PARAMS = {"objective": "binary", "num_leaves": 7, "verbosity": -1,
          "min_data_in_leaf": 5}


@pytest.fixture(autouse=True)
def _fresh_registry():
    from lightgbm_tpu.obs import server as _srv
    from lightgbm_tpu.obs import trace as _trc

    obs.reset()
    _trc.reset_trace()
    yield
    _srv.stop_server()
    obs.reset()
    _trc.reset_trace()


def _setup(n=500, f=6, rounds=4, seed=0):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, f)
    y = (X[:, 0] + 0.5 * X[:, 1] > 0).astype(float)
    ds = lgb.Dataset(X, label=y)
    bst = lgb.Booster(params=dict(PARAMS), train_set=ds)
    for _ in range(rounds):
        bst.update()
    return bst, ds, X, y, rng


def _chunk(rng, n=150, f=6):
    Xc = rng.randn(n, f)
    yc = (Xc[:, 0] + 0.5 * Xc[:, 1] > 0).astype(float)
    return Xc, yc


def _trees(bst):
    s = bst.model_to_string()
    return s[s.index("Tree=0"):s.index("end of trees")]


def _prom_value(url, name):
    text = urllib.request.urlopen(url, timeout=10).read().decode()
    for line in text.splitlines():
        if line.startswith(name + " "):
            return float(line.split()[1])
    return None


# ---------------------------------------------------------------------------
# THE acceptance: rollover under load
# ---------------------------------------------------------------------------

def test_rollover_under_concurrent_load_bitwise_and_budget_pinned(tmp_path):
    """>=2 refit + >=1 append rollovers while concurrent callers hammer
    the serving runtime: every response bitwise-matches a legitimately
    published ensemble version, zero sheds, warm budget pinned across
    the swap with telemetry + tracing + the HTTP server ON, and the
    staleness gauges drop at each rollover on the live /metrics."""
    from lightgbm_tpu.obs import server as _srv

    srv = _srv.start_server(0)
    bst, ds, X, y, rng = _setup()
    rt = ServingRuntime(bst, max_wait_ms=5, shed_unhealthy=False)
    cr = lgb.continual_train(
        bst, {"update_every_rows": 120, "append_trees": 2},
        runtime=rt, reference=ds, state_dir=str(tmp_path), start=False)

    Q = rng.randn(64, 6)
    slices = [Q[i * 16:(i + 1) * 16] for i in range(4)]
    versions = [bst]  # every ensemble ever published
    responses = []
    stop = threading.Event()
    errors = []

    def caller():
        try:
            while not stop.is_set():
                for i, s in enumerate(slices):
                    responses.append((i, rt.predict(
                        s, raw_score=True, timeout=60)))
        except BaseException as e:  # noqa: BLE001
            errors.append(e)

    threads = [threading.Thread(target=caller) for _ in range(3)]
    for t in threads:
        t.start()
    try:
        # 2 refit rollovers + 1 append rollover, live
        for kind_want in ("refit", "refit", "append"):
            Xc, yc = _chunk(rng)
            cr.ingest(Xc, yc)
            stale_rows = obs.gauge("model_staleness_rows").value
            assert stale_rows >= 150, stale_rows
            kind = cr.update(kind_want)
            assert kind == kind_want
            versions.append(cr.booster)
            assert obs.gauge("model_staleness_rows").value == 0.0
        time.sleep(0.2)  # let callers observe the final version
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=30)
    assert not errors, errors

    # bitwise: every in-flight response equals SOME published version's
    # cold predict; the post-rollover predict equals the FINAL version
    refs = [[v.predict(s, raw_score=True) for s in slices]
            for v in versions]
    for i, got in responses:
        assert any(np.array_equal(refs[v][i], got)
                   for v in range(len(versions))), (
            f"response for slice {i} matches no published ensemble")
    final = rt.predict(Q[:32], raw_score=True, timeout=60)
    assert np.array_equal(final, versions[-1].predict(Q[:32],
                                                      raw_score=True))
    assert cr.booster.num_trees() == 6  # 4 + append_trees

    # zero sheds attributable to the swaps
    assert obs.counter("serve_shed_total").value == 0

    # warm budget ACROSS the rollovers: 1 dispatch + 1 accounted sync,
    # no recompile — telemetry + tracing + HTTP server all ON
    rt.predict(Q[:32], raw_score=True, timeout=60)  # warm the rung
    with DispatchCounter() as d:
        rt.predict(Q[:32], raw_score=True, timeout=60)
    assert d.dispatches == 1, d.dispatches
    assert d.host_syncs == 1, d.host_syncs
    d.assert_no_recompile("warm predict across continual rollovers")

    # staleness visible on the LIVE endpoint: ingest raises it, the
    # rollover drops it
    Xc, yc = _chunk(rng)
    cr.ingest(Xc, yc)
    up = _prom_value(srv.url("/metrics"), "lgbmtpu_model_staleness_rows")
    assert up is not None and up >= 150
    cr.update("refit")
    down = _prom_value(srv.url("/metrics"), "lgbmtpu_model_staleness_rows")
    assert down == 0.0
    # rollover events carry the sanitizer ledger deltas
    evs = obs.events("continual_rollover")
    assert len(evs) == 4
    assert all("dispatches" in e and "host_syncs" in e for e in evs)
    assert {e["mode"] for e in evs} == {"refit", "append"}
    rt.stop()


# ---------------------------------------------------------------------------
# refit: parity, determinism, budget, bitwise online == offline
# ---------------------------------------------------------------------------

def test_device_refit_matches_host_refit_and_budget():
    bst, ds, X, y, rng = _setup()
    Xn, yn = _chunk(rng, n=300)
    host = bst.refit(Xn, yn, decay_rate=0.9)

    clone = lgb.Booster(model_str=bst.model_to_string())
    clone._gbdt.cfg = bst._gbdt.cfg
    entry = make_refit_entry(clone._gbdt.objective, 0.9,
                             clone._gbdt.cfg.lambda_l2)
    refit_leaves(clone._gbdt, Xn, yn, entry=entry)
    a = host.predict(X[:64], raw_score=True)
    b = clone.predict(X[:64], raw_score=True)
    # device f32 vs the host's f64 accumulation: numerically equal to
    # well under any split threshold's resolution
    assert np.abs(a - b).max() < 1e-4, np.abs(a - b).max()

    # determinism: the same refit twice is BITWISE the same model
    clone2 = lgb.Booster(model_str=bst.model_to_string())
    clone2._gbdt.cfg = bst._gbdt.cfg
    with DispatchCounter() as d:
        refit_leaves(clone2._gbdt, Xn, yn, entry=entry)
    assert clone.model_to_string() == clone2.model_to_string()
    # warm refit: ONE donated dispatch + ONE accounted sync, no recompile
    assert d.dispatches == 1 and d.host_syncs == 1, (d.dispatches,
                                                     d.host_syncs)
    d.assert_no_recompile("warm continual refit")


def test_multiclass_refit_matches_host_and_budget():
    """Round 20: the k-aware scan renews a multiclass ensemble — device
    refit vs the host ``Booster.refit`` recipe, determinism, and the
    1-dispatch/1-sync budget all hold at k=3."""
    rng = np.random.RandomState(7)
    X = rng.randn(400, 6)
    y = rng.randint(0, 3, 400).astype(float)
    params = {"objective": "multiclass", "num_class": 3, "num_leaves": 7,
              "verbosity": -1, "min_data_in_leaf": 5}
    bst = lgb.train(params, lgb.Dataset(X, label=y), num_boost_round=4)
    Xn = rng.randn(400, 6)
    yn = rng.randint(0, 3, 400).astype(float)
    host = bst.refit(Xn, yn, decay_rate=0.9)

    clone = lgb.Booster(model_str=bst.model_to_string())
    clone._gbdt.cfg = bst.cfg
    entry = make_refit_entry(clone._gbdt.objective, 0.9,
                             clone._gbdt.cfg.lambda_l2, k=3)
    refit_leaves(clone._gbdt, Xn, yn, entry=entry)
    a = host.predict(X[:64], raw_score=True)
    b = clone.predict(X[:64], raw_score=True)
    assert np.abs(a - b).max() < 1e-4, np.abs(a - b).max()

    # determinism + budget: same refit twice is BITWISE the same model,
    # one donated dispatch + one accounted sync, no recompile
    clone2 = lgb.Booster(model_str=bst.model_to_string())
    clone2._gbdt.cfg = bst.cfg
    with DispatchCounter() as d:
        refit_leaves(clone2._gbdt, Xn, yn, entry=entry)
    assert clone.model_to_string() == clone2.model_to_string()
    assert d.dispatches == 1 and d.host_syncs == 1, (d.dispatches,
                                                     d.host_syncs)
    d.assert_no_recompile("warm multiclass refit")


def test_weighted_refit_matches_host_and_weight_flows():
    """Round 20: ``weight=`` reaches objective.get_gradients — device vs
    the host ``Booster.refit(weight=...)``, and weighted != unweighted."""
    bst, ds, X, y, rng = _setup()
    Xn, yn = _chunk(rng, n=300)
    w = rng.uniform(0.5, 2.0, len(yn))
    host = bst.refit(Xn, yn, decay_rate=0.9, weight=w)

    clone = lgb.Booster(model_str=bst.model_to_string())
    clone._gbdt.cfg = bst._gbdt.cfg
    refit_leaves(clone._gbdt, Xn, yn, weight=w)
    a = host.predict(X[:64], raw_score=True)
    b = clone.predict(X[:64], raw_score=True)
    assert np.abs(a - b).max() < 1e-4, np.abs(a - b).max()

    # the weights actually flow: unweighted refit lands elsewhere
    unw = lgb.Booster(model_str=bst.model_to_string())
    unw._gbdt.cfg = bst._gbdt.cfg
    refit_leaves(unw._gbdt, Xn, yn)
    assert np.abs(b - unw.predict(X[:64], raw_score=True)).max() > 1e-7


def test_fleet_refit_one_dispatch_matches_per_lane_solo():
    """The batched twin: B lanes renewed in ONE donated dispatch + ONE
    accounted sync, each lane's result equal (to f32 resolution) to a
    solo refit_leaves of that lane — weighted and unweighted."""
    from lightgbm_tpu.continual import fleet_refit_leaves

    rng = np.random.RandomState(11)
    B, N, F = 4, 400, 6
    X = rng.randn(N, F)
    labels = np.stack([(X[:, 0] + rng.randn(N) > 0).astype(float)
                       for _ in range(B)])
    fb = lgb.train_fleet(dict(PARAMS), lgb.Dataset(X), labels,
                         num_boost_round=3)
    Xn = rng.randn(N, F)
    labels_n = np.stack([(Xn[:, 0] > 0).astype(float) for _ in range(B)])
    W = rng.uniform(0.5, 2.0, (B, N))

    for weights in (None, W):
        fb2 = lgb.train_fleet(dict(PARAMS), lgb.Dataset(X), labels,
                              num_boost_round=3)
        solo = []
        for b in range(B):
            cp = lgb.Booster(model_str=fb.booster(b).model_to_string())
            cp._gbdt.cfg = fb.booster(b).cfg
            refit_leaves(cp._gbdt, Xn, labels_n[b],
                         weight=None if weights is None else weights[b])
            solo.append(cp)
        with DispatchCounter() as d:
            fleet_refit_leaves(fb2, Xn, labels_n, weights=weights)
        assert d.dispatches == 1 and d.host_syncs == 1, (d.dispatches,
                                                         d.host_syncs)
        for b in range(B):
            ps = np.asarray(solo[b].predict(Xn[:64], raw_score=True))
            pf = np.asarray(fb2.booster(b).predict(Xn[:64], raw_score=True))
            assert np.abs(ps - pf).max() < 1e-5, (weights is not None, b)

    # envelope: a multiclass lane refuses loudly
    ymc = rng.randint(0, 3, N).astype(float)
    mc = lgb.train({"objective": "multiclass", "num_class": 3,
                    "num_leaves": 7, "verbosity": -1,
                    "min_data_in_leaf": 5},
                   lgb.Dataset(X, label=ymc), num_boost_round=2)
    with pytest.raises(ContinualError):
        fleet_refit_leaves([mc], Xn, labels_n[:1])


def test_runner_rollovers_bitwise_equal_offline_application(tmp_path):
    """The under-load runner path IS the offline path: replaying the
    same ingest/update sequence offline reproduces the runner's ensemble
    tree-bitwise (refit and append both)."""
    bst, ds, X, y, rng = _setup()
    cr = lgb.continual_train(bst, {"append_trees": 2}, reference=ds,
                             start=False)
    chunks = [_chunk(rng) for _ in range(3)]
    cr.ingest(*chunks[0])
    cr.update("refit")
    cr.ingest(*chunks[1])
    cr.ingest(*chunks[2])
    cr.update("append")

    # offline: same primitives, by hand
    off = lgb.Booster(model_str=bst.model_to_string())
    off._gbdt.cfg = bst._gbdt.cfg
    entry = make_refit_entry(off._gbdt.objective,
                             off._gbdt.cfg.refit_decay_rate,
                             off._gbdt.cfg.lambda_l2)
    refit_leaves(off._gbdt, chunks[0][0], chunks[0][1], entry=entry)
    Xw = np.concatenate([c[0] for c in chunks])
    yw = np.concatenate([c[1] for c in chunks])
    params = dict(PARAMS)
    off2 = lgb.train(params, lgb.Dataset(Xw, label=yw, reference=ds),
                     num_boost_round=2, init_model=off)
    assert _trees(cr.booster) == _trees(off2)
    q = rng.randn(40, 6)
    assert np.array_equal(cr.booster.predict(q), off2.predict(q))


# ---------------------------------------------------------------------------
# crash mid-rollover: previous ensemble serves on, manifest resumes
# ---------------------------------------------------------------------------

_CRASH_COMMON = """
import os, sys, json
import numpy as np
sys.path.insert(0, {repo!r})
import lightgbm_tpu as lgb

rng = np.random.RandomState(11)
X = rng.randn(400, 5)
y = (X @ rng.randn(5) > 0).astype(np.float64)
ds = lgb.Dataset(X, label=y)
bst = lgb.Booster(params={params!r}, train_set=ds)
for _ in range(4):
    bst.update()
rt = lgb.serve(bst, {{"serve_max_wait_ms": 2}})
Q = rng.randn(32, 5)
c1 = (rng.randn(150, 5), None)
c1 = (c1[0], (c1[0] @ np.ones(5) > 0).astype(float))
c2 = (rng.randn(150, 5), None)
c2 = (c2[0], (c2[0] @ np.ones(5) > 0).astype(float))
"""

_CRASH_PART1 = _CRASH_COMMON + """
cr = lgb.continual_train(bst, {{}}, runtime=rt, reference=ds,
                         state_dir={d!r}, start=False)
cr.ingest(*c1)
cr.update("refit")
print("PRED1=" + json.dumps(
    rt.predict(Q, raw_score=True, timeout=60).tolist()), flush=True)
cr.ingest(*c2)
cr.update("refit")  # armed: continual_swap:2 crashes here
print("COMPLETED_WITHOUT_FAULT", flush=True)
"""

_CRASH_PART2 = _CRASH_COMMON + """
cr = lgb.continual_train(bst, {{}}, runtime=rt, reference=ds,
                         state_dir={d!r}, resume=True, start=False)
print("SEQ=%d" % cr.seq, flush=True)
print("PRED2=" + json.dumps(
    rt.predict(Q, raw_score=True, timeout=60).tolist()), flush=True)
"""


def test_crash_mid_rollover_resumes_previous_still_serving(tmp_path):
    """LGBMTPU_FAULT=continual_swap:2: update 2's durable checkpoint
    lands but the swap never happens — the process's served predictions
    stayed on ensemble seq-1 (no torn pack, no seq-2 rollover event),
    and a restarted runner resumes seq 2 from the manifest bitwise."""
    from lightgbm_tpu.utils.faults import CRASH_EXIT_CODE

    d = str(tmp_path)
    events = os.path.join(d, "events.jsonl")
    env = dict(os.environ, LGBMTPU_FAULT="continual_swap:2",
               LGBMTPU_EVENTS_FILE=events, **_CPU_ENV)
    env.pop("PYTEST_CURRENT_TEST", None)
    r = subprocess.run(
        [sys.executable, "-c",
         _CRASH_PART1.format(repo=REPO, d=d, params=PARAMS)],
        env=env, capture_output=True, text=True, timeout=300)
    assert r.returncode == CRASH_EXIT_CODE, (r.stdout, r.stderr)
    assert "COMPLETED_WITHOUT_FAULT" not in r.stdout
    pred1 = json.loads(r.stdout.split("PRED1=")[1].splitlines()[0])

    # the update WAS durably checkpointed (seq 2 fleet-valid) ...
    found = ckpt.latest_valid_fleet_manifest(d, 1)
    assert found is not None and found[0] == 2, found
    # ... but never PUBLISHED: the event trail shows the seq-1 rollover,
    # the armed fault, and no seq-2 rollover
    with open(events, encoding="utf-8") as fh:
        evs = [json.loads(line) for line in fh if line.strip()]
    rollovers = [e for e in evs if e["kind"] == "continual_rollover"]
    assert [e["seq"] for e in rollovers] == [1]
    assert any(e["kind"] == "fault" and e["site"] == "continual_swap"
               for e in evs)

    # offline reference (no fault): seq-1 and seq-2 ensembles
    os.makedirs(os.path.join(d, "ref"), exist_ok=True)
    env2 = dict(os.environ, **_CPU_ENV)
    env2.pop("PYTEST_CURRENT_TEST", None)
    r_ref = subprocess.run(
        [sys.executable, "-c",
         _CRASH_PART1.format(repo=REPO, d=os.path.join(d, "ref"),
                             params=PARAMS)],
        env=env2, capture_output=True, text=True, timeout=300)
    assert "COMPLETED_WITHOUT_FAULT" in r_ref.stdout, (r_ref.stdout,
                                                       r_ref.stderr)
    ref1 = json.loads(r_ref.stdout.split("PRED1=")[1].splitlines()[0])
    # the crashed process served the seq-1 ensemble to the end
    assert pred1 == ref1

    # resume: the restarted runner picks seq 2 up from the manifest and
    # serves it — bitwise the ensemble the fault interrupted
    r2 = subprocess.run(
        [sys.executable, "-c",
         _CRASH_PART2.format(repo=REPO, d=d, params=PARAMS)],
        env=env2, capture_output=True, text=True, timeout=300)
    assert r2.returncode == 0, (r2.stdout, r2.stderr)
    assert "SEQ=2" in r2.stdout
    pred2 = json.loads(r2.stdout.split("PRED2=")[1].splitlines()[0])
    r2_ref = subprocess.run(
        [sys.executable, "-c",
         _CRASH_PART2.format(repo=REPO, d=os.path.join(d, "ref"),
                             params=PARAMS)],
        env=env2, capture_output=True, text=True, timeout=300)
    assert r2_ref.returncode == 0, (r2_ref.stdout, r2_ref.stderr)
    ref2 = json.loads(r2_ref.stdout.split("PRED2=")[1].splitlines()[0])
    assert pred2 == ref2


# ---------------------------------------------------------------------------
# the mutation/serve race surface (ISSUE 14 satellite 1)
# ---------------------------------------------------------------------------

def test_concurrent_inplace_refits_under_serving_load_evict_stale_packs():
    """Hammer coalesced predicts while the trainer thread refits the
    SERVED model in place: every response is bitwise one of the refit
    generations (the pack lock makes bump+lookup atomic and the build
    retry excludes torn packs), and the versioned cache EVICTS — the
    stale-pack eviction counter grows under swap load."""
    bst, ds, X, y, rng = _setup()
    g = bst._gbdt
    entry = make_refit_entry(g.objective, 0.9, g.cfg.lambda_l2)
    rt = ServingRuntime(bst, max_wait_ms=2, shed_unhealthy=False)
    Q = rng.randn(16, 6)
    stop = threading.Event()
    got = []
    errors = []

    def caller():
        try:
            while not stop.is_set():
                got.append(np.array(rt.predict(Q, raw_score=True,
                                               timeout=60)))
        except BaseException as e:  # noqa: BLE001
            errors.append(e)

    threads = [threading.Thread(target=caller) for _ in range(2)]
    for t in threads:
        t.start()
    generations = [bst.predict(Q, raw_score=True)]
    try:
        for k in range(6):
            Xc, yc = _chunk(rng, n=120)
            refit_leaves(g, Xc, yc, entry=entry)  # in-place, served live
            generations.append(bst.predict(Q, raw_score=True))
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=30)
    rt.stop()
    assert not errors, errors
    assert len(got) > 0
    for resp in got:
        assert any(np.array_equal(resp, gen) for gen in generations), (
            "a response matches NO refit generation — torn pack served")
    # 7 versions through a keep-2 window: stale packs were evicted
    assert obs.counter("predict_stale_pack_evictions_total").value > 0
    assert g._pack_version >= 6


# ---------------------------------------------------------------------------
# ingest: clamp-and-count, drift, durability, validation
# ---------------------------------------------------------------------------

def test_ingest_clamps_and_counts_against_frozen_mappers(tmp_path):
    bst, ds, X, y, rng = _setup()
    cache = str(tmp_path / "ingest.bin")
    cr = lgb.continual_train(bst, {}, reference=ds, cache_path=cache,
                             start=False)
    # rows far outside the training range: clamped into edge bins,
    # counted, never rebinned
    Xc, yc = _chunk(rng, n=100)
    Xc[:10, 0] = 1e9
    Xc[:5, 1] = -1e9
    s = cr.ingest(Xc, yc)
    assert s["clamped"] >= 15
    assert obs.counter("continual_clamped_values_total").value >= 15
    # the frozen mappers binned it: the durable cache holds exactly the
    # reference transform
    from lightgbm_tpu.io.stream import BinCacheStream

    st = BinCacheStream(cache)
    assert st.n_rows == 100
    swept = np.concatenate([v.copy() for _, v in st.chunks(64)])
    assert np.array_equal(swept, ds.binner.transform(Xc).astype(st.dtype))

    # drift telemetry: a label-shifted chunk moves the gauge
    Xs, _ = _chunk(rng, n=100)
    s2 = cr.ingest(Xs, np.ones(100))
    assert s2["label_drift"] > 0
    assert obs.gauge("continual_label_drift").value == s2["label_drift"]
    assert len(obs.events("continual_chunk")) == 2
    assert BinCacheStream(cache).n_rows == 200

    # non-finite labels refuse at the gate
    with pytest.raises(lgb.LightGBMError):
        cr.ingest(Xs[:3], np.asarray([0.0, np.nan, 1.0]))


def test_staleness_slo_flips_healthz_degraded():
    from lightgbm_tpu.obs import server as _srv

    bst, ds, X, y, rng = _setup()
    cr = lgb.continual_train(bst, {}, reference=ds, start=False,
                             staleness_slo_s=0.05)
    code, body = _srv.health()
    assert code == 200 and body["status"] == "ok"
    cr.ingest(*_chunk(rng))
    time.sleep(0.1)
    cr._publish_staleness()
    assert obs.gauge("continual_staleness_exceeded").value == 1.0
    code, body = _srv.health()
    assert code == 200 and body["status"] == "degraded"
    assert any(p.get("gauge") == "continual_staleness_exceeded"
               for p in body["problems"])
    cr.update("refit")
    assert obs.gauge("continual_staleness_exceeded").value == 0.0
    assert _srv.health()[1]["status"] == "ok"


def test_runner_thread_drives_row_policy():
    bst, ds, X, y, rng = _setup()
    cr = lgb.continual_train(bst, {"update_every_rows": 100},
                             reference=ds, start=True)
    try:
        before = obs.counter("continual_rollovers_total").value
        cr.ingest(*_chunk(rng, n=150))
        deadline = time.monotonic() + 20
        while (obs.counter("continual_rollovers_total").value == before
               and time.monotonic() < deadline):
            time.sleep(0.05)
        assert obs.counter("continual_rollovers_total").value == before + 1
        assert obs.counter("continual_refits_total").value >= 1
    finally:
        cr.stop()


def test_time_policy_update_every_s():
    bst, ds, X, y, rng = _setup()
    cr = lgb.continual_train(bst, {"update_every_s": 0.05},
                             reference=ds, start=False)
    cr.ingest(*_chunk(rng, n=10))
    time.sleep(0.08)  # the oldest un-incorporated row ages past the bound
    assert cr._due()
    assert cr.update("auto") == "refit"
    assert not cr._due()


# ---------------------------------------------------------------------------
# envelope refusals: loud, typed, never silent
# ---------------------------------------------------------------------------

def test_envelope_refusals():
    # multiclass: refused through round 19; round 21's k-aware scan makes
    # it ELIGIBLE — pin that the runner refits a k=3 model without error
    rng = np.random.RandomState(1)
    X = rng.randn(300, 5)
    y = rng.randint(0, 3, 300).astype(float)
    mc = lgb.Booster(params={"objective": "multiclass", "num_class": 3,
                             "num_leaves": 7, "verbosity": -1},
                     train_set=lgb.Dataset(X, label=y))
    mc.update()
    cr = lgb.continual_train(mc, {}, start=False)
    cr.ingest(X[:50], y[:50])
    assert cr.update("refit") == "refit"

    # append without frozen mappers refuses
    bst, ds, _, _, rng2 = _setup()
    plain = lgb.Booster(model_str=bst.model_to_string())
    plain._gbdt.cfg = bst._gbdt.cfg
    cr2 = lgb.continual_train(plain, {"append_trees": 2}, start=False)
    cr2.ingest(*_chunk(rng2))
    with pytest.raises(ContinualError):
        cr2.update("append")

    # a runner over a model the runtime does not serve refuses up front
    rt = ServingRuntime(bst, max_wait_ms=2, shed_unhealthy=False,
                        start=False)
    with pytest.raises(lgb.LightGBMError):
        lgb.continual_train(bst, {}, runtime=rt, model_name="other",
                            start=False)
    rt.stop()


def test_auto_update_falls_back_to_append_when_refit_ineligible():
    """A refit-ineligible ensemble (linear leaves — multiclass became
    eligible in round 21) with append_trees configured: auto updates
    take the append path instead of failing toward the refit the
    envelope already refused."""
    rng = np.random.RandomState(2)
    Xm = rng.randn(300, 5)
    ym = (Xm[:, 0] + 0.1 * rng.randn(300)).astype(float)
    dsm = lgb.Dataset(Xm, label=ym)
    lin = lgb.Booster(params={"objective": "regression", "linear_tree": True,
                              "num_leaves": 5, "verbosity": -1},
                      train_set=dsm)
    lin.update()
    cr = lgb.continual_train(lin, {"update_every_rows": 50,
                                   "append_trees": 1},
                             reference=dsm, start=False)
    cr.ingest(Xm[:60], ym[:60])
    assert cr.update("auto") == "append"
    assert cr.booster.num_trees() == 2  # 1 + 1 appended iteration


def test_window_overflow_evicts_pending_rows_honestly():
    """Rows evicted from the rolling window before any update could
    incorporate them leave the staleness accounting AND are counted as
    lost (continual_window_evicted_pending_rows_total) — never silently
    reported as incorporated."""
    bst, ds, X, y, rng = _setup()
    cr = lgb.continual_train(bst, {}, reference=ds, start=False,
                             window_rows=100)
    for _ in range(4):
        Xc, yc = _chunk(rng, n=60)
        cr.ingest(Xc, yc)
    # cap 100 holds ONE 60-row chunk: three chunks evicted while pending
    assert obs.counter(
        "continual_window_evicted_pending_rows_total").value == 180
    assert obs.gauge("model_staleness_rows").value == 60.0
    assert obs.events("continual_window_overflow")
    cr.update("refit")
    assert obs.gauge("model_staleness_rows").value == 0.0


def test_runner_thread_failure_backoff_and_healthz():
    """A deterministically failing update (linear-leaf refit-only runner
    — refit refuses linear models and no append is configured) backs off
    exponentially instead of retrying at tick cadence, and the failure
    counter flips /healthz degraded."""
    from lightgbm_tpu.obs import server as _srv

    rng = np.random.RandomState(3)
    Xm = rng.randn(200, 4)
    ym = (Xm[:, 0] + 0.1 * rng.randn(200)).astype(float)
    mc = lgb.Booster(params={"objective": "regression", "linear_tree": True,
                             "num_leaves": 5, "verbosity": -1},
                     train_set=lgb.Dataset(Xm, label=ym))
    mc.update()
    cr = lgb.continual_train(mc, {"update_every_rows": 10}, start=True)
    try:
        cr.ingest(Xm[:20], ym[:20])
        time.sleep(1.2)
    finally:
        cr.stop()
    fails = obs.counter("continual_update_failures_total").value
    assert 1 <= fails <= 3, fails  # ~24 ticks elapsed; backoff held
    code, body = _srv.health()
    assert code == 200 and body["status"] == "degraded"
    assert any(p.get("counter") == "continual_update_failures_total"
               for p in body["problems"])
