"""Launcher watchdog unit tests (parallel/launcher.py::_watch_workers):
per-worker liveness via poll + exit-code harvest, fast failure with the
dead worker's log tail, process-group zombie cleanup on timeout — all
against thin dummy subprocesses (no jax import), so they run in tier-1.
Also: the bounded retry-with-backoff around the distributed rendezvous
(parallel/distributed.py)."""

import os
import subprocess
import sys
import time

import pytest

from lightgbm_tpu.parallel.launcher import (WorkerFailure, _log_tail,
                                            _watch_workers)


def _worker(tmp_path, rank, code):
    log_path = str(tmp_path / f"w{rank}.log")
    log_fh = open(log_path, "wb")
    proc = subprocess.Popen(
        [sys.executable, "-c", code], stdout=log_fh,
        stderr=subprocess.STDOUT, start_new_session=True)
    log_fh.close()
    return rank, proc, log_path


def test_all_workers_exit_zero(tmp_path):
    workers = [_worker(tmp_path, r, "print('ok rank', %d)" % r)
               for r in range(3)]
    _watch_workers(workers, timeout_s=30)
    assert all(p.returncode == 0 for _, p, _ in workers)


def test_dead_worker_fails_in_seconds_with_log_excerpt(tmp_path):
    """One rank dies (exit 7) while the others would happily sleep out a
    600 s communicate() timeout: the watchdog must fail the run in
    seconds, name the rank, include its log tail, and leave no survivor
    running."""
    workers = [
        _worker(tmp_path, 0, "import time; time.sleep(600)"),
        _worker(tmp_path, 1,
                "import sys; print('rendezvous exploded'); sys.exit(7)"),
        _worker(tmp_path, 2, "import time; time.sleep(600)"),
    ]
    t0 = time.monotonic()
    with pytest.raises(WorkerFailure) as ei:
        _watch_workers(workers, timeout_s=600)
    elapsed = time.monotonic() - t0
    assert elapsed < 30, f"watchdog took {elapsed:.1f}s — it hung"
    assert ei.value.rank == 1 and not ei.value.timed_out
    msg = str(ei.value)
    assert "rank 1" in msg and "exit code 7" in msg
    assert "rendezvous exploded" in msg  # the log tail made it into the error
    for _, p, _ in workers:
        assert p.poll() is not None, "watchdog leaked a live worker"


def test_timeout_kills_process_groups_and_dumps_tails(tmp_path):
    """The zombie-cleanup satellite: on timeout, the whole process GROUP
    dies (including children the workers spawned) and every worker's log
    tail lands in the error."""
    spawn_child = (
        "import subprocess, sys, time\n"
        "print('worker with child', flush=True)\n"
        "c = subprocess.Popen([sys.executable, '-c', "
        "'import time; time.sleep(600)'])\n"
        "print('CHILD_PID', c.pid, flush=True)\n"
        "time.sleep(600)\n")
    workers = [_worker(tmp_path, 0, spawn_child)]
    # let the worker print its child pid
    deadline = time.monotonic() + 20
    child_pid = None
    while time.monotonic() < deadline and child_pid is None:
        tail = _log_tail(workers[0][2])
        for line in tail.splitlines():
            if line.startswith("CHILD_PID"):
                child_pid = int(line.split()[1])
        time.sleep(0.1)
    assert child_pid is not None

    with pytest.raises(WorkerFailure) as ei:
        _watch_workers(workers, timeout_s=1)
    assert ei.value.timed_out
    assert "worker with child" in str(ei.value)
    # the worker AND its child are gone (process-group kill)
    assert workers[0][1].poll() is not None
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline:
        try:
            os.kill(child_pid, 0)
        except ProcessLookupError:
            break
        time.sleep(0.1)
    else:
        os.kill(child_pid, 9)
        pytest.fail("worker's child survived the process-group kill")


def test_log_tail_truncates_and_survives_missing_files(tmp_path):
    p = tmp_path / "big.log"
    p.write_bytes(b"x" * 10000 + b"THE-END")
    tail = _log_tail(str(p), nbytes=100)
    assert tail.endswith("THE-END") and len(tail) <= 107
    assert "unreadable" in _log_tail(str(tmp_path / "nope.log"))


def test_distributed_init_retries_with_backoff(monkeypatch):
    """parallel/distributed.py: transient rendezvous failures are retried
    with exponential backoff, bounded by LGBMTPU_INIT_RETRIES; success on
    a later attempt initializes normally."""
    import jax

    from lightgbm_tpu.config import Config
    from lightgbm_tpu.parallel import distributed

    attempts = []
    sleeps = []

    def flaky_init(**kwargs):
        attempts.append(kwargs)
        if len(attempts) < 3:
            raise RuntimeError("coordination service unavailable (transient)")

    monkeypatch.setattr(jax.distributed, "initialize", flaky_init)
    monkeypatch.setattr(distributed.time, "sleep", sleeps.append)
    monkeypatch.setattr(distributed, "_initialized", False)
    monkeypatch.setenv("LIGHTGBM_TPU_RANK", "0")
    monkeypatch.setenv("LGBMTPU_INIT_RETRIES", "3")

    cfg = Config.from_dict({
        "num_machines": 2, "machines": "127.0.0.1:9999,127.0.0.1:9998",
        "local_listen_port": 9999, "time_out": 1})
    assert distributed.init_distributed(cfg) is True
    assert len(attempts) == 3
    assert sleeps == [1.0, 2.0]  # exponential backoff between attempts
    monkeypatch.setattr(distributed, "_initialized", False)


def test_distributed_init_exhausts_retries(monkeypatch):
    import jax

    from lightgbm_tpu.config import Config
    from lightgbm_tpu.parallel import distributed

    def always_fail(**kwargs):
        raise RuntimeError("coordinator never came up")

    monkeypatch.setattr(jax.distributed, "initialize", always_fail)
    monkeypatch.setattr(distributed.time, "sleep", lambda s: None)
    monkeypatch.setattr(distributed, "_initialized", False)
    monkeypatch.setenv("LIGHTGBM_TPU_RANK", "1")
    monkeypatch.setenv("LGBMTPU_INIT_RETRIES", "2")

    cfg = Config.from_dict({
        "num_machines": 2, "machines": "127.0.0.1:9999,127.0.0.1:9998",
        "local_listen_port": 9998, "time_out": 1})
    with pytest.raises(RuntimeError, match="never came up"):
        distributed.init_distributed(cfg)
    assert distributed._initialized is False


# ---------------------------------------------------------------------------
# hang-aware heartbeat watchdog (round 13) — thin subprocesses, no jax
# ---------------------------------------------------------------------------

def _write_heartbeat(path, value):
    import json

    with open(path, "w", encoding="utf-8") as fh:
        json.dump({"gauges": {"heartbeat_ts": value}}, fh)


def test_hung_worker_detected_via_stale_heartbeat(tmp_path):
    """A worker that stays ALIVE but whose heartbeat stops changing is
    declared hung within a bounded multiple of the timeout, killed, and
    reported as WorkerFailure(hung=True) — the exit-code watchdog alone
    would sit out the full launch timeout."""
    import threading

    from lightgbm_tpu.parallel.launcher import _watch_workers

    workers = [_worker(tmp_path, 0, "import time; time.sleep(600)")]
    hb_path = str(tmp_path / "w0.metrics.json")

    def beat():
        # two distinct values ARM staleness (round-1 compiles must not
        # trip the detector), then the heartbeat goes silent
        _write_heartbeat(hb_path, 1.0)
        time.sleep(0.4)
        _write_heartbeat(hb_path, 2.0)

    threading.Thread(target=beat, daemon=True).start()
    t0 = time.monotonic()
    with pytest.raises(WorkerFailure) as ei:
        _watch_workers(workers, timeout_s=600,
                       heartbeat_timeout_s=1.0,
                       heartbeat_paths={0: hb_path})
    elapsed = time.monotonic() - t0
    assert ei.value.hung and ei.value.rank == 0 and not ei.value.timed_out
    assert "HUNG" in str(ei.value)
    assert elapsed < 10, f"hang detection took {elapsed:.1f}s"
    assert workers[0][1].poll() is not None, "hung worker left alive"


def test_static_heartbeat_from_the_start_never_trips(tmp_path):
    """Staleness is armed only after the heartbeat has been seen to
    CHANGE: a value that is static from the first observation models (a)
    round-1 jit compilation and (b) a stale snapshot file left by a
    previous launch attempt — neither may be declared a hang."""
    from lightgbm_tpu.parallel.launcher import _watch_workers

    hb_path = str(tmp_path / "w0.metrics.json")
    _write_heartbeat(hb_path, 42.0)  # pre-existing, never changes
    workers = [_worker(tmp_path, 0, "import time; time.sleep(3)")]
    _watch_workers(workers, timeout_s=60,
                   heartbeat_timeout_s=0.5,
                   heartbeat_paths={0: hb_path})
    assert workers[0][1].returncode == 0


def test_missing_or_torn_heartbeat_file_is_not_a_hang(tmp_path):
    """No snapshot yet (worker still importing) and torn JSON both read
    as 'no heartbeat signal', covered by the launch timeout — not a
    hang verdict."""
    from lightgbm_tpu.parallel.launcher import (_read_heartbeat,
                                                _watch_workers)

    assert _read_heartbeat(str(tmp_path / "nope.json")) is None
    torn = tmp_path / "torn.json"
    torn.write_text('{"gauges": {"heartbeat_')
    assert _read_heartbeat(str(torn)) is None
    assert _read_heartbeat(None) is None

    workers = [_worker(tmp_path, 0, "import time; time.sleep(2)")]
    _watch_workers(workers, timeout_s=60, heartbeat_timeout_s=0.5,
                   heartbeat_paths={0: str(torn)})
    assert workers[0][1].returncode == 0


# ---------------------------------------------------------------------------
# slow-rank detection + live fleet collector (round 14) — thin processes
# ---------------------------------------------------------------------------

def test_slow_rank_detection_emits_event_and_counter(tmp_path, monkeypatch):
    """A rank that keeps beating but k x slower than the fleet median is
    DETECTED (event + counter + exported age), not killed — the class
    the full-stall watchdog can never see."""
    import threading

    from lightgbm_tpu.obs import metrics as _obs
    from lightgbm_tpu.parallel import launcher

    monkeypatch.setattr(launcher, "_SLOW_RANK_FLOOR_S", 0.05)
    # the effective floor adds 2x the snapshot period (write/read phase
    # aliasing headroom); shrink it so the thin-process stall qualifies
    monkeypatch.setenv("LGBMTPU_METRICS_SNAPSHOT_PERIOD_S", "0.1")
    workers = [_worker(tmp_path, r, "import time; time.sleep(6)")
               for r in range(3)]
    paths = {r: str(tmp_path / f"w{r}.metrics.json") for r in range(3)}
    stop = threading.Event()

    def beat():
        v = 0.0
        while not stop.is_set():
            v += 1.0
            for r in (0, 1):
                _write_heartbeat(paths[r], v)
            if v <= 12:  # rank 2 arms (changes across several polls)...
                _write_heartbeat(paths[2], v)
            time.sleep(0.2)  # ...then stalls at ~2.4 s while 0/1 beat on

    threading.Thread(target=beat, daemon=True).start()
    c0 = _obs.counter("fleet_slow_ranks_total").value
    ages = {}
    try:
        launcher._watch_workers(workers, timeout_s=60, heartbeat_paths=paths,
                                slow_rank_factor=3.0, hb_ages=ages)
    finally:
        stop.set()
    assert _obs.counter("fleet_slow_ranks_total").value >= c0 + 1
    evs = [e for e in _obs.events("fleet_slow_rank")
           if e.get("worker_rank") == 2]
    assert evs, "slow rank 2 not detected"
    assert evs[-1]["age_s"] > 0 and evs[-1]["factor"] == 3.0
    # no rank was killed: detection only
    assert all(p.returncode == 0 for _, p, _ in workers)


def test_slow_rank_not_tripped_by_healthy_jitter(tmp_path, monkeypatch):
    """All ranks beating at the same cadence: ages stay under the
    absolute floor and no slow-rank event fires."""
    import threading

    from lightgbm_tpu.obs import metrics as _obs
    from lightgbm_tpu.parallel import launcher

    workers = [_worker(tmp_path, r, "import time; time.sleep(3)")
               for r in range(2)]
    paths = {r: str(tmp_path / f"h{r}.metrics.json") for r in range(2)}
    stop = threading.Event()

    def beat():
        v = 0.0
        while not stop.is_set():
            v += 1.0
            for r in range(2):
                _write_heartbeat(paths[r], v)
            time.sleep(0.2)

    threading.Thread(target=beat, daemon=True).start()
    c0 = _obs.counter("fleet_slow_ranks_total").value
    try:
        launcher._watch_workers(workers, timeout_s=60, heartbeat_paths=paths,
                                slow_rank_factor=3.0, hb_ages={})
    finally:
        stop.set()
    assert _obs.counter("fleet_slow_ranks_total").value == c0


def test_fleet_live_collector_labels_ranks_and_skips_torn(tmp_path):
    """The launcher-side live collector merges per-rank snapshot files
    into rank-labeled metric names (+ heartbeat ages from the watchdog's
    shared dict); a torn rank file skips one scrape, never raises."""
    import json

    from lightgbm_tpu.obs import metrics as _obs
    from lightgbm_tpu.parallel.launcher import _fleet_live_collector

    for r in range(2):
        (tmp_path / f"worker{r}.metrics.json").write_text(json.dumps(
            {"counters": {"boost_rounds_total": 5 + r},
             "gauges": {"heartbeat_ts": 1.5}}))
    (tmp_path / "worker2.metrics.json").write_text('{"torn')
    out = _fleet_live_collector(str(tmp_path), 3, {0: 0.0, 1: 2.5})()
    assert out["counters"]['boost_rounds_total{rank="0"}'] == 5
    assert out["counters"]['boost_rounds_total{rank="1"}'] == 6
    assert out["gauges"]['heartbeat_ts{rank="1"}'] == 1.5
    assert out["gauges"]['fleet_heartbeat_age_s{rank="1"}'] == 2.5
    assert not any('rank="2"' in k for k in out["counters"])

    # registered, the families reach the Prometheus exposition with real
    # label sets — what a dashboard scraping the LAUNCHER's endpoint sees
    _obs.REGISTRY.register_collector(
        "fleet_live", _fleet_live_collector(str(tmp_path), 3, {1: 2.5}))
    try:
        text = _obs.render_prometheus()
        assert 'fleet_heartbeat_age_s{rank="1"} 2.5' in text
        assert 'boost_rounds_total{rank="0"} 5' in text
    finally:
        _obs.REGISTRY.register_collector("fleet_live", lambda: {})


def test_slow_rank_median_is_per_slice_not_fleet_wide(tmp_path, monkeypatch):
    """ISSUE 15 satellite: with ``slice_of`` the straggler threshold
    medians WITHIN each slice.  A uniformly slow slice 1 (both ranks at a
    lazy-but-matched cadence) must not inflate the comparison median for
    slice 0, where rank 1 genuinely stalls against a fast peer — the
    fleet-wide median (~the slow slice's cadence) would have hidden it."""
    import threading

    from lightgbm_tpu.obs import metrics as _obs
    from lightgbm_tpu.parallel import launcher

    monkeypatch.setattr(launcher, "_SLOW_RANK_FLOOR_S", 0.05)
    monkeypatch.setenv("LGBMTPU_METRICS_SNAPSHOT_PERIOD_S", "0.1")
    workers = [_worker(tmp_path, r, "import time; time.sleep(7)")
               for r in range(4)]
    paths = {r: str(tmp_path / f"s{r}.metrics.json") for r in range(4)}
    slice_of = {0: 0, 1: 0, 2: 1, 3: 1}
    stop = threading.Event()

    def beat():
        v = 0.0
        while not stop.is_set():
            v += 1.0
            _write_heartbeat(paths[0], v)          # slice 0: fast peer
            if v <= 12:                            # slice 0: rank 1 arms...
                _write_heartbeat(paths[1], v)      # ...then stalls
            if v % 4 == 0:                         # slice 1: slow cadence
                _write_heartbeat(paths[2], v)      # (0.8 s — under the
                _write_heartbeat(paths[3], v)      # 1.2 s floor, so read-
                # phase desync between its two matched ranks can't trip)
            time.sleep(0.2)

    threading.Thread(target=beat, daemon=True).start()
    c0 = _obs.counter("fleet_slow_ranks_total").value
    # the event ring is process-wide: scope to THIS watch (earlier tests
    # in this module emit fleet_slow_rank events for their own ranks)
    ev0 = len(list(_obs.events("fleet_slow_rank")))
    ages = {}
    try:
        launcher._watch_workers(workers, timeout_s=60, heartbeat_paths=paths,
                                slow_rank_factor=3.0, hb_ages=ages,
                                slice_of=slice_of)
    finally:
        stop.set()
    evs = list(_obs.events("fleet_slow_rank"))[ev0:]
    flagged = {e["worker_rank"] for e in evs}
    assert 1 in flagged, "intra-slice straggler missed"
    # the matched-cadence slow slice never trips — its own median IS its
    # cadence; and no event ever compared against a cross-slice median
    # (the slice-0 events' median is the fast peer's age, well under the
    # slow slice's ~1.6 s cadence)
    assert not ({2, 3} & flagged), evs
    r1 = [e for e in evs if e["worker_rank"] == 1]
    assert all(e.get("slice") == 0 for e in r1)
    assert all(e["fleet_median_s"] < 1.0 for e in r1), r1
    assert _obs.counter("fleet_slow_ranks_total").value >= c0 + 1
    assert all(p.returncode == 0 for _, p, _ in workers)  # detection only
