"""Fused training-step guards (models/gbdt.py _fused_eligible/_get_fused_step)."""

import numpy as np
import jax.numpy as jnp
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.ops.histogram import histogram_onehot_multi, histogram_scatter

pytestmark = pytest.mark.slow


def _fit(params, n=400, rounds=3, rank=False):
    rng = np.random.RandomState(0)
    X = rng.randn(n, 4)
    if rank:
        y = rng.randint(0, 3, n).astype(float)
        d = lgb.Dataset(X, label=y, group=np.full(n // 20, 20))
    else:
        y = (X[:, 0] > 0).astype(float)
        d = lgb.Dataset(X, label=y)
    bst = lgb.train({**params, "verbosity": -1}, d, num_boost_round=rounds)
    return bst


def test_stateful_ranking_objectives_not_fused():
    # rank_xendcg draws fresh RNG per iteration -> never fusable
    bst = _fit({"objective": "rank_xendcg", "tree_growth_mode": "rounds"}, rank=True)
    assert not bst._gbdt._fused_eligible(None)
    assert bst.num_trees() == 3


def test_position_bias_lambdarank_fuses_and_matches():
    # position-bias state rides the fused step as a carry (fused_state
    # protocol) — the fused run must reproduce the unfused run exactly,
    # including the learned biases
    rng = np.random.RandomState(0)
    X = rng.randn(400, 4)
    y = rng.randint(0, 3, 400).astype(float)
    params = {"objective": "lambdarank", "verbosity": -1, "num_leaves": 7,
              "lambdarank_position_bias_regularization": 0.1,
              "tree_growth_mode": "rounds"}
    preds, biases = {}, {}
    for fuse in (True, False):
        d = lgb.Dataset(X, label=y, group=np.full(20, 20),
                        position=np.tile(np.arange(20), 20))
        bst = lgb.Booster(params=params, train_set=d)
        if fuse:
            assert bst._gbdt._fused_eligible(None)
        else:
            bst._gbdt._fused_eligible = lambda grad: False
        for _ in range(3):
            bst.update()
        preds[fuse] = bst.predict(X)
        biases[fuse] = np.asarray(bst._gbdt.objective.pos_bias)
    assert np.abs(biases[True]).max() > 0  # biases actually learned
    np.testing.assert_allclose(biases[True], biases[False], rtol=1e-5,
                               atol=1e-7)
    np.testing.assert_allclose(preds[True], preds[False], rtol=1e-5,
                               atol=1e-7)


def test_plain_lambdarank_fuses_and_matches():
    rng = np.random.RandomState(5)
    X = rng.randn(400, 4)
    y = rng.randint(0, 3, 400).astype(float)
    params = {"objective": "lambdarank", "verbosity": -1,
              "num_leaves": 7, "tree_growth_mode": "rounds"}
    preds = {}
    for fuse in (True, False):
        d = lgb.Dataset(X, label=y, group=np.full(20, 20))
        bst = lgb.Booster(params=params, train_set=d)
        if fuse:
            assert bst._gbdt._fused_eligible(None)
        else:
            bst._gbdt._fused_eligible = lambda grad: False
        for _ in range(3):
            bst.update()
        preds[fuse] = bst.predict(X)
    np.testing.assert_allclose(preds[True], preds[False], rtol=1e-5, atol=1e-7)


def test_reset_parameter_schedule_does_not_invalidate_fused_cache():
    bst = _fit({"objective": "binary", "tree_growth_mode": "rounds"})
    g = bst._gbdt
    if not g._fused_eligible(None):
        pytest.skip("fused path not engaged on this backend")
    step_before = g._get_fused_step()
    # learning_rate is a traced runtime arg: schedule changes must keep cache
    g.cfg.update({"learning_rate": 0.05})
    g.reset_split_params()
    assert g._fused_step is step_before
    # a baked constant (lambda_l2) must invalidate
    g.cfg.update({"lambda_l2": 3.0})
    g.reset_split_params()
    assert g._fused_step is None


def test_fused_path_matches_unfused():
    rng = np.random.RandomState(1)
    X = rng.randn(600, 5)
    y = (X[:, 0] + 0.5 * X[:, 1] > 0).astype(float)
    preds = {}
    for mode, fuse in (("rounds", True), ("rounds", False)):
        d = lgb.Dataset(X, label=y)
        bst = lgb.Booster(params={"objective": "binary", "num_leaves": 7,
                                  "verbosity": -1, "tree_growth_mode": mode},
                          train_set=d)
        if not fuse:
            # force the unfused path
            bst._gbdt._fused_eligible = lambda grad: False
        for _ in range(4):
            bst.update()
        preds[fuse] = bst.predict(X)
    np.testing.assert_allclose(preds[True], preds[False], rtol=1e-5, atol=1e-7)


def test_fused_goss_matches_unfused():
    """In-trace GOSS uses the same PRNG stream and formula as the host
    path, so fused and unfused training must build identical models."""
    rng = np.random.RandomState(3)
    X = rng.randn(800, 5)
    y = (X[:, 0] + 0.5 * rng.randn(800) > 0).astype(float)
    params = {"objective": "binary", "num_leaves": 7, "verbosity": -1,
              "data_sample_strategy": "goss", "learning_rate": 0.5,
              "top_rate": 0.3, "other_rate": 0.2,
              "tree_growth_mode": "rounds"}
    preds = {}
    for fuse in (True, False):
        d = lgb.Dataset(X, label=y)
        bst = lgb.Booster(params=params, train_set=d)
        if not fuse:
            bst._gbdt._fused_eligible = lambda grad: False
        for _ in range(6):  # warmup = 2 iters at lr 0.5, then real GOSS
            bst.update()
        preds[fuse] = bst.predict(X)
    np.testing.assert_allclose(preds[True], preds[False], rtol=1e-5, atol=1e-7)


def test_fused_multiclass_matches_unfused():
    rng = np.random.RandomState(4)
    X = rng.randn(500, 5)
    y = rng.randint(0, 3, 500)
    params = {"objective": "multiclass", "num_class": 3, "num_leaves": 7,
              "verbosity": -1, "tree_growth_mode": "rounds"}
    preds = {}
    for fuse in (True, False):
        d = lgb.Dataset(X, label=y.astype(float))
        bst = lgb.Booster(params=params, train_set=d)
        if not fuse:
            bst._gbdt._fused_eligible = lambda grad: False
        for _ in range(3):
            bst.update()
        assert bst.num_trees() == 9 if fuse else True
        preds[fuse] = bst.predict(X)
    np.testing.assert_allclose(preds[True], preds[False], rtol=1e-5, atol=1e-7)


def test_onehot_multi_bf16_precision():
    n, F, B, L = 3000, 4, 32, 2
    rng = np.random.RandomState(2)
    bins = jnp.asarray(rng.randint(0, B, size=(n, F)).astype(np.int16))
    grad = jnp.asarray(rng.randn(n).astype(np.float32))
    hess = jnp.asarray(rng.rand(n).astype(np.float32))
    mask = jnp.ones((n,), bool)
    lid = jnp.asarray(rng.randint(0, L, size=(n,)).astype(np.int32))
    out = histogram_onehot_multi(bins, grad, hess, mask, lid, 0, L, B,
                                 precision="bf16")
    assert out.shape == (L, 3, F, B)
    ref = histogram_scatter(bins, grad, hess, (lid == 0).astype(jnp.float32), B)
    scale = np.abs(np.asarray(ref)).max() + 1
    rel = np.max(np.abs(np.asarray(out[0]) - np.asarray(ref))) / scale
    assert rel < 5e-3  # bf16-rounded payload tolerance


def test_fused_failure_falls_back_to_unfused():
    # a compile/transport failure in the fused step must degrade to the
    # unfused path, not kill training
    bst = _fit({"objective": "binary", "tree_growth_mode": "rounds"}, rounds=1)
    g = bst._gbdt
    if not g._fused_eligible(None):
        pytest.skip("fused path not engaged on this backend")

    def boom():
        def step(*a, **k):
            raise RuntimeError("synthetic remote-compile failure")
        return step

    g._get_fused_step = boom
    assert not g.train_one_iter()  # completes via the unfused path
    assert g._fused_disabled
    assert not g._fused_eligible(None)
    assert bst.num_trees() == 2
