"""Distributed (SPMD) tests on the virtual 8-device CPU mesh.

Reference test-strategy analogue: tests/python_package_test/test_dask.py
(distributed model ~ single-process model) and
tests/distributed/_test_distributed.py (SURVEY.md §5.2-5.3).
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.binning import DatasetBinner
from lightgbm_tpu.ops.split import SplitParams
from lightgbm_tpu.ops.treegrow import grow_tree
from lightgbm_tpu.parallel.data_parallel import ShardedData, grow_tree_data_parallel
from lightgbm_tpu.parallel.mesh import make_mesh


@pytest.fixture(scope="module")
def synth():
    rng = np.random.RandomState(0)
    n, f = 4000, 10
    X = rng.randn(n, f)
    w = rng.randn(f)
    y = ((X @ w + rng.randn(n)) > 0).astype(np.float64)
    return X, y

pytestmark = pytest.mark.slow


def test_eight_devices_available():
    assert jax.device_count() >= 8


def test_dp_tree_matches_serial(synth):
    """Data-parallel growth must produce the same tree as serial growth
    (reference invariant: every rank applies the identical split)."""
    X, y = synth
    n, f = X.shape
    binner = DatasetBinner.fit(X, max_bin=63)
    bins = binner.transform(X)
    rng = np.random.RandomState(1)
    grad = (0.5 - y + 0.1 * rng.rand(n)).astype(np.float32)
    hess = np.full(n, 0.25, np.float32)
    params = SplitParams(min_data_in_leaf=10)

    tree_s, leaf_s = grow_tree(
        jnp.asarray(bins.astype(np.int32)), jnp.asarray(grad), jnp.asarray(hess),
        jnp.ones(n, bool), jnp.ones(n, jnp.float32), jnp.ones(f, bool),
        jnp.asarray(binner.num_bins_per_feature), jnp.asarray(binner.missing_bin_per_feature),
        num_leaves=15, num_bins=binner.max_num_bins, params=params,
    )

    mesh = make_mesh(8)
    sharded = ShardedData(mesh, bins, binner.num_bins_per_feature, binner.missing_bin_per_feature)
    tree_d, leaf_d = grow_tree_data_parallel(
        sharded,
        sharded.pad_rows(grad),
        sharded.pad_rows(hess),
        sharded.row_valid,
        sharded.pad_rows(np.ones(n, np.float32), fill=1.0),
        jnp.ones(f, bool),
        num_leaves=15, num_bins=binner.max_num_bins, params=params,
    )

    assert int(tree_s.num_leaves) == int(tree_d.num_leaves)
    m = int(tree_s.num_leaves) - 1
    np.testing.assert_array_equal(
        np.asarray(tree_s.split_feature)[:m], np.asarray(tree_d.split_feature)[:m]
    )
    np.testing.assert_array_equal(
        np.asarray(tree_s.threshold_bin)[:m], np.asarray(tree_d.threshold_bin)[:m]
    )
    np.testing.assert_allclose(
        np.asarray(tree_s.leaf_value)[: m + 1], np.asarray(tree_d.leaf_value)[: m + 1],
        rtol=2e-3, atol=2e-3,
    )
    np.testing.assert_array_equal(np.asarray(leaf_s), np.asarray(leaf_d)[:n])


def test_end_to_end_data_parallel_close_to_serial(synth):
    """Full training with tree_learner=data ~ serial (reference: test_dask.py
    asserts distributed model predictions close to single-process)."""
    X, y = synth
    params = {"objective": "binary", "num_leaves": 15, "verbosity": -1,
              "min_data_in_leaf": 10, "max_bin": 63}
    b_serial = lgb.train(dict(params), lgb.Dataset(X, label=y), num_boost_round=10)
    b_dp = lgb.train(dict(params, tree_learner="data"), lgb.Dataset(X, label=y), num_boost_round=10)
    assert b_dp._gbdt._dp is not None, "data-parallel path not engaged"
    p_s = b_serial.predict(X, raw_score=True)
    p_d = b_dp.predict(X, raw_score=True)
    np.testing.assert_allclose(p_s, p_d, rtol=5e-3, atol=5e-3)


def test_dryrun_multichip_entry():
    import sys
    sys.path.insert(0, "/root/repo")
    import __graft_entry__ as ge

    ge.dryrun_multichip(8)


def test_dryrun_multislice_entry():
    """The BENCH_MODE=multislice lever's hermetic subprocess dryrun: the
    hierarchical round over a 2x2 nested mesh equals the single-mesh
    sharded round at full top-k coverage (ISSUE 15)."""
    import sys
    sys.path.insert(0, "/root/repo")
    import __graft_entry__ as ge

    ge.dryrun_multislice_windowed(2, 2, "psum")


def test_entry_compiles():
    import sys
    sys.path.insert(0, "/root/repo")
    import __graft_entry__ as ge

    fn, args = ge.entry()
    out = jax.jit(fn)(*args)
    assert np.asarray(out).shape == (args[0].shape[0],)


def test_fp_tree_matches_serial(synth):
    """Feature-parallel growth (features sharded, rows replicated) must equal
    the serial tree (reference: FeatureParallelTreeLearner applies the
    identical split on every machine)."""
    from lightgbm_tpu.parallel.feature_parallel import (
        FeatureShardedData, grow_tree_feature_parallel,
    )

    X, y = synth
    n, f = X.shape
    binner = DatasetBinner.fit(X, max_bin=63)
    bins = binner.transform(X)
    rng = np.random.RandomState(2)
    grad = (0.5 - y + 0.1 * rng.rand(n)).astype(np.float32)
    hess = np.full(n, 0.25, np.float32)
    params = SplitParams(min_data_in_leaf=10)

    tree_s, leaf_s = grow_tree(
        jnp.asarray(bins.astype(np.int32)), jnp.asarray(grad), jnp.asarray(hess),
        jnp.ones(n, bool), jnp.ones(n, jnp.float32), jnp.ones(f, bool),
        jnp.asarray(binner.num_bins_per_feature), jnp.asarray(binner.missing_bin_per_feature),
        num_leaves=15, num_bins=binner.max_num_bins, params=params,
    )

    mesh = make_mesh(8)
    fsh = FeatureShardedData(mesh, bins, binner.num_bins_per_feature,
                             binner.missing_bin_per_feature)
    tree_f, leaf_f = grow_tree_feature_parallel(
        fsh, jnp.asarray(grad), jnp.asarray(hess), jnp.ones(n, bool),
        jnp.ones(n, jnp.float32), np.ones(f, bool),
        num_leaves=15, num_bins=binner.max_num_bins, params=params,
    )
    assert int(tree_s.num_leaves) == int(tree_f.num_leaves)
    m = int(tree_s.num_leaves) - 1
    np.testing.assert_array_equal(
        np.asarray(tree_s.split_feature)[:m], np.asarray(tree_f.split_feature)[:m]
    )
    np.testing.assert_array_equal(
        np.asarray(tree_s.threshold_bin)[:m], np.asarray(tree_f.threshold_bin)[:m]
    )
    np.testing.assert_allclose(
        np.asarray(tree_s.leaf_value)[: m + 1], np.asarray(tree_f.leaf_value)[: m + 1],
        rtol=2e-3, atol=2e-3,
    )
    np.testing.assert_array_equal(np.asarray(leaf_s), np.asarray(leaf_f)[:n])


def test_end_to_end_feature_parallel(synth):
    X, y = synth
    params = {"objective": "binary", "num_leaves": 15, "verbosity": -1,
              "min_data_in_leaf": 10, "max_bin": 63}
    b_serial = lgb.train(dict(params), lgb.Dataset(X, label=y), num_boost_round=8)
    b_fp = lgb.train(dict(params, tree_learner="feature"), lgb.Dataset(X, label=y), num_boost_round=8)
    assert b_fp._gbdt._fp is not None, "feature-parallel path not engaged"
    np.testing.assert_allclose(
        b_serial.predict(X, raw_score=True), b_fp.predict(X, raw_score=True),
        rtol=5e-3, atol=5e-3,
    )


def test_end_to_end_voting_parallel(synth):
    """Voting-parallel (PV-Tree): with top_k >= num_features the election is
    exhaustive, so the model must match data-parallel/serial closely; with a
    small top_k it must still train a usable model (reference:
    VotingParallelTreeLearner is an approximation by design)."""
    X, y = synth
    params = {"objective": "binary", "num_leaves": 15, "verbosity": -1,
              "min_data_in_leaf": 10, "max_bin": 63}
    b_serial = lgb.train(dict(params), lgb.Dataset(X, label=y), num_boost_round=8)
    b_vp_full = lgb.train(
        dict(params, tree_learner="voting", top_k=X.shape[1]),
        lgb.Dataset(X, label=y), num_boost_round=8,
    )
    np.testing.assert_allclose(
        b_serial.predict(X, raw_score=True), b_vp_full.predict(X, raw_score=True),
        rtol=5e-3, atol=5e-3,
    )
    b_vp = lgb.train(
        dict(params, tree_learner="voting", top_k=3),
        lgb.Dataset(X, label=y), num_boost_round=8,
    )
    pred = b_vp.predict(X)
    acc = float(((pred > 0.5) == (y > 0.5)).mean())
    assert acc > 0.8, acc


def test_rounds_grower_serial_equals_data_parallel():
    """The round-batched grower must produce the identical tree under SPMD
    data parallelism (per-round psum merge) as serially."""
    import jax
    import jax.numpy as jnp
    from lightgbm_tpu.ops.split import SplitParams
    from lightgbm_tpu.ops.treegrow_fast import grow_tree_fast
    from lightgbm_tpu.parallel.data_parallel import (
        ShardedData, grow_tree_fast_data_parallel,
    )
    from lightgbm_tpu.parallel.mesh import make_mesh

    rng = np.random.RandomState(11)
    n, f, B = 4096, 6, 32
    bins = rng.randint(0, B - 1, size=(n, f)).astype(np.int32)
    y = (bins[:, 0] + bins[:, 1] > B).astype(np.float32)
    grad = (0.5 - y).astype(np.float32)
    hess = np.full(n, 0.25, np.float32)
    nbpf = np.full(f, B, np.int32)
    mbpf = np.full(f, -1, np.int32)
    params = SplitParams(min_data_in_leaf=5)

    t_serial, _ = grow_tree_fast(
        jnp.asarray(bins), jnp.asarray(grad), jnp.asarray(hess),
        jnp.ones((n,), bool), jnp.ones((n,), jnp.float32),
        jnp.ones((f,), bool), jnp.asarray(nbpf), jnp.asarray(mbpf),
        num_leaves=15, num_bins=B, params=params, use_pallas=False,
    )

    mesh = make_mesh()
    sd = ShardedData(mesh, bins, nbpf, mbpf)
    t_dp, _ = grow_tree_fast_data_parallel(
        sd, sd.pad_rows(grad), sd.pad_rows(hess),
        sd.pad_rows(np.ones(n, bool), fill=False),
        sd.pad_rows(np.ones(n, np.float32), fill=1.0),
        jnp.ones((f,), bool),
        num_leaves=15, num_bins=B, params=params, use_pallas=False,
    )
    assert int(t_serial.num_leaves) == int(t_dp.num_leaves)
    for name in ("split_feature", "threshold_bin", "left_child", "right_child"):
        np.testing.assert_array_equal(
            np.asarray(getattr(t_serial, name)), np.asarray(getattr(t_dp, name))
        )
    np.testing.assert_allclose(
        np.asarray(t_serial.leaf_value), np.asarray(t_dp.leaf_value),
        rtol=1e-5, atol=1e-5,
    )


def test_booster_data_parallel_rounds_mode_trains():
    """Booster-level: tree_learner=data + rounds grower (the async fast-DP
    dispatch incl. device-side pad/reshard) trains and predicts sanely."""
    rng = np.random.RandomState(12)
    X = rng.randn(4000, 6).astype(np.float32)
    y = ((X @ rng.randn(6)) > 0).astype(np.float64)
    import lightgbm_tpu as lgb

    ds = lgb.Dataset(X, label=y)
    bst = lgb.Booster(
        params={"objective": "binary", "num_leaves": 15, "verbosity": -1,
                "tree_learner": "data", "tree_growth_mode": "rounds"},
        train_set=ds,
    )
    for _ in range(8):
        bst.update()
    assert bst._gbdt._use_fast_dp  # the fast-DP branch actually ran
    p = bst.predict(X)
    acc = np.mean((p > 0.5) == (y > 0))
    assert acc > 0.9


@pytest.mark.parametrize("mode", ["strict", "rounds"])
def test_data_parallel_monotone_intermediate(mode):
    """Intermediate monotone bounds under tree_learner=data on the 8-device
    mesh: leaf state is replicated (hists psummed before split search), so
    the bound recomputation is SPMD-safe in both growth modes and the
    trained model must be pointwise monotone (PARITY.md row 29)."""
    rng = np.random.RandomState(4)
    n = 2000
    X = rng.randn(n, 3)
    y = (2.0 * X[:, 0] + np.sin(3 * X[:, 0]) - 1.5 * X[:, 1]
         + np.sin(2 * X[:, 2]) + 0.1 * rng.randn(n))
    bst = lgb.train(
        {"objective": "regression", "num_leaves": 15, "verbosity": -1,
         "tree_learner": "data", "tree_growth_mode": mode,
         "min_data_in_leaf": 5, "max_bin": 63,
         "monotone_constraints": [1, -1, 0],
         "monotone_constraints_method": "intermediate"},
        lgb.Dataset(X, label=y), 8)
    assert bst._gbdt._dp is not None, "data-parallel path not engaged"
    for f_idx, sign in ((0, 1), (1, -1)):
        for i in range(10):
            rows = np.repeat(rng.randn(1, 3), 60, axis=0)
            rows[:, f_idx] = np.linspace(-2.5, 2.5, 60)
            d = np.diff(bst.predict(rows)) * sign
            assert d.min() >= -1e-6, (mode, f_idx, d.min())


# ---------------------------------------------------------------------------
# sharded fused windowed rounds (round 14 tentpole)
# ---------------------------------------------------------------------------

def _windowed_case(seed=5, n=1600, f=10, quant=0):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, f)
    y = X @ rng.randn(f) + 0.2 * rng.randn(n)
    binner = DatasetBinner.fit(X, max_bin=31)
    bins = binner.transform(X)
    grad = jnp.asarray(0.6 * y, jnp.float32)
    hess = jnp.ones((n,), jnp.float32)
    kw = dict(num_leaves=15, num_bins=32,
              params=SplitParams(min_data_in_leaf=5.0), leaf_tile=4,
              use_pallas=False)
    if quant:
        kw.update(quantize_bins=quant, stochastic_rounding=False,
                  quant_renew=True)
    return X, bins, binner, grad, hess, kw


def _assert_same_tree(tree_s, tree_d, leaf_s, leaf_d, n):
    assert int(tree_s.num_leaves) == int(tree_d.num_leaves)
    m = int(tree_s.num_leaves) - 1
    for name in ("split_feature", "threshold_bin", "left_child",
                 "right_child", "default_left"):
        np.testing.assert_array_equal(
            np.asarray(getattr(tree_s, name))[:m],
            np.asarray(getattr(tree_d, name))[:m], err_msg=name)
    np.testing.assert_allclose(
        np.asarray(tree_s.leaf_value)[:m + 1],
        np.asarray(tree_d.leaf_value)[:m + 1], rtol=2e-3, atol=2e-3)
    np.testing.assert_array_equal(np.asarray(leaf_s), np.asarray(leaf_d)[:n])


@pytest.mark.parametrize("quant", [0, 16], ids=["float", "quantized"])
@pytest.mark.parametrize("merge", ["psum", "scatter"])
def test_sharded_fused_windowed_equals_single_device(merge, quant):
    """ISSUE 9 acceptance: loopback-mesh sharded fused windowed training
    (in-dispatch psum / owned-feature psum_scatter merge) produces the
    SAME tree as single-device windowed growth — split structure exactly,
    leaf values to collective-ordering tolerance, shard-local leaf ids
    equal to the serial ones — for float and int8-quantized training on
    both merge strategies, with zero window retries and zero blocking
    syncs."""
    from lightgbm_tpu.ops.treegrow_windowed import grow_tree_windowed
    from lightgbm_tpu.parallel.data_parallel import (
        grow_tree_windowed_data_parallel)

    X, bins, binner, grad, hess, kw = _windowed_case(quant=quant)
    n, f = X.shape
    qk = jax.random.PRNGKey(3) if quant else None
    tree_s, leaf_s = grow_tree_windowed(
        jnp.asarray(bins.T, jnp.int16), grad, hess,
        jnp.ones((n,), bool), jnp.ones((n,), jnp.float32),
        jnp.ones((f,), bool),
        jnp.asarray(binner.num_bins_per_feature),
        jnp.asarray(binner.missing_bin_per_feature), quant_key=qk, **kw)

    mesh = make_mesh()
    sd = ShardedData(mesh, bins, binner.num_bins_per_feature,
                     binner.missing_bin_per_feature)
    stats = {}
    tree_d, leaf_d = grow_tree_windowed_data_parallel(
        sd, sd.pad_rows(np.asarray(grad)), sd.pad_rows(np.asarray(hess)),
        sd.row_valid, sd.pad_rows(np.ones(n, np.float32), fill=1.0),
        jnp.ones((f,), bool), quant_key=qk, merge=merge, stats=stats, **kw)
    assert stats["retries"] == 0 and stats["host_syncs"] == 0, stats
    _assert_same_tree(tree_s, tree_d, leaf_s, leaf_d, n)


def test_sharded_windowed_scatter_pads_undivisible_features():
    """merge='scatter' needs F divisible by the mesh axis; a 10-feature
    matrix on 8 devices pads to 16 dead features — the padded features
    must never win a split and the tree must still match psum's."""
    from lightgbm_tpu.parallel.data_parallel import (
        grow_tree_windowed_data_parallel)

    X, bins, binner, grad, hess, kw = _windowed_case(seed=8)
    n, f = X.shape
    assert f % 8 != 0  # the case under test
    mesh = make_mesh()
    sd = ShardedData(mesh, bins, binner.num_bins_per_feature,
                     binner.missing_bin_per_feature)
    args = (sd, sd.pad_rows(np.asarray(grad)), sd.pad_rows(np.asarray(hess)),
            sd.row_valid, sd.pad_rows(np.ones(n, np.float32), fill=1.0),
            jnp.ones((f,), bool))
    t_ps, l_ps = grow_tree_windowed_data_parallel(*args, merge="psum", **kw)
    t_sc, l_sc = grow_tree_windowed_data_parallel(*args, merge="scatter",
                                                  **kw)
    m = int(t_ps.num_leaves) - 1
    assert np.asarray(t_sc.split_feature)[:m].max() < f
    _assert_same_tree(t_ps, t_sc, l_ps[:n], l_sc, n)


def test_sharded_windowed_scatter_refuses_bynode_sampling():
    from lightgbm_tpu.parallel.data_parallel import (
        grow_tree_windowed_data_parallel)

    X, bins, binner, grad, hess, kw = _windowed_case()
    n, f = X.shape
    mesh = make_mesh()
    sd = ShardedData(mesh, bins, binner.num_bins_per_feature,
                     binner.missing_bin_per_feature)
    kw["params"] = SplitParams(min_data_in_leaf=5.0,
                               feature_fraction_bynode=0.5)
    with pytest.raises(ValueError, match="scatter"):
        grow_tree_windowed_data_parallel(
            sd, sd.pad_rows(np.asarray(grad)), sd.pad_rows(np.asarray(hess)),
            sd.row_valid, sd.pad_rows(np.ones(n, np.float32), fill=1.0),
            jnp.ones((f,), bool), rng_key=jax.random.PRNGKey(0),
            merge="scatter", **kw)


def test_booster_sharded_windowed_data_and_voting(monkeypatch):
    """Booster-level routing: tree_learner=data|voting with the windowed
    gate forced (the real gate needs a TPU + wide shape) takes the
    sharded fused path and trains an accurate model; voting maps to the
    owned-feature scatter merge."""
    from lightgbm_tpu.models.gbdt import GBDT

    rng = np.random.RandomState(12)
    X = rng.randn(4000, 6).astype(np.float32)
    y = ((X @ rng.randn(6)) > 0).astype(np.float64)
    monkeypatch.setattr(GBDT, "_use_windowed_dp",
                        lambda self, ts: self._dp is not None)
    for tl, want_merge in (("data", "psum"), ("voting", "scatter")):
        ds = lgb.Dataset(X, label=y)
        bst = lgb.Booster(
            params={"objective": "binary", "num_leaves": 15,
                    "verbosity": -1, "tree_learner": tl,
                    "tree_growth_mode": "rounds"}, train_set=ds)
        assert bst._gbdt._windowed_dp_merge() == want_merge
        for _ in range(6):
            bst.update()
        p = bst.predict(X)
        acc = np.mean((p > 0.5) == (y > 0))
        assert acc > 0.9, (tl, acc)
