"""Tests for the leaf-ordered partition op (ops/partition.py — the
DataPartition analogue that round 3's windowed histogram passes build on)."""

import numpy as np
import pytest

from lightgbm_tpu.ops.partition import stable_partition_ranges


def _ref_partition(order, seg_id, seg_start, seg_len, go_left):
    out = order.copy()
    lefts = np.zeros(len(seg_start), np.int32)
    for s in range(len(seg_start)):
        lo, ln = seg_start[s], seg_len[s]
        if ln == 0:
            continue
        pos = np.arange(lo, lo + ln)
        gl = go_left[pos]
        out[lo:lo + ln] = np.concatenate([order[pos][gl], order[pos][~gl]])
        lefts[s] = gl.sum()
    return out, lefts


def test_stable_partition_matches_reference_semantics():
    rng = np.random.RandomState(0)
    n = 10_000
    order = rng.permutation(n).astype(np.int32)
    # carve 4 disjoint segments; the rest untouched
    seg_start = np.asarray([0, 3000, 5000, 9000], np.int32)
    seg_len = np.asarray([1500, 800, 2500, 1000], np.int32)
    seg_id = np.full(n, -1, np.int32)
    for s, (lo, ln) in enumerate(zip(seg_start, seg_len)):
        seg_id[lo:lo + ln] = s
    go_left = rng.rand(n) < 0.4

    got, got_l = stable_partition_ranges(order, seg_id, seg_start, seg_len, go_left)
    want, want_l = _ref_partition(order, seg_id, seg_start, seg_len, go_left)
    np.testing.assert_array_equal(np.asarray(got), want)
    np.testing.assert_array_equal(np.asarray(got_l), want_l)


def test_stable_partition_all_one_side_and_empty_segments():
    order = np.arange(100, dtype=np.int32)
    seg_start = np.asarray([10, 50], np.int32)
    seg_len = np.asarray([20, 0], np.int32)
    seg_id = np.full(100, -1, np.int32)
    seg_id[10:30] = 0
    go_left = np.zeros(100, bool)  # everything right
    got, lefts = stable_partition_ranges(order, seg_id, seg_start, seg_len, go_left)
    np.testing.assert_array_equal(np.asarray(got), order)
    assert int(lefts[0]) == 0 and int(lefts[1]) == 0
    go_left[:] = True  # everything left
    got, lefts = stable_partition_ranges(order, seg_id, seg_start, seg_len, go_left)
    np.testing.assert_array_equal(np.asarray(got), order)
    assert int(lefts[0]) == 20


def test_partition_pallas_matches_xla_path():
    """The Pallas segment kernel (interpret mode — tier-1 has no TPU) must
    reproduce stable_partition_ranges bit-for-bit: same stable order
    within every segment, same left counts, untouched positions intact."""
    import jax.numpy as jnp

    from lightgbm_tpu.ops.partition import partition_rows

    rng = np.random.RandomState(3)
    n = 6000
    order = rng.permutation(n).astype(np.int32)
    seg_start = np.asarray([100, 1500, 2048, 5800], np.int32)
    seg_len = np.asarray([900, 500, 3000, 200], np.int32)
    seg_id = np.full(n, -1, np.int32)
    for s, (lo, ln) in enumerate(zip(seg_start, seg_len)):
        seg_id[lo:lo + ln] = s
    go_left = rng.rand(n) < 0.55

    args = (jnp.asarray(order), jnp.asarray(seg_id), jnp.asarray(seg_start),
            jnp.asarray(seg_len), jnp.asarray(go_left))
    want, want_l = partition_rows(*args, use_pallas=False)
    got, got_l = partition_rows(*args, interpret=True)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
    np.testing.assert_array_equal(np.asarray(got_l), np.asarray(want_l))


def test_partition_pallas_degenerate_segments():
    """Zero-length segments, single-element segments, all-left and
    all-right segments — the carry/cursor edge cases of the kernel's
    sequential grid."""
    import jax.numpy as jnp

    from lightgbm_tpu.ops.partition import partition_rows

    n = 1100
    order = np.arange(n, dtype=np.int32)[::-1].copy()
    seg_start = np.asarray([0, 512, 513, 600], np.int32)
    seg_len = np.asarray([512, 1, 0, 500], np.int32)
    seg_id = np.full(n, -1, np.int32)
    for s, (lo, ln) in enumerate(zip(seg_start, seg_len)):
        seg_id[lo:lo + ln] = s
    go_left = np.zeros(n, bool)
    go_left[:512] = True  # segment 0 all left
    # segment 3 all right (already False)

    args = (jnp.asarray(order), jnp.asarray(seg_id), jnp.asarray(seg_start),
            jnp.asarray(seg_len), jnp.asarray(go_left))
    want, want_l = partition_rows(*args, use_pallas=False)
    got, got_l = partition_rows(*args, interpret=True)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
    np.testing.assert_array_equal(np.asarray(got_l), np.asarray(want_l))
    # every position OUTSIDE the segments (the 513-599 gap) is bitwise
    # the input — the kernel's raw output is undefined there and the
    # dispatcher's seg_id merge must restore it
    np.testing.assert_array_equal(
        np.asarray(got)[seg_id < 0], order[seg_id < 0])


def test_partition_rows_has_no_row_cap():
    """The v1 kernel silently fell back to the XLA permutation above
    650k rows (whole-array VMEM staging); v2 is HBM-resident and must
    take the Pallas path at ANY N.  Pinned without executing a 1M-row
    kernel: trace the dispatcher at 1M rows with a sentinel-raising
    kernel — if the sentinel fires, the Pallas path was selected (the
    old cap returned the XLA result before ever touching the kernel)."""
    import jax
    import jax.numpy as jnp

    from lightgbm_tpu.ops import partition as part
    from lightgbm_tpu.ops import partition_pallas as pp

    assert not hasattr(pp, "_MAX_VMEM_ROWS"), \
        "the whole-array VMEM row cap is back"

    n, s = 1_000_000, 4

    class _Sentinel(Exception):
        pass

    def _boom(*a, **k):
        raise _Sentinel

    orig = part.__dict__.get("partition_pallas_segments")
    try:
        import lightgbm_tpu.ops.partition_pallas as _ppmod

        saved = _ppmod.partition_pallas_segments
        _ppmod.partition_pallas_segments = _boom
        with pytest.raises(_Sentinel):
            jax.eval_shape(
                lambda o, sid, st, ln, gl: part.partition_rows(
                    o, sid, st, ln, gl, use_pallas=True),
                jax.ShapeDtypeStruct((n,), jnp.int32),
                jax.ShapeDtypeStruct((n,), jnp.int32),
                jax.ShapeDtypeStruct((s,), jnp.int32),
                jax.ShapeDtypeStruct((s,), jnp.int32),
                jax.ShapeDtypeStruct((n,), bool),
            )
    finally:
        _ppmod.partition_pallas_segments = saved
        if orig is not None:
            part.partition_pallas_segments = orig


@pytest.mark.slow
def test_partition_pallas_interpret_above_650k_rows():
    """The regime v1 could not reach: >650k rows through the DMA kernel
    (interpret mode), bitwise against the XLA permutation.  Slow-marked —
    the interpreter streams ~1.4k chunks per segment sweep."""
    import jax.numpy as jnp

    from lightgbm_tpu.ops.partition import partition_rows

    rng = np.random.RandomState(9)
    n = 700_000  # > the deleted 650k cap
    order = rng.permutation(n).astype(np.int32)
    seg_start = np.asarray([0, 250_000, 400_128, 690_000], np.int32)
    seg_len = np.asarray([200_000, 100_001, 150_000, 10_000], np.int32)
    seg_id = np.full(n, -1, np.int32)
    for s, (lo, ln) in enumerate(zip(seg_start, seg_len)):
        seg_id[lo:lo + ln] = s
    go_left = rng.rand(n) < 0.5

    args = (jnp.asarray(order), jnp.asarray(seg_id), jnp.asarray(seg_start),
            jnp.asarray(seg_len), jnp.asarray(go_left))
    want, want_l = partition_rows(*args, use_pallas=False)
    got, got_l = partition_rows(*args, interpret=True)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
    np.testing.assert_array_equal(np.asarray(got_l), np.asarray(want_l))


def test_windowed_grower_with_pallas_partition_matches_xla_partition():
    """End-to-end: the fused windowed round with the Pallas partition
    (interpret) grows the IDENTICAL tree as with the XLA partition."""
    import os

    import jax.numpy as jnp

    from lightgbm_tpu.binning import DatasetBinner
    from lightgbm_tpu.ops.split import SplitParams
    from lightgbm_tpu.ops import treegrow_windowed as tw

    rng = np.random.RandomState(11)
    n, f = 2000, 12
    X = rng.randn(n, f)
    y = X @ rng.randn(f) + 0.2 * rng.randn(n)
    binner = DatasetBinner.fit(X, max_bin=63)
    bins_t = jnp.asarray(binner.transform(X).T, jnp.int16)
    grad = jnp.asarray(0.6 * y, jnp.float32)
    kw = dict(num_leaves=15, num_bins=64, params=SplitParams(
        min_data_in_leaf=5.0), leaf_tile=4, use_pallas=False)
    args = (bins_t, grad, jnp.ones((n,), jnp.float32),
            jnp.ones((n,), bool), jnp.ones((n,), jnp.float32),
            jnp.ones((f,), bool), jnp.asarray(binner.num_bins_per_feature),
            jnp.asarray(binner.missing_bin_per_feature))

    t_xla, lid_xla = tw.grow_tree_windowed(*args, **kw)

    # force the pallas partition through the interpreter: patch the
    # dispatcher choice the fused body makes at trace time
    orig = tw.partition_rows

    def forced(*a, **k):
        k.pop("use_pallas", None)
        k.pop("interpret", None)
        return orig(*a, interpret=True)

    tw.partition_rows = forced
    tw._round_fused._clear_cache()
    try:
        t_pl, lid_pl = tw.grow_tree_windowed(*args, **kw)
    finally:
        tw.partition_rows = orig
        tw._round_fused._clear_cache()

    nl = int(t_xla.num_leaves)
    assert int(t_pl.num_leaves) == nl and nl > 1
    np.testing.assert_array_equal(
        np.asarray(t_pl.split_feature[: nl - 1]),
        np.asarray(t_xla.split_feature[: nl - 1]))
    np.testing.assert_array_equal(np.asarray(lid_pl), np.asarray(lid_xla))
    np.testing.assert_allclose(
        np.asarray(t_pl.leaf_value[:nl]), np.asarray(t_xla.leaf_value[:nl]),
        rtol=1e-5, atol=1e-7)
