"""Tests for the leaf-ordered partition op (ops/partition.py — the
DataPartition analogue that round 3's windowed histogram passes build on)."""

import numpy as np

from lightgbm_tpu.ops.partition import stable_partition_ranges


def _ref_partition(order, seg_id, seg_start, seg_len, go_left):
    out = order.copy()
    lefts = np.zeros(len(seg_start), np.int32)
    for s in range(len(seg_start)):
        lo, ln = seg_start[s], seg_len[s]
        if ln == 0:
            continue
        pos = np.arange(lo, lo + ln)
        gl = go_left[pos]
        out[lo:lo + ln] = np.concatenate([order[pos][gl], order[pos][~gl]])
        lefts[s] = gl.sum()
    return out, lefts


def test_stable_partition_matches_reference_semantics():
    rng = np.random.RandomState(0)
    n = 10_000
    order = rng.permutation(n).astype(np.int32)
    # carve 4 disjoint segments; the rest untouched
    seg_start = np.asarray([0, 3000, 5000, 9000], np.int32)
    seg_len = np.asarray([1500, 800, 2500, 1000], np.int32)
    seg_id = np.full(n, -1, np.int32)
    for s, (lo, ln) in enumerate(zip(seg_start, seg_len)):
        seg_id[lo:lo + ln] = s
    go_left = rng.rand(n) < 0.4

    got, got_l = stable_partition_ranges(order, seg_id, seg_start, seg_len, go_left)
    want, want_l = _ref_partition(order, seg_id, seg_start, seg_len, go_left)
    np.testing.assert_array_equal(np.asarray(got), want)
    np.testing.assert_array_equal(np.asarray(got_l), want_l)


def test_stable_partition_all_one_side_and_empty_segments():
    order = np.arange(100, dtype=np.int32)
    seg_start = np.asarray([10, 50], np.int32)
    seg_len = np.asarray([20, 0], np.int32)
    seg_id = np.full(100, -1, np.int32)
    seg_id[10:30] = 0
    go_left = np.zeros(100, bool)  # everything right
    got, lefts = stable_partition_ranges(order, seg_id, seg_start, seg_len, go_left)
    np.testing.assert_array_equal(np.asarray(got), order)
    assert int(lefts[0]) == 0 and int(lefts[1]) == 0
    go_left[:] = True  # everything left
    got, lefts = stable_partition_ranges(order, seg_id, seg_start, seg_len, go_left)
    np.testing.assert_array_equal(np.asarray(got), order)
    assert int(lefts[0]) == 20
