"""Two-process loopback bring-up test (SURVEY §5.2 pattern; VERDICT item 9):
each process maps the reference-style machine list onto
jax.distributed.initialize, forms the GLOBAL device backend, and runs a
cross-process psum — the DCN collective path of the distributed learners."""

import os
import subprocess
import sys

import pytest

_WORKER = r"""
import os, sys
sys.path.insert(0, {repo!r})

from lightgbm_tpu.config import Config
from lightgbm_tpu.parallel.distributed import init_distributed

cfg = Config.from_dict({{
    "num_machines": 2,
    "machines": "127.0.0.1:{port},127.0.0.1:{port2}",
    "local_listen_port": {port},
    "time_out": 2,
}})
assert init_distributed(cfg)

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

assert jax.process_count() == 2, jax.process_count()
assert jax.device_count() == 4, jax.device_count()

mesh = Mesh(np.asarray(jax.devices()), ("d",))
rank = jax.process_index()

def f(x):
    return jax.lax.psum(x, "d")

g = jax.jit(jax.shard_map(f, mesh=mesh, in_specs=P("d"), out_specs=P()))
local = jax.make_array_from_process_local_data(
    jax.sharding.NamedSharding(mesh, P("d")),
    np.full((2,), float(rank + 1), np.float32),
)
out = g(local)
# ranks contribute 1+1+2+2 = 6; result is replicated so locally readable
val = float(np.asarray(out.addressable_data(0)).ravel()[0])
assert abs(val - 6.0) < 1e-6, val
print(f"RANK{{rank}}_OK", val)
"""


@pytest.mark.skipif(os.environ.get("SKIP_MULTIHOST") == "1", reason="opt-out")
def test_two_process_loopback_psum(tmp_path):
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    port, port2 = 29771, 29772
    procs = []
    for rank in range(2):
        script = _WORKER.format(repo=repo, port=port, port2=port2)
        env = dict(os.environ)
        env["LIGHTGBM_TPU_RANK"] = str(rank)
        # the axon plugin registers at interpreter startup (sitecustomize);
        # the scrub must happen BEFORE python starts, in the child env
        env["JAX_PLATFORMS"] = "cpu"
        env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
        env["PALLAS_AXON_POOL_IPS"] = ""
        env.pop("PYTEST_CURRENT_TEST", None)
        procs.append(
            subprocess.Popen(
                [sys.executable, "-c", script],
                env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            )
        )
    outs = []
    for p in procs:
        out, _ = p.communicate(timeout=240)
        outs.append(out.decode())
    for rank, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"rank {rank} failed:\n{out[-3000:]}"
        assert f"RANK{rank}_OK" in out
