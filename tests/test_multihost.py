"""Two-process loopback bring-up test (SURVEY §5.2 pattern; VERDICT item 9):
each process maps the reference-style machine list onto
jax.distributed.initialize, forms the GLOBAL device backend, and runs a
cross-process psum — the DCN collective path of the distributed learners."""

import os
import subprocess
import sys

import pytest

_WORKER = r"""
import os, sys
sys.path.insert(0, {repo!r})

from lightgbm_tpu.config import Config
from lightgbm_tpu.parallel.distributed import init_distributed

cfg = Config.from_dict({{
    "num_machines": 2,
    "machines": "127.0.0.1:{port},127.0.0.1:{port2}",
    "local_listen_port": {port},
    "time_out": 2,
}})
assert init_distributed(cfg)

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

assert jax.process_count() == 2, jax.process_count()
assert jax.device_count() == 4, jax.device_count()

mesh = Mesh(np.asarray(jax.devices()), ("d",))
rank = jax.process_index()

def f(x):
    return jax.lax.psum(x, "d")

g = jax.jit(jax.shard_map(f, mesh=mesh, in_specs=P("d"), out_specs=P()))
local = jax.make_array_from_process_local_data(
    jax.sharding.NamedSharding(mesh, P("d")),
    np.full((2,), float(rank + 1), np.float32),
)
out = g(local)
# ranks contribute 1+1+2+2 = 6; result is replicated so locally readable
val = float(np.asarray(out.addressable_data(0)).ravel()[0])
assert abs(val - 6.0) < 1e-6, val
print(f"RANK{{rank}}_OK", val)
"""


pytestmark = pytest.mark.slow

@pytest.mark.skipif(os.environ.get("SKIP_MULTIHOST") == "1", reason="opt-out")
def test_two_process_loopback_psum(tmp_path):
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    port, port2 = 29771, 29772
    procs = []
    for rank in range(2):
        script = _WORKER.format(repo=repo, port=port, port2=port2)
        env = dict(os.environ)
        env["LIGHTGBM_TPU_RANK"] = str(rank)
        # the axon plugin registers at interpreter startup (sitecustomize);
        # the scrub must happen BEFORE python starts, in the child env
        env["JAX_PLATFORMS"] = "cpu"
        env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
        env["PALLAS_AXON_POOL_IPS"] = ""
        env.pop("PYTEST_CURRENT_TEST", None)
        procs.append(
            subprocess.Popen(
                [sys.executable, "-c", script],
                env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            )
        )
    outs = []
    for p in procs:
        out, _ = p.communicate(timeout=240)
        outs.append(out.decode())
    for rank, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"rank {rank} failed:\n{out[-3000:]}"
        assert f"RANK{rank}_OK" in out


_TRAIN_WORKER = r"""
import os, sys
sys.path.insert(0, {repo!r})

from lightgbm_tpu.config import Config
from lightgbm_tpu.parallel.distributed import init_distributed

cfg = Config.from_dict({{
    "num_machines": 2,
    "machines": "127.0.0.1:{port},127.0.0.1:{port2}",
    "local_listen_port": {port},
    "time_out": 2,
}})
assert init_distributed(cfg)

import jax
import numpy as np
import lightgbm_tpu as lgb

assert jax.process_count() == 2
rank = jax.process_index()

rng = np.random.RandomState(11)
X = rng.randn(4000, 6)
y = (X @ rng.randn(6) + 0.3 * rng.randn(4000) > 0).astype(float)
params = {{"objective": "binary", "num_leaves": 8, "verbosity": -1,
          "tree_learner": "data", "min_data_in_leaf": 5}}
ds = lgb.Dataset(X, label=y)
bst = lgb.train(params, ds, 3)
s_dist = bst.model_to_string()
with open({out!r} + f".rank{{rank}}", "w") as fh:
    fh.write(s_dist)
if rank == 0:
    # reference: tests/distributed/_test_distributed.py — the distributed
    # model must equal the single-machine model.  Structure must match
    # EXACTLY; leaf values may differ at f32-psum-ordering level (the same
    # tolerance tests/test_distributed.py uses single-process).
    ds2 = lgb.Dataset(X, label=y)
    bst2 = lgb.train(dict(params, tree_learner="serial"), ds2, 3)
    s_serial = bst2.model_to_string()

    def parts(s, key):
        return [ln for ln in s.splitlines() if ln.startswith(key + "=")]

    for key in ("split_feature", "threshold", "decision_type", "num_leaves"):
        assert parts(s_dist, key) == parts(s_serial, key), key
    lv_d = [float(v) for ln in parts(s_dist, "leaf_value")
            for v in ln.split("=")[1].split()]
    lv_s = [float(v) for ln in parts(s_serial, "leaf_value")
            for v in ln.split("=")[1].split()]
    np.testing.assert_allclose(lv_d, lv_s, rtol=2e-3, atol=2e-3)
print(f"RANK{{rank}}_TRAIN_OK")
"""


_TWO_ROUND_WORKER = r"""
import os, sys
sys.path.insert(0, {repo!r})

from lightgbm_tpu.config import Config
from lightgbm_tpu.parallel.distributed import init_distributed

cfg = Config.from_dict({{
    "num_machines": 2,
    "machines": "127.0.0.1:{port},127.0.0.1:{port2}",
    "local_listen_port": {port},
    "time_out": 2,
}})
assert init_distributed(cfg)

import jax
import numpy as np
import lightgbm_tpu as lgb

rank = jax.process_index()
rng = np.random.RandomState(13)
n = 4000
X = rng.randn(n, 5)
y = (X @ rng.randn(5) + 0.3 * rng.randn(n) > 0).astype(float)
nv = 1000
Xv = rng.randn(nv, 5)
yv = (Xv @ rng.randn(5) > 0).astype(float)

# each rank streams ONLY its contiguous shard from disk (two_round +
# pre_partition: bin boundaries must sync from the global reservoir sample)
lo, hi = rank * n // 2, (rank + 1) * n // 2
shard_path = {out!r} + f".shard{{rank}}.csv"
np.savetxt(shard_path, np.column_stack([y[lo:hi], X[lo:hi]]), delimiter=",")
params = {{"objective": "binary", "num_leaves": 8, "verbosity": -1,
          "tree_learner": "data", "min_data_in_leaf": 5,
          "pre_partition": True, "two_round": True,
          "bin_construct_sample_cnt": n,
          "metric": ["binary_logloss", "auc"]}}
ds = lgb.Dataset(shard_path, params=params)
vlo, vhi = rank * nv // 2, (rank + 1) * nv // 2
dv = lgb.Dataset(Xv[vlo:vhi], label=yv[vlo:vhi], reference=ds)
rec = {{}}
bst = lgb.train(params, ds, 3, valid_sets=[dv], valid_names=["v"],
                callbacks=[lgb.record_evaluation(rec)])
s_dist = bst.model_to_string()
with open({out!r} + f".rank{{rank}}", "w") as fh:
    fh.write(s_dist)
if rank == 0:
    # serial single-process on the full data must match: structure exactly,
    # leaf values and synced eval metrics to f32-ordering tolerance
    ds2 = lgb.Dataset(X, label=y, params={{"bin_construct_sample_cnt": n}})
    dv2 = lgb.Dataset(Xv, label=yv, reference=ds2)
    rec2 = {{}}
    bst2 = lgb.train({{"objective": "binary", "num_leaves": 8,
                      "verbosity": -1, "min_data_in_leaf": 5,
                      "metric": ["binary_logloss", "auc"]}}, ds2, 3,
                     valid_sets=[dv2], valid_names=["v"],
                     callbacks=[lgb.record_evaluation(rec2)])
    s_serial = bst2.model_to_string()

    def parts(s, key):
        return [ln for ln in s.splitlines() if ln.startswith(key + "=")]

    for key in ("split_feature", "threshold", "decision_type", "num_leaves"):
        assert parts(s_dist, key) == parts(s_serial, key), key
    # the synced valid-set metrics equal the serial full-set metrics
    for mname in ("binary_logloss", "auc"):
        a = np.asarray(rec["v"][mname], float)
        b = np.asarray(rec2["v"][mname], float)
        np.testing.assert_allclose(a, b, rtol=2e-3, atol=2e-3), mname
print(f"RANK{{rank}}_2R_OK")
"""


@pytest.mark.skipif(os.environ.get("SKIP_MULTIHOST") == "1", reason="opt-out")
def test_two_round_pre_partition_with_synced_eval(tmp_path):
    """two_round streamed per-rank file shards + pre_partition: bin
    boundaries sync from the global reservoir sample, and valid-set metrics
    sync across ranks (GlobalSyncUpBySum analogue: decomposable metrics sum
    (num, den); AUC gathers shard predictions)."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    port, port2 = 29791, 29792
    out = str(tmp_path / "model")
    procs = []
    for rank in range(2):
        script = _TWO_ROUND_WORKER.format(repo=repo, port=port, port2=port2,
                                          out=out)
        env = dict(os.environ)
        env["LIGHTGBM_TPU_RANK"] = str(rank)
        env["JAX_PLATFORMS"] = "cpu"
        env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
        env["PALLAS_AXON_POOL_IPS"] = ""
        env.pop("PYTEST_CURRENT_TEST", None)
        procs.append(
            subprocess.Popen(
                [sys.executable, "-c", script],
                env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            )
        )
    outs = []
    for p in procs:
        o, _ = p.communicate(timeout=300)
        outs.append(o.decode())
    for rank, (p, o) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"rank {rank} failed:\n{o[-4000:]}"
        assert f"RANK{rank}_2R_OK" in o
    with open(out + ".rank0") as fh:
        m0 = fh.read()
    with open(out + ".rank1") as fh:
        m1 = fh.read()
    assert m0 == m1


@pytest.mark.skipif(os.environ.get("SKIP_MULTIHOST") == "1", reason="opt-out")
def test_two_process_training_equality(tmp_path):
    """End-to-end cross-process training: 2 processes, rows sharded over a
    4-device global mesh (tree_learner=data), and the resulting model must be
    byte-identical to single-process serial training (reference:
    tests/distributed/_test_distributed.py)."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    port, port2 = 29781, 29782
    out = str(tmp_path / "model")
    procs = []
    for rank in range(2):
        script = _TRAIN_WORKER.format(repo=repo, port=port, port2=port2, out=out)
        env = dict(os.environ)
        env["LIGHTGBM_TPU_RANK"] = str(rank)
        env["JAX_PLATFORMS"] = "cpu"
        env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
        env["PALLAS_AXON_POOL_IPS"] = ""
        env.pop("PYTEST_CURRENT_TEST", None)
        procs.append(
            subprocess.Popen(
                [sys.executable, "-c", script],
                env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            )
        )
    outs = []
    for p in procs:
        o, _ = p.communicate(timeout=300)
        outs.append(o.decode())
    for rank, (p, o) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"rank {rank} failed:\n{o[-4000:]}"
        assert f"RANK{rank}_TRAIN_OK" in o
    with open(out + ".rank0") as fh:
        m0 = fh.read()
    with open(out + ".rank1") as fh:
        m1 = fh.read()
    assert m0 == m1  # both processes hold the identical model


_WINDOWED_WORKER = r"""
import os, sys
sys.path.insert(0, {repo!r})

from lightgbm_tpu.config import Config
from lightgbm_tpu.parallel.distributed import init_distributed

cfg = Config.from_dict({{
    "num_machines": 2,
    "machines": "127.0.0.1:{port},127.0.0.1:{port2}",
    "local_listen_port": {port},
    "time_out": 2,
}})
assert init_distributed(cfg)

import jax
import numpy as np
import lightgbm_tpu as lgb
from lightgbm_tpu.models.gbdt import GBDT

assert jax.process_count() == 2
rank = jax.process_index()

rng = np.random.RandomState(11)
X = rng.randn(4000, 6)
y = (X @ rng.randn(6) + 0.3 * rng.randn(4000) > 0).astype(float)
params = {{"objective": "binary", "num_leaves": 15, "verbosity": -1,
           "min_data_in_leaf": 10, "max_bin": 63}}

# force the windowed gates (the real ones require a TPU + wide shape);
# the serial reference runs the single-device windowed grower on this
# process's default device, the distributed run takes the sharded fused
# round across BOTH processes' devices (in-dispatch psum over DCN)
GBDT._use_windowed = lambda self, ts: jax.device_count() == 1
GBDT._use_windowed_dp = lambda self, ts: self._dp is not None

b_dp = lgb.train(dict(params, tree_learner="data"),
                 lgb.Dataset(X, label=y), num_boost_round=6)
p_d = b_dp.predict(X, raw_score=True)
text = b_dp.model_to_string()
import hashlib
print("MODEL_SHA", rank, hashlib.sha256(text.encode()).hexdigest(),
      flush=True)

b_serial = lgb.train(dict(params), lgb.Dataset(X, label=y),
                     num_boost_round=6)
p_s = b_serial.predict(X, raw_score=True)
if not np.allclose(p_s, p_d, rtol=5e-3, atol=5e-3):
    print("MISMATCH", float(np.max(np.abs(p_s - p_d))), flush=True)
    sys.exit(3)
print(f"RANK{{rank}}_WINDOWED_OK", flush=True)
"""


@pytest.mark.skipif(os.environ.get("SKIP_MULTIHOST") == "1", reason="opt-out")
def test_two_process_sharded_windowed_training(tmp_path):
    """2-rank multiproc variant of the sharded fused windowed round
    (ISSUE 9): both processes drive the identical shard_mapped one-
    dispatch round, the histogram merge crosses the process boundary,
    and every rank's model matches the serial windowed model (and each
    other, byte-identically).  Self-skips where the container jax lacks
    loopback multiproc collectives (PR 3 note)."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    port, port2 = 29781, 29782
    procs = []
    for rank in range(2):
        script = _WINDOWED_WORKER.format(repo=repo, port=port, port2=port2)
        env = dict(os.environ)
        env["LIGHTGBM_TPU_RANK"] = str(rank)
        env["JAX_PLATFORMS"] = "cpu"
        env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
        env["PALLAS_AXON_POOL_IPS"] = ""
        env.pop("PYTEST_CURRENT_TEST", None)
        procs.append(subprocess.Popen(
            [sys.executable, "-c", script],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT))
    outs = []
    for p in procs:
        out, _ = p.communicate(timeout=360)
        outs.append(out.decode())
    if any(p.returncode != 0 for p in procs):
        if any("MISMATCH" in o for o in outs):
            raise AssertionError(
                "sharded windowed model diverged from serial:\n"
                + "\n".join(o[-2000:] for o in outs))
        # skip ONLY on the multiproc-collective infra signature (the PR 3
        # container limitation — the sibling 2-process tests fail the
        # same way at HEAD here); an application-level failure in the
        # sharded path must stay a loud failure on healthy jax builds
        infra = ("multihost_utils", "xla_extension", "jax.distributed",
                 "UNIMPLEMENTED", "coordination", "DEADLINE_EXCEEDED")
        if any(sig in o for o in outs for sig in infra):
            pytest.skip("container jax lacks loopback multiproc "
                        "collectives: "
                        + outs[0][-300:].replace("\n", " ")[:200])
        raise AssertionError(
            "sharded windowed 2-process worker failed (not the known "
            "collective-infra signature):\n"
            + "\n".join(o[-2000:] for o in outs))
    shas = set()
    for rank, out in enumerate(outs):
        assert f"RANK{rank}_WINDOWED_OK" in out, out[-2000:]
        shas.update(line.split()[-1] for line in out.splitlines()
                    if line.startswith("MODEL_SHA"))
    assert len(shas) == 1, "ranks hold different models"
