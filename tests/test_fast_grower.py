"""Tests for the round-batched grower (ops/treegrow_fast.py) and the async
training path (pending device trees, device valid scoring).

Runs on CPU (use_pallas=False fallback) — the same code paths the TPU takes
minus the Pallas kernel, which is covered by benchmarks/hist_bench.py on
hardware.
"""

import numpy as np
import pytest

import lightgbm_tpu as lgb

pytestmark = pytest.mark.slow


def _data(n=4000, f=8, seed=0):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, f).astype(np.float32)
    w = rng.randn(f)
    y = ((X @ w + 0.5 * rng.randn(n)) > 0).astype(np.float64)
    return X, y


def _auc(y, p):
    order = np.argsort(p)
    ranks = np.empty(len(p)); ranks[order] = np.arange(len(p))
    pos = y > 0
    return (ranks[pos].mean() - (pos.sum() - 1) / 2) / max((~pos).sum(), 1)


def test_rounds_mode_trains_and_matches_strict_quality():
    X, y = _data()
    out = {}
    for mode in ("strict", "rounds"):
        ds = lgb.Dataset(X, label=y)
        bst = lgb.Booster(
            params={"objective": "binary", "num_leaves": 15, "verbosity": -1,
                    "tree_growth_mode": mode},
            train_set=ds,
        )
        for _ in range(15):
            bst.update()
        out[mode] = _auc(y, bst.predict(X))
    assert out["rounds"] > 0.9
    assert abs(out["rounds"] - out["strict"]) < 0.02


def test_rounds_mode_tree_structure_valid():
    X, y = _data()
    ds = lgb.Dataset(X, label=y)
    bst = lgb.Booster(
        params={"objective": "binary", "num_leaves": 31, "verbosity": -1,
                "tree_growth_mode": "rounds"},
        train_set=ds,
    )
    for _ in range(3):
        bst.update()
    for tree in bst._gbdt.models:
        if tree.num_internal == 0:
            continue
        seen = set()

        def walk(node, depth=0):
            assert depth < 64
            if node < 0:
                seen.add(~node)
                return
            walk(int(tree.left_child[node]), depth + 1)
            walk(int(tree.right_child[node]), depth + 1)

        walk(0)
        assert len(seen) == tree.num_leaves


def test_rounds_mode_save_load_roundtrip():
    X, y = _data()
    ds = lgb.Dataset(X, label=y)
    bst = lgb.Booster(
        params={"objective": "binary", "num_leaves": 15, "verbosity": -1,
                "tree_growth_mode": "rounds"},
        train_set=ds,
    )
    for _ in range(8):
        bst.update()
    p = bst.predict(X)
    bst2 = lgb.Booster(model_str=bst.model_to_string())
    assert np.abs(p - bst2.predict(X)).max() < 1e-6


def test_rounds_mode_valid_scores_match_prediction():
    X, y = _data()
    Xv, yv = _data(n=1500, seed=1)
    ds = lgb.Dataset(X, label=y)
    dv = lgb.Dataset(Xv, label=yv, reference=ds)
    res = {}
    bst = lgb.train(
        {"objective": "binary", "num_leaves": 15, "verbosity": -1,
         "tree_growth_mode": "rounds", "metric": "binary_logloss"},
        ds, num_boost_round=8, valid_sets=[dv], valid_names=["v"],
        callbacks=[lgb.record_evaluation(res)],
    )
    # incremental device valid score must equal a from-scratch prediction
    from lightgbm_tpu.metrics import create_metrics

    p = bst.predict(Xv, raw_score=False)
    eps = 1e-7
    ll = -np.mean(yv * np.log(p + eps) + (1 - yv) * np.log(1 - p + eps))
    assert abs(res["v"]["binary_logloss"][-1] - ll) < 1e-3


def test_pending_trees_flush_on_access():
    X, y = _data()
    ds = lgb.Dataset(X, label=y)
    bst = lgb.Booster(
        params={"objective": "binary", "num_leaves": 15, "verbosity": -1,
                "tree_growth_mode": "rounds"},
        train_set=ds,
    )
    for _ in range(3):
        bst.update()
    assert len(bst._gbdt.models) == 3  # property flushes pending
    for _ in range(2):
        bst.update()
    assert len(bst._gbdt.models) == 5
    assert bst.current_iteration() == 5


def test_predict_leaf_arrays_matches_host_walk():
    import jax.numpy as jnp
    from lightgbm_tpu.ops.treegrow_fast import grow_tree_fast, predict_leaf_arrays
    from lightgbm_tpu.ops.split import SplitParams

    rng = np.random.RandomState(3)
    n, f, B = 3000, 6, 32
    Xb = rng.randint(0, B - 1, size=(n, f)).astype(np.int32)
    y = (Xb[:, 0] + Xb[:, 1] > B).astype(np.float32)
    grad = jnp.asarray(0.5 - y)
    hess = jnp.asarray(np.full(n, 0.25, np.float32))
    bins = jnp.asarray(Xb)
    nbpf = jnp.full((f,), B, np.int32)
    mbpf = jnp.full((f,), -1, np.int32)
    tree, leaf_id = grow_tree_fast(
        bins, grad, hess, jnp.ones((n,), bool), jnp.ones((n,), jnp.float32),
        jnp.ones((f,), bool), nbpf, mbpf,
        num_leaves=15, num_bins=B, params=SplitParams(min_data_in_leaf=5),
        use_pallas=False,
    )
    # the walk over the SAME rows must reproduce the training partition
    walked = predict_leaf_arrays(tree, bins, mbpf)
    assert np.array_equal(np.asarray(walked), np.asarray(leaf_id))


def test_config_rejects_bad_growth_mode():
    with pytest.raises(ValueError):
        lgb.Dataset(np.zeros((10, 2))), lgb.Booster(
            params={"tree_growth_mode": "round"},
            train_set=lgb.Dataset(np.zeros((10, 2)), label=np.zeros(10)),
        )


def test_quantized_training_matches_fp32_quality():
    """Quantized (int-histogram) training must track fp32 AUC (reference:
    quantized-training paper's parity claim; gradient_discretizer.cpp)."""
    X, y = _data(n=6000, f=10, seed=5)
    aucs = {}
    for quant in (False, True):
        ds = lgb.Dataset(X, label=y)
        bst = lgb.Booster(
            params={"objective": "binary", "num_leaves": 15, "verbosity": -1,
                    "tree_growth_mode": "rounds", "use_quantized_grad": quant,
                    "num_grad_quant_bins": 8, "quant_train_renew_leaf": True},
            train_set=ds,
        )
        for _ in range(15):
            bst.update()
        aucs[quant] = _auc(y, bst.predict(X))
    assert aucs[True] > 0.9
    assert abs(aucs[True] - aucs[False]) < 0.02
