"""EFB tests (reference: DatasetLoader::FindGroups/FastFeatureBundling;
VERDICT round-1 item 5)."""

import pytest
import numpy as np

import lightgbm_tpu as lgb
from lightgbm_tpu.io.efb import find_bundles

pytestmark = pytest.mark.slow


def _onehot_data(n=6000, groups=40, seed=0):
    """`groups` blocks of 8 mutually-exclusive one-hot columns + 2 dense."""
    rng = np.random.RandomState(seed)
    cats = rng.randint(0, 8, size=(n, groups))
    X = np.zeros((n, groups * 8 + 2), np.float32)
    for g in range(groups):
        X[np.arange(n), g * 8 + cats[:, g]] = 1.0
    X[:, -2] = rng.randn(n)
    X[:, -1] = rng.randn(n)
    logit = (cats[:, 0] == 3) * 2.0 + (cats[:, 1] >= 4) * 1.0 + X[:, -2]
    y = ((logit + rng.randn(n) * 0.5) > 1.0).astype(np.float64)
    return X, y


def test_find_bundles_merges_exclusive_columns():
    X, y = _onehot_data()
    ds = lgb.Dataset(X, label=y)
    ds.construct()
    assert ds.efb is not None
    f = X.shape[1]
    # 320 one-hot columns collapse into a handful of bundles
    assert ds.efb.num_bundled < f // 3
    # round-trip sanity: unbundling tables cover every non-default bin once
    nb = ds.binner.num_bins_per_feature
    B = ds.max_num_bins
    gi = ds.efb.gather_idx
    used = gi[gi < ds.efb.num_bundled * B]
    assert len(np.unique(used)) == len(used)  # no slot aliased twice


def test_efb_histograms_match_unbundled():
    import jax.numpy as jnp
    from lightgbm_tpu.ops.histogram import histogram_scatter

    X, y = _onehot_data(n=2000, groups=10)
    ds = lgb.Dataset(X, label=y)
    ds.construct()
    efb = ds.efb
    assert efb is not None
    n, f = ds.bins.shape
    rng = np.random.RandomState(1)
    grad = rng.randn(n).astype(np.float32)
    hess = rng.rand(n).astype(np.float32)
    B = ds.max_num_bins
    # bundle histogram -> unbundled per-feature hist must equal direct hist
    hb = np.asarray(histogram_scatter(
        jnp.asarray(efb.bundled_bins), jnp.asarray(grad), jnp.asarray(hess),
        jnp.ones((n,), bool), B,
    ))  # (3, F_b, B) channel-first
    flat = np.concatenate([hb.reshape(3, -1), np.zeros((3, 1))], axis=1)
    hf = flat[:, efb.gather_idx.reshape(-1)].reshape(3, f, B)
    tot = hb[:, 0].sum(axis=1)  # (3,) leaf totals
    fill = tot[:, None] - hf.sum(axis=2)  # (3, F)
    hf = hf + efb.default_mask[None] * fill[:, :, None]
    direct = np.asarray(histogram_scatter(
        ds.bins_device, jnp.asarray(grad), jnp.asarray(hess),
        jnp.ones((n,), bool), B,
    ))
    assert np.allclose(hf, direct, atol=1e-2)


def test_efb_training_quality_unchanged():
    X, y = _onehot_data()

    def auc(p):
        order = np.argsort(p); ranks = np.empty(len(p)); ranks[order] = np.arange(len(p))
        pos = y > 0
        return (ranks[pos].mean() - (pos.sum() - 1) / 2) / max((~pos).sum(), 1)

    out = {}
    for bundle in (True, False):
        ds = lgb.Dataset(X, label=y, params={"enable_bundle": bundle})
        bst = lgb.Booster(
            params={"objective": "binary", "num_leaves": 15, "verbosity": -1,
                    "tree_growth_mode": "rounds", "enable_bundle": bundle},
            train_set=ds,
        )
        for _ in range(10):
            bst.update()
        out[bundle] = auc(bst.predict(X))
        if bundle:
            assert ds.efb is not None and ds.efb.num_bundled < X.shape[1] // 3
    assert out[True] > 0.85
    assert abs(out[True] - out[False]) < 0.02
