"""Sparse ingestion without densify (VERDICT r2 item 7): scipy input is
binned straight from CSC (reference: src/io/sparse_bin.hpp — stored
nonzeros + implicit zero counts); dense raw floats are never materialized."""

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.binning import DatasetBinner

sp = pytest.importorskip("scipy.sparse")

pytestmark = pytest.mark.slow


def _rand_sparse(n, f, nnz_per_row, seed=0):
    rng = np.random.RandomState(seed)
    rows = np.repeat(np.arange(n), nnz_per_row)
    cols = rng.randint(0, f, size=nnz_per_row * n)
    vals = rng.rand(nnz_per_row * n) + 0.5
    X = sp.csr_matrix((vals, (rows, cols)), shape=(n, f))
    X.sum_duplicates()
    return X


def test_sparse_binner_matches_dense():
    X = _rand_sparse(5000, 64, 3)
    dense = X.toarray()
    b_d = DatasetBinner.fit(dense, max_bin=63)
    b_s = DatasetBinner.fit_sparse(X.tocsc(), max_bin=63)
    for md, ms in zip(b_d.mappers, b_s.mappers):
        np.testing.assert_array_equal(md.upper_bounds, ms.upper_bounds)
        assert md.missing_type == ms.missing_type
    np.testing.assert_array_equal(
        b_d.transform(dense), b_s.transform_sparse(X.tocsc())
    )


def test_sparse_train_no_densify_matches_dense_train():
    n, f = 60_000, 512
    X = _rand_sparse(n, f, 2, seed=1)
    y = np.asarray(X[:, :8].sum(axis=1)).ravel() + 0.05 * np.random.RandomState(2).randn(n)

    dense_bst = lgb.train(
        {"objective": "regression", "num_leaves": 15, "verbosity": -1},
        lgb.Dataset(X.toarray(), label=y), 5)

    # forbid ANY densification of the training matrix
    def boom(*a, **k):
        raise AssertionError("sparse input was densified")

    X.toarray = boom
    X.todense = boom
    bst = lgb.train(
        {"objective": "regression", "num_leaves": 15, "verbosity": -1},
        lgb.Dataset(X, label=y), 5)
    assert bst.model_to_string() == dense_bst.model_to_string()

    # chunked sparse predict (no full densify) matches dense predict; with
    # f=512 the 512MB byte budget gives 125k-row chunks, so 130k rows
    # exercises the multi-chunk recursion
    Xp = _rand_sparse(130_000, f, 2, seed=3)
    p_sparse = bst.predict(Xp)
    p_dense = bst.predict(Xp.toarray())
    np.testing.assert_allclose(p_sparse, p_dense, rtol=1e-6)


def test_sparse_onehot_efb_bundles_and_memory():
    """One-hot-style blocks bundle via EFB so the device matrix is narrow."""
    rng = np.random.RandomState(4)
    n, blocks, block_w = 50_000, 8, 64  # 512 one-hot columns
    cols = np.concatenate([
        b * block_w + rng.randint(0, block_w, n) for b in range(blocks)
    ])
    rows = np.tile(np.arange(n), blocks)
    X = sp.csr_matrix((np.ones(blocks * n), (rows, cols)),
                      shape=(n, blocks * block_w))
    beta = rng.randn(blocks * block_w)
    y = np.asarray(X @ beta).ravel() + 0.1 * rng.randn(n)
    X.toarray = X.todense = lambda *a, **k: (_ for _ in ()).throw(AssertionError)
    ds = lgb.Dataset(X, label=y)
    bst = lgb.train({"objective": "regression", "num_leaves": 31,
                     "verbosity": -1}, ds, 10, keep_training_booster=True)
    ts = bst._gbdt.train_set
    assert ts.efb is not None and ts.efb.num_bundled < 64  # 512 -> few bundles
    assert ts.bins.dtype == np.uint8  # compact binned storage, no floats
    pred = bst.predict(_rand_sparse(1000, blocks * block_w, 2, seed=5))
    assert np.isfinite(pred).all()
