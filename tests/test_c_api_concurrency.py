"""C API thread-safety (VERDICT r2 weak #9): LGBM_GetLastError isolation per
thread (reference: thread_local in c_api.cpp) and predict-during-update from
a second thread (reference: Booster's yamc shared mutex; here the embedded
CPython GIL serializes entry points)."""

import ctypes
import threading

import numpy as np
import pytest

import lightgbm_tpu as lgb
from tests.test_c_api import _build

pytestmark = pytest.mark.slow


def test_get_last_error_is_thread_local_and_predict_during_update():
    rng = np.random.RandomState(0)
    X = rng.randn(4000, 6)
    y = ((X @ rng.randn(6)) > 0).astype(np.float64)

    lib = ctypes.CDLL(_build())
    lib.LGBM_GetLastError.restype = ctypes.c_char_p

    dsh = ctypes.c_void_p()
    Xc = np.ascontiguousarray(X)
    rc = lib.LGBM_DatasetCreateFromMat(
        Xc.ctypes.data_as(ctypes.c_void_p), 1, 4000, 6, 1, b"max_bin=63",
        None, ctypes.byref(dsh))
    assert rc == 0
    yv = y.astype(np.float32)
    assert lib.LGBM_DatasetSetField(dsh, b"label",
                                    yv.ctypes.data_as(ctypes.c_void_p),
                                    4000, 0) == 0
    bh = ctypes.c_void_p()
    assert lib.LGBM_BoosterCreate(
        dsh, b"objective=binary num_leaves=15 verbosity=-1",
        ctypes.byref(bh)) == 0
    fin = ctypes.c_int()
    assert lib.LGBM_BoosterUpdateOneIter(bh, ctypes.byref(fin)) == 0

    errors, results = [], []

    def trainer():
        for _ in range(15):
            if lib.LGBM_BoosterUpdateOneIter(bh, ctypes.byref(ctypes.c_int())) != 0:
                errors.append(("train", lib.LGBM_GetLastError()))

    def predictor():
        out = np.zeros(4000, np.float64)
        n_out = ctypes.c_int64()
        for _ in range(15):
            rc = lib.LGBM_BoosterPredictForMat(
                bh, Xc.ctypes.data_as(ctypes.c_void_p), 1, 4000, 6, 1, 0,
                0, -1, b"", ctypes.byref(n_out),
                out.ctypes.data_as(ctypes.POINTER(ctypes.c_double)))
            if rc != 0:
                errors.append(("predict", lib.LGBM_GetLastError()))
            else:
                results.append(out.copy())

    def failer():
        # deliberately broken call: its error must stay on THIS thread.
        # The filename is unique to this thread — the main-thread slot may
        # legitimately hold a stale error from an earlier test (the
        # reference's GetLastError also persists until the next error).
        # Failures report via the shared list: an assert raised inside a
        # Thread would be swallowed at join().
        bad = ctypes.c_void_p()
        for _ in range(15):
            rc = lib.LGBM_BoosterCreateFromModelfile(
                b"/nonexistent/failer_thread_only.txt", ctypes.byref(bad))
            if rc == 0:
                errors.append(("failer", "expected failure, got rc=0"))
                continue
            msg = lib.LGBM_GetLastError().decode()
            if "failer_thread_only" not in msg:
                errors.append(("failer", msg))

    threads = [threading.Thread(target=trainer),
               threading.Thread(target=predictor),
               threading.Thread(target=failer)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()

    assert not errors, errors
    assert results and all(np.isfinite(r).all() for r in results)
    # the failer thread's errors never leaked into this thread's slot
    main_msg = lib.LGBM_GetLastError().decode()
    assert "failer_thread_only" not in main_msg, main_msg
    assert lib.LGBM_BoosterFree(bh) == 0
    assert lib.LGBM_DatasetFree(dsh) == 0
