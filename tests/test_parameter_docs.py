"""Parameters.md <-> config table sync (reference analogue: the CI check
that parameter_generator.py output is committed and current)."""

from pathlib import Path

from helpers.parameter_docs import generate


def test_parameters_doc_is_current():
    committed = Path(__file__).resolve().parents[1] / "docs" / "Parameters.md"
    assert committed.read_text() == generate(), (
        "docs/Parameters.md is stale; run python helpers/parameter_docs.py"
    )


# The reference's full parameter surface (include/LightGBM/config.h 4.x,
# reconstructed by group while the reference mount is empty — re-anchor
# against docs/Parameters.rst when it appears).  This is the parity
# contract VERDICT r3 item 5 asks to enumerate: every name below must be a
# Config field (or resolve through the alias table); counting closes the
# "param tail" explicitly instead of against SURVEY §9's rough ~180
# estimate, which double-counted aliases.
UPSTREAM_PARAMS = """
config task objective boosting data_sample_strategy data valid num_iterations
learning_rate num_leaves tree_learner num_threads device_type seed deterministic
force_col_wise force_row_wise histogram_pool_size max_depth min_data_in_leaf
min_sum_hessian_in_leaf bagging_fraction pos_bagging_fraction neg_bagging_fraction
bagging_freq bagging_seed bagging_by_query feature_fraction feature_fraction_bynode
feature_fraction_seed extra_trees extra_seed early_stopping_round
early_stopping_min_delta first_metric_only max_delta_step lambda_l1 lambda_l2
linear_lambda min_gain_to_split drop_rate max_drop skip_drop xgboost_dart_mode
uniform_drop drop_seed top_rate other_rate min_data_per_group max_cat_threshold
cat_l2 cat_smooth max_cat_to_onehot top_k monotone_constraints
monotone_constraints_method monotone_penalty feature_contri forcedsplits_filename
refit_decay_rate cegb_tradeoff cegb_penalty_split cegb_penalty_feature_lazy
cegb_penalty_feature_coupled path_smooth interaction_constraints verbosity
input_model output_model saved_feature_importance_type snapshot_freq
use_quantized_grad num_grad_quant_bins quant_train_renew_leaf stochastic_rounding
linear_tree max_bin max_bin_by_feature min_data_in_bin bin_construct_sample_cnt
data_random_seed is_enable_sparse enable_bundle use_missing zero_as_missing
feature_pre_filter pre_partition two_round header label_column weight_column
group_column ignore_column categorical_feature forcedbins_filename save_binary
precise_float_parser parser_config_file
start_iteration_predict num_iteration_predict predict_raw_score
predict_leaf_index predict_contrib predict_disable_shape_check pred_early_stop
pred_early_stop_freq pred_early_stop_margin output_result
convert_model_language convert_model
objective_seed num_class is_unbalance scale_pos_weight sigmoid
boost_from_average reg_sqrt alpha fair_c poisson_max_delta_step
tweedie_variance_power lambdarank_truncation_level lambdarank_norm
lambdarank_position_bias_regularization label_gain
metric metric_freq is_provide_training_metric eval_at multi_error_top_k
auc_mu_weights
num_machines local_listen_port time_out machine_list_filename machines
gpu_platform_id gpu_device_id gpu_use_dp num_gpu
""".split()

# the reference's alias table (src/io/config_auto.cpp parameter2aliases),
# same reconstruction caveat
UPSTREAM_ALIASES = {
    "config_file", "task_type", "objective_type", "app", "application",
    "loss", "boosting_type", "boost", "train", "train_data",
    "train_data_file", "data_filename", "test", "valid_data",
    "valid_data_file", "test_data", "test_data_file", "valid_filenames",
    "num_iteration", "n_iter", "num_tree", "num_trees", "num_round",
    "num_rounds", "nrounds", "num_boost_round", "n_estimators", "max_iter",
    "shrinkage_rate", "eta", "num_leaf", "max_leaves", "max_leaf",
    "max_leaf_nodes", "tree", "tree_type", "tree_learner_type",
    "num_thread", "nthread", "nthreads", "n_jobs", "device", "random_seed",
    "random_state", "min_data_per_leaf", "min_data", "min_child_samples",
    "min_samples_leaf", "min_sum_hessian_per_leaf", "min_sum_hessian",
    "min_hessian", "min_child_weight", "sub_row", "subsample", "bagging",
    "pos_sub_row", "pos_subsample", "pos_bagging", "neg_sub_row",
    "neg_subsample", "neg_bagging", "subsample_freq",
    "bagging_fraction_seed", "sub_feature", "colsample_bytree",
    "sub_feature_bynode", "colsample_bynode", "extra_tree",
    "early_stopping_rounds", "early_stopping", "n_iter_no_change",
    "max_tree_output", "max_leaf_output", "reg_alpha", "l1_regularization",
    "reg_lambda", "lambda", "l2_regularization", "min_split_gain",
    "rate_drop", "topk", "mc", "monotone_constraint", "monotonic_cst",
    "monotone_constraining_method", "mc_method", "monotone_splits_penalty",
    "ms_penalty", "mc_penalty", "feature_contrib", "fc", "fp",
    "feature_penalty", "fs", "forced_splits_filename", "forced_splits_file",
    "forced_splits", "interaction_constraint", "verbose", "model_output",
    "model_out", "save_period", "model_input", "model_in", "predict_result",
    "prediction_result", "predict_name", "prediction_name", "pred_name",
    "name_pred", "is_pre_partition", "is_enable_bundle", "bundle",
    "is_sparse", "enable_sparse", "sparse", "two_round_loading",
    "use_two_round_loading", "is_save_binary", "is_save_binary_file",
    "has_header", "label", "weight", "group", "group_id", "query_column",
    "query", "query_id", "ignore_feature", "blacklist", "cat_feature",
    "categorical_column", "cat_column", "is_predict_raw_score",
    "predict_rawscore", "raw_score", "is_predict_leaf_index", "leaf_index",
    "is_predict_contrib", "contrib", "convert_model_file", "num_classes",
    "unbalance", "unbalanced_sets", "metrics", "metric_types",
    "output_freq", "training_metric", "is_training_metric", "train_metric",
    "ndcg_eval_at", "ndcg_at", "map_eval_at", "map_at", "num_machine",
    "local_port", "port", "machine_list_file", "machine_list", "mlist",
    "workers", "nodes", "subsample_for_bin", "hist_pool_size",
    "linear_trees", "data_seed",
}


def test_upstream_parameter_contract_is_closed():
    import dataclasses

    from lightgbm_tpu.config import _ALIASES, Config

    ours = {f.name for f in dataclasses.fields(Config)}
    missing = set(UPSTREAM_PARAMS) - ours
    assert not missing, f"reference params without a Config field: {missing}"
    # every alias must resolve to a real field
    bad_targets = {a for a, c in _ALIASES.items() if c not in ours}
    assert not bad_targets, f"aliases pointing at unknown fields: {bad_targets}"
    missing_aliases = UPSTREAM_ALIASES - set(_ALIASES)
    assert not missing_aliases, (
        f"reference aliases missing from the table: {missing_aliases}")
