"""Parameters.md <-> config table sync (reference analogue: the CI check
that parameter_generator.py output is committed and current)."""

from pathlib import Path

from helpers.parameter_docs import generate


def test_parameters_doc_is_current():
    committed = Path(__file__).resolve().parents[1] / "docs" / "Parameters.md"
    assert committed.read_text() == generate(), (
        "docs/Parameters.md is stale; run python helpers/parameter_docs.py"
    )
