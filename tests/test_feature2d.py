"""2-D (feature x row) sharded windowed training — loopback pins.

The third mesh axis (parallel/feature2d.py): the bin matrix lives as
``P(feature, row)`` tiles, per-leaf histograms are complete for the owned
feature block by LAYOUT (the merge is the row psum alone — zero feature
collectives in the histogram phase, pinned structurally by jaxlint R20 and
the ``windowed_round_2d_*`` jaxpr contracts), and the split election rides
the scatter merge's owned-feature winner machinery with the feature axis
as the owning axis.

This suite pins the loopback semantics on 8 virtual CPU devices
(conftest): every mesh shape times {float, int8} grows the STRUCTURALLY
EXACT tree of the single-device windowed grower, within the same
1-dispatch-per-round / 0-host-sync / 0-retrace budget — with telemetry and
span tracing ON (the defaults; obs must never cost the budget) — plus the
booster-level routing, the non-divisor fallback, the dead-feature padding
guard, and the model round-trip.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import lightgbm_tpu as lgb
from lightgbm_tpu.binning import DatasetBinner
from lightgbm_tpu.models.gbdt import GBDT
from lightgbm_tpu.obs import metrics as obs_metrics
from lightgbm_tpu.ops.split import SplitParams
from lightgbm_tpu.ops.treegrow_windowed import grow_tree_windowed
from lightgbm_tpu.parallel.feature2d import (
    Sharded2DData, grow_tree_windowed_feature2d)
from lightgbm_tpu.parallel.mesh import make_mesh_2d
from lightgbm_tpu.utils.sanitizer import CompileCounter


def _case(seed=5, n=1600, f=10, quant=0):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, f)
    y = X @ rng.randn(f) + 0.2 * rng.randn(n)
    binner = DatasetBinner.fit(X, max_bin=31)
    bins = binner.transform(X)
    grad = jnp.asarray(0.6 * y, jnp.float32)
    hess = jnp.ones((n,), jnp.float32)
    kw = dict(num_leaves=15, num_bins=32,
              params=SplitParams(min_data_in_leaf=5.0), leaf_tile=4,
              use_pallas=False)
    if quant:
        # deterministic rounding: int8 training must be EXACTLY the
        # single-device int8 training, not merely statistically close
        kw.update(quantize_bins=quant, stochastic_rounding=False,
                  quant_renew=True)
    return X, bins, binner, grad, hess, kw


def _grow_solo(bins, binner, grad, hess, kw, quant_key):
    n, f = bins.shape
    return grow_tree_windowed(
        jnp.asarray(bins.T, jnp.int16), grad, hess,
        jnp.ones((n,), bool), jnp.ones((n,), jnp.float32),
        jnp.ones((f,), bool),
        jnp.asarray(binner.num_bins_per_feature),
        jnp.asarray(binner.missing_bin_per_feature),
        quant_key=quant_key, **kw)


def _grow_2d(mesh, bins, binner, grad, hess, kw, quant_key, stats):
    n, f = bins.shape
    sd = Sharded2DData(mesh, bins.astype(np.int16),
                       binner.num_bins_per_feature,
                       binner.missing_bin_per_feature)
    return sd, grow_tree_windowed_feature2d(
        sd, sd.pad_rows_device(np.asarray(grad), jnp.float32),
        sd.pad_rows_device(np.asarray(hess), jnp.float32),
        sd.row_valid,
        sd.pad_rows_device(np.ones(n, np.float32), jnp.float32, fill=1.0),
        jnp.ones((f,), bool), quant_key=quant_key, stats=stats, **kw)


def _assert_same_tree(tree_s, tree_d, leaf_s, leaf_d, n, label):
    assert int(tree_s.num_leaves) == int(tree_d.num_leaves), label
    m = int(tree_s.num_leaves) - 1
    for name in ("split_feature", "threshold_bin", "left_child",
                 "right_child", "default_left"):
        np.testing.assert_array_equal(
            np.asarray(getattr(tree_s, name))[:m],
            np.asarray(getattr(tree_d, name))[:m],
            err_msg=f"{name} {label}")
    np.testing.assert_allclose(
        np.asarray(tree_s.leaf_value)[:m + 1],
        np.asarray(tree_d.leaf_value)[:m + 1], rtol=2e-3, atol=2e-3)
    np.testing.assert_array_equal(np.asarray(leaf_s),
                                  np.asarray(leaf_d)[:n],
                                  err_msg=f"leaf ids {label}")


def _run_parity(dr, df, quant):
    assert obs_metrics.enabled()  # budget holds with telemetry ON
    X, bins, binner, grad, hess, kw = _case(quant=quant)
    n = X.shape[0]
    qk = jax.random.PRNGKey(3) if quant else None
    tree_s, leaf_s = _grow_solo(bins, binner, grad, hess, kw, qk)
    mesh = make_mesh_2d(dr, df)
    stats = {}
    _, (tree_d, leaf_d) = _grow_2d(mesh, bins, binner, grad, hess, kw, qk,
                                   stats)
    assert stats["retries"] == 0, stats
    assert stats["host_syncs"] == 0, stats
    assert stats["dispatches"] == stats["rounds"], stats
    _assert_same_tree(tree_s, tree_d, leaf_s, leaf_d, n,
                      f"{dr}x{df} quant={quant}")


@pytest.mark.parametrize("quant", [0, 16], ids=["float", "int8"])
def test_parity_2x2(quant):
    """Tier-1 anchor: the genuinely 2-D mesh (both axes > 1), float AND
    int8, structurally exact vs the single-device windowed grower within
    the per-rank budget."""
    _run_parity(2, 2, quant)


@pytest.mark.slow
@pytest.mark.parametrize("quant", [0, 16], ids=["float", "int8"])
@pytest.mark.parametrize("dr,df", [(1, 8), (8, 1), (2, 4)])
def test_parity_matrix(dr, df, quant):
    """Degenerate edges — (1, d) pure-feature, (d, 1) pure-row (must
    reduce to data-parallel semantics) — and the wide 2x4."""
    _run_parity(dr, df, quant)


def test_second_tree_is_retrace_free():
    """The windowed 0-retrace budget extends to the 2-D builders: the
    second tree on the same mesh/shape reuses every cached executable."""
    X, bins, binner, grad, hess, kw = _case()
    mesh = make_mesh_2d(2, 2)
    _grow_2d(mesh, bins, binner, grad, hess, kw, None, {})  # warm
    with CompileCounter() as c:
        stats = {}
        _grow_2d(mesh, bins, binner, grad, hess, kw, None, stats)
    c.assert_no_recompile("second feature2d tree")
    assert stats["dispatches"] == stats["rounds"]


def test_refuses_node_level_rng():
    """Per-node RNG (bynode fractions / extra trees) draws on the winner's
    owner block only — not replicated across the feature axis — so the
    layer refuses instead of silently diverging."""
    X, bins, binner, grad, hess, kw = _case(n=256, f=8)
    mesh = make_mesh_2d(2, 2)
    sd = Sharded2DData(mesh, bins.astype(np.int16),
                       binner.num_bins_per_feature,
                       binner.missing_bin_per_feature)
    with pytest.raises(ValueError, match="feature2d"):
        grow_tree_windowed_feature2d(
            sd, sd.pad_rows_device(np.asarray(grad), jnp.float32),
            sd.pad_rows_device(np.asarray(hess), jnp.float32),
            sd.row_valid,
            sd.pad_rows_device(np.ones(256, np.float32), jnp.float32,
                               fill=1.0),
            jnp.ones((8,), bool), rng_key=jax.random.PRNGKey(0), **kw)


def test_padded_features_never_elected():
    """Indivisible F pads dead feature slots (num_bins 1, missing -1,
    mask False) exactly like the scatter merge's `_pad_features`; a padded
    slot must NEVER win an election.  f=10 over d_f=4 pads to 12 — two
    dead slots on the last block — and every split the grower emits must
    name a REAL feature."""
    X, bins, binner, grad, hess, kw = _case(f=10)
    mesh = make_mesh_2d(2, 4)
    sd, (tree_d, _) = _grow_2d(mesh, bins, binner, grad, hess, kw, None, {})
    assert sd.f_pad == 12 and sd.num_features == 10
    m = int(tree_d.num_leaves) - 1
    sf = np.asarray(tree_d.split_feature)[:m]
    assert m > 0 and np.all(sf < 10), sf


# ---------------------------------------------------------------------------
# booster-level routing
# ---------------------------------------------------------------------------


def _force_windowed(monkeypatch):
    # loopback CPU: force the windowed gate past the on_tpu/F/leaves floors
    monkeypatch.setattr(
        GBDT, "_use_windowed_dp",
        lambda self, ts: self._dp is not None or self._dp2d is not None)


def test_booster_routes_feature2d(monkeypatch):
    _force_windowed(monkeypatch)
    rng = np.random.RandomState(12)
    X = rng.randn(2000, 6).astype(np.float32)
    y = ((X @ rng.randn(6)) > 0).astype(np.float64)
    bst = lgb.Booster(
        params={"objective": "binary", "num_leaves": 15, "verbosity": -1,
                "tree_learner": "feature2d", "tree_growth_mode": "rounds",
                "num_feature_shards": 2},
        train_set=lgb.Dataset(X, label=y))
    g = bst._gbdt
    assert g._dp2d is not None, "2-D layout not built"
    assert g._dp2d.n_feature_shards == 2 and g._dp2d.n_row_shards == 4
    assert g._use_windowed_2d(g.train_set)
    for _ in range(5):
        bst.update()
    acc = np.mean((bst.predict(X) > 0.5) == (y > 0))
    assert acc > 0.85, acc

    # shard-local leaf ids localize to the same global tree the text model
    # round-trips: a reloaded booster predicts bitwise
    s = bst.model_to_string()
    clone = lgb.Booster(model_str=s)
    np.testing.assert_array_equal(clone.predict(X, raw_score=True),
                                  bst.predict(X, raw_score=True))


def test_non_divisor_shards_fall_back_single_mesh(monkeypatch):
    """num_feature_shards that does not divide the device count warns and
    trains on the plain row mesh — never a crash, never a silent wrong
    grid."""
    _force_windowed(monkeypatch)
    rng = np.random.RandomState(3)
    X = rng.randn(800, 6).astype(np.float32)
    y = ((X[:, 0] + X[:, 1]) > 0).astype(np.float64)
    bst = lgb.Booster(
        params={"objective": "binary", "num_leaves": 7, "verbosity": -1,
                "tree_learner": "feature2d", "num_feature_shards": 3},
        train_set=lgb.Dataset(X, label=y))
    assert bst._gbdt._dp2d is None
    assert bst._gbdt._dp is not None
    bst.update()
    assert bst.num_trees() == 1


def test_feature_fraction_trees_never_split_padded(monkeypatch):
    """Per-tree feature sampling rides the padded feature mask: many trees
    of a feature_fraction<1 booster on an indivisible F must only ever
    split real features (the padded-slot election guard at booster
    level)."""
    _force_windowed(monkeypatch)
    rng = np.random.RandomState(7)
    X = rng.randn(1500, 6).astype(np.float32)
    y = ((X @ rng.randn(6)) > 0).astype(np.float64)
    bst = lgb.Booster(
        params={"objective": "binary", "num_leaves": 15, "verbosity": -1,
                "tree_learner": "feature2d", "tree_growth_mode": "rounds",
                "num_feature_shards": 4, "feature_fraction": 0.8,
                "seed": 11},
        train_set=lgb.Dataset(X, label=y))
    g = bst._gbdt
    assert g._dp2d is not None and g._dp2d.f_pad == 8
    for _ in range(8):
        bst.update()
    for t in g.models:
        sf = np.asarray(t.split_feature)
        assert sf.size and np.all(sf < 6), sf
