"""Elastic fleet recovery (docs/ROBUSTNESS.md "Elastic fleet recovery",
ISSUE 8): coordinated fleet checkpoints (rank-0 snapshot + manifest +
per-rank sha acks), the hang-aware heartbeat watchdog, and
resume-to-round relaunches that reproduce an uninterrupted run BITWISE.

Layers under test:

* the manifest protocol itself (utils/checkpoint.py) with SIMULATED
  ranks — runs everywhere, no subprocesses;
* engine.train's ``resume=<manifest>`` verification (torn / unconfirmed
  manifests refused, shard-fingerprint mismatch refused);
* the end-to-end elastic scenarios through the REAL launcher with a
  1-rank fleet (no multi-process collectives needed, so these run on
  the container jax): LGBMTPU_FAULT=host_crash:<k> and
  worker_hang:<rank>:<k> under max_restarts=1 resume from round k's
  fleet manifest and finish bitwise-identical to an uninterrupted
  launcher run;
* the loopback 2-rank variant, slow-marked and self-skipping where the
  container jax lacks multiproc collectives (PR 3 note).
"""

import json
import os
import subprocess
import sys
import time

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.basic import LightGBMError
from lightgbm_tpu.utils import checkpoint as ckpt

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_CPU_ENV = {
    "JAX_PLATFORMS": "cpu",
    "XLA_FLAGS": "--xla_force_host_platform_device_count=1",
}


def _data(n=400, f=5, seed=3):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, f)
    y = (X @ rng.randn(f) > 0).astype(np.float64)
    return X, y


PARAMS = {"objective": "binary", "num_leaves": 8, "verbosity": -1,
          "min_data_in_leaf": 5}


_MODEL_TEXT_CACHE = {}


def _model_text(rounds=2, seed=3):
    key = (rounds, seed)
    if key not in _MODEL_TEXT_CACHE:
        X, y = _data(seed=seed)
        bst = lgb.train(PARAMS, lgb.Dataset(X, label=y), rounds)
        _MODEL_TEXT_CACHE[key] = bst.model_to_string(raw_deltas=True)
    return _MODEL_TEXT_CACHE[key]


# ---------------------------------------------------------------------------
# the manifest protocol, simulated ranks (no subprocesses)
# ---------------------------------------------------------------------------

def test_manifest_schema_and_roundtrip(tmp_path):
    d = str(tmp_path)
    text = _model_text()
    mpath = ckpt.write_fleet_checkpoint(d, text, 4, 3,
                                        {"0": "fp0", "1": "fp1", "2": "fp2"})
    raw = json.load(open(mpath))
    assert raw["schema"] == "lgbmtpu-fleet-ckpt-v1"
    assert raw["round"] == 4 and raw["world_size"] == 3
    assert raw["ensemble_sha256"] == ckpt.ensemble_digest(text)
    assert raw["shards"] == {"0": "fp0", "1": "fp1", "2": "fp2"}
    # snapshot landed through the trailer-stamped path
    assert ckpt.verify_file(ckpt.fleet_snapshot_path(d, 4)) is True


def test_unconfirmed_round_is_not_fleet_valid(tmp_path):
    """rank-0's snapshot + manifest alone do NOT make a round resumable:
    every non-zero rank must ack — and with a MATCHING ensemble sha."""
    d = str(tmp_path)
    text = _model_text()
    mpath = ckpt.write_fleet_checkpoint(d, text, 2, 3, {})
    assert ckpt.fleet_manifest_valid(mpath) is None  # no acks yet
    ckpt.confirm_fleet_checkpoint(d, 2, 1, text)
    assert ckpt.fleet_manifest_valid(mpath) is None  # rank 2 still silent
    ckpt.confirm_fleet_checkpoint(d, 2, 2, text)
    m = ckpt.fleet_manifest_valid(mpath)
    assert m is not None and m["round"] == 2
    assert ckpt.latest_valid_fleet_manifest(d, 3)[0] == 2
    # world-size mismatch is refused (a resume must not mix fleet sizes)
    assert ckpt.fleet_manifest_valid(mpath, world_size=2) is None


def test_diverged_rank_ack_invalidates_the_round(tmp_path):
    """An ack carrying a DIFFERENT ensemble sha proves the fleet forked —
    that round must never be resumed into."""
    d = str(tmp_path)
    text = _model_text()
    mpath = ckpt.write_fleet_checkpoint(d, text, 2, 2, {})
    ckpt.confirm_fleet_checkpoint(d, 2, 1, text + "# divergent\n")
    assert ckpt.fleet_manifest_valid(mpath) is None


def test_torn_manifest_and_torn_snapshot_are_refused(tmp_path):
    d = str(tmp_path)
    text = _model_text()
    mpath = ckpt.write_fleet_checkpoint(d, text, 2, 1, {})
    assert ckpt.fleet_manifest_valid(mpath) is not None
    # tear the snapshot: round 2 stops being fleet-valid
    spath = ckpt.fleet_snapshot_path(d, 2)
    snap_text = open(spath).read()
    open(spath, "w").write(snap_text[: len(snap_text) // 2])
    assert ckpt.fleet_manifest_valid(mpath) is None
    # restore; tear the manifest JSON instead
    open(spath, "w").write(snap_text)
    assert ckpt.fleet_manifest_valid(mpath) is not None
    mtext = open(mpath).read()
    open(mpath, "w").write(mtext[: len(mtext) // 2])
    assert ckpt.fleet_manifest_valid(mpath) is None
    assert ckpt.latest_valid_fleet_manifest(d, 1) is None


def test_latest_valid_skips_newer_torn_round(tmp_path):
    """The previous fleet-valid round stays authoritative when the newest
    round's manifest (or snapshot) is torn."""
    d = str(tmp_path)
    ckpt.write_fleet_checkpoint(d, _model_text(2), 2, 1, {})
    ckpt.write_fleet_checkpoint(d, _model_text(4), 4, 1, {})
    os.unlink(ckpt.fleet_manifest_path(d, 4))  # crash before publish
    found = ckpt.latest_valid_fleet_manifest(d, 1)
    assert found is not None and found[0] == 2


def test_engine_refuses_invalid_manifest_and_changed_shard(tmp_path,
                                                          monkeypatch):
    d = str(tmp_path)
    X, y = _data()
    text = _model_text()
    mpath = ckpt.write_fleet_checkpoint(d, text, 2, 2, {"0": "fp-original"})
    # unconfirmed (rank 1 never acked): refused
    with pytest.raises(LightGBMError, match="not fleet-valid"):
        lgb.train(PARAMS, lgb.Dataset(X, label=y), 6, resume=mpath)
    ckpt.confirm_fleet_checkpoint(d, 2, 1, text)
    # confirmed but THIS rank's data shard changed: refused
    monkeypatch.setenv("LIGHTGBM_TPU_RANK", "0")
    monkeypatch.setenv("LGBMTPU_SHARD_FINGERPRINT", "fp-changed")
    with pytest.raises(LightGBMError, match="fingerprint"):
        lgb.train(PARAMS, lgb.Dataset(X, label=y), 6, resume=mpath)
    # matching fingerprint resumes: 2 checkpointed + 4 remaining rounds
    monkeypatch.setenv("LGBMTPU_SHARD_FINGERPRINT", "fp-original")
    bst = lgb.train(PARAMS, lgb.Dataset(X, label=y), 6, resume=mpath)
    assert bst.num_trees() == 6


def test_manifest_resume_is_bitwise_identical(tmp_path):
    """The core exactness contract WITHOUT the launcher: train 2 rounds
    through the fleet-checkpoint callback, resume from the round-2
    manifest, and match the uninterrupted 6-round run's model text
    byte for byte (raw-delta snapshots + separated init score + .17g
    checkpoint serialization make the round-trip lossless)."""
    d = str(tmp_path)
    X, y = _data()
    full = lgb.train(PARAMS, lgb.Dataset(X, label=y), 6)

    def cb(env):
        it = env.model.current_iteration()
        if it % 2 == 0:
            ckpt.write_fleet_checkpoint(
                d, env.model.model_to_string(raw_deltas=True), it, 1, {})
    cb.order = 100
    lgb.train(PARAMS, lgb.Dataset(X, label=y), 2, callbacks=[cb])
    resumed = lgb.train(PARAMS, lgb.Dataset(X, label=y), 6,
                        resume=ckpt.fleet_manifest_path(d, 2),
                        callbacks=[cb])
    assert resumed.model_to_string() == full.model_to_string()
    # ...and the resumed run kept checkpointing on the GLOBAL numbering
    assert ckpt.latest_valid_fleet_manifest(d, 1)[0] == 6


def test_fleet_retention_prunes_old_rounds_never_newest_valid(tmp_path):
    d = str(tmp_path)
    for k in (2, 4, 6):
        ckpt.write_fleet_checkpoint(d, _model_text(k), k, 1, {})
    pruned = ckpt.prune_fleet_checkpoints(d, keep=2)
    assert pruned == [2]
    assert not os.path.exists(ckpt.fleet_manifest_path(d, 2))
    assert ckpt.latest_valid_fleet_manifest(d, 1)[0] == 6
    # newest round torn: keep=1 must NOT prune the newest VALID round
    os.unlink(ckpt.fleet_manifest_path(d, 6))
    pruned = ckpt.prune_fleet_checkpoints(d, keep=1)
    assert 4 not in pruned
    assert ckpt.latest_valid_fleet_manifest(d, 1)[0] == 4


# ---------------------------------------------------------------------------
# manifest_write crash injection: the torn-fleet-state window
# ---------------------------------------------------------------------------

_MANIFEST_CRASH_SCRIPT = """
import os, sys
import numpy as np
sys.path.insert(0, {repo!r})
import lightgbm_tpu as lgb
from lightgbm_tpu.utils import checkpoint as ckpt

rng = np.random.RandomState(3)
X = rng.randn(400, 5)
y = (X @ rng.randn(5) > 0).astype(np.float64)
d = {d!r}

def cb(env):
    it = env.model.current_iteration()
    if it % 2 == 0:
        ckpt.write_fleet_checkpoint(
            d, env.model.model_to_string(raw_deltas=True), it, 1, {{}})
cb.order = 100
lgb.train({params!r}, lgb.Dataset(X, label=y), 6, callbacks=[cb])
print("COMPLETED_WITHOUT_FAULT", flush=True)
"""


def test_manifest_write_crash_leaves_previous_round_authoritative(tmp_path):
    """Crash BETWEEN the rank-0 snapshot landing and the manifest publish
    (the manifest_write site): the round-4 snapshot exists on disk but
    round 2 stays the newest fleet-valid state, and resuming from it
    reproduces the uninterrupted run bitwise."""
    from lightgbm_tpu.utils.faults import CRASH_EXIT_CODE

    d = str(tmp_path)
    env = dict(os.environ, LGBMTPU_FAULT="manifest_write:4", **_CPU_ENV)
    env.pop("PYTEST_CURRENT_TEST", None)
    r = subprocess.run(
        [sys.executable, "-c", _MANIFEST_CRASH_SCRIPT.format(
            repo=REPO, d=d, params=PARAMS)],
        env=env, capture_output=True, text=True, timeout=300)
    assert r.returncode == CRASH_EXIT_CODE, (r.stdout, r.stderr)
    assert "COMPLETED_WITHOUT_FAULT" not in r.stdout

    # the snapshot landed, the manifest did not: round 4 is torn state
    assert os.path.exists(ckpt.fleet_snapshot_path(d, 4))
    assert not os.path.exists(ckpt.fleet_manifest_path(d, 4))
    found = ckpt.latest_valid_fleet_manifest(d, 1)
    assert found is not None and found[0] == 2

    X, y = _data()
    full = lgb.train(PARAMS, lgb.Dataset(X, label=y), 6)
    resumed = lgb.train(PARAMS, lgb.Dataset(X, label=y), 6,
                        resume=found[1])
    assert resumed.model_to_string() == full.model_to_string()


# ---------------------------------------------------------------------------
# elastic e2e through the real launcher (1-rank fleet: runs everywhere)
# ---------------------------------------------------------------------------

def _fleet_events(tmp):
    path = os.path.join(tmp, "fleet_events.jsonl")
    with open(path, encoding="utf-8") as fh:
        return [json.loads(line) for line in fh if line.strip()]


def _launch(params, X, y, rounds=6, **kw):
    from lightgbm_tpu.parallel import launcher

    bst, files = launcher.train_distributed(
        params, X, y, num_boost_round=rounds, num_machines=1,
        env_extra=dict(_CPU_ENV), **kw)
    return bst, files, launcher._LAST_LAUNCH_DIR


def _e2e_params(X):
    return dict(PARAMS, bin_construct_sample_cnt=len(X), snapshot_freq=2)


@pytest.fixture(scope="module")
def uninterrupted_ref_text():
    """One uninterrupted 1-rank launcher run shared by both elastic-e2e
    scenarios (each worker pays a full jax import — sharing the
    reference keeps the module inside the tier-1 budget)."""
    X, y = _data()
    assert "LGBMTPU_FAULT" not in os.environ
    _, ref_files, _ = _launch(_e2e_params(X), X, y)
    return open(ref_files[0]).read()


def test_elastic_resume_after_host_crash_is_bitwise(monkeypatch,
                                                    uninterrupted_ref_text):
    """THE acceptance scenario: rank 0 is killed at round 5 under
    max_restarts=1; the relaunch resumes every rank from round 4's fleet
    manifest (not round 0) and the final rank-0 model file is
    byte-identical to an uninterrupted launcher run's."""
    X, y = _data()
    params = _e2e_params(X)

    monkeypatch.setenv("LGBMTPU_FAULT", "host_crash:5")
    _, files, tmp = _launch(params, X, y, max_restarts=1,
                            restart_backoff_s=0.1)
    assert open(files[0]).read() == uninterrupted_ref_text

    ev = _fleet_events(tmp)
    kinds = [e["kind"] for e in ev]
    assert "worker_death" in kinds and "fleet_relaunch" in kinds
    resumes = [e for e in ev if e["kind"] == "fleet_resume"]
    assert resumes and all(e["round"] == 4 for e in resumes)
    # the relaunched worker trained ONLY the remaining rounds (5, 6)
    relaunch_ts = max(e["ts"] for e in ev if e["kind"] == "fleet_relaunch")
    post = [e for e in ev
            if e["kind"] == "boost_round" and e["ts"] > relaunch_ts]
    assert len(post) == 2, [e["kind"] for e in ev]


def test_hung_rank_is_detected_killed_and_resumed_bitwise(
        monkeypatch, uninterrupted_ref_text):
    """worker_hang:<rank>:<round>: a rank that sleeps forever inside the
    round loop never exits, so only the heartbeat watchdog can catch it.
    It must be declared hung within a bounded multiple of the timeout
    (stale_s recorded in the event trail), killed, and the relaunch must
    resume from the last fleet-valid round and finish bitwise."""
    X, y = _data()
    params = _e2e_params(X)

    timeout = 4.0
    monkeypatch.setenv("LGBMTPU_FAULT", "worker_hang:0:3")
    _, files, tmp = _launch(params, X, y, max_restarts=1,
                            restart_backoff_s=0.1,
                            heartbeat_timeout_s=timeout)
    assert open(files[0]).read() == uninterrupted_ref_text

    ev = _fleet_events(tmp)
    hangs = [e for e in ev if e["kind"] == "worker_hang"]
    assert len(hangs) == 1 and hangs[0]["worker_rank"] == 0
    # detection bound: staleness at detection within 2x the timeout
    # (one timeout to qualify + at most one snapshot period + one check
    # interval of slack)
    assert timeout < hangs[0]["stale_s"] <= 2 * timeout
    assert [e["round"] for e in ev if e["kind"] == "fleet_resume"] == [2, 2]
    assert any(e["kind"] == "fleet_relaunch" and e.get("hung")
               for e in ev)


# ---------------------------------------------------------------------------
# loopback multi-rank variant (slow; self-skips on the container jax)
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_elastic_resume_loopback_two_ranks(monkeypatch):
    """The 2-rank loopback form of the acceptance scenario: rank 1 dies
    at round 3; the fleet relaunches from the newest fleet-valid manifest
    (every rank confirmed) and both ranks converge to the identical model
    an uninterrupted 2-rank run produces.  Self-skips where the container
    jax lacks multiproc collectives (PR 3 note)."""
    from lightgbm_tpu.parallel.launcher import WorkerFailure, train_distributed

    X, y = _data(n=4000, f=6, seed=11)
    params = dict(PARAMS, bin_construct_sample_cnt=len(X), snapshot_freq=2)
    try:
        _, ref_files = train_distributed(
            params, X, y, num_boost_round=6, num_machines=2,
            env_extra=dict(_CPU_ENV))
    except WorkerFailure as e:
        pytest.skip(f"container jax lacks loopback multiproc collectives: "
                    f"{str(e)[:160]}")
    ref_text = open(ref_files[0]).read()

    monkeypatch.setenv("LGBMTPU_FAULT", "worker_death:3")
    monkeypatch.setenv("LGBMTPU_FAULT_RANK", "1")
    bst, files = train_distributed(
        params, X, y, num_boost_round=6, num_machines=2,
        max_restarts=1, restart_backoff_s=0.1, env_extra=dict(_CPU_ENV))
    texts = [open(f).read() for f in files]
    assert texts[0] == texts[1] == ref_text
    from lightgbm_tpu.parallel import launcher

    ev = _fleet_events(launcher._LAST_LAUNCH_DIR)
    resumes = [e for e in ev if e["kind"] == "fleet_resume"]
    assert resumes and all(e["round"] == 2 for e in resumes)


def test_manifest_resume_refuses_overshoot(tmp_path):
    """A manifest round BEYOND the requested num_iterations is refused —
    silently returning a bigger model than asked is the stale-newer
    hazard, not a resume."""
    d = str(tmp_path)
    X, y = _data()
    mpath = ckpt.write_fleet_checkpoint(d, _model_text(4), 4, 1, {})
    with pytest.raises(LightGBMError, match="beyond the requested"):
        lgb.train(PARAMS, lgb.Dataset(X, label=y), 2, resume=mpath)


# ---------------------------------------------------------------------------
# live fleet /metrics from the launcher (round 14)
# ---------------------------------------------------------------------------

def test_launcher_live_fleet_metrics_endpoint(monkeypatch):
    """metrics_port= in the launch params starts an endpoint in the
    LAUNCHER process whose /metrics serves the merged per-rank snapshot
    files with rank labels — queryable while workers run AND after (the
    collector stays registered over the persisted files), not only via
    the at-exit fleet_metrics.json merge."""
    import threading
    import urllib.request

    from lightgbm_tpu.obs import server as obs_server

    X, y = _data()
    params = dict(PARAMS, bin_construct_sample_cnt=len(X), metrics_port=0)

    live = {"scrapes": 0, "labeled": False}
    stop = threading.Event()

    def poll():
        while not stop.is_set():
            srv = obs_server.get_server()
            if srv is not None:
                try:
                    text = urllib.request.urlopen(
                        srv.url("/metrics"), timeout=2).read().decode()
                    live["scrapes"] += 1
                    if 'rank="0"' in text:
                        live["labeled"] = True
                except OSError:
                    pass
            time.sleep(0.15)

    t = threading.Thread(target=poll, daemon=True)
    t.start()
    try:
        _launch(params, X, y, rounds=3)
    finally:
        stop.set()
        t.join(3)
    try:
        srv = obs_server.get_server()
        assert srv is not None, "launcher did not start the live endpoint"
        # deterministic post-run scrape: the per-rank snapshot files
        # persist and the collector is still registered, so rank-labeled
        # families (incl. the worker's own heartbeat gauge) must appear
        text = urllib.request.urlopen(
            srv.url("/metrics"), timeout=5).read().decode()
        assert 'rank="0"' in text, text[:800]
        assert live["scrapes"] > 0, "endpoint never answered during the run"
    finally:
        from lightgbm_tpu.obs import metrics as _obs
        _obs.REGISTRY.register_collector("fleet_live", lambda: {})
        obs_server.stop_server()


# ---------------------------------------------------------------------------
# slice-granular recovery (ISSUE 15 — docs/ROBUSTNESS.md "Slice-granular
# recovery"): manifests carry slice membership, a lost slice resumes from
# the newest SLICE-valid round, survivors never restart
# ---------------------------------------------------------------------------

def test_slice_valid_manifest_excludes_lost_ranks(tmp_path):
    """Simulated 2-slice x 2-rank fleet: round 4 is acked only by the
    SURVIVORS (slice 1's ranks died before acking), so it is not
    fleet-valid — but it IS slice-valid for slice 1's replacement, whose
    dead members' acks cannot be required.  A diverged ack from an
    excluded rank still poisons the round."""
    d = str(tmp_path)
    text2, text4 = _model_text(2), _model_text(4)
    slices = {"0": 0, "1": 0, "2": 1, "3": 1}
    ckpt.write_fleet_checkpoint(d, text2, 2, 4, {}, slices=slices)
    for r in (1, 2, 3):
        ckpt.confirm_fleet_checkpoint(d, 2, r, text2)
    mpath4 = ckpt.write_fleet_checkpoint(d, text4, 4, 4, {}, slices=slices)
    ckpt.confirm_fleet_checkpoint(d, 4, 1, text4)  # slice-0 survivor only

    raw = json.load(open(mpath4))
    assert raw["slices"] == slices and raw["num_slices"] == 2

    # fleet-valid scan: round 4 unconfirmed (ranks 2, 3 silent) -> 2
    assert ckpt.latest_valid_fleet_manifest(d, 4)[0] == 2
    # slice-valid for the LOST slice {2, 3}: round 4 qualifies
    got = ckpt.latest_slice_valid_fleet_manifest(d, 4, (2, 3))
    assert got is not None and got[0] == 4
    # but a rank OUTSIDE the lost slice missing its ack still disqualifies
    assert ckpt.latest_slice_valid_fleet_manifest(d, 4, (3,))[0] == 2
    # a diverged ack from an EXCLUDED rank proves forked state: refused
    ckpt.confirm_fleet_checkpoint(d, 4, 3, text4 + "# fork\n")
    assert ckpt.latest_slice_valid_fleet_manifest(d, 4, (2, 3))[0] == 2


def test_slice_granular_recovery_survivors_never_restart(
        monkeypatch, uninterrupted_ref_text):
    """THE ISSUE 15 recovery acceptance, loopback form: a 2-slice fleet
    (1 rank per slice — each slice its own rendezvous world training the
    shared plan) loses slice 1 at round 5.  ONLY slice 1 is killed and
    respawned — from the newest SLICE-valid manifest round, not round 0
    — while slice 0 keeps running untouched (exactly one spawn for rank
    0, no fleet_relaunch), and every final model file is byte-identical
    to an uninterrupted run's."""
    from lightgbm_tpu.obs import metrics as _obs
    from lightgbm_tpu.parallel import launcher

    X, y = _data()
    params = _e2e_params(X)
    monkeypatch.setenv("LGBMTPU_FAULT", "worker_death:1:5")
    c0 = _obs.counter("fleet_slice_resumes_total").value
    # the launch-scoped fleet_live collector outlives the run by design
    # (post-mortem scrapes of the LAUNCHER's endpoint); drop it after so
    # this faulted fleet's on-disk counters cannot flip later tests'
    # /healthz probes (obs.reset() deliberately keeps collectors)
    try:
        bst, files = launcher.train_distributed(
            params, X, y, num_boost_round=6, num_machines=2, num_slices=2,
            max_restarts=1, restart_backoff_s=0.1, env_extra=dict(_CPU_ENV))
    finally:
        _obs.unregister_collector("fleet_live")
    tmp = launcher._LAST_LAUNCH_DIR
    texts = [open(f).read() for f in files]
    assert texts[0] == texts[1] == uninterrupted_ref_text

    assert _obs.counter("fleet_slice_resumes_total").value == c0 + 1
    ev = _fleet_events(tmp)
    kinds = [e["kind"] for e in ev]
    assert "fleet_relaunch" not in kinds  # the fleet never restarted
    deaths = [e for e in ev if e["kind"] == "worker_death"]
    assert [e["worker_rank"] for e in deaths] == [1]
    resumes = [e for e in ev if e["kind"] == "fleet_slice_resume"]
    assert len(resumes) == 1 and resumes[0]["slice"] == 1
    assert resumes[0]["ranks"] == [1]
    # resumed from a slice-valid ROUND (>= the last round slice 1 acked
    # before dying; the survivors may have confirmed further) — never 0
    assert resumes[0]["round"] is not None and resumes[0]["round"] >= 4
    # the survivor was spawned exactly once; the lost rank exactly twice
    spawns = [e["worker_rank"] for e in ev if e["kind"] == "worker_spawn"]
    assert spawns.count(0) == 1 and spawns.count(1) == 2
    # the manifests on disk carry slice membership
    found = ckpt.latest_valid_fleet_manifest(tmp, 2)
    assert found is not None and found[2]["slices"] == {"0": 0, "1": 1}
