"""forcedsplits_filename (reference: SerialTreeLearner::ForceSplits —
the JSON tree prefix is applied before gain-driven growth)."""

import json
import os
import tempfile

import numpy as np
import pytest

import lightgbm_tpu as lgb

pytestmark = pytest.mark.slow


def _train(forced, n=2000, num_leaves=8, extra=None, mode="strict"):
    rng = np.random.RandomState(0)
    X = rng.randn(n, 4)
    # signal on feature 0 so free growth would NEVER pick feature 2 first
    y = (X[:, 0] > 0).astype(float) + 0.01 * rng.randn(n)
    with tempfile.NamedTemporaryFile("w", suffix=".json", delete=False) as f:
        json.dump(forced, f)
        path = f.name
    try:
        params = {"objective": "regression", "num_leaves": num_leaves,
                  "verbosity": -1, "tree_growth_mode": mode,
                  "forcedsplits_filename": path}
        params.update(extra or {})
        d = lgb.Dataset(X, label=y)
        bst = lgb.train(params, d, num_boost_round=1)
        return bst.dump_model()["tree_info"][0]["tree_structure"]
    finally:
        os.unlink(path)


@pytest.mark.parametrize("mode", ["strict", "rounds"])
def test_forced_root_split(mode):
    root = _train({"feature": 2, "threshold": 0.5}, mode=mode)
    assert root["split_feature"] == 2
    assert root["threshold"] == pytest.approx(0.5, abs=0.2)  # bin upper bound


@pytest.mark.parametrize("mode", ["strict", "rounds"])
def test_forced_nested_splits(mode):
    forced = {
        "feature": 2, "threshold": 0.0,
        "left": {"feature": 3, "threshold": -0.5},
        "right": {"feature": 1, "threshold": 0.75},
    }
    root = _train(forced, mode=mode)
    assert root["split_feature"] == 2
    assert root["left_child"]["split_feature"] == 3
    assert root["right_child"]["split_feature"] == 1
    # growth continues by gain below the forced prefix: the strong signal
    # feature 0 must appear somewhere deeper
    def features(nd):
        if "split_feature" not in nd:
            return []
        return [nd["split_feature"]] + features(nd["left_child"]) + features(nd["right_child"])
    assert 0 in features(root)


@pytest.mark.parametrize("mode", ["strict", "rounds"])
def test_invalid_forced_split_skipped(mode):
    # threshold far outside the data range: one side empty -> the forced
    # split is invalid and normal growth takes over (reference skips it)
    root = _train({"feature": 2, "threshold": 1e9}, mode=mode)
    assert root["split_feature"] == 0  # the gain-driven choice


@pytest.mark.parametrize("mode", ["strict", "rounds"])
def test_invalid_forced_split_disables_rest(mode):
    """The first invalid forced entry must disable ALL remaining entries
    (reference: ForceSplits stops applying the prefix at the first invalid
    split) — the precomputed schedule's leaf ids assume every prior entry
    applied, so a later entry would latch onto the wrong leaf."""
    import lightgbm_tpu as lgb

    rng = np.random.RandomState(3)
    n = 2000
    X = rng.randn(n, 4)
    y = X[:, 0]  # linear signal: every leaf keeps its gain on feature 0
    forced = {"feature": 2, "threshold": 1e9,  # invalid: one side empty
              "right": {"feature": 3, "threshold": 0.0}}
    with tempfile.NamedTemporaryFile("w", suffix=".json", delete=False) as f:
        json.dump(forced, f)
        path = f.name
    try:
        d = lgb.Dataset(X, label=y)
        bst = lgb.train(
            {"objective": "regression", "num_leaves": 3, "verbosity": -1,
             "tree_growth_mode": mode, "forcedsplits_filename": path},
            d, num_boost_round=1)
        root = bst.dump_model()["tree_info"][0]["tree_structure"]

        def features(nd):
            if "split_feature" not in nd:
                return []
            return ([nd["split_feature"]] + features(nd["left_child"])
                    + features(nd["right_child"]))

        # without the cascade, entry 1 (feature 3) was force-applied to the
        # leaf created by the gain-driven root split
        assert 3 not in features(root)
        assert root["split_feature"] == 0
    finally:
        os.unlink(path)
