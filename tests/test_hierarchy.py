"""Hierarchical two-level merge on the loopback nested mesh (ISSUE 15,
docs/DISTRIBUTED.md "Hierarchical merge").

Acceptance under test: a 2-slice x 2-rank nested (dcn, ici) mesh training
through the fused windowed round — intra-slice psum AND psum_scatter
merges — produces trees structurally EXACT vs single-device windowed
growth when ``top_k_features`` covers every candidate feature, with the
per-rank 1-dispatch/0-sync/0-retrace steady-state budget pinned with
telemetry + span tracing ON.  Smaller top-k is the PV-Tree
approximation: it must still train a usable model under a statically
bounded DCN byte bill (the jaxpr-audit side lives in
tests/test_jaxpr_audit.py).
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.binning import DatasetBinner
from lightgbm_tpu.ops.split import SplitParams
from lightgbm_tpu.ops.treegrow_windowed import grow_tree_windowed
from lightgbm_tpu.parallel.hierarchy import (SlicedData,
                                             grow_tree_windowed_hierarchical)
from lightgbm_tpu.parallel.mesh import (DCN_AXIS, ICI_AXIS,
                                        make_mesh_hierarchical,
                                        slice_axis_sizes)


@pytest.fixture(scope="module")
def case():
    rng = np.random.RandomState(5)
    n, f = 1600, 10
    X = rng.randn(n, f)
    y = X @ rng.randn(f) + 0.2 * rng.randn(n)
    binner = DatasetBinner.fit(X, max_bin=31)
    bins = binner.transform(X)
    grad = jnp.asarray(0.6 * y, jnp.float32)
    hess = jnp.ones((n,), jnp.float32)
    kw = dict(num_leaves=15, num_bins=32,
              params=SplitParams(min_data_in_leaf=5.0), leaf_tile=4,
              use_pallas=False)
    tree_s, leaf_s = grow_tree_windowed(
        jnp.asarray(bins.T, jnp.int16), grad, hess,
        jnp.ones((n,), bool), jnp.ones((n,), jnp.float32),
        jnp.ones((f,), bool),
        jnp.asarray(binner.num_bins_per_feature),
        jnp.asarray(binner.missing_bin_per_feature), **kw)
    return dict(n=n, f=f, bins=bins, binner=binner, grad=grad, hess=hess,
                kw=kw, tree_s=tree_s, leaf_s=leaf_s)


def _sliced(case):
    mesh = make_mesh_hierarchical(2, 2)
    assert slice_axis_sizes(mesh) == (2, 2)
    return SlicedData(mesh, case["bins"],
                      case["binner"].num_bins_per_feature,
                      case["binner"].missing_bin_per_feature)


def _grow_hier(case, sd, merge, top_k, stats=None):
    n = case["n"]
    return grow_tree_windowed_hierarchical(
        sd, sd.pad_rows(np.asarray(case["grad"])),
        sd.pad_rows(np.asarray(case["hess"])), sd.row_valid,
        sd.pad_rows(np.ones(n, np.float32), fill=1.0),
        jnp.ones((case["f"],), bool), merge=merge,
        top_k_features=top_k, stats=stats, **case["kw"])


def _assert_same_tree(tree_s, tree_h, leaf_s, leaf_h, n):
    assert int(tree_s.num_leaves) == int(tree_h.num_leaves)
    m = int(tree_s.num_leaves) - 1
    for name in ("split_feature", "threshold_bin", "left_child",
                 "right_child", "default_left"):
        np.testing.assert_array_equal(
            np.asarray(getattr(tree_s, name))[:m],
            np.asarray(getattr(tree_h, name))[:m], err_msg=name)
    np.testing.assert_allclose(
        np.asarray(tree_s.leaf_value)[:m + 1],
        np.asarray(tree_h.leaf_value)[:m + 1], rtol=2e-3, atol=2e-3)
    np.testing.assert_array_equal(np.asarray(leaf_s),
                                  np.asarray(leaf_h)[:n])


@pytest.mark.parametrize("merge", ["psum", "scatter"])
def test_hierarchical_full_topk_equals_single_device(case, merge):
    """ISSUE 15 acceptance: 2-slice x 2-rank nested-mesh training with
    top_k covering all candidate features is structurally EXACT vs
    single-device windowed growth — both intra-slice merges — with zero
    retries and zero blocking syncs."""
    sd = _sliced(case)
    stats = {}
    tree_h, leaf_h = _grow_hier(case, sd, merge, case["f"], stats)
    assert stats["retries"] == 0 and stats["host_syncs"] == 0, stats
    _assert_same_tree(case["tree_s"], tree_h, case["leaf_s"], leaf_h,
                      case["n"])


def test_hierarchical_budget_one_dispatch_per_round_telemetry_on(case):
    """The per-rank round budget on the nested mesh: 1 donated dispatch,
    0 blocking syncs, 0 retraces per steady-state round — pinned by the
    same DispatchCounter the single-level rounds use, with telemetry AND
    span tracing default-ON (both the intra-slice merge and the dcn
    election ride inside the one dispatch)."""
    from lightgbm_tpu.obs import metrics as obs_metrics
    from lightgbm_tpu.obs import trace as obs_trace
    from lightgbm_tpu.utils.sanitizer import DispatchCounter

    assert obs_metrics.enabled()
    sd = _sliced(case)
    # warmup: compiles init, the round at this shard's ladder rung(s),
    # finalize
    tree, leaf = _grow_hier(case, sd, "psum", 4)
    jax.block_until_ready(leaf)
    assert int(tree.num_leaves) > 1
    sd2 = _sliced(case)
    spans_before = len(obs_trace.spans("windowed_round"))
    stats = {}
    with DispatchCounter() as d:
        tree, leaf = _grow_hier(case, sd2, "psum", 4, stats)
        jax.block_until_ready(leaf)
    assert stats["rounds"] >= 3, stats
    d.assert_round_budget(stats["rounds"], what="hierarchical rounds")
    assert stats["host_syncs"] == 0 and stats["retries"] == 0, stats
    assert stats["async_resolves"] <= stats["rounds"], stats
    d.assert_no_recompile("hierarchical windowed steady state")
    assert (len(obs_trace.spans("windowed_round")) - spans_before
            == stats["rounds"])


def test_hierarchical_small_topk_trains_valid_tree(case):
    """top_k < F is the PV-Tree approximation: the election may pick a
    different split than the exhaustive search, but the tree must be
    valid, grown, and the round budget intact."""
    sd = _sliced(case)
    stats = {}
    tree_h, leaf_h = _grow_hier(case, sd, "psum", 3, stats)
    assert int(tree_h.num_leaves) > 1
    assert stats["retries"] == 0 and stats["host_syncs"] == 0, stats
    lid = np.asarray(leaf_h)[: case["n"]]
    assert lid.min() >= 0 and lid.max() < int(tree_h.num_leaves)


def test_hierarchical_categorical_splits_same_partition(case):
    """Categorical hierarchy training: split features/thresholds/gains
    match the single-device round, and every categorical node's bin
    mask describes the SAME partition — exactly equal, or the
    complement (sides swapped): the many-vs-many asc/desc ratio scans
    evaluate one partition from both ends at the (used+1)//2 cap, so
    collective summation order may flip which direction wins a
    float-tie.  The partition itself — which bins separate from which —
    is invariant."""
    rng = np.random.RandomState(7)
    n = 1200
    Xc = rng.randint(0, 6, size=(n, 2)).astype(np.float64)
    Xn = rng.randn(n, 3)
    X = np.concatenate([Xn, Xc], axis=1)
    f = X.shape[1]
    y = (Xn[:, 0] + (Xc[:, 0] > 2) + 0.3 * rng.randn(n) > 0.5)
    binner = DatasetBinner.fit(X, max_bin=31, categorical_features=[3, 4])
    bins = binner.transform(X)
    grad = jnp.asarray(0.6 * (y - 0.5), jnp.float32)
    hess = jnp.ones((n,), jnp.float32)
    cmask = jnp.asarray(np.asarray(binner.categorical_mask))
    kw = dict(num_leaves=11, num_bins=32,
              params=SplitParams(min_data_in_leaf=5.0), leaf_tile=4,
              use_pallas=False)
    tree_s, _ = grow_tree_windowed(
        jnp.asarray(bins.T, jnp.int16), grad, hess, jnp.ones((n,), bool),
        jnp.ones((n,), jnp.float32), jnp.ones((f,), bool),
        jnp.asarray(binner.num_bins_per_feature),
        jnp.asarray(binner.missing_bin_per_feature),
        categorical_mask=cmask, **kw)
    sd = SlicedData(make_mesh_hierarchical(2, 2), bins,
                    binner.num_bins_per_feature,
                    binner.missing_bin_per_feature)
    tree_h, _ = grow_tree_windowed_hierarchical(
        sd, sd.pad_rows(np.asarray(grad)), sd.pad_rows(np.asarray(hess)),
        sd.row_valid, sd.pad_rows(np.ones(n, np.float32), fill=1.0),
        jnp.ones((f,), bool), categorical_mask=cmask, merge="psum",
        top_k_features=f, **kw)
    assert int(tree_s.num_leaves) == int(tree_h.num_leaves)
    m = int(tree_s.num_leaves) - 1
    np.testing.assert_array_equal(
        np.asarray(tree_s.split_feature)[:m],
        np.asarray(tree_h.split_feature)[:m])
    np.testing.assert_array_equal(
        np.asarray(tree_s.is_cat)[:m], np.asarray(tree_h.is_cat)[:m])
    np.testing.assert_allclose(
        np.asarray(tree_s.split_gain)[:m],
        np.asarray(tree_h.split_gain)[:m], rtol=1e-4, atol=1e-5)
    ms = np.asarray(tree_s.cat_mask)[:m]
    mh = np.asarray(tree_h.cat_mask)[:m]
    for i in np.nonzero(np.asarray(tree_s.is_cat)[:m])[0]:
        same = (ms[i] == mh[i]).all()
        complement = not (ms[i] & mh[i]).any() and ms[i].any() and mh[i].any()
        assert same or complement, (i, ms[i], mh[i])


def test_hierarchical_refuses_per_node_sampling(case):
    sd = _sliced(case)
    kw = dict(case["kw"])
    kw["params"] = SplitParams(min_data_in_leaf=5.0,
                               feature_fraction_bynode=0.5)
    n = case["n"]
    with pytest.raises(ValueError, match="per-node feature sampling"):
        grow_tree_windowed_hierarchical(
            sd, sd.pad_rows(np.asarray(case["grad"])),
            sd.pad_rows(np.asarray(case["hess"])), sd.row_valid,
            sd.pad_rows(np.ones(n, np.float32), fill=1.0),
            jnp.ones((case["f"],), bool), **kw)


def test_mesh_axes_and_divisibility():
    mesh = make_mesh_hierarchical(2, 2)
    assert mesh.axis_names == (DCN_AXIS, ICI_AXIS)
    with pytest.raises(ValueError, match="divide"):
        make_mesh_hierarchical(3)  # 8 devices / 3 slices
    with pytest.raises(ValueError, match=">= 1"):
        make_mesh_hierarchical(0)


def test_booster_routes_num_slices_to_hierarchical(monkeypatch):
    """Booster-level routing: num_slices=2 with tree_learner=data|voting
    (windowed gate forced — the real gate needs a TPU + wide shape)
    builds the nested mesh, dispatches through the hierarchical path,
    and trains an accurate model; voting maps to the owned-feature
    scatter merge intra-slice."""
    from lightgbm_tpu.models.gbdt import GBDT

    rng = np.random.RandomState(12)
    X = rng.randn(2000, 6).astype(np.float32)
    y = ((X @ rng.randn(6)) > 0).astype(np.float64)
    monkeypatch.setattr(GBDT, "_use_windowed_dp",
                        lambda self, ts: self._dp is not None)
    for tl, want_merge in (("data", "psum"), ("voting", "scatter")):
        ds = lgb.Dataset(X, label=y)
        bst = lgb.Booster(
            params={"objective": "binary", "num_leaves": 15,
                    "verbosity": -1, "tree_learner": tl,
                    "tree_growth_mode": "rounds", "num_slices": 2,
                    "top_k_features": 6}, train_set=ds)
        g = bst._gbdt
        assert g._dp_hier is not None and g._dp_hier.num_slices == 2
        assert g._use_windowed_hier(g.train_set)
        assert g._windowed_dp_merge() == want_merge
        for _ in range(5):
            bst.update()
        p = bst.predict(X)
        acc = np.mean((p > 0.5) == (y > 0))
        assert acc > 0.85, (tl, acc)


def test_booster_num_slices_indivisible_falls_back(monkeypatch):
    """num_slices that does not divide the device count warns and trains
    on the single-level mesh instead of failing."""
    rng = np.random.RandomState(3)
    X = rng.randn(600, 5).astype(np.float32)
    y = ((X @ rng.randn(5)) > 0).astype(np.float64)
    ds = lgb.Dataset(X, label=y)
    bst = lgb.Booster(
        params={"objective": "binary", "num_leaves": 7, "verbosity": -1,
                "tree_learner": "data", "num_slices": 3}, train_set=ds)
    assert bst._gbdt._dp_hier is None
    assert bst._gbdt._dp is not None
    bst.update()
    assert bst.num_trees() == 1
