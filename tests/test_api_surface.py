"""Booster/Dataset API-surface parity (reference: basic.py methods
trees_to_dataframe, lower/upper_bound, reset_parameter, shuffle_models,
Dataset get_/set_ helpers)."""

import numpy as np
import pytest

import lightgbm_tpu as lgb


@pytest.fixture(scope="module")
def trained():
    rng = np.random.RandomState(0)
    X = rng.randn(500, 4)
    y = (X[:, 0] + 0.3 * X[:, 1] > 0).astype(float)
    d = lgb.Dataset(X, label=y, free_raw_data=False)
    bst = lgb.train({"objective": "binary", "num_leaves": 7, "verbosity": -1},
                    d, num_boost_round=4)
    return X, y, d, bst


def test_trees_to_dataframe(trained):
    _, _, _, bst = trained
    df = bst.trees_to_dataframe()
    assert set(df["tree_index"]) == {0, 1, 2, 3}
    assert {"node_index", "parent_index", "split_feature", "value", "count"} <= set(df.columns)
    roots = df[df["parent_index"].isna()]
    assert len(roots) == 4  # one root per tree
    # leaves have no split_feature; internals have feature NAMES
    internal = df[df["split_feature"].notna()]
    assert internal["split_feature"].str.startswith("Column_").all()
    # per-tree node count = 2*num_leaves-1
    m = bst.dump_model()
    for t in m["tree_info"]:
        nodes = df[df["tree_index"] == t["tree_index"]]
        assert len(nodes) == 2 * t["num_leaves"] - 1


def test_bounds(trained):
    X, _, _, bst = trained
    lo, hi = bst.lower_bound(), bst.upper_bound()
    assert lo < hi
    raw = bst.predict(X, raw_score=True)
    assert raw.min() >= lo - 1e-6
    assert raw.max() <= hi + 1e-6


def test_reset_parameter(trained):
    _, _, _, bst = trained
    bst.reset_parameter({"learning_rate": 0.25})
    assert bst._gbdt.cfg.learning_rate == 0.25


def test_shuffle_models_prediction_invariant(trained):
    X, _, _, bst = trained
    before = bst.predict(X, raw_score=True)
    bst.shuffle_models()
    after = bst.predict(X, raw_score=True)
    np.testing.assert_allclose(before, after, rtol=1e-6)


def test_dataset_getters_setters():
    rng = np.random.RandomState(1)
    X = rng.randn(100, 3)
    y = rng.rand(100)
    d = lgb.Dataset(X, label=y, free_raw_data=False)
    assert d.get_data() is X
    np.testing.assert_array_equal(d.get_label(), y)
    d.set_weight(np.ones(100))
    assert d.get_weight().sum() == 100
    d.set_position(np.arange(100))
    assert d.get_position()[-1] == 99
    d.set_feature_name(["a", "b", "c"])
    d.construct()
    assert d.get_feature_name() == ["a", "b", "c"]
    assert d.feature_num_bin("a") > 1
    with pytest.raises(lgb.LightGBMError):
        d.set_feature_name(["x"])  # wrong length after construction


def test_dataset_ref_chain_and_set_reference():
    rng = np.random.RandomState(2)
    X = rng.randn(200, 3)
    d1 = lgb.Dataset(X, label=(X[:, 0] > 0).astype(float))
    d2 = lgb.Dataset(X + 0.1, label=(X[:, 0] > 0).astype(float))
    d2.set_reference(d1)
    d2.construct()
    assert d2.binner is d1.binner
    chain = d2.get_ref_chain()
    assert d1 in chain and d2 in chain


def test_add_features_from():
    rng = np.random.RandomState(3)
    X1 = rng.randn(150, 2)
    X2 = rng.randn(150, 3)
    d1 = lgb.Dataset(X1, label=(X1[:, 0] > 0).astype(float), free_raw_data=False)
    d2 = lgb.Dataset(X2, free_raw_data=False)
    d1.construct()
    d1.add_features_from(d2)
    assert d1.num_feature() == 5
    assert len(d1.get_feature_name()) == 5
    # still trainable after concat
    bst = lgb.train({"objective": "binary", "verbosity": -1}, d1, num_boost_round=2)
    assert bst.num_trees() == 2


def test_set_train_data_name(trained):
    _, _, _, bst = trained
    bst.set_train_data_name("my_train")
    assert bst._train_data_name == "my_train"
