"""Plotting + eval-recording surface (reference: tests cover plotting via
test_plotting.py in the python package)."""

import matplotlib

matplotlib.use("Agg")

import numpy as np
import pytest

import lightgbm_tpu as lgb


@pytest.fixture(scope="module")
def model():
    rng = np.random.RandomState(0)
    X = rng.randn(300, 5)
    y = (X[:, 0] + 0.5 * X[:, 1] > 0).astype(float)
    d = lgb.Dataset(X, label=y)
    bst = lgb.train(
        {"objective": "binary", "num_leaves": 7, "verbosity": -1},
        d, num_boost_round=5,
    )
    return bst

pytestmark = pytest.mark.slow


def test_plot_importance(model):
    ax = lgb.plot_importance(model)
    labels = [t.get_text() for t in ax.get_yticklabels()]
    assert "Column_0" in labels
    assert ax.get_title() == "Feature importance"


def test_plot_split_value_histogram(model):
    ax = lgb.plot_split_value_histogram(model, feature=0)
    assert ax is not None
    with pytest.raises(ValueError):
        # feature 4 may or may not be used; an unknown name must raise
        lgb.plot_split_value_histogram(model, feature="nope")


def test_get_split_value_histogram(model):
    hist, edges = model.get_split_value_histogram(0)
    assert hist.sum() == int(model.feature_importance("split")[0])
    assert len(edges) == len(hist) + 1


def test_plot_metric_from_record():
    rng = np.random.RandomState(1)
    X = rng.randn(300, 5)
    y = (X[:, 0] > 0).astype(float)
    d = lgb.Dataset(X, label=y)
    ev = {}
    lgb.train(
        {"objective": "binary", "metric": "binary_logloss", "verbosity": -1},
        d, num_boost_round=5, valid_sets=[d], valid_names=["train"],
        callbacks=[lgb.record_evaluation(ev)],
    )
    ax = lgb.plot_metric(ev)
    assert ax.get_ylabel() == "binary_logloss"
    with pytest.raises(TypeError):
        lgb.plot_metric(lgb.Booster.__new__(lgb.Booster))


def test_plot_tree_and_digraph(model):
    g = lgb.create_tree_digraph(model, tree_index=0, show_info=["internal_count", "leaf_count"])
    src = g.source
    assert "split0" in src and "leaf" in src
    with pytest.raises(IndexError):
        lgb.create_tree_digraph(model, tree_index=99)
    # plot_tree renders through graphviz's dot binary; skip if absent
    import shutil

    if shutil.which("dot") is None:
        pytest.skip("graphviz dot binary not installed")
    ax = lgb.plot_tree(model)
    assert ax is not None


def test_sklearn_evals_result():
    rng = np.random.RandomState(2)
    X = rng.randn(300, 5)
    y = (X[:, 0] > 0).astype(int)
    clf = lgb.LGBMClassifier(n_estimators=5, verbosity=-1)
    clf.fit(X, y, eval_set=[(X, y)], eval_metric="binary_logloss")
    assert "valid_0" in clf.evals_result_
    assert len(clf.evals_result_["valid_0"]["binary_logloss"]) == 5
    ax = lgb.plot_metric(clf)
    assert ax is not None
