"""Fault-injection harness unit tests (utils/faults.py): spec parsing,
deterministic once-only firing, rank gating, cross-process markers.  The
end-to-end recovery scenarios the harness drives live in test_resume.py
(host crash / snapshot-write crash), test_nonfinite.py (NaN grads),
test_degrade.py (Pallas kernel failure) and test_launcher.py
(worker death + watchdog restart)."""

import numpy as np
import pytest

from lightgbm_tpu.utils import faults


@pytest.fixture(autouse=True)
def _clean_registry():
    faults.reset()
    yield
    faults.reset()


def test_parse_spec_grammar():
    assert faults.parse_spec("") == {}
    assert faults.parse_spec("host_crash:3") == {"host_crash": 3}
    assert faults.parse_spec("host_crash:3,pallas_hist:0") == {
        "host_crash": 3, "pallas_hist": 0}
    with pytest.raises(ValueError):
        faults.parse_spec("host_crash")  # missing round
    with pytest.raises(ValueError):
        faults.parse_spec("host_crash:x")


def test_fire_is_deterministic_and_once(monkeypatch):
    monkeypatch.setenv("LGBMTPU_FAULT", "host_crash:3")
    assert not faults.fire("host_crash", 1)
    assert not faults.fire("host_crash", 2)
    assert faults.fire("host_crash", 3)
    # once only, even if the same round is probed again (a resumed loop)
    assert not faults.fire("host_crash", 3)
    # unarmed sites never fire
    assert not faults.fire("snapshot_write", 3)


def test_unarmed_env_is_free_of_side_effects(monkeypatch):
    monkeypatch.delenv("LGBMTPU_FAULT", raising=False)
    assert not faults.fire("host_crash", 1)
    faults.maybe_fail("pallas_hist")  # call-counted site: must not raise
    arr = np.ones(4)
    assert faults.corrupt_nonfinite("nonfinite_grad", 1, arr) is arr


def test_call_counted_sites(monkeypatch):
    monkeypatch.setenv("LGBMTPU_FAULT", "pallas_hist:2")
    faults.maybe_fail("pallas_hist")  # call 0
    faults.maybe_fail("pallas_hist")  # call 1
    with pytest.raises(faults.InjectedFault) as ei:
        faults.maybe_fail("pallas_hist")  # call 2 fires
    assert ei.value.site == "pallas_hist"
    faults.maybe_fail("pallas_hist")  # counter moved past: clean again
    monkeypatch.setenv("LGBMTPU_FAULT", "host_crash:1")
    with pytest.raises(ValueError):
        faults.fire("host_crash")  # armed round-stamped site needs a round


def test_rank_gating(monkeypatch):
    monkeypatch.setenv("LGBMTPU_FAULT", "worker_death:1")
    monkeypatch.setenv("LGBMTPU_FAULT_RANK", "1")
    monkeypatch.setenv("LIGHTGBM_TPU_RANK", "0")
    assert not faults.fire("worker_death", 1)
    faults.reset()
    monkeypatch.setenv("LIGHTGBM_TPU_RANK", "1")
    assert faults.fire("worker_death", 1)


def test_once_dir_markers_survive_process_registry(tmp_path, monkeypatch):
    """The cross-process once-only contract: a marker file left by the
    'first process' stops the 'second process' (fresh registry) from
    re-firing — how a watchdog relaunch runs clean."""
    monkeypatch.setenv("LGBMTPU_FAULT", "worker_death:2")
    monkeypatch.setenv("LGBMTPU_FAULT_ONCE_DIR", str(tmp_path))
    assert faults.fire("worker_death", 2)
    faults.reset()  # simulate the relaunched process
    assert not faults.fire("worker_death", 2)
    markers = list(tmp_path.glob("lgbmtpu_fault_*.fired"))
    assert len(markers) == 1


def test_corrupt_nonfinite_poisons_at_round(monkeypatch):
    monkeypatch.setenv("LGBMTPU_FAULT", "nonfinite_grad:2")
    a = np.zeros(5)
    assert faults.corrupt_nonfinite("nonfinite_grad", 1, a) is a
    b = faults.corrupt_nonfinite("nonfinite_grad", 2, np.zeros(5))
    assert np.isnan(b[0]) and np.isfinite(b[1:]).all()

    import jax.numpy as jnp

    faults.reset()
    d = faults.corrupt_nonfinite("nonfinite_grad", 2, jnp.zeros((4,)))
    assert bool(jnp.isnan(d[0]))
