"""Regression tests for review findings (zero_as_missing routing, RF alias
shrinkage, rank_xendcg objective, train-set eval alias, f32 threshold
rounding)."""

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.binning import find_bin, MISSING_ZERO


def test_zero_as_missing_has_missing_bin():
    rng = np.random.RandomState(0)
    v = rng.randn(1000)
    v[rng.rand(1000) < 0.3] = 0.0
    m = find_bin(v, max_bin=15, zero_as_missing=True)
    assert m.missing_type == MISSING_ZERO
    assert m.missing_bin >= 0
    bins = m.transform(v)
    assert np.all(bins[v == 0.0] == m.missing_bin)
    assert np.all(bins[v != 0.0] != m.missing_bin)
    # NaN joins the zero stream
    assert m.transform(np.asarray([np.nan]))[0] == m.missing_bin


def test_zero_as_missing_train_predict_agree():
    rng = np.random.RandomState(1)
    n = 600
    X = rng.randn(n, 4)
    X[rng.rand(n, 4) < 0.4] = 0.0
    y = (X[:, 0] + 0.5 * X[:, 1] > 0).astype(np.float64)
    ds = lgb.Dataset(X, label=y, params={"zero_as_missing": True})
    bst = lgb.train(
        {"objective": "binary", "num_leaves": 15, "zero_as_missing": True,
         "verbosity": -1, "min_data_in_leaf": 5},
        ds, num_boost_round=10,
    )
    # raw-value prediction must match the training-time leaf routing: compare
    # prediction on the training matrix with the internal training score
    import jax.numpy as jnp

    internal = np.asarray(bst._gbdt.objective.convert_output(bst._gbdt._score))
    external = bst.predict(X)
    np.testing.assert_allclose(external, internal, rtol=1e-4, atol=1e-5)


def test_rf_alias_matches_rf():
    rng = np.random.RandomState(2)
    X = rng.randn(500, 5)
    y = (X[:, 0] > 0).astype(np.float64)
    params = {
        "objective": "binary", "num_leaves": 7, "verbosity": -1,
        "bagging_freq": 1, "bagging_fraction": 0.8, "learning_rate": 0.1,
        "min_data_in_leaf": 5, "seed": 7,
    }
    p1 = lgb.train({**params, "boosting": "rf"}, lgb.Dataset(X, label=y), 5).predict(X)
    p2 = lgb.train({**params, "boosting": "random_forest"}, lgb.Dataset(X, label=y), 5).predict(X)
    np.testing.assert_allclose(p1, p2, rtol=1e-6)


def test_rank_xendcg_trains_and_improves_ndcg():
    rng = np.random.RandomState(3)
    n_q, q_len = 40, 12
    n = n_q * q_len
    X = rng.randn(n, 6)
    rel = X[:, 0] * 1.5 + 0.5 * X[:, 1] + 0.3 * rng.randn(n)
    label = np.digitize(rel, np.quantile(rel, [0.5, 0.75, 0.9])).astype(np.float64)
    group = np.full(n_q, q_len)
    ds = lgb.Dataset(X, label=label, group=group)
    bst = lgb.train(
        {"objective": "xendcg", "num_leaves": 15, "verbosity": -1,
         "min_data_in_leaf": 3, "metric": "ndcg", "eval_at": [5]},
        ds, num_boost_round=30,
    )
    from lightgbm_tpu.metrics import ndcg_at_k

    qb = np.arange(0, n + 1, q_len)
    gains = np.asarray([2.0**i - 1 for i in range(31)])
    pred = bst.predict(X, raw_score=True)
    nd = ndcg_at_k(pred, label, qb, 5, gains)
    nd0 = ndcg_at_k(np.zeros(n), label, qb, 5, gains)
    assert nd > nd0 + 0.05, (nd, nd0)


def test_train_set_alias_in_valid_names():
    rng = np.random.RandomState(4)
    X = rng.randn(300, 4)
    y = (X[:, 0] > 0).astype(np.float64)
    ds = lgb.Dataset(X, label=y)
    rec = {}
    lgb.train(
        {"objective": "binary", "num_leaves": 7, "verbosity": -1, "metric": "binary_logloss"},
        ds, num_boost_round=3,
        valid_sets=[ds], valid_names=["train"],
        callbacks=[lgb.record_evaluation(rec)],
    )
    assert "train" in rec, rec.keys()


def test_f32_threshold_round_up():
    from lightgbm_tpu.models.gbdt import _f32_threshold_upper

    t = np.asarray([0.1 + 1e-12, 1.0, np.float64(np.float32(2.5))])
    t32 = _f32_threshold_upper(t)
    assert t32.dtype == np.float32
    assert np.all(t32.astype(np.float64) >= t)
    assert t32[2] == np.float32(2.5)


def test_ranking_variable_query_lengths_row0_gradient():
    """Regression: padded-query scatter used .set with duplicate index 0 —
    any ragged query layout silently zeroed document 0's grad/hess."""
    import numpy as np
    import jax.numpy as jnp
    from lightgbm_tpu.config import Config
    from lightgbm_tpu.objectives import LambdarankNDCG, RankXENDCG

    group = np.array([3, 5, 2])
    qb = np.concatenate([[0], np.cumsum(group)])
    n = int(qb[-1])
    rng = np.random.RandomState(0)
    labels = rng.randint(0, 3, n).astype(np.float64)
    score = jnp.asarray(rng.randn(n), jnp.float32)
    for cls in (LambdarankNDCG, RankXENDCG):
        obj = cls(Config(objective="lambdarank"))
        obj.set_query(qb, labels)
        g, h = obj.get_gradients(score, jnp.asarray(labels, jnp.float32), None)
        g, h = np.asarray(g), np.asarray(h)
        assert np.all(np.isfinite(g)) and np.all(np.isfinite(h))
        # row 0 belongs to a non-degenerate query: its hessian must be > 0
        assert h[0] > 0, (cls.__name__, h[:5])


def test_reset_parameter_num_leaves_applies_to_fused_path():
    """Advisor r2 (medium): the fused step bakes num_leaves as a trace
    constant; reset_parameter({'num_leaves': ...}) must invalidate it
    (reference: GBDT::ResetConfig propagates to the tree learner)."""
    rng = np.random.RandomState(0)
    X = rng.randn(3000, 10)
    y = (X @ rng.randn(10) > 0).astype(np.float64)
    d = lgb.Dataset(X, label=y)
    bst = lgb.train(
        {"objective": "binary", "num_leaves": 31, "verbosity": -1,
         "fused_training": True, "min_data_in_leaf": 5},
        d, num_boost_round=3, keep_training_booster=True)
    bst.reset_parameter({"num_leaves": 4})
    for _ in range(3):
        bst.update()
    info = bst.dump_model()["tree_info"]
    assert any(t["num_leaves"] > 4 for t in info[:3])
    assert all(t["num_leaves"] <= 4 for t in info[3:])


def test_capi_parse_params_bool_strings():
    """Advisor r2 (low): 'header=false' must not evaluate truthy."""
    from lightgbm_tpu.capi_helpers import _parse_params

    p = _parse_params("header=false two_round=true verbosity=-1 label_column=name:y")
    assert p["header"] is False
    assert p["two_round"] is True
    assert p["verbosity"] == -1
    assert p["label_column"] == "name:y"


def test_capi_get_eval_uses_registration_order():
    """Advisor r2 (low): data_idx must index valid sets by registration
    order, not lexicographic name order (reference: LGBM_BoosterGetEval)."""
    from lightgbm_tpu.capi_helpers import booster_get_eval_into

    rng = np.random.RandomState(1)
    X = rng.randn(600, 5)
    y = rng.randn(600)
    d = lgb.Dataset(X, label=y)
    valids, names = [], []
    # 11 valid sets: lexicographic order of auto names != registration order
    for i in range(11):
        Xi = rng.randn(50, 5) + i  # shifted -> distinct l2
        valids.append(lgb.Dataset(Xi, label=rng.randn(50) + i, reference=d))
        names.append(f"valid_{i}")
    bst = lgb.train({"objective": "regression", "num_leaves": 4,
                     "verbosity": -1, "metric": "l2"},
                    d, num_boost_round=2, valid_sets=valids,
                    valid_names=names, keep_training_booster=True)
    expected = {name: val for name, _m, val, _b in bst.eval_valid()}
    out = np.zeros(4, np.float64)
    for idx, name in enumerate(names, start=1):
        n = booster_get_eval_into(bst, idx, out.ctypes.data)
        assert n >= 1
        assert out[0] == pytest.approx(expected[name])


def test_capi_refit_uses_init_score_and_weights():
    """Advisor r3 (medium): LGBM_BoosterRefit must compute first-iteration
    gradients at the model's init score (boost_from_average) with the
    training weights, not at zero/unweighted (reference: GBDT::RefitTree).

    With refit_decay_rate=0, identical data/weights/leaf assignments make
    the refitted leaf values reproduce training's own first-tree values —
    only if score init and weighting match training exactly."""
    from lightgbm_tpu.capi_helpers import booster_refit_leaf_preds

    rng = np.random.RandomState(5)
    n = 600
    X = rng.randn(n, 5)
    y = ((X @ rng.randn(5) + 0.8) > 0).astype(np.float64)  # unbalanced
    w = rng.uniform(0.5, 2.0, n)
    ds = lgb.Dataset(X, label=y, weight=w)
    bst = lgb.train({"objective": "binary", "num_leaves": 15,
                     "verbosity": -1, "refit_decay_rate": 0.0,
                     "min_data_in_leaf": 5}, ds, 1,
                    keep_training_booster=True)
    assert bst._gbdt.init_scores and bst._gbdt.init_scores[0] != 0.0
    tree = bst._gbdt.models[0]
    before = np.asarray(tree.leaf_value).copy()
    leaf = np.ascontiguousarray(
        bst.predict(X, pred_leaf=True).astype(np.int32).reshape(n, -1))
    assert booster_refit_leaf_preds(bst, leaf.ctypes.data, n, leaf.shape[1])
    after = np.asarray(bst._gbdt.models[0].leaf_value)
    np.testing.assert_allclose(after, before, rtol=1e-4, atol=1e-7)


def test_serialized_reference_is_inert_data():
    """Advisor r3 (medium): the schema buffer crossing process/machine
    boundaries must be data (magic + npz arrays), never pickle."""
    import ctypes

    from lightgbm_tpu.capi_helpers import (
        _SCHEMA_MAGIC, dataset_from_serialized_reference,
        dataset_serialize_reference)

    rng = np.random.RandomState(6)
    X = rng.randn(300, 4)
    X[rng.rand(300, 4) < 0.2] = np.nan
    ds = lgb.Dataset(X, label=(X[:, 0] > 0).astype(float),
                     params={"max_bin": 31})
    buf = dataset_serialize_reference(ds)
    assert buf.startswith(_SCHEMA_MAGIC)
    assert b"pickle" not in buf and b"BinMapper" not in buf

    # round trip preserves every mapper field
    arr = (ctypes.c_uint8 * len(buf)).from_buffer(bytearray(buf))
    sds = dataset_from_serialized_reference(ctypes.addressof(arr), len(buf),
                                            300, "")
    src = ds.construct().binner.mappers
    got = sds.reference.binner.mappers
    assert len(src) == len(got)
    for a, b in zip(src, got):
        assert a.missing_type == b.missing_type
        assert a.is_categorical == b.is_categorical
        np.testing.assert_array_equal(np.asarray(a.upper_bounds),
                                      np.asarray(b.upper_bounds))

    # tampered magic is rejected, not deserialized
    bad = b"XX" + buf[2:]
    arr2 = (ctypes.c_uint8 * len(bad)).from_buffer(bytearray(bad))
    with pytest.raises(ValueError, match="magic"):
        dataset_from_serialized_reference(ctypes.addressof(arr2), len(bad),
                                          300, "")


def test_save_binary_reload_trains_identically(tmp_path):
    """save_binary checkpoints reload as a Dataset path (reference:
    DatasetLoader::LoadFromBinFile): binned matrix + mappers round-trip and
    training from the reload is bit-identical."""
    rng = np.random.RandomState(0)
    X = rng.randn(2000, 6)
    X[rng.rand(2000, 6) < 0.1] = np.nan
    y = (np.nan_to_num(X) @ rng.randn(6) > 0).astype(float)
    params = {"objective": "binary", "verbosity": -1, "num_leaves": 7,
              "max_bin": 63}
    ds = lgb.Dataset(X, label=y, params=params)
    p = str(tmp_path / "d.bin")
    ds.construct()
    ds.save_binary(p)

    ds2 = lgb.Dataset(p, params=params)
    ds2.construct()
    np.testing.assert_array_equal(np.asarray(ds.bins), np.asarray(ds2.bins))
    for a, b in zip(ds.binner.mappers, ds2.binner.mappers):
        assert a.missing_type == b.missing_type
        np.testing.assert_array_equal(a.upper_bounds, b.upper_bounds)

    b1 = lgb.train(params, lgb.Dataset(X, label=y, params=params), 5)
    b2 = lgb.train(params, lgb.Dataset(p, params=params), 5)
    assert b1.model_to_string() == b2.model_to_string()


def test_save_binary_preserves_init_score_and_position(tmp_path):
    """Metadata init_score/position survive the binary round-trip
    (reference: Metadata::SaveBinaryToFile persists init_score_ and
    positions_; a reload that silently dropped them would retrain
    differently)."""
    rng = np.random.RandomState(1)
    X = rng.randn(500, 4)
    y = (X @ rng.randn(4) > 0).astype(float)
    init = rng.randn(500)
    pos = rng.randint(0, 10, 500).astype(np.int64)
    params = {"objective": "binary", "verbosity": -1, "max_bin": 63}
    ds = lgb.Dataset(X, label=y, init_score=init, position=pos, params=params)
    p = str(tmp_path / "d.bin")
    ds.construct()
    ds.save_binary(p)

    ds2 = lgb.Dataset(p, params=params)
    ds2.construct()
    np.testing.assert_array_equal(ds2.get_init_score(), init)
    np.testing.assert_array_equal(ds2.get_position(), pos)
    # training from the reload matches training from the original metadata
    b1 = lgb.train(params, lgb.Dataset(X, label=y, init_score=init,
                                       params=params), 5)
    b2 = lgb.train(params, lgb.Dataset(p, params=params), 5)
    assert b1.model_to_string() == b2.model_to_string()


def test_quantized_wide_default_gate():
    """The int8 wide-regime default is a TPU device default for the rounds
    grower only; an explicit user choice or monotone constraints disable
    it.  The gate is a pure predicate (models/gbdt.py) so the TPU branch
    is testable on the CPU-pinned suite."""
    from lightgbm_tpu.models.gbdt import _quantized_wide_default as gate

    base = dict(on_tpu=True, n_features=2000, max_num_bins=256,
                tree_learner="serial", tree_growth_mode="auto",
                explicitly_set=False, has_monotone=False)
    assert gate(**base) is True  # the Epsilon-class shape on TPU
    assert gate(**{**base, "on_tpu": False}) is False  # CPU stays float
    assert gate(**{**base, "n_features": 28}) is False  # narrow stays float
    assert gate(**{**base, "max_num_bins": 64}) is False
    assert gate(**{**base, "explicitly_set": True}) is False  # user wins
    assert gate(**{**base, "has_monotone": True}) is False
    assert gate(**{**base, "tree_growth_mode": "strict"}) is False
    assert gate(**{**base, "tree_learner": "feature"}) is False
    # 'data' rides the rounds grower only multi-device (_use_fast_dp gate);
    # single-device 'data' falls to the strict grower, which trains float
    assert gate(**{**base, "tree_learner": "data"}) is False
    assert gate(**{**base, "tree_learner": "data", "device_count": 8}) is True

    # end-to-end on the CPU suite: the booster stays float and records an
    # explicit choice
    rng = np.random.RandomState(0)
    X = rng.randn(400, 300).astype(np.float32)
    y = (X[:, 0] > 0).astype(np.float64)
    b = lgb.Booster(params={"objective": "binary", "max_bin": 255,
                            "verbosity": -1},
                    train_set=lgb.Dataset(X, label=y, params={"max_bin": 255}))
    assert b._gbdt.cfg.use_quantized_grad is False
    b2 = lgb.Booster(params={"objective": "binary", "max_bin": 255,
                             "verbosity": -1, "use_quantized_grad": False},
                     train_set=lgb.Dataset(X, label=y,
                                           params={"max_bin": 255}))
    assert b2._gbdt.cfg.is_set("use_quantized_grad")
    assert b2._gbdt.cfg.use_quantized_grad is False
