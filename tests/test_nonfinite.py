"""Non-finite guard rails (docs/ROBUSTNESS.md): boundary validation at
Dataset construction, the windowed grower's info-vector guard (which must
cost zero extra dispatches/syncs — the round-7 budget pin holds with
guards on), and the deferred device-side guard on the fast/full-pass
paths."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.utils import faults
from lightgbm_tpu.utils.guards import NonFiniteError


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.reset()
    yield
    faults.reset()


def _data(n=300, f=5, seed=0):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, f)
    y = (X[:, 0] + 0.4 * X[:, 1] > 0).astype(float)
    return X, y


# ---------------------------------------------------------------------------
# boundary validation
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("bad", [np.nan, np.inf, -np.inf])
def test_nonfinite_label_raises_at_construct(bad):
    X, y = _data()
    y = y.copy()
    y[7] = bad
    with pytest.raises(NonFiniteError, match=r"label.*index 7"):
        lgb.Dataset(X, label=y).construct()


def test_nonfinite_weight_and_init_score_raise():
    X, y = _data()
    w = np.ones(len(y))
    w[3] = np.nan
    with pytest.raises(NonFiniteError, match="weight"):
        lgb.Dataset(X, label=y, weight=w).construct()
    s = np.zeros(len(y))
    s[0] = np.inf
    with pytest.raises(NonFiniteError, match="init_score"):
        lgb.Dataset(X, label=y, init_score=s).construct()


def test_set_field_validates_too():
    X, y = _data()
    d = lgb.Dataset(X, label=y)
    bad = y.copy()
    bad[0] = np.nan
    with pytest.raises(NonFiniteError):
        d.set_label(bad)


def test_train_boundary_raises_before_any_boosting():
    X, y = _data()
    y = y.copy()
    y[0] = np.nan
    with pytest.raises(NonFiniteError):
        lgb.train({"objective": "binary", "verbosity": -1},
                  lgb.Dataset(X, label=y), 2)


def test_nan_features_are_still_fine():
    """Features keep the missing-value path — only targets are guarded."""
    X, y = _data()
    X = X.copy()
    X[::7, 2] = np.nan
    bst = lgb.train({"objective": "binary", "num_leaves": 7,
                     "verbosity": -1}, lgb.Dataset(X, label=y), 3)
    assert np.isfinite(bst.predict(X)).all()


# ---------------------------------------------------------------------------
# windowed grower: guard rides the async info vector
# ---------------------------------------------------------------------------

def _windowed_inputs(n=900, f=8, seed=5):
    from lightgbm_tpu.binning import DatasetBinner
    from lightgbm_tpu.ops.split import SplitParams

    rng = np.random.RandomState(seed)
    X = rng.randn(n, f)
    y = X @ rng.randn(f) + 0.2 * rng.randn(n)
    binner = DatasetBinner.fit(X, max_bin=31)
    bins_t = jnp.asarray(binner.transform(X).T, jnp.int16)
    grad = jnp.asarray(0.6 * y, jnp.float32)
    kw = dict(
        row_mask=jnp.ones((n,), bool),
        sample_weight=jnp.ones((n,), jnp.float32),
        feature_mask=jnp.ones((f,), bool),
        num_bins_pf=jnp.asarray(binner.num_bins_per_feature),
        missing_bin_pf=jnp.asarray(binner.missing_bin_per_feature),
    )
    static = dict(num_leaves=15, num_bins=32, params=SplitParams(
        min_data_in_leaf=5.0), leaf_tile=4, use_pallas=False)
    return bins_t, grad, jnp.ones((n,), jnp.float32), kw, static


def test_windowed_guard_raises_round_stamped_without_syncs():
    """NaN gradients must abort windowed growth with a round-stamped
    error, and the guard must have ridden the async info vector: zero
    blocking host pulls even on the failure path."""
    from lightgbm_tpu.ops.treegrow_windowed import grow_tree_windowed
    from lightgbm_tpu.utils.sanitizer import DispatchCounter

    bins_t, grad, hess, kw, static = _windowed_inputs()
    bad = grad.at[0].set(np.nan)
    with DispatchCounter() as d:
        with pytest.raises(NonFiniteError, match=r"windowed round \d"):
            grow_tree_windowed(bins_t, bad, hess, **kw, **static,
                               guard_label=" (boosting iteration 1)")
    assert d.host_syncs == 0


def test_windowed_clean_budget_pin_with_guards_on():
    """The acceptance pin restated locally: with the finite guard folded
    into the info vector, a steady-state windowed round is still exactly
    ONE dispatch and ZERO blocking syncs (the wider retrace pin lives in
    tests/test_retrace.py)."""
    from lightgbm_tpu.ops.treegrow_windowed import grow_tree_windowed
    from lightgbm_tpu.utils.sanitizer import DispatchCounter

    bins_t, grad, hess, kw, static = _windowed_inputs(seed=6)
    tree, leaf = grow_tree_windowed(bins_t, grad, hess, **kw, **static)
    jax.block_until_ready(leaf)  # warmup compiles

    stats = {}
    with DispatchCounter() as d:
        tree, leaf = grow_tree_windowed(bins_t, grad, hess, **kw, **static,
                                        stats=stats)
        jax.block_until_ready(leaf)
    assert int(tree.num_leaves) > 1
    d.assert_round_budget(stats["rounds"], what="windowed rounds, guards on")
    assert stats["host_syncs"] == 0 and stats["retries"] == 0, stats


# ---------------------------------------------------------------------------
# fast/full-pass mirror: deferred device-side guard
# ---------------------------------------------------------------------------

def test_custom_fobj_nan_grads_raise_round_stamped():
    """A custom objective emitting NaN gradients at iteration 3 must fail
    loudly with that iteration in the message.  Detection is deferred to
    a sync point (here: model serialization) by design — the stamp, not
    the detection latency, is the contract."""
    X, y = _data(seed=1)
    d = lgb.Dataset(X, label=y)

    calls = {"n": 0}

    def fobj(preds, train_set):
        calls["n"] += 1
        g = preds - y
        h = np.ones_like(g)
        if calls["n"] == 3:
            g = g.copy()
            g[0] = np.nan
        return g, h

    bst = lgb.train({"objective": fobj, "num_leaves": 7, "verbosity": -1},
                    d, 5)
    with pytest.raises(NonFiniteError, match="iteration 3"):
        bst.model_to_string()


def test_injected_nonfinite_grad_detected_via_eval_sync():
    """LGBMTPU_FAULT=nonfinite_grad:2 on a run with a valid set: eval
    syncs every round, so the guard fires within a round of the
    corruption, stamped with iteration 2."""
    import os

    X, y = _data(seed=2)
    os.environ["LGBMTPU_FAULT"] = "nonfinite_grad:2"
    try:
        d = lgb.Dataset(X, label=y)
        dv = lgb.Dataset(X[:100], label=y[:100], reference=d)
        with pytest.raises(NonFiniteError, match="iteration 2"):
            # fused_training=False keeps the per-phase path, where the
            # gradient injection site lives (fused steps compute g/h
            # in-trace and are covered by the fobj test above)
            lgb.train({"objective": "regression", "fused_training": False,
                       "num_leaves": 7, "verbosity": -1},
                      d, 6, valid_sets=[dv])
    finally:
        os.environ.pop("LGBMTPU_FAULT", None)


def test_injected_nonfinite_hess_detected_at_save():
    import os

    X, y = _data(seed=3)
    os.environ["LGBMTPU_FAULT"] = "nonfinite_hess:1"
    try:
        bst = lgb.train({"objective": "regression", "num_leaves": 7,
                         "verbosity": -1, "fused_training": False},
                        lgb.Dataset(X, label=y), 3)
        with pytest.raises(NonFiniteError, match="iteration 1"):
            bst.model_to_string()
    finally:
        os.environ.pop("LGBMTPU_FAULT", None)
