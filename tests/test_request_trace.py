"""Request-scoped distributed tracing pins (round 24, ISSUE 20).

The tracing contract across the serve/continual/fleet runtime: every
``/predict`` response names its trace (honoring an inbound W3C
``traceparent``), cross-thread span emission takes the EXPLICIT parent
context (never the worker thread's ambient stack — the round-24 bugfix
jaxlint R21 now polices), per-request phase breakdowns land in labeled
reservoirs with zero new device pulls, the latency series carries a
trace-id exemplar, and one hedged + requeued request reconstructs as a
single connected story from the MERGED flight-recorder export — across
threads, replicas and per-rank trace files.
"""

import json
import os
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.obs import metrics as obs
from lightgbm_tpu.obs import trace as _trc
from lightgbm_tpu.serve import ServingFleet, ServingRuntime
from lightgbm_tpu.utils import faults as flt


@pytest.fixture(autouse=True)
def _fresh_state():
    from lightgbm_tpu.obs import server as _srv

    obs.reset()
    _trc.reset_trace()
    _trc.configure_request_tracing(True, 1.0)
    os.environ.pop("LGBMTPU_FAULT", None)
    flt.reset()
    yield
    os.environ.pop("LGBMTPU_FAULT", None)
    flt.reset()
    _srv.stop_server()
    obs.reset()
    _trc.reset_trace()
    _trc.configure_request_tracing(True, 1.0)


def _binary_booster(n=400, f=6, rounds=4, seed=0):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, f)
    y = (X[:, 0] + 0.5 * X[:, 1] > 0).astype(float)
    bst = lgb.Booster(params={"objective": "binary", "num_leaves": 7,
                              "verbosity": -1},
                      train_set=lgb.Dataset(X, label=y))
    for _ in range(rounds):
        bst.update()
    return bst, X


# ---------------------------------------------------------------------------
# the round-24 bugfix: explicit parent context wins over the worker
# thread's ambient span stack
# ---------------------------------------------------------------------------

def test_cross_thread_span_takes_explicit_parent_two_dispatchers():
    """Two dispatcher threads, each with its OWN ambient housekeeping
    span open, emit request spans for two different requests.  Pre-fix,
    Span.__enter__ let the thread-local stack leak into parentage even
    when an explicit parent was given — each request span would file
    under its dispatcher's housekeeping span (the WRONG trace).  The pin:
    every span lands in exactly its request's trace, parented on the
    request context it was handed."""
    reqs = [_trc.mint_request_context() for _ in range(2)]
    barrier = threading.Barrier(2)

    def dispatcher(ctx):
        with _trc.span("dispatcher.housekeeping"):
            barrier.wait()  # both ambient spans are open right now
            with _trc.span("serve.request", parent=ctx, rows=1):
                pass
            _trc.record_span("serve.batch", 1e-4, ctx=ctx.sibling())

    threads = [threading.Thread(target=dispatcher, args=(c,))
               for c in reqs]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
    assert not any(t.is_alive() for t in threads)

    req_spans = _trc.spans("serve.request")
    batch_spans = _trc.spans("serve.batch")
    assert len(req_spans) == 2 and len(batch_spans) == 2
    house_traces = {s["trace"] for s in _trc.spans("dispatcher.housekeeping")}
    for ctx in reqs:
        mine = [s for s in req_spans if s["trace"] == ctx.trace_id]
        assert len(mine) == 1, "request span filed under the wrong trace"
        # parented on the handed context, not the ambient housekeeping
        assert mine[0]["psid"] == ctx.span_id
        assert mine[0]["trace"] not in house_traces
        sib = [s for s in batch_spans if s["trace"] == ctx.trace_id]
        assert len(sib) == 1 and "psid" not in sib[0]  # sibling: no parent


def test_record_span_without_identity_still_adopts_same_thread_parent():
    """The training-loop form is unchanged: on ONE thread, a record_span
    with no explicit identity nests under the open ambient span."""
    with _trc.span("boost_round", iteration=3) as sp:
        _trc.record_span("windowed_round", 1e-4, trees=1)
    rec = _trc.spans("windowed_round")[-1]
    assert rec["trace"] == sp.ctx.trace_id
    assert rec["psid"] == sp.ctx.span_id


# ---------------------------------------------------------------------------
# /predict front door: traceparent in, trace_id out — on EVERY outcome
# ---------------------------------------------------------------------------

def test_http_predict_honors_inbound_traceparent_and_echoes_header():
    from lightgbm_tpu.obs import server as _srv

    srv = _srv.start_server(0)
    bst, X = _binary_booster()
    caller_trace = _trc.new_trace_id()
    caller_span = _trc.new_span_id()
    with ServingRuntime(bst, max_wait_ms=10, shed_unhealthy=False) as rt:
        body = json.dumps({"rows": X[:4].tolist(),
                           "raw_score": True}).encode()
        req = urllib.request.Request(
            srv.url("/predict"), data=body,
            headers={"Content-Type": "application/json",
                     "traceparent": f"00-{caller_trace}-{caller_span}-01"})
        resp = urllib.request.urlopen(req, timeout=60)
        out = json.loads(resp.read().decode())
        # the request JOINED the caller's trace: body + response header
        assert out["trace_id"] == caller_trace
        tp_out = resp.headers.get("traceparent")
        assert tp_out is not None and tp_out.startswith(
            f"00-{caller_trace}-")
        assert tp_out.endswith("-01")
        assert np.allclose(out["predictions"],
                           bst.predict(X[:4], raw_score=True))
    # and the serve.request span descends from the caller's span
    reqs = [s for s in _trc.spans("serve.request")
            if s["trace"] == caller_trace]
    assert len(reqs) == 1
    assert reqs[0]["psid"] == caller_span
    assert reqs[0]["attrs"]["outcome"] == "ok"


def test_http_predict_error_responses_still_carry_trace_id():
    from lightgbm_tpu.obs import server as _srv

    srv = _srv.start_server(0)
    bst, _ = _binary_booster()
    caller_trace = _trc.new_trace_id()
    with ServingRuntime(bst, max_wait_ms=10, shed_unhealthy=False):
        req = urllib.request.Request(  # no "rows": a 400, not a shed
            srv.url("/predict"), data=b'{"wrong": 1}',
            headers={"traceparent":
                     f"00-{caller_trace}-{_trc.new_span_id()}-01"})
        try:
            urllib.request.urlopen(req, timeout=60)
            raise AssertionError("expected HTTP 400")
        except urllib.error.HTTPError as e:
            assert e.code == 400
            out = json.loads(e.read().decode())
            assert out["error"] == "bad_request"
            # the failed request is exactly the one the caller needs to
            # look up: its trace rides the error body AND the header
            assert out["trace_id"] == caller_trace
            assert e.headers.get("traceparent", "").startswith(
                f"00-{caller_trace}-")


def test_http_predict_mints_fresh_trace_without_inbound_header():
    bst, X = _binary_booster()
    with ServingRuntime(bst, max_wait_ms=10, shed_unhealthy=False) as rt:
        code, body, tp = rt._http_predict(
            {"rows": X[:4].tolist(), "raw_score": True})
        assert code == 200
        tid = body["trace_id"]
        assert len(tid) == 32 and int(tid, 16) != 0
        assert tp == f"00-{tid}-" + tp.split("-")[2] + "-01"
        assert _trc.spans_for_trace(tid), "no spans under the minted trace"


def test_unsampled_request_keeps_ids_but_drops_spans():
    """trace_sample=0: the response still names a trace (correlation
    never degrades) but the recorder stays empty — and the flags nibble
    of the outbound traceparent says so."""
    _trc.configure_request_tracing(True, 0.0)
    bst, X = _binary_booster()
    with ServingRuntime(bst, max_wait_ms=10, shed_unhealthy=False) as rt:
        code, body, tp = rt._http_predict(
            {"rows": X[:4].tolist(), "raw_score": True})
        assert code == 200
        assert len(body["trace_id"]) == 32
        assert tp.endswith("-00")  # unsampled flag
    assert _trc.spans("serve.request") == []
    assert _trc.spans("serve.batch") == []


# ---------------------------------------------------------------------------
# phase breakdown + exemplar: the already-accounted sync points speak
# ---------------------------------------------------------------------------

def test_phase_breakdown_reservoirs_and_latency_exemplar():
    bst, X = _binary_booster()
    with ServingRuntime(bst, max_wait_ms=10, shed_unhealthy=False) as rt:
        y = rt.predict(X[:8], raw_score=True, timeout=120)
        assert np.array_equal(y, bst.predict(X[:8], raw_score=True))
    for ph in ("queue", "coalesce", "staging", "dispatch", "sliceout"):
        h = obs.histogram(obs.labeled("serve_phase_ms", phase=ph))
        assert h.count >= 1, f"phase reservoir {ph} never fed"
        assert h.min >= 0.0
    # the request span carries the same breakdown as attributes
    rec = _trc.spans("serve.request")[-1]
    for ph in ("queue", "coalesce", "staging", "dispatch", "sliceout"):
        assert f"{ph}_ms" in rec["attrs"]
    # the latency reservoir kept a witness trace id, and the Prometheus
    # render emits it as an OpenMetrics exemplar on the count series
    ex = obs.histogram("serve_request_latency_ms").exemplar
    assert ex and ex["trace_id"] == rec["trace"]
    prom = obs.render_prometheus(obs.snapshot())
    assert f'# {{trace_id="{ex["trace_id"]}"}}' in prom


# ---------------------------------------------------------------------------
# THE acceptance: one hedged + one requeued request reconstruct
# end-to-end from the MERGED flight-recorder export
# ---------------------------------------------------------------------------

def test_hedged_and_requeued_requests_reconstruct_from_merged_export(
        tmp_path):
    from lightgbm_tpu.obs.__main__ import main as obs_main

    bst, X = _binary_booster()

    # leg 1 — a REQUEUED request on a hedge-disabled fleet (a hedge would
    # race the injected failure and deliver first, absorbing the
    # requeue): dispatch failure at stage A of the first armed
    # execution, retried exactly once onto the other replica
    fl = ServingFleet(bst, replicas=2, max_wait_ms=60, hedge_ms=0,
                      restart_backoff_ms=50, shed_unhealthy=False)
    try:
        got = fl.predict(X[:16], raw_score=True, timeout=120)  # warm
        assert np.array_equal(got, bst.predict(X[:16], raw_score=True))
        fl.predict(X[:8], raw_score=True, timeout=120)  # warm the 8-rung
        os.environ["LGBMTPU_FAULT"] = "replica_dispatch:0"
        h = fl.submit(X[:8], raw_score=True)
        y = fl.result(h, timeout=120)
        assert np.array_equal(y, bst.predict(X[:8], raw_score=True))
        assert obs.counter("serve_requeues_total").value >= 1
    finally:
        os.environ.pop("LGBMTPU_FAULT", None)
        flt.reset()
        fl.stop()

    # leg 2 — a HEDGED request on a second fleet (the span ring spans
    # both lifetimes, exactly like a flight recorder): the armed replica
    # wedges at stage A, the 25 ms hedge dispatches a second copy, first
    # result wins, the watchdog reaps the wedged leg afterwards
    fl = ServingFleet(bst, replicas=2, max_wait_ms=60, hedge_ms=25,
                      hang_timeout_ms=2_000, restart_backoff_ms=50,
                      shed_unhealthy=False)
    try:
        got = fl.predict(X[:16], raw_score=True, timeout=120)  # warm
        os.environ["LGBMTPU_FAULT"] = "replica_hang:0"
        got = fl.predict(X[16:32], raw_score=True, timeout=120)
        assert np.array_equal(got, bst.predict(X[16:32], raw_score=True))
        assert obs.counter("serve_hedges_total").value >= 1
        # wait for the watchdog: the wedged leg's serve.leg span
        # (outcome=hang) is part of the story being reconstructed
        deadline = time.monotonic() + 30
        while (obs.counter("serve_replica_hangs_total").value < 1
               and time.monotonic() < deadline):
            time.sleep(0.02)
        assert obs.counter("serve_replica_hangs_total").value == 1
    finally:
        fl.stop()

    # split the ring across two per-"rank" trace files — request spans
    # on one lane, leg/batch/requeue/hedge records on the other — so the
    # reconstruction below can only succeed THROUGH the merge
    all_spans = _trc.spans()
    rank0 = [s for s in all_spans if s["name"] == "serve.request"]
    rank1 = [s for s in all_spans if s["name"] != "serve.request"]
    p0, p1 = str(tmp_path / "worker0.trace.json"), \
        str(tmp_path / "worker1.trace.json")
    _trc.write_trace(p0, rank0)
    _trc.write_trace(p1, rank1)
    merged = _trc.merge_trace_files([p0, p1])
    assert merged["lgbmtpu"]["merged"]["clock"] == "unix-wall"
    assert len(merged["lgbmtpu"]["merged"]["sources"]) == 2
    mspans = merged["lgbmtpu"]["spans"]

    # the REQUEUED request: its slice holds the whole story — its own
    # span (attempt=1), the failed leg, the requeue decision, and the
    # winning batch — drawn from BOTH source files
    retried = [s for s in mspans if s["name"] == "serve.request"
               and s["attrs"].get("attempt", 0) >= 1
               and s["attrs"].get("outcome") == "ok"]
    assert retried, "no request span records its retried attempt"
    sl = _trc.trace_slice(retried[0]["trace"], mspans)
    names = {s["name"] for s in sl}
    assert {"serve.request", "serve.leg", "serve.requeue",
            "serve.batch"} <= names, names
    legs = [s for s in sl if s["name"] == "serve.leg"]
    assert any(s["attrs"]["outcome"] == "error" for s in legs)
    assert all("replica" in s["attrs"] for s in legs)
    assert {s.get("src") for s in sl} == {"worker0.trace.json",
                                          "worker1.trace.json"}

    # the HEDGED request: both legs stay reachable — the hedge record,
    # the wedged original (outcome=hang), and the winning batch
    hedges = [s for s in mspans if s["name"] == "serve.hedge"]
    assert hedges and hedges[0]["attrs"]["outcome"] == "hedged"
    sl2 = _trc.trace_slice(hedges[0]["trace"], mspans)
    names2 = {s["name"] for s in sl2}
    assert {"serve.request", "serve.hedge", "serve.leg",
            "serve.batch"} <= names2, names2
    assert any(s["attrs"].get("outcome") == "hang"
               for s in sl2 if s["name"] == "serve.leg")
    assert any(s["attrs"].get("outcome") == "ok"
               for s in sl2 if s["name"] == "serve.batch")

    # CLI round-trip: merge + narrow to the requeued request's trace
    out = str(tmp_path / "slice.json")
    rc = obs_main(["trace", p0, p1, "--merge",
                   "--trace-id", retried[0]["trace"], "-o", out])
    assert rc == 0
    doc = _trc.load_trace(out)
    cli_names = {s["name"] for s in doc["lgbmtpu"]["spans"]}
    assert {"serve.request", "serve.leg", "serve.requeue",
            "serve.batch"} <= cli_names
    assert doc["lgbmtpu"]["merged"]["clock"] == "unix-wall"
    # the narrowed export is the slice, not the union
    assert len(doc["lgbmtpu"]["spans"]) == len(sl)


# ---------------------------------------------------------------------------
# launcher triad: per-rank trace files aggregate like events/metrics
# ---------------------------------------------------------------------------

def test_launcher_aggregates_per_rank_trace_files(tmp_path):
    from lightgbm_tpu.parallel.launcher import aggregate_fleet_trace

    ctx = _trc.TraceContext(_trc.new_trace_id())
    _trc.record_span("boost_round", 0.01, ctx=ctx, iteration=0)
    _trc.write_trace(str(tmp_path / "worker0.trace.json"))
    _trc.reset_trace()
    _trc.record_span("windowed_round", 0.005, parent=ctx, trees=1)
    _trc.write_trace(str(tmp_path / "worker1.trace.json"))

    merged_path = aggregate_fleet_trace(str(tmp_path), 2)
    assert merged_path == str(tmp_path / "fleet_trace.json")
    doc = _trc.load_trace(merged_path)
    srcs = {s["src"] for s in doc["lgbmtpu"]["spans"]}
    assert srcs == {"worker0.trace.json", "worker1.trace.json"}
    # rank 1's span joined rank 0's trace across files
    sl = _trc.trace_slice(ctx.trace_id, doc["lgbmtpu"]["spans"])
    assert {s["name"] for s in sl} == {"boost_round", "windowed_round"}

    # a missing rank file is a missing rank, not a crash; none -> None
    assert aggregate_fleet_trace(str(tmp_path), 4) is not None
    empty = tmp_path / "empty"
    empty.mkdir()
    assert aggregate_fleet_trace(str(empty), 2) is None
