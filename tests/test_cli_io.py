"""CLI driver + text parsers + native loader tests.

Reference test-strategy analogue: tests/python_package_test/test_consistency.py
(CLI-vs-Python parity via train.conf scenarios) and tests/distributed/'s
CLI-subprocess pattern (SURVEY.md §5.2).
"""

import os
import subprocess
import sys

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.io import load_data_file, parse_text
from lightgbm_tpu.native import get_lib, parse_file_native


@pytest.fixture(scope="module")
def csv_files(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("cli")
    rng = np.random.RandomState(0)
    n, f = 1200, 6
    X = rng.randn(n, f)
    w = rng.randn(f)
    y = ((X @ w + 0.5 * rng.randn(n)) > 0).astype(np.float64)
    train_p = tmp / "train.csv"
    valid_p = tmp / "valid.csv"

    def write(path, Xa, ya):
        with open(path, "w") as fh:
            for i in range(len(Xa)):
                fh.write(",".join([f"{ya[i]:g}"] + [f"{v:.8g}" for v in Xa[i]]) + "\n")

    write(train_p, X[:1000], y[:1000])
    write(valid_p, X[1000:], y[1000:])
    return dict(tmp=tmp, train=str(train_p), valid=str(valid_p),
                X=X, y=y)

pytestmark = pytest.mark.slow


def test_native_loader_builds():
    lib = get_lib()
    assert lib is not None, "native loader failed to build (g++ present per env)"


def test_native_csv_matches_numpy(csv_files):
    native = parse_file_native(csv_files["train"], "csv", False, 0)
    assert native is not None
    data_n, label_n = native
    with open(csv_files["train"]) as fh:
        data_p, _, fmt = parse_text(fh.read(), "csv")
    assert fmt == "csv"
    # label_idx=0: column 0 becomes the label and is excluded from data
    np.testing.assert_allclose(data_n, data_p[:, 1:], rtol=0, atol=0)
    np.testing.assert_allclose(label_n, data_p[:, 0])


def test_native_libsvm(tmp_path):
    path = tmp_path / "t.svm"
    path.write_text("1 0:1.5 3:2.5\n0 1:1.0\n1 2:-3.0 3:0.25\n")
    out = parse_file_native(str(path), "libsvm", False, 0)
    assert out is not None
    data, label = out
    np.testing.assert_array_equal(label, [1, 0, 1])
    expect = np.array(
        [[1.5, 0, 0, 2.5], [0, 1.0, 0, 0], [0, 0, -3.0, 0.25]]
    )
    np.testing.assert_allclose(data, expect)


def test_load_data_file_weight_group_columns(tmp_path):
    path = tmp_path / "t.csv"
    # label, f0, weight, f1
    path.write_text("1,0.5,2.0,9\n0,1.5,1.0,8\n1,2.5,0.5,7\n")
    out = load_data_file(str(path), label_column="0", weight_column="2")
    np.testing.assert_array_equal(out["label"], [1, 0, 1])
    np.testing.assert_array_equal(out["weight"], [2.0, 1.0, 0.5])
    np.testing.assert_allclose(out["data"], [[0.5, 9], [1.5, 8], [2.5, 7]])


def test_cli_train_predict_roundtrip(csv_files):
    tmp = csv_files["tmp"]
    conf = tmp / "train.conf"
    model_p = tmp / "model.txt"
    conf.write_text(
        f"task = train\n"
        f"objective = binary\n"
        f"data = {csv_files['train']}\n"
        f"valid = {csv_files['valid']}\n"
        f"num_iterations = 10   # comment\n"
        f"num_leaves = 15\n"
        f"verbosity = -1\n"
        f"output_model = {model_p}\n"
    )
    env = dict(os.environ, PYTHONPATH="/root/repo",
               JAX_PLATFORMS="cpu", PALLAS_AXON_POOL_IPS="")
    r = subprocess.run(
        [sys.executable, "-m", "lightgbm_tpu", f"config={conf}"],
        capture_output=True, text=True, env=env, timeout=300,
    )
    assert r.returncode == 0, r.stderr[-2000:]
    assert model_p.exists()

    # predict via CLI and compare against the Python API
    out_p = tmp / "preds.txt"
    r = subprocess.run(
        [sys.executable, "-m", "lightgbm_tpu", "task=predict",
         f"data={csv_files['valid']}", f"input_model={model_p}",
         f"output_result={out_p}"],
        capture_output=True, text=True, env=env, timeout=300,
    )
    assert r.returncode == 0, r.stderr[-2000:]
    cli_preds = np.loadtxt(out_p)
    bst = lgb.Booster(model_file=str(model_p))
    api_preds = bst.predict(csv_files["X"][1000:])
    np.testing.assert_allclose(cli_preds, api_preds, rtol=1e-12, atol=1e-12)
    # the model must actually classify
    acc = ((api_preds > 0.5) == (csv_files["y"][1000:] > 0.5)).mean()
    assert acc > 0.85, acc


def test_cli_convert_model_compiles_and_matches(csv_files, tmp_path):
    """task=convert_model: generated C++ compiles with g++ and predicts
    identically to the framework (reference: Tree::ToIfElse contract)."""
    import ctypes

    X, y = csv_files["X"], csv_files["y"]
    bst = lgb.train(
        {"objective": "binary", "num_leaves": 7, "verbosity": -1},
        lgb.Dataset(X, label=y), num_boost_round=3,
    )
    model_p = tmp_path / "m.txt"
    bst.save_model(str(model_p))
    cpp_p = tmp_path / "pred.cpp"
    from lightgbm_tpu.cli import run

    rc = run([f"task=convert_model", f"input_model={model_p}",
              f"convert_model={cpp_p}"])
    assert rc == 0 and cpp_p.exists()
    so_p = tmp_path / "pred.so"
    r = subprocess.run(
        ["g++", "-O2", "-fPIC", "-shared", "-o", str(so_p), str(cpp_p)],
        capture_output=True, text=True, timeout=120,
    )
    assert r.returncode == 0, r.stderr[-2000:]
    lib = ctypes.CDLL(str(so_p))
    lib.Predict.restype = ctypes.c_double
    lib.Predict.argtypes = [ctypes.POINTER(ctypes.c_double)]
    # Contract (see GBDT.to_if_else): generated C++ is float64 and must
    # bit-match the host f64 tree walk; the f32 device predict path agrees
    # only to float32 roundoff.
    trees = bst._gbdt._trees_for_export(0, -1)
    raw64 = np.sum([t.predict(X[:64]) for t in trees], axis=0)
    host64 = 1.0 / (1.0 + np.exp(-raw64))
    api32 = bst.predict(X[:64])
    for i in range(64):
        row = np.ascontiguousarray(X[i], dtype=np.float64)
        got = lib.Predict(row.ctypes.data_as(ctypes.POINTER(ctypes.c_double)))
        assert abs(got - host64[i]) < 1e-12, (i, got, host64[i])
        assert abs(got - api32[i]) < 1e-5, (i, got, api32[i])


def test_cli_refit(csv_files):
    tmp = csv_files["tmp"]
    model_p = tmp / "m_refit_src.txt"
    X, y = csv_files["X"], csv_files["y"]
    bst = lgb.train(
        {"objective": "binary", "num_leaves": 7, "verbosity": -1},
        lgb.Dataset(X[:1000], label=y[:1000]), num_boost_round=3,
    )
    bst.save_model(str(model_p))
    out_p = tmp / "m_refit.txt"
    env = dict(os.environ, PYTHONPATH="/root/repo",
               JAX_PLATFORMS="cpu", PALLAS_AXON_POOL_IPS="")
    r = subprocess.run(
        [sys.executable, "-m", "lightgbm_tpu", "task=refit",
         f"data={csv_files['train']}", f"input_model={model_p}",
         f"output_model={out_p}", "verbosity=-1"],
        capture_output=True, text=True, env=env, timeout=300,
    )
    assert r.returncode == 0, r.stderr[-2000:]
    assert out_p.exists()
    refitted = lgb.Booster(model_file=str(out_p))
    assert np.isfinite(refitted.predict(X[:10])).all()


def test_native_trailing_empty_fields_are_nan(tmp_path):
    """Trailing empty delimited fields must parse as NaN (missing), matching
    the numpy fallback's np.full(..., nan) init."""
    path = tmp_path / "trail.csv"
    path.write_text("1,2.5,\n0,,4.5\n1,5.5,6.5\n")
    out = parse_file_native(str(path), "csv", False, 0)
    assert out is not None
    data, label = out
    np.testing.assert_array_equal(label, [1, 0, 1])
    assert np.isnan(data[0, 1]) and np.isnan(data[1, 0])
    np.testing.assert_allclose(data[2], [5.5, 6.5])
