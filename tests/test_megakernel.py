"""The round megakernel (ops/round_pallas.py): one HBM sweep of the bin
matrix per boosting round.  Acceptance matrix (ISSUE 11):

* BITWISE equality with the three-pass fused round across float /
  int8-quantized / categorical (Mosaic interpret mode — tier-1 has no
  TPU), single-device AND sharded (where the in-dispatch collective
  merge must stay unchanged);
* the per-feature on-core split-gain reduction is bitwise-equal to the
  flat-plane selection (ops/split.py shared machinery);
* unsupported scenarios (EFB bundles, per-node rng) fall back to the
  three-pass round LOUDLY — counter + event — never silently diverge;
* an injected Pallas failure degrades to the three-pass round through
  the utils/degrade.py registry without killing training, and interpret
  mode (the correctness harness) SURFACES failures instead.
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from lightgbm_tpu.binning import DatasetBinner
from lightgbm_tpu.obs import metrics as obs
from lightgbm_tpu.ops import split as sp
from lightgbm_tpu.ops.split import SplitParams
from lightgbm_tpu.ops.treegrow_windowed import grow_tree_windowed
from lightgbm_tpu.utils import degrade, faults


@pytest.fixture(autouse=True)
def _clean(monkeypatch):
    degrade.reset()
    faults.reset()
    monkeypatch.delenv("LGBMTPU_FAULT", raising=False)
    yield
    degrade.reset()
    faults.reset()


def _grow_both(args, kw, monkeypatch):
    """Grow one tree with the three-pass round and one with the
    megakernel round (interpret mode), returning both."""
    monkeypatch.setenv("LGBMTPU_MEGAKERNEL", "0")
    t0, l0 = grow_tree_windowed(*args, **kw)
    monkeypatch.setenv("LGBMTPU_MEGAKERNEL", "interpret")
    t1, l1 = grow_tree_windowed(*args, **kw)
    return (t0, l0), (t1, l1)


def _assert_trees_bitwise(got, want, tag=""):
    (t0, l0), (t1, l1) = want, got
    assert int(t1.num_leaves) == int(t0.num_leaves), tag
    for name in t0._fields:
        np.testing.assert_array_equal(
            np.asarray(getattr(t1, name)), np.asarray(getattr(t0, name)),
            err_msg=f"{tag}: TreeArrays.{name} diverged")
    np.testing.assert_array_equal(np.asarray(l1), np.asarray(l0),
                                  err_msg=f"{tag}: leaf ids diverged")


def _inputs(n=2500, f=12, seed=3, max_bin=63):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, f)
    y = X @ rng.randn(f) + 0.3 * rng.randn(n)
    binner = DatasetBinner.fit(X, max_bin=max_bin)
    bins_t = jnp.asarray(binner.transform(X).T, jnp.int16)
    grad = jnp.asarray(0.6 * y, jnp.float32)
    hess = jnp.ones((n,), jnp.float32)
    return binner, bins_t, grad, hess


_BASE = dict(num_leaves=15, num_bins=64,
             params=SplitParams(min_data_in_leaf=5.0), leaf_tile=4,
             use_pallas=False)


def _args(binner, bins_t, grad, hess, mask=None):
    n = bins_t.shape[1]
    f = bins_t.shape[0]
    return (bins_t, grad, hess,
            jnp.ones((n,), bool) if mask is None else mask,
            jnp.ones((n,), jnp.float32), jnp.ones((f,), bool),
            jnp.asarray(binner.num_bins_per_feature),
            jnp.asarray(binner.missing_bin_per_feature))


@pytest.mark.parametrize("masked", [False, True])
def test_megakernel_bitwise_float(masked, monkeypatch):
    """Float path, with and without a bagging mask: the megakernel round
    (partition + one-sweep histogram + on-core gain reduction) grows the
    bit-identical tree."""
    binner, bins_t, grad, hess = _inputs()
    n = bins_t.shape[1]
    mask = (jnp.asarray(np.random.RandomState(1).rand(n) < 0.8)
            if masked else None)
    want, got = _grow_both(_args(binner, bins_t, grad, hess, mask), _BASE,
                           monkeypatch)
    assert int(want[0].num_leaves) > 4
    _assert_trees_bitwise(got, want, f"float masked={masked}")


def test_megakernel_bitwise_quantized(monkeypatch):
    """int8-quantized config (CPU trace: dequantized fallback histograms,
    same as the three-pass round's) — the wide-regime default."""
    binner, bins_t, grad, hess = _inputs(n=1800, seed=7)
    kw = dict(_BASE, leaf_tile=2, quantize_bins=16,
              stochastic_rounding=False, quant_renew=True)
    want, got = _grow_both(_args(binner, bins_t, grad, hess), kw,
                           monkeypatch)
    assert int(want[0].num_leaves) > 4
    _assert_trees_bitwise(got, want, "quantized")


def test_megakernel_bitwise_categorical(monkeypatch):
    """Categorical splits: the on-core reduction carries the winning
    variant out and the winner's bitset mask is replayed bitwise from the
    child histogram (split.categorical_winner_mask)."""
    rng = np.random.RandomState(5)
    n, f, n_cat = 1800, 12, 8
    X = rng.randn(n, f)
    cats = rng.randint(0, n_cat, n)
    X[:, 0] = cats
    y = (rng.randn(n_cat) * 2.0)[cats] + X[:, 1] + 0.2 * rng.randn(n)
    binner = DatasetBinner.fit(X, max_bin=63, categorical_features=[0])
    bins_t = jnp.asarray(binner.transform(X).T, jnp.int16)
    grad = jnp.asarray(0.6 * y, jnp.float32)
    hess = jnp.ones((n,), jnp.float32)
    kw = dict(_BASE, leaf_tile=2,
              categorical_mask=jnp.asarray(np.arange(f) == 0))
    want, got = _grow_both(_args(binner, bins_t, grad, hess), kw,
                           monkeypatch)
    nl = int(want[0].num_leaves)
    assert bool(np.asarray(want[0].is_cat[: nl - 1]).any()), \
        "fixture grew no categorical splits"
    _assert_trees_bitwise(got, want, "categorical")


def test_per_feature_selection_matches_flat_selection():
    """The megakernel's on-core reduction contract: per-feature argmax +
    cross-feature selection (reduce_plane_per_feature +
    select_from_feature_best) is BITWISE the flat-plane argmax
    (find_best_split), including tie-heavy planes (duplicated feature
    columns) and the categorical variants."""
    F, B = 12, 32
    params = SplitParams(min_data_in_leaf=5.0)
    for seed in range(4):
        for cat in (False, True):
            for dup in (False, True):
                r = np.random.RandomState(seed)
                hist = np.abs(r.randn(3, F, B)).astype(np.float32)
                hist[0] = r.randn(F, B)
                if dup:  # duplicated columns -> exact cross-feature ties
                    hist[:, 1] = hist[:, 0]
                    hist[:, 7] = hist[:, 0]
                nbpf = np.full(F, B, np.int32)
                mbpf = np.full(F, B - 1, np.int32)
                mbpf[::3] = -1
                cmask = (jnp.asarray(np.arange(F) % 4 == 0) if cat
                         else None)
                pg = jnp.float32(hist[0].sum())
                ph = jnp.float32(hist[1].sum())
                pc = jnp.float32(hist[2].sum())
                histj = jnp.asarray(hist)
                kw = dict(categorical_mask=cmask, depth=jnp.float32(1.0),
                          parent_output=jnp.float32(0.1))
                want = sp.find_best_split(
                    histj, pg, ph, pc, jnp.asarray(nbpf), jnp.asarray(mbpf),
                    params, **kw)
                gain, ctx = sp.gain_plane(
                    histj, pg, ph, pc, jnp.asarray(nbpf), jnp.asarray(mbpf),
                    params, **kw)
                fb = sp.reduce_plane_per_feature(gain, ctx)
                got = sp.select_from_feature_best(
                    fb, pg, ph, pc, categorical_mask=cmask, cand_hist=histj,
                    missing_bin_per_feature=jnp.asarray(mbpf), params=params,
                    num_bins=B)
                for name in want._fields:
                    np.testing.assert_array_equal(
                        np.asarray(getattr(got, name)),
                        np.asarray(getattr(want, name)),
                        err_msg=f"seed={seed} cat={cat} dup={dup}: {name}")


def test_per_feature_reduction_is_feature_block_separable():
    """The in-kernel reduction runs on feature-BLOCK slices and
    concatenates — per-feature outputs must be identical to the full-F
    reduction (the property that lets the VMEM carry stay FB-sized)."""
    F, B, FB = 12, 32, 8
    params = SplitParams(min_data_in_leaf=5.0)
    r = np.random.RandomState(2)
    hist = jnp.asarray(np.abs(r.randn(3, F, B)).astype(np.float32))
    nbpf = jnp.full((F,), B, jnp.int32)
    mbpf = jnp.full((F,), B - 1, jnp.int32)
    pg, ph, pc = (jnp.float32(float(v.sum())) for v in np.asarray(hist))
    gain, ctx = sp.gain_plane(hist, pg, ph, pc, nbpf, mbpf, params)
    whole = sp.reduce_plane_per_feature(gain, ctx)
    parts = []
    for lo in range(0, F, FB):
        hi = min(lo + FB, F)
        g_s, ctx_s = sp.gain_plane(hist[:, lo:hi], pg, ph, pc,
                                   nbpf[lo:hi], mbpf[lo:hi], params)
        parts.append(sp.reduce_plane_per_feature(g_s, ctx_s))
    for name in whole._fields:
        np.testing.assert_array_equal(
            np.concatenate([np.asarray(getattr(p, name)) for p in parts]),
            np.asarray(getattr(whole, name)), err_msg=name)


def test_megakernel_envelope_efb_falls_back_loudly(monkeypatch):
    """EFB bundles are outside the megakernel envelope: with the
    megakernel FORCED on, the round must fall back to the three-pass
    body (bitwise-identical tree), bump the fallback counter, and leave
    a megakernel_fallback event — never silently diverge."""
    import lightgbm_tpu as lgb

    rng = np.random.RandomState(6)
    n, groups = 1500, 8
    blocks = []
    for _ in range(groups):
        col = rng.randint(0, 8, n)
        oh = np.zeros((n, 8))
        oh[np.arange(n), col] = 1.0
        blocks.append(oh)
    X = np.concatenate(blocks + [rng.randn(n, 2)], axis=1)
    y = X @ rng.randn(X.shape[1]) + 0.1 * rng.randn(n)
    ds = lgb.Dataset(X, label=y)
    ds.construct()
    assert ds.efb is not None
    tabs = ds.efb_device_tables()
    f = ds.bins.shape[1]
    args = (jnp.asarray(ds.bins, jnp.int16).T,
            jnp.asarray(0.6 * y, jnp.float32), jnp.ones((n,), jnp.float32),
            jnp.ones((n,), bool), jnp.ones((n,), jnp.float32),
            jnp.ones((f,), bool), ds.num_bins_pf_device,
            ds.missing_bin_pf_device)
    kw = dict(num_leaves=15, num_bins=ds.max_num_bins,
              params=SplitParams(min_data_in_leaf=5.0), leaf_tile=4,
              use_pallas=False,
              efb_bins_t=ds.efb_bins_device_t(), efb_gather=tabs[1],
              efb_default=tabs[2])

    monkeypatch.setenv("LGBMTPU_MEGAKERNEL", "0")
    t0, l0 = grow_tree_windowed(*args, **kw)
    before = obs.counter("megakernel_envelope_fallbacks_total").value
    monkeypatch.setenv("LGBMTPU_MEGAKERNEL", "1")
    t1, l1 = grow_tree_windowed(*args, **kw)
    assert obs.counter(
        "megakernel_envelope_fallbacks_total").value == before + 1
    evs = [e for e in obs.events("megakernel_fallback")
           if e.get("reason") == "efb"]
    assert evs, "no megakernel_fallback event for the EFB exclusion"
    _assert_trees_bitwise((t1, l1), (t0, l0), "efb fallback")


def test_megakernel_envelope_node_rng_falls_back_loudly(monkeypatch):
    """Per-node feature sampling (rng-keyed scan) cannot run on-core —
    same loud fallback contract."""
    binner, bins_t, grad, hess = _inputs(n=1200, seed=11)
    kw = dict(_BASE, params=SplitParams(min_data_in_leaf=5.0,
                                        feature_fraction_bynode=0.5))
    args = _args(binner, bins_t, grad, hess) + (jax.random.PRNGKey(0),)

    monkeypatch.setenv("LGBMTPU_MEGAKERNEL", "0")
    t0, l0 = grow_tree_windowed(*args, **kw)
    before = obs.counter("megakernel_envelope_fallbacks_total").value
    monkeypatch.setenv("LGBMTPU_MEGAKERNEL", "1")
    t1, l1 = grow_tree_windowed(*args, **kw)
    assert obs.counter(
        "megakernel_envelope_fallbacks_total").value == before + 1
    assert any(e.get("reason") == "node_rng"
               for e in obs.events("megakernel_fallback"))
    _assert_trees_bitwise((t1, l1), (t0, l0), "node-rng fallback")


def test_megakernel_envelope_quantized_pallas_falls_back_loudly():
    """On the Pallas hot path, int8-quantized training is OUTSIDE the
    envelope: the three-pass round accumulates exact int8 histograms on
    the MXU while the committed megakernel folds dequantized f32 — until
    the int8 MXU accumulate lands, a quantized+Pallas config must fall
    back loudly rather than silently change numerics.  The CPU fallback
    path (no Pallas hist) stays in-envelope — that is what the bitwise
    quantized parity test above exercises."""
    from lightgbm_tpu.ops.treegrow_windowed import megakernel_mode

    before = obs.counter("megakernel_envelope_fallbacks_total").value
    mk, _ = megakernel_mode(True, quantize_bins=16, mode="1")
    assert mk is False
    assert obs.counter(
        "megakernel_envelope_fallbacks_total").value == before + 1
    assert any(e.get("reason") == "quantized_mxu"
               for e in obs.events("megakernel_fallback"))
    assert megakernel_mode(False, quantize_bins=16, mode="interpret")[0]
    assert megakernel_mode(True, quantize_bins=0, mode="1")[0]


def test_megakernel_interpret_ignores_degraded_registry():
    """The correctness harness must never silently grow three-pass trees
    because a PRIOR tree degraded ROUND: interpret mode bypasses the
    registry (the partition kernel's interpret contract); device modes
    honour it."""
    from lightgbm_tpu.ops.treegrow_windowed import megakernel_mode

    degrade.disable(degrade.ROUND, "test: prior failure")
    assert megakernel_mode(False, mode="interpret")[0] is True
    assert megakernel_mode(True, mode="1")[0] is False
    assert megakernel_mode(True, mode="auto")[0] is False


def test_megakernel_degrades_on_injected_failure(monkeypatch):
    """An injected pallas_round fault (modelling a Mosaic rejection of
    the megakernel) degrades ROUND permanently and regrows the tree on
    the three-pass round — training survives, results identical."""
    binner, bins_t, grad, hess = _inputs(n=1200, seed=12)
    args = _args(binner, bins_t, grad, hess)

    monkeypatch.setenv("LGBMTPU_MEGAKERNEL", "0")
    t0, l0 = grow_tree_windowed(*args, **_BASE)

    monkeypatch.setenv("LGBMTPU_MEGAKERNEL", "1")
    monkeypatch.setenv("LGBMTPU_FAULT", "pallas_round:0")
    t1, l1 = grow_tree_windowed(*args, **_BASE)
    _assert_trees_bitwise((t1, l1), (t0, l0), "degraded round")
    assert not degrade.available(degrade.ROUND)
    assert degrade.available(degrade.HIST)  # layered: only ROUND degraded
    # degraded process: the megakernel is skipped without needing a fault
    t2, l2 = grow_tree_windowed(*args, **_BASE)
    _assert_trees_bitwise((t2, l2), (t0, l0), "post-degrade")


def test_megakernel_interpret_mode_failures_surface(monkeypatch):
    """interpret mode is the correctness harness — injected failures must
    NOT be swallowed into a silent fallback (the partition kernel's
    contract, extended to the megakernel)."""
    binner, bins_t, grad, hess = _inputs(n=1200, seed=13)
    args = _args(binner, bins_t, grad, hess)
    monkeypatch.setenv("LGBMTPU_MEGAKERNEL", "interpret")
    monkeypatch.setenv("LGBMTPU_FAULT", "pallas_round:0")
    with pytest.raises(faults.InjectedFault):
        grow_tree_windowed(*args, **_BASE)
    assert degrade.available(degrade.ROUND)


def test_sharded_megakernel_bitwise_with_merge_unchanged(monkeypatch):
    """SPMD: the megakernel fuses each rank's partition + window
    histogram; the leaf-histogram merge stays the round's single
    in-dispatch collective (the jaxpr contract
    windowed_round_sharded_megakernel_psum pins the sequence is
    UNCHANGED), and the grown tree is bitwise the non-megakernel
    sharded tree."""
    from lightgbm_tpu.parallel.data_parallel import (
        ShardedData, grow_tree_windowed_data_parallel)
    from lightgbm_tpu.parallel.mesh import make_mesh

    rng = np.random.RandomState(9)
    n, f = 1024, 8
    X = rng.randn(n, f)
    y = X @ rng.randn(f) + 0.2 * rng.randn(n)
    binner = DatasetBinner.fit(X, max_bin=31)
    mesh = make_mesh()
    sd = ShardedData(mesh, binner.transform(X),
                     binner.num_bins_per_feature,
                     binner.missing_bin_per_feature)
    grad = sd.pad_rows((0.6 * y).astype(np.float32))
    hess = sd.pad_rows(np.ones(n, np.float32))
    sw = sd.pad_rows(np.ones(n, np.float32), fill=1.0)
    kw = dict(num_leaves=15, num_bins=32,
              params=SplitParams(min_data_in_leaf=5.0), leaf_tile=2,
              use_pallas=False)

    monkeypatch.setenv("LGBMTPU_MEGAKERNEL", "0")
    t0, l0 = grow_tree_windowed_data_parallel(
        sd, grad, hess, sd.row_valid, sw, jnp.ones((f,), bool), **kw)
    monkeypatch.setenv("LGBMTPU_MEGAKERNEL", "interpret")
    t1, l1 = grow_tree_windowed_data_parallel(
        sd, grad, hess, sd.row_valid, sw, jnp.ones((f,), bool), **kw)
    _assert_trees_bitwise((t1, l1), (t0, l0), "sharded psum")

    # the LAYERED degrade net, sharded edition: an injected megakernel
    # failure disables ROUND and regrows on the three-pass sharded round
    # (same tree) instead of killing distributed training
    monkeypatch.setenv("LGBMTPU_MEGAKERNEL", "1")
    monkeypatch.setenv("LGBMTPU_FAULT", "pallas_round:0")
    t2, l2 = grow_tree_windowed_data_parallel(
        sd, grad, hess, sd.row_valid, sw, jnp.ones((f,), bool), **kw)
    _assert_trees_bitwise((t2, l2), (t0, l0), "sharded degraded round")
    assert not degrade.available(degrade.ROUND)
