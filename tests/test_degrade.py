"""Graceful kernel degradation (utils/degrade.py): a Pallas kernel
failure is caught once, logged via utils/log.py, and the process
permanently falls back to the numerically identical XLA path — no manual
env var, no dead run.  Driven by the pallas_* fault-injection sites."""

import logging

import numpy as np
import jax.numpy as jnp
import pytest

from lightgbm_tpu.utils import degrade, faults


@pytest.fixture(autouse=True)
def _clean():
    degrade.reset()
    faults.reset()
    yield
    degrade.reset()
    faults.reset()


def test_classifier_recognizes_kernel_failures_only():
    assert degrade.is_pallas_failure(faults.InjectedFault("pallas_hist", 0))
    assert degrade.is_pallas_failure(RuntimeError("Mosaic lowering failed"))
    assert degrade.is_pallas_failure(ValueError("pallas_call: bad block"))
    assert not degrade.is_pallas_failure(ValueError("shapes do not match"))
    assert not degrade.is_pallas_failure(
        faults.InjectedFault("worker_death", 1))


def test_disable_logs_once(caplog):
    logger = logging.getLogger("lgbm_degrade_test")
    import lightgbm_tpu as lgb
    from lightgbm_tpu.utils.log import set_verbosity

    # earlier suite tests train with verbosity=-1, which silences
    # log_warning process-wide — pin it back for this assertion
    set_verbosity(1)
    lgb.register_logger(logger)
    try:
        with caplog.at_level(logging.WARNING, logger="lgbm_degrade_test"):
            degrade.disable(degrade.HIST, "test reason")
            degrade.disable(degrade.HIST, "second reason ignored")
        assert len(caplog.records) == 1
        assert "falling back to the XLA path" in caplog.records[0].message
        assert not degrade.available(degrade.HIST)
        assert degrade.disabled_reason(degrade.HIST) == "test reason"
    finally:
        from lightgbm_tpu.utils import log as _log

        _log._logger = None


def _partition_fixture(n=64, s=2, seed=0):
    rng = np.random.RandomState(seed)
    order = jnp.asarray(rng.permutation(n).astype(np.int32))
    seg_start = jnp.asarray([0, 40], jnp.int32)
    seg_len = jnp.asarray([24, 24], jnp.int32)
    seg_id = np.full(n, -1, np.int32)
    seg_id[0:24] = 0
    seg_id[40:64] = 1
    go_left = jnp.asarray(rng.rand(n) < 0.5)
    return order, jnp.asarray(seg_id), seg_start, seg_len, go_left


def test_partition_dispatcher_degrades_and_matches_xla(monkeypatch):
    """An injected Pallas failure in partition_rows falls back to the XLA
    permutation with IDENTICAL results, and records the degradation so
    later traces skip the kernel entirely."""
    from lightgbm_tpu.ops.partition import (partition_rows,
                                            stable_partition_ranges)

    order, seg_id, seg_start, seg_len, go_left = _partition_fixture()
    ref_order, ref_counts = stable_partition_ranges(
        order, seg_id, seg_start, seg_len, go_left)

    monkeypatch.setenv("LGBMTPU_FAULT", "pallas_partition:0")
    got_order, got_counts = partition_rows(
        order, seg_id, seg_start, seg_len, go_left, use_pallas=True)
    np.testing.assert_array_equal(np.asarray(got_order), np.asarray(ref_order))
    np.testing.assert_array_equal(np.asarray(got_counts),
                                  np.asarray(ref_counts))
    assert not degrade.available(degrade.PARTITION)
    # degraded process: the kernel is skipped without needing the fault
    got2, _ = partition_rows(order, seg_id, seg_start, seg_len, go_left,
                             use_pallas=True)
    np.testing.assert_array_equal(np.asarray(got2), np.asarray(ref_order))


def test_partition_interpret_mode_failures_surface(monkeypatch):
    """interpret=True is the correctness harness — injected failures must
    NOT be swallowed into a silent fallback there."""
    from lightgbm_tpu.ops.partition import partition_rows

    order, seg_id, seg_start, seg_len, go_left = _partition_fixture(seed=1)
    monkeypatch.setenv("LGBMTPU_FAULT", "pallas_partition:0")
    with pytest.raises(faults.InjectedFault):
        partition_rows(order, seg_id, seg_start, seg_len, go_left,
                       interpret=True)
    assert degrade.available(degrade.PARTITION)


def _hist_fixture(n=256, f=4, tile=2, bins=16, seed=0):
    rng = np.random.RandomState(seed)
    b = jnp.asarray(rng.randint(0, bins, (n, f)), jnp.int16)
    g = jnp.asarray(rng.randn(n), jnp.float32)
    h = jnp.asarray(rng.rand(n) + 0.5, jnp.float32)
    mask = jnp.asarray(rng.rand(n) < 0.9)
    leaf = jnp.asarray(rng.randint(0, tile, n), jnp.int32)
    return b, g, h, mask, leaf, tile, bins


def test_hist_dispatcher_degrades_and_matches_xla(monkeypatch):
    from lightgbm_tpu.ops.histogram import histogram_multi, histogram_onehot_multi

    b, g, h, mask, leaf, tile, bins = _hist_fixture()
    ref = histogram_onehot_multi(b, g, h, mask, leaf, 0, tile, bins)

    monkeypatch.setenv("LGBMTPU_FAULT", "pallas_hist:0")
    got = histogram_multi(b, g, h, mask, leaf, 0, tile, bins)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)
    assert not degrade.available(degrade.HIST)


def test_hist_dispatcher_quantized_degrades(monkeypatch):
    from lightgbm_tpu.ops.histogram import (histogram_multi_quantized,
                                            histogram_onehot_multi_quantized)

    rng = np.random.RandomState(1)
    n, f, tile, bins = 256, 3, 2, 16
    b = jnp.asarray(rng.randint(0, bins, (n, f)), jnp.int16)
    gq = jnp.asarray(rng.randint(-50, 50, n), jnp.int8)
    hq = jnp.asarray(rng.randint(0, 100, n), jnp.int8)
    mask = jnp.ones((n,), bool)
    leaf = jnp.asarray(rng.randint(0, tile, n), jnp.int32)
    ref = histogram_onehot_multi_quantized(b, gq, hq, mask, leaf, 0, tile,
                                           bins)
    monkeypatch.setenv("LGBMTPU_FAULT", "pallas_hist:0")
    got = histogram_multi_quantized(b, gq, hq, mask, leaf, 0, tile, bins)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(ref))
    assert not degrade.available(degrade.HIST)


def test_grower_level_retry_catches_execute_time_failures(monkeypatch):
    """A Pallas failure that escapes the trace-time dispatchers (compile/
    execute time) is caught by the grower wrapper: disable + regrow on
    the XLA path from the original inputs.  Since round 16 the net is
    LAYERED: with the megakernel active (the use_pallas default), the
    first failure is attributed to the ROUND kernel (retry on the
    three-pass round, Pallas hist still on); a second failure degrades
    HIST and lands on the XLA path."""
    from lightgbm_tpu.ops import treegrow_windowed as tw

    calls = []
    real = tw._grow_windowed_impl

    def flaky(*args, **kwargs):
        calls.append(kwargs.get("use_pallas"))
        if kwargs.get("use_pallas"):
            raise RuntimeError("Mosaic kernel compile failed (injected)")
        return real(*args, **kwargs)

    monkeypatch.setattr(tw, "_grow_windowed_impl", flaky)

    from tests.test_nonfinite import _windowed_inputs

    bins_t, grad, hess, kw, static = _windowed_inputs(seed=9)
    static = dict(static, use_pallas=True)
    tree, leaf = tw.grow_tree_windowed(bins_t, grad, hess, **kw, **static)
    assert calls == [True, True, False]
    assert int(tree.num_leaves) > 1
    assert not degrade.available(degrade.ROUND)
    assert not degrade.available(degrade.HIST)

    # a second tree folds the registry into the static before dispatch:
    # no pallas attempt, no exception
    calls.clear()
    tree2, _ = tw.grow_tree_windowed(bins_t, grad, hess, **kw, **static)
    assert calls == [False]


def test_grower_level_retry_does_not_swallow_real_errors(monkeypatch):
    from lightgbm_tpu.ops import treegrow_windowed as tw

    def broken(*args, **kwargs):
        raise ValueError("genuine bug, not a kernel failure")

    monkeypatch.setattr(tw, "_grow_windowed_impl", broken)
    from tests.test_nonfinite import _windowed_inputs

    bins_t, grad, hess, kw, static = _windowed_inputs(seed=10)
    with pytest.raises(ValueError, match="genuine bug"):
        tw.grow_tree_windowed(bins_t, grad, hess, **kw,
                              **dict(static, use_pallas=True))
    assert degrade.available(degrade.HIST)
