"""Distributed launcher (the dask.py-analogue orchestration layer): spawn
per-rank processes, feed per-rank row shards (pre_partition), train
tree_learner=data, and verify every rank holds the identical model that
matches single-process serial training."""

import os

import numpy as np
import pytest

pytestmark = pytest.mark.slow


def test_launcher_end_to_end_loopback():
    from lightgbm_tpu.parallel.launcher import train_distributed
    import lightgbm_tpu as lgb

    rng = np.random.RandomState(11)
    n = 4000  # divides evenly over 2 machines x 1 device
    X = rng.randn(n, 6)
    y = (X @ rng.randn(6) + 0.3 * rng.randn(n) > 0).astype(np.float64)
    params = {"objective": "binary", "num_leaves": 8, "verbosity": -1,
              "min_data_in_leaf": 5, "bin_construct_sample_cnt": n}

    bst, model_files = train_distributed(
        params, X, y, num_boost_round=3, num_machines=2,
        env_extra={
            "JAX_PLATFORMS": "cpu",
            "XLA_FLAGS": "--xla_force_host_platform_device_count=1",
            "PALLAS_AXON_POOL_IPS": "",
        },
    )
    # every rank converged to the identical model
    texts = [open(f).read() for f in model_files]
    assert texts[0] == texts[1]

    # structural equality vs serial single-process training (same tolerance
    # policy as tests/test_multihost.py)
    serial = lgb.train(dict(params, tree_learner="serial"),
                       lgb.Dataset(X, label=y), 3)
    s_d, s_s = texts[0], serial.model_to_string()

    def parts(s, key):
        return [ln for ln in s.splitlines() if ln.startswith(key + "=")]

    for key in ("split_feature", "threshold", "num_leaves"):
        assert parts(s_d, key) == parts(s_s, key), key
    lv = lambda s: [float(v) for ln in parts(s, "leaf_value")
                    for v in ln.split("=")[1].split()]
    np.testing.assert_allclose(lv(s_d), lv(s_s), rtol=2e-3, atol=2e-3)

    # and the returned booster predicts
    p = bst.predict(X[:100])
    assert np.isfinite(p).all()


_CPU_ENV = {
    "JAX_PLATFORMS": "cpu",
    "XLA_FLAGS": "--xla_force_host_platform_device_count=1",
    "PALLAS_AXON_POOL_IPS": "",
}


def _patched_env(monkeypatch):
    """Route the estimators' worker processes to CPU (the launcher workers
    inherit os.environ)."""
    for k, v in _CPU_ENV.items():
        monkeypatch.setenv(k, v)


def test_distributed_regressor_estimator(monkeypatch):
    """VERDICT r3 item 8: a user-facing fit-an-estimator-across-processes
    API (reference: dask.py DaskLGBMRegressor -> _train)."""
    _patched_env(monkeypatch)
    import lightgbm_tpu as lgb

    rng = np.random.RandomState(12)
    n = 3000
    X = rng.randn(n, 5)
    y = X @ rng.randn(5) + 0.2 * rng.randn(n)
    est = lgb.DaskLGBMRegressor(num_machines=2, n_estimators=4, num_leaves=8,
                                min_child_samples=5,
                                subsample_for_bin=n)
    est.fit(X, y)
    p = est.predict(X)
    assert np.isfinite(p).all()
    # distributed model ~ local estimator (same data, same params)
    local = lgb.LGBMRegressor(n_estimators=4, num_leaves=8,
                              min_child_samples=5, subsample_for_bin=n)
    local.fit(X, y)
    np.testing.assert_allclose(p, local.predict(X), rtol=5e-2, atol=5e-2)


def test_distributed_classifier_estimator(monkeypatch):
    _patched_env(monkeypatch)
    import lightgbm_tpu as lgb

    rng = np.random.RandomState(13)
    n = 3000
    X = rng.randn(n, 5)
    y_raw = (X @ rng.randn(5) > 0)
    y = np.where(y_raw, "pos", "neg")  # string labels exercise the encoder
    est = lgb.DaskLGBMClassifier(num_machines=2, n_estimators=4, num_leaves=8,
                                 min_child_samples=5, subsample_for_bin=n)
    est.fit(X, y)
    assert set(est.classes_) == {"neg", "pos"}
    proba = est.predict_proba(X)
    assert proba.shape == (n, 2)
    pred = est.predict(X)
    assert (pred == y).mean() > 0.8


def test_distributed_ranker_estimator(monkeypatch):
    _patched_env(monkeypatch)
    import lightgbm_tpu as lgb

    rng = np.random.RandomState(14)
    # UNEVEN query sizes: shards can't split evenly, so the query-boundary
    # snap AND the trailing weight-0 pad query path both run
    group = rng.randint(30, 70, 47)
    n = int(group.sum())
    X = rng.randn(n, 6)
    rel = X[:, 0] * 0.8 + 0.3 * rng.randn(n)
    y = np.clip(np.floor(rel) + 2, 0, 4).astype(np.float64)
    est = lgb.DaskLGBMRanker(num_machines=2, n_estimators=4, num_leaves=8,
                             min_child_samples=5, subsample_for_bin=n)
    est.fit(X, y, group=group)
    p = est.predict(X)
    assert np.isfinite(p).all()
    # scores must rank the relevant docs above within queries on average
    bounds = np.concatenate([[0], np.cumsum(group)])
    gained = np.array([y[lo:hi][p[lo:hi].argmax()]
                       for lo, hi in zip(bounds[:-1], bounds[1:])])
    assert gained.mean() > y.mean()


def test_distributed_eval_set_early_stopping(monkeypatch):
    """VERDICT r4 item 8: eval_set on the distributed estimators — each
    rank evaluates its shard of the valid set through the synced metric
    path, and early stopping fires identically on every rank (reference:
    dask.py _train(eval_set...))."""
    _patched_env(monkeypatch)
    import lightgbm_tpu as lgb

    rng = np.random.RandomState(15)
    n = 3000
    X = rng.randn(n, 5)
    y = X @ rng.randn(5) + 0.2 * rng.randn(n)
    Xv, yv = X[:800], y[:800] + 0.01 * rng.randn(800)
    est = lgb.DaskLGBMRegressor(num_machines=2, n_estimators=40,
                                num_leaves=4, min_child_samples=5,
                                learning_rate=0.5, subsample_for_bin=n)
    est.fit(X, y, eval_set=[(Xv, yv)], eval_names=["val"],
            eval_metric="l2", early_stopping_rounds=3)
    # the evals curve came back from rank 0 and early stopping recorded a
    # best iteration within the training run
    assert "val" in est.evals_result_
    curve = est.evals_result_["val"]["l2"]
    assert len(curve) >= 4
    assert 1 <= est.best_iteration_ <= 40
    assert np.isfinite(est.predict(X[:50])).all()
    # a fast-overfitting config must actually STOP early
    est2 = lgb.DaskLGBMRegressor(num_machines=2, n_estimators=200,
                                 num_leaves=31, min_child_samples=2,
                                 learning_rate=0.9, subsample_for_bin=n)
    rng2 = np.random.RandomState(16)
    yv_noise = rng2.randn(800)  # unlearnable valid target
    est2.fit(X, y, eval_set=[(X[:800], yv_noise)],
             early_stopping_rounds=2)
    assert len(est2.evals_result_["valid_0"]["l2"]) < 200


def test_worker_death_fails_fast_with_watchdog():
    """A dead worker must fail the launch in seconds via the poll-based
    watchdog — not sit out the full timeout on the surviving rank's
    blocked collectives — with the dead rank's log tail in the error.
    (Rank attribution of the FIRST observed death is racy once the
    distributed runtime propagates the failure to peers, so the pin is
    on latency + error shape, not the rank id; injection specificity is
    unit-tested in tests/test_faults.py.)"""
    import time

    from lightgbm_tpu.parallel.launcher import WorkerFailure, train_distributed

    rng = np.random.RandomState(21)
    n = 2000
    X = rng.randn(n, 5)
    y = (X @ rng.randn(5) > 0).astype(np.float64)
    params = {"objective": "binary", "num_leaves": 8, "verbosity": -1,
              "min_data_in_leaf": 5, "bin_construct_sample_cnt": n}
    t0 = time.monotonic()
    with pytest.raises(WorkerFailure) as ei:
        train_distributed(
            params, X, y, num_boost_round=4, num_machines=2,
            timeout_s=300,
            env_extra={
                **_CPU_ENV,
                "LGBMTPU_FAULT": "worker_death:2",
                "LGBMTPU_FAULT_RANK": "1",
            },
        )
    elapsed = time.monotonic() - t0
    assert ei.value.rank is not None and not ei.value.timed_out
    assert "died with exit code" in str(ei.value)
    assert "Tail of rank" in str(ei.value)
    # well under the 300 s timeout: the watchdog caught the death by poll
    assert elapsed < 120, f"watchdog took {elapsed:.0f}s"


def test_worker_death_recovers_via_restart_and_matches_serial():
    """The acceptance scenario: a worker killed mid-run, the launcher's
    bounded restart relaunches the fleet (the fault is once-only across
    launches via the marker dir), and the recovered run reproduces the
    un-faulted distributed model exactly."""
    from lightgbm_tpu.parallel.launcher import WorkerFailure, train_distributed

    rng = np.random.RandomState(22)
    n = 2000
    X = rng.randn(n, 5)
    y = (X @ rng.randn(5) > 0).astype(np.float64)
    params = {"objective": "binary", "num_leaves": 8, "verbosity": -1,
              "min_data_in_leaf": 5, "bin_construct_sample_cnt": n}

    # the un-faulted reference doubles as the environment probe: where
    # the container JAX cannot run multiprocess CPU collectives (the
    # pre-existing limitation of the loopback e2e suite), skip — this
    # scenario needs REAL distributed training to recover
    try:
        ref, _ = train_distributed(
            params, X, y, num_boost_round=3, num_machines=2,
            timeout_s=300, env_extra=dict(_CPU_ENV),
        )
    except WorkerFailure as e:
        if "Multiprocess computations aren't implemented" in str(e):
            pytest.skip("container JAX lacks multiprocess CPU collectives")
        raise

    bst, files = train_distributed(
        params, X, y, num_boost_round=3, num_machines=2,
        max_restarts=1, restart_backoff_s=0.1, timeout_s=300,
        env_extra={
            **_CPU_ENV,
            "LGBMTPU_FAULT": "worker_death:2",
            "LGBMTPU_FAULT_RANK": "0",
        },
    )
    assert bst.model_to_string() == ref.model_to_string()
