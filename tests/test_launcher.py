"""Distributed launcher (the dask.py-analogue orchestration layer): spawn
per-rank processes, feed per-rank row shards (pre_partition), train
tree_learner=data, and verify every rank holds the identical model that
matches single-process serial training."""

import os

import numpy as np
import pytest

pytestmark = pytest.mark.slow


def test_launcher_end_to_end_loopback():
    from lightgbm_tpu.parallel.launcher import train_distributed
    import lightgbm_tpu as lgb

    rng = np.random.RandomState(11)
    n = 4000  # divides evenly over 2 machines x 1 device
    X = rng.randn(n, 6)
    y = (X @ rng.randn(6) + 0.3 * rng.randn(n) > 0).astype(np.float64)
    params = {"objective": "binary", "num_leaves": 8, "verbosity": -1,
              "min_data_in_leaf": 5, "bin_construct_sample_cnt": n}

    bst, model_files = train_distributed(
        params, X, y, num_boost_round=3, num_machines=2,
        env_extra={
            "JAX_PLATFORMS": "cpu",
            "XLA_FLAGS": "--xla_force_host_platform_device_count=1",
            "PALLAS_AXON_POOL_IPS": "",
        },
    )
    # every rank converged to the identical model
    texts = [open(f).read() for f in model_files]
    assert texts[0] == texts[1]

    # structural equality vs serial single-process training (same tolerance
    # policy as tests/test_multihost.py)
    serial = lgb.train(dict(params, tree_learner="serial"),
                       lgb.Dataset(X, label=y), 3)
    s_d, s_s = texts[0], serial.model_to_string()

    def parts(s, key):
        return [ln for ln in s.splitlines() if ln.startswith(key + "=")]

    for key in ("split_feature", "threshold", "num_leaves"):
        assert parts(s_d, key) == parts(s_s, key), key
    lv = lambda s: [float(v) for ln in parts(s, "leaf_value")
                    for v in ln.split("=")[1].split()]
    np.testing.assert_allclose(lv(s_d), lv(s_s), rtol=2e-3, atol=2e-3)

    # and the returned booster predicts
    p = bst.predict(X[:100])
    assert np.isfinite(p).all()
