"""Retrace regression tests (runtime half of the jaxlint pass): the
compile-counter in utils/sanitizer.py pins "N boosting rounds at a fixed
(shape, dtype) config compile exactly once" — the per-round recompile class
docs/NEXT.md suspects in the windowed admit phase becomes an executable
assertion instead of benchmark archaeology."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.binning import DatasetBinner
from lightgbm_tpu.ops.split import SplitParams
from lightgbm_tpu.ops.treegrow_fast import grow_tree_fast
from lightgbm_tpu.utils.sanitizer import (CompileCounter, RetraceError,
                                          expect_compiles)


def _grower_inputs(n=800, f=8, seed=0):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, f)
    y = X @ rng.randn(f) + 0.2 * rng.randn(n)
    binner = DatasetBinner.fit(X, max_bin=31)
    bins = jnp.asarray(binner.transform(X), jnp.int16)
    kw = dict(
        row_mask=jnp.ones((n,), bool),
        sample_weight=jnp.ones((n,), jnp.float32),
        feature_mask=jnp.ones((f,), bool),
        num_bins_per_feature=jnp.asarray(binner.num_bins_per_feature),
        missing_bin_per_feature=jnp.asarray(binner.missing_bin_per_feature),
    )
    grads = [jnp.asarray(2.0 * (0.3 * y) + 0.1 * k, jnp.float32)
             for k in range(4)]
    hess = jnp.ones((n,), jnp.float32)
    static = dict(num_leaves=15, num_bins=32, params=SplitParams(
        min_data_in_leaf=5.0), leaf_tile=4, use_pallas=False)
    return bins, grads, hess, kw, static


def test_fast_grower_compiles_once_across_rounds():
    """Boosting calls the fast grower once per tree with identical shapes
    and statics; after the warm-up call, further rounds must be pure cache
    hits — zero traces, zero backend compiles."""
    bins, grads, hess, kw, static = _grower_inputs()
    # warm-up: the one compile this (shape, dtype, static) config is allowed
    tree, leaf = grow_tree_fast(bins, grads[0], hess, **kw, **static)
    jax.block_until_ready(leaf)

    with CompileCounter() as c:
        for g in grads[1:]:
            tree, leaf = grow_tree_fast(bins, g, hess, **kw, **static)
        jax.block_until_ready(leaf)
    c.assert_no_recompile("3 boosting rounds at fixed shape")


def test_counter_detects_artificial_retrace():
    """Introduce the retrace class the gate protects against — a static
    argument that varies across rounds — and demonstrate the counter
    catches it (the regression test above would fail exactly like this)."""
    bins, grads, hess, kw, static = _grower_inputs()
    tree, leaf = grow_tree_fast(bins, grads[0], hess, **kw, **static)
    jax.block_until_ready(leaf)

    with CompileCounter() as c:
        # same data, same shapes — but leaf_tile (a static) changes, which
        # is precisely what a per-round varying static does to the cache
        retraced = dict(static, leaf_tile=8)
        tree, leaf = grow_tree_fast(bins, grads[1], hess, **kw, **retraced)
        jax.block_until_ready(leaf)
    assert c.traces >= 1, "artificial retrace went unnoticed by the counter"

    with pytest.raises(RetraceError):
        c.assert_no_recompile("artificial retrace")


def test_expect_compiles_contract():
    @jax.jit
    def fn(x):
        return x * 2

    x = jnp.arange(8.0)
    with expect_compiles(1, "cold jit"):
        jax.block_until_ready(fn(x))
    with expect_compiles(0, "warm jit"):
        jax.block_until_ready(fn(x))
    with pytest.raises(RetraceError):
        with expect_compiles(3, "wrong expectation"):
            jax.block_until_ready(fn(x))


def test_booster_steady_state_does_not_retrace():
    """Engine-level: after two warm iterations (round 1 compiles the fused
    step; round 2 covers anything keyed off iteration parity), further
    Booster.update() rounds must not trace or compile anything new."""
    rng = np.random.RandomState(3)
    X = rng.randn(400, 5)
    y = (X[:, 0] + 0.5 * X[:, 1] > 0).astype(float)
    d = lgb.Dataset(X, label=y)
    bst = lgb.Booster(params={"objective": "binary", "num_leaves": 7,
                              "verbosity": -1}, train_set=d)
    for _ in range(2):
        bst.update()
    np.asarray(bst._gbdt._score)  # drain pending device work

    with CompileCounter() as c:
        for _ in range(3):
            bst.update()
        np.asarray(bst._gbdt._score)
    c.assert_no_recompile("Booster.update steady state")


def _windowed_inputs(n=900, f=8, seed=5):
    from lightgbm_tpu.binning import DatasetBinner
    from lightgbm_tpu.ops.split import SplitParams

    rng = np.random.RandomState(seed)
    X = rng.randn(n, f)
    y = X @ rng.randn(f) + 0.2 * rng.randn(n)
    binner = DatasetBinner.fit(X, max_bin=31)
    bins_t = jnp.asarray(binner.transform(X).T, jnp.int16)
    grads = [jnp.asarray(0.6 * y + 0.05 * k, jnp.float32) for k in range(3)]
    kw = dict(
        row_mask=jnp.ones((n,), bool),
        sample_weight=jnp.ones((n,), jnp.float32),
        feature_mask=jnp.ones((f,), bool),
        num_bins_pf=jnp.asarray(binner.num_bins_per_feature),
        missing_bin_pf=jnp.asarray(binner.missing_bin_per_feature),
    )
    static = dict(num_leaves=15, num_bins=32, params=SplitParams(
        min_data_in_leaf=5.0), leaf_tile=4, use_pallas=False)
    return bins_t, grads, jnp.ones((n,), jnp.float32), kw, static


def test_windowed_steady_state_one_dispatch_zero_syncs_no_retrace():
    """The round-7 fused-round contract (ISSUE acceptance): after warmup,
    windowed rounds at fixed shape trace ZERO times and cost exactly ONE
    device dispatch and ZERO blocking host pulls per round — pinned by
    the DispatchCounter, not inferred from benchmarks."""
    from lightgbm_tpu.ops.treegrow_windowed import grow_tree_windowed
    from lightgbm_tpu.utils.sanitizer import DispatchCounter

    bins_t, grads, hess, kw, static = _windowed_inputs()
    # warmup: compiles _w_init, the fused round at this shape's single
    # window-ladder rung, and _w_finalize
    tree, leaf = grow_tree_windowed(bins_t, grads[0], hess, **kw, **static)
    jax.block_until_ready(leaf)

    stats = {}
    with DispatchCounter() as d:
        tree, leaf = grow_tree_windowed(bins_t, grads[1], hess, **kw,
                                        **static, stats=stats)
        jax.block_until_ready(leaf)
    # steady state: 1 dispatch per round, 0 blocking syncs, 0 mispredicted
    # windows, and the whole tree was warm-cache (zero traces/compiles)
    assert stats["rounds"] >= 3, stats  # a 15-leaf tree takes several rounds
    d.assert_round_budget(stats["rounds"], what="windowed steady state")
    assert stats["dispatches"] == stats["rounds"], stats
    assert stats["host_syncs"] == 0, stats
    assert stats["retries"] == 0, stats
    # info reads resolve one round behind and never block the device queue
    assert stats["async_resolves"] <= stats["rounds"], stats
    d.assert_no_recompile("3+ windowed rounds at fixed shape")


def test_windowed_budget_gate_enforces(monkeypatch):
    """LGBMTPU_DISPATCH_BUDGET=1 arms the in-driver gate; a blocking pull
    smuggled into the loop breaks the budget and raises."""
    from lightgbm_tpu.ops.treegrow_windowed import grow_tree_windowed
    from lightgbm_tpu.utils import sanitizer as san

    bins_t, grads, hess, kw, static = _windowed_inputs(seed=6)
    monkeypatch.setenv("LGBMTPU_DISPATCH_BUDGET", "1")
    # clean run passes the gate
    tree, leaf = grow_tree_windowed(bins_t, grads[0], hess, **kw, **static)
    assert int(tree.num_leaves) > 1

    # a sync_pull inside the loop (e.g. a re-introduced per-round host
    # read) must trip the gate
    orig = san.async_pull_result

    def leaky(x):
        san.sync_pull(x)  # the regression class: a blocking pull per round
        return orig(x)

    monkeypatch.setattr(san, "async_pull_result", leaky)
    with pytest.raises(san.BudgetError):
        grow_tree_windowed(bins_t, grads[1], hess, **kw, **static)


def test_windowed_megakernel_one_dispatch_zero_syncs_no_retrace(monkeypatch):
    """ISSUE 11 acceptance: the MEGAKERNEL round (ops/round_pallas.py,
    interpret mode off-chip) holds the same steady-state budget as the
    three-pass round — 1 dispatch, 0 blocking syncs, 0 retraces per
    round, telemetry + span tracing default-ON.  The kernel rides INSIDE
    the donated round dispatch; window sizes are data-dependent loop
    bounds in-kernel, so the W ladder cannot force retraces either."""
    from lightgbm_tpu.obs import metrics as obs_metrics
    from lightgbm_tpu.ops.treegrow_windowed import grow_tree_windowed
    from lightgbm_tpu.utils.sanitizer import DispatchCounter

    assert obs_metrics.enabled()
    monkeypatch.setenv("LGBMTPU_MEGAKERNEL", "interpret")
    bins_t, grads, hess, kw, static = _windowed_inputs(seed=8)
    tree, leaf = grow_tree_windowed(bins_t, grads[0], hess, **kw, **static)
    jax.block_until_ready(leaf)
    assert int(tree.num_leaves) > 1

    stats = {}
    with DispatchCounter() as d:
        tree, leaf = grow_tree_windowed(bins_t, grads[1], hess, **kw,
                                        **static, stats=stats)
        jax.block_until_ready(leaf)
    assert stats["rounds"] >= 3, stats
    d.assert_round_budget(stats["rounds"], what="megakernel windowed rounds")
    assert stats["dispatches"] == stats["rounds"], stats
    assert stats["host_syncs"] == 0, stats
    assert stats["retries"] == 0, stats
    d.assert_no_recompile("3+ megakernel windowed rounds at fixed shape")


def test_sharded_windowed_one_dispatch_zero_syncs_per_rank_telemetry_on():
    """ISSUE 9 acceptance: the SHARDED fused windowed round (8-device
    loopback mesh, in-dispatch psum merge) keeps the 1-dispatch/0-sync/
    0-retrace steady-state budget PER RANK — single-controller, so the
    host's one dispatch IS every rank's dispatch — with telemetry and
    span tracing default-ON, pinned by the same DispatchCounter the
    single-device round uses."""
    from lightgbm_tpu.obs import metrics as obs_metrics
    from lightgbm_tpu.obs import trace as obs_trace
    from lightgbm_tpu.parallel.data_parallel import (
        ShardedData, grow_tree_windowed_data_parallel)
    from lightgbm_tpu.parallel.mesh import make_mesh
    from lightgbm_tpu.utils.sanitizer import DispatchCounter

    assert obs_metrics.enabled()  # telemetry default-on: the pin's point
    rng = np.random.RandomState(9)
    n, f = 1024, 8
    X = rng.randn(n, f)
    y = X @ rng.randn(f) + 0.2 * rng.randn(n)
    from lightgbm_tpu.binning import DatasetBinner

    binner = DatasetBinner.fit(X, max_bin=31)
    mesh = make_mesh()
    sd = ShardedData(mesh, binner.transform(X),
                     binner.num_bins_per_feature,
                     binner.missing_bin_per_feature)
    grads = [sd.pad_rows((0.6 * y + 0.05 * k).astype(np.float32))
             for k in range(2)]
    hess = sd.pad_rows(np.ones(n, np.float32))
    sw = sd.pad_rows(np.ones(n, np.float32), fill=1.0)
    kw = dict(num_leaves=15, num_bins=32,
              params=SplitParams(min_data_in_leaf=5.0), leaf_tile=4,
              use_pallas=False)
    # warmup: compiles sharded init, the fused round at this shard size's
    # ladder rung(s), and finalize
    tree, leaf = grow_tree_windowed_data_parallel(
        sd, grads[0], hess, sd.row_valid, sw, jnp.ones((f,), bool), **kw)
    jax.block_until_ready(leaf)
    assert int(tree.num_leaves) > 1

    spans_before = len(obs_trace.spans("windowed_round"))
    stats = {}
    with DispatchCounter() as d:
        tree, leaf = grow_tree_windowed_data_parallel(
            sd, grads[1], hess, sd.row_valid, sw, jnp.ones((f,), bool),
            stats=stats, **kw)
        jax.block_until_ready(leaf)
    assert stats["rounds"] >= 3, stats
    d.assert_round_budget(stats["rounds"], what="sharded windowed rounds")
    assert stats["host_syncs"] == 0 and stats["retries"] == 0, stats
    assert stats["async_resolves"] <= stats["rounds"], stats
    d.assert_no_recompile("sharded windowed steady state")
    # the obs/span hooks rode the SAME accounted resolves: every round of
    # the second tree left a windowed_round span, none added a sync
    assert (len(obs_trace.spans("windowed_round")) - spans_before
            == stats["rounds"])


def test_fleet_steady_state_one_dispatch_zero_syncs_no_retrace():
    """ISSUE 17 acceptance: the vmapped fleet round holds the solo
    steady-state budget at ANY B — exactly ONE donated dispatch and ZERO
    blocking host pulls per ladder round, ZERO retries, ZERO compiles
    past warmup — with telemetry + span tracing ON.  Read from the
    fleet_round event ledger, whose dispatches/host_syncs fields are the
    driver's own DispatchCounter totals (ops/treegrow_windowed.py
    _run_fused_rounds), so this is the counter pin, not an inference."""
    from lightgbm_tpu.obs import metrics as _obs

    rng = np.random.RandomState(17)
    n, f, R = 300, 5, 5
    X = rng.rand(n, f)
    params = {"objective": "binary", "num_leaves": 7, "verbosity": -1,
              "min_data_in_leaf": 5, "seed": 3}
    for B in (2, 16):
        labels = (rng.rand(B, n) > 0.5).astype(np.float64)
        ds = lgb.Dataset(X, label=labels[0], params={"verbosity": -1})
        ev0 = len(_obs.events("fleet_round"))
        fb = lgb.train_fleet(dict(params), ds, labels, num_boost_round=R)
        events = _obs.events("fleet_round")[ev0:]
        assert len(events) == R, "one fleet_round event per iteration"
        assert all(e["models"] == B for e in events)
        # warmup may compile (_fleet_init / the round at this rung /
        # _fleet_finalize + the per-fleet prep/update jits); iterations
        # past it must be fully warm
        warm = [e for e in events if e["iteration"] > 2]
        assert len(warm) == R - 2
        for e in warm:
            assert e["dispatches"] == e["rounds"], e
            assert e["host_syncs"] == 0, e
            assert e["retries"] == 0, e
            assert e["compiles"] == 0, e
        assert int(fb.booster(B - 1).num_trees()) == R
