"""Serving-runtime pins (round 18, ISSUE 13 — lightgbm_tpu/serve).

The continuous micro-batching contract: coalesced responses are BITWISE
equal to individual ``Booster.predict`` calls (single, multiclass,
converted), one coalesced batch costs ONE dispatch + ONE accounted sync
with telemetry, span tracing and the HTTP server ON, overload sheds with
a typed ``Overloaded`` error (never a hang), hot-swapping a model never
cools the cache, tenants are quota-bounded and label-attributed — and
the serve module owns NO jitted code, so the serving loop can only
dispatch the already-audited warm-predict executables.
"""

import ast
import json
import threading
import time
import urllib.request
from pathlib import Path

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.obs import metrics as obs
from lightgbm_tpu.serve import MAX_BATCH_ROWS, Overloaded, ServingRuntime
from lightgbm_tpu.utils.sanitizer import DispatchCounter


@pytest.fixture(autouse=True)
def _fresh_registry():
    from lightgbm_tpu.obs import server as _srv
    from lightgbm_tpu.obs import trace as _trc

    obs.reset()
    _trc.reset_trace()
    yield
    _srv.stop_server()
    obs.reset()
    _trc.reset_trace()


def _binary_booster(n=400, f=6, rounds=4, seed=0, **extra):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, f)
    y = (X[:, 0] + 0.5 * X[:, 1] > 0).astype(float)
    params = {"objective": "binary", "num_leaves": 7, "verbosity": -1}
    params.update(extra)
    bst = lgb.Booster(params=params, train_set=lgb.Dataset(X, label=y))
    for _ in range(rounds):
        bst.update()
    return bst, X


def _multiclass_booster(n=300, f=5, k=3, rounds=3, seed=1):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, f)
    y = rng.randint(0, k, n).astype(float)
    bst = lgb.Booster(params={"objective": "multiclass", "num_class": k,
                              "num_leaves": 7, "verbosity": -1},
                      train_set=lgb.Dataset(X, label=y))
    for _ in range(rounds):
        bst.update()
    return bst, X


def _queue_then_start(rt, parts, **kw):
    """Deterministic coalescing harness: enqueue every request on the
    UNSTARTED runtime, then start — the coalescer finds them all queued
    and packs maximally, no wall-clock races."""
    handles = [rt.submit(p, **kw) for p in parts]
    rt.start()
    return [rt.result(h, timeout=60) for h in handles]


# ---------------------------------------------------------------------------
# bitwise parity: coalesced == individual (the acceptance headline)
# ---------------------------------------------------------------------------

def test_coalesced_bitwise_parity_single_and_converted():
    bst, X = _binary_booster()
    parts = [X[0:10], X[10:17], X[17:40], X[40:41]]
    want_raw = [bst.predict(p, raw_score=True) for p in parts]
    want_cvt = [bst.predict(p) for p in parts]

    rt = ServingRuntime(bst, max_wait_ms=200, start=False,
                        shed_unhealthy=False)
    got_raw = _queue_then_start(rt, parts, raw_score=True)
    got_cvt = [rt.result(h, timeout=60)
               for h in [rt.submit(p) for p in parts]]
    rt.stop()
    for w, g in zip(want_raw, got_raw):
        assert np.array_equal(w, g), "coalesced raw diverged"
    for w, g in zip(want_cvt, got_cvt):
        assert np.array_equal(w, g), "coalesced converted diverged"
    # the raw group really coalesced: 4 requests, 1 batch
    assert obs.counter("serve_batches_total").value >= 1
    assert obs.counter("serve_requests_total").value == 8


def test_coalesced_bitwise_parity_multiclass():
    bst, X = _multiclass_booster()
    parts = [X[0:9], X[9:30], X[30:32]]
    want_raw = [bst.predict(p, raw_score=True) for p in parts]
    want_cvt = [bst.predict(p) for p in parts]
    rt = ServingRuntime(bst, max_wait_ms=200, start=False,
                        shed_unhealthy=False)
    got_raw = _queue_then_start(rt, parts, raw_score=True)
    got_cvt = [rt.result(h, timeout=60)
               for h in [rt.submit(p) for p in parts]]
    rt.stop()
    for w, g in zip(want_raw + want_cvt, got_raw + got_cvt):
        assert np.array_equal(w, g), "coalesced multiclass diverged"


def test_concurrent_callers_parity():
    """C concurrent blocking callers through a LIVE runtime: every
    response equals its individual predict, and the queue drains."""
    bst, X = _binary_booster()
    slices = [X[i * 16:(i + 1) * 16] for i in range(8)]
    want = [bst.predict(s, raw_score=True) for s in slices]
    errs = []

    with ServingRuntime(bst, max_wait_ms=20,
                        shed_unhealthy=False) as rt:
        def call(i):
            try:
                got = rt.predict(slices[i], raw_score=True, timeout=60)
                assert np.array_equal(got, want[i]), f"caller {i} diverged"
            except BaseException as e:  # noqa: BLE001
                errs.append(e)

        threads = [threading.Thread(target=call, args=(i,))
                   for i in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60)
        assert rt.stats()["queue_depth"] == 0
    assert not errs, errs


# ---------------------------------------------------------------------------
# the budget: 1 dispatch + 1 accounted sync per coalesced batch, with
# telemetry + span tracing + the HTTP server ON
# ---------------------------------------------------------------------------

def test_coalesced_batch_budget_with_server_and_tracing_on():
    from lightgbm_tpu.obs import server as _srv
    from lightgbm_tpu.obs import trace as _trc

    srv = _srv.start_server(0)
    bst, X = _binary_booster()
    parts = [X[0:8], X[8:16], X[16:24], X[24:32]]  # 32 rows: exact rung

    def run_once():
        rt = ServingRuntime(bst, max_wait_ms=200, start=False,
                            shed_unhealthy=False)
        out = _queue_then_start(rt, parts, raw_score=True)
        rt.stop()
        return out

    batches0 = obs.counter("serve_batches_total").value
    run_once()  # warm: compiles the 32-row coalesced bucket once
    assert obs.counter("serve_batches_total").value == batches0 + 1

    with DispatchCounter() as d:
        got = run_once()
    assert obs.counter("serve_batches_total").value == batches0 + 2
    assert d.dispatches == 1, d.dispatches
    assert d.host_syncs == 1, d.host_syncs
    d.assert_no_recompile("warm coalesced batch (telemetry+tracing+server)")
    for w, g in zip([bst.predict(p, raw_score=True) for p in parts], got):
        assert np.array_equal(w, g)

    # the serving loop left its telemetry through the LIVE endpoint
    prom = urllib.request.urlopen(srv.url("/metrics"),
                                  timeout=10).read().decode()
    assert "lgbmtpu_serve_batches_total" in prom
    assert "lgbmtpu_serve_queue_depth" in prom
    assert "lgbmtpu_serve_batch_occupancy" in prom
    assert 'lgbmtpu_serve_request_latency_ms{quantile=' in prom.replace(
        '{tenant="default",quantile=', '{quantile=') or \
        'lgbmtpu_serve_request_latency_ms' in prom
    assert _trc.spans("serve.batch"), "no serve.batch spans"
    assert _trc.spans("predict.coalesced"), "no coalesced predict spans"
    occ = obs.histogram("serve_batch_occupancy")
    assert occ.count >= 2 and occ.max <= 1.0
    # round 24: the latency series carries an OpenMetrics exemplar — a
    # witness request's trace_id rides the _count line, and the phase
    # reservoirs were fed at the already-accounted sync points
    ex = obs.histogram("serve_request_latency_ms").exemplar
    assert ex and len(ex["trace_id"]) == 32
    assert f'# {{trace_id="{ex["trace_id"]}"}}' in prom
    for ph in ("queue", "coalesce", "staging", "dispatch", "sliceout"):
        assert obs.histogram(
            obs.labeled("serve_phase_ms", phase=ph)).count >= 1, ph


def test_rung_fill_flushes_before_the_admission_window():
    """32 queued rows fill the 32-rung exactly: the batch must flush
    immediately, not after the (deliberately huge) admission window."""
    bst, X = _binary_booster()
    rt = ServingRuntime(bst, max_wait_ms=30_000, start=False,
                        shed_unhealthy=False)
    t0 = time.monotonic()
    _queue_then_start(rt, [X[0:16], X[16:32]], raw_score=True)
    elapsed = time.monotonic() - t0
    rt.stop()
    assert elapsed < 10, f"rung-fill flush waited {elapsed:.1f}s"


# ---------------------------------------------------------------------------
# load shedding: typed, counted, evented, /healthz-visible — never a hang
# ---------------------------------------------------------------------------

def test_queue_bound_sheds_with_typed_error_and_healthz_state():
    from lightgbm_tpu.obs import server as _srv

    bst, X = _binary_booster()
    rt = ServingRuntime(bst, max_queue=2, start=False, shed_unhealthy=False)
    rt.submit(X[:4])
    rt.submit(X[:4])
    with pytest.raises(Overloaded) as ei:
        rt.submit(X[:4])
    assert ei.value.reason == "queue_full"
    assert ei.value.tenant == "default"
    assert obs.counter("serve_shed_total").value == 1
    assert obs.counter(
        obs.labeled("serve_shed_total", tenant="default")).value == 1
    assert [e for e in obs.events("serve_shed")
            if e["reason"] == "queue_full"]
    # /healthz: degraded + shedding while the gauge is up
    assert obs.gauge("serve_shedding").value == 1.0
    code, body = _srv.health()
    assert code == 200 and body["status"] == "degraded"
    assert body["shedding"] is True
    # draining clears the state: accepted submissions reset the gauge
    rt.start()
    out = rt.predict(X[:4], timeout=60)
    assert out.shape == (4,)
    assert obs.gauge("serve_shedding").value == 0.0
    assert _srv.health()[1]["shedding"] is False
    rt.stop()


def test_slo_p99_sheds_under_queue_pressure_only():
    bst, X = _binary_booster()
    bst.predict(X[:8], raw_score=True)  # cold compile
    bst.predict(X[:8], raw_score=True)  # warm: populates the reservoir
    assert obs.histogram("predict_warm_latency_ms").count >= 1
    rt = ServingRuntime(bst, slo_p99_ms=1e-6, start=False,
                        shed_unhealthy=False)
    rt.submit(X[:4])  # empty queue: the SLO alone must NOT shed
    with pytest.raises(Overloaded) as ei:
        rt.submit(X[:4])  # queued + p99 over SLO: shed
    assert ei.value.reason == "slo_p99"
    rt.start()
    rt.stop()


def test_unhealthy_process_sheds_when_enabled():
    bst, X = _binary_booster()
    obs.counter("train_nonfinite_errors_total").inc()  # unhealthy state
    rt = ServingRuntime(bst, start=False)  # shed_unhealthy defaults True
    with pytest.raises(Overloaded) as ei:
        rt.submit(X[:4])
    assert ei.value.reason == "unhealthy"
    # opting out serves anyway (the test-suite escape the docstring notes)
    rt2 = ServingRuntime(bst, start=False, shed_unhealthy=False)
    rt2.submit(X[:4])
    rt2.start()
    rt2.stop()
    rt.stop()


def test_result_timeout_never_hangs():
    bst, X = _binary_booster()
    rt = ServingRuntime(bst, start=False, shed_unhealthy=False)
    h = rt.submit(X[:4])
    with pytest.raises(TimeoutError):
        rt.result(h, timeout=0.05)  # runtime never started: must not hang
    rt.stop()
    with pytest.raises(lgb.LightGBMError):
        rt.result(h, timeout=5)  # stop() failed the pending request loudly


# ---------------------------------------------------------------------------
# multi-model, tenants, hot swap
# ---------------------------------------------------------------------------

def test_multi_model_routing_and_tenant_labels():
    b1, X = _binary_booster(rounds=2, seed=3)
    b2, _ = _binary_booster(rounds=6, seed=4)
    rt = ServingRuntime(models={"a": b1, "b": b2}, max_wait_ms=100,
                        start=False, shed_unhealthy=False)
    ha = rt.submit(X[:12], model="a", raw_score=True)
    hb = rt.submit(X[:12], model="b", raw_score=True)
    rt.start()
    got_a, got_b = rt.result(ha, timeout=60), rt.result(hb, timeout=60)
    rt.stop()
    assert np.array_equal(got_a, b1.predict(X[:12], raw_score=True))
    assert np.array_equal(got_b, b2.predict(X[:12], raw_score=True))
    assert not np.array_equal(got_a, got_b)
    assert obs.counter(
        obs.labeled("serve_requests_total", tenant="a")).value == 1
    assert obs.counter(
        obs.labeled("serve_requests_total", tenant="b")).value == 1
    assert obs.histogram(
        obs.labeled("serve_request_latency_ms", tenant="a")).count == 1


def test_tenant_quota_sheds_one_tenant_not_the_other():
    b1, X = _binary_booster(rounds=2, seed=3)
    b2, _ = _binary_booster(rounds=3, seed=4)
    rt = ServingRuntime(models={"a": b1, "b": b2}, tenant_quota=1,
                        start=False, shed_unhealthy=False)
    rt.submit(X[:4], model="a")
    with pytest.raises(Overloaded) as ei:
        rt.submit(X[:4], model="a")
    assert ei.value.reason == "tenant_quota" and ei.value.tenant == "a"
    rt.submit(X[:4], model="b")  # the other tenant keeps serving
    rt.start()
    rt.stop()


def test_hot_swap_serves_new_model_and_never_cools_the_cache():
    b1, X = _binary_booster(rounds=2, seed=5)
    b2, _ = _binary_booster(rounds=7, seed=6)
    with ServingRuntime(b1, max_wait_ms=20,
                        shed_unhealthy=False) as rt:
        got1 = rt.predict(X[:16], raw_score=True, timeout=60)
        assert np.array_equal(got1, b1.predict(X[:16], raw_score=True))
        # swap builds the replacement's pack BEFORE publishing it
        assert not b2._gbdt._pred_cache
        rt.swap_model("default", b2)
        assert b2._gbdt._pred_cache, "swap published a cold pack"
        got2 = rt.predict(X[:16], raw_score=True, timeout=60)
        assert np.array_equal(got2, b2.predict(X[:16], raw_score=True))
        # the OLD model's pack was never invalidated by the swap: an
        # in-flight predict against b1 would still be a cache hit
        assert b1._gbdt._pred_cache
    assert obs.counter("serve_model_swaps_total").value == 1
    assert obs.events("serve_model_swap")


# ---------------------------------------------------------------------------
# serial fallback: ineligible models still serve, uncoalesced
# ---------------------------------------------------------------------------

def test_early_stop_model_serves_serially_and_matches_predict():
    bst, X = _binary_booster(rounds=8, pred_early_stop=True,
                             pred_early_stop_freq=2,
                             pred_early_stop_margin=0.5)
    want = bst.predict(X[:64])
    with ServingRuntime(bst, max_wait_ms=20,
                        shed_unhealthy=False) as rt:
        got = rt.predict(X[:64], timeout=60)
    assert np.array_equal(want, got)
    assert obs.counter("serve_uncoalesced_total").value >= 1


# ---------------------------------------------------------------------------
# engine entry + structural pins
# ---------------------------------------------------------------------------

def test_engine_serve_entry_starts_runtime_and_endpoint():
    from lightgbm_tpu.obs import server as _srv

    bst, X = _binary_booster()
    rt = lgb.serve(bst, {"serve_max_wait_ms": 1, "metrics_port": 0})
    try:
        assert isinstance(rt, ServingRuntime)
        got = rt.predict(X[:8], raw_score=True, timeout=60)
        assert np.array_equal(got, bst.predict(X[:8], raw_score=True))
        srv = _srv.get_server()
        assert srv is not None
        hz = json.load(urllib.request.urlopen(srv.url("/healthz"),
                                              timeout=10))
        assert hz["status"] in ("ok", "degraded")
    finally:
        rt.stop()


def test_serve_module_owns_no_jitted_code():
    """The serving loop may only STAGE and DISPATCH the existing audited
    entries — a serve-owned jit/pjit/pallas_call would open a second
    executable family the predict_coalesced_bucket contract cannot see."""
    from lightgbm_tpu.serve import runtime as serve_rt

    serve_dir = Path(serve_rt.__file__).resolve().parent
    banned = {"jit", "pjit", "pallas_call", "shard_map"}
    for py in serve_dir.glob("*.py"):
        tree = ast.parse(py.read_text())
        for node in ast.walk(tree):
            if isinstance(node, ast.Attribute) and node.attr in banned:
                raise AssertionError(
                    f"{py.name}:{node.lineno} uses {node.attr} — the serve "
                    "module must not own jitted code")
            if isinstance(node, ast.Name) and node.id in banned:
                raise AssertionError(
                    f"{py.name}:{node.lineno} references {node.id}")


def test_serve_name_is_both_entry_point_and_namespace():
    """`lgb.serve` is the entry-point FUNCTION (engine.serve), and the
    subpackage's public names are grafted onto it so every import
    spelling works — the attribute-shadowing trap is closed."""
    import importlib

    assert callable(lgb.serve)
    assert lgb.serve.ServingRuntime is ServingRuntime
    assert lgb.serve.Overloaded is Overloaded
    assert lgb.serve.MAX_BATCH_ROWS == MAX_BATCH_ROWS
    mod = importlib.import_module("lightgbm_tpu.serve")
    assert mod.ServingRuntime is ServingRuntime
    from lightgbm_tpu.serve.runtime import ServingRuntime as SR2
    assert SR2 is ServingRuntime
    assert lgb.serve.runtime.ServingRuntime is ServingRuntime


def test_max_batch_rows_caps_one_batch():
    assert MAX_BATCH_ROWS >= 8
    bst, X = _binary_booster(n=64)
    # a single request larger than the cap still serves (its own batch)
    big = np.tile(X, (MAX_BATCH_ROWS // 64 + 1, 1))
    want = bst.predict(big, raw_score=True)
    rt = ServingRuntime(bst, max_wait_ms=5, start=False,
                        shed_unhealthy=False)
    got = _queue_then_start(rt, [big], raw_score=True)[0]
    rt.stop()
    assert np.array_equal(want, got)
