"""jaxlint rule self-tests: positive / negative / pragma-suppressed fixture
snippets per rule (R1-R5), so rule regressions are caught independently of
the package's own code (which the gate in test_jaxlint_gate.py covers)."""

import textwrap

import pytest

from lightgbm_tpu.analysis import run


def _scan(tmp_path, sources, rules=None):
    """sources: {filename: code} written into one scanned root."""
    root = tmp_path / "fixture_pkg"
    root.mkdir()
    for name, code in sources.items():
        (root / name).write_text(textwrap.dedent(code))
    return run([root], rules)


# ---------------------------------------------------------------------------
# R1 host-sync-in-hot-path
# ---------------------------------------------------------------------------

def test_r1_positive_sync_in_jit(tmp_path):
    rep = _scan(tmp_path, {"mod.py": """
        import jax
        import numpy as np

        @jax.jit
        def f(x):
            y = np.asarray(x)
            z = x.item()
            return float(x) + y + z
    """}, rules=["R1"])
    lines = sorted(f.line for f in rep.findings)
    assert len(rep.findings) == 3, rep.findings
    assert all(f.rule == "R1" for f in rep.findings)
    assert lines == [7, 8, 9]


def test_r1_positive_sync_in_host_driver_loop(tmp_path):
    rep = _scan(tmp_path, {"mod.py": """
        import jax
        import numpy as np

        @jax.jit
        def step(s):
            return s + 1

        def drive(s):
            for _ in range(3):
                s = step(s)
                k = np.asarray(s)
            return k
    """}, rules=["R1"])
    assert len(rep.findings) == 1
    assert rep.findings[0].line == 12


def test_r1_positive_reachable_helper_in_other_module(tmp_path):
    """Host-sync in a helper REACHABLE from a jitted function through a
    relative import is still hot."""
    rep = _scan(tmp_path, {
        "helper.py": """
            def pull(x):
                return x.item()
        """,
        "mod.py": """
            import jax
            from .helper import pull

            @jax.jit
            def f(x):
                return pull(x)
        """,
    }, rules=["R1"])
    assert len(rep.findings) == 1
    assert rep.findings[0].file.endswith("helper.py")


def test_r1_positive_submodule_attribute_call(tmp_path):
    """`from . import sub; sub.jitted(x)` in a host loop must resolve —
    the module-attribute call style gbdt/basic use for the predict ops."""
    rep = _scan(tmp_path, {
        "kern.py": """
            import jax

            @jax.jit
            def f(s):
                return s + 1
        """,
        "mod.py": """
            import numpy as np
            from . import kern

            def drive(s):
                for _ in range(3):
                    s = kern.f(s)
                    k = np.asarray(s)
                return k
        """,
    }, rules=["R1"])
    assert len(rep.findings) == 1
    assert rep.findings[0].file.endswith("mod.py")


def test_r1_positive_through_init_reexport(tmp_path):
    """A hot-path sync reached through a package __init__ re-export
    (`from .sub import helper` where sub/__init__.py re-exports it from
    sub/impl.py) must still resolve: relative imports inside __init__
    modules resolve at the package's own level, and re-export chains are
    followed to the defining module."""
    root = tmp_path / "fixture_pkg"
    (root / "sub").mkdir(parents=True)
    (root / "sub" / "__init__.py").write_text(
        "from .impl import helper\n")
    (root / "sub" / "impl.py").write_text(
        "def helper(x):\n    return x.item()\n")
    (root / "main.py").write_text(
        "import jax\nfrom .sub import helper\n\n"
        "@jax.jit\ndef f(x):\n    return helper(x)\n")
    rep = run([root], ["R1"])
    assert len(rep.findings) == 1, rep.findings
    assert rep.findings[0].file.endswith("impl.py")


def test_r1_negative_shape_and_cold_code(tmp_path):
    rep = _scan(tmp_path, {"mod.py": """
        import jax
        import jax.numpy as jnp
        import numpy as np

        @jax.jit
        def f(x):
            n = float(x.shape[0])
            m = int(len(x))
            return x * n * m

        def host_setup(data):
            return np.asarray(data)
    """}, rules=["R1"])
    assert rep.findings == []


def test_r1_pragma_suppressed(tmp_path):
    rep = _scan(tmp_path, {"mod.py": """
        import jax
        import numpy as np

        @jax.jit
        def f(x):
            y = np.asarray(x)  # jaxlint: disable=R1 (fixture: documented exception)
            return y
    """}, rules=["R1"])
    assert rep.findings == []
    assert len(rep.suppressed) == 1
    assert rep.suppressed[0][1].reason == "fixture: documented exception"


# ---------------------------------------------------------------------------
# R2 recompile-hazard
# ---------------------------------------------------------------------------

def test_r2_positive_jit_per_call(tmp_path):
    rep = _scan(tmp_path, {"mod.py": """
        import jax

        def make(x):
            f = jax.jit(lambda v: v + 1)
            return f(x)

        def outer(x):
            @jax.jit
            def inner(v):
                return v * 2
            return inner(x)
    """}, rules=["R2"])
    assert len(rep.findings) == 2
    assert all(f.rule == "R2" for f in rep.findings)


def test_r2_negative_cached_factory_and_module_jit(tmp_path):
    rep = _scan(tmp_path, {"mod.py": """
        import functools
        import jax

        @functools.lru_cache(maxsize=4)
        def make():
            return jax.jit(lambda v: v + 1)

        @functools.partial(jax.jit, static_argnames=("k",))
        def g(x, k):
            return x * k
    """}, rules=["R2"])
    assert rep.findings == []


def test_r2_positive_unhashable_static_literal(tmp_path):
    rep = _scan(tmp_path, {"mod.py": """
        import functools
        import jax

        @functools.partial(jax.jit, static_argnames=("opts",))
        def g(x, opts):
            return x

        def call(x):
            return g(x, opts=[1, 2])
    """}, rules=["R2"])
    assert len(rep.findings) == 1
    assert "unhashable" in rep.findings[0].message


def test_r2_positive_unhashable_static_kwarg_by_argnum(tmp_path):
    """A static param named via static_argnums but passed by KEYWORD must
    still be checked for unhashable literals."""
    rep = _scan(tmp_path, {"mod.py": """
        import functools
        import jax

        @functools.partial(jax.jit, static_argnums=(1,))
        def g(x, cfg):
            return x

        def call(x):
            return g(x, cfg=[1, 2])
    """}, rules=["R2"])
    assert len(rep.findings) == 1
    assert "unhashable" in rep.findings[0].message


def test_r2_negative_hashable_static(tmp_path):
    rep = _scan(tmp_path, {"mod.py": """
        import functools
        import jax

        @functools.partial(jax.jit, static_argnames=("opts",))
        def g(x, opts):
            return x

        def call(x):
            return g(x, opts=(1, 2))
    """}, rules=["R2"])
    assert rep.findings == []


def test_r2_pragma_suppressed(tmp_path):
    rep = _scan(tmp_path, {"mod.py": """
        import jax

        def make(x):
            f = jax.jit(lambda v: v + 1)  # jaxlint: disable=R2 (fixture: cached by caller)
            return f(x)
    """}, rules=["R2"])
    assert rep.findings == []
    assert len(rep.suppressed) == 1


# ---------------------------------------------------------------------------
# R3 use-after-donate
# ---------------------------------------------------------------------------

def test_r3_positive_read_after_donate(tmp_path):
    rep = _scan(tmp_path, {"mod.py": """
        import functools
        import jax

        @functools.partial(jax.jit, donate_argnums=(0,))
        def upd(state, d):
            return state + d

        def bad(state, d):
            out = upd(state, d)
            return state + out
    """}, rules=["R3"])
    assert len(rep.findings) == 1
    assert rep.findings[0].line == 11
    assert "donated" in rep.findings[0].message


def test_r3_negative_linear_threading(tmp_path):
    rep = _scan(tmp_path, {"mod.py": """
        import functools
        import jax

        @functools.partial(jax.jit, donate_argnums=(0,))
        def upd(state, d):
            return state + d

        def good(state, d):
            for _ in range(3):
                state = upd(state, d)
            return state
    """}, rules=["R3"])
    assert rep.findings == []


def test_r3_positive_donate_argnames(tmp_path):
    rep = _scan(tmp_path, {"mod.py": """
        import functools
        import jax

        @functools.partial(jax.jit, donate_argnames=("state",))
        def upd(state, d):
            return state + d

        def bad(state, d):
            out = upd(state=state, d=d)
            probe = state.sum()
            return out, probe
    """}, rules=["R3"])
    assert len(rep.findings) == 1
    assert rep.findings[0].line == 11


def test_r3_pragma_suppressed(tmp_path):
    rep = _scan(tmp_path, {"mod.py": """
        import functools
        import jax

        @functools.partial(jax.jit, donate_argnums=(0,))
        def upd(state, d):
            return state + d

        def checked(state, d):
            out = upd(state, d)
            assert state.is_deleted()  # jaxlint: disable=R3 (fixture: donation assertion itself)
            return out
    """}, rules=["R3"])
    assert rep.findings == []
    assert len(rep.suppressed) == 1


# ---------------------------------------------------------------------------
# R4 collective-axis-name
# ---------------------------------------------------------------------------

_MESH = """
    DATA_AXIS = "data"
    FEATURE_AXIS = "feature"
"""


def test_r4_positive_undeclared_literal(tmp_path):
    rep = _scan(tmp_path, {
        "mesh.py": _MESH,
        "mod.py": """
            import jax

            def reduce(x):
                return jax.lax.psum(x, "rows")
        """,
    }, rules=["R4"])
    assert len(rep.findings) == 1
    assert "'rows'" in rep.findings[0].message


def test_r4_negative_declared_and_dynamic(tmp_path):
    rep = _scan(tmp_path, {
        "mesh.py": _MESH,
        "mod.py": """
            import jax
            from .mesh import DATA_AXIS

            def reduce(x):
                return jax.lax.psum(x, DATA_AXIS)

            def literal(x):
                return jax.lax.pmax(x, "feature")

            def dynamic(x, axis_name):
                return jax.lax.psum(x, axis_name)
        """,
    }, rules=["R4"])
    assert rep.findings == []


def test_r4_axis_index_first_positional(tmp_path):
    rep = _scan(tmp_path, {
        "mesh.py": _MESH,
        "mod.py": """
            import jax

            def rank(x):
                return jax.lax.axis_index("machines")
        """,
    }, rules=["R4"])
    assert len(rep.findings) == 1


def test_r4_positive_imported_nonaxis_constant(tmp_path):
    """A Name-bound axis arg that resolves to a module-level string
    constant which is NOT a declared axis must be flagged."""
    rep = _scan(tmp_path, {
        "mesh.py": _MESH,
        "misc.py": """
            SOME_NAME = "rows"
        """,
        "mod.py": """
            import jax
            from .misc import SOME_NAME

            def reduce(x):
                return jax.lax.psum(x, SOME_NAME)
        """,
    }, rules=["R4"])
    assert len(rep.findings) == 1
    assert "'rows'" in rep.findings[0].message


def test_r4_pragma_suppressed(tmp_path):
    rep = _scan(tmp_path, {
        "mesh.py": _MESH,
        "mod.py": """
            import jax

            def reduce(x):
                return jax.lax.psum(x, "rows")  # jaxlint: disable=R4 (fixture: axis from a test-only mesh)
        """,
    }, rules=["R4"])
    assert rep.findings == []
    assert len(rep.suppressed) == 1


# ---------------------------------------------------------------------------
# R5 impure-under-jit
# ---------------------------------------------------------------------------

def test_r5_positive_time_rng_global(tmp_path):
    rep = _scan(tmp_path, {"mod.py": """
        import time
        import numpy as np
        import jax

        COUNT = 0

        @jax.jit
        def f(x):
            global COUNT
            COUNT += 1
            t = time.time()
            r = np.random.rand()
            return x + t + r
    """}, rules=["R5"])
    assert len(rep.findings) == 3, rep.findings
    assert any("global" in f.message for f in rep.findings)
    assert any("time.time" in f.message for f in rep.findings)
    assert any("np.random.rand" in f.message for f in rep.findings)


def test_r5_negative_jax_random_and_host_code(tmp_path):
    rep = _scan(tmp_path, {"mod.py": """
        import time
        import numpy as np
        import jax

        @jax.jit
        def f(x, key):
            return x + jax.random.uniform(key, x.shape)

        def host_bench():
            t0 = time.time()
            rng = np.random.RandomState(0)
            return time.time() - t0, rng.rand()
    """}, rules=["R5"])
    assert rep.findings == []


def test_r5_pragma_suppressed(tmp_path):
    rep = _scan(tmp_path, {"mod.py": """
        import time
        import jax

        @jax.jit
        def f(x):
            t = time.time()  # jaxlint: disable=R5 (fixture: trace-time stamp is intended)
            return x + t
    """}, rules=["R5"])
    assert rep.findings == []
    assert len(rep.suppressed) == 1


# ---------------------------------------------------------------------------
# pragma hygiene + CLI plumbing
# ---------------------------------------------------------------------------

def test_pragma_without_reason_is_a_finding(tmp_path):
    rep = _scan(tmp_path, {"mod.py": """
        import jax
        import numpy as np

        @jax.jit
        def f(x):
            return np.asarray(x)  # jaxlint: disable=R1
    """})
    assert any(f.rule == "P0" for f in rep.findings)
    # and the R1 is NOT suppressed by the reasonless pragma
    assert any(f.rule == "R1" for f in rep.findings)


def test_pragma_unknown_rule_is_a_finding(tmp_path):
    rep = _scan(tmp_path, {"mod.py": """
        x = 1  # jaxlint: disable=R99 (no such rule)
    """})
    assert any(f.rule == "P0" for f in rep.findings)


def test_comment_only_pragma_covers_next_line(tmp_path):
    rep = _scan(tmp_path, {"mod.py": """
        import jax
        import numpy as np

        @jax.jit
        def f(x):
            # jaxlint: disable=R1 (fixture: pragma on its own line)
            return np.asarray(x)
    """}, rules=["R1"])
    assert rep.findings == []
    assert len(rep.suppressed) == 1


def test_no_duplicate_findings_for_nested_defs(tmp_path):
    """A defect inside a nested def must be reported exactly once (nested
    functions are their own FuncInfos AND appear in include_nested walks —
    a regression here double-reports every nested finding)."""
    rep = _scan(tmp_path, {"mod.py": """
        import functools
        import time
        import jax

        @functools.partial(jax.jit, static_argnames=("cfg",))
        def g(x, cfg):
            return x

        def outer(x):
            def inner(v):
                return g(v, cfg=[1, 2])
            return inner(x)

        @jax.jit
        def traced(x):
            def helper(v):
                return v + time.time()
            return helper(x)
    """})
    r2 = [f for f in rep.findings if f.rule == "R2"]
    r5 = [f for f in rep.findings if f.rule == "R5"]
    assert len(r2) == 1, r2
    assert len(r5) == 1, r5


def test_comment_only_pragma_skips_blank_lines(tmp_path):
    rep = _scan(tmp_path, {"mod.py": """
        import jax
        import numpy as np

        @jax.jit
        def f(x):
            # jaxlint: disable=R1 (fixture: blank line between pragma and code)

            return np.asarray(x)
    """}, rules=["R1"])
    assert rep.findings == []
    assert len(rep.suppressed) == 1


def test_unknown_rule_selection_raises(tmp_path):
    with pytest.raises(ValueError):
        _scan(tmp_path, {"mod.py": "x = 1\n"}, rules=["R42"])


def test_syntax_error_is_reported_not_fatal(tmp_path):
    rep = _scan(tmp_path, {"mod.py": "def broken(:\n"})
    assert any(f.rule == "E0" for f in rep.findings)


# ---------------------------------------------------------------------------
# R6 fusable-round-loop
# ---------------------------------------------------------------------------

_R6_TWO_PHASE = """
    import functools
    import jax

    @functools.partial(jax.jit, donate_argnums=(0,))
    def admit(state):
        return state + 1, state * 2

    @functools.partial(jax.jit, donate_argnums=(0,))
    def run_pass(state, w):
        return state - w

    def drive(state, w):
        for _ in range(5):
            state, info = admit(state)
            state = run_pass(state, w)
        return state
"""


def test_r6_positive_two_phase_round_loop(tmp_path):
    rep = _scan(tmp_path, {"mod.py": _R6_TWO_PHASE}, rules=["R6"])
    assert len(rep.findings) == 1, rep.findings
    f = rep.findings[0]
    assert f.rule == "R6" and f.line == 16  # the second dispatch
    assert "run_pass" in f.message and "admit" in f.message


def test_r6_negative_host_consumer_between(tmp_path):
    """A host read of the first phase's output between the dispatches is a
    real data dependency — the loop cannot be fused blindly (that sync is
    R1's business, and the async-read protocol the hint points at)."""
    rep = _scan(tmp_path, {"mod.py": """
        import functools
        import jax
        import numpy as np

        @functools.partial(jax.jit, donate_argnums=(0,))
        def admit(state):
            return state + 1, state * 2

        @functools.partial(jax.jit, donate_argnums=(0,))
        def run_pass(state, w):
            return state - w

        def drive(state, w):
            for _ in range(5):
                state, info = admit(state)
                k = int(np.asarray(info)[0])
                state = run_pass(state, k)
            return state
    """}, rules=["R6"])
    assert rep.findings == []


def test_r6_negative_undonated_calls(tmp_path):
    """Without donation the two dispatches do not thread an in-place
    state buffer — nothing forces them into one round body."""
    rep = _scan(tmp_path, {"mod.py": """
        import jax

        @jax.jit
        def admit(state):
            return state + 1

        @jax.jit
        def run_pass(state, w):
            return state - w

        def drive(state, w):
            for _ in range(5):
                state = admit(state)
                state = run_pass(state, w)
            return state
    """}, rules=["R6"])
    assert rep.findings == []


def test_r6_negative_outside_loop(tmp_path):
    """Back-to-back donated dispatches NOT in a loop are a one-off cost,
    not the per-round dispatch class."""
    rep = _scan(tmp_path, {"mod.py": """
        import functools
        import jax

        @functools.partial(jax.jit, donate_argnums=(0,))
        def admit(state):
            return state + 1

        @functools.partial(jax.jit, donate_argnums=(0,))
        def run_pass(state):
            return state * 2

        def setup(state):
            state = admit(state)
            state = run_pass(state)
            return state
    """}, rules=["R6"])
    assert rep.findings == []


def test_r6_pragma_suppressed(tmp_path):
    src = _R6_TWO_PHASE.replace(
        "state = run_pass(state, w)",
        "state = run_pass(state, w)  "
        "# jaxlint: disable=R6 (phases keep separate Mosaic budgets)")
    rep = _scan(tmp_path, {"mod.py": src}, rules=["R6"])
    assert rep.findings == []
    assert len(rep.suppressed) == 1


def test_r6_negative_sequential_single_dispatch_loops(tmp_path):
    """Two SEPARATE loops, each already one dispatch per iteration, must
    not pair across loop boundaries (they cannot be fused per-round)."""
    rep = _scan(tmp_path, {"mod.py": """
        import functools
        import jax

        @functools.partial(jax.jit, donate_argnums=(0,))
        def admit(state):
            return state + 1

        @functools.partial(jax.jit, donate_argnums=(0,))
        def run_pass(state):
            return state * 2

        def drive(state):
            for _ in range(5):
                state = admit(state)
            for _ in range(5):
                state = run_pass(state)
            return state
    """}, rules=["R6"])
    assert rep.findings == []


def test_r6_negative_consumer_on_second_dispatch_line(tmp_path):
    """A host read of the first phase's output INSIDE the second call's
    argument list is still a real data dependency — not fusable."""
    rep = _scan(tmp_path, {"mod.py": """
        import functools
        import jax
        import numpy as np

        @functools.partial(jax.jit, donate_argnums=(0,))
        def admit(state):
            return state + 1, state * 2

        @functools.partial(jax.jit, donate_argnums=(0,))
        def run_pass(state, w):
            return state - w

        def drive(state):
            for _ in range(5):
                state, info = admit(state)
                state = run_pass(state, int(np.asarray(info)[0]))
            return state
    """}, rules=["R6"])
    assert rep.findings == []


def test_r6_negative_bare_read_of_first_dispatch_output(tmp_path):
    """A bare read of the first dispatch's side output between the calls
    (`if info[0]: break` — no recognizable sync call) still implies a
    host data dependency; R6 suppresses conservatively."""
    rep = _scan(tmp_path, {"mod.py": """
        import functools
        import jax

        @functools.partial(jax.jit, donate_argnums=(0,))
        def admit(state):
            return state + 1, state * 2

        @functools.partial(jax.jit, donate_argnums=(0,))
        def run_pass(state, w):
            return state - w

        def drive(state, w):
            for _ in range(5):
                state, info = admit(state)
                if info[0] == 0:
                    break
                state = run_pass(state, w)
            return state
    """}, rules=["R6"])
    assert rep.findings == []


def test_r6_positive_side_output_as_device_argument(tmp_path):
    """Passing the first dispatch's side output straight into the second
    jitted call is device-to-device data flow — the flagship fusable
    shape, NOT a host consumer."""
    rep = _scan(tmp_path, {"mod.py": """
        import functools
        import jax

        @functools.partial(jax.jit, donate_argnums=(0,))
        def admit(state):
            return state + 1, state * 2

        @functools.partial(jax.jit, donate_argnums=(0,))
        def run_pass(state, w):
            return state - w

        def drive(state):
            for _ in range(5):
                state, info = admit(state)
                state = run_pass(state, info)
            return state
    """}, rules=["R6"])
    assert len(rep.findings) == 1, rep.findings
    assert rep.findings[0].rule == "R6"


def test_r6_negative_mutually_exclusive_branches(tmp_path):
    """Dispatches in if/else arms of the same conditional: only one runs
    per iteration — nothing to fuse."""
    rep = _scan(tmp_path, {"mod.py": """
        import functools
        import jax

        @functools.partial(jax.jit, donate_argnums=(0,))
        def fast(state):
            return state + 1

        @functools.partial(jax.jit, donate_argnums=(0,))
        def slow(state):
            return state * 2

        def drive(state, big):
            for _ in range(5):
                if big:
                    state = fast(state)
                else:
                    state = slow(state)
            return state
    """}, rules=["R6"])
    assert rep.findings == []


def test_r6_negative_match_case_arms(tmp_path):
    """match/case arms are mutually exclusive per iteration, exactly like
    if/else."""
    rep = _scan(tmp_path, {"mod.py": """
        import functools
        import jax

        @functools.partial(jax.jit, donate_argnums=(0,))
        def fast(state):
            return state + 1

        @functools.partial(jax.jit, donate_argnums=(0,))
        def slow(state):
            return state * 2

        def drive(state, phase):
            for _ in range(5):
                match phase:
                    case 0:
                        state = fast(state)
                    case _:
                        state = slow(state)
            return state
    """}, rules=["R6"])
    assert rep.findings == []


# ---------------------------------------------------------------------------
# R7 host-nonfinite-guard
# ---------------------------------------------------------------------------

def test_r7_positive_np_isnan_in_driver_loop(tmp_path):
    """Host np.isnan on a per-round tensor inside a grower loop — one
    blocking device pull per round, the guard anti-pattern."""
    rep = _scan(tmp_path, {"mod.py": """
        import jax
        import numpy as np

        @jax.jit
        def step(s):
            return s + 1

        def drive(s):
            for _ in range(5):
                s = step(s)
                if np.isnan(s).any():
                    raise ValueError("nan")
            return s
    """}, rules=["R7"])
    assert len(rep.findings) == 1, rep.findings
    assert rep.findings[0].rule == "R7"
    assert "np.isnan" in rep.findings[0].message


def test_r7_positive_math_isnan_and_float_jnp_pull(tmp_path):
    """math.isnan(...) and bool(jnp.isfinite(...)) in the loop are the
    same sync wearing different costumes."""
    rep = _scan(tmp_path, {"mod.py": """
        import math
        import jax
        import jax.numpy as jnp

        @jax.jit
        def step(s):
            return s + 1

        def drive(s, g):
            for _ in range(5):
                s = step(s)
                if math.isnan(g):
                    break
                if bool(jnp.isfinite(s).all()):
                    continue
            return s
    """}, rules=["R7"])
    assert len(rep.findings) == 2, rep.findings
    assert all(f.rule == "R7" for f in rep.findings)


def test_r7_negative_outside_loop_and_device_side(tmp_path):
    """np.isfinite BEFORE the loop is a once-per-call boundary check, and
    jnp.isfinite folded into the dispatched step is the supported
    device-side guard — neither is flagged."""
    rep = _scan(tmp_path, {"mod.py": """
        import jax
        import jax.numpy as jnp
        import numpy as np

        @jax.jit
        def step(s):
            return s + 1, jnp.isfinite(s).all()

        def drive(s, label):
            if not np.isfinite(label).all():
                raise ValueError("bad label")
            for _ in range(5):
                s, flag = step(s)
            return s, flag
    """}, rules=["R7"])
    assert rep.findings == []


def test_r7_negative_non_driver_function(tmp_path):
    """A plain host function (no jit dispatch in its loops) may isnan all
    it likes — numpy-on-numpy is not a device pull."""
    rep = _scan(tmp_path, {"mod.py": """
        import numpy as np

        def clean(rows):
            for r in rows:
                if np.isnan(r).any():
                    raise ValueError("nan row")
            return rows
    """}, rules=["R7"])
    assert rep.findings == []


def test_r7_pragma_suppression(tmp_path):
    rep = _scan(tmp_path, {"mod.py": """
        import jax
        import numpy as np

        @jax.jit
        def step(s):
            return s + 1

        def drive(s):
            for _ in range(5):
                s = step(s)
                if np.isnan(s).any():  # jaxlint: disable=R7 (debug harness, not a hot loop)
                    raise ValueError("nan")
            return s
    """}, rules=["R7"])
    assert rep.findings == []
    assert len(rep.suppressed) == 1


def test_r7_positive_implicit_bool_branch(tmp_path):
    """`if jnp.isnan(x).any():` in a driver loop triggers __bool__ on a
    device array — the implicit form of the sync, flagged exactly once
    (no double count with the explicit-cast check)."""
    rep = _scan(tmp_path, {"mod.py": """
        import jax
        import jax.numpy as jnp

        @jax.jit
        def step(s):
            return s + 1

        def drive(s):
            for _ in range(5):
                s = step(s)
                if jnp.isnan(s).any():
                    raise ValueError("nan")
                while jnp.isfinite(s).all():
                    break
            return s
    """}, rules=["R7"])
    assert len(rep.findings) == 2, rep.findings
    assert all("implicit bool" in f.message for f in rep.findings)


# ---------------------------------------------------------------------------
# R8 unbucketed-predict-entry
# ---------------------------------------------------------------------------

def test_r8_positive_boolean_mask_subscript_in_loop(tmp_path):
    """The exact pre-round-9 early-stop anti-pattern: the active set
    shrinks host-side and a jitted entry sees a new leading dim per
    chunk."""
    rep = _scan(tmp_path, {"mod.py": """
        import jax
        import numpy as np

        @jax.jit
        def predict_chunk(x):
            return x.sum(axis=1)

        def predict_early_stop(X, margin):
            raw = np.zeros(X.shape[0])
            active = np.ones(X.shape[0], dtype=bool)
            for _ in range(10):
                raw[active] += predict_chunk(X[active])
                active &= np.abs(raw) < margin
            return raw
    """}, rules=["R8"])
    assert len(rep.findings) == 1, rep.findings
    assert rep.findings[0].rule == "R8"
    assert "active" in rep.findings[0].message


def test_r8_positive_inline_comparison_mask(tmp_path):
    rep = _scan(tmp_path, {"mod.py": """
        import jax
        import numpy as np

        @jax.jit
        def score(x):
            return x * 2

        def drive(X, raw):
            for _ in range(4):
                raw = raw + score(X[raw < 0.5])
            return raw
    """}, rules=["R8"])
    assert len(rep.findings) == 1, rep.findings


def test_r8_negative_padded_bucket_with_device_mask(tmp_path):
    """The supported serving pattern: full padded batch + mask ARGUMENT
    (not subscript) — nothing to flag."""
    rep = _scan(tmp_path, {"mod.py": """
        import jax
        import numpy as np

        @jax.jit
        def predict_chunk(x, active):
            import jax.numpy as jnp
            return jnp.where(active, x.sum(axis=1), 0.0)

        def predict_early_stop(X, margin):
            raw = np.zeros(X.shape[0])
            active = np.ones(X.shape[0], dtype=bool)
            for _ in range(10):
                raw = raw + predict_chunk(X, active)
                active &= np.abs(raw) < margin
            return raw
    """}, rules=["R8"])
    assert not rep.findings, rep.findings


def test_r8_negative_static_subscripts_and_outside_loop(tmp_path):
    """Constant/slice subscripts and one-off calls before the loop keep a
    stable shape — not the recompile class R8 hunts."""
    rep = _scan(tmp_path, {"mod.py": """
        import jax
        import numpy as np

        @jax.jit
        def step(x):
            return x + 1

        def drive(X, mask):
            warm = step(X[mask])  # once per call, outside the loop
            s = X[:128]
            for i in range(4):
                s = step(s)
                s = step(X[0:128])
            return warm + s
    """}, rules=["R8"])
    assert not rep.findings, rep.findings


def test_r8_pragma_suppression(tmp_path):
    rep = _scan(tmp_path, {"mod.py": """
        import jax
        import numpy as np

        @jax.jit
        def step(x):
            return x

        def drive(X):
            m = np.ones(4, bool)
            for _ in range(3):
                m &= np.abs(step(X[m])) < 1.0  # jaxlint: disable=R8 (tiny fixed cap, measured cheaper than padding)
            return m
    """}, rules=["R8"])
    assert not rep.findings
    assert len(rep.suppressed) == 1


# ---------------------------------------------------------------------------
# R9 untimed-device-section
# ---------------------------------------------------------------------------

def test_r9_positive_perf_counter_around_dispatch(tmp_path):
    """The async-dispatch mistiming anti-pattern: the delta reads before
    any host pull, so it measures the ~1 ms enqueue, not the device."""
    rep = _scan(tmp_path, {"mod.py": """
        import time
        import jax

        @jax.jit
        def step(x):
            return x + 1

        def bench(x):
            t0 = time.perf_counter()
            x = step(x)
            dt = time.perf_counter() - t0
            return x, dt
    """}, rules=["R9"])
    assert len(rep.findings) == 1, rep.findings
    assert rep.findings[0].rule == "R9"
    assert rep.findings[0].line == 12


def test_r9_positive_time_time_in_loop(tmp_path):
    """time.time() deltas around a loop of dispatches are the same class
    (the ISSUE names both timer spellings)."""
    rep = _scan(tmp_path, {"mod.py": """
        import time
        import jax

        @jax.jit
        def step(x):
            return x * 2

        def bench(x):
            t0 = time.time()
            for _ in range(5):
                x = step(x)
            print(time.time() - t0)
            return x
    """}, rules=["R9"])
    assert len(rep.findings) == 1, rep.findings


def test_r9_negative_host_pull_between(tmp_path):
    """An np.asarray of the dispatched value before the read drains the
    queue — the delta is honest, nothing to flag."""
    rep = _scan(tmp_path, {"mod.py": """
        import time
        import jax
        import numpy as np

        @jax.jit
        def step(x):
            return x + 1

        def bench(x):
            t0 = time.perf_counter()
            x = step(x)
            _ = np.asarray(x)
            dt = time.perf_counter() - t0
            return x, dt
    """}, rules=["R9"])
    assert not rep.findings, rep.findings


def test_r9_positive_two_var_delta(tmp_path):
    """The stored-second-read spelling — t1 = perf_counter(); dt = t1 - t0
    — is the same mistiming with no inline timer call in the Sub."""
    rep = _scan(tmp_path, {"mod.py": """
        import time
        import jax

        @jax.jit
        def step(x):
            return x + 1

        def bench(x):
            t0 = time.perf_counter()
            x = step(x)
            t1 = time.perf_counter()
            dt = t1 - t0
            return x, dt
    """}, rules=["R9"])
    assert len(rep.findings) == 1, rep.findings
    assert rep.findings[0].line == 13


def test_r9_negative_same_line_pull(tmp_path):
    """np.asarray(step(x)) — the one-line pull-the-dispatch idiom the
    hint itself recommends — syncs on the dispatch's own line and must
    not be flagged."""
    rep = _scan(tmp_path, {"mod.py": """
        import time
        import jax
        import numpy as np

        @jax.jit
        def step(x):
            return x + 1

        def bench(x):
            t0 = time.perf_counter()
            r = np.asarray(step(x))
            dt = time.perf_counter() - t0
            return r, dt
    """}, rules=["R9"])
    assert not rep.findings, rep.findings


def test_r9_negative_async_pull_protocol_and_no_dispatch(tmp_path):
    """The windowed driver's shape: an async_pull_result between dispatch
    and read accounts the section; a delta with no dispatch inside its
    window is plain host timing."""
    rep = _scan(tmp_path, {"mod.py": """
        import time
        import jax

        @jax.jit
        def round_fused(s):
            return s, s

        def drive(s, san):
            t_last = time.perf_counter()
            pend = []
            for _ in range(4):
                s, info = round_fused(s)
                pend.append(info)
                got = san.async_pull_result(pend.pop(0))
                t_now = time.perf_counter()
                print(t_now - t_last, got)
                t_last = t_now
            return s

        def host_only(a, b):
            t0 = time.perf_counter()
            c = a + b
            return c, time.perf_counter() - t0
    """}, rules=["R9"])
    assert not rep.findings, rep.findings


def test_r9_pragma_suppression(tmp_path):
    rep = _scan(tmp_path, {"mod.py": """
        import time
        import jax

        @jax.jit
        def step(x):
            return x

        def bench(x):
            t0 = time.perf_counter()
            x = step(x)
            dt = time.perf_counter() - t0  # jaxlint: disable=R9 (fixture: enqueue latency is the quantity under test)
            return x, dt
    """}, rules=["R9"])
    assert not rep.findings
    assert len(rep.suppressed) == 1


# ---------------------------------------------------------------------------
# R10 sync-in-span-close
# ---------------------------------------------------------------------------

def test_r10_positive_pull_in_span_exit(tmp_path):
    """A Span __exit__ that pulls the device value to 'drain for the
    timer' — one hidden blocking sync per span, the class R10 exists
    for."""
    rep = _scan(tmp_path, {"mod.py": """
        import time
        import numpy as np

        class TraceSpan:
            def __enter__(self):
                self.t0 = time.perf_counter()
                return self

            def __exit__(self, *exc):
                _ = np.asarray(self.result)
                self.dur = time.perf_counter() - self.t0
    """}, rules=["R10"])
    assert len(rep.findings) == 1, rep.findings
    assert rep.findings[0].rule == "R10"
    assert rep.findings[0].line == 11


def test_r10_positive_block_until_ready_in_span_close(tmp_path):
    """close()-spelled span finalizers are the same close path, and
    block_until_ready is the same fresh drain."""
    rep = _scan(tmp_path, {"mod.py": """
        class SpanRecorder:
            def close(self):
                self.out.block_until_ready()
                self.done = True
    """}, rules=["R10"])
    assert len(rep.findings) == 1, rep.findings


def test_r10_positive_contextmanager_span_tail(tmp_path):
    """A @contextmanager generator named like a span: the code after the
    yield IS the close path."""
    rep = _scan(tmp_path, {"mod.py": """
        import contextlib
        import numpy as np

        @contextlib.contextmanager
        def device_span(name, x):
            yield
            _ = np.asarray(x)
    """}, rules=["R10"])
    assert len(rep.findings) == 1, rep.findings
    assert rep.findings[0].line == 8


def test_r10_negative_clean_close_and_accounted_sync(tmp_path):
    """A close that only reads the host clock is the designed pattern;
    sanitizer-routed accounted reads (sync_pull/async_pull_result) are
    closing AT an accounted sync — allowed, not flagged.  Pulls before
    the yield (the OPEN path of a cm span) are not close-path either."""
    rep = _scan(tmp_path, {"mod.py": """
        import contextlib
        import time
        import numpy as np

        class Span:
            def __exit__(self, *exc):
                self.dur = time.perf_counter() - self.t0
                self.ring.append(self.dur)

        class ResolveSpan:
            def __exit__(self, *exc):
                info = self.san.async_pull_result(self.pending)
                self.attrs["k"] = int(info[0])

        @contextlib.contextmanager
        def warmup_span(x):
            _ = np.asarray(x)
            yield
    """}, rules=["R10"])
    assert not rep.findings, rep.findings


def test_r10_negative_non_span_close_not_matched(tmp_path):
    """Ordinary resource closes pull-at-will — R10 is scoped to span
    closes, not every __exit__ in the tree."""
    rep = _scan(tmp_path, {"mod.py": """
        import numpy as np

        class FileSink:
            def __exit__(self, *exc):
                self.fh.write(str(np.asarray(self.buf)))
                self.fh.close()
    """}, rules=["R10"])
    assert not rep.findings, rep.findings


def test_r10_pragma_suppression(tmp_path):
    rep = _scan(tmp_path, {"mod.py": """
        import numpy as np

        class DebugSpan:
            def __exit__(self, *exc):
                _ = np.asarray(self.x)  # jaxlint: disable=R10 (fixture: debug span, sync cost accepted)
    """}, rules=["R10"])
    assert not rep.findings
    assert len(rep.suppressed) == 1


# ---------------------------------------------------------------------------
# R11 whole-array-vmem-staging
# ---------------------------------------------------------------------------

def test_r11_positive_whole_array_block(tmp_path):
    """The v1 partition kernel's exact shape: a variable-size dimension
    staged as ONE block (constant index map) — O(N) staging traffic and a
    VMEM row cap."""
    rep = _scan(tmp_path, {"mod.py": """
        from jax.experimental import pallas as pl
        from jax.experimental.pallas import tpu as pltpu

        def call_kernel(kernel, order, n):
            return pl.pallas_call(
                kernel,
                in_specs=[
                    pl.BlockSpec((1, n), lambda s: (0, 0),
                                 memory_space=pltpu.VMEM),
                ],
            )(order)
    """}, rules=["R11"])
    assert len(rep.findings) == 1, rep.findings
    assert rep.findings[0].rule == "R11"
    assert "VMEM" in rep.findings[0].message


def test_r11_positive_missing_index_map_defaults_to_whole(tmp_path):
    """No index map at all stages the array whole too — same finding."""
    rep = _scan(tmp_path, {"mod.py": """
        from jax.experimental import pallas as pl

        def build_spec(n_pad):
            return pl.BlockSpec((n_pad,))
    """}, rules=["R11"])
    assert len(rep.findings) == 1, rep.findings


def test_r11_positive_keyword_form(tmp_path):
    """The same anti-pattern written with keyword arguments
    (block_shape=/index_map=) is flagged too."""
    rep = _scan(tmp_path, {"mod.py": """
        from jax.experimental import pallas as pl
        from jax.experimental.pallas import tpu as pltpu

        def build_spec(n_pad):
            return pl.BlockSpec(block_shape=(1, n_pad),
                                index_map=lambda s: (0, 0),
                                memory_space=pltpu.VMEM)
    """}, rules=["R11"])
    assert len(rep.findings) == 1, rep.findings
    assert rep.findings[0].rule == "R11"


def test_r11_negative_hbm_ref_and_grid_blocking_and_fixed_tiles(tmp_path):
    """The three normal idioms stay clean: the HBM-ref fix pattern
    (memory_space=ANY), real grid blocking (index map uses a grid arg),
    and literal fixed-size tiles."""
    rep = _scan(tmp_path, {"mod.py": """
        from jax.experimental import pallas as pl
        from jax.experimental.pallas import tpu as pltpu

        def specs(n, row_tile, nc):
            hbm = pl.BlockSpec(memory_space=pltpu.ANY)
            hbm2 = pl.BlockSpec((1, n), lambda s: (0, 0),
                                memory_space=pltpu.ANY)
            grid_blocked = pl.BlockSpec((row_tile, nc), lambda j, i: (i, 0),
                                        memory_space=pltpu.VMEM)
            fixed = pl.BlockSpec((1, 512), lambda s: (0, 0),
                                 memory_space=pltpu.VMEM)
            return hbm, hbm2, grid_blocked, fixed
    """}, rules=["R11"])
    assert not rep.findings, rep.findings


def test_r11_negative_no_pallas_import_not_scanned(tmp_path):
    """BlockSpec-named calls outside pallas modules are someone else's
    API — not scanned."""
    rep = _scan(tmp_path, {"mod.py": """
        def f(layout, n):
            return layout.BlockSpec((1, n), lambda s: (0, 0))
    """}, rules=["R11"])
    assert not rep.findings, rep.findings


def test_r11_positive_data_sized_vmem_scratch(tmp_path):
    """Round-16 extension: a pltpu.VMEM SCRATCH allocation sized by a
    data-dependent dimension is whole-array staging by another name."""
    rep = _scan(tmp_path, {"mod.py": """
        from jax.experimental import pallas as pl
        from jax.experimental.pallas import tpu as pltpu
        import jax.numpy as jnp

        def scratches(n, n_pad):
            a = pltpu.VMEM((2, n), jnp.int32)
            b = pltpu.VMEM((1, n_pad), jnp.float32)
            return a, b
    """}, rules=["R11"])
    assert len(rep.findings) == 2, rep.findings
    assert all("scratch" in f.message for f in rep.findings)


def test_r11_negative_const_and_caps_vmem_scratch(tmp_path):
    """Fixed tiles stay clean: literal dims, module-level int constants
    (the partition kernel's _CHUNK), and ALL-CAPS config-tile names (the
    megakernel's budget-derived FB) are the normal idiom."""
    rep = _scan(tmp_path, {"mod.py": """
        from jax.experimental import pallas as pl
        from jax.experimental.pallas import tpu as pltpu
        import jax.numpy as jnp

        _CHUNK = 512

        def scratches(T, FB, B):
            a = pltpu.VMEM((2, 1, _CHUNK), jnp.int32)
            b = pltpu.VMEM((T, 3, FB, B), jnp.float32)
            c = pltpu.VMEM((4, 128), jnp.float32)
            return a, b, c
    """}, rules=["R11"])
    assert not rep.findings, rep.findings


def test_r11_vmem_scratch_pragma_suppression(tmp_path):
    rep = _scan(tmp_path, {"mod.py": """
        from jax.experimental import pallas as pl
        from jax.experimental.pallas import tpu as pltpu
        import jax.numpy as jnp

        def scratch(n_seg):
            # jaxlint: disable=R11 (fixture: O(S) per-segment table)
            return pltpu.VMEM((1, n_seg), jnp.int32)
    """}, rules=["R11"])
    assert not rep.findings
    assert len(rep.suppressed) == 1


def test_r11_pragma_suppression(tmp_path):
    """An intentionally staged SMALL variable-size block (O(S) segment
    table) documents itself with the pragma + reason."""
    rep = _scan(tmp_path, {"mod.py": """
        from jax.experimental import pallas as pl
        from jax.experimental.pallas import tpu as pltpu

        def spec(S):
            # jaxlint: disable=R11 (fixture: O(S) table, a few KB)
            return pl.BlockSpec((1, S), lambda s: (0, 0),
                                memory_space=pltpu.VMEM)
    """}, rules=["R11"])
    assert not rep.findings
    assert len(rep.suppressed) == 1


# ---------------------------------------------------------------------------
# R12 raw-model-write
# ---------------------------------------------------------------------------

def test_r12_positive_raw_open_write_of_model_artifact(tmp_path):
    """A raw open(..., 'w'/'wb') of a model/snapshot path outside the
    checkpoint helper is the torn-file class the atomic writer exists to
    exclude."""
    rep = _scan(tmp_path, {"mod.py": """
        def save(model_path, text, snap):
            with open(model_path, "w") as fh:
                fh.write(text)
            with open(snap + ".snapshot_iter_3", "wb") as fh:
                fh.write(text.encode())
    """}, rules=["R12"])
    assert len(rep.findings) == 2, rep.findings
    assert all(f.rule == "R12" for f in rep.findings)


def test_r12_positive_np_save_and_os_replace(tmp_path):
    rep = _scan(tmp_path, {"mod.py": """
        import os
        import numpy as np

        def persist(arrays, tmp, manifest_path):
            np.savez("ensemble_snapshot.npz", **arrays)
            os.replace(tmp, manifest_path)
    """}, rules=["R12"])
    assert len(rep.findings) == 2, rep.findings


def test_r12_negative_non_artifact_writes_and_reads(tmp_path):
    """Logs, predictions, data paths: not artifacts.  Reading a model is
    not a write.  Mode must actually contain 'w'."""
    rep = _scan(tmp_path, {"mod.py": """
        import numpy as np

        def ok(log_path, model_path, data):
            with open(log_path, "w") as fh:
                fh.write("line")
            with open(model_path) as fh:
                text = fh.read()
            with open(model_path, "rb") as fh:
                raw = fh.read()
            np.savez("bins_cache.npz", bins=data)
            return text, raw
    """}, rules=["R12"])
    assert not rep.findings, rep.findings


def test_r12_negative_checkpoint_module_exempt(tmp_path):
    """utils/checkpoint.py IS the sanctioned writer — its own raw
    open/os.replace are the implementation, not a violation."""
    rep = _scan(tmp_path, {"checkpoint.py": """
        import os

        def atomic_write_text(model_path, text, tmp):
            with open(tmp, "w") as fh:
                fh.write(text)
            os.replace(tmp, model_path)
    """}, rules=["R12"])
    assert not rep.findings, rep.findings


def test_r12_pragma_suppression(tmp_path):
    rep = _scan(tmp_path, {"mod.py": """
        def convert(cfg, code):
            with open(cfg.convert_model, "w") as fh:  # jaxlint: disable=R12 (fixture: generated source, not a loadable artifact)
                fh.write(code)
    """}, rules=["R12"])
    assert not rep.findings
    assert len(rep.suppressed) == 1


# ---------------------------------------------------------------------------
# R13 collective-outside-fused-round
# ---------------------------------------------------------------------------

def test_r13_positive_eager_collective_in_round_loop(tmp_path):
    rep = _scan(tmp_path, {"mod.py": """
        import functools
        import jax

        @functools.partial(jax.jit, donate_argnums=(0,))
        def round_fused(state, grad):
            return state + grad, state.sum()

        def drive(state, grad):
            for _ in range(10):
                state, hist = round_fused(state, grad)
                merged = jax.lax.psum(hist, "data")
            return merged
    """}, rules=["R13"])
    assert len(rep.findings) == 1, rep.findings
    assert rep.findings[0].rule == "R13"
    assert "psum" in rep.findings[0].message


def test_r13_positive_jitted_collective_helper_per_round(tmp_path):
    rep = _scan(tmp_path, {"mod.py": """
        import functools
        import jax

        @jax.jit
        def merge_hists(h):
            return jax.lax.psum_scatter(h, "data")

        @functools.partial(jax.jit, donate_argnums=(0,))
        def round_fused(state, grad):
            return state + grad, state.sum()

        def drive(state, grad):
            for _ in range(10):
                state, hist = round_fused(state, grad)
                hist = merge_hists(hist)
            return hist
    """}, rules=["R13"])
    assert len(rep.findings) == 1, rep.findings
    assert "merge_hists" in rep.findings[0].message


def test_r13_negative_collective_inside_donated_round(tmp_path):
    """The FIX pattern: the collective lives inside the donated round
    body (in-dispatch merge) — no finding."""
    rep = _scan(tmp_path, {"mod.py": """
        import functools
        import jax

        @functools.partial(jax.jit, donate_argnums=(0,))
        def round_fused(state, grad):
            hist = jax.lax.psum(grad, "data")
            return state + hist, hist.sum()

        def drive(state, grad):
            for _ in range(10):
                state, info = round_fused(state, grad)
            return state
    """}, rules=["R13"])
    assert rep.findings == []


def test_r13_negative_loop_without_donated_dispatch(tmp_path):
    """Collectives in setup/eval loops with no donated round dispatch
    are out of scope (not the per-round regression class)."""
    rep = _scan(tmp_path, {"mod.py": """
        import jax

        @jax.jit
        def evaluate(score):
            return score.sum()

        def eval_all(scores):
            out = []
            for s in scores:
                loss = evaluate(s)
                out.append(jax.lax.psum(loss, "data"))
            return out
    """}, rules=["R13"])
    assert rep.findings == []


def test_r13_pragma_suppression(tmp_path):
    rep = _scan(tmp_path, {"mod.py": """
        import functools
        import jax

        @functools.partial(jax.jit, donate_argnums=(0,))
        def round_fused(state, grad):
            return state + grad, state.sum()

        def drive(state, grad):
            for _ in range(10):
                state, hist = round_fused(state, grad)
                merged = jax.lax.psum(hist, "data")  # jaxlint: disable=R13 (fixture: debug-only fleet probe)
            return merged
    """}, rules=["R13"])
    assert rep.findings == []


# ---------------------------------------------------------------------------
# R14 metadata-via-device-pull
# ---------------------------------------------------------------------------

def test_r14_positive_asarray_shape(tmp_path):
    """The PR-9 review class: reading a length through a whole-array
    conversion of a (possibly jitted) output."""
    rep = _scan(tmp_path, {"mod.py": """
        import numpy as np

        def f(x):
            return np.asarray(x).shape[0]
    """}, rules=["R14"])
    assert len(rep.findings) == 1, rep.findings
    assert rep.findings[0].rule == "R14"
    assert rep.findings[0].line == 5


def test_r14_positive_len_of_asarray_and_dtype(tmp_path):
    rep = _scan(tmp_path, {"mod.py": """
        import numpy as np

        def f(x, y):
            n = len(np.asarray(x))
            out = np.full(n, 0, dtype=np.array(y).dtype)
            return out
    """}, rules=["R14"])
    assert len(rep.findings) == 2, rep.findings
    assert sorted(f.line for f in rep.findings) == [5, 6]


def test_r14_positive_shape_item(tmp_path):
    """.item() on a shape entry: shape entries are already Python ints."""
    rep = _scan(tmp_path, {"mod.py": """
        def f(x):
            return x.shape[0].item()
    """}, rules=["R14"])
    assert len(rep.findings) == 1, rep.findings


def test_r14_negative_direct_metadata_and_bound_conversion(tmp_path):
    """Reading .shape/.dtype directly, np.shape(), and converting ONCE
    into a binding whose data is then used are all clean."""
    rep = _scan(tmp_path, {"mod.py": """
        import numpy as np

        def f(x):
            n = x.shape[0]
            m = np.shape(x)[0]
            a = np.asarray(x)
            return a.dtype, a[: n + m]
    """}, rules=["R14"])
    assert rep.findings == [], rep.findings


def test_r14_pragma_suppression(tmp_path):
    rep = _scan(tmp_path, {"mod.py": """
        import numpy as np

        def f(x):
            return np.asarray(x).shape  # jaxlint: disable=R14 (x is a host list; conversion is how we learn the shape)
    """}, rules=["R14"])
    assert rep.findings == []
    assert len(rep.suppressed) == 1


# ---------------------------------------------------------------------------
# R15 staging-alloc-in-serve-loop
# ---------------------------------------------------------------------------

def test_r15_positive_fresh_alloc_in_serve_loop(tmp_path):
    """The anti-pattern the pinned-buffer serving design exists to
    prevent: a fresh staging buffer allocated per request iteration."""
    rep = _scan(tmp_path, {"mod.py": """
        import numpy as np

        def serve_loop(g, requests):
            outs = []
            for X in requests:
                buf = np.zeros((128, X.shape[1]), np.float32)
                buf[: X.shape[0]] = X
                outs.append(g.predict_raw(buf))
            return outs
    """}, rules=["R15"])
    assert len(rep.findings) == 1, rep.findings
    assert rep.findings[0].rule == "R15"
    assert rep.findings[0].line == 7


def test_r15_positive_upload_of_fresh_host_array(tmp_path):
    """jnp.asarray over a freshly constructed host array inside the loop:
    allocate-then-upload per call — ONE finding, not two (the wrapped
    alloc reports as the upload form)."""
    rep = _scan(tmp_path, {"mod.py": """
        import jax.numpy as jnp
        import numpy as np
        from .san import sync_pull

        def drive(entry, reqs):
            for X in reqs:
                out = entry(jnp.asarray(np.empty((8, 4), np.float32)))
                sync_pull(out)
    """, "san.py": """
        def sync_pull(x):
            return x
    """}, rules=["R15"])
    assert len(rep.findings) == 1, rep.findings
    assert "allocate-then-upload" in rep.findings[0].message


def test_r15_negative_pinned_buffer_reused_across_iterations(tmp_path):
    """The sanctioned pattern: the buffer hoisted out of the loop, filled
    per request, uploaded BY NAME — exactly serve/runtime.py's staging."""
    rep = _scan(tmp_path, {"mod.py": """
        import jax.numpy as jnp
        import numpy as np

        def serve_loop(g, requests, f):
            buf = np.zeros((128, f), np.float32)  # pinned, reused
            outs = []
            for X in requests:
                buf[: X.shape[0]] = X
                outs.append(g.predict_raw(jnp.asarray(buf)))
            return outs
    """}, rules=["R15"])
    assert rep.findings == [], rep.findings


def test_r15_negative_alloc_in_non_predict_loop(tmp_path):
    """Loops with no accounted predict entry (setup, training drivers)
    are out of scope — R1/R14 own their allocation hygiene."""
    rep = _scan(tmp_path, {"mod.py": """
        import numpy as np

        def build_tables(sizes):
            tables = []
            for n in sizes:
                tables.append(np.zeros((n, 4), np.float32))
            return tables
    """}, rules=["R15"])
    assert rep.findings == [], rep.findings


def test_r15_pragma_suppression(tmp_path):
    rep = _scan(tmp_path, {"mod.py": """
        import numpy as np

        def replay(g, reqs):
            for X in reqs:
                pad = np.zeros((64, 4), np.float32)  # jaxlint: disable=R15 (fixture: one-shot replay tool, not a serving loop)
                pad[: X.shape[0]] = X
                g.predict_raw(pad)
    """}, rules=["R15"])
    assert rep.findings == []
    assert len(rep.suppressed) == 1


# ---------------------------------------------------------------------------
# stale-pragma detection (P1)
# ---------------------------------------------------------------------------

def test_stale_pragma_reported_as_warning_by_default(tmp_path):
    """A suppression whose line no longer triggers the named rule is
    reported in Report.stale but does not fail the default run."""
    rep = _scan(tmp_path, {"mod.py": """
        import numpy as np

        def f(x):
            return x + 1  # jaxlint: disable=R1 (retired: the pull was removed)
    """})
    assert rep.findings == []
    assert len(rep.stale) == 1
    assert rep.stale[0].rule == "P1"
    assert "R1" in rep.stale[0].message


def test_stale_pragma_fails_under_strict(tmp_path):
    import textwrap
    root = tmp_path / "fixture_pkg"
    root.mkdir()
    (root / "mod.py").write_text(textwrap.dedent("""
        def f(x):
            return x  # jaxlint: disable=R5 (retired)
    """))
    rep = run([root], strict_pragmas=True)
    assert not rep.ok
    assert any(f.rule == "P1" for f in rep.findings)


def test_live_pragma_is_not_stale(tmp_path):
    """A pragma that still suppresses a real finding stays untouched."""
    rep = _scan(tmp_path, {"mod.py": """
        import jax
        import numpy as np

        @jax.jit
        def f(x):
            return np.asarray(x)  # jaxlint: disable=R1 (fixture: intentional)
    """})
    assert rep.findings == []
    assert rep.stale == []
    assert len(rep.suppressed) == 1


def test_stale_pragma_subset_run_does_not_misjudge(tmp_path):
    """A subset run (--rules) cannot conclude staleness for unselected
    rules: a pragma naming an unselected rule is left alone."""
    rep = _scan(tmp_path, {"mod.py": """
        def f(x):
            return x  # jaxlint: disable=R5 (would be stale under a full run)
    """}, rules=["R1"])
    assert rep.stale == []


def test_pragma_inside_docstring_is_ignored(tmp_path):
    """Pragma-shaped text in a string literal is documentation, not a
    suppression — it must neither suppress nor count as stale."""
    rep = _scan(tmp_path, {"mod.py": '''
        def f(x):
            """Example: y = np.asarray(d)  # jaxlint: disable=R1 (why)"""
            return x
    '''})
    assert rep.stale == []
    assert rep.suppressed == []


# ---------------------------------------------------------------------------
# R16 mutation-outside-version-bump
# ---------------------------------------------------------------------------

def _scan_tree(tmp_path, sources, rules=None):
    """Like _scan, but filenames may carry subdirectories — R16 is scoped
    to serve/ and continual/ paths."""
    root = tmp_path / "fixture_pkg"
    for name, code in sources.items():
        p = root / name
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(code))
    return run([root], rules)


def test_r16_positive_models_subscript_write_in_serve(tmp_path):
    rep = _scan_tree(tmp_path, {"serve/swap.py": """
        def hot_patch(g, i, tree):
            g.models[i] = tree
            return g
    """}, rules=["R16"])
    assert len(rep.findings) == 1
    assert rep.findings[0].rule == "R16"
    assert ".models" in rep.findings[0].message


def test_r16_positive_leaf_write_and_list_mutator_in_continual(tmp_path):
    rep = _scan_tree(tmp_path, {"continual/refitlike.py": """
        def renew(g, new_lv, extra_tree):
            for i, t in enumerate(g.models):
                t.leaf_value = new_lv[i]
            g._models.append(extra_tree)
    """}, rules=["R16"])
    assert len(rep.findings) == 2, rep.findings
    assert {f.rule for f in rep.findings} == {"R16"}


def test_r16_negative_mutation_routed_through_bump(tmp_path):
    rep = _scan_tree(tmp_path, {"continual/refitlike.py": """
        def renew(g, new_lv):
            for i, t in enumerate(g.models):
                t.leaf_value = new_lv[i]
            g._invalidate_pred_cache("renew")
    """}, rules=["R16"])
    assert rep.findings == []


def test_r16_negative_outside_scoped_dirs(tmp_path):
    """The identical mutation OUTSIDE serve/continual paths is out of
    scope (the versioned key's n_models component and the runtime pins
    own it — docs/ANALYSIS.md static-limits note)."""
    rep = _scan_tree(tmp_path, {"models/trainer.py": """
        def grow(g, tree):
            g._models.append(tree)
    """}, rules=["R16"])
    assert rep.findings == []


def test_r16_pragma_suppression(tmp_path):
    rep = _scan_tree(tmp_path, {"serve/swap.py": """
        def hot_patch(g, i, tree):
            g.models[i] = tree  # jaxlint: disable=R16 (fixture: caller holds the pack lock and bumps)
            return g
    """}, rules=["R16"])
    assert rep.findings == []
    assert len(rep.suppressed) == 1


# ---------------------------------------------------------------------------
# R17 full-histogram-over-dcn
# ---------------------------------------------------------------------------

def test_r17_positive_full_hist_psum_over_dcn(tmp_path):
    rep = _scan(tmp_path, {"mod.py": """
        import jax

        def merge(fresh_hists):
            return jax.lax.psum(fresh_hists, "dcn")
    """}, rules=["R17"])
    assert len(rep.findings) == 1
    assert rep.findings[0].rule == "R17"
    assert "dcn" in rep.findings[0].message


def test_r17_positive_all_gather_hist_via_axis_constant(tmp_path):
    """The DCN axis referenced through the mesh constant (incl. a
    both-axes tuple) is still the dcn axis."""
    rep = _scan(tmp_path, {"mod.py": """
        import jax

        ICI_AXIS = "ici"
        DCN_AXIS = "dcn"

        def gather(hist0):
            return jax.lax.all_gather(hist0, DCN_AXIS)

        def both(cand_hist):
            return jax.lax.psum(cand_hist, (ICI_AXIS, DCN_AXIS))
    """}, rules=["R17"])
    assert len(rep.findings) == 2
    assert all(f.rule == "R17" for f in rep.findings)


def test_r17_negative_topk_shaped_and_scalar_operands(tmp_path):
    """The sanctioned shapes: an elected top-k histogram subset
    (take_along_axis by the vote's indices) and scalar/gain traffic
    cross dcn clean; the full merge stays on ici."""
    rep = _scan(tmp_path, {"mod.py": """
        import jax
        import jax.numpy as jnp

        def election(cand_hists, g_idx, vote_gain, total):
            sub_hists = jnp.take_along_axis(
                cand_hists, g_idx[:, None, :, None], axis=2)
            sub_hists = jax.lax.psum(sub_hists, "dcn")
            gains = jax.lax.all_gather(vote_gain, "dcn")
            worst = jax.lax.pmax(total, ("ici", "dcn"))
            slice_hists = jax.lax.psum(cand_hists, "ici")
            return sub_hists, gains, worst, slice_hists
    """}, rules=["R17"])
    assert rep.findings == []


def test_r17_negative_full_hist_inside_slice(tmp_path):
    """The intra-slice full merge is the design, not a finding."""
    rep = _scan(tmp_path, {"mod.py": """
        import jax

        DATA_AXIS = "data"

        def merge(fresh_hists, hist0):
            a = jax.lax.psum(fresh_hists, "ici")
            b = jax.lax.psum_scatter(hist0, DATA_AXIS,
                                     scatter_dimension=2, tiled=True)
            return a, b
    """}, rules=["R17"])
    assert rep.findings == []


def test_r17_pragma_suppression(tmp_path):
    rep = _scan(tmp_path, {"mod.py": """
        import jax

        def debug_merge(dbg_hists):
            return jax.lax.psum(dbg_hists, "dcn")  # jaxlint: disable=R17 (fixture: one-off debug parity probe, never the round path)
    """}, rules=["R17"])
    assert rep.findings == []
    assert len(rep.suppressed) == 1


def test_r17_nested_def_neither_duplicates_nor_misses_enclosing_gather(
        tmp_path):
    """Nested defs are walked through their enclosing function only: a
    top-k gather assigned in the ENCLOSING scope sanctions a dcn
    collective inside a nested def (no false positive), and a genuine
    violation inside a nested def reports exactly once."""
    rep = _scan(tmp_path, {"mod.py": """
        import jax
        import jax.numpy as jnp

        def outer_clean(cand_hists, g_idx):
            sub_hists = jnp.take_along_axis(
                cand_hists, g_idx[:, None, :, None], axis=2)

            def merge():
                return jax.lax.psum(sub_hists, "dcn")
            return merge

        def outer_bad(fresh_hists):
            def merge():
                return jax.lax.psum(fresh_hists, "dcn")
            return merge
    """}, rules=["R17"])
    assert len(rep.findings) == 1, rep.findings
    assert "outer_bad" in rep.findings[0].message


# ---------------------------------------------------------------------------
# R18 host-loop-over-independent-boosters
# ---------------------------------------------------------------------------

def test_r18_positive_train_per_dataset_loop(tmp_path):
    rep = _scan(tmp_path, {"mod.py": """
        import lightgbm_tpu as lgb

        def sweep(params, datasets):
            boosters = []
            for ds in datasets:
                boosters.append(lgb.train(params, ds, num_boost_round=50))
            return boosters
    """}, rules=["R18"])
    assert len(rep.findings) == 1
    assert rep.findings[0].rule == "R18"
    assert "host loop" in rep.findings[0].message


def test_r18_positive_refit_and_iter_over_model_dict(tmp_path):
    """Both non-train entry spellings fire, qualified or bare, keyed or
    enumerated."""
    rep = _scan(tmp_path, {"mod.py": """
        from lightgbm_tpu.continual import refit_leaves

        def renew_all(models, X, ys):
            for name, g in models.items():
                refit_leaves(g, X, ys[name])

        def advance_all(lanes, grads):
            for i in range(len(lanes)):
                lanes[i].train_one_iter(grads[i])
    """}, rules=["R18"])
    assert len(rep.findings) == 2, rep.findings
    assert {f.rule for f in rep.findings} == {"R18"}


def test_r18_negative_loop_carried_dependence(tmp_path):
    """Warm-start chains and a running score feeding the next refit are
    sequential by construction — iteration i reads what iteration i-1
    assigned."""
    rep = _scan(tmp_path, {"mod.py": """
        import lightgbm_tpu as lgb
        from lightgbm_tpu.continual import refit_leaves

        def warm_chain(params, datasets):
            bst = None
            for ds in datasets:
                bst = lgb.train(params, ds, init_model=bst)
            return bst

        def staged_refit(g, chunks):
            y = None
            for X, y_next in chunks:
                if y is not None:
                    refit_leaves(g, X, y)
                y = y_next
    """}, rules=["R18"])
    assert rep.findings == []


def test_r18_negative_unrelated_train_methods(tmp_path):
    """`.train()` on arbitrary objects (torch-style mode switches, a
    scheduler) is not the package entry — the spelling heuristic keeps
    them out of scope."""
    rep = _scan(tmp_path, {"mod.py": """
        def toggle(modules):
            for m in modules:
                m.train()

        def drive(trainers, batches):
            for t, b in zip(trainers, batches):
                t.model.train(b)
    """}, rules=["R18"])
    assert rep.findings == []


def test_r18_pragma_suppression(tmp_path):
    rep = _scan(tmp_path, {"mod.py": """
        import lightgbm_tpu as lgb

        def baseline(params, datasets):
            out = []
            for ds in datasets:
                out.append(lgb.train(params, ds))  # jaxlint: disable=R18 (fixture: the measured host-loop baseline itself)
            return out
    """}, rules=["R18"])
    assert rep.findings == []
    assert len(rep.suppressed) == 1


# ---------------------------------------------------------------------------
# R19 unbounded-retry
# ---------------------------------------------------------------------------

def test_r19_positive_hot_retry_loop(tmp_path):
    """The canonical anti-pattern: swallow everything, loop straight back
    into the next attempt — no pacing, no budget, no deadline."""
    rep = _scan(tmp_path, {"mod.py": """
        import requests

        def poll(url):
            while True:
                try:
                    return requests.get(url)
                except Exception:
                    continue
    """}, rules=["R19"])
    assert len(rep.findings) == 1
    assert rep.findings[0].rule == "R19"
    assert "backoff" in rep.findings[0].message


def test_r19_positive_bare_except_swallow(tmp_path):
    """A bare except that logs and spins is the same hazard; re-dispatch
    spellings (predict/send) count as IO-ish."""
    rep = _scan(tmp_path, {"mod.py": """
        def drive(runtime, batch, log):
            while True:
                try:
                    runtime.predict(batch)
                except:
                    log.warning("dispatch failed")
    """}, rules=["R19"])
    assert len(rep.findings) == 1, rep.findings
    assert "predict" in rep.findings[0].message


def test_r19_negative_paced_or_bounded(tmp_path):
    """Pacing (sleep/backoff), a retry budget, or a deadline check each
    bound the loop — any one of them clears the finding."""
    rep = _scan(tmp_path, {"mod.py": """
        import time
        import requests

        def paced(url):
            backoff = 0.05
            while True:
                try:
                    return requests.get(url)
                except Exception:
                    time.sleep(backoff)
                    backoff *= 2

        def budgeted(url, clock):
            deadline = clock() + 30.0
            while clock() < deadline:
                try:
                    return requests.get(url)
                except Exception:
                    pass
            raise TimeoutError(url)
    """}, rules=["R19"])
    assert rep.findings == []


def test_r19_negative_narrow_catch_and_worker_loop(tmp_path):
    """A narrow catch names the one expected failure instead of swallowing
    all of them, and a worker loop blocking on a bare queue ``.get()`` for
    its next item cannot hot-spin (the serve dispatcher shape); a handler
    that re-raises or breaks surfaces the failure instead of retrying."""
    rep = _scan(tmp_path, {"mod.py": """
        import queue

        def worker(hand, runtime):
            while True:
                try:
                    item = hand.get()
                    runtime.predict(item)
                except queue.Empty:
                    continue

        def surfaced(runtime, batch):
            while True:
                try:
                    return runtime.predict(batch)
                except Exception:
                    raise
    """}, rules=["R19"])
    assert rep.findings == []


def test_r19_pragma_suppression(tmp_path):
    rep = _scan(tmp_path, {"mod.py": """
        import requests

        def poll(url):
            while True:
                try:  # jaxlint: disable=R19 (fixture: chaos-harness spin probe, bounded by the harness timeout)
                    return requests.get(url)
                except Exception:
                    continue
    """}, rules=["R19"])
    assert rep.findings == []
    assert len(rep.suppressed) == 1


# ---------------------------------------------------------------------------
# R20 feature-axis-hist-collective
# ---------------------------------------------------------------------------

def test_r20_positive_hist_psum_over_feature_literal(tmp_path):
    rep = _scan(tmp_path, {"mod.py": """
        import jax

        def merge(leaf_hists):
            return jax.lax.psum(leaf_hists, "feature")
    """}, rules=["R20"])
    assert len(rep.findings) == 1
    assert rep.findings[0].rule == "R20"
    assert "feature axis" in rep.findings[0].message


def test_r20_positive_axis_constant_and_tuple(tmp_path):
    """The feature axis referenced through the mesh constant or a
    feature_axis_name variable — including in a both-axes tuple — is
    still the feature axis."""
    rep = _scan(tmp_path, {"mod.py": """
        import jax

        DATA_AXIS = "data"
        FEATURE_AXIS = "feature"

        def gather(hist0):
            return jax.lax.all_gather(hist0, FEATURE_AXIS)

        def both(cand_hist, feature_axis_name):
            return jax.lax.psum(cand_hist, (DATA_AXIS, feature_axis_name))
    """}, rules=["R20"])
    assert len(rep.findings) == 2
    assert all(f.rule == "R20" for f in rep.findings)


def test_r20_negative_row_merge_and_non_hist_broadcast(tmp_path):
    """The sanctioned feature2d traffic: the histogram merge over the ROW
    axis, the winner's go/no-go row broadcast (not hist-named), and
    election scalars cross the feature axis clean."""
    rep = _scan(tmp_path, {"mod.py": """
        import jax
        import jax.numpy as jnp

        DATA_AXIS = "data"
        FEATURE_AXIS = "feature"

        def round_body(fresh_hists, go_left, own_pos, gain):
            merged_hists = jax.lax.psum(fresh_hists, DATA_AXIS)
            go_left = jax.lax.psum(
                jnp.where(own_pos, go_left, False).astype(jnp.int32),
                FEATURE_AXIS) > 0
            best = jax.lax.pmax(gain, (DATA_AXIS, FEATURE_AXIS))
            return merged_hists, go_left, best
    """}, rules=["R20"])
    assert rep.findings == []


def test_r20_negative_topk_shaped_subset(tmp_path):
    """An elected top-k histogram subset (take_along_axis by the vote's
    indices) may cross the feature axis — the R17 escape carries over."""
    rep = _scan(tmp_path, {"mod.py": """
        import jax
        import jax.numpy as jnp

        def election(cand_hists, g_idx):
            sub_hists = jnp.take_along_axis(
                cand_hists, g_idx[:, None, :, None], axis=2)
            return jax.lax.psum(sub_hists, "feature")
    """}, rules=["R20"])
    assert rep.findings == []


def test_r20_pragma_suppression(tmp_path):
    rep = _scan(tmp_path, {"mod.py": """
        import jax

        def debug_merge(dbg_hists):
            return jax.lax.psum(dbg_hists, "feature")  # jaxlint: disable=R20 (fixture: one-off parity probe, never the round path)
    """}, rules=["R20"])
    assert rep.findings == []
    assert len(rep.suppressed) == 1


# ---------------------------------------------------------------------------
# R21 unlinked-cross-thread-span
# ---------------------------------------------------------------------------

def test_r21_positive_implicit_span_in_thread_target(tmp_path):
    """A record_span with no ctx/parent/links inside a Thread target:
    the worker's thread-local span stack is empty, so the span roots a
    fresh trace instead of joining the crossing request."""
    rep = _scan_tree(tmp_path, {"serve/worker.py": """
        import threading
        from ..obs import trace as _trace

        class Runtime:
            def start(self):
                self._t = threading.Thread(target=self._dispatch_loop,
                                           daemon=True)
                self._t.start()

            def _dispatch_loop(self):
                while True:
                    batch = self._pop()
                    _trace.record_span("serve.batch", 0.001, rows=8)
    """}, rules=["R21"])
    assert len(rep.findings) == 1
    assert rep.findings[0].rule == "R21"
    assert "_dispatch_loop" in rep.findings[0].message


def test_r21_positive_executor_submitted_span_context_manager(tmp_path):
    """executor.submit(fn) marks fn as a thread entry too; a bare
    span() context manager there is the same empty-stack trap."""
    rep = _scan_tree(tmp_path, {"continual/roller.py": """
        from ..obs import trace as _trace

        class Runner:
            def kick(self, pool):
                pool.submit(self._rollover)

            def _rollover(self):
                with _trace.span("continual.rollover", mode="refit"):
                    self._do_roll()
    """}, rules=["R21"])
    assert len(rep.findings) == 1
    assert rep.findings[0].rule == "R21"


def test_r21_negative_explicit_ctx_and_links(tmp_path):
    """Spans that carry their causal identity explicitly — ctx= on the
    leg span, links= adopting the batch members — are the designed
    cross-thread pattern and pass clean."""
    rep = _scan_tree(tmp_path, {"serve/worker.py": """
        import threading
        from ..obs import trace as _trace

        class Runtime:
            def start(self):
                self._t = threading.Thread(target=self._dispatch_loop,
                                           daemon=True)

            def _dispatch_loop(self):
                while True:
                    batch = self._pop()
                    leg = batch[0].ctx.sibling()
                    _trace.record_span("serve.batch", 0.001, ctx=leg,
                                       links=[r.ctx for r in batch])
    """}, rules=["R21"])
    assert rep.findings == []


def test_r21_negative_outside_scoped_dirs_and_non_entry(tmp_path):
    """Both escapes at once: the identical implicit span OUTSIDE
    serve//continual/ paths is out of scope, and a function never handed
    to Thread/submit is not an entry even inside them."""
    rep = _scan_tree(tmp_path, {
        "obs/exporter.py": """
            import threading
            from . import trace as _trace

            def start(self):
                threading.Thread(target=_flush_loop, daemon=True).start()

            def _flush_loop():
                _trace.record_span("obs.flush", 0.001)
        """,
        "serve/helpers.py": """
            from ..obs import trace as _trace

            def note_admit(runtime):
                _trace.record_span("serve.admit", 0.0001)
        """}, rules=["R21"])
    assert rep.findings == []


def test_r21_pragma_suppression(tmp_path):
    rep = _scan_tree(tmp_path, {"serve/worker.py": """
        import threading
        from ..obs import trace as _trace

        class Runtime:
            def start(self):
                self._t = threading.Thread(target=self._gc_loop, daemon=True)

            def _gc_loop(self):
                while True:
                    _trace.record_span("serve.gc", 0.001)  # jaxlint: disable=R21 (fixture: maintenance sweep owns no request; rootless by design)
    """}, rules=["R21"])
    assert rep.findings == []
    assert len(rep.suppressed) == 1
