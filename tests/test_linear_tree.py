"""Linear tree tests (reference: linear_tree_learner.cpp; VERDICT item 8)."""

import numpy as np
import pytest

import lightgbm_tpu as lgb

pytestmark = pytest.mark.slow


def _piecewise_linear(n=5000, seed=0):
    rng = np.random.RandomState(seed)
    x0 = rng.uniform(-2, 2, n)
    x1 = rng.uniform(-2, 2, n)
    # slope depends on the sign of x1 -> a 2-leaf linear tree nails it
    y = np.where(x1 > 0, 3.0 * x0 + 1.0, -2.0 * x0 - 0.5) + 0.05 * rng.randn(n)
    X = np.stack([x0, x1], axis=1).astype(np.float32)
    return X, y


@pytest.mark.parametrize("mode", ["strict", "rounds"])
def test_linear_tree_beats_constant_on_piecewise_linear(mode):
    X, y = _piecewise_linear()
    mses = {}
    for lin in (False, True):
        ds = lgb.Dataset(X, label=y, params={"linear_tree": lin})
        bst = lgb.Booster(
            params={"objective": "regression", "num_leaves": 4, "verbosity": -1,
                    "linear_tree": lin, "learning_rate": 0.5,
                    "tree_growth_mode": mode, "min_data_in_leaf": 20},
            train_set=ds,
        )
        for _ in range(20):
            bst.update()
        p = bst.predict(X)
        mses[lin] = float(np.mean((p - y) ** 2))
    # constant leaves cannot express the slopes at 4 leaves; linear leaf
    # models (fit on path features, like the reference) can once the slope
    # features appear on paths
    assert mses[True] < mses[False] * 0.25
    assert mses[True] < 0.05


def test_linear_tree_model_roundtrip():
    X, y = _piecewise_linear()
    ds = lgb.Dataset(X, label=y, params={"linear_tree": True})
    bst = lgb.Booster(
        params={"objective": "regression", "num_leaves": 4, "verbosity": -1,
                "linear_tree": True, "learning_rate": 0.5},
        train_set=ds,
    )
    for _ in range(5):
        bst.update()
    p = bst.predict(X)
    s = bst.model_to_string()
    assert "is_linear=1" in s and "leaf_coeff=" in s
    bst2 = lgb.Booster(model_str=s)
    assert np.abs(p - bst2.predict(X)).max() < 1e-6


def test_linear_tree_nan_rows_fall_back_to_constant():
    X, y = _piecewise_linear()
    ds = lgb.Dataset(X, label=y, params={"linear_tree": True})
    bst = lgb.Booster(
        params={"objective": "regression", "num_leaves": 4, "verbosity": -1,
                "linear_tree": True},
        train_set=ds,
    )
    for _ in range(3):
        bst.update()
    Xn = X[:50].copy()
    Xn[:, 0] = np.nan  # x0 used in leaf models -> constant fallback
    p = bst.predict(Xn)
    assert np.all(np.isfinite(p))
