"""Serving-fleet resilience pins (round 23 — lightgbm_tpu/serve/fleet).

The fleet contract under chaos: N replicas behind ONE admission queue
lose ZERO admitted requests when a replica dies, hangs, or fails a
dispatch — every response stays BITWISE equal to the solo
``ServingRuntime`` (itself bitwise equal to ``Booster.predict``), a
failed batch's requests are requeued EXACTLY once onto a healthy
replica, the circuit breaker never ejects the LAST healthy replica, a
replacement replica warms its packs BEFORE joining rotation, and the
warm per-batch budget (1 dispatch + 1 accounted sync) holds at any
replica count.  The whole file runs under the session-wide STRICT lock
sanitizer (conftest) with telemetry and span tracing on — resilience
machinery that only works with observability off would be theater.

Fault-injection notes (utils/faults.py): the serve sites are
call-counted — sequential submits coalesce into ONE batch, and each
replica execution touches every serve site twice (stage A before the
dispatch, stage B after), so ``<site>:0`` arms stage A of the first
armed execution and ``<site>:1`` stage B.  ``fire()`` only advances a
site's counter while the site is armed, so tests warm the executables
FIRST (env unset), then arm the env — the warm traffic never skews the
counters.
"""

import json
import os
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.obs import metrics as obs
from lightgbm_tpu.serve import (DeadlineExceeded, Overloaded, ServingFleet,
                                ServingRuntime)
from lightgbm_tpu.utils import faults as flt
from lightgbm_tpu.utils.sanitizer import DispatchCounter


@pytest.fixture(autouse=True)
def _fresh_state():
    from lightgbm_tpu.obs import server as _srv
    from lightgbm_tpu.obs import trace as _trc

    obs.reset()
    _trc.reset_trace()
    os.environ.pop("LGBMTPU_FAULT", None)
    flt.reset()
    yield
    os.environ.pop("LGBMTPU_FAULT", None)
    flt.reset()
    _srv.stop_server()
    obs.reset()
    _trc.reset_trace()


def _binary_booster(n=400, f=6, rounds=4, seed=0):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, f)
    y = (X[:, 0] + 0.5 * X[:, 1] > 0).astype(float)
    bst = lgb.Booster(params={"objective": "binary", "num_leaves": 7,
                              "verbosity": -1},
                      train_set=lgb.Dataset(X, label=y))
    for _ in range(rounds):
        bst.update()
    return bst, X


def _fleet(bst, replicas=2, **kw):
    kw.setdefault("max_wait_ms", 20)
    kw.setdefault("shed_unhealthy", False)
    kw.setdefault("hang_timeout_ms", 30_000)  # hang tests override
    kw.setdefault("hedge_ms", 0)
    return ServingFleet(bst, replicas=replicas, **kw)


def _warm(fl, X):
    """One round of traffic with NO fault armed: compiles the coalesced
    bucket executables so chaos rounds dispatch in milliseconds (a cold
    jit compile under a short hang timeout would false-positive the
    watchdog) and leaves the fault call-counters untouched (fire() only
    counts armed sites)."""
    assert "LGBMTPU_FAULT" not in os.environ
    got = fl.predict(X[:16], raw_score=True, timeout=120)
    assert got.shape == (16,)


def _arm(spec):
    os.environ["LGBMTPU_FAULT"] = spec


# ---------------------------------------------------------------------------
# parity: fleet == solo runtime == Booster.predict, bitwise
# ---------------------------------------------------------------------------

def test_fleet_bitwise_parity_vs_solo_runtime():
    bst, X = _binary_booster()
    slices = [X[i * 16:(i + 1) * 16] for i in range(6)]
    with ServingRuntime(bst, max_wait_ms=20, shed_unhealthy=False) as solo:
        want = [solo.predict(s, raw_score=True, timeout=120) for s in slices]
    for w, s in zip(want, slices):
        assert np.array_equal(w, bst.predict(s, raw_score=True))
    fl = _fleet(bst, replicas=2)
    try:
        got = [fl.predict(s, raw_score=True, timeout=120) for s in slices]
    finally:
        fl.stop()
    for w, g in zip(want, got):
        assert np.array_equal(w, g), "fleet diverged from solo runtime"


def test_engine_serve_entry_returns_fleet():
    bst, X = _binary_booster()
    rt = lgb.serve(bst, {"serve_replicas": 2, "serve_max_wait_ms": 10})
    try:
        assert isinstance(rt, ServingFleet)
        got = rt.predict(X[:8], raw_score=True, timeout=120)
        assert np.array_equal(got, bst.predict(X[:8], raw_score=True))
        assert rt.stats()["replicas"] == {0: "active", 1: "active"}
    finally:
        rt.stop()


# ---------------------------------------------------------------------------
# THE chaos matrix: death / hang at each pipeline stage x replica counts
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("replicas", [1, 2, 4])
@pytest.mark.parametrize("stage", [0, 1], ids=["stageA", "stageB"])
@pytest.mark.parametrize("site", ["replica_death", "replica_hang"])
def test_chaos_matrix_zero_loss_bitwise(site, stage, replicas):
    """A replica killed or wedged at EITHER side of the dispatch loses
    zero admitted requests: the inflight batch requeues onto a healthy
    replica (or the restarted one, at replicas=1) and every response is
    bitwise equal to Booster.predict."""
    bst, X = _binary_booster()
    slices = [X[i * 8:(i + 1) * 8] for i in range(4)]
    want = [bst.predict(s, raw_score=True) for s in slices]
    fl = _fleet(bst, replicas=replicas,
                hang_timeout_ms=1_500, restart_backoff_ms=50,
                max_wait_ms=60)
    try:
        _warm(fl, X)
        _arm(f"{site}:{stage}")
        handles = [fl.submit(s, raw_score=True) for s in slices]
        got = [fl.result(h, timeout=120) for h in handles]
        for w, g in zip(want, got):
            assert np.array_equal(w, g), f"{site}@{stage} diverged"
        assert obs.counter("faults_injected_total").value == 1
        dead = ("serve_replica_hangs_total" if site == "replica_hang"
                else "serve_replica_deaths_total")
        assert obs.counter(dead).value == 1
        assert obs.counter("serve_requeues_total").value >= 1
        # round 24: every chaos cell leaves its trace — the lost leg's
        # span wears the failure kind and the requeue links its members,
        # so the matrix reconstructs from the export alone
        from lightgbm_tpu.obs import trace as _trc

        want = "hang" if site == "replica_hang" else "death"
        legs = [s for s in _trc.spans("serve.leg")
                if s["attrs"].get("outcome") == want]
        assert legs and all("replica" in s["attrs"] for s in legs)
        assert any(s.get("links") for s in legs)
        assert _trc.spans("serve.requeue")
    finally:
        # stop() must return promptly even though the wedged incarnation
        # sleeps forever: the watchdog either marked it hung (skipped at
        # join) or already replaced rep.thread with a fresh incarnation —
        # the daemon is abandoned, never joined
        t0 = time.monotonic()
        fl.stop()
        assert time.monotonic() - t0 < 20, "stop() joined a wedged thread"


def test_replacement_warms_before_rotation_and_restart_counted():
    """After a death the supervisor restarts the replica; the replacement
    re-warms the pack ladder BEFORE taking traffic, so post-recovery
    batches stay on the warm budget."""
    bst, X = _binary_booster()
    fl = _fleet(bst, replicas=2, restart_backoff_ms=30, max_wait_ms=30)
    try:
        _warm(fl, X)
        _arm("replica_death:0")
        got = fl.predict(X[:16], raw_score=True, timeout=120)
        assert np.array_equal(got, bst.predict(X[:16], raw_score=True))
        deadline = time.monotonic() + 30
        while (obs.counter("serve_replica_restarts_total").value < 1
               and time.monotonic() < deadline):
            time.sleep(0.02)
        assert obs.counter("serve_replica_restarts_total").value == 1
        with fl._cv:
            states = [r.state for r in fl._replicas]
        assert states == [0, 0], f"replica not back in rotation: {states}"
        # the restarted fleet serves warm: 1 dispatch + 1 sync per batch
        with DispatchCounter() as d:
            out = fl.predict(X[:16], raw_score=True, timeout=120)
        # read the ledger BEFORE the reference predict below adds to it
        assert d.dispatches == 1 and d.host_syncs == 1
        d.assert_no_recompile("post-restart fleet batch")
        assert np.array_equal(out, bst.predict(X[:16], raw_score=True))
    finally:
        fl.stop()


# ---------------------------------------------------------------------------
# exactly-once requeue + typed failure when the retry is also lost
# ---------------------------------------------------------------------------

def test_requeue_is_exactly_once_then_typed_error():
    """dispatch-failure at stage A requeues the batch once (counted per
    request); when the RETRIED batch dies too, the requests surface a
    typed error — never a second requeue, never a hang."""
    bst, X = _binary_booster()
    slices = [X[0:8], X[8:16]]
    fl = _fleet(bst, replicas=2, max_wait_ms=60, restart_backoff_ms=50)
    try:
        _warm(fl, X)
        # dispatch counter touch 0 = first armed execution's stage A
        # (death touched once there, c0); the REQUEUED execution touches
        # death at stage A (c1) and stage B (c2) — arm c2 to kill the
        # replica right after the retried dispatch
        _arm("replica_dispatch:0,replica_death:2")
        handles = [fl.submit(s, raw_score=True) for s in slices]
        errs = []
        for h in handles:
            with pytest.raises(RuntimeError, match="died"):
                try:
                    fl.result(h, timeout=120)
                except RuntimeError as e:
                    errs.append(e)
                    raise
        assert len(errs) == 2
        # one requeue per request of the failed batch — and ONLY one
        assert obs.counter("serve_requeues_total").value == 2
        assert obs.counter("serve_replica_failures_total").value >= 1
        assert obs.events("serve_requeue")
    finally:
        fl.stop()


def test_retry_budget_exhaustion_degrades_to_shedding():
    """With the retry budget drained a failed batch does NOT requeue: the
    requests fail typed and the exhaustion is counted — a sick fleet
    sheds instead of retry-storming."""
    bst, X = _binary_booster()
    fl = _fleet(bst, replicas=2, max_wait_ms=30)
    try:
        _warm(fl, X)
        with fl._cv:
            fl._retry_tokens = 0.0
        fl._retry_rate = 0.0  # submit must not refill for this pin
        _arm("replica_dispatch:0")
        h = fl.submit(X[:8], raw_score=True)
        with pytest.raises(flt.InjectedFault):
            fl.result(h, timeout=120)
        assert obs.counter("serve_retry_budget_exhausted_total").value == 1
        assert obs.counter("serve_requeues_total").value == 0
    finally:
        fl.stop()


# ---------------------------------------------------------------------------
# circuit breaker: ejection, half-open readmission, last-replica guard
# ---------------------------------------------------------------------------

def test_breaker_ejects_readmits_and_never_ejects_last_replica():
    bst, X = _binary_booster()
    fl = _fleet(bst, replicas=2, trip=1, cooldown_ms=60, max_wait_ms=30)
    try:
        _warm(fl, X)
        _arm("replica_dispatch:0")
        got = fl.predict(X[:8], raw_score=True, timeout=120)
        assert np.array_equal(got, bst.predict(X[:8], raw_score=True))
        assert obs.counter("serve_replica_ejections_total").value == 1
        assert obs.events("serve_replica_eject")
        # cooldown -> half-open -> a probe batch readmits it
        deadline = time.monotonic() + 30
        while (obs.counter("serve_replica_readmissions_total").value < 1
               and time.monotonic() < deadline):
            fl.predict(X[:8], raw_score=True, timeout=120)
            time.sleep(0.02)
        assert obs.counter("serve_replica_readmissions_total").value == 1
        assert obs.events("serve_replica_readmit")
        # the LAST healthy replica is never ejected, whatever its streak
        with fl._cv:
            last = next(r for r in fl._replicas if r.state == 0)
            for other in fl._replicas:
                if other is not last:
                    other.state = 2  # ejected
            last.fail_streak = 99
            fl._breaker_failure_locked(last, time.monotonic())
            assert last.state == 0, "last healthy replica was ejected"
            for other in fl._replicas:
                if other is not last:
                    other.state = 0
        assert obs.counter("serve_replica_ejections_total").value == 1
    finally:
        fl.stop()


# ---------------------------------------------------------------------------
# deadlines and hedging
# ---------------------------------------------------------------------------

def test_deadline_exceeded_is_typed_and_distinct_from_overloaded():
    bst, X = _binary_booster()
    fl = _fleet(bst, replicas=2, deadline_ms=40, start=False)
    h = fl.submit(X[:8])
    time.sleep(0.1)  # never started: the deadline lapses in the queue
    with pytest.raises(DeadlineExceeded) as ei:
        fl.result(h, timeout=10)
    assert not isinstance(ei.value, Overloaded)
    assert ei.value.deadline_ms == pytest.approx(40.0)
    assert obs.counter("serve_deadline_exceeded_total").value == 1
    assert obs.events("serve_deadline")
    fl.stop()


def test_hedge_dispatches_second_copy_and_dedups():
    """A dispatch that outlives the hedge delay gets a second copy on the
    other replica; whichever publishes first wins and the loser's publish
    is skipped — responses stay correct and are delivered once."""
    bst, X = _binary_booster()
    # a wedged stage-A dispatch is the deterministic slow replica; the
    # 25 ms hedge fires long before the 2 s hang watchdog, which then
    # reaps the wedged incarnation so stop() stays prompt
    fl = _fleet(bst, replicas=2, hedge_ms=25, hang_timeout_ms=2_000,
                restart_backoff_ms=50)
    try:
        _warm(fl, X)
        _arm("replica_hang:0")
        got = fl.predict(X[:16], raw_score=True, timeout=120)
        assert np.array_equal(got, bst.predict(X[:16], raw_score=True))
        assert obs.counter("serve_hedges_total").value >= 1
        assert obs.events("serve_hedge")
        # the hedge answered the caller; the watchdog reaps the wedged
        # replica afterwards without disturbing the delivered response
        deadline = time.monotonic() + 30
        while (obs.counter("serve_replica_hangs_total").value < 1
               and time.monotonic() < deadline):
            time.sleep(0.02)
        assert obs.counter("serve_replica_hangs_total").value == 1
    finally:
        fl.stop()


# ---------------------------------------------------------------------------
# warm budget: 1 dispatch + 1 accounted sync per fleet batch
# ---------------------------------------------------------------------------

def test_fleet_warm_batch_budget_with_telemetry_and_tracing_on():
    from lightgbm_tpu.obs import trace as _trc

    bst, X = _binary_booster()
    fl = _fleet(bst, replicas=2, max_wait_ms=120)
    try:
        _warm(fl, X)
        with DispatchCounter() as d:
            got = fl.predict(X[:16], raw_score=True, timeout=120)
        # read the ledger BEFORE the reference predict below adds to it
        assert d.dispatches == 1, d.dispatches
        assert d.host_syncs == 1, d.host_syncs
        d.assert_no_recompile("warm fleet batch (strict lock tracing on)")
        assert np.array_equal(got, bst.predict(X[:16], raw_score=True))
        spans = _trc.spans("serve.batch")
        assert spans and "replica" in spans[-1]["attrs"]
        assert obs.histogram("serve_replica_batch_ms").count >= 1
    finally:
        fl.stop()


# ---------------------------------------------------------------------------
# stop() drains: admitted requests are answered or failed typed (bugfix pin)
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("cls", [ServingRuntime, ServingFleet],
                         ids=["solo", "fleet"])
def test_stop_under_load_answers_every_admitted_request(cls):
    """stop() racing live submitters: every request admitted before (or
    during) shutdown gets a result or a TYPED error promptly — no
    stranded Event, no TimeoutError-only resolution."""
    bst, X = _binary_booster()
    kw = {"max_wait_ms": 10, "shed_unhealthy": False}
    if cls is ServingFleet:
        kw["replicas"] = 2
    rt = cls(bst, **kw)
    rt.predict(X[:16], raw_score=True, timeout=120)  # warm
    outcomes = []
    lock = threading.Lock()

    def caller(i):
        s = X[(i % 20) * 8:(i % 20) * 8 + 8]
        try:
            h = rt.submit(s, raw_score=True)
        except (Overloaded, lgb.LightGBMError, RuntimeError):
            # admission refused typed (shed, or the runtime had already
            # stopped) — a legitimate outcome for a submit racing stop()
            with lock:
                outcomes.append("shed")
            return
        try:
            got = rt.result(h, timeout=30)
            ok = np.array_equal(got, bst.predict(s, raw_score=True))
            with lock:
                outcomes.append("ok" if ok else "WRONG")
        except (lgb.LightGBMError, Overloaded, DeadlineExceeded,
                RuntimeError, flt.InjectedFault):
            with lock:
                outcomes.append("typed")
        except TimeoutError:
            with lock:
                outcomes.append("HUNG")

    threads = [threading.Thread(target=caller, args=(i,)) for i in range(24)]
    for i, t in enumerate(threads):
        t.start()
        if i == 8:
            stopper = threading.Thread(target=rt.stop)
            stopper.start()
    for t in threads:
        t.join(timeout=60)
    stopper.join(timeout=60)
    assert not stopper.is_alive(), "stop() hung under load"
    assert len(outcomes) == 24
    assert "WRONG" not in outcomes
    assert "HUNG" not in outcomes, f"stranded requests: {outcomes}"
    assert outcomes.count("ok") >= 1


# ---------------------------------------------------------------------------
# swap chaos: a failed publish leaves the OLD model serving
# ---------------------------------------------------------------------------

def test_swap_publish_fault_keeps_old_model_serving():
    b1, X = _binary_booster(rounds=2, seed=5)
    b2, _ = _binary_booster(rounds=7, seed=6)
    fl = _fleet(bst=b1, replicas=2)
    try:
        _warm(fl, X)
        _arm("swap_publish:0")
        with pytest.raises(flt.InjectedFault):
            fl.swap_model("default", b2)
        os.environ.pop("LGBMTPU_FAULT", None)
        got = fl.predict(X[:16], raw_score=True, timeout=120)
        assert np.array_equal(got, b1.predict(X[:16], raw_score=True)), \
            "failed publish leaked the replacement model"
    finally:
        fl.stop()


# ---------------------------------------------------------------------------
# /predict front door + /healthz replica table (HTTP satellites)
# ---------------------------------------------------------------------------

def _post(url, body, timeout=60):
    req = urllib.request.Request(
        url, data=json.dumps(body).encode(), method="POST",
        headers={"Content-Type": "application/json"})
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.status, json.loads(resp.read().decode())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read().decode())


def test_http_predict_route_codes_and_parity():
    from lightgbm_tpu.obs import server as _srv

    srv = _srv.start_server(0)
    bst, X = _binary_booster()
    fl = _fleet(bst, replicas=2)
    try:
        _warm(fl, X)
        code, body = _post(srv.url("/predict"),
                           {"rows": X[:8].tolist(), "raw_score": True})
        assert code == 200
        assert np.array_equal(np.asarray(body["predictions"]),
                              bst.predict(X[:8], raw_score=True))
        assert body["rows"] == 8
        code, body = _post(srv.url("/predict"), {"nope": 1})
        assert code == 400 and body["error"] == "bad_request"
        assert obs.counter("serve_http_requests_total").value == 2
        # the fleet's replica table rides on /healthz
        hz = json.load(urllib.request.urlopen(srv.url("/healthz"),
                                              timeout=10))
        assert "serve_fleet" in hz
        reps = hz["serve_fleet"]["replicas"]
        assert len(reps) == 2
        assert {r["state"] for r in reps} == {"active"}
    finally:
        fl.stop()
    # a stopped runtime unregisters its route: 503, not a hang
    code, body = _post(srv.url("/predict"), {"rows": X[:2].tolist()})
    assert code == 503


def test_http_predict_shed_and_deadline_status_codes():
    from lightgbm_tpu.obs import server as _srv

    srv = _srv.start_server(0)
    bst, X = _binary_booster()
    # UNSTARTED tiny queue: requests queue forever -> 429 on overflow and
    # 504 once the deadline lapses.  start() is what registers the route
    # (no workers run here by design), so attach the front door directly
    # max_queue=2: the expired 504 request STAYS queued (nothing dequeues
    # on an unstarted fleet), so the explicit submit below is slot #2
    fl = _fleet(bst, replicas=2, max_queue=2, deadline_ms=300, start=False)
    _srv.set_predict_handler(fl._http_predict)
    try:
        code, body = _post(srv.url("/predict"), {"rows": X[:4].tolist()})
        assert code == 504 and body["error"] == "deadline_exceeded"
        fl.submit(X[:4])  # fills the queue
        code, body = _post(srv.url("/predict"), {"rows": X[:4].tolist()})
        assert code == 429 and body["error"] == "overloaded"
        assert body["reason"] == "queue_full"
    finally:
        fl.stop()


def test_http_predict_unhealthy_is_503():
    from lightgbm_tpu.obs import server as _srv

    srv = _srv.start_server(0)
    bst, X = _binary_booster()
    obs.counter("train_nonfinite_errors_total").inc()  # unhealthy process
    fl = ServingFleet(bst, replicas=2, hedge_ms=0)  # started: route live
    try:
        code, body = _post(srv.url("/predict"), {"rows": X[:4].tolist()})
        assert code == 503 and body["reason"] == "unhealthy"
    finally:
        fl.stop()


# ---------------------------------------------------------------------------
# THE acceptance: open-loop death chaos, zero loss, healthz flips, warm
# budget re-pinned — telemetry + tracing + strict locktrace all ON
# ---------------------------------------------------------------------------

def test_acceptance_open_loop_death_zero_loss_bitwise_and_recovery():
    from lightgbm_tpu.obs import server as _srv
    from lightgbm_tpu.obs import trace as _trc

    srv = _srv.start_server(0)
    bst, X = _binary_booster()
    slices = [X[(i % 24) * 8:(i % 24) * 8 + 8] for i in range(30)]
    with ServingRuntime(bst, max_wait_ms=20, shed_unhealthy=False) as solo:
        want = [solo.predict(s, raw_score=True, timeout=120)
                for s in slices[:4]]
    want += [bst.predict(s, raw_score=True) for s in slices[4:]]

    # 1.5 s restart backoff keeps the degraded /healthz window wide enough
    # for the live poll below to observe it even when warm-up is instant
    # (persistent compile cache) and the poll thread is starved by the 30
    # submitter threads
    fl = _fleet(bst, replicas=2, restart_backoff_ms=1500, max_wait_ms=15,
                max_queue=256)
    got = [None] * len(slices)
    errs = []

    def _healthz():
        return json.load(urllib.request.urlopen(srv.url("/healthz"),
                                                timeout=10))

    def _fleet_problem(hz):
        return [p for p in hz["problems"]
                if p.get("gauge") == "serve_fleet_degraded"]

    try:
        _warm(fl, X)
        _arm("replica_death:0")

        def caller(i):
            try:
                got[i] = fl.predict(slices[i], raw_score=True, timeout=120)
            except BaseException as e:  # noqa: BLE001
                errs.append((i, e))

        threads = [threading.Thread(target=caller, args=(i,))
                   for i in range(len(slices))]
        for t in threads:  # open loop: keep submitting across the death
            t.start()
            time.sleep(0.004)
        # /healthz flips to degraded WHILE the replica is out of rotation
        saw_degraded = False
        deadline = time.monotonic() + 20
        while time.monotonic() < deadline and not saw_degraded:
            hz = _healthz()
            saw_degraded = (hz["status"] == "degraded"
                            and bool(_fleet_problem(hz)))
        assert saw_degraded, "/healthz never showed the fleet degraded"
        for t in threads:
            t.join(timeout=120)
        assert not errs, f"admitted requests were lost: {errs[:3]}"
        for i, (w, g) in enumerate(zip(want, got)):
            assert g is not None, f"request {i} got no response"
            assert np.array_equal(w, g), f"request {i} diverged from solo"
        # the death really happened and was survived
        assert obs.counter("serve_replica_deaths_total").value == 1
        assert obs.counter("serve_requeues_total").value >= 1
        # the replacement rejoined: restart counted, both replicas active,
        # the fleet-degraded condition cleared from /healthz (the injected
        # fault's cumulative degraded marker — faults_injected_total —
        # remains by design: chaos leaves an audit trail)
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            with fl._cv:
                if (all(r.state == 0 for r in fl._replicas)
                        and obs.counter(
                            "serve_replica_restarts_total").value >= 1):
                    break
            time.sleep(0.02)
        assert obs.counter("serve_replica_restarts_total").value == 1
        assert obs.gauge("serve_fleet_degraded").value == 0.0
        hz = _healthz()
        assert not _fleet_problem(hz), hz["problems"]
        assert [p for p in hz["problems"]
                if p.get("counter") == "faults_injected_total"]
        assert all(r["state"] == "active"
                   for r in hz["serve_fleet"]["replicas"])
        # degradation WAS visible while the replica was down
        assert [e for e in obs.events("serve_replica_death")]
        # warm budget re-pinned on the recovered fleet
        with DispatchCounter() as d:
            out = fl.predict(X[:16], raw_score=True, timeout=120)
        assert d.dispatches == 1 and d.host_syncs == 1
        d.assert_no_recompile("recovered fleet warm batch")
        assert np.array_equal(out, bst.predict(X[:16], raw_score=True))
        assert _trc.spans("serve.batch")
        # round 24: the whole death story reconstructs from the trace
        # export alone — the killed dispatch left a serve.leg span
        # (outcome=death) and the requeue decision a serve.requeue span,
        # each naming its replica and linked to its member requests
        legs = [s for s in _trc.spans("serve.leg")
                if s["attrs"].get("outcome") == "death"]
        assert legs, "no serve.leg span for the killed dispatch"
        assert all("replica" in s["attrs"] for s in legs)
        rqs = _trc.spans("serve.requeue")
        assert rqs and rqs[0]["attrs"]["outcome"] == "requeued"
        assert rqs[0].get("links"), "requeue span lost its member links"
        retried = [s for s in _trc.spans("serve.request")
                   if s["attrs"].get("attempt", 0) >= 1
                   and s["attrs"].get("outcome") == "ok"]
        assert retried, "no request span records its retried attempt"
        # one requeued request's CONNECTED trace: its own span, the dead
        # leg, the requeue record, and the winning batch — end to end
        sl = _trc.trace_slice(retried[0]["trace"])
        names = {s["name"] for s in sl}
        assert {"serve.request", "serve.leg", "serve.requeue",
                "serve.batch"} <= names, names
    finally:
        fl.stop()
