"""Out-of-core data-path equivalence (docs round 12, ISSUE 7).

The contract under test: streaming the binned matrix — from a
``save_binary`` cache or a host array, in ANY chunk size — may never
change a trained model by a single bit.

* resident regime (rows <= max_rows_in_hbm budget, or no budget): the
  streamed chunks assemble the identical device matrix, training runs
  the standard growers — bitwise trivially, pinned anyway.
* spill regime (rows > max_rows_in_hbm): the chunked-histogram grower
  (ops/treegrow_ooc.py) is a strict-grower mirror whose seeded
  scatter-add fold is order-preserving — bitwise vs IN-MEMORY training
  on the scatter histogram strategy (max_bin > 64), across chunk sizes
  {1 row, odd, pow2, N}.
* the windowed grower's 1-dispatch/0-sync steady-state budget stays
  green when fed from a stream-assembled (out_of_core resident) matrix.
"""

import numpy as np
import pytest

import lightgbm_tpu as lgb


def _make_data(n=400, f=6, seed=0):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, f)
    y = (X[:, 0] + 0.5 * X[:, 1] + 0.2 * rng.randn(n) > 0).astype(float)
    return X, y


# the spill grower mirrors the strict grower bitwise on the SCATTER
# histogram strategy — max_bin > 64 selects it in-memory too (the wide
# regime out-of-core exists for; ops/treegrow_ooc.py module docstring)
_PARAMS = {
    "objective": "binary",
    "num_leaves": 7,
    "max_bin": 255,
    "verbosity": -1,
    "feature_pre_filter": False,  # scans the host matrix OOC never holds
    "min_data_in_leaf": 5,
}


def _train_model_str(train_set, rounds=3, **extra):
    params = dict(_PARAMS)
    params.update(extra)
    bst = lgb.Booster(params=params, train_set=train_set)
    for _ in range(rounds):
        bst.update()
    return bst, bst.model_to_string()


# ---------------------------------------------------------------------------
# streaming reader
# ---------------------------------------------------------------------------

def test_bin_cache_stream_round_trips_the_matrix(tmp_path):
    """Chunked sequential reads of the npz member reassemble the exact
    binned matrix — including through the REUSED buffer (consumers that
    copy per chunk see stable data)."""
    from lightgbm_tpu.io.stream import BinCacheStream

    X, y = _make_data(n=123, f=5)
    ds = lgb.Dataset(X, label=y, params={"max_bin": 255})
    cache = str(tmp_path / "ds.bin")
    ds.construct()
    ds.save_binary(cache)
    want = np.asarray(ds.bins)

    stream = BinCacheStream(cache)
    assert stream.shape == want.shape
    for chunk_rows in (1, 7, 64, 123, 200):
        got = np.zeros_like(want)
        for lo, view in stream.chunks(chunk_rows):
            got[lo:lo + view.shape[0]] = view  # copy out of the reused buf
        np.testing.assert_array_equal(got, want)


def test_prefetch_device_preserves_chunks_despite_buffer_reuse():
    """The one-deep prefetch uploads with copy semantics: the reused host
    buffer being refilled for chunk k+1 must not corrupt chunk k."""
    from lightgbm_tpu.io.stream import prefetch_device

    rng = np.random.RandomState(1)
    data = rng.randint(0, 100, (50, 4)).astype(np.int16)
    buf = np.empty((8, 4), np.int16)

    def reusing_chunks():
        for lo in range(0, 50, 8):
            m = min(8, 50 - lo)
            buf[:m] = data[lo:lo + m]
            yield lo, buf[:m]

    seen = np.zeros_like(data)
    for lo, m, dev in prefetch_device(reusing_chunks(), pad_rows=8):
        seen[lo:lo + m] = np.asarray(dev)[:m]
    np.testing.assert_array_equal(seen, data)


# ---------------------------------------------------------------------------
# resident regime: streamed ingest, standard growers
# ---------------------------------------------------------------------------

def test_resident_ooc_from_cache_is_bitwise_across_chunk_sizes(tmp_path):
    X, y = _make_data()
    n = X.shape[0]
    mem_ds = lgb.Dataset(X, label=y, params=dict(_PARAMS))
    _, want = _train_model_str(mem_ds)

    base = lgb.Dataset(X, label=y, params=dict(_PARAMS))
    cache = str(tmp_path / "train.bin")
    base.construct()
    base.save_binary(cache)

    for chunk in (1, 37, 128, n):  # 1 row, odd, pow2, all-N
        ds = lgb.Dataset(cache, params=dict(
            _PARAMS, out_of_core=True, out_of_core_chunk_rows=chunk))
        bst, got = _train_model_str(ds)
        assert got == want, f"resident OOC diverged at chunk_rows={chunk}"
        # the ingest never materialized a host matrix
        assert ds.bins is None
        assert ds.bins_device is not None and not ds.ooc_spill


def test_resident_ooc_from_ndarray_uploads_whole_matrix():
    """out_of_core=True on an in-memory ndarray (no cache to stream from)
    in the resident regime takes the direct whole-array upload — host
    bins already exist, chunked placement would be pure overhead — and
    the device matrix is identical to the plain in-memory path's."""
    X, y = _make_data()
    mem = lgb.Dataset(X, label=y, params=dict(_PARAMS)).construct()
    ooc = lgb.Dataset(X, label=y, params=dict(
        _PARAMS, out_of_core=True)).construct()
    assert not ooc.ooc_spill and ooc.bins is not None
    np.testing.assert_array_equal(
        np.asarray(ooc.bins_device), np.asarray(mem.bins_device))


def test_resident_ooc_whole_matrix_paths_materialize_from_device(tmp_path):
    """subset()/add_features_from() (and other whole-matrix consumers)
    work on a resident out_of_core dataset by materializing ONE host copy
    from the assembled device matrix — they do not crash on bins=None."""
    X, y = _make_data(n=150, f=4)
    base = lgb.Dataset(X, label=y, params=dict(_PARAMS))
    cache = str(tmp_path / "r.bin")
    base.construct()
    base.save_binary(cache)

    ds = lgb.Dataset(cache, params=dict(_PARAMS, out_of_core=True))
    ds.construct()
    assert ds.bins is None
    sub = ds.subset([0, 5, 9, 44])
    np.testing.assert_array_equal(sub.bins, np.asarray(base.bins)[[0, 5, 9, 44]])

    ds2 = lgb.Dataset(cache, params=dict(_PARAMS, out_of_core=True))
    ds2.construct()
    other = lgb.Dataset(X[:, :2], label=y, params=dict(_PARAMS))
    other.construct()
    joined = ds2.add_features_from(other)
    assert joined.bins.shape == (150, 6)


def test_spill_ooc_whole_matrix_paths_raise_envelope_error(tmp_path):
    """A cache-streamed spill dataset has NO whole matrix anywhere — the
    same paths raise the clear envelope error, not a raw TypeError."""
    X, y = _make_data(n=200, f=4)
    base = lgb.Dataset(X, label=y, params=dict(_PARAMS))
    cache = str(tmp_path / "s.bin")
    base.construct()
    base.save_binary(cache)
    ds = lgb.Dataset(cache, params=dict(
        _PARAMS, out_of_core=True, max_rows_in_hbm=50))
    ds.construct()
    assert ds.ooc_spill and ds.bins is None and ds.bins_device is None
    with pytest.raises(lgb.basic.LightGBMError, match="spill regime"):
        ds.subset([0, 1, 2])
    other = lgb.Dataset(X[:, :2], label=y, params=dict(_PARAMS))
    with pytest.raises(lgb.basic.LightGBMError, match="spill regime"):
        ds.add_features_from(other)


# ---------------------------------------------------------------------------
# spill regime: chunked-histogram training
# ---------------------------------------------------------------------------

def test_spill_ooc_is_bitwise_identical_to_in_memory_training(tmp_path):
    """The headline equivalence (ISSUE acceptance): rows exceed the HBM
    budget, the matrix is never device-resident, and the trained model is
    BIT-identical to plain in-memory training — across chunk sizes
    {1, odd, pow2, N}, from both chunk sources (host array and cache)."""
    X, y = _make_data()
    n = X.shape[0]
    mem_ds = lgb.Dataset(X, label=y, params=dict(_PARAMS))
    _, want = _train_model_str(mem_ds)

    base = lgb.Dataset(X, label=y, params=dict(_PARAMS))
    cache = str(tmp_path / "train.bin")
    base.construct()
    base.save_binary(cache)

    for chunk in (1, 37, 128, n):
        ds = lgb.Dataset(cache, params=dict(
            _PARAMS, out_of_core=True, max_rows_in_hbm=n // 4,
            out_of_core_chunk_rows=chunk))
        bst, got = _train_model_str(ds)
        assert ds.ooc_spill and ds.bins_device is None
        assert got == want, f"spill OOC diverged at chunk_rows={chunk}"

    # host-array source (in-memory data whose DEVICE residency is capped)
    ds = lgb.Dataset(X, label=y, params=dict(
        _PARAMS, out_of_core=True, max_rows_in_hbm=100,
        out_of_core_chunk_rows=53))
    _, got = _train_model_str(ds)
    assert ds.ooc_spill
    assert got == want


def test_spill_ooc_with_bagging_and_feature_fraction(tmp_path):
    """Row/feature sampling rides the resident vectors, not the streamed
    matrix — sampled runs must stay bitwise too."""
    X, y = _make_data(n=350, seed=3)
    extra = dict(bagging_fraction=0.7, bagging_freq=1, feature_fraction=0.8)
    mem_ds = lgb.Dataset(X, label=y, params=dict(_PARAMS))
    _, want = _train_model_str(mem_ds, **extra)

    ds = lgb.Dataset(X, label=y, params=dict(
        _PARAMS, out_of_core=True, max_rows_in_hbm=64,
        out_of_core_chunk_rows=41))
    _, got = _train_model_str(ds, **extra)
    assert got == want


def test_spill_predictions_match_in_memory(tmp_path):
    X, y = _make_data(n=300, seed=5)
    mem_ds = lgb.Dataset(X, label=y, params=dict(_PARAMS))
    bst_mem, _ = _train_model_str(mem_ds)
    ds = lgb.Dataset(X, label=y, params=dict(
        _PARAMS, out_of_core=True, max_rows_in_hbm=50,
        out_of_core_chunk_rows=64))
    bst_ooc, _ = _train_model_str(ds)
    np.testing.assert_array_equal(
        bst_mem.predict(X), bst_ooc.predict(X))


def test_spill_envelope_raises_on_unsupported_features():
    X, y = _make_data(n=200)
    ds = lgb.Dataset(X, label=y, params=dict(
        _PARAMS, out_of_core=True, max_rows_in_hbm=50))
    with pytest.raises(ValueError, match="out_of_core spill"):
        lgb.Booster(params=dict(_PARAMS, out_of_core=True,
                                max_rows_in_hbm=50,
                                monotone_constraints=[1, 0, 0, 0, 0, 0]),
                    train_set=ds)


def test_spill_dispatch_accounting(tmp_path):
    """The spill grower's cost model is explicit: ceil(N/chunk) chunk
    dispatches per pass, 1 root pass + 1 pass per split, one accounted
    pull per split decision — all visible to the sanitizer ledger."""
    import jax.numpy as jnp

    from lightgbm_tpu.ops.split import SplitParams
    from lightgbm_tpu.ops.treegrow_ooc import grow_tree_ooc
    from lightgbm_tpu.io.stream import array_chunks
    from lightgbm_tpu.binning import DatasetBinner

    X, y = _make_data(n=256, f=5, seed=7)
    binner = DatasetBinner.fit(X, max_bin=255)
    bins = binner.transform(X)
    n, f = bins.shape
    stats = {}
    tree, leaf_id = grow_tree_ooc(
        lambda: array_chunks(bins, 64), n, f,
        jnp.asarray(0.6 * (y - 0.5), jnp.float32),
        jnp.ones((n,), jnp.float32),
        jnp.ones((n,), bool), jnp.ones((n,), jnp.float32),
        jnp.ones((f,), bool),
        jnp.asarray(binner.num_bins_per_feature),
        jnp.asarray(binner.missing_bin_per_feature),
        num_leaves=7, num_bins=256, params=SplitParams(min_data_in_leaf=5.0),
        chunk_rows=64, stats=stats)
    assert int(tree.num_leaves) > 1
    assert stats["passes"] == stats["splits"] + 1
    assert stats["chunks"] == stats["passes"] * 4  # 256 rows / 64-row chunks
    assert leaf_id.shape == (n,)


# ---------------------------------------------------------------------------
# the windowed budget pin with out_of_core on (resident regime)
# ---------------------------------------------------------------------------

def test_windowed_budget_green_on_stream_assembled_matrix(tmp_path):
    """ISSUE acceptance: the steady-state windowed budget (1 dispatch /
    0 syncs / 0 retraces per round) holds when the grower's bins come
    from an out_of_core stream-assembled device matrix — the chunk feed
    happens at ingest, the round loop's async-info protocol is
    untouched."""
    import jax
    import jax.numpy as jnp

    from lightgbm_tpu.ops.split import SplitParams
    from lightgbm_tpu.ops.treegrow_windowed import grow_tree_windowed
    from lightgbm_tpu.utils.sanitizer import DispatchCounter

    rng = np.random.RandomState(11)
    n, f = 900, 8
    X = rng.randn(n, f)
    y = X @ rng.randn(f) + 0.2 * rng.randn(n)
    mem = lgb.Dataset(X, label=y, params={"max_bin": 31})
    mem.construct()
    cache = str(tmp_path / "w.bin")
    mem.save_binary(cache)
    ooc = lgb.Dataset(cache, params={
        "max_bin": 31, "out_of_core": True, "out_of_core_chunk_rows": 111})
    ooc.construct()
    # the stream-assembled matrix IS the in-memory matrix
    np.testing.assert_array_equal(
        np.asarray(ooc.bins_device), np.asarray(mem.bins_device))

    bins_t = ooc.bins_device_t()
    kw = dict(
        row_mask=jnp.ones((n,), bool),
        sample_weight=jnp.ones((n,), jnp.float32),
        feature_mask=jnp.ones((f,), bool),
        num_bins_pf=jnp.asarray(ooc.binner.num_bins_per_feature),
        missing_bin_pf=jnp.asarray(ooc.binner.missing_bin_per_feature),
    )
    static = dict(num_leaves=15, num_bins=32, params=SplitParams(
        min_data_in_leaf=5.0), leaf_tile=4, use_pallas=False)
    grads = [jnp.asarray(0.6 * y + 0.05 * k, jnp.float32) for k in range(2)]
    tree, leaf = grow_tree_windowed(bins_t, grads[0], kw["sample_weight"],
                                    **kw, **static)
    jax.block_until_ready(leaf)

    stats = {}
    with DispatchCounter() as d:
        tree, leaf = grow_tree_windowed(bins_t, grads[1],
                                        kw["sample_weight"], **kw, **static,
                                        stats=stats)
        jax.block_until_ready(leaf)
    assert stats["rounds"] >= 3, stats
    d.assert_round_budget(stats["rounds"], what="windowed rounds on OOC bins")
    assert stats["host_syncs"] == 0, stats
    assert stats["retries"] == 0, stats
    d.assert_no_recompile("windowed rounds on a stream-assembled matrix")
