"""Out-of-core data-path equivalence (docs round 12, ISSUE 7).

The contract under test: streaming the binned matrix — from a
``save_binary`` cache or a host array, in ANY chunk size — may never
change a trained model by a single bit.

* resident regime (rows <= max_rows_in_hbm budget, or no budget): the
  streamed chunks assemble the identical device matrix, training runs
  the standard growers — bitwise trivially, pinned anyway.
* spill regime (rows > max_rows_in_hbm): the chunked-histogram grower
  (ops/treegrow_ooc.py) is a strict-grower mirror whose seeded
  scatter-add fold is order-preserving — bitwise vs IN-MEMORY training
  on the scatter histogram strategy (max_bin > 64), across chunk sizes
  {1 row, odd, pow2, N}.
* the windowed grower's 1-dispatch/0-sync steady-state budget stays
  green when fed from a stream-assembled (out_of_core resident) matrix.
"""

import os

import numpy as np
import pytest

import lightgbm_tpu as lgb


def _make_data(n=400, f=6, seed=0):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, f)
    y = (X[:, 0] + 0.5 * X[:, 1] + 0.2 * rng.randn(n) > 0).astype(float)
    return X, y


# the spill grower mirrors the strict grower bitwise on the SCATTER
# histogram strategy — max_bin > 64 selects it in-memory too (the wide
# regime out-of-core exists for; ops/treegrow_ooc.py module docstring)
_PARAMS = {
    "objective": "binary",
    "num_leaves": 7,
    "max_bin": 255,
    "verbosity": -1,
    "feature_pre_filter": False,  # scans the host matrix OOC never holds
    "min_data_in_leaf": 5,
}


def _train_model_str(train_set, rounds=3, **extra):
    params = dict(_PARAMS)
    params.update(extra)
    bst = lgb.Booster(params=params, train_set=train_set)
    for _ in range(rounds):
        bst.update()
    return bst, bst.model_to_string()


# ---------------------------------------------------------------------------
# streaming reader
# ---------------------------------------------------------------------------

def test_bin_cache_stream_round_trips_the_matrix(tmp_path):
    """Chunked sequential reads of the npz member reassemble the exact
    binned matrix — including through the REUSED buffer (consumers that
    copy per chunk see stable data)."""
    from lightgbm_tpu.io.stream import BinCacheStream

    X, y = _make_data(n=123, f=5)
    ds = lgb.Dataset(X, label=y, params={"max_bin": 255})
    cache = str(tmp_path / "ds.bin")
    ds.construct()
    ds.save_binary(cache)
    want = np.asarray(ds.bins)

    stream = BinCacheStream(cache)
    assert stream.shape == want.shape
    for chunk_rows in (1, 7, 64, 123, 200):
        got = np.zeros_like(want)
        for lo, view in stream.chunks(chunk_rows):
            got[lo:lo + view.shape[0]] = view  # copy out of the reused buf
        np.testing.assert_array_equal(got, want)


def test_prefetch_device_preserves_chunks_despite_buffer_reuse():
    """The one-deep prefetch uploads with copy semantics: the reused host
    buffer being refilled for chunk k+1 must not corrupt chunk k."""
    from lightgbm_tpu.io.stream import prefetch_device

    rng = np.random.RandomState(1)
    data = rng.randint(0, 100, (50, 4)).astype(np.int16)
    buf = np.empty((8, 4), np.int16)

    def reusing_chunks():
        for lo in range(0, 50, 8):
            m = min(8, 50 - lo)
            buf[:m] = data[lo:lo + m]
            yield lo, buf[:m]

    seen = np.zeros_like(data)
    for lo, m, dev in prefetch_device(reusing_chunks(), pad_rows=8):
        seen[lo:lo + m] = np.asarray(dev)[:m]
    np.testing.assert_array_equal(seen, data)


# ---------------------------------------------------------------------------
# resident regime: streamed ingest, standard growers
# ---------------------------------------------------------------------------

def test_resident_ooc_from_cache_is_bitwise_across_chunk_sizes(tmp_path):
    X, y = _make_data()
    n = X.shape[0]
    mem_ds = lgb.Dataset(X, label=y, params=dict(_PARAMS))
    _, want = _train_model_str(mem_ds)

    base = lgb.Dataset(X, label=y, params=dict(_PARAMS))
    cache = str(tmp_path / "train.bin")
    base.construct()
    base.save_binary(cache)

    for chunk in (1, 37, 128, n):  # 1 row, odd, pow2, all-N
        ds = lgb.Dataset(cache, params=dict(
            _PARAMS, out_of_core=True, out_of_core_chunk_rows=chunk))
        bst, got = _train_model_str(ds)
        assert got == want, f"resident OOC diverged at chunk_rows={chunk}"
        # the ingest never materialized a host matrix
        assert ds.bins is None
        assert ds.bins_device is not None and not ds.ooc_spill


def test_resident_ooc_from_ndarray_uploads_whole_matrix():
    """out_of_core=True on an in-memory ndarray (no cache to stream from)
    in the resident regime takes the direct whole-array upload — host
    bins already exist, chunked placement would be pure overhead — and
    the device matrix is identical to the plain in-memory path's."""
    X, y = _make_data()
    mem = lgb.Dataset(X, label=y, params=dict(_PARAMS)).construct()
    ooc = lgb.Dataset(X, label=y, params=dict(
        _PARAMS, out_of_core=True)).construct()
    assert not ooc.ooc_spill and ooc.bins is not None
    np.testing.assert_array_equal(
        np.asarray(ooc.bins_device), np.asarray(mem.bins_device))


def test_resident_ooc_whole_matrix_paths_materialize_from_device(tmp_path):
    """subset()/add_features_from() (and other whole-matrix consumers)
    work on a resident out_of_core dataset by materializing ONE host copy
    from the assembled device matrix — they do not crash on bins=None."""
    X, y = _make_data(n=150, f=4)
    base = lgb.Dataset(X, label=y, params=dict(_PARAMS))
    cache = str(tmp_path / "r.bin")
    base.construct()
    base.save_binary(cache)

    ds = lgb.Dataset(cache, params=dict(_PARAMS, out_of_core=True))
    ds.construct()
    assert ds.bins is None
    sub = ds.subset([0, 5, 9, 44])
    np.testing.assert_array_equal(sub.bins, np.asarray(base.bins)[[0, 5, 9, 44]])

    ds2 = lgb.Dataset(cache, params=dict(_PARAMS, out_of_core=True))
    ds2.construct()
    other = lgb.Dataset(X[:, :2], label=y, params=dict(_PARAMS))
    other.construct()
    joined = ds2.add_features_from(other)
    assert joined.bins.shape == (150, 6)


def test_spill_ooc_whole_matrix_paths_raise_envelope_error(tmp_path):
    """A cache-streamed spill dataset has NO whole matrix anywhere — the
    same paths raise the clear envelope error, not a raw TypeError."""
    X, y = _make_data(n=200, f=4)
    base = lgb.Dataset(X, label=y, params=dict(_PARAMS))
    cache = str(tmp_path / "s.bin")
    base.construct()
    base.save_binary(cache)
    ds = lgb.Dataset(cache, params=dict(
        _PARAMS, out_of_core=True, max_rows_in_hbm=50))
    ds.construct()
    assert ds.ooc_spill and ds.bins is None and ds.bins_device is None
    with pytest.raises(lgb.basic.LightGBMError, match="spill regime"):
        ds.subset([0, 1, 2])
    other = lgb.Dataset(X[:, :2], label=y, params=dict(_PARAMS))
    with pytest.raises(lgb.basic.LightGBMError, match="spill regime"):
        ds.add_features_from(other)


# ---------------------------------------------------------------------------
# spill regime: chunked-histogram training
# ---------------------------------------------------------------------------

def test_spill_ooc_is_bitwise_identical_to_in_memory_training(tmp_path):
    """The headline equivalence (ISSUE acceptance): rows exceed the HBM
    budget, the matrix is never device-resident, and the trained model is
    BIT-identical to plain in-memory training — across chunk sizes
    {1, odd, pow2, N}, from both chunk sources (host array and cache)."""
    X, y = _make_data()
    n = X.shape[0]
    mem_ds = lgb.Dataset(X, label=y, params=dict(_PARAMS))
    _, want = _train_model_str(mem_ds)

    base = lgb.Dataset(X, label=y, params=dict(_PARAMS))
    cache = str(tmp_path / "train.bin")
    base.construct()
    base.save_binary(cache)

    for chunk in (1, 37, 128, n):
        ds = lgb.Dataset(cache, params=dict(
            _PARAMS, out_of_core=True, max_rows_in_hbm=n // 4,
            out_of_core_chunk_rows=chunk))
        bst, got = _train_model_str(ds)
        assert ds.ooc_spill and ds.bins_device is None
        assert got == want, f"spill OOC diverged at chunk_rows={chunk}"

    # host-array source (in-memory data whose DEVICE residency is capped)
    ds = lgb.Dataset(X, label=y, params=dict(
        _PARAMS, out_of_core=True, max_rows_in_hbm=100,
        out_of_core_chunk_rows=53))
    _, got = _train_model_str(ds)
    assert ds.ooc_spill
    assert got == want


def test_spill_ooc_with_bagging_and_feature_fraction(tmp_path):
    """Row/feature sampling rides the resident vectors, not the streamed
    matrix — sampled runs must stay bitwise too."""
    X, y = _make_data(n=350, seed=3)
    extra = dict(bagging_fraction=0.7, bagging_freq=1, feature_fraction=0.8)
    mem_ds = lgb.Dataset(X, label=y, params=dict(_PARAMS))
    _, want = _train_model_str(mem_ds, **extra)

    ds = lgb.Dataset(X, label=y, params=dict(
        _PARAMS, out_of_core=True, max_rows_in_hbm=64,
        out_of_core_chunk_rows=41))
    _, got = _train_model_str(ds, **extra)
    assert got == want


def test_spill_predictions_match_in_memory(tmp_path):
    X, y = _make_data(n=300, seed=5)
    mem_ds = lgb.Dataset(X, label=y, params=dict(_PARAMS))
    bst_mem, _ = _train_model_str(mem_ds)
    ds = lgb.Dataset(X, label=y, params=dict(
        _PARAMS, out_of_core=True, max_rows_in_hbm=50,
        out_of_core_chunk_rows=64))
    bst_ooc, _ = _train_model_str(ds)
    np.testing.assert_array_equal(
        bst_mem.predict(X), bst_ooc.predict(X))


def test_spill_envelope_raises_on_unsupported_features():
    X, y = _make_data(n=200)
    ds = lgb.Dataset(X, label=y, params=dict(
        _PARAMS, out_of_core=True, max_rows_in_hbm=50))
    with pytest.raises(ValueError, match="out_of_core spill"):
        lgb.Booster(params=dict(_PARAMS, out_of_core=True,
                                max_rows_in_hbm=50,
                                monotone_constraints=[1, 0, 0, 0, 0, 0]),
                    train_set=ds)


def test_spill_dispatch_accounting(tmp_path):
    """The spill grower's cost model is explicit: ceil(N/chunk) chunk
    dispatches per pass, 1 root pass + 1 pass per split, one accounted
    pull per split decision — all visible to the sanitizer ledger."""
    import jax.numpy as jnp

    from lightgbm_tpu.ops.split import SplitParams
    from lightgbm_tpu.ops.treegrow_ooc import grow_tree_ooc
    from lightgbm_tpu.io.stream import array_chunks
    from lightgbm_tpu.binning import DatasetBinner

    X, y = _make_data(n=256, f=5, seed=7)
    binner = DatasetBinner.fit(X, max_bin=255)
    bins = binner.transform(X)
    n, f = bins.shape
    stats = {}
    tree, leaf_id = grow_tree_ooc(
        lambda: array_chunks(bins, 64), n, f,
        jnp.asarray(0.6 * (y - 0.5), jnp.float32),
        jnp.ones((n,), jnp.float32),
        jnp.ones((n,), bool), jnp.ones((n,), jnp.float32),
        jnp.ones((f,), bool),
        jnp.asarray(binner.num_bins_per_feature),
        jnp.asarray(binner.missing_bin_per_feature),
        num_leaves=7, num_bins=256, params=SplitParams(min_data_in_leaf=5.0),
        chunk_rows=64, stats=stats)
    assert int(tree.num_leaves) > 1
    assert stats["passes"] == stats["splits"] + 1
    assert stats["chunks"] == stats["passes"] * 4  # 256 rows / 64-row chunks
    assert leaf_id.shape == (n,)


# ---------------------------------------------------------------------------
# the windowed budget pin with out_of_core on (resident regime)
# ---------------------------------------------------------------------------

def test_windowed_budget_green_on_stream_assembled_matrix(tmp_path):
    """ISSUE acceptance: the steady-state windowed budget (1 dispatch /
    0 syncs / 0 retraces per round) holds when the grower's bins come
    from an out_of_core stream-assembled device matrix — the chunk feed
    happens at ingest, the round loop's async-info protocol is
    untouched."""
    import jax
    import jax.numpy as jnp

    from lightgbm_tpu.ops.split import SplitParams
    from lightgbm_tpu.ops.treegrow_windowed import grow_tree_windowed
    from lightgbm_tpu.utils.sanitizer import DispatchCounter

    rng = np.random.RandomState(11)
    n, f = 900, 8
    X = rng.randn(n, f)
    y = X @ rng.randn(f) + 0.2 * rng.randn(n)
    mem = lgb.Dataset(X, label=y, params={"max_bin": 31})
    mem.construct()
    cache = str(tmp_path / "w.bin")
    mem.save_binary(cache)
    ooc = lgb.Dataset(cache, params={
        "max_bin": 31, "out_of_core": True, "out_of_core_chunk_rows": 111})
    ooc.construct()
    # the stream-assembled matrix IS the in-memory matrix
    np.testing.assert_array_equal(
        np.asarray(ooc.bins_device), np.asarray(mem.bins_device))

    bins_t = ooc.bins_device_t()
    kw = dict(
        row_mask=jnp.ones((n,), bool),
        sample_weight=jnp.ones((n,), jnp.float32),
        feature_mask=jnp.ones((f,), bool),
        num_bins_pf=jnp.asarray(ooc.binner.num_bins_per_feature),
        missing_bin_pf=jnp.asarray(ooc.binner.missing_bin_per_feature),
    )
    static = dict(num_leaves=15, num_bins=32, params=SplitParams(
        min_data_in_leaf=5.0), leaf_tile=4, use_pallas=False)
    grads = [jnp.asarray(0.6 * y + 0.05 * k, jnp.float32) for k in range(2)]
    tree, leaf = grow_tree_windowed(bins_t, grads[0], kw["sample_weight"],
                                    **kw, **static)
    jax.block_until_ready(leaf)

    stats = {}
    with DispatchCounter() as d:
        tree, leaf = grow_tree_windowed(bins_t, grads[1],
                                        kw["sample_weight"], **kw, **static,
                                        stats=stats)
        jax.block_until_ready(leaf)
    assert stats["rounds"] >= 3, stats
    d.assert_round_budget(stats["rounds"], what="windowed rounds on OOC bins")
    assert stats["host_syncs"] == 0, stats
    assert stats["retries"] == 0, stats
    d.assert_no_recompile("windowed rounds on a stream-assembled matrix")


# ---------------------------------------------------------------------------
# per-chunk CRC32 integrity (round 13, ISSUE 8): a corrupt or truncated
# bin cache fails fast + row-ranged instead of training on garbage bins
# ---------------------------------------------------------------------------

def _make_cache(tmp_path, n=300, f=4, name="crc.bin"):
    X, y = _make_data(n=n, f=f)
    ds = lgb.Dataset(X, label=y, params=dict(_PARAMS))
    ds.construct()
    cache = str(tmp_path / name)
    ds.save_binary(cache)
    return cache, np.asarray(ds.bins)


def _rewrite_member(src, dst, member, transform):
    """Copy an npz, applying ``transform(bytes)`` to one member (None
    drops it)."""
    import zipfile

    with zipfile.ZipFile(src) as zin, zipfile.ZipFile(dst, "w") as zout:
        for name in zin.namelist():
            data = zin.read(name)
            if name == member:
                data = transform(data)
                if data is None:
                    continue
            zout.writestr(name, data)


def test_save_binary_carries_crc_table_and_verifies(tmp_path):
    from lightgbm_tpu.io.stream import BinCacheStream, bin_crc32s

    cache, bins = _make_cache(tmp_path)
    s = BinCacheStream(cache)
    assert s.crcs is not None and s.crc_rows > 0
    np.testing.assert_array_equal(s.crcs, bin_crc32s(bins, s.crc_rows))
    got = np.zeros_like(bins)
    for lo, view in s.chunks(37):
        got[lo:lo + view.shape[0]] = view
    np.testing.assert_array_equal(got, bins)


def test_corrupt_bin_cache_raises_row_ranged_error(tmp_path):
    """A flipped byte in the bins member surfaces as CorruptBinCacheError
    naming the failing CRC chunk and its row range — never as garbage
    bins silently reaching training.  Exercised with a small custom CRC
    block size so the MIDDLE chunk is the one named."""
    import zlib

    from lightgbm_tpu.io.stream import (BinCacheStream,
                                        CorruptBinCacheError, bin_crc32s)

    cache, bins = _make_cache(tmp_path)
    # rebuild the cache with 64-row CRC blocks and corrupt a row in
    # block 2 (rows 128..191) — stored UNCOMPRESSED so the byte flip
    # reaches the CRC check rather than a zlib error
    bad_bins = bins.copy()
    bad_bins[150, 1] ^= 0x1
    crc_rows = 64

    def poison(_):
        import io

        buf = io.BytesIO()
        np.save(buf, bad_bins)
        return buf.getvalue()

    bad = str(tmp_path / "bad.bin")
    _rewrite_member(cache, bad, "bins.npy", poison)
    _rewrite_member(bad, bad + "2", "bins_crc_rows.npy", lambda _: (
        lambda b: (np.save(b, np.asarray(crc_rows, np.int64)), b.getvalue())[1])(
        __import__("io").BytesIO()))
    good_crcs = bin_crc32s(bins, crc_rows)  # CRCs of the TRUE data

    def crc_member(_):
        import io

        buf = io.BytesIO()
        np.save(buf, good_crcs)
        return buf.getvalue()

    final = str(tmp_path / "final.bin")
    _rewrite_member(bad + "2", final, "bins_crc32.npy", crc_member)

    s = BinCacheStream(final)
    assert s.crc_rows == crc_rows
    with pytest.raises(CorruptBinCacheError) as ei:
        for _ in s.chunks(50):
            pass
    assert ei.value.chunk_index == 150 // crc_rows
    assert ei.value.row_lo == 128 and ei.value.row_hi == 192
    assert "rows [128, 192)" in str(ei.value)


def test_truncated_bin_cache_raises_corrupt_error(tmp_path):
    from lightgbm_tpu.io.stream import BinCacheStream, CorruptBinCacheError

    cache, bins = _make_cache(tmp_path)

    def truncate(data):
        return data[: len(data) - len(data) // 3]

    torn = str(tmp_path / "torn.bin")
    _rewrite_member(cache, torn, "bins.npy", truncate)
    with pytest.raises(CorruptBinCacheError, match="corrupt at CRC chunk"):
        for _ in BinCacheStream(torn).chunks(64):
            pass


def test_corrupt_cache_fails_training_not_silently(tmp_path):
    """End to end: an out_of_core dataset built on a corrupt cache raises
    CorruptBinCacheError during ingest — training never sees the bins."""
    from lightgbm_tpu.io.stream import CorruptBinCacheError

    cache, bins = _make_cache(tmp_path)
    bad_bins = bins.copy()
    bad_bins[7, 0] ^= 0x1

    def poison(_):
        import io

        buf = io.BytesIO()
        np.save(buf, bad_bins)
        return buf.getvalue()

    bad = str(tmp_path / "bad_e2e.bin")
    _rewrite_member(cache, bad, "bins.npy", poison)
    ds = lgb.Dataset(bad, params=dict(_PARAMS, out_of_core=True))
    with pytest.raises(CorruptBinCacheError):
        _train_model_str(ds)


def test_legacy_trailerless_cache_loads_with_warning(tmp_path, caplog):
    """Pre-round-13 caches (no CRC members) still stream — with a logged
    warning, since nothing can vouch for their bytes."""
    from lightgbm_tpu.io.stream import BinCacheStream

    cache, bins = _make_cache(tmp_path)
    legacy = str(tmp_path / "legacy.bin")
    _rewrite_member(cache, legacy, "bins_crc32.npy", lambda _: None)
    _rewrite_member(legacy, legacy + "2", "bins_crc_rows.npy",
                    lambda _: None)
    s = BinCacheStream(legacy + "2")
    assert s.crcs is None
    got = np.zeros_like(bins)
    for lo, view in s.chunks(100):
        got[lo:lo + view.shape[0]] = view
    np.testing.assert_array_equal(got, bins)


# ---------------------------------------------------------------------------
# crash-at-round-k resume equivalence in the SPILL regime (ISSUE 8):
# stream + chunked-histogram state resumes bitwise, across chunk sizes
# ---------------------------------------------------------------------------

_OOC_CRASH_SCRIPT = """
import os, sys
import numpy as np
sys.path.insert(0, {repo!r})
import lightgbm_tpu as lgb

params = dict({params!r}, out_of_core=True, max_rows_in_hbm={hbm},
              out_of_core_chunk_rows={chunk}, snapshot_freq=2,
              output_model={out!r})
ds = lgb.Dataset({cache!r}, params=params)
lgb.train(params, ds, 6)
print("COMPLETED_WITHOUT_FAULT", flush=True)
"""


@pytest.mark.parametrize("chunk", [53])
def test_spill_crash_at_round_k_resume_is_bitwise(tmp_path, chunk):
    """Kill the host at round 5 of 6 while training a cache-streamed
    SPILL dataset; re-running the command with resume=auto continues
    from the round-4 snapshot — stream position restarts per pass and
    the chunked-histogram folds replay — and the final model is BITWISE
    identical to the uninterrupted spill run (which is itself bitwise
    the in-memory model, pinned above)."""
    import subprocess
    import sys

    from lightgbm_tpu.utils.faults import CRASH_EXIT_CODE

    X, y = _make_data()
    n = X.shape[0]
    base = lgb.Dataset(X, label=y, params=dict(_PARAMS))
    base.construct()
    cache = str(tmp_path / "train.bin")
    base.save_binary(cache)

    ooc = dict(_PARAMS, out_of_core=True, max_rows_in_hbm=n // 4,
               out_of_core_chunk_rows=chunk)
    full_ds = lgb.Dataset(cache, params=ooc)
    full = lgb.train(ooc, full_ds, 6)

    out = str(tmp_path / f"m{chunk}.txt")
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ, LGBMTPU_FAULT="host_crash:5",
               JAX_PLATFORMS="cpu")
    env.pop("PYTEST_CURRENT_TEST", None)
    r = subprocess.run(
        [sys.executable, "-c", _OOC_CRASH_SCRIPT.format(
            repo=repo, params=_PARAMS, hbm=n // 4, chunk=chunk,
            out=out, cache=cache)],
        env=env, capture_output=True, text=True, timeout=300)
    assert r.returncode == CRASH_EXIT_CODE, (r.stdout, r.stderr)

    resume_params = dict(ooc, snapshot_freq=2, output_model=out)
    ds = lgb.Dataset(cache, params=resume_params)
    resumed = lgb.train(resume_params, ds, 6, resume="auto")
    assert resumed.num_trees() == 6
    assert ds.ooc_spill
    # params echo differs (snapshot_freq/output_model); the TREES must
    # not differ by a single bit
    def trees(s):
        return s.partition("\nTree=")[2]

    assert trees(resumed.model_to_string()) == trees(full.model_to_string())


def test_spill_resume_with_categorical_trees(tmp_path):
    """Categorical splits are inside the spill envelope, so resume must
    handle them too: the streamed multi-tree replay excludes cat trees,
    and the per-tree fallback walks host chunks — still bitwise."""
    rng = np.random.RandomState(5)
    n = 300
    X = np.hstack([rng.randint(0, 8, (n, 2)).astype(float),
                   rng.randn(n, 3)])
    y = ((X[:, 0] == 3) | (X[:, 2] > 0)).astype(float)
    base_params = dict(_PARAMS, categorical_feature=[0, 1])
    base = lgb.Dataset(X, label=y, params=base_params,
                       categorical_feature=[0, 1])
    base.construct()
    cache = str(tmp_path / "cat.bin")
    base.save_binary(cache)

    P = dict(base_params, out_of_core=True, max_rows_in_hbm=64,
             out_of_core_chunk_rows=53)
    full = lgb.train(P, lgb.Dataset(cache, params=P), 4)
    assert any(t.num_cat > 0 for t in full._gbdt.models)

    run = dict(P, snapshot_freq=2, output_model=str(tmp_path / "m.txt"))
    lgb.train(run, lgb.Dataset(cache, params=run), 2)
    resumed = lgb.train(run, lgb.Dataset(cache, params=run), 4,
                        resume="auto")

    def trees(s):
        return s.partition("\nTree=")[2]

    assert trees(resumed.model_to_string()) == trees(full.model_to_string())


# ---------------------------------------------------------------------------
# rank-sharded streams (round 14): each rank streams only its (row_lo,
# row_hi) shard of one shared save_binary cache
# ---------------------------------------------------------------------------

def test_shard_stream_parity_with_whole_cache(tmp_path):
    """A (row_lo, row_hi) shard stream must yield byte-identical rows to
    the same slice of a whole-cache sweep — across shard boundaries that
    cut CRC blocks and chunk sizes that straddle them."""
    from lightgbm_tpu.io.stream import BinCacheStream

    cache, bins = _make_cache(tmp_path, n=300, f=4)
    whole = np.zeros_like(bins)
    for lo, view in BinCacheStream(cache).chunks(41):
        whole[lo:lo + view.shape[0]] = view
    np.testing.assert_array_equal(whole, bins)
    for lo, hi in ((0, 100), (100, 230), (230, 300), (37, 263), (299, 300)):
        s = BinCacheStream(cache, shard=(lo, hi))
        assert s.shard_rows == hi - lo and s.n_rows == bins.shape[0]
        got = np.zeros((hi - lo, bins.shape[1]), bins.dtype)
        first = None
        for glo, view in s.chunks(41):
            first = glo if first is None else first
            got[glo - lo: glo - lo + view.shape[0]] = view
        assert first == lo  # yields GLOBAL row offsets
        np.testing.assert_array_equal(got, bins[lo:hi])


def test_shard_stream_rejects_bad_range(tmp_path):
    from lightgbm_tpu.io.stream import BinCacheStream

    cache, bins = _make_cache(tmp_path)
    for bad in ((-1, 10), (10, 10), (0, bins.shape[0] + 1), (20, 5)):
        with pytest.raises(ValueError):
            BinCacheStream(cache, shard=bad)


def _poisoned_cache(tmp_path, bins, cache, crc_rows=64, bad_row=150):
    """Rebuild ``cache`` with ``crc_rows``-row CRC blocks over the TRUE
    data but one corrupted row in the bins member (the
    test_corrupt_bin_cache_raises_row_ranged_error recipe)."""
    import io

    from lightgbm_tpu.io.stream import bin_crc32s

    bad_bins = bins.copy()
    bad_bins[bad_row, 1] ^= 0x1

    def npy_bytes(arr):
        buf = io.BytesIO()
        np.save(buf, arr)
        return buf.getvalue()

    p1 = str(tmp_path / "shard_bad1.bin")
    p2 = str(tmp_path / "shard_bad2.bin")
    final = str(tmp_path / "shard_bad.bin")
    _rewrite_member(cache, p1, "bins.npy", lambda _: npy_bytes(bad_bins))
    _rewrite_member(p1, p2, "bins_crc_rows.npy",
                    lambda _: npy_bytes(np.asarray(crc_rows, np.int64)))
    _rewrite_member(p2, final, "bins_crc32.npy",
                    lambda _: npy_bytes(bin_crc32s(bins, crc_rows)))
    return final, bad_bins


def test_shard_stream_verifies_fully_covered_crc_blocks(tmp_path):
    """Shard sweeps keep the integrity contract wherever it is provable:
    a corrupt byte in a FULLY covered CRC block raises row-ranged; blocks
    the shard cuts mid-way are skipped (their leading bytes were never
    read), not trusted blind."""
    from lightgbm_tpu.io.stream import BinCacheStream, CorruptBinCacheError

    cache, bins = _make_cache(tmp_path)
    final, bad_bins = _poisoned_cache(tmp_path, bins, cache)
    # corruption at row 150 lives in CRC block 2 (rows [128, 192))
    s = BinCacheStream(final, shard=(128, 300))
    with pytest.raises(CorruptBinCacheError) as ei:
        for _ in s.chunks(50):
            pass
    assert ei.value.row_lo == 128 and ei.value.row_hi == 192

    # shard entering block 2 mid-way: the block is unverifiable and
    # skipped; later blocks still verify — the sweep completes with the
    # shard's bytes intact
    s2 = BinCacheStream(final, shard=(140, 300))
    got = np.zeros((160, bins.shape[1]), bins.dtype)
    for glo, view in s2.chunks(33):
        got[glo - 140: glo - 140 + view.shape[0]] = view
    np.testing.assert_array_equal(got, bad_bins[140:300])

    # shard ending inside block 2 never completes the block: no check
    # fires, the partial rows stream through
    s3 = BinCacheStream(final, shard=(0, 160))
    rows = sum(v.shape[0] for _, v in s3.chunks(64))
    assert rows == 160


# ---------------------------------------------------------------------------
# append-able caches (round 19, ISSUE 14 — continual ingest durability)
# ---------------------------------------------------------------------------

def _bins_payload_offset(path, member="bins.npy"):
    """Byte offset of the member's raw element data inside the zip."""
    import zipfile

    with zipfile.ZipFile(path) as zf:
        off = zf.getinfo(member).header_offset
    data = open(path, "rb").read()
    idx = data.index(b"\x93NUMPY", off)
    hlen = int.from_bytes(data[idx + 8:idx + 10], "little")
    return idx + 10 + hlen


def test_append_rows_round_trip_and_dataset_reload(tmp_path):
    """Appending frozen-mapper-binned rows grows the cache in place:
    the CRC table covers old + new rows, the append log records the
    seam, and a Dataset reload sees the concatenation exactly."""
    from lightgbm_tpu.io.stream import BinCacheStream, append_rows

    cache, bins = _make_cache(tmp_path, n=300, f=4)
    ds0 = lgb.Dataset(cache, params=dict(_PARAMS))
    ds0.construct()
    Xn, yn = _make_data(n=120, f=4, seed=9)
    new_bins = ds0.binner.transform(Xn)
    total = append_rows(cache, new_bins, label=yn)
    assert total == 420

    s = BinCacheStream(cache)
    assert s.shape == (420, 4)
    assert list(s.append_log) == [300]
    got = np.concatenate([v.copy() for _, v in s.chunks(64)])
    np.testing.assert_array_equal(got[:300], bins)
    np.testing.assert_array_equal(got[300:], new_bins.astype(s.dtype))

    ds = lgb.Dataset(cache, params=dict(_PARAMS))
    ds.construct()
    assert ds.num_data() == 420
    np.testing.assert_array_equal(np.asarray(ds.bins)[300:],
                                  new_bins.astype(ds.bins.dtype))
    np.testing.assert_allclose(np.asarray(ds.label)[300:], yn)
    # a second append extends the log
    append_rows(cache, new_bins[:10], label=yn[:10])
    assert list(BinCacheStream(cache).append_log) == [300, 420]


def test_append_rows_validation(tmp_path):
    from lightgbm_tpu.io.stream import append_rows

    cache, bins = _make_cache(tmp_path, n=300, f=4)
    with pytest.raises(ValueError, match="labels"):
        append_rows(cache, bins[:5])  # cache carries labels; chunk must too
    with pytest.raises(ValueError, match="shape"):
        append_rows(cache, np.zeros((5, 9), np.uint8), label=np.zeros(5))
    with pytest.raises(ValueError, match="labels"):
        append_rows(cache, bins[:5], label=np.zeros(4))


def test_append_to_legacy_cache_upgrades_crc_table(tmp_path):
    """Appending to a trailerless (pre-round-13) cache UPGRADES it: the
    new file carries a full CRC table covering every row — old rows
    included — instead of silently mixing verified and unverifiable
    blocks."""
    from lightgbm_tpu.io.stream import (BinCacheStream, append_rows,
                                        bin_crc32s)

    cache, bins = _make_cache(tmp_path, n=300, f=4)
    legacy = str(tmp_path / "legacy.bin")
    _rewrite_member(cache, legacy, "bins_crc32.npy", lambda b: None)
    _rewrite_member(legacy, legacy + ".2", "bins_crc_rows.npy",
                    lambda b: None)
    os.replace(legacy + ".2", legacy)
    assert BinCacheStream(legacy).crcs is None  # really trailerless

    ds0 = lgb.Dataset(cache, params=dict(_PARAMS))
    ds0.construct()
    Xn, yn = _make_data(n=80, f=4, seed=9)
    append_rows(legacy, ds0.binner.transform(Xn), label=yn)
    s = BinCacheStream(legacy)
    assert s.crcs is not None
    got = np.concatenate([v.copy() for _, v in s.chunks(50)])  # verifies
    np.testing.assert_array_equal(
        s.crcs, bin_crc32s(got.astype(s.dtype), s.crc_rows))
    from lightgbm_tpu.obs import metrics as obs
    assert obs.counter("bin_cache_crc_upgrades_total").value >= 1


def _make_appended_cache(tmp_path, n_base=4000, n_new=2000, crc_rows=512):
    """A cache written with a small CRC block size, then appended once —
    the bins member comes out ZIP_STORED, so byte offsets map 1:1 to
    rows and the per-block table is fine-grained enough that OUR check
    fires before zipfile's whole-member CRC at EOF."""
    from lightgbm_tpu.io.stream import append_rows, write_bin_cache

    X, y = _make_data(n=n_base, f=4)
    ds = lgb.Dataset(X, label=y, params=dict(_PARAMS))
    ds.construct()
    cache = str(tmp_path / "appendable.bin")
    with open(cache, "wb") as fh:
        write_bin_cache(fh, ds.bins, ds.binner.mappers, label=y,
                        feature_names=ds.feature_names, crc_rows=crc_rows)
    Xn, yn = _make_data(n=n_new, f=4, seed=9)
    append_rows(cache, ds.binner.transform(Xn), label=yn)
    return cache, ds


def test_append_corruption_error_names_the_appended_chunk(tmp_path):
    """A corrupt byte in the appended region raises row-ranged AND names
    which append_rows() call wrote the bad rows."""
    from lightgbm_tpu.io.stream import BinCacheStream, CorruptBinCacheError

    cache, _ds = _make_appended_cache(tmp_path)
    data = bytearray(open(cache, "rb").read())
    payload = _bins_payload_offset(cache)
    data[payload + 4500 * 4 + 1] ^= 0xFF  # row 4500: inside the append
    open(cache, "wb").write(bytes(data))
    with pytest.raises(CorruptBinCacheError) as ei:
        for _ in BinCacheStream(cache).chunks(256):
            pass
    msg = str(ei.value)
    assert "appended chunk 0" in msg and "row 4000" in msg, msg
    # row-ranged at the 512-row CRC block holding row 4500
    assert ei.value.row_lo == 4096 and ei.value.row_hi == 4608, msg


def test_append_to_corrupt_cache_refuses_before_replace(tmp_path):
    """The old payload streams through the VERIFIED path on its way into
    the new file: a corrupt source raises row-ranged BEFORE the atomic
    replace, leaving the (corrupt, but unreplaced) original untouched —
    an append can never launder bad bytes under a fresh CRC table."""
    from lightgbm_tpu.io.stream import CorruptBinCacheError, append_rows

    cache, ds = _make_appended_cache(tmp_path)
    data = bytearray(open(cache, "rb").read())
    payload = _bins_payload_offset(cache)
    data[payload + 1000 * 4] ^= 0xFF
    open(cache, "wb").write(bytes(data))
    before = open(cache, "rb").read()
    Xn, yn = _make_data(n=50, f=4, seed=9)
    with pytest.raises(CorruptBinCacheError):
        append_rows(cache, ds.binner.transform(Xn), label=yn)
    assert open(cache, "rb").read() == before


# ---------------------------------------------------------------------------
# the launcher's rank-sharded cache feed (ISSUE 15 satellite): workers
# materialize ONLY their shard of a shared cache via BinCacheStream(shard=)
# ---------------------------------------------------------------------------

def test_dataset_bin_cache_shard_parity(tmp_path):
    """Dataset(cache, params={'bin_cache_shard': (lo, hi, pad)}) builds
    the identical binned rows/label/weight the full cache holds at
    [lo, hi) — plus weight-0 zero-bin padding to the fleet's equal-shard
    size — without ever materializing the whole matrix member."""
    cache, bins = _make_cache(tmp_path, n=300, f=4)
    with np.load(cache, allow_pickle=False) as z:
        full_label = np.asarray(z["label"])
    lo, hi, pad = 37, 263, 240  # a range cutting CRC blocks, padded
    ds = lgb.Dataset(cache,
                     params=dict(_PARAMS, bin_cache_shard=(lo, hi, pad)))
    ds.construct()
    got = np.asarray(ds.bins)
    assert got.shape == (pad, bins.shape[1])
    np.testing.assert_array_equal(got[: hi - lo], bins[lo:hi])
    assert (got[hi - lo:] == 0).all()
    np.testing.assert_array_equal(np.asarray(ds.label)[: hi - lo],
                                  full_label[lo:hi])
    w = np.asarray(ds.weight)
    assert (w[: hi - lo] == 1.0).all() and (w[hi - lo:] == 0.0).all()
    # an unpadded shard keeps weight=None semantics (no synthetic ones)
    ds2 = lgb.Dataset(cache, params=dict(_PARAMS,
                                         bin_cache_shard=(lo, hi)))
    ds2.construct()
    assert ds2.weight is None
    np.testing.assert_array_equal(np.asarray(ds2.bins), bins[lo:hi])


def test_dataset_bin_cache_shard_crc_boundary(tmp_path):
    """The shard feed keeps the integrity contract: a corrupt byte in a
    CRC block the shard fully covers raises row-ranged through
    read_cache_shard; a shard cutting the poisoned block mid-way cannot
    verify it (leading bytes never read) and streams through."""
    from lightgbm_tpu.io.stream import CorruptBinCacheError

    cache, bins = _make_cache(tmp_path)
    final, bad_bins = _poisoned_cache(tmp_path, bins, cache)
    ds = lgb.Dataset(final, params=dict(_PARAMS,
                                        bin_cache_shard=(128, 300)))
    with pytest.raises(CorruptBinCacheError) as ei:
        ds.construct()
    assert ei.value.row_lo == 128 and ei.value.row_hi == 192
    ds2 = lgb.Dataset(final, params=dict(_PARAMS,
                                         bin_cache_shard=(140, 300)))
    ds2.construct()
    np.testing.assert_array_equal(np.asarray(ds2.bins), bad_bins[140:300])


def test_cache_shard_fingerprint_tracks_bytes(tmp_path):
    """The launcher's shard fingerprint (CRC-table-derived, no payload
    read) is stable across reads, distinct per range, and flips when the
    shard's bytes change."""
    from lightgbm_tpu.io.stream import cache_shard_fingerprint

    cache, bins = _make_cache(tmp_path)
    fp = cache_shard_fingerprint(cache, 0, 150)
    assert fp and fp == cache_shard_fingerprint(cache, 0, 150)
    assert fp != cache_shard_fingerprint(cache, 150, 300)
    final, _ = _poisoned_cache(tmp_path, bins, cache, bad_row=10)
    assert cache_shard_fingerprint(final, 0, 150) != fp


def test_launcher_cache_feed_trains_equal_to_in_memory(tmp_path):
    """End to end: train_distributed(data_cache=) feeds the worker
    through the shard stream and produces the identical model a plain
    in-process training on the same cache does."""
    from lightgbm_tpu.parallel import launcher

    cache, _bins = _make_cache(tmp_path, n=400, f=5, name="feed.bin")
    params = dict(_PARAMS, bin_construct_sample_cnt=400)
    ref = lgb.train(dict(params), lgb.Dataset(cache), num_boost_round=4)
    ref_path = str(tmp_path / "ref_model.txt")
    ref.save_model(ref_path)
    bst, files = launcher.train_distributed(
        params, None, None, num_boost_round=4, num_machines=1,
        data_cache=cache,
        env_extra={"JAX_PLATFORMS": "cpu",
                   "XLA_FLAGS": "--xla_force_host_platform_device_count=1"})
    assert open(files[0]).read() == open(ref_path).read()
    with pytest.raises(ValueError, match="XOR"):
        launcher.train_distributed(params, np.zeros((4, 2)), None,
                                   num_boost_round=1, num_machines=1,
                                   data_cache=cache)


# ---------------------------------------------------------------------------
# segmented appends + compaction (round 23 — the continual runner's
# O(new rows) steady-state ingest: sidecar segments, threshold-triggered
# fold-back, crash-stranded sidecars ignored via the watermark)
# ---------------------------------------------------------------------------

def test_segment_append_leaves_base_untouched_and_reloads(tmp_path):
    """Under the threshold, appends land in CRC'd sidecars: the base file
    is BYTE-identical afterwards (O(new rows) per append), the stream and
    a Dataset reload both see base + segments as one logical cache, and
    the append log records every seam."""
    from lightgbm_tpu.io.stream import BinCacheStream, append_rows
    from lightgbm_tpu.obs import metrics as obs

    cache, bins = _make_cache(tmp_path, n=300, f=4, name="seg.bin")
    base_bytes = open(cache, "rb").read()
    ds0 = lgb.Dataset(cache, params=dict(_PARAMS))
    ds0.construct()
    Xn, yn = _make_data(n=90, f=4, seed=9)
    nb = ds0.binner.transform(Xn)
    c0 = obs.counter("bin_cache_segment_appends_total").value
    assert append_rows(cache, nb[:40], label=yn[:40],
                       segment_threshold=3) == 340
    assert append_rows(cache, nb[40:], label=yn[40:],
                       segment_threshold=3) == 390
    assert open(cache, "rb").read() == base_bytes  # base never rewritten
    assert os.path.exists(cache + ".seg.0")
    assert os.path.exists(cache + ".seg.1")
    assert obs.counter("bin_cache_segment_appends_total").value == c0 + 2

    s = BinCacheStream(cache)
    assert s.shape == (390, 4)
    assert [k for k, _sp, _n in s.segments] == [0, 1]
    assert list(s.append_log) == [300, 340]
    got = np.concatenate([v.copy() for _, v in s.chunks(64)])
    np.testing.assert_array_equal(got[:300], bins)
    np.testing.assert_array_equal(got[300:], nb.astype(s.dtype))

    ds = lgb.Dataset(cache, params=dict(_PARAMS))
    ds.construct()
    assert ds.num_data() == 390
    np.testing.assert_array_equal(np.asarray(ds.bins)[300:],
                                  nb.astype(np.asarray(ds.bins).dtype))
    np.testing.assert_allclose(np.asarray(ds.label)[300:], yn)


def test_segment_threshold_triggers_compaction(tmp_path):
    """Reaching the threshold folds every live segment back into the base
    through the verified rewrite: sidecars are deleted, the watermark
    covers the folded indices, and the logical rows are preserved
    exactly."""
    from lightgbm_tpu.io.stream import BinCacheStream, append_rows
    from lightgbm_tpu.obs import metrics as obs

    cache, bins = _make_cache(tmp_path, n=300, f=4, name="fold.bin")
    ds0 = lgb.Dataset(cache, params=dict(_PARAMS))
    ds0.construct()
    Xn, yn = _make_data(n=80, f=4, seed=9)
    nb = ds0.binner.transform(Xn)
    c0 = obs.counter("bin_cache_compactions_total").value
    append_rows(cache, nb[:30], label=yn[:30], segment_threshold=2)
    assert os.path.exists(cache + ".seg.0")
    assert obs.counter("bin_cache_compactions_total").value == c0
    append_rows(cache, nb[30:], label=yn[30:], segment_threshold=2)
    assert obs.counter("bin_cache_compactions_total").value == c0 + 1
    assert not os.path.exists(cache + ".seg.0")
    assert not os.path.exists(cache + ".seg.1")

    s = BinCacheStream(cache)
    assert not s.segments and s.shape == (380, 4)
    assert s.seg_watermark == 1  # both folded indices covered
    got = np.concatenate([v.copy() for _, v in s.chunks(50)])
    np.testing.assert_array_equal(got[:300], bins)
    np.testing.assert_array_equal(got[300:], nb.astype(s.dtype))
    with np.load(cache, allow_pickle=False) as z:
        assert len(z["label"]) == 380  # labels folded into the base npz
    ds = lgb.Dataset(cache, params=dict(_PARAMS))
    ds.construct()
    assert ds.num_data() == 380
    np.testing.assert_allclose(np.asarray(ds.label)[300:], yn)


def test_stale_sidecar_past_watermark_is_ignored(tmp_path):
    """A crash between compaction's atomic replace and its sidecar
    deletes strands already-folded segment files: the watermark makes
    every reader skip them — rows are never double-counted."""
    from lightgbm_tpu.io.stream import BinCacheStream, append_rows

    cache, _bins = _make_cache(tmp_path, n=300, f=4, name="stale.bin")
    ds0 = lgb.Dataset(cache, params=dict(_PARAMS))
    ds0.construct()
    Xn, yn = _make_data(n=60, f=4, seed=9)
    nb = ds0.binner.transform(Xn)
    append_rows(cache, nb[:25], label=yn[:25], segment_threshold=2)
    stranded = open(cache + ".seg.0", "rb").read()
    append_rows(cache, nb[25:], label=yn[25:], segment_threshold=2)
    assert not os.path.exists(cache + ".seg.0")  # compaction reaped it
    # the crash: the folded sidecar reappears after the base replace
    open(cache + ".seg.0", "wb").write(stranded)

    s = BinCacheStream(cache)
    assert not s.segments, "stale sidecar was re-counted"
    assert s.shape == (360, 4)
    ds = lgb.Dataset(cache, params=dict(_PARAMS))
    ds.construct()
    assert ds.num_data() == 360
    # temp files from an in-flight segment write are skipped too
    open(cache + ".seg.tmp123", "wb").write(b"junk")
    assert not BinCacheStream(cache).segments


def test_segment_fingerprint_moves_on_append_and_compaction(tmp_path):
    """The shard fingerprint covers sidecar bytes: every segment append
    moves it (the fleet manifests must notice new rows without reading
    payloads), and it never goes empty while segments carry CRC
    tables."""
    from lightgbm_tpu.io.stream import (append_rows,
                                        cache_shard_fingerprint)

    cache, _bins = _make_cache(tmp_path, n=300, f=4, name="fp.bin")
    ds0 = lgb.Dataset(cache, params=dict(_PARAMS))
    ds0.construct()
    Xn, yn = _make_data(n=60, f=4, seed=9)
    nb = ds0.binner.transform(Xn)
    fps = [cache_shard_fingerprint(cache, 0, 10_000)]
    append_rows(cache, nb[:20], label=yn[:20], segment_threshold=4)
    fps.append(cache_shard_fingerprint(cache, 0, 10_000))
    append_rows(cache, nb[20:], label=yn[20:], segment_threshold=4)
    fps.append(cache_shard_fingerprint(cache, 0, 10_000))
    assert all(fps), "fingerprint went unverifiable mid-ingest"
    assert len(set(fps)) == 3, "an append did not move the fingerprint"
    # a base-range fingerprint ignores the sidecars entirely
    assert cache_shard_fingerprint(cache, 0, 300) == \
        cache_shard_fingerprint(cache, 0, 300)
